"""FleetEngine — E experiment variants as one vmapped device program.

The paper's round economics say a conservative window costs roughly
kernel-count × fixed per-kernel launch cost, so a second experiment riding
the same jitted window loop is nearly free. This engine makes that the
serving shape: E experiments of ONE topology shape class (same host count,
same latencies, same capacities — docs/SEMANTICS.md §"Fleet contract")
stack onto a leading experiment axis and run through ``jax.vmap`` of the
exact single-device ``window_step``:

* every ``SimState`` leaf grows a leading ``[E, ...]`` axis (event
  buffers ``[E, C, H]``, metrics ``[E]``, telemetry rings ``[E, W, F]``);
* the per-experiment *variants* — RNG key, loss thresholds, fault tables,
  ``max_rounds`` — ride a batched pytree zipped with the state, so lane e
  executes with exactly the constants a solo run of experiment e would
  close over;
* everything trace-structural is shared: one compiled program, one launch
  per chunk, per-window cost = the max lane's round count.

**Per-experiment determinism contract**: lane e's digest stream, metrics
and model state are bit-identical to running experiment e alone (solo tpu
engine or the cpu oracle) — ``tools/fleetprobe.py`` verifies it, and
``tests/test_fleet.py`` asserts it per PR. The mechanism: ``vmap`` batches
the identical integer ops (RNG is counter-based per (key, host, ctr), so
lanes cannot interact), and the batched ``while_loop`` freezes finished
lanes with per-lane selects, so even per-lane ``rounds`` counts stay
exact.

**Recovery plane** (docs/SEMANTICS.md §"Fleet recovery contract"): the
``[E, ...]`` state pytree is a well-defined transaction unit, so
``--on-overflow retry`` rolls the WHOLE fleet back to the chunk-start
state, grows the (fleet-uniform) cap one ladder step via the leading-axis-
aware ``tune/resize.py`` migration and replays the chunk bit-exactly;
``--auto-caps`` feeds the CapController fleet-global gauges (max fill over
lanes, summed overflow); and failing/finished lanes are sliced out
mid-sweep (``select_lanes`` / ``lane_done`` — fleet/run.py drives the
policy). What the fleet plane still rejects (structured FleetConfigError,
``kind="mode"``): the sharded engine (vmap-over-shard_map composition is a
follow-up). Pallas kernel impls and sparse-window compaction downgrade to
their XLA/full-width twins with a warning (bit-identical by contract).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from shadow1_tpu import rng
from shadow1_tpu.config.compiled import NO_STOP
from shadow1_tpu.consts import EngineParams
from shadow1_tpu.core.engine import (
    Ctx,
    SimState,
    _metrics_init,
    _model_module,
    build_base_ctx,
    check_digest_params,
    check_probe_params,
    window_step,
)
from shadow1_tpu.core.events import evbuf_init
from shadow1_tpu.core.outbox import outbox_init
from shadow1_tpu.fleet.expand import FleetConfigError, check_uniform


def slice_experiment(st: SimState, e: int) -> SimState:
    """Lane e of a fleet state as a standalone solo SimState (leading
    experiment axis stripped from every leaf). The per-experiment resume
    slice: saved via ckpt.save_state it loads into a solo Engine of the
    same config and continues bit-identically (tests/test_fleet.py)."""
    return jax.tree.map(lambda x: x[e], st)


def select_lanes(st: SimState, keep) -> SimState:
    """The sub-fleet state holding only lanes ``keep`` (local indices, in
    order): every leaf's leading experiment axis gathered down to E'. Lanes
    are vmap-independent — the batched program applies the identical
    per-lane computation to whatever rides the axis — so a kept lane's
    continuation from a selected state is bit-identical to its continuation
    in the full fleet (the quarantine / early-finalize repack primitive;
    tests/test_fleet_recover.py proves it against E-1-from-scratch runs)."""
    idx = np.asarray(list(keep), np.int32)
    return jax.tree.map(lambda x: x[idx], st)


def fleet_metrics_per_exp(st: SimState) -> list[dict[str, int]]:
    """Per-experiment metric dicts from a fleet state ([E] leaves)."""
    arrs = {k: np.asarray(v) for k, v in st.metrics._asdict().items()}
    n = len(next(iter(arrs.values())))
    return [{k: int(v[e]) for k, v in arrs.items()} for e in range(n)]


def drain_fleet_rings(st: SimState, window_ns: int, start: int = 0,
                      exp_base: int = 0, exp_ids=None) -> list[dict]:
    """Per-experiment telemetry-ring drain: the solo ``drain_ring`` per
    lane, each record tagged with its experiment id (``exp``) — the shape
    tools/heartbeat_report.py and captune group by (docs/OBSERVABILITY.md
    §fleet). ``exp_base`` offsets the ids: a memory-downshifted sub-batch
    (cli --on-oom downshift) runs lanes [base, base+k) of the sweep, and
    its ring records must carry the SWEEP-global experiment ids;
    ``exp_ids`` (explicit per-lane global ids, wins over exp_base) is the
    same need after mid-sweep quarantine/finalize leaves the surviving ids
    non-contiguous. TWO device→host fetches total (the [E, W, F] ring and
    the window counters), then pure numpy lane views — never a per-lane
    slice of the whole fleet state."""
    from types import SimpleNamespace

    from shadow1_tpu.telemetry.ring import drain_ring

    if getattr(st, "telem", None) is None:
        return []
    buf = np.asarray(st.telem.buf)               # [E, W, F]
    windows = np.asarray(st.metrics.windows)     # [E]
    recs: list[dict] = []
    for e in range(buf.shape[0]):
        lane = SimpleNamespace(
            telem=SimpleNamespace(buf=buf[e]),
            metrics=SimpleNamespace(windows=int(windows[e])),
        )
        gid = exp_ids[e] if exp_ids is not None else e + exp_base
        for r in drain_ring(lane, window_ns, start=start):
            recs.append({**r, "exp": int(gid)})
    return recs


def drain_fleet_probes(st: SimState, window_ns: int, probes: tuple,
                       start: int = 0, exp_base: int = 0,
                       exp_ids=None) -> list[dict]:
    """Per-experiment probe-ring drain: the solo ``drain_probes`` per lane
    over the [E, W, K, F] fleet ring, each ``flow`` record tagged with its
    sweep-global experiment id (``exp``) — same id rules and same
    two-fetch-then-numpy-views discipline as ``drain_fleet_rings``."""
    from types import SimpleNamespace

    from shadow1_tpu.telemetry.probes import drain_probes

    if getattr(st, "probes", None) is None:
        return []
    buf = np.asarray(st.probes.buf)              # [E, W, K, F]
    windows = np.asarray(st.metrics.windows)     # [E]
    recs: list[dict] = []
    for e in range(buf.shape[0]):
        lane = SimpleNamespace(
            probes=SimpleNamespace(buf=buf[e]),
            metrics=SimpleNamespace(windows=int(windows[e])),
        )
        gid = exp_ids[e] if exp_ids is not None else e + exp_base
        for r in drain_probes(lane, window_ns, probes, start=start):
            recs.append({**r, "exp": int(gid)})
    return recs


def drain_fleet_links(st: SimState, window_ns: int, start: int = 0,
                      exp_base: int = 0, exp_ids=None) -> list[dict]:
    """Per-experiment link drain: the solo ``drain_links`` per lane over
    the [E, V, V, F] fleet accumulator, each ``link`` record tagged with
    its sweep-global experiment id (``exp``) — same id rules and same
    two-fetch-then-numpy-views discipline as ``drain_fleet_rings``. A
    fresh lane bound mid-sweep (recovery-plane rebind) whose window count
    sits below the sweep cursor yields that lane's ``link_gap`` rebase
    marker, exactly as the solo drain would."""
    from types import SimpleNamespace

    from shadow1_tpu.telemetry.links import drain_links

    if getattr(st, "links", None) is None:
        return []
    buf = np.asarray(st.links.buf)               # [E, V, V, F]
    windows = np.asarray(st.metrics.windows)     # [E]
    recs: list[dict] = []
    for e in range(buf.shape[0]):
        lane = SimpleNamespace(
            links=SimpleNamespace(buf=buf[e]),
            metrics=SimpleNamespace(windows=int(windows[e])),
        )
        gid = exp_ids[e] if exp_ids is not None else e + exp_base
        for r in drain_links(lane, window_ns, start=start):
            recs.append({**r, "exp": int(gid)})
    return recs


def _stack_host_intervals(exps) -> tuple[np.ndarray, np.ndarray]:
    """Per-experiment [K_i, H] down/up interval tensors → [E, Kmax, H],
    padded with the empty [NO_STOP, NO_STOP) interval no time satisfies."""
    from shadow1_tpu.fault.schedule import host_interval_tensors

    tabs = [host_interval_tensors(e) for e in exps]
    h = exps[0].n_hosts
    kmax = max((d.shape[0] for d, _ in tabs), default=0)
    kmax = max(kmax, 1)  # keep a well-formed [E, 1, H] even when fault-free
    downs, ups = [], []
    for d, u in tabs:
        pad = kmax - d.shape[0]
        if pad:
            filler = np.full((pad, h), NO_STOP, np.int64)
            d = np.concatenate([d, filler]) if d.size else filler
            u = np.concatenate([u, filler]) if u.size else filler
        downs.append(d)
        ups.append(u)
    return np.stack(downs), np.stack(ups)


def _stack_link_tables(exps):
    """[E, Lmax] (src, dst, t0, t1) link-outage tables, or None when no
    experiment has any. Padding rows use t0 == t1 == 0 — an empty outage
    window no departure time can hit."""
    from shadow1_tpu.fault.schedule import link_tables

    tabs = [link_tables(e) for e in exps]
    if all(t is None for t in tabs):
        return None
    lmax = max(len(t[0]) for t in tabs if t is not None)

    def pad(t):
        if t is None:
            t = (np.zeros(0, np.int32), np.zeros(0, np.int32),
                 np.zeros(0, np.int64), np.zeros(0, np.int64))
        n = lmax - len(t[0])
        return tuple(np.concatenate([np.asarray(a), np.zeros(n, a.dtype)])
                     for a in t)

    cols = [pad(t) for t in tabs]
    return tuple(np.stack([c[i] for c in cols]) for i in range(4))


def _stack_ramp_tables(exps):
    """[E, Rmax] (src, dst, t0, t1, thr) loss-ramp tables, or None.
    Padding rows are inert the same way (t0 == t1)."""
    from shadow1_tpu.fault.schedule import ramp_tables

    tabs = [ramp_tables(e) for e in exps]
    if all(t is None for t in tabs):
        return None
    rmax = max(len(t[0]) for t in tabs if t is not None)

    def pad(t):
        if t is None:
            t = (np.zeros(0, np.int32), np.zeros(0, np.int32),
                 np.zeros(0, np.int64), np.zeros(0, np.int64),
                 np.zeros(0, np.uint64))
        n = rmax - len(t[0])
        return tuple(np.concatenate([np.asarray(a), np.zeros(n, a.dtype)])
                     for a in t)

    cols = [pad(t) for t in tabs]
    return tuple(np.stack([c[i] for c in cols]) for i in range(5))


class FleetEngine:
    """Batched engine over E CompiledExperiments of one shape class.

    API mirrors core.engine.Engine where the chunk runners need it
    (``init_state`` / ``run`` / ``place_state`` / ``n_windows`` /
    ``window`` / ``params``), plus the per-experiment accessors
    (``metrics_per_exp`` / ``slice_experiment`` / ``drain_rings``).
    ``metrics_dict`` returns the FLEET AGGREGATE (counters summed, gauges
    maxed) so generic chunk plumbing keeps working; anything that needs
    the real contract reads the per-experiment dicts."""

    def __init__(self, exps: list, params: EngineParams | None = None,
                 max_rounds: list[int] | None = None):
        if not exps:
            raise FleetConfigError("fleet needs >= 1 experiment")
        for exp in exps:
            exp.validate()
        self.params = params or EngineParams()
        check_uniform(exps, [self.params] * len(exps))
        check_digest_params(self.params)
        check_probe_params(self.params)
        from shadow1_tpu.telemetry.links import check_link_params

        check_link_params(self.params, np.asarray(exps[0].lat_vv).shape[0])
        self.params = self._resolve_fleet_params(self.params)
        self.exps = list(exps)
        self.exp = exps[0]
        self.n_exp = len(exps)
        self.window = self.exp.window
        self.n_windows = int(-(-self.exp.end_time // self.window))
        self.max_rounds = [int(m) for m in
                           (max_rounds or [self.params.max_rounds]
                            * self.n_exp)]
        if len(self.max_rounds) != self.n_exp:
            raise FleetConfigError(
                f"max_rounds list ({len(self.max_rounds)}) != experiment "
                f"count ({self.n_exp})")
        if len(set(self.max_rounds)) == 1 \
                and self.max_rounds[0] != self.params.max_rounds:
            # A UNIFORM list never becomes a variant leaf (the per-lane
            # substitution in _lane_ctx fires only for non-uniform lists),
            # so the compiled program would silently run params.max_rounds
            # instead — normalize params to the list before anything is
            # traced.
            self.params = dataclasses.replace(
                self.params, max_rounds=self.max_rounds[0])
        # Sweep-global id of lane 0 — nonzero only for a memory-downshifted
        # sub-batch (cli --on-oom downshift), so records keep global ids.
        self.exp_base = 0
        # Explicit per-lane sweep-global ids (wins over exp_base when set):
        # after a mid-sweep quarantine/finalize the surviving ids are
        # non-contiguous, and every ring record must still carry the id the
        # lane had in the original sweep (fleet/run.py sets this on repack).
        self.exp_ids: list[int] | None = None
        self._model = _model_module(self.exp.model)
        self._base_ctx = build_base_ctx(self.exp, self.params,
                                        window=self.window)
        self._variants, self._has = self._build_variants()
        self._base_ctx = dataclasses.replace(
            self._base_ctx,
            has_stop=self._has["stop"], has_restart=self._has["restart"],
            has_link_fault=self._has["link"],
            has_loss_ramp=self._has["ramp"],
        )
        if self._has["restart"]:
            # Per-experiment restart target: the model pytree exactly as
            # init() builds it under each lane's constants, captured once
            # (eager vmap) and carried as a batched variant leaf —
            # window_step restores restarted hosts' columns from lane e's
            # capture, same as the solo engine's device constant.
            cap = jax.vmap(self._lane_init_model)(self._variants)
            self._variants["init_model"] = jax.tree.map(
                lambda x: jnp.asarray(np.asarray(x)), cap)
        self._run_jit = jax.jit(self._make_run())

    # -- construction ------------------------------------------------------
    def _resolve_fleet_params(self, params: EngineParams) -> EngineParams:
        # auto_caps and on_overflow=retry were structured kind="mode"
        # rejections through PR 12: both now work fleet-wide — the [E, ...]
        # pytree is the transaction unit, caps stay fleet-uniform, and
        # tune/resize.py migrates the batched planes per lane (PR 13,
        # docs/SEMANTICS.md §"Fleet recovery contract").
        repl = {}
        if "pallas" in (params.pop_impl, params.push_impl):
            import warnings

            warnings.warn("fleet mode runs the XLA pop/push kernels "
                          "(pallas fused kernels are not vmapped); "
                          "falling back to pop_impl=push_impl='xla'")
            repl.update(pop_impl="xla", push_impl="xla")
        if params.compact_cap:
            import warnings

            warnings.warn("fleet mode ignores compact_cap: under vmap the "
                          "compacted and full-width branches would both "
                          "execute per window, negating the win; running "
                          "full-width (bit-identical by the compaction "
                          "contract)")
            repl.update(compact_cap=0)
        return dataclasses.replace(params, **repl) if repl else params

    def _build_variants(self) -> tuple[dict, dict]:
        exps = self.exps
        variants: dict[str, Any] = {
            "key": jnp.stack([rng.base_key(e.seed) for e in exps]),
            "loss_thr_vv": jnp.stack([
                jnp.asarray(rng.prob_threshold(np.asarray(e.loss_vv)))
                for e in exps]),
        }
        down, up = _stack_host_intervals(exps)
        variants["fault_down"] = jnp.asarray(down)
        variants["fault_up"] = jnp.asarray(up)
        lf = _stack_link_tables(exps)
        if lf is not None:
            variants["link_fault"] = tuple(jnp.asarray(a) for a in lf)
        rt = _stack_ramp_tables(exps)
        if rt is not None:
            variants["loss_ramp"] = tuple(jnp.asarray(a) for a in rt)
        if len(set(self.max_rounds)) > 1:
            variants["max_rounds"] = jnp.asarray(self.max_rounds, jnp.int32)
        has = {
            "stop": bool(down.min() < NO_STOP),
            "restart": bool((up < NO_STOP).any()),
            "link": lf is not None,
            "ramp": rt is not None,
        }
        return variants, has

    def _lane_ctx(self, var: dict) -> Ctx:
        """The solo Ctx lane e would close over, with this lane's variant
        leaves substituted (traced under vmap)."""
        params = self._base_ctx.params
        if "max_rounds" in var:
            params = dataclasses.replace(params, max_rounds=var["max_rounds"])
        return dataclasses.replace(
            self._base_ctx,
            params=params,
            key=var["key"],
            loss_thr_vv=var["loss_thr_vv"],
            fault_down=var["fault_down"],
            fault_up=var["fault_up"],
            link_fault=var.get("link_fault"),
            loss_ramp=var.get("loss_ramp"),
            init_model=var.get("init_model"),
        )

    def _lane_init_model(self, var: dict):
        ctx = self._lane_ctx(var)
        model0, _, _ = self._model.init(
            ctx, evbuf_init(self.exp.n_hosts, self.params.ev_cap))
        return model0

    # -- state -------------------------------------------------------------
    def _lane_init_state(self, var: dict) -> SimState:
        from shadow1_tpu.telemetry.links import link_init
        from shadow1_tpu.telemetry.probes import probe_init
        from shadow1_tpu.telemetry.ring import ring_init

        ctx = self._lane_ctx(var)
        evbuf = evbuf_init(self.exp.n_hosts, self.params.ev_cap)
        model, evbuf, seed_over = self._model.init(ctx, evbuf)
        metrics = _metrics_init()
        return SimState(
            win_start=jnp.zeros((), jnp.int64),
            evbuf=evbuf,
            outbox=outbox_init(self.exp.n_hosts, self.params.outbox_cap),
            model=model,
            metrics=metrics._replace(
                ev_overflow=metrics.ev_overflow + seed_over),
            cpu_busy=jnp.zeros(self.exp.n_hosts, jnp.int64),
            telem=ring_init(self.params.metrics_ring),
            probes=probe_init(self.params.metrics_ring, self.params.probes),
            links=link_init(self.params.link_telem,
                            np.asarray(self.exp.lat_vv).shape[0]),
        )

    def init_state(self) -> SimState:
        return jax.vmap(self._lane_init_state)(self._variants)

    def place_state(self, st: SimState) -> SimState:
        return jax.device_put(st)

    # -- run ---------------------------------------------------------------
    def _lane_window_step(self, st: SimState, var: dict) -> SimState:
        ctx = self._lane_ctx(var)
        handlers = self._model.make_handlers(ctx)
        pre = getattr(self._model, "make_pre_window",
                      lambda c: None)(ctx)
        return window_step(st, ctx, handlers, pre_window=pre,
                           make_handlers=self._model.make_handlers)

    def _make_run(self):
        # The per-lane variants ride as a TRACED ARGUMENT, not closure
        # constants: the compiled program is then a pure function of the
        # state/variant SHAPES, so a later experiment set of the same shape
        # class (new seeds, loss rates, fault schedules) reuses the
        # already-compiled executable via ``rebind`` — the serving plane's
        # hot-engine cache (shadow1_tpu/serve/cache.py) rests on this.
        def run(st: SimState, n_windows, variants) -> SimState:
            def body(_, s):
                return jax.vmap(self._lane_window_step)(s, variants)

            return jax.lax.fori_loop(0, n_windows, body, st)

        return run

    def run(self, st: SimState | None = None,
            n_windows: int | None = None) -> SimState:
        if st is None:
            st = self.init_state()
        n = n_windows if n_windows is not None else self.n_windows
        return self._run_jit(st, jnp.asarray(n, jnp.int32), self._variants)

    @staticmethod
    def _signature(variants: dict, has: dict) -> tuple:
        leaves, treedef = jax.tree_util.tree_flatten(variants)
        return (tuple(sorted(has.items())), str(treedef),
                tuple((tuple(l.shape), str(l.dtype)) for l in leaves))

    def variant_signature(self) -> tuple:
        """Trace-structure fingerprint of the variant pytree + has-flags:
        two experiment sets whose signatures match run through the SAME
        compiled program after ``rebind`` (jit keys on treedef + leaf
        shapes/dtypes; the has-flags are Python-level trace gates)."""
        return self._signature(self._variants, self._has)

    def rebind(self, exps: list, max_rounds: list[int] | None = None
               ) -> "FleetEngine":
        """Swap a NEW experiment set of the same shape class into this
        already-compiled engine — no re-jit, no re-trace.

        The new set may differ in exactly the fleet-variable knobs (seed,
        loss, fault schedules, legacy stop_time, per-lane max_rounds):
        those ride the variant pytree, which ``run`` takes as a traced
        argument. Everything the base ctx closes over (topology, window,
        caps, model config) must be identical — the serve-plane engine
        cache guarantees it by keying on the shape-class fingerprint
        (serve/cache.py); this method re-checks the cheap invariants and
        raises FleetConfigError (kind="mode") when the new set's trace
        structure (lane count, has-flags, variant table shapes) would
        force a recompile, so a caller can fall back to a fresh build."""
        if len(exps) != self.n_exp:
            raise FleetConfigError(
                f"rebind: lane count {len(exps)} != compiled {self.n_exp} "
                f"(state shapes differ — build a fresh engine)",
                kind="mode", knob="n_exp")
        for exp in exps:
            exp.validate()
        check_uniform(exps, [self.params] * len(exps))
        # The compiled program closed over the OLD exps' shared constants
        # (topology tables, horizon, model config): every field outside
        # the fleet-variable set must compare EQUAL to the compiled one,
        # not just within the new set — the serve cache's fingerprint
        # guarantees this, but a direct caller gets the same wall.
        from shadow1_tpu.fleet.expand import _VARIABLE_EXP, _np_equal

        for f in (fld.name for fld in dataclasses.fields(type(self.exp))):
            if f in _VARIABLE_EXP:
                continue
            if not _np_equal(getattr(self.exp, f), getattr(exps[0], f)):
                raise FleetConfigError(
                    f"rebind: {f!r} differs from the compiled engine's — "
                    f"it is closed over as a device constant (or picks "
                    f"shapes); a different shape class needs a fresh "
                    f"engine", kind="shape", knob=f)
        new_mr = [int(m) for m in
                  (max_rounds or [self.params.max_rounds] * self.n_exp)]
        if len(set(new_mr)) == 1 and new_mr[0] != self.params.max_rounds:
            # A uniform list is baked into the compiled program as
            # params.max_rounds (no variant leaf) — a different uniform
            # value cannot ride a rebind.
            raise FleetConfigError(
                f"rebind: uniform max_rounds {new_mr[0]} != compiled "
                f"{self.params.max_rounds} — baked into the traced round "
                f"loop; build a fresh engine", kind="mode",
                knob="max_rounds")
        old_exps, old_mr = self.exps, self.max_rounds
        old_variants, old_has = self._variants, self._has
        self.exps = list(exps)
        self.exp = exps[0]
        self.max_rounds = new_mr
        try:
            variants, has = self._build_variants()
            if has != old_has:
                raise FleetConfigError(
                    f"rebind: fault-plane trace gates changed "
                    f"({old_has} -> {has}) — the compiled program was "
                    f"traced without those passes; build a fresh engine",
                    kind="mode", knob="faults")
            if has["restart"]:
                cap = jax.vmap(self._lane_init_model)(variants)
                variants["init_model"] = jax.tree.map(
                    lambda x: jnp.asarray(np.asarray(x)), cap)
            if self._signature(variants, has) \
                    != self._signature(old_variants, old_has):
                raise FleetConfigError(
                    "rebind: variant table shapes changed (fault schedule "
                    "sizes / per-lane max_rounds presence) — a same-shape "
                    "program cannot serve them; build a fresh engine",
                    kind="mode", knob="variants")
        except Exception:
            self.exps, self.exp = old_exps, old_exps[0]
            self.max_rounds = old_mr
            raise
        self._variants, self._has = variants, has
        self.exp_base = 0
        self.exp_ids = None
        return self

    # -- accessors ---------------------------------------------------------
    @staticmethod
    def metrics_per_exp(st: SimState) -> list[dict[str, int]]:
        return fleet_metrics_per_exp(st)

    @staticmethod
    def metrics_dict(st: SimState) -> dict[str, int]:
        """Fleet AGGREGATE: counters sum across experiments, gauges max —
        keeps generic chunk plumbing (progress display, normalize)
        working; per-experiment truth is metrics_per_exp."""
        from shadow1_tpu.telemetry.registry import gauge_names

        gauges = set(gauge_names())
        out = {}
        for k, v in st.metrics._asdict().items():
            a = np.asarray(v)
            out[k] = int(a.max()) if k in gauges else int(a.sum())
        # windows advance in lockstep across lanes — report one fleet
        # window count, not E× it.
        out["windows"] = int(np.asarray(st.metrics.windows).max())
        out["rounds"] = int(np.asarray(st.metrics.rounds).max())
        return out

    def drain_rings(self, st: SimState, start: int = 0) -> list[dict]:
        recs = drain_fleet_rings(st, self.window, start=start,
                                 exp_base=self.exp_base,
                                 exp_ids=self.exp_ids)
        recs += drain_fleet_probes(st, self.window, self.params.probes,
                                   start=start, exp_base=self.exp_base,
                                   exp_ids=self.exp_ids)
        recs += drain_fleet_links(st, self.window, start=start,
                                  exp_base=self.exp_base,
                                  exp_ids=self.exp_ids)
        return recs

    @staticmethod
    def lane_done(st: SimState) -> np.ndarray:
        """Per-lane "nothing can ever happen again" flags ([E] bool).

        A lane is DONE when its event buffer holds no live event: all event
        creation flows from handling events (model init seeds the first
        ones; restart resets restore model columns but never push), and
        chunk boundaries always see an empty outbox (cleared at window
        end), so an empty buffer means every further window is a pure
        no-op on the lane's model/digest state. The basis of --lane-finalize
        (fleet/run.py slices such lanes out and emits their final record
        immediately). One [E, C, H] host fetch — call at chunk boundaries
        only, and only when the policy is on."""
        from shadow1_tpu.consts import K_NONE

        kind = np.asarray(st.evbuf.kind)          # [E, C, H]
        return ~(kind != K_NONE).any(axis=(-2, -1))

    def model_summary(self, st: SimState, e: int) -> dict[str, Any]:
        lane = slice_experiment(st, e)
        return jax.tree.map(
            np.asarray, self._model.summary(lane.model, self._base_ctx))
