// Thread-per-core PHOLD comparator — the honest CPU baseline.
//
// The north-star target (BASELINE.json) is measured against a
// "thread-per-core CPU scheduler"; this is that scheduler, built the way
// the reference builds it (src/main/core/scheduler/scheduler-policy-host-
// steal.c: hosts partitioned across worker threads, conservative windows
// with barrier rounds, cross-thread event push through locked queues) —
// NOT the Python oracle, whose interpreter overhead would flatter the TPU
// engine by orders of magnitude.
//
// Exact-parity contract: this program simulates the IDENTICAL experiment
// the JAX engine and the Python oracle run — same splitmix64 counter RNG
// (the Q32 log2 table is loaded from a file dumped by Python so no libm
// rounding difference can creep in), same fixed-point exponential, same
// multiply-shift randint, same (time, tb) event order, same ev_cap /
// outbox_cap accounting (docs/SEMANTICS.md). Its event/packet counters
// must equal the other two engines' bit for bit (tests/test_native_
// comparator.py), which is what makes its wall-clock an honest baseline.
//
// Usage:
//   phold_comparator <table_file> <n_hosts> <seed> <n_windows> <window_ns>
//                    <mean_delay_ns> <init_events> <ev_cap> <outbox_cap>
//                    <n_threads>
// Prints one JSON line with counters and wall seconds.

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------- RNG ----
// Mirrors shadow1_tpu/rng.py exactly (integer pipeline).
constexpr uint64_t C1 = 0xBF58476D1CE4E5B9ull;
constexpr uint64_t C2 = 0x94D049BB133111EBull;
constexpr uint64_t P1 = 0x9E3779B97F4A7C15ull;
constexpr uint64_t P2 = 0xC2B2AE3D27D4EB4Full;
constexpr uint64_t P3 = 0x165667B19E3779F9ull;
constexpr int LOG_BITS = 12;

uint64_t LOG_TBL[(1 << LOG_BITS) + 1];  // loaded from the Python dump
uint64_t LN2_Q32 = 0;                   // loaded (= round(ln 2 * 2^32))

inline uint64_t mix(uint64_t z) {
  z ^= z >> 30; z *= C1; z ^= z >> 27; z *= C2; z ^= z >> 31; return z;
}

inline uint64_t base_key(uint64_t seed) { return seed * P1 + C2; }

inline uint32_t rng_bits(uint64_t key, uint64_t purpose, uint64_t host,
                         uint64_t ctr) {
  uint64_t z = key + purpose * P1 + host * P2 + ctr * P3;
  return static_cast<uint32_t>(mix(mix(z)) >> 32);
}

inline uint64_t neg_log1m_q32(uint32_t b) {
  uint64_t x = (1ull << 32) - static_cast<uint64_t>(b);  // [1, 2^32]
  int k = 63 - __builtin_clzll(x);
  uint64_t m = x << (63 - k);
  uint64_t frac = (m << 1) >> 1;
  uint64_t idx = frac >> (63 - LOG_BITS);
  uint64_t rem = (frac >> (63 - LOG_BITS - 24)) & ((1ull << 24) - 1);
  uint64_t lo = LOG_TBL[idx], hi = LOG_TBL[idx + 1];
  uint64_t log2_frac = lo + (((hi - lo) * rem) >> 24);
  uint64_t log2_x = (static_cast<uint64_t>(k) << 32) + log2_frac;
  uint64_t e2 = (32ull << 32) - log2_x;
  return (e2 * (LN2_Q32 >> 5)) >> 27;
}

inline int64_t exponential_ns(uint32_t b, uint64_t mean_ns) {
  uint64_t e = neg_log1m_q32(b);
  if (mean_ns > (1ull << 38)) mean_ns = 1ull << 38;
  uint64_t d = mean_ns * (e >> 32) + ((mean_ns * ((e & 0xFFFFFFFFull) >> 7)) >> 25);
  return d < 1 ? 1 : static_cast<int64_t>(d);
}

inline int32_t randint(uint32_t b, uint64_t n) {
  return static_cast<int32_t>((static_cast<uint64_t>(b) * n) >> 32);
}

// ------------------------------------------------------------- engine ----
constexpr uint64_t R_PHOLD_DELAY = 1, R_PHOLD_DST = 2;
constexpr int64_t TB_PACKET_BASE = 1ll << 62;

struct Ev {
  int64_t time;
  int64_t tb;
  int32_t host;
  bool operator>(const Ev& o) const {
    if (time != o.time) return time > o.time;
    return tb > o.tb;
  }
};

struct Shard {
  std::priority_queue<Ev, std::vector<Ev>, std::greater<Ev>> heap;
  std::vector<Ev> mailbox;          // cross-thread deliveries (locked)
  std::mutex mbox_mu;
  // counters
  int64_t events = 0, pkts_sent = 0, pkts_delivered = 0;
  int64_t ev_overflow = 0, ob_overflow = 0;
  char pad[64];                     // no false sharing between shards
};

int main_run(int argc, char** argv) {
  if (argc != 11) {
    std::fprintf(stderr, "need 10 args\n");
    return 2;
  }
  const char* table_file = argv[1];
  const int64_t n_hosts = std::atoll(argv[2]);
  const uint64_t seed = std::strtoull(argv[3], nullptr, 10);
  const int64_t n_windows = std::atoll(argv[4]);
  const int64_t window_ns = std::atoll(argv[5]);
  const uint64_t mean_delay = std::strtoull(argv[6], nullptr, 10);
  const int init_events = std::atoi(argv[7]);
  const int64_t ev_cap = std::atoll(argv[8]);
  const int64_t ob_cap = std::atoll(argv[9]);
  const int n_threads = std::atoi(argv[10]);

  {  // Q32 log2 table + ln2 constant, dumped by shadow1_tpu.native
    std::FILE* f = std::fopen(table_file, "rb");
    if (!f) { std::fprintf(stderr, "cannot open %s\n", table_file); return 2; }
    size_t want = (1 << LOG_BITS) + 1;
    if (std::fread(LOG_TBL, 8, want, f) != want ||
        std::fread(&LN2_Q32, 8, 1, f) != 1) {
      std::fprintf(stderr, "bad table file\n");
      std::fclose(f);
      return 2;
    }
    std::fclose(f);
  }

  const uint64_t key = base_key(seed);
  const int64_t lat = window_ns;  // single-vertex experiment: lat == window
  const int64_t end_time = n_windows * window_ns;

  // Per-host state (SoA, shared; each host touched by exactly one thread).
  std::vector<int64_t> self_ctr(n_hosts, 0), pkt_ctr(n_hosts, 0),
      draw_ctr(n_hosts, 0), pending(n_hosts, 0), ob_used(n_hosts, 0),
      ob_win(n_hosts, -1), hops(n_hosts, 0);

  std::vector<Shard> shards(n_threads);
  auto owner = [&](int64_t h) {
    return static_cast<int>(h * n_threads / n_hosts);
  };

  // Seed: init_events per host at t=0 (tb = self_ctr ordering, ev_cap'd).
  for (int64_t h = 0; h < n_hosts; ++h) {
    Shard& s = shards[owner(h)];
    for (int i = 0; i < init_events; ++i) {
      if (pending[h] >= ev_cap) { s.ev_overflow++; continue; }
      pending[h]++;
      s.heap.push({0, self_ctr[h]++, static_cast<int32_t>(h)});
    }
  }

  std::atomic<int> barrier_count{0};
  std::atomic<int64_t> barrier_gen{0};
  auto barrier = [&]() {
    int64_t gen = barrier_gen.load();
    if (barrier_count.fetch_add(1) == n_threads - 1) {
      barrier_count.store(0);
      barrier_gen.fetch_add(1);
    } else {
      while (barrier_gen.load() == gen) std::this_thread::yield();
    }
  };

  auto worker = [&](int t) {
    Shard& me = shards[t];
    for (int64_t w = 0; w < n_windows; ++w) {
      const int64_t win_end = (w + 1) * window_ns;
      while (!me.heap.empty() && me.heap.top().time < win_end) {
        Ev ev = me.heap.top();
        me.heap.pop();
        const int64_t h = ev.host;
        pending[h]--;
        me.events++;
        hops[h]++;
        // PHOLD hop: exponential delay + uniform destination.
        const int64_t c = draw_ctr[h]++;
        const int64_t delay =
            exponential_ns(rng_bits(key, R_PHOLD_DELAY, h, c), mean_delay);
        const int32_t dst = randint(rng_bits(key, R_PHOLD_DST, h, c),
                                    static_cast<uint64_t>(n_hosts));
        const int64_t t_next = ev.time + delay;
        if (dst == h) {
          if (pending[h] >= ev_cap) { me.ev_overflow++; continue; }
          pending[h]++;
          me.heap.push({t_next, self_ctr[h]++, static_cast<int32_t>(h)});
        } else {
          // outbox accounting per (src, window of `now`)
          const int64_t cur_win = ev.time / window_ns;
          if (ob_win[h] != cur_win) { ob_win[h] = cur_win; ob_used[h] = 0; }
          if (ob_used[h] >= ob_cap) { me.ob_overflow++; continue; }
          ob_used[h]++;
          const int64_t pc = pkt_ctr[h]++;
          me.pkts_sent++;
          // loss_vv == 0 on the bench config; loss draw elided (the Python
          // oracle draws lazily per packet only when loss > 0... it draws
          // always; counters unaffected since threshold 0 never fires)
          const int64_t arrival = t_next + lat;
          const int64_t tb = TB_PACKET_BASE + (h << 32) + (pc & 0xFFFFFFFF);
          Shard& dsts = shards[owner(dst)];
          if (&dsts == &me) {
            // same thread: deliver directly (arrival is next window —
            // conservative lookahead keeps this window-safe)
            if (pending[dst] >= ev_cap) { me.ev_overflow++; continue; }
            pending[dst]++;
            me.pkts_delivered++;
            me.heap.push({arrival, tb, dst});
          } else {
            std::lock_guard<std::mutex> g(dsts.mbox_mu);
            dsts.mailbox.push_back({arrival, tb, dst});
          }
        }
      }
      barrier();  // all threads done with [w*W, (w+1)*W)
      // drain my mailbox (ev_cap accounting on MY hosts — single writer)
      {
        std::lock_guard<std::mutex> g(me.mbox_mu);
        for (const Ev& ev : me.mailbox) {
          if (pending[ev.host] >= ev_cap) { me.ev_overflow++; continue; }
          pending[ev.host]++;
          me.pkts_delivered++;
          me.heap.push(ev);
        }
        me.mailbox.clear();
      }
      barrier();  // mailboxes drained before anyone enters the next window
    }
  };

  auto t0 = std::chrono::steady_clock::now();
  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker, t);
  for (auto& th : threads) th.join();
  const double wall =
      std::chrono::duration<double>(std::chrono::steady_clock::now() - t0)
          .count();

  int64_t events = 0, sent = 0, deliv = 0, ev_over = 0, ob_over = 0;
  for (const Shard& s : shards) {
    events += s.events; sent += s.pkts_sent; deliv += s.pkts_delivered;
    ev_over += s.ev_overflow; ob_over += s.ob_overflow;
  }
  (void)end_time;
  std::printf(
      "{\"events\": %lld, \"pkts_sent\": %lld, \"pkts_delivered\": %lld, "
      "\"ev_overflow\": %lld, \"ob_overflow\": %lld, \"wall_s\": %.6f, "
      "\"events_per_sec\": %.1f, \"n_threads\": %d}\n",
      static_cast<long long>(events), static_cast<long long>(sent),
      static_cast<long long>(deliv), static_cast<long long>(ev_over),
      static_cast<long long>(ob_over), wall, events / wall, n_threads);
  return 0;
}

}  // namespace

int main(int argc, char** argv) { return main_run(argc, argv); }
