"""Determinism flight recorder: state digests + paritytrace bisection.

The digest contract (ISSUE 3 acceptance, docs/SEMANTICS.md §"State
digest"): per-window subsystem digest words are bit-identical across the
CPU oracle, the single-chip engine, the sharded engine, the pallas/xla
kernel variants and a checkpoint-resumed run; they are invariant under
slot-layout permutation (identity lives in (time, tb) keys, never slot
indices); and a single flipped bit in any digested subsystem changes that
subsystem's word in that window. ``tools/paritytrace.py`` turns the stream
into a first-divergence bisector — tested here end to end via corruption
injection.
"""

import dataclasses
import json

import numpy as np
import pytest

from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import MS, SEC, EngineParams
from shadow1_tpu.core import digest as D
from shadow1_tpu.core.engine import Engine
from shadow1_tpu.cpu_engine import CpuEngine
from shadow1_tpu.telemetry.registry import RING_DIGESTS
from shadow1_tpu.telemetry.ring import drain_ring


def phold_exp(n_hosts=16, seed=7, end=200 * MS, loss=0.0):
    return single_vertex_experiment(
        n_hosts=n_hosts,
        seed=seed,
        end_time=end,
        latency_ns=10 * MS,
        loss=loss,
        model="phold",
        model_cfg={"mean_delay_ns": float(20 * MS), "init_events": 2},
    )


def filexfer_exp(n_hosts=2, seed=11, loss=0.02, end=4 * SEC):
    role = np.full(n_hosts, 1, np.int64)
    role[0] = 0
    return single_vertex_experiment(
        n_hosts=n_hosts,
        seed=seed,
        end_time=end,
        latency_ns=10 * MS,
        loss=loss,
        bw_bits=10**7,
        model="net",
        model_cfg={
            "app": "filexfer",
            "role": role,
            "server": np.zeros(n_hosts, np.int64),
            "flow_bytes": np.full(n_hosts, 40_000, np.int64),
            "start_time": np.full(n_hosts, 1 * MS, np.int64),
            "flow_count": np.where(role == 1, 1, 0),
        },
    )


DIGEST_PARAMS = EngineParams(metrics_ring=1024, state_digest=1)


def ring_digests(st, window_ns):
    return {
        r["window"]: tuple(r[f] for f in RING_DIGESTS)
        for r in drain_ring(st, window_ns)
        if r["type"] == "ring"
    }


def oracle_digests(eng):
    return {
        r["window"]: tuple(r[f"dg_{s}"] for s in D.SUBSYSTEMS)
        for r in eng.digest_rows
    }


def assert_streams_equal(a, b, label):
    assert sorted(a) == sorted(b), (label, "window sets differ")
    for w in sorted(a):
        assert a[w] == b[w], (label, "window", w, a[w], b[w])


# ---------------------------------------------------------------------------
# implementation twins
# ---------------------------------------------------------------------------

def test_word_impl_twins_agree():
    """The jnp, numpy-vector, and python-int hash pipelines are the same
    function — the precondition for oracle↔engine digest equality."""
    rng = np.random.RandomState(3)
    hosts = np.arange(8, dtype=np.int64)
    cols = [hosts] + [rng.randint(0, 1 << 62, 8).astype(np.int64)
                      for _ in range(3)]
    w_np = D._words_np(D.SEED_RNG, cols)
    w_jnp = np.asarray(D._words(D.SEED_RNG, cols))
    np.testing.assert_array_equal(w_np, w_jnp)
    for i in range(8):
        assert D.word_int(D.SEED_RNG, [c[i] for c in cols]) == int(w_np[i])
    # i32 masking rule: an i32 field hashes as its low 32 bits.
    v32 = rng.randint(-(1 << 31), 1 << 31, 8).astype(np.int32)
    w_np = D._words_np(D.SEED_EVBUF, [hosts, v32])
    w_jnp = np.asarray(D._words(D.SEED_EVBUF, [hosts,
                                               np.asarray(v32)]))
    np.testing.assert_array_equal(w_np, w_jnp)
    for i in range(8):
        assert D.word_int(
            D.SEED_EVBUF, [hosts[i], int(v32[i]) & 0xFFFFFFFF]
        ) == int(w_np[i])


# ---------------------------------------------------------------------------
# invariance + sensitivity fuzz
# ---------------------------------------------------------------------------

def _mid_state(exp=None, params=None):
    eng = Engine(exp or phold_exp(), params or DIGEST_PARAMS)
    st = eng.run(n_windows=5)
    return eng, st


def test_evbuf_digest_slot_permutation_invariant():
    """Permuting event slots (what a cap migration or a different push
    layout does) must not change the digest: identity is (host, time, tb),
    never the slot index."""
    import jax.numpy as jnp

    eng, st = _mid_state()
    buf = st.evbuf
    assert int(np.asarray((buf.kind != 0).sum())) > 0
    rng = np.random.RandomState(0)
    perm = rng.permutation(buf.kind.shape[0])
    permuted = buf._replace(
        time_hi=buf.time_hi[perm], time_lo=buf.time_lo[perm],
        t32=buf.t32[perm], tb_hi=buf.tb_hi[perm], tb_lo=buf.tb_lo[perm],
        kind=buf.kind[perm], p=jnp.asarray(np.asarray(buf.p)[:, perm]),
    )
    hosts = eng.ctx.hosts
    assert int(D.digest_evbuf(buf, hosts)) == int(
        D.digest_evbuf(permuted, hosts))


def test_digest_bit_flip_changes_exactly_its_subsystem():
    """A single corrupted value in any digested plane changes that
    subsystem's word (and, for independent planes, only that word)."""
    eng, st = _mid_state()
    hosts = eng.ctx.hosts
    dg0 = np.asarray(D.state_digests(st, eng.ctx, jnp_zero()))

    def vec(st2):
        return np.asarray(D.state_digests(st2, eng.ctx, jnp_zero()))

    # evbuf: flip one payload bit of an occupied slot
    kind = np.asarray(st.evbuf.kind)
    c, h = [int(x[0]) for x in np.nonzero(kind != 0)]
    p = np.asarray(st.evbuf.p).copy()
    p[0, c, h] ^= 1
    st_ev = st._replace(evbuf=st.evbuf._replace(p=p))
    delta = vec(st_ev) != dg0
    assert delta[0] and not delta[2] and not delta[3] and not delta[4]

    # rng: bump a tie-break counter
    sc = np.asarray(st.evbuf.self_ctr).copy()
    sc[3] += 1
    delta = vec(st._replace(evbuf=st.evbuf._replace(self_ctr=sc))) != dg0
    assert delta[4] and not delta[0]

    # outbox digest: sends of a window hash through digest_outbox
    ob0 = int(D.digest_outbox(st.outbox, hosts))
    cnt = np.asarray(st.outbox.cnt).copy()
    if cnt.max() == 0:  # make a slot visible if the boundary outbox is empty
        cnt[0] = 1
    else:
        cnt[int(cnt.argmax())] -= 1
    ob1 = int(D.digest_outbox(
        st.outbox._replace(cnt=cnt), hosts))
    assert ob0 != ob1


def jnp_zero():
    import jax.numpy as jnp

    return jnp.zeros((), jnp.int64)


def test_tcp_nic_digest_bit_flip_sensitivity():
    eng, st = _mid_state(filexfer_exp(end=2 * SEC),
                         dataclasses.replace(DIGEST_PARAMS))
    dg0 = np.asarray(D.state_digests(st, eng.ctx, jnp_zero()))
    # tcp: bump a live socket's snd_nxt
    tcp = dict(st.model.tcp)
    live = np.nonzero(np.asarray(tcp["st"]) != 0)
    assert len(live[0]), "no live socket mid-transfer?"
    v = np.asarray(tcp["snd_nxt"]).copy()
    v[live[0][0], live[1][0]] ^= 1
    tcp["snd_nxt"] = v
    d = np.asarray(D.state_digests(
        st._replace(model=st.model._replace(tcp=tcp)), eng.ctx,
        jnp_zero())) != dg0
    assert d[2] and not d[3] and not d[4] and not d[0]
    # nic: bump a byte counter
    nb = np.asarray(st.model.nic.rx_bytes).copy()
    nb[1] += 1
    d = np.asarray(D.state_digests(
        st._replace(model=st.model._replace(
            nic=st.model.nic._replace(rx_bytes=nb))), eng.ctx,
        jnp_zero())) != dg0
    assert d[3] and not d[2]


# ---------------------------------------------------------------------------
# cross-engine / cross-impl stream equality (the acceptance matrix)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("loss", [0.0, 0.3])
def test_phold_digest_stream_cpu_vs_tpu(loss):
    exp = phold_exp(loss=loss)
    cpu = CpuEngine(exp, DIGEST_PARAMS)
    cpu.run()
    eng = Engine(exp, DIGEST_PARAMS)
    st = eng.run()
    assert_streams_equal(ring_digests(st, eng.window), oracle_digests(cpu),
                         f"phold loss={loss}")


def test_net_digest_stream_cpu_vs_tpu():
    """TCP/NIC plane digests under loss (retransmits, dup-ACKs, FIN
    teardown all exercised by the lossy transfer)."""
    exp = filexfer_exp()
    cpu = CpuEngine(exp, DIGEST_PARAMS)
    cpu.run()
    eng = Engine(exp, DIGEST_PARAMS)
    st = eng.run()
    tm = Engine.metrics_dict(st)
    assert tm["tcp_rto"] + tm["tcp_fast_rtx"] > 0  # loss actually recovered
    assert_streams_equal(ring_digests(st, eng.window), oracle_digests(cpu),
                         "filexfer")


def test_digest_stream_sharded_vs_single():
    from shadow1_tpu.shard.engine import ShardedEngine

    exp = phold_exp(n_hosts=64, end=100 * MS)
    eng = Engine(exp, DIGEST_PARAMS)
    st1 = eng.run()
    sh = ShardedEngine(exp, DIGEST_PARAMS)
    assert sh.n_dev == 8
    st8 = sh.run()
    assert_streams_equal(ring_digests(st1, eng.window),
                         ring_digests(st8, sh.window), "sharded")


def test_digest_stream_pallas_vs_xla():
    exp = phold_exp(n_hosts=8, end=100 * MS)
    a = Engine(exp, dataclasses.replace(DIGEST_PARAMS, ev_cap=32,
                                        outbox_cap=32))
    b = Engine(exp, dataclasses.replace(DIGEST_PARAMS, ev_cap=32,
                                        outbox_cap=32, pop_impl="pallas",
                                        push_impl="pallas"))
    assert_streams_equal(ring_digests(a.run(), a.window),
                         ring_digests(b.run(), b.window), "pallas")


def test_digest_stream_resume_vs_straight(tmp_path):
    """No digest state rides snapshots — the words are pure functions of
    engine state, so a save/load roundtrip continues the stream exactly."""
    from shadow1_tpu import ckpt

    exp = phold_exp()
    eng = Engine(exp, DIGEST_PARAMS)
    ref = eng.run(n_windows=20)
    path = str(tmp_path / "dg.npz")
    st = eng.run(n_windows=10)
    ckpt.save_state(st, path)
    st = ckpt.load_state(eng.init_state(), path)
    st = eng.run(st, n_windows=10)
    assert_streams_equal(ring_digests(ref, eng.window),
                         ring_digests(st, eng.window), "resume")


def test_digest_off_means_zero_columns_and_requires_ring():
    eng = Engine(phold_exp(), EngineParams(metrics_ring=64))
    st = eng.run(n_windows=5)
    for r in drain_ring(st, eng.window):
        assert all(r[f] == 0 for f in RING_DIGESTS)
    with pytest.raises(ValueError, match="metrics_ring"):
        Engine(phold_exp(), EngineParams(state_digest=1))


# ---------------------------------------------------------------------------
# paritytrace end to end
# ---------------------------------------------------------------------------

PHOLD_YAML = """\
general: {{seed: 7, stop_time: {stop} ms}}
engine: {{scheduler: tpu}}
network: {{single_vertex: {{latency: 10 ms}}}}
hosts:
  - {{name: host, count: 12}}
app:
  model: phold
  params: {{mean_delay_ns: 2.0e7, init_events: 2}}
"""


def _write_cfg(tmp_path, stop_ms=400):
    p = tmp_path / "phold.yaml"
    p.write_text(PHOLD_YAML.format(stop=stop_ms))
    return str(p)


def test_paritytrace_clean_run_exit_zero(tmp_path, capsys):
    from shadow1_tpu.tools import paritytrace

    rc = paritytrace.main([_write_cfg(tmp_path), "tpu", "cpu",
                           "--windows", "20", "--chunk", "8"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["first_divergence"] is None


@pytest.mark.parametrize("subsys,side", [("rng", "b"), ("evbuf", "a")])
def test_paritytrace_localizes_injected_corruption(tmp_path, capsys,
                                                   subsys, side):
    """The acceptance bisect: a corruption injected at window K is reported
    as first divergence exactly (K, subsys), whichever side is corrupted."""
    from shadow1_tpu.tools import paritytrace

    dump = tmp_path / "diff.jsonl"
    rc = paritytrace.main([
        _write_cfg(tmp_path), "tpu", "cpu", "--windows", "30",
        "--chunk", "10", "--inject", f"17:{subsys}:{side}",
        "--dump", str(dump),
    ])
    assert rc == 3
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["first_divergence"]["window"] == 17
    assert out["first_divergence"]["subsystems"] == [subsys]
    recs = [json.loads(x) for x in dump.read_text().splitlines()]
    assert any(r.get("type") == "plane_diff" for r in recs)


def test_paritytrace_resume_side_identical(tmp_path, capsys):
    from shadow1_tpu.tools import paritytrace

    rc = paritytrace.main([_write_cfg(tmp_path, stop_ms=200), "tpu",
                           "tpu+resume", "--windows", "12", "--chunk", "4"])
    assert rc == 0
