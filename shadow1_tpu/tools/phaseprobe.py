"""Per-phase wall attribution of the jitted window program.

    python -m shadow1_tpu.tools.phaseprobe smoke            # dense phold
    python -m shadow1_tpu.tools.phaseprobe cfg.yaml         # any config
    python -m shadow1_tpu.tools.phaseprobe cfg.yaml --device-trace DIR

The performance attribution plane's wall-clock half (the op/fusion half is
tools/opcensus.py). ``core/engine.window_step`` is the composition of the
``window_phases`` stage list — prepare (restart resets, work gauges, the
net model's NIC arrival batch, rebase), rounds (the pop + handler
while-loop), deliver (route + scatter + clear), telem (gauges + the
telemetry-ring row). This tool times each stage as its OWN jitted program
over window frames captured from a real run of the config, so every
ms/round of the straight run attributes to a phase:

* **capture** — run N windows stage-by-stage (same composition, same
  states bit-for-bit) recording each stage's input frame;
* **replay** — for each stage, one jitted ``lax.scan`` maps the stage over
  its N captured inputs; the min wall over reps is that phase's cost for
  those N windows (min, not mean: shared-container noise only ever adds);
* **total** — the straight ``engine.run`` over the same N windows from the
  same start state, same min-over-reps discipline;
* **coverage** — Σ phase wall / straight wall. The phases PARTITION the
  window program, so coverage ≈ 1; the jit boundaries the split adds cost
  extra rather than hiding work, so coverage < 0.9 means the attribution
  is broken (the acceptance gate: ``--min-coverage 0.9`` exits 1).

Two sub-phase rows refine the big stages without entering the coverage
sum (they are contained in their parents, estimated from isolated-primitive
timings × measured rounds/window): ``rounds.pop_est`` (the pop chain — the
rest of ``rounds`` is the handler passes) and ``deliver.route_est`` (the
latency/loss routing — the rest of ``deliver`` is the destination scatter).

``--device-trace DIR`` additionally captures a ``jax.profiler`` device
trace of one straight chunk through telemetry/profiler.device_trace: the
engine's ``jax.named_scope("phase:...")`` annotations make the window
phases appear as spans in Perfetto (https://ui.perfetto.dev) next to the
host-side phase spans (``DIR/phases.trace.json``).

Prints one JSON line per phase plus a final summary line on stdout (the
bench.py contract) and an aligned human table on stderr; ``--md`` emits
the markdown attribution table docs/PERF.md commits.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

# The dense-phold smoke shape — tools/benchgate.py's phold row, so the
# attribution and the regression gate describe the same program.
SMOKE_HOSTS = 2048
SMOKE_EV_CAP = 48
SMOKE_OUTBOX_CAP = 24


def build_engine(config: str, metrics_ring: int = 0, hosts: int = SMOKE_HOSTS):
    """(engine, config_label) for a YAML path or the built-in "smoke"."""
    import dataclasses

    from shadow1_tpu.consts import MS, EngineParams
    from shadow1_tpu.core.engine import Engine

    if config == "smoke":
        from shadow1_tpu.config.compiled import single_vertex_experiment

        exp = single_vertex_experiment(
            n_hosts=hosts, seed=1234, end_time=10**15,
            latency_ns=1 * MS, model="phold",
            model_cfg={"mean_delay_ns": float(2 * MS), "init_events": 16},
        )
        params = EngineParams(ev_cap=SMOKE_EV_CAP, outbox_cap=SMOKE_OUTBOX_CAP,
                              max_rounds=128, metrics_ring=metrics_ring)
        return Engine(exp, params), "smoke_phold"
    from shadow1_tpu.config.experiment import load_experiment

    exp, params, scheduler = load_experiment(config)
    if scheduler not in (None, "tpu"):
        raise SystemExit(f"phaseprobe attributes the single-device window "
                         f"program; config asks for scheduler={scheduler!r}")
    if metrics_ring:
        params = dataclasses.replace(params, metrics_ring=metrics_ring)
    import os

    return Engine(exp, params), os.path.basename(config)


def capture_frames(eng, st0, n_windows: int):
    """Run ``n_windows`` stage-by-stage from ``st0``, recording each stage's
    input frames (stacked [N, ...] pytrees). The staged composition IS
    window_step, so the captured states match the straight run bit-for-bit."""
    import jax
    import jax.numpy as jnp

    from shadow1_tpu.core.engine import window_frame, window_phases

    phases = window_phases(eng.ctx, eng._handlers, None, eng._pre_window,
                           eng._model.make_handlers, None)
    jitted = {name: jax.jit(fn) for name, fn in phases}
    inputs = {name: [] for name, _ in phases}
    st = st0
    for _ in range(n_windows):
        fr = window_frame(st, eng.ctx)
        for name, _fn in phases:
            inputs[name].append(fr)
            fr = jitted[name](fr)
        st = fr.st
    stacked = {
        name: jax.tree.map(lambda *xs: jnp.stack(xs), *frs)
        for name, frs in inputs.items()
    }
    return phases, stacked, st


def _time_reps(f, arg, reps: int) -> float:
    """min wall of ``jax.block_until_ready(f(arg))`` over ``reps`` (after a
    compile warmup) — the roundprobe discipline."""
    import jax

    jax.block_until_ready(f(arg))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(f(arg))
        best = min(best, time.perf_counter() - t0)
    return best


def _scan_phase(fn):
    """One jitted program mapping ``fn`` over the stacked frames. The scan
    RETURNS the stacked outputs, so XLA cannot dead-code-eliminate any of
    the phase's work."""
    import jax
    import jax.numpy as jnp

    def mapped(frames):
        def body(carry, fr):
            return carry, fn(fr)

        _, outs = jax.lax.scan(body, jnp.zeros((), jnp.int32), frames)
        return outs

    return jax.jit(mapped)


def attribution(eng, n_windows: int = 16, warmup: int = 8, reps: int = 3,
                subphases: bool = True) -> dict:
    """The per-config attribution table: phase → ms/window, ms/round, % of
    the straight run, plus total/coverage. Importable (tests/ci assert on
    the returned dict); the CLI below is a thin printer around it."""
    import jax

    from shadow1_tpu.core.engine import Engine

    st0 = eng.run(eng.init_state(), n_windows=warmup)
    jax.block_until_ready(st0)
    m0 = Engine.metrics_dict(st0)

    # The straight reference: the engine's own jitted window loop.
    def straight(st):
        return eng.run(st, n_windows=n_windows)

    total_s = _time_reps(straight, st0, reps)
    st1 = eng.run(st0, n_windows=n_windows)
    jax.block_until_ready(st1)
    m1 = Engine.metrics_dict(st1)
    rounds = m1["rounds"] - m0["rounds"]
    rpw = rounds / n_windows

    phases, stacked, st_cap = capture_frames(eng, st0, n_windows)
    # Capture must reproduce the straight run exactly — the attribution is
    # meaningless if the staged states drifted.
    assert Engine.metrics_dict(st_cap) == m1, (
        "staged composition diverged from window_step — phase refactor bug"
    )
    total_ms_w = total_s * 1e3 / n_windows
    out = {
        "windows": n_windows,
        "reps": reps,
        "rounds_per_window": round(rpw, 2),
        "ms_per_window": round(total_ms_w, 4),
        "ms_per_round": round(total_s * 1e3 / max(rounds, 1), 4),
        "events": m1["events"] - m0["events"],
        "phases": {},
        "subphases": {},
    }
    phase_sum = 0.0
    for name, fn in phases:
        wall = _time_reps(_scan_phase(fn), stacked[name], reps)
        ms_w = wall * 1e3 / n_windows
        phase_sum += ms_w
        out["phases"][name] = {
            "ms_per_window": round(ms_w, 4),
            "ms_per_round": round(wall * 1e3 / max(rounds, 1), 4),
            "pct": round(100 * ms_w / total_ms_w, 1) if total_ms_w else None,
        }
    out["phases_ms_per_window"] = round(phase_sum, 4)
    out["coverage"] = round(phase_sum / total_ms_w, 3) if total_ms_w else None

    if subphases:
        # Contained estimates (never in the coverage sum): isolate the pop
        # chain and the routing gather on the frames they actually see.
        from shadow1_tpu.core.engine import route_outbox
        from shadow1_tpu.core.events import pop_until

        def pop_fn(fr):
            buf, ev = pop_until(fr.st.evbuf, fr.win_end,
                                extract=eng.ctx.params.pop_extract)
            return fr._replace(st=fr.st._replace(evbuf=buf))

        pop_wall = _time_reps(_scan_phase(pop_fn), stacked["rounds"], reps)
        out["subphases"]["rounds.pop_est"] = {
            "ms_per_window": round(pop_wall * 1e3 * rpw / n_windows, 4),
            "ms_per_round": round(pop_wall * 1e3 / n_windows, 4),
            "note": "one pop x measured rounds/window",
        }

        def route_fn(fr):
            fp, n_sent, n_lost, n_ld = route_outbox(eng.ctx, fr.st.outbox)
            return fr._replace(dg_ob=fr.dg_ob + n_sent + n_lost + n_ld
                               + fp.arrival.sum() + fp.keep.sum())

        route_wall = _time_reps(_scan_phase(route_fn), stacked["deliver"],
                                reps)
        out["subphases"]["deliver.route_est"] = {
            "ms_per_window": round(route_wall * 1e3 / n_windows, 4),
            "ms_per_round": round(route_wall * 1e3 / max(rounds, 1), 4),
            "note": "route_outbox alone on the deliver-phase inputs",
        }
    return out


def _table(label: str, att: dict, md: bool = False) -> str:
    rows = [("phase", "ms/window", "ms/round", "% of round")]
    for name, d in att["phases"].items():
        rows.append((name, f"{d['ms_per_window']:.3f}",
                     f"{d['ms_per_round']:.3f}", f"{d['pct']:.1f}%"))
    for name, d in att["subphases"].items():
        rows.append((f"  {name}", f"{d['ms_per_window']:.3f}",
                     f"{d['ms_per_round']:.3f}", "(contained)"))
    rows.append(("TOTAL (straight run)", f"{att['ms_per_window']:.3f}",
                 f"{att['ms_per_round']:.3f}", "100%"))
    rows.append(("coverage (Σ phases / total)", "", "",
                 f"{att['coverage'] * 100:.1f}%"))
    lines = [f"== phase attribution: {label} "
             f"({att['windows']} windows, {att['rounds_per_window']} "
             f"rounds/window) =="]
    if md:
        lines = [f"| {' | '.join(rows[0])} |",
                 "|" + "---|" * len(rows[0])]
        lines += [f"| {' | '.join(r)} |" for r in rows[1:]]
        return "\n".join(lines)
    width = [max(len(r[i]) for r in rows) for i in range(4)]
    for r in rows:
        lines.append("  " + "  ".join(c.ljust(w) for c, w in zip(r, width)))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="shadow1_tpu.tools.phaseprobe")
    ap.add_argument("config", help='YAML experiment file or "smoke" '
                                   "(the benchgate dense-phold shape)")
    ap.add_argument("--windows", type=int, default=16,
                    help="windows to capture and attribute (default 16)")
    ap.add_argument("--warmup", type=int, default=8,
                    help="windows run before capture (state realism)")
    ap.add_argument("--reps", type=int, default=3,
                    help="timing reps; min wall is reported")
    ap.add_argument("--hosts", type=int, default=SMOKE_HOSTS,
                    help="host count for the smoke config")
    ap.add_argument("--metrics-ring", type=int, default=0,
                    help="attribute with a W-deep telemetry ring (the telem "
                         "phase is ~empty without one)")
    ap.add_argument("--min-coverage", type=float, default=0.0,
                    help="exit 1 when Σ phases / total falls below this "
                         "(ci.sh passes 0.9 — the acceptance bound)")
    ap.add_argument("--device-trace", default=None, metavar="DIR",
                    help="capture a jax.profiler device trace of one "
                         "straight chunk (phases appear as named_scope "
                         "spans in Perfetto); also writes "
                         "DIR/phases.trace.json host spans")
    ap.add_argument("--md", action="store_true",
                    help="print the attribution table as markdown "
                         "(the docs/PERF.md format)")
    args = ap.parse_args(argv)

    import shadow1_tpu  # noqa: F401  (x64 before jax arrays)
    from shadow1_tpu.platform import ensure_live_platform

    ensure_live_platform(min_devices=1)
    import jax

    eng, label = build_engine(args.config, metrics_ring=args.metrics_ring,
                              hosts=args.hosts)
    att = attribution(eng, n_windows=args.windows, warmup=args.warmup,
                      reps=args.reps)
    att = {"probe": "phaseprobe", "config": label,
           "backend": jax.default_backend(), **att}
    if args.device_trace:
        from shadow1_tpu.telemetry import PhaseProfiler, device_trace

        prof = PhaseProfiler()
        st0 = eng.run(eng.init_state(), n_windows=args.warmup)
        jax.block_until_ready(st0)
        with device_trace(args.device_trace, profiler=prof):
            jax.block_until_ready(eng.run(st0, n_windows=args.windows))
        import os

        prof.write(os.path.join(args.device_trace, "phases.trace.json"))
        att["device_trace"] = args.device_trace
    print(_table(label, att, md=args.md), file=sys.stderr, flush=True)
    print(json.dumps(att))
    if args.min_coverage and (att["coverage"] or 0) < args.min_coverage:
        print(f"[phaseprobe] attribution coverage {att['coverage']} below "
              f"{args.min_coverage} — the phase split no longer accounts "
              f"for the window program", file=sys.stderr, flush=True)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
