"""Memory plane — pre-flight HBM budgeting, OOM taxonomy, downshift planning.

The ROADMAP's scale levers (256k–1M-host dense ladders, wide fleet sweeps)
all grow the state planes toward the device-memory wall, and an
oversubscribed config today dies with a raw ``XlaRuntimeError:
RESOURCE_EXHAUSTED`` mid-compile — after minutes of trace time — and the
supervisor respawns it straight into the same wall. This module makes
memory a *structured* failure domain, completing the taxonomy (capacity →
PR 5, faults → PR 4, preemption → PR 7, memory → this):

* **pre-flight estimator** (:func:`estimate`): the engine state pytree is
  traced ABSTRACTLY with ``jax.eval_shape`` over the real model-init path
  (no device allocation — jnp ops on constants stage instead of
  executing), so the per-leaf shapes/dtypes are exactly what
  ``Engine.init_state`` would allocate: caps/H/E/ring-W in, bytes out.
  On top of the resident state ride the known peaks: the non-donated run
  output (a second full state copy during execution), the transactional
  rollback copy (``--on-overflow retry`` keeps the chunk-start state),
  and the window-end routing/rebase temporaries.
* **budget check** (:func:`check_budget`): estimate vs the backend's
  reported device memory (``memory_stats()['bytes_limit']``; env
  ``SHADOW1_MEM_BYTES`` overrides — the CPU backend reports nothing).
  Over budget ⇒ :class:`MemoryBudgetError` with per-plane byte
  attribution and paste-ready ``engine:``/``sweep:`` advice, BEFORE any
  compile is attempted — mirroring ``txn.CapacityExceededError``.
* **downshift planner** (:func:`downshift` — CLI ``--on-oom downshift``):
  graceful degradation in bit-exactness-preserving order: drop the txn
  rollback copy (``retry`` demotes to ``halt`` — replay needs the copy),
  shrink the telemetry ring W (observability only; the simulation never
  reads the ring), and split a fleet's E lanes into sequential
  sub-batches (lanes are independent — vmap batches identical integer
  ops — so sub-batching is digest-neutral per lane;
  ``tools/memprobe.py --subbatch`` is the chaosprobe-style proof).
* **runtime taxonomy** (:func:`is_oom`): a RESOURCE_EXHAUSTED that slips
  past the estimate (transients beyond the model, concurrent tenants) is
  caught by the CLI, mapped to ``consts.EXIT_MEMORY`` with a parseable
  stdout record, and classified deterministic by ``cli._supervise`` — no
  crash-loop through the backoff ladder.

Accuracy contract: :func:`estimate`'s ``state``/``resident`` bytes must
track ``jax.live_arrays()`` within 10% on every ladder config —
``tools/memprobe.py --audit`` measures it, ``tests/test_mem.py`` gates it.

jax is imported lazily inside the estimator functions so the error types
and record helpers stay importable by jax-free report tools.
"""

from __future__ import annotations

import dataclasses
import math
import os

from shadow1_tpu.consts import EXIT_MEMORY, NP  # noqa: F401 (re-export)

# Env override for the device byte budget (integer bytes). Wins over the
# backend's reported memory — the deterministic knob ci.sh and the tests
# use on the CPU backend, which reports no memory_stats at all.
MEM_BYTES_ENV = "SHADOW1_MEM_BYTES"

# Fallback audit tolerance (estimator vs measured live bytes) — the
# acceptance bound memprobe and the tests check against.
AUDIT_TOLERANCE = 0.10

# Bytes of flat routing temporaries per outbox slot (route_outbox): the
# [P*H] flattened arrival/tb/depart i64 columns, the dst/kind/ctr/mask i32
# columns, and the [NP, P*H] payload view — the window-end working set that
# exists alongside the state during delivery.
_ROUTE_BYTES_PER_SLOT = 3 * 8 + 4 * 4 + 4 * NP


class MemoryBudgetError(RuntimeError):
    """The pre-flight byte estimate exceeds the device memory budget.

    Structured: ``estimated`` (peak bytes), ``budget`` (device bytes),
    ``budget_source`` ("env" or "backend"), ``planes`` (resident per-plane
    byte attribution), ``peaks`` (transient adders), ``advice`` (the
    paste-ready remedy block). Raised BEFORE compile, so an oversubscribed
    config costs milliseconds, not minutes of trace time plus a crash."""

    def __init__(self, estimated: int, budget: int, planes: dict,
                 peaks: dict, advice: str, budget_source: str = "env",
                 detail: str = ""):
        self.estimated = int(estimated)
        self.budget = int(budget)
        self.budget_source = budget_source
        self.planes = {k: int(v) for k, v in planes.items()}
        self.peaks = {k: int(v) for k, v in peaks.items()}
        self.advice = advice
        attribution = "  ".join(
            f"{k}={fmt_bytes(v)}" for k, v in
            sorted({**self.planes, **self.peaks}.items(),
                   key=lambda kv: -kv[1]) if v)
        super().__init__(
            f"estimated peak device memory {fmt_bytes(self.estimated)} "
            f"exceeds the {budget_source} budget {fmt_bytes(self.budget)}"
            f"{detail} — rejected before compile (an oversubscribed config "
            f"would otherwise burn minutes of trace time and die with a raw "
            f"RESOURCE_EXHAUSTED). Plane attribution: {attribution}.\n"
            f"{advice}"
        )


def fmt_bytes(n) -> str:
    """Human-readable bytes; shared by the error messages, advice blocks
    and the jax-free report tools (heartbeat_report). None → "?"."""
    if n is None:
        return "?"
    n = int(n)
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20), ("KiB", 1 << 10)):
        if abs(n) >= div:
            return f"{n / div:.2f} {unit}"
    return f"{n} B"


def is_oom(e: BaseException) -> bool:
    """Is this exception a device out-of-memory condition?

    Matches the XLA runtime's RESOURCE_EXHAUSTED (compile-time buffer
    assignment or run-time allocation) and Python's MemoryError. Our own
    structured errors never match — they are handled by type."""
    if isinstance(e, MemoryBudgetError):
        return False
    return isinstance(e, MemoryError) or "RESOURCE_EXHAUSTED" in str(e)


def device_budget() -> tuple[int | None, str | None]:
    """The device byte budget: (bytes, source) or (None, None).

    ``SHADOW1_MEM_BYTES`` (integer bytes) wins — the deterministic CI/test
    knob. Otherwise the backend's reported limit
    (``Device.memory_stats()['bytes_limit']`` — present on TPU/GPU, absent
    on the CPU backend, where the estimate is informational only)."""
    env = os.environ.get(MEM_BYTES_ENV)
    if env:
        try:
            return int(env), "env"
        except ValueError:
            import sys

            # Budget discovery must never kill a run: a malformed
            # override ("8GiB", "8<<30") is announced and ignored, not a
            # crash-loop through the supervisor's backoff ladder.
            print(f"[mem] ignoring malformed {MEM_BYTES_ENV}={env!r} "
                  f"(integer bytes expected)", file=sys.stderr, flush=True)
    try:
        import jax

        stats = jax.devices()[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"]), "backend"
    except Exception:  # noqa: BLE001 — budget discovery must never kill a run
        pass
    return None, None


def device_peak_in_use() -> int | None:
    """The backend's measured high-water allocation, when it reports one
    (``peak_bytes_in_use``) — the comparand for the end-of-run mem record."""
    try:
        import jax

        stats = jax.devices()[0].memory_stats()
        if stats:
            v = stats.get("peak_bytes_in_use")
            return int(v) if v is not None else None
    except Exception:  # noqa: BLE001
        pass
    return None


def live_bytes() -> int:
    """Bytes of every live device BUFFER in this process — the measured
    side of the estimator audit (memprobe/tests). Deduplicated by buffer
    pointer: on the CPU backend a host↔device round-trip (e.g. the restart
    capture's ``jnp.asarray(np.asarray(x))``) can alias the same memory
    under two Array objects, and counting both would double-charge bytes
    the device only holds once."""
    import jax

    seen: set = set()
    tot = 0
    for a in jax.live_arrays():
        try:
            key = a.unsafe_buffer_pointer()
        except Exception:  # noqa: BLE001 — sharded/committed arrays
            key = id(a)
        if key in seen:
            continue
        seen.add(key)
        tot += int(a.nbytes)
    return tot


def tree_bytes(tree) -> int:
    """Total bytes of a pytree of arrays / ShapeDtypeStructs."""
    import jax
    import numpy as np

    tot = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "nbytes"):
            tot += int(leaf.nbytes)
        elif hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            tot += int(np.dtype(leaf.dtype).itemsize
                       * int(np.prod(leaf.shape, dtype=np.int64)))
    return tot


def abstract_state(exp, params):
    """(abstract SimState, eager Ctx) for one experiment lane.

    The Ctx is built EAGERLY — its topology/fault tables are O(V²)+O(K·H)
    device constants, small next to the [C, H] state planes and needed as
    concrete arrays anyway — while the state construction (the [C, H]
    planes, the model pytree, the telemetry ring) is traced under
    ``jax.eval_shape``: jnp ops stage into the jaxpr instead of executing,
    so NO state-sized allocation happens. The returned shapes/dtypes are
    exactly ``Engine.init_state()``'s, model init path included — which is
    what makes the estimator exact rather than a drift-prone mirror of the
    plane layouts."""
    import jax
    import jax.numpy as jnp

    from shadow1_tpu.core.engine import (
        SimState,
        _metrics_init,
        _model_module,
        build_base_ctx,
    )
    from shadow1_tpu.core.events import evbuf_init
    from shadow1_tpu.core.outbox import outbox_init
    from shadow1_tpu.telemetry.ring import ring_init

    ctx = build_base_ctx(exp, params)
    mod = _model_module(exp.model)

    def build():
        evbuf = evbuf_init(exp.n_hosts, params.ev_cap)
        model, evbuf, seed_over = mod.init(ctx, evbuf)
        metrics = _metrics_init()
        return SimState(
            win_start=jnp.zeros((), jnp.int64),
            evbuf=evbuf,
            outbox=outbox_init(exp.n_hosts, params.outbox_cap),
            model=model,
            metrics=metrics._replace(
                ev_overflow=metrics.ev_overflow + seed_over),
            cpu_busy=jnp.zeros(exp.n_hosts, jnp.int64),
            telem=ring_init(params.metrics_ring),
        )

    return jax.eval_shape(build), ctx


def _ctx_bytes(ctx) -> int:
    """Bytes of the Ctx's device-constant tables (topology, fault plane,
    fidelity knobs, model_cfg arrays) — closed over by the jitted program
    and resident for the engine's lifetime."""
    tot = 0
    for f in dataclasses.fields(ctx):
        tot += tree_bytes(getattr(ctx, f.name))
    return tot


def _variant_lane_bytes(ctx) -> int:
    """Per-lane bytes of the fleet variant pytree (fleet/engine.py
    _build_variants): RNG key, loss thresholds, fault tables. Lane tables
    pad to the sweep-wide max shape, so this lane-0 figure is a floor —
    the audit tolerance absorbs the padding."""
    return tree_bytes((ctx.key, ctx.loss_thr_vv, ctx.fault_down,
                       ctx.fault_up, ctx.link_fault, ctx.loss_ramp))


@dataclasses.dataclass
class MemEstimate:
    """The pre-flight memory model of one run configuration.

    ``planes`` are RESIDENT bytes (alive for the run: the state pytree per
    plane, ctx constants, fleet variants, the restart capture); ``peaks``
    are TRANSIENT adders that coexist with the residents at the worst
    moment (the run-output state copy, the txn rollback copy, the
    window-end routing/rebase temporaries). The budget compares
    ``peak_bytes``; the live-bytes audit compares ``resident_bytes``."""

    planes: dict
    peaks: dict
    n_exp: int
    n_dev: int
    params: object
    # linear unit costs for advice / downshift math
    evbuf_per_cap: int      # bytes per ev_cap step (whole fleet, per dev)
    outbox_per_cap: int
    telem_per_w: int        # bytes per ring window (whole fleet)
    lane_bytes: int         # peak bytes proportional to one fleet lane
    fixed_bytes: int        # peak bytes independent of the lane count

    @property
    def state_bytes(self) -> int:
        return sum(v for k, v in self.planes.items()
                   if k not in ("const", "variants", "init_model"))

    @property
    def resident_bytes(self) -> int:
        return sum(self.planes.values())

    @property
    def peak_bytes(self) -> int:
        return self.resident_bytes + sum(self.peaks.values())

    def max_lanes(self, budget: int) -> int:
        """Largest fleet sub-batch E' whose peak fits ``budget`` (≥ 0)."""
        if self.lane_bytes <= 0:
            return self.n_exp
        return max(int(budget) - self.fixed_bytes, 0) // self.lane_bytes

    def record(self, budget: int | None = None,
               budget_source: str | None = None, **extra) -> dict:
        """The parseable ``mem`` JSONL record (docs/OBSERVABILITY.md)."""
        rec = {
            "type": "mem",
            "event": "estimate",
            "n_exp": self.n_exp,
            "n_dev": self.n_dev,
            "estimated_state": self.state_bytes,
            "estimated_resident": self.resident_bytes,
            "estimated_peak": self.peak_bytes,
            "budget": budget,
            "budget_source": budget_source,
            "headroom": (int(budget) - self.peak_bytes
                         if budget is not None else None),
            "planes": {k: int(v) for k, v in self.planes.items()},
            "peaks": {k: int(v) for k, v in self.peaks.items()},
        }
        rec.update(extra)
        return rec

    # -- advice ------------------------------------------------------------
    def advice(self, budget: int) -> str:
        """Paste-ready remedy block: ranked plane attribution, concrete
        knob values computed by linear scaling (evbuf ∝ ev_cap, ring ∝ W,
        fleet ∝ E), and the structural remedies (--on-oom downshift,
        --on-overflow halt). The captune idiom: every suggestion is a line
        the operator can paste, never just 'use less memory'."""
        from shadow1_tpu.tune.ladder import LADDER_MIN, cap_ladder

        budget = int(budget)
        over = self.peak_bytes - budget
        p = self.params
        lines = [f"Peak over budget by {fmt_bytes(over)}. Remedies "
                 f"(largest planes first):"]
        ranked = sorted({**self.planes, **self.peaks}.items(),
                        key=lambda kv: -kv[1])
        attribution = ", ".join(f"{k} {fmt_bytes(v)}"
                                for k, v in ranked if v)
        lines.append(f"  planes: {attribution}")
        if self.peaks.get("rollback"):
            lines.append(f"  --on-overflow halt  # frees the transactional "
                         f"rollback copy: {fmt_bytes(self.peaks['rollback'])}")
        if self.planes.get("telem"):
            spare = budget - (self.peak_bytes - self.planes["telem"])
            w_fit = max(spare, 0) // max(self.telem_per_w, 1)
            if 0 < w_fit < p.metrics_ring:
                lines.append(f"  --metrics-ring {w_fit}  # ring "
                             f"{p.metrics_ring}→{w_fit} windows frees "
                             f"{fmt_bytes((p.metrics_ring - w_fit) * self.telem_per_w)}")
        # Cap ladder-down: the largest ladder cap whose scaled peak fits.
        # The output copy holds a second plane and the window temporaries
        # shrink with it too — count the plane's share of both, 2x.
        eng_lines = []
        residual = self.peak_bytes
        for knob, cur, per in (("ev_cap", p.ev_cap, self.evbuf_per_cap),
                               ("outbox_cap", p.outbox_cap,
                                self.outbox_per_cap)):
            ladder = [c for c in cap_ladder(cur) if LADDER_MIN <= c < cur]
            pick = None
            for cap in reversed(ladder):  # largest reduction last resort
                pick = cap
                if residual - 2 * (cur - cap) * per <= budget:
                    break
            if pick is not None:
                residual -= 2 * (cur - pick) * per
                eng_lines.append(f"    {knob}: {pick}  # from {cur}")
        if eng_lines and residual <= budget:
            lines.append("  engine:  # size precisely from a recorded "
                         "run: python -m shadow1_tpu.tools.captune")
            lines.extend(eng_lines)
        if self.n_exp > 1:
            k = self.max_lanes(budget)
            if 1 <= k < self.n_exp:
                lines.append(f"  sweep: run <= {k} lane(s) per batch "
                             f"(--on-oom downshift sub-batches the "
                             f"{self.n_exp}-lane fleet automatically, "
                             f"bit-identically per lane)")
        lines.append("  or rerun with --on-oom downshift (rollback drop → "
                     "ring shrink → fleet sub-batch, in that order); "
                     "probe the feasible envelope: python -m "
                     "shadow1_tpu.tools.memprobe <config> --maxfit")
        return "\n".join(lines)


def estimate(exp, params, n_exp: int = 1, n_dev: int = 1) -> MemEstimate:
    """The pre-flight byte estimate for one run configuration.

    ``n_exp`` > 1 models a fleet (every state leaf ×E, plus the stacked
    variant tables); ``n_dev`` > 1 models host-axis sharding (the [.., H]
    planes divide across devices; metrics/ring/scalars replicate — the
    budget is per device). The state side is EXACT (abstract trace of the
    real init); the const/variant/transient sides are explicit models
    documented in docs/SEMANTICS.md §"Memory contract"."""
    st, ctx = abstract_state(exp, params)
    E, D = int(n_exp), max(int(n_dev), 1)

    def sharded(n):  # host-axis planes divide across devices
        return -(-int(n) * E // D)

    per = {f: tree_bytes(getattr(st, f))
           for f in ("evbuf", "outbox", "model", "metrics", "cpu_busy",
                     "telem")}
    scalars = tree_bytes(st.win_start)
    planes = {
        "evbuf": sharded(per["evbuf"]),
        "outbox": sharded(per["outbox"]),
        "model": sharded(per["model"]),
        "metrics": per["metrics"] * E,
        "telem": per["telem"] * E,
        "scalars": (per["cpu_busy"] + scalars) * E,
        "const": _ctx_bytes(ctx),
    }
    if E > 1:
        planes["variants"] = _variant_lane_bytes(ctx) * E
    if ctx.has_restart:
        # The restart capture (init_model): a full model pytree held as a
        # device constant (per lane under fleet).
        planes["init_model"] = sharded(per["model"])
    state = sum(v for k, v in planes.items()
                if k not in ("const", "variants", "init_model"))
    # Transients: the non-donated run output (a full second state during
    # execution), the txn rollback copy (retry holds the chunk-start state
    # across the chunk), and the window-end working set (route_outbox's
    # flattened [P*H] columns + the rebase/digest i64 [C, H] temporaries).
    route = sharded(params.outbox_cap * exp.n_hosts * _ROUTE_BYTES_PER_SLOT)
    rebase = sharded(params.ev_cap * exp.n_hosts * 8)
    peaks = {
        "output": state,
        "rollback": state if params.on_overflow == "retry" else 0,
        "transient": route + rebase,
    }
    if D > 1:
        # Sharded exchange staging: the per-window all_to_all buckets
        # (send + receive sides) on each device. The guaranteed-fit
        # escalation cap is the shard's WHOLE outbox (shard/engine.py),
        # so 2× the per-device outbox plane is the conservative bound.
        peaks["x2x"] = 2 * planes["outbox"]
    lane = ((state + peaks["output"] + peaks["rollback"]
             + peaks["transient"]) // E
            + planes.get("variants", 0) // max(E, 1)
            + planes.get("init_model", 0) // E)
    est = MemEstimate(
        planes=planes, peaks=peaks, n_exp=E, n_dev=D, params=params,
        evbuf_per_cap=sharded(per["evbuf"]) // max(params.ev_cap, 1),
        outbox_per_cap=sharded(per["outbox"]) // max(params.outbox_cap, 1),
        telem_per_w=(per["telem"] * E) // max(params.metrics_ring, 1)
        if params.metrics_ring else 0,
        lane_bytes=lane,
        fixed_bytes=planes["const"],
    )
    return est


def check_budget(est: MemEstimate, budget: int | None,
                 budget_source: str | None = None, detail: str = "") -> None:
    """Raise :class:`MemoryBudgetError` when the estimated peak exceeds the
    budget. A None budget (CPU backend, no env override) checks nothing —
    the estimate is then informational (the ``mem`` record still flows)."""
    if budget is None or est.peak_bytes <= int(budget):
        return
    raise MemoryBudgetError(
        estimated=est.peak_bytes, budget=budget, planes=est.planes,
        peaks=est.peaks, advice=est.advice(budget),
        budget_source=budget_source or "env", detail=detail)


def downshift(exp, params, n_exp: int, budget: int, n_dev: int = 1,
              resumable: bool = False, subbatch_resumable: bool = False):
    """The graceful-degradation planner (CLI ``--on-oom downshift``).

    Applies the bit-exactness-preserving downshifts IN ORDER until the
    estimated peak fits ``budget``:

    1. **drop the rollback copy** — ``on_overflow=retry`` demotes to
       ``halt`` (the replay NEEDS the chunk-start copy, so retry cannot be
       kept without its memory; halt keeps overflow loud instead of lossy);
    2. **shrink the telemetry ring** — the simulation never reads the ring
       (observability only), so a narrower W changes which windows get
       per-window records, never any digest word that IS recorded
       (``state_digest`` keeps W ≥ 1: the words need a transport).
       SKIPPED when ``resumable`` (--ckpt/--resume): the ring is a state
       leaf, so a shrunk W could not load snapshots taken at the original
       width — a budget change against an existing lineage would then
       crash-loop on a shape mismatch instead of downshifting;
    3. **sub-batch the fleet** — split E lanes into sequential batches of
       the largest k that fits; lanes are independent, so each lane's
       digest stream/metrics are bit-identical to the full-E run
       (tools/memprobe.py --subbatch proves it per invocation). With
       ``subbatch_resumable`` (the CLI sets it for plain ``--ckpt`` runs)
       sub-batching composes with checkpointing: each batch snapshots its
       own [k, ...] state with the batch cursor riding the lineage
       manifest (cli._fleet_subbatched). Refused only for an explicit
       ``--resume``/``--save-state`` path, which names ONE snapshot and
       has no cursor.

    The rollback drop is the one stage always available: it frees a
    transient copy, never a state leaf, so snapshots stay loadable (the
    CLI keeps the retry-era cap-migration path alive across the
    demotion).

    Returns ``(params', sub_batch_or_None, actions)`` — ``actions`` is the
    audit list the ``mem`` downshift record carries. Raises
    :class:`MemoryBudgetError` when every downshift is exhausted and the
    estimate still exceeds the budget."""
    budget = int(budget)
    actions: list[dict] = []
    est = estimate(exp, params, n_exp=n_exp, n_dev=n_dev)
    if est.peak_bytes <= budget:
        return params, None, actions
    if params.on_overflow == "retry":
        freed = est.peaks["rollback"]
        params = dataclasses.replace(params, on_overflow="halt")
        actions.append({"action": "drop_rollback", "on_overflow": "halt",
                        "freed": int(freed)})
        est = estimate(exp, params, n_exp=n_exp, n_dev=n_dev)
    if est.peak_bytes > budget and params.metrics_ring > 0 and not resumable:
        min_w = 1 if params.state_digest else 0
        # Each removed ring window frees its state row AND the row's share
        # of the non-donated run-output copy — 2× telem_per_w — so divide
        # by both or the ring over-shrinks (observability sacrificed for
        # nothing). The re-estimate below is the exact check; one more
        # exact pass handles any rounding shortfall.
        row = 2 * max(est.telem_per_w, 1)
        w0 = params.metrics_ring
        w_new = w0
        for _ in range(2):
            need = est.peak_bytes - budget
            if need <= 0 or w_new <= min_w:
                break
            w_new = max(min_w, w_new - math.ceil(need / row))
            params = dataclasses.replace(params, metrics_ring=w_new)
            est = estimate(exp, params, n_exp=n_exp, n_dev=n_dev)
        if w_new < w0:
            actions.append({"action": "shrink_ring",
                            "metrics_ring": [w0, w_new],
                            "freed": int((w0 - w_new) * row)})
    sub_batch = None
    if est.peak_bytes > budget and n_exp > 1:
        k = est.max_lanes(budget)
        if 1 <= k < n_exp:
            if resumable and not subbatch_resumable:
                raise MemoryBudgetError(
                    estimated=est.peak_bytes, budget=budget,
                    planes=est.planes, peaks=est.peaks,
                    advice=est.advice(budget),
                    detail=" (sub-batched downshift does not compose with "
                           "an explicit --resume/--save-state snapshot "
                           "path — it names one all-lane state and "
                           "carries no batch cursor; use --ckpt, whose "
                           "lineage manifest records the sub-batch "
                           "cursor, or shrink the sweep)")
            sub_batch = k
            actions.append({"action": "sub_batch", "lanes": k,
                            "batches": -(-n_exp // k)})
            # By max_lanes construction a k-lane batch fits the budget.
            est = estimate(exp, params, n_exp=k, n_dev=n_dev)
    if est.peak_bytes > budget:
        detail = (" (the state-shape-preserving downshifts are exhausted: "
                  "rollback dropped — ring shrink is unavailable under "
                  "--ckpt/--resume because it changes the snapshot shape, "
                  "and sub-batching needs --ckpt's batch cursor (not an "
                  "explicit --resume/--save-state path); drop the "
                  "checkpoint flags or shrink the config)" if resumable else
                  " (every --on-oom downshift is exhausted: rollback "
                  "dropped, ring floored, fleet at one lane — the base "
                  "state planes alone exceed the device)")
        raise MemoryBudgetError(
            estimated=est.peak_bytes, budget=budget, planes=est.planes,
            peaks=est.peaks, advice=est.advice(budget), detail=detail)
    return params, sub_batch, actions


def downshift_record(actions: list[dict], est_peak: int,
                     budget: int) -> dict:
    """The parseable ``mem`` downshift record (docs/OBSERVABILITY.md)."""
    return {"type": "mem", "event": "downshift", "actions": actions,
            "estimated_peak": int(est_peak), "budget": int(budget)}
