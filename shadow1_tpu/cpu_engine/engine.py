"""CPU reference engine — the readable discrete-event oracle.

A small, sequential heapq simulator implementing *exactly* the semantics in
docs/SEMANTICS.md: same event ordering keys, same capacity bounds, same
counter-based RNG streams, same integer arithmetic. It plays the role the
real Linux kernel played for the reference's test strategy (SURVEY §4: "the
real OS is the oracle"): every workload must produce identical event/packet/
byte counts and final clocks on this engine and on the batched TPU engine.

Structurally it mirrors the reference's sequential scheduler policy
(src/main/core/scheduler/scheduler-policy-global-single.c): one global
priority queue, events executed in total (time, tb) order.
"""

from __future__ import annotations

import heapq
from typing import Any

import numpy as np

from shadow1_tpu.config.compiled import CompiledExperiment
from shadow1_tpu.consts import (
    KIND_METRIC_FIELDS,
    K_PHOLD,
    K_PKT,
    R_JITTER,
    R_LOSS,
    R_PHOLD_DELAY,
    R_PHOLD_DST,
    EngineParams,
    packet_tb,
)
from shadow1_tpu.cpu_engine.rngcache import DrawCache


class CpuEngine:
    def __init__(self, exp: CompiledExperiment, params: EngineParams | None = None,
                 capture=None):
        """``capture(time_ns, src, dst, p, dropped)`` is called for every
        routed packet (pcap hook — tools/pcap.py; reference per-NIC capture,
        src/main/utility/pcap-writer.c)."""
        exp.validate()
        self.exp = exp
        self.capture = capture
        self.params = params or EngineParams()
        self.window = exp.window
        self.n_windows = int(-(-exp.end_time // self.window))
        self.draws = DrawCache(exp.seed)
        # Loss decisions are integer-threshold compares on the raw bits
        # (backend-exact; mirrors route_outbox in core/engine.py).
        from shadow1_tpu import rng as _rng

        self.loss_thr = _rng.prob_threshold(exp.loss_vv)
        h = exp.n_hosts
        self.heap: list[tuple] = []  # (time, tb, gseq, host, kind, p)
        self._gseq = 0
        self.pending = np.zeros(h, np.int64)   # events queued per host (ev_cap)
        self.self_ctr = np.zeros(h, np.int64)  # local push tie-break counters
        self.pkt_ctr = np.zeros(h, np.int64)   # per-src packet counters
        self._ob_win = np.full(h, -1, np.int64)  # outbox accounting: window idx
        self._ob_used = np.zeros(h, np.int64)    # ... sends used this window
        # Fidelity mirrors (docs/SEMANTICS.md; identical rules to run_round /
        # route_outbox / deliver_flat in core/engine.py). The fault plane
        # compiles through the SAME builders as the batched engines
        # (fault/schedule.py), so down predicates, outage windows and ramp
        # thresholds are the identical integers.
        from shadow1_tpu.config.compiled import NO_STOP
        from shadow1_tpu.fault.schedule import (
            host_interval_tensors,
            hosts_down_at_np,
            link_tables,
            ramp_tables,
        )

        self._hosts_down_at_np = hosts_down_at_np  # hot path: bind once
        self.fault_down, self.fault_up = host_interval_tensors(exp)
        self.has_stop = bool(self.fault_down.min() < NO_STOP)
        self._link_fault = link_tables(exp)
        self._loss_ramp = ramp_tables(exp)
        self.has_link_fault = self._link_fault is not None
        self.has_loss_ramp = self._loss_ramp is not None
        self.cpu_cost = np.asarray(exp.cpu_ns_per_event, np.int64)
        self.has_cpu = bool(self.cpu_cost.max() > 0)
        self.cpu_busy = np.zeros(h, np.int64)
        self.jitter_vv = np.asarray(exp.jitter_vv, np.int64)
        self.has_jitter = bool(self.jitter_vv.max() > 0)
        self.metrics = {
            "events": 0,
            "pkts_sent": 0,
            "pkts_delivered": 0,
            "pkts_lost": 0,
            "ev_overflow": 0,
            "ob_overflow": 0,
            "down_events": 0,
            "down_pkts": 0,
            "link_down_pkts": 0,
            "host_restarts": 0,
            "nic_tx_drops": 0,
            "nic_rx_drops": 0,
            "nic_aqm_drops": 0,
            "pops_pkt": 0,
            "pops_deliver": 0,
            "pops_timer": 0,
            "pops_txr": 0,
            "pops_app": 0,
            # Capacity high-water gauges, mirroring the batch engines'
            # window-end sampling (core/engine.py window_step): the boundary
            # pending-event sets are engine-independent (all events created
            # before a window boundary with time ≥ it — eager delivery vs
            # window-end scatter land the same sets), so these match the TPU
            # gauges bit-exactly on overflow-free runs.
            "ev_max_fill": 0,
            "ob_max_fill": 0,
            # Wasted-work accounting (performance attribution plane):
            # running sums of the per-window boundary samples, mirroring
            # core/engine.window_phases ph_prepare/deliver_window. Same
            # engine-independence argument as the fill gauges: the
            # window-start pending set and the per-window send set are
            # identical across engines on overflow-free runs.
            "active_hosts": 0,
            "elig_events": 0,
            "outbox_hosts": 0,
        }
        self._next_boundary = self.window  # first window-end sample point
        # Per-kind pop occupancy fields (shared table — consts).
        self._pops_field = {k: f[0] for k, f in KIND_METRIC_FIELDS.items()}
        # Determinism flight recorder (core/digest.py): the oracle mirrors
        # the batched engines' per-window subsystem digests at window
        # boundaries. The pending-event digest is maintained incrementally
        # (add the element word on push, subtract it on pop — the sum is
        # order-independent, so this equals the TPU's plane scan); outbox
        # send words accumulate per window as sends happen; plane digests
        # (tcp/nic/rng) are recomputed per boundary from live state. Rows
        # land in ``digest_rows`` as JSONL-ready REC_DIGEST dicts.
        self.digest_on = bool(self.params.state_digest)
        # Overflow policy / self-check at window boundaries (txn.py): the
        # oracle runs the SAME boundary checks as the chunked batch runner
        # — "halt" raises the structured CapacityExceededError on fresh
        # overflow, --selfcheck verifies the drop-accounting identity.
        # "retry" is inert here like auto_caps: the eager oracle cannot
        # re-run a window (the CLI warns; parity tests compare a batched
        # retry run against the oracle run at the final caps instead).
        self._halt_on_overflow = self.params.on_overflow == "halt"
        self._selfcheck = bool(self.params.selfcheck)
        self._of_seen = {"ev_overflow": 0, "ob_overflow": 0}
        self._ev_dg = 0
        self._ev_word: dict[int, int] = {}  # gseq → element word
        self._ob_dg: dict[int, int] = {}    # window → send-word sum
        self.digest_rows: list[dict] = []
        # Wasted-work accounting (performance attribution plane): per-window
        # boundary samples, mirroring the batched engines' ring columns
        # (telemetry/registry.RING_WORK). Gated on metrics_ring like the
        # digest rows — the per-boundary heap scan is pay-for-use, and the
        # ring is where the batched engines carry the per-window values.
        # Rows land in ``work_rows`` as JSONL-ready REC_WORK dicts; the
        # cumulative metrics counters advance in lockstep so final counters
        # compare bit-exactly against the batched engines.
        self.work_on = self.params.metrics_ring > 0
        self.work_rows: list[dict] = []
        # Flow-probe plane (telemetry/probes.py): the oracle samples the
        # same watched (host, sock) columns at every window boundary as the
        # batched engines' probe ring, into JSONL-ready REC_FLOW dicts.
        # i32-semantics columns mask with & 0xFFFFFFFF (the u32 widen's
        # twin); inflight stays the signed seq distance. No ring needed —
        # rows accumulate directly.
        self.probe_on = bool(self.params.probes)
        self.probe_rows: list[dict] = []
        # Link-telemetry plane (telemetry/links.py): the oracle maintains
        # the same cumulative [V, V, F] per-edge accumulator — offered
        # packets / wire bytes / queued ns at the send gates below, drop
        # partition at the matching gate, NIC drop-tail drops via
        # _link_nic_drop from the model's _tx — and emits the same
        # cumulative ``link`` snapshot records at run boundaries
        # (link_rows). Bit-exact against the batched engines at any window
        # boundary: every column is a sum/max over the same per-packet
        # integers, and summation order cannot matter.
        self.link_on = bool(self.params.link_telem)
        self.link_rows: list[dict] = []
        self._link_next = 0  # last drained window boundary (never re-emit)
        if self.link_on:
            from shadow1_tpu.telemetry.links import check_link_params
            from shadow1_tpu.telemetry.registry import LINK_FIELDS

            v = np.asarray(exp.lat_vv).shape[0]
            check_link_params(self.params, v)
            self._link_acc = np.zeros((v, v, len(LINK_FIELDS)), np.int64)
        self._work_pending: dict[int, dict] = {}  # window → open row
        self._ob_hosts: dict[int, int] = {}       # window → distinct senders
        self._work_next_open = 0                  # next window to sample
        self.model = self._make_model()
        self.model.start()
        # Seed-time overflow is baselined out, mirroring the batch guard's
        # bind-after-init_state: only overflow DURING a window is fresh.
        self._of_seen = {c: self.metrics[c] for c in self._of_seen}
        # Host restart schedule (fault plane): every finite window-quantized
        # up boundary, sorted — a restarted host's model columns restore to
        # the POST-start snapshot captured here (the oracle twin of the
        # batched engines' init-model capture: same moment in the lifecycle,
        # before any event has run), its virtual-CPU clock zeroes, and its
        # event heap entries are deliberately untouched (dead-interval ones
        # discard at pop; later ones execute against the reset state).
        self._restart_sched: list[tuple[int, list[int]]] = []
        self._restart_i = 0
        self._cur_end = 0
        if (self.fault_up < NO_STOP).any():
            by_b: dict[int, list[int]] = {}
            ks, hs = np.nonzero(self.fault_up < NO_STOP)
            for k, h_ in zip(ks, hs):
                by_b.setdefault(int(self.fault_up[k, h_]), []).append(int(h_))
            self._restart_sched = sorted(
                (b, sorted(v)) for b, v in by_b.items()
            )
            self._restart_snap = self.model.snapshot_host_state()

    def _make_model(self):
        if self.exp.model == "phold":
            return CpuPhold(self)
        if self.exp.model == "net":
            from shadow1_tpu.cpu_engine.net import CpuNetModel

            return CpuNetModel(self)
        raise ValueError(f"unknown model {self.exp.model!r}")

    # -- fault plane (mirrors fault/plane.py on the shared tables) --------
    def _down_at(self, host: int, t: int) -> bool:
        """Host ``host`` is down at time ``t`` (any interval contains t)."""
        return self._hosts_down_at_np(self.fault_down, self.fault_up, host, t)

    def _link_down(self, vs: int, vd: int, dep: int) -> bool:
        src, dst, t0, t1 = self._link_fault
        return bool(((vs == src) & (vd == dst)
                     & (dep >= t0) & (dep < t1)).any())

    def _ramp_thr(self, vs: int, vd: int, dep: int, thr: int) -> int:
        src, dst, t0, t1, rthr = self._loss_ramp
        for i in range(len(src)):
            if (vs == src[i] and vd == dst[i]
                    and t0[i] <= dep < t1[i]):
                thr = int(rthr[i])  # entries in order: later wins
        return thr

    def _apply_restarts_pending(self, upto: int) -> bool:
        """Apply every scheduled restart whose boundary b satisfies
        b ≤ upto, b < the current run end (the batched engine only runs
        window starts < end), and b < the next UNprocessed boundary (its
        digest row — the pre-restart state — must already be emitted).
        Returns True if any host was reset (digest planes then stale)."""
        applied = False
        while self._restart_i < len(self._restart_sched):
            b, hosts = self._restart_sched[self._restart_i]
            if b > upto or b >= self._cur_end or b >= self._next_boundary:
                break
            for h_ in hosts:
                self.model.reset_host(h_, self._restart_snap)
                self.cpu_busy[h_] = 0
                self.metrics["host_restarts"] += 1
            self._restart_i += 1
            applied = True
        return applied

    # -- scheduling primitives (semantics shared with the TPU engine) -----
    def schedule_local(self, host: int, time: int, kind: int, p: tuple) -> None:
        if self.pending[host] >= self.params.ev_cap:
            self.metrics["ev_overflow"] += 1
            return
        tb = int(self.self_ctr[host])
        self.self_ctr[host] += 1
        self._push(time, tb, host, kind, p)

    def outbox_space(self, host: int, now: int) -> int:
        w = now // self.window
        if self._ob_win[host] != w:
            self._ob_win[host] = w
            self._ob_used[host] = 0
        return self.params.outbox_cap - int(self._ob_used[host])

    def send(self, src: int, dst: int, kind: int, depart: int, p: tuple, now: int) -> bool:
        """Route one packet: NIC outbox accounting, path latency, loss draw.

        ``depart`` is the time the packet leaves the src NIC; the outbox slot
        is consumed in the window containing the handler's ``now`` (the TPU
        engine drains and resets outboxes at each window end).
        """
        if self.outbox_space(src, now) <= 0:
            self.metrics["ob_overflow"] += 1
            return False
        self._ob_used[src] += 1
        if int(self._ob_used[src]) > self.metrics["ob_max_fill"]:
            self.metrics["ob_max_fill"] = int(self._ob_used[src])
        if self.work_on and int(self._ob_used[src]) == 1:
            # First outbox slot this host touched this window — the
            # outbox_hosts gauge's element (deliver_window counts cnt > 0
            # at window end; the sets are identical).
            w = now // self.window
            self._ob_hosts[w] = self._ob_hosts.get(w, 0) + 1
        ctr = int(self.pkt_ctr[src])
        self.pkt_ctr[src] += 1
        if self.digest_on:
            # The send occupies an outbox slot in the window of ``now`` —
            # hashed before the loss draw, exactly like the TPU outbox
            # (loss is drawn at routing time, after the slot was consumed).
            from shadow1_tpu.core.digest import packet_word

            w = now // self.window
            self._ob_dg[w] = self._ob_dg.get(w, 0) + packet_word(
                src, dst, depart, ctr, kind, p
            )
        self.metrics["pkts_sent"] += 1
        vs = int(self.exp.host_vertex[src])
        vd = int(self.exp.host_vertex[dst])
        if self.link_on:
            # Offered on the edge (the pkts_sent population): counts, wire
            # bytes, NIC queueing ns — mirror of link_route_accum's scatter
            # (depart − window start of the send window; the TPU routes at
            # the end of the window the outbox slot was consumed in).
            from shadow1_tpu.consts import WIRE_OVERHEAD

            a = self._link_acc[vs, vd]
            a[0] += 1
            a[1] += (int(p[4]) if len(p) > 4 else 0) + WIRE_OVERHEAD
            q = depart - (now // self.window) * self.window
            a[5] += q
            if q > a[6]:
                a[6] = q
        if self.has_link_fault and self._link_down(vs, vd, depart):
            # Link outage (fault plane): deterministic drop on departure,
            # BEFORE the loss draw — counted separately, never in
            # pkts_lost (route_outbox orders the gates identically).
            self.metrics["link_down_pkts"] += 1
            if self.link_on:
                self._link_acc[vs, vd, 3] += 1
            if self.capture is not None:
                self.capture(depart, src, dst, p, True)
            return True
        thr = int(self.loss_thr[vs, vd])
        if self.has_loss_ramp:
            thr = self._ramp_thr(vs, vd, depart, thr)
        if int(self.draws.bits(R_LOSS, src, ctr)) < thr:
            self.metrics["pkts_lost"] += 1
            if self.link_on:
                self._link_acc[vs, vd, 2] += 1
            if self.capture is not None:
                self.capture(depart, src, dst, p, True)
            return True
        arrival = depart + int(self.exp.lat_vv[vs, vd])
        if self.has_jitter:
            jit = int(self.jitter_vv[vs, vd])
            if jit:
                arrival += self.draws.randint(R_JITTER, src, ctr, 2 * jit + 1) - jit
        if self.has_stop and self._down_at(dst, arrival):
            self.metrics["down_pkts"] += 1
            return True
        if self.pending[dst] >= self.params.ev_cap:
            self.metrics["ev_overflow"] += 1
            return True
        self._push(arrival, packet_tb(src, ctr), dst, kind, p)
        self.metrics["pkts_delivered"] += 1
        if self.capture is not None:
            self.capture(arrival, src, dst, p, False)
        return True

    def schedule_packet(self, host: int, time: int, tb: int, kind: int,
                        p: tuple) -> None:
        """Push with a caller-supplied tie-break (the packet's own): used by
        the NIC rx fast path, which converts a just-popped K_PKT slot in
        place — capacity cannot overflow (the pop freed a slot)."""
        assert self.pending[host] < self.params.ev_cap
        self._push(time, tb, host, kind, p)

    def _push(self, time: int, tb: int, host: int, kind: int, p: tuple) -> None:
        self.pending[host] += 1
        if self.digest_on:
            from shadow1_tpu.core.digest import event_word

            w = event_word(host, time, tb, kind, p)
            self._ev_word[self._gseq] = w
            self._ev_dg += w
        heapq.heappush(self.heap, (time, tb, self._gseq, host, kind, p))
        self._gseq += 1

    def _sample_fill(self, upto: int) -> None:
        """Window-end occupancy samples for every boundary ≤ ``upto``
        (exclusive of later ones): between two events the pending sets are
        static, so sampling when the next event's time crosses a boundary
        sees exactly the state the batch engine gauges at window end —
        and, with digests on, exactly the state the batch engine digests
        there (docs/SEMANTICS.md: the boundary pending/live sets are
        engine-independent). Host restart resets interleave here in
        boundary order: a restart at boundary b applies AFTER the digest
        row for window b/W−1 (the pre-restart state) and before any event
        with time ≥ b — exactly where window_step applies it."""
        self._apply_restarts_pending(upto)
        if self._next_boundary > upto:
            return
        # Window index of the FIRST boundary crossed now — the window the
        # boundary checks below attribute fresh overflow / violations to
        # (no event ran between consecutive skipped boundaries, so the
        # counters cannot have moved after the first one).
        first_w = self._next_boundary // self.window - 1
        fill = int(self.pending.max()) if self.pending.size else 0
        if fill > self.metrics["ev_max_fill"]:
            self.metrics["ev_max_fill"] = fill
        if not self.digest_on and not self.work_on and not self.probe_on:
            n_skipped = (upto - self._next_boundary) // self.window + 1
            self._next_boundary += n_skipped * self.window
            self._apply_restarts_pending(upto)
            self._boundary_checks(first_w)
            return
        # One pass per boundary. The plane digests are static across a
        # multi-boundary stretch (no event ran in between, and no restart
        # fired — a restart invalidates the cache) — computed once; only
        # the per-window outbox sums differ (0 for idle windows, matching
        # the TPU's empty-outbox digest). The work-gauge samples, by
        # contrast, move per boundary (the eligibility bound advances one
        # window each time), so they are recomputed per window from the
        # static heap.
        if self.digest_on:
            from shadow1_tpu.telemetry.registry import REC_DIGEST

            dg_tcp, dg_nic, dg_rng = self._digest_planes()
        while self._next_boundary <= upto:
            b = self._next_boundary
            w = b // self.window - 1
            if self.digest_on:
                self.digest_rows.append({
                    "type": REC_DIGEST,
                    "window": w,
                    "dg_evbuf": self._ev_dg,
                    "dg_outbox": self._ob_dg.pop(w, 0),
                    "dg_tcp": dg_tcp,
                    "dg_nic": dg_nic,
                    "dg_rng": dg_rng,
                })
            if self.probe_on:
                self._probe_sample(b, w)
            if self.work_on:
                self._work_close(w)
            self._next_boundary += self.window
            if self._apply_restarts_pending(b) and self.digest_on:
                dg_tcp, dg_nic, dg_rng = self._digest_planes()
            if self.work_on:
                self._work_catchup()
        self._boundary_checks(first_w)

    # -- wasted-work accounting (performance attribution plane) -----------
    def _work_catchup(self) -> None:
        """Open the window-start work sample of every window whose start
        boundary has been crossed (all earlier events executed — the heap
        IS the engine-independent boundary pending set) and that this run
        will actually execute (start < run end). Monotonic, so incremental
        run() continuations (paritytrace lockstep chunks) sample each
        window exactly once, including window 0 on the first call."""
        while (self._work_next_open * self.window < self._cur_end
               and self._work_next_open * self.window < self._next_boundary):
            self._work_open(self._work_next_open * self.window)
            self._work_next_open += 1

    def _work_open(self, b: int) -> None:
        """Window-start sample for the window beginning at sim time ``b``:
        active hosts (≥1 pending event with time < b+W) and eligible
        events — exactly the raw window-start set core/engine.window_phases
        ph_prepare gauges before the NIC arrival batch rewrites times."""
        from shadow1_tpu.telemetry.registry import REC_WORK

        bound = b + self.window
        hosts = set()
        n_el = 0
        for ent in self.heap:
            if ent[0] < bound:
                n_el += 1
                hosts.add(ent[3])
        self.metrics["active_hosts"] += len(hosts)
        self.metrics["elig_events"] += n_el
        self._work_pending[b // self.window] = {
            "type": REC_WORK, "window": b // self.window,
            "active_hosts": len(hosts), "elig_events": n_el,
        }

    def _work_close(self, w: int) -> None:
        """Window-end half of the sample: the distinct-sender count the
        batched engine reads off the outbox ``cnt`` plane before its
        window-end clear."""
        row = self._work_pending.pop(w, None)
        if row is None:
            return
        n = self._ob_hosts.pop(w, 0)
        self.metrics["outbox_hosts"] += n
        row["outbox_hosts"] = n
        self.work_rows.append(row)

    def _boundary_checks(self, w: int) -> None:
        """The chunk-boundary guard's window-granularity twin (txn.py):
        ``halt`` raises on fresh overflow since the last boundary;
        ``--selfcheck`` verifies the drop-accounting identity. ``w`` is the
        window the fresh activity belongs to."""
        if self._halt_on_overflow:
            from shadow1_tpu.tune.ladder import next_step, recommend_cap
            from shadow1_tpu.txn import CapacityExceededError

            for ctr, knob, gauge in (
                    ("ev_overflow", "ev_cap", "ev_max_fill"),
                    ("ob_overflow", "outbox_cap", "ob_max_fill")):
                fresh = self.metrics[ctr] - self._of_seen[ctr]
                self._of_seen[ctr] = self.metrics[ctr]
                if fresh > 0:
                    cap = getattr(self.params, knob)
                    peak = int(self.metrics.get(gauge, 0))
                    raise CapacityExceededError(
                        knob=knob, counter=ctr, cap=cap, overflow=fresh,
                        window_range=(max(w, 0), w + 1),
                        recommended=max(next_step(cap),
                                        recommend_cap(peak) if peak else 0))
        if self._selfcheck:
            from shadow1_tpu.txn import check_boundary_identity

            check_boundary_identity(
                self.metrics, where=f"window {w} boundary (cpu oracle)")

    def _probe_sample(self, b: int, w: int) -> None:
        """One REC_FLOW row per watched (host, sock) at boundary ``b`` —
        the oracle twin of telemetry/probes.probe_sample. i32-semantics TCP
        columns mask with & 0xFFFFFFFF (the batched engines widen the same
        i32 planes through u32); ``inflight`` is the signed seq distance;
        NIC backlog is free-time relative to the boundary."""
        from shadow1_tpu.consts import SEC, seq_sub
        from shadow1_tpu.telemetry.registry import PROBE_FIELDS, REC_FLOW

        model = self.model
        has_net = hasattr(model, "socks")
        t = round(b / SEC, 9)
        m32 = 0xFFFFFFFF
        for gh, sock in self.params.probes:
            cols = dict.fromkeys(PROBE_FIELDS, 0)
            if has_net and sock >= 0:
                k = model.socks[gh][sock]
                cols["tcp_state"] = k.st & m32
                cols["cwnd"] = k.cwnd & m32
                cols["ssthresh"] = k.ssthresh & m32
                cols["snd_max"] = k.snd_max & m32
                cols["peer_wnd"] = k.peer_wnd & m32
                cols["inflight"] = seq_sub(k.snd_nxt, k.snd_una)
                cols["srtt"] = int(k.srtt)
                cols["rttvar"] = int(k.rttvar)
                cols["rto"] = int(k.rto)
            if has_net:
                cols["nic_tx_backlog_ns"] = max(int(model.tx_free[gh]) - b, 0)
                cols["nic_rx_backlog_ns"] = max(int(model.rx_free[gh]) - b, 0)
                cols["nic_tx_bytes"] = int(model.tx_bytes[gh])
                cols["nic_rx_bytes"] = int(model.rx_bytes[gh])
            cols["pending_events"] = int(self.pending[gh])
            rec = {
                "type": REC_FLOW,
                "window": w,
                "sim_time_s": t,
                "host": int(gh),
                "sock": int(sock),
            }
            rec.update({f: int(cols[f]) for f in PROBE_FIELDS})
            self.probe_rows.append(rec)

    def _link_nic_drop(self, src: int, dst: int) -> None:
        """Egress-edge attribution of a NIC uplink drop-tail drop — the
        oracle twin of telemetry.links.link_nic_drops (called from the
        model's _tx at the exact nic_tx_drops site; RED drops excluded)."""
        if self.link_on:
            vs = int(self.exp.host_vertex[src])
            vd = int(self.exp.host_vertex[dst])
            self._link_acc[vs, vd, 4] += 1

    def _drain_links(self, done: int) -> None:
        """Cumulative per-edge ``link`` snapshots at window boundary
        ``done`` — field-for-field the records telemetry.links.drain_links
        emits from the batched accumulator at the same boundary. The
        cursor keeps run() continuations (paritytrace lockstep chunks)
        from re-emitting a boundary."""
        if not self.link_on or done <= self._link_next:
            return
        from shadow1_tpu.consts import SEC
        from shadow1_tpu.telemetry.registry import LINK_FIELDS, REC_LINK

        self._link_next = done
        t = round(done * self.window / SEC, 9)
        for vs, vd in zip(*np.nonzero(self._link_acc.any(axis=-1))):
            rec = {
                "type": REC_LINK,
                "window": done - 1,
                "sim_time_s": t,
                "src_vertex": int(vs),
                "dst_vertex": int(vd),
            }
            rec.update({f: int(x) for f, x in
                        zip(LINK_FIELDS, self._link_acc[vs, vd])})
            self.link_rows.append(rec)

    def _digest_planes(self) -> tuple[int, int, int]:
        """(dg_tcp, dg_nic, dg_rng) of the CURRENT state — the oracle twins
        of core/digest.py's plane digests, same element words, same field
        order."""
        from shadow1_tpu.core import digest as D

        model = self.model
        dg_tcp = dg_nic = 0
        extras: list = []
        if hasattr(model, "socks"):  # net model: tcp + nic planes
            from shadow1_tpu.consts import TCP_FREE

            for h, socks in enumerate(model.socks):
                for s, k in enumerate(socks):
                    if k.st != TCP_FREE:
                        dg_tcp += D.sock_word(h, s, k)
            dg_nic = D.digest_nic_np(model.tx_free, model.rx_free,
                                     model.tx_bytes, model.rx_bytes,
                                     model.aqm_ctr)
        elif hasattr(model, "hops"):  # phold: draw counters are model state
            extras = [model.hops, model.ctr]
        dg_rng = D.digest_rng_np(
            [self.self_ctr, self.pkt_ctr, self.cpu_busy] + extras
        )
        return dg_tcp, dg_nic, dg_rng

    # -- main loop ---------------------------------------------------------
    def run(self, n_windows: int | None = None) -> dict[str, Any]:
        end = (self.n_windows if n_windows is None else n_windows) * self.window
        # Restart resets apply only at window starts the batched engine
        # actually runs (win_start < end); a boundary AT the run end defers
        # to a later run() continuation (paritytrace's lockstep chunks).
        self._cur_end = max(self._cur_end, end)
        if self.work_on:
            self._work_catchup()
        rx_batch = getattr(self.model, "rx_batch", False)
        while self.heap and self.heap[0][0] < end:
            self._sample_fill(int(self.heap[0][0]))
            time, tb, _g, host, kind, p = heapq.heappop(self.heap)
            self.pending[host] -= 1
            if self.digest_on:
                self._ev_dg -= self._ev_word.pop(_g)
            # churn: a dead host discards its events (core run_round rule)
            if self.has_stop and self._down_at(host, time):
                self.metrics["down_events"] += 1
                continue
            # NIC arrival fast path: rx processing is plumbing, not an event
            # — no event count, no virtual-CPU charge (mirror of the batched
            # engine's window-start conversion, net.make_pre_window).
            if kind == K_PKT and rx_batch:
                self.model.rx_convert(host, time, tb, p)
                continue
            # virtual CPU (host/cpu.c): execute at eff = max(time, busy); an
            # execution slipping past the window boundary re-queues at
            # (eff, original tb) unexecuted — identical rule to run_round.
            if self.has_cpu:
                eff = max(time, int(self.cpu_busy[host]))
                if eff >= (time // self.window + 1) * self.window:
                    self._push(eff, tb, host, kind, p)
                    continue
                self.cpu_busy[host] = eff + int(self.cpu_cost[host])
                time = eff
            self.metrics["events"] += 1
            f = self._pops_field.get(kind)
            if f:
                self.metrics[f] += 1
            self.model.handle(host, time, kind, p)
        # Remaining boundaries up to the run end see a static pending set.
        self._sample_fill(end)
        self._drain_links(end // self.window)
        return dict(self.metrics)

    def summary(self) -> dict[str, Any]:
        return self.model.summary()


def snap_host_arrays(obj, n_hosts: int) -> dict[str, np.ndarray]:
    """Copy every per-host numpy attribute of ``obj`` (host axis 0 — the
    oracle layout convention, transposed from the batch engines' host-minor
    tensors). Config arrays that happen to match are harmless: restoring a
    never-mutated array is the identity, exactly like the batched engines'
    whole-model column reset (fault/plane.reset_host_columns)."""
    return {
        k: v.copy() for k, v in vars(obj).items()
        if isinstance(v, np.ndarray) and v.ndim >= 1 and v.shape[0] == n_hosts
    }


def reset_host_arrays(obj, snap: dict[str, np.ndarray], host: int) -> None:
    for k, v in snap.items():
        getattr(obj, k)[host] = v[host]


class CpuPhold:
    """Oracle PHOLD (semantics mirror of shadow1_tpu.core.phold)."""

    def __init__(self, eng: CpuEngine):
        self.eng = eng
        cfg = eng.exp.model_cfg
        self.mean = float(cfg["mean_delay_ns"])
        self.init_events = int(cfg.get("init_events", 1))
        self.hops = np.zeros(eng.exp.n_hosts, np.int64)
        self.ctr = np.zeros(eng.exp.n_hosts, np.int64)

    def start(self) -> None:
        for h in range(self.eng.exp.n_hosts):
            for _ in range(self.init_events):
                self.eng.schedule_local(h, 0, K_PHOLD, ())

    # -- fault-plane restart (mirror of the init-model column reset) ------
    def snapshot_host_state(self):
        return snap_host_arrays(self, self.eng.exp.n_hosts)

    def reset_host(self, host: int, snap) -> None:
        reset_host_arrays(self, snap, host)

    def handle(self, host: int, time: int, kind: int, p: tuple) -> None:
        d = self.eng.draws
        ctr = int(self.ctr[host])
        delay = d.exponential_ns(R_PHOLD_DELAY, host, ctr, self.mean)
        dst = d.randint(R_PHOLD_DST, host, ctr, self.eng.exp.n_hosts)
        self.ctr[host] += 1
        self.hops[host] += 1
        t_next = time + delay
        if dst == host:
            self.eng.schedule_local(host, t_next, K_PHOLD, ())
        else:
            self.eng.send(host, dst, K_PHOLD, t_next, (), now=time)

    def summary(self) -> dict[str, Any]:
        return {"hops": self.hops, "total_hops": int(self.hops.sum())}
