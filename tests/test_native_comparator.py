"""The C++ thread-per-core comparator simulates the identical experiment.

Counter equality against the Python oracle (itself parity-locked to the
TPU engine) is what entitles bench.py to quote the comparator's wall clock
as the honest thread-per-core baseline (SURVEY §7.3.5).
"""

import pytest

from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import MS, EngineParams
from shadow1_tpu.cpu_engine import CpuEngine

native = pytest.importorskip("shadow1_tpu.native")


def _config(n_hosts=256, windows=40, init=3):
    exp = single_vertex_experiment(
        n_hosts=n_hosts, seed=77, end_time=windows * MS, latency_ns=1 * MS,
        model="phold",
        model_cfg={"mean_delay_ns": float(2 * MS), "init_events": init},
    )
    params = EngineParams(ev_cap=32, outbox_cap=16, max_rounds=64)
    return exp, params, windows


def _run_native(exp, params, windows, n_threads):
    try:
        return native.run_phold(
            n_hosts=exp.n_hosts, seed=exp.seed, n_windows=windows,
            window_ns=exp.window, mean_delay_ns=exp.model_cfg["mean_delay_ns"],
            init_events=exp.model_cfg["init_events"], ev_cap=params.ev_cap,
            outbox_cap=params.outbox_cap, n_threads=n_threads,
        )
    except native.NativeUnavailable as e:
        pytest.skip(str(e))


@pytest.mark.parametrize("n_threads", [1, 4])
def test_native_matches_oracle(n_threads):
    exp, params, windows = _config()
    cm = CpuEngine(exp, params).run()
    assert cm["ev_overflow"] == 0 and cm["ob_overflow"] == 0, (
        "config must be overflow-free for exact parity"
    )
    nm = _run_native(exp, params, windows, n_threads)
    for k in ("events", "pkts_sent", "pkts_delivered", "ev_overflow", "ob_overflow"):
        assert nm[k] == cm[k], (k, nm[k], cm[k], f"threads={n_threads}")


# --------------------------------------------------------------------------
# Net-model comparator (round 4): full virtual-TCP stack + model apps.
# Counter equality against the oracle on every app family is what entitles
# bench_ladder to quote vs_cpp on the net rungs (VERDICT r3 missing #3).
# --------------------------------------------------------------------------
NET_KEYS = (
    "events", "pkts_sent", "pkts_delivered", "pkts_lost", "ev_overflow",
    "ob_overflow", "tcp_fast_rtx", "tcp_rto", "tcp_ooo_drops",
    "pops_deliver", "pops_timer", "pops_txr", "pops_app",
)


def _compare_net(exp, params, windows, summary_keys, n_threads=2):
    import numpy as np

    cpu = CpuEngine(exp, params)
    cm = cpu.run(n_windows=windows)
    cs = cpu.summary()
    try:
        nm = native.run_net(exp, params, windows, n_threads=n_threads)
    except native.NativeUnavailable as e:
        pytest.skip(str(e))
    for k in NET_KEYS:
        assert nm[k] == cm[k], (k, nm[k], cm[k])
    for k in summary_keys:
        want = cs[k]
        want = int(want if np.ndim(want) == 0 else np.asarray(want).sum())
        assert int(nm[k]) == want, (k, nm[k], want)


def test_net_native_filexfer_lossy():
    import numpy as np
    from shadow1_tpu.consts import SEC

    n = 8
    role = np.full(n, 1, np.int64)
    role[0] = 0
    exp = single_vertex_experiment(
        n_hosts=n, seed=3, end_time=20 * SEC, latency_ns=10 * MS,
        loss=0.01, bw_bits=10**7, model="net",
        model_cfg={
            "app": "filexfer", "role": role, "server": np.zeros(n, np.int64),
            "flow_bytes": np.full(n, 30_000, np.int64),
            "start_time": np.full(n, MS, np.int64),
            "flow_count": np.where(role == 1, 1, 0),
        },
    )
    _compare_net(exp, EngineParams(ev_cap=256), 2000,
                 ("total_flows_done", "total_rx_bytes"))


def test_net_native_tor():
    from shadow1_tpu.consts import SEC
    from tests.test_tor_parity import tor_exp

    exp = tor_exp(seed=11, end=30 * SEC)
    _compare_net(exp, EngineParams(ev_cap=256, sockets_per_host=32), 1000,
                 ("total_streams_done", "total_cells_fwd", "total_cells_rx",
                  "clients_done", "total_ct_overflow"))


def test_net_native_bitcoin():
    from tests.test_bitcoin_parity import btc_exp

    exp = btc_exp(seed=5)
    _compare_net(exp, EngineParams(ev_cap=256), 1200,
                 ("total_seen", "total_tx_rx"))


def test_net_native_refuses_unmodeled_fidelity():
    import numpy as np
    from shadow1_tpu.consts import SEC

    n = 4
    role = np.full(n, 1, np.int64)
    role[0] = 0
    exp = single_vertex_experiment(
        n_hosts=n, seed=3, end_time=2 * SEC, latency_ns=10 * MS,
        bw_bits=10**7, model="net",
        model_cfg={
            "app": "filexfer", "role": role, "server": np.zeros(n, np.int64),
            "flow_bytes": np.full(n, 1_000, np.int64),
            "start_time": np.full(n, MS, np.int64),
            "flow_count": np.where(role == 1, 1, 0),
        },
        cpu_ns_per_event=np.full(n, 100, np.int64),
    )
    with pytest.raises(native.NativeUnavailable, match="virtual CPU"):
        native.run_net(exp, EngineParams(), 10)
