"""Fleet↔solo digest-parity verifier — the fleet contract, live.

    python -m shadow1_tpu.tools.fleetprobe sweep.yaml [--sides tpu,cpu]
        [--windows N] [--exps 0,2] [--json-only]
    python -m shadow1_tpu.tools.fleetprobe sweep.yaml --retry
        [--sides tpu,cpu] [--windows N] [--chunk C]

Expands the config's ``sweep:`` section, runs the WHOLE fleet as one
vmapped program with the determinism flight recorder on, then runs each
experiment ALONE on the requested sides (``tpu`` = the solo batched
engine, ``cpu`` = the eager oracle) and asserts the per-window digest
streams are bit-identical per experiment: lane e of the fleet must be
indistinguishable from running experiment e by itself
(docs/SEMANTICS.md §"Fleet contract").

``--retry`` proves the FLEET TRANSACTIONAL RETRY contract instead
(docs/SEMANTICS.md §"Fleet recovery contract", mirroring the PR 5 solo
proof): the config must be deliberately under-capped — the sweep runs
under ``--on-overflow retry`` (chunks discarded, the fleet-uniform cap
grown, replayed), must actually retry at least once, and every lane's
committed digest stream must bit-match (a) the straight fleet run at the
final grown caps and (b, ``cpu`` side) the eager oracle at those caps.

Exit codes follow tools/paritytrace.py: 0 = parity, 3 = divergence (the
last stdout line is a JSON verdict either way). On a mismatch the verdict
names the first divergent (experiment, side, window, subsystems) — feed
that experiment's config to paritytrace for the per-slot plane diff.
"""

from __future__ import annotations

import argparse
import json
import sys

EXIT_DIVERGED = 3


def _ring_digest_stream(st, window_ns: int) -> dict[int, tuple]:
    from shadow1_tpu.core.digest import SUBSYSTEMS
    from shadow1_tpu.telemetry.ring import drain_ring

    return {
        r["window"]: tuple(r[f"dg_{s}"] for s in SUBSYSTEMS)
        for r in drain_ring(st, window_ns)
        if r["type"] == "ring"
    }


def _first_mismatch(fleet: dict, solo: dict) -> dict | None:
    from shadow1_tpu.core.digest import SUBSYSTEMS

    if sorted(fleet) != sorted(solo):
        return {"window": None,
                "reason": f"window sets differ (fleet {len(fleet)}, "
                          f"solo {len(solo)})"}
    for w in sorted(fleet):
        if fleet[w] != solo[w]:
            subs = [s for s, a, b in
                    zip(SUBSYSTEMS, fleet[w], solo[w]) if a != b]
            return {"window": w, "subsystems": subs}
    return None


def _retry_probe(plan, params, windows: int, chunk: int, sides, say) -> dict:
    """The fleet-retry bit-exactness proof: under-capped fleet + retry ==
    straight fleet at the final grown caps, per lane, plus the cpu-oracle
    side at those caps."""
    import dataclasses

    from shadow1_tpu.core.digest import SUBSYSTEMS
    from shadow1_tpu.fleet.engine import FleetEngine, slice_experiment
    from shadow1_tpu.fleet.run import run_fleet

    p_retry = dataclasses.replace(params, on_overflow="retry")
    eng = FleetEngine(plan.exps, p_retry, plan.max_rounds)
    say(f"[fleetprobe] retry fleet: {eng.n_exp} experiments x {windows} "
        f"windows at ev_cap={p_retry.ev_cap}")
    st, hb = run_fleet(eng, n_windows=windows, every_windows=chunk,
                       stream=False)
    guard = hb.guard
    verdict = {
        "mode": "retry",
        "experiments": eng.n_exp,
        "windows": windows,
        "chunk_retries": guard.chunk_retries,
        "retry_windows_rerun": guard.retry_windows_rerun,
        "final_caps": guard.final_caps,
        "sides": list(sides),
        "mismatches": [],
    }
    if guard.chunk_retries == 0:
        verdict.update(ok=False, error="config never overflowed — the "
                                       "retry proof needs an under-capped "
                                       "sweep (shrink engine.ev_cap)")
        return verdict
    retry_streams = [
        _ring_digest_stream(slice_experiment(st, e), eng.window)
        for e in range(eng.n_exp)
    ]
    p_big = dataclasses.replace(
        params, on_overflow="drop",
        ev_cap=guard.final_caps["ev_cap"],
        outbox_cap=guard.final_caps["outbox_cap"])
    if "tpu" in sides:
        big = FleetEngine(plan.exps, p_big, plan.max_rounds)
        st_big = big.run(n_windows=windows)
        for e in range(eng.n_exp):
            solo = _ring_digest_stream(slice_experiment(st_big, e),
                                       big.window)
            mm = _first_mismatch(retry_streams[e], solo)
            if mm is None:
                say(f"[fleetprobe] exp {e} retry vs big-cap fleet: "
                    f"{len(solo)} windows bit-identical")
            else:
                verdict["mismatches"].append(
                    {"exp": e, "side": "tpu", **mm})
    if "cpu" in sides:
        from shadow1_tpu.core.digest import SUBSYSTEMS
        from shadow1_tpu.cpu_engine import CpuEngine

        for e, exp in enumerate(plan.exps):
            cpu = CpuEngine(exp, dataclasses.replace(
                p_big, max_rounds=plan.max_rounds[e]))
            cpu.run(n_windows=windows)
            orc = {r["window"]: tuple(r[f"dg_{s}"] for s in SUBSYSTEMS)
                   for r in cpu.digest_rows}
            sub = {w: retry_streams[e][w] for w in orc
                   if w in retry_streams[e]}
            if orc == sub and len(orc) == len(retry_streams[e]):
                say(f"[fleetprobe] exp {e} retry vs cpu oracle at final "
                    f"caps: {len(orc)} windows bit-identical")
            else:
                mm = _first_mismatch(retry_streams[e], orc)
                verdict["mismatches"].append(
                    {"exp": e, "side": "cpu", **(mm or {})})
    verdict["ok"] = not verdict["mismatches"]
    return verdict


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="shadow1_tpu.tools.fleetprobe")
    ap.add_argument("config", help="YAML experiment file with a sweep: "
                                   "section")
    ap.add_argument("--sides", default="tpu,cpu",
                    help="comma list of solo sides to compare each fleet "
                         "lane against: tpu (solo batched engine), cpu "
                         "(eager oracle). Default both.")
    ap.add_argument("--windows", type=int, default=None,
                    help="compare only this many windows (default: the "
                         "configured run, capped at 200)")
    ap.add_argument("--exps", default=None,
                    help="comma list of experiment indices to solo-check "
                         "(default: all)")
    ap.add_argument("--retry", action="store_true",
                    help="fleet transactional-retry proof: run the "
                         "(deliberately under-capped) sweep under "
                         "--on-overflow retry and assert per-lane digest "
                         "parity with the straight big-cap fleet run "
                         "(tpu side) and the eager oracle (cpu side)")
    ap.add_argument("--chunk", type=int, default=5,
                    help="chunk (windows) for the --retry transactional "
                         "boundaries")
    ap.add_argument("--json-only", action="store_true",
                    help="suppress progress lines; print only the verdict")
    args = ap.parse_args(argv)

    import dataclasses

    import shadow1_tpu  # noqa: F401  (x64 before jax arrays)
    from shadow1_tpu.fleet.expand import FleetConfigError, load_sweep

    def config_exit(e: FleetConfigError) -> int:
        print(f"fleetprobe: {e}", file=sys.stderr)
        print(json.dumps({"ok": False, "error": "fleet_config",
                          "kind": e.kind, "knob": e.knob,
                          "message": str(e)}))
        return 2

    try:
        plan = load_sweep(args.config)
    except FleetConfigError as e:
        return config_exit(e)
    windows = args.windows
    sides = [s.strip() for s in args.sides.split(",") if s.strip()]

    def say(msg):
        if not args.json_only:
            print(msg, file=sys.stderr, flush=True)

    from shadow1_tpu.core.engine import Engine
    from shadow1_tpu.fleet.engine import FleetEngine, slice_experiment

    # Digest transport: a ring deep enough to hold the whole compared run.
    n_total = int(-(-plan.exps[0].end_time // plan.exps[0].window))
    if windows is None:
        windows = min(n_total, 200)
    params = dataclasses.replace(plan.params, state_digest=1,
                                 metrics_ring=max(windows, 1))

    if args.retry:
        verdict = _retry_probe(plan, params, windows, args.chunk, sides,
                               say)
        print(json.dumps(verdict))
        if verdict.get("error"):
            return 2
        return 0 if verdict["ok"] else EXIT_DIVERGED

    try:
        fleet = FleetEngine(plan.exps, params, plan.max_rounds)
    except FleetConfigError as e:
        # Mode rejections raised at engine construction (auto_caps /
        # on_overflow=retry in the config's engine: section) keep the
        # same "last stdout line is a JSON verdict" contract.
        return config_exit(e)
    say(f"[fleetprobe] fleet: {fleet.n_exp} experiments x {windows} "
        f"windows, {fleet.exp.n_hosts} hosts")
    stf = fleet.run(n_windows=windows)
    fleet_streams = [
        _ring_digest_stream(slice_experiment(stf, e), fleet.window)
        for e in range(fleet.n_exp)
    ]

    exp_ids = (range(fleet.n_exp) if args.exps is None
               else [int(x) for x in args.exps.split(",")])
    compared = {s: 0 for s in sides}
    mismatches = []
    for e in exp_ids:
        exp = plan.exps[e]
        p_e = dataclasses.replace(params, max_rounds=plan.max_rounds[e])
        for side in sides:
            if side == "tpu":
                eng = Engine(exp, p_e)
                solo = _ring_digest_stream(eng.run(n_windows=windows),
                                           eng.window)
            elif side == "cpu":
                from shadow1_tpu.cpu_engine import CpuEngine

                cpu = CpuEngine(exp, p_e)
                cpu.run(n_windows=windows)
                from shadow1_tpu.core.digest import SUBSYSTEMS

                solo = {
                    r["window"]: tuple(r[f"dg_{s}"] for s in SUBSYSTEMS)
                    for r in cpu.digest_rows
                }
            else:
                raise SystemExit(f"unknown side {side!r} (tpu|cpu)")
            mm = _first_mismatch(fleet_streams[e], solo)
            if mm is None:
                compared[side] += 1
                say(f"[fleetprobe] exp {e} vs solo {side}: "
                    f"{len(solo)} windows bit-identical")
            else:
                mismatches.append({"exp": e, "side": side, **mm})
                say(f"[fleetprobe] exp {e} vs solo {side}: DIVERGED {mm}")

    verdict = {
        "ok": not mismatches,
        "experiments": fleet.n_exp,
        "solo_checked": list(exp_ids),
        "windows": windows,
        "sides": sides,
        "streams_compared": compared,
        "mismatches": mismatches,
    }
    print(json.dumps(verdict))
    return 0 if not mismatches else EXIT_DIVERGED


if __name__ == "__main__":
    raise SystemExit(main())
