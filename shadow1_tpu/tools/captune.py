"""Offline capacity tuner — measured occupancy → recommended `engine:` caps.

    python -m shadow1_tpu.tools.captune run.log [more logs/records ...]
        [--headroom 1.5] [--json]

Reads any mix of the run records the framework emits and distills the
measured peak occupancy of every bounded structure:

* telemetry-ring JSONL (``type: "ring"`` — CLI ``--metrics-ring``, stderr):
  per-window ``evbuf_fill`` plus the running ``*_max_fill`` gauges;
* heartbeat JSONL (``type: "heartbeat"``): the ``fill`` block with the caps
  it was measured against;
* the CLI's final stdout JSON (``{"metrics": ..., "caps": ...}``);
* ``tools/occprobe.py`` audit rows (``boundary_peak_occupancy``/``ev_cap``).

It then prints, per knob, the measured peak, the configured cap (when the
records carry it), the verdict (grow / shrink / ok — tune/ladder.classify),
and a paste-ready config-YAML ``engine:`` block whose provenance comments
follow the ``dense_tgen50k.yaml`` convention — so every rung config can
carry its measurement. Plane-pass economics: every pop/push/clear is a full
``[cap, H]`` pass, so a cap cut is an almost-proportional cut of the whole
round path (docs/PERF.md "cap economics"); the projected saving printed is
``1 − new/old`` of the plane height.

All peaks here are window-end / boundary samples — LOWER bounds on the true
mid-window peak. Recommendations carry ladder-quantized ×1.5 headroom, and
a cap change only counts as validated after an overflow-free full run
(``ev_overflow`` is the authoritative guard; `occprobe` says the same).

Deliberately jax-free (importable by report tools without an accelerator
runtime).
"""

from __future__ import annotations

import argparse
import json
import sys

from shadow1_tpu.tune.ladder import HEADROOM, classify, recommend_cap

# knob → (peak sources, cap key) in priority order. ``evbuf_fill`` (the
# per-window series) and ``ev_max_fill`` (its running max) measure the same
# quantity; max() over everything seen is the run peak either way.
_KNOBS = {
    "ev_cap": ("ev_max_fill", "evbuf_fill", "boundary_peak_occupancy"),
    "outbox_cap": ("ob_max_fill",),
    "compact_cap": ("compact_max_fill",),
    "x2x_cap": ("x2x_max_fill",),
}


def load_records(paths: list[str]) -> list[dict]:
    recs: list[dict] = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    recs.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    return recs


def group_records(recs: list[dict]) -> dict[str, list[dict]]:
    """Partition records by the config they measured (occprobe rows carry
    ``config``; a single run's ring/heartbeat/final records do not and land
    in one shared group) — peaks must never aggregate across configs.

    Fleet records (ring rows / ``fleet_exp`` finals with an ``exp``
    experiment id) further partition per experiment: a sweep's cap
    verdicts come out one per experiment, and one lane's occupancy can
    never inflate another's recommendation. The experiment id is purely a
    grouping key — it enters no peak/percentile math. Records WITHOUT an
    ``exp`` field from a fleet log (the aggregate heartbeat /
    ``fleet_summary``) land in the shared base group, whose gauges are
    fleet maxima — per-experiment truth stays in the exp groups."""
    groups: dict[str, list[dict]] = {}
    for r in recs:
        key = str(r.get("config", "(run)"))
        if isinstance(r.get("exp"), int):
            key += f" [exp {r['exp']}]"
        groups.setdefault(key, []).append(r)
    return groups


def peaks_from_records(recs: list[dict]) -> tuple[dict, dict, dict]:
    """→ (peaks, caps, overflow): measured peak fill, configured cap and
    summed overflow counters per knob, from whatever record shapes appear."""
    peaks: dict[str, int] = {}
    caps: dict[str, int] = {}
    # Overflow arrives in three redundant shapes — per-window ring deltas,
    # per-chunk heartbeat deltas (which the ring rows sum to), and the
    # cumulative counters of metrics/occprobe records. Accumulate each
    # channel separately and take the max, so any one of them suffices and
    # their redundancy never double-counts into a bogus total.
    _CTRS = (("ev_overflow", "ev_cap"), ("ob_overflow", "outbox_cap"),
             ("x2x_overflow", "x2x_cap"))
    ring_sum = {k: 0 for _, k in _CTRS}
    hb_sum = {k: 0 for _, k in _CTRS}
    cum_max = {k: 0 for _, k in _CTRS}

    def bump(knob, v):
        if v is not None and int(v) > peaks.get(knob, 0):
            peaks[knob] = int(v)

    for r in recs:
        flat = dict(r)
        # Nested shapes: CLI final record / heartbeat fill block.
        for sub in ("metrics", "fill", "caps"):
            if isinstance(r.get(sub), dict):
                flat.update(r[sub])
        for knob, fields in _KNOBS.items():
            for f in fields:
                bump(knob, flat.get(f))
            if isinstance(flat.get(knob), (int, float)) and flat[knob]:
                caps[knob] = int(flat[knob])
        delta = r.get("delta") if isinstance(r.get("delta"), dict) else {}
        if isinstance(r.get("drops"), dict):
            # Heartbeats group the drop counters under a structured block
            # (telemetry.registry.DROP_FIELDS) — same chunk deltas.
            delta = {**delta, **r["drops"]}
        for ctr, knob in _CTRS:
            if r.get("type") == "ring" and isinstance(r.get(ctr), (int, float)):
                ring_sum[knob] += int(r[ctr])
            elif isinstance(delta.get(ctr), (int, float)):
                hb_sum[knob] += int(delta[ctr])
            elif isinstance(flat.get(ctr), (int, float)):
                cum_max[knob] = max(cum_max[knob], int(flat[ctr]))
    overflow = {k: max(ring_sum[k], hb_sum[k], cum_max[k])
                for _, k in _CTRS}
    return peaks, caps, overflow


def advise(peaks: dict, caps: dict, overflow: dict | None = None,
           headroom: float = HEADROOM) -> list[dict]:
    """One advisory row per knob with measured data."""
    out = []
    overflow = overflow or {}
    for knob in _KNOBS:
        peak = peaks.get(knob)
        if not peak:
            continue
        cap = caps.get(knob)
        row = {"knob": knob, "peak": peak, "cap": cap,
               "overflowed": bool(overflow.get(knob))}
        if (knob == "outbox_cap" and cap and peak >= cap
                and not row["overflowed"]):
            # A full outbox with ob_overflow == 0 is TCP send pacing (the
            # flush defers on outbox_space by design), not imminent loss —
            # and outbox_cap is a SEMANTIC knob for TCP (changing it changes
            # the event stream), so never advise a resize from fill alone.
            row.update({"verdict": "pacing", "recommended": cap,
                        "over_factor": 1.0, "target": cap})
        elif cap:
            row.update(classify(peak, cap, headroom))
            if row["verdict"] == "shrink":
                # Plane-pass cost ∝ cap: the projected round-path saving.
                row["plane_pass_saving"] = round(1 - row["recommended"] / cap, 2)
        else:
            row["verdict"] = "measure"
            row["recommended"] = recommend_cap(peak, headroom)
        out.append(row)
    return out


def advise_lines(rows: list[dict]) -> list[str]:
    """Human-readable one-liners (shared with tools/heartbeat_report.py)."""
    lines = []
    for r in rows:
        bits = [f"{r['knob']}: measured peak {r['peak']}"]
        if r.get("cap"):
            bits.append(f"cap {r['cap']} ({r['over_factor']}x peak)")
        if r["verdict"] == "shrink":
            bits.append(f"SHRINK -> {r['recommended']} "
                        f"(~{int(r['plane_pass_saving'] * 100)}% plane-pass cut)")
        elif r["verdict"] == "grow":
            bits.append(f"GROW -> {r['recommended']} (overflow risk)")
        elif r["verdict"] == "pacing":
            bits.append("full at cap with 0 drops — TCP send pacing "
                        "(semantic knob); resizing changes the event stream")
        elif r["verdict"] == "measure":
            bits.append(f"recommend {r['recommended']} (no configured cap seen)")
        else:
            bits.append("ok")
        if r.get("overflowed"):
            bits.append("[RUN OVERFLOWED — peak is a floor, not a peak]")
        lines.append(", ".join(bits))
    return lines


def render_yaml(rows: list[dict], headroom: float = HEADROOM) -> str:
    """Paste-ready ``engine:`` block with measured-peak provenance comments
    (the dense_tgen50k.yaml convention)."""
    if not rows:
        return ""
    lines = ["engine:"]
    for r in rows:
        if r["verdict"] == "pacing":
            lines.append(
                f"  {r['knob']}: {r['cap']}  # captune: full at cap with 0 "
                f"drops = TCP send pacing; semantic knob — keep"
            )
        elif r["verdict"] == "ok":
            lines.append(
                f"  {r['knob']}: {r['cap']}  # captune: measured peak "
                f"{r['peak']} (window-end sample), cap already within the "
                f"x{headroom} headroom band — keep"
            )
        else:
            was = (f"; was {r['cap']} ({r['over_factor']}x over peak)"
                   if r.get("cap") and r["verdict"] == "shrink" else "")
            lines.append(
                f"  {r['knob']}: {r['recommended']}  # captune: measured "
                f"peak {r['peak']} (window-end sample), x{headroom} headroom "
                f"-> ladder {r['recommended']}{was}"
            )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="shadow1_tpu.tools.captune")
    ap.add_argument("records", nargs="+",
                    help="run logs/records: ring/heartbeat JSONL, the CLI's "
                         "final JSON line, occprobe rows — any mix")
    ap.add_argument("--headroom", type=float, default=HEADROOM,
                    help=f"sizing headroom over the measured peak "
                         f"(default {HEADROOM})")
    ap.add_argument("--json", action="store_true",
                    help="emit the advisory rows as one JSON line instead "
                         "of text")
    args = ap.parse_args(argv)
    recs = load_records(args.records)
    if not recs:
        print("no JSON records found", file=sys.stderr)
        return 1
    by_cfg = {
        cfg: advise(*peaks_from_records(group), headroom=args.headroom)
        for cfg, group in group_records(recs).items()
    }
    by_cfg = {cfg: rows for cfg, rows in by_cfg.items() if rows}
    if not by_cfg:
        print("records carry no occupancy gauges (need a run with "
              "--metrics-ring, a final-metrics record with ev_max_fill, or "
              "an occprobe row)", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps({"advice": by_cfg}))
        return 0
    for cfg, rows in by_cfg.items():
        print(f"== captune: {cfg} ==")
        for line in advise_lines(rows):
            print("  " + line)
        print("-- config-YAML (paste into the experiment file) --")
        print(render_yaml(rows, headroom=args.headroom))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
