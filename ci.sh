#!/usr/bin/env bash
# CI driver — the `./setup test` analogue (reference: setup + cmake + ctest).
#
#   ./ci.sh            fast tier: full suite minus the slow mid-scale tier
#   ./ci.sh all        everything, including 512–1024-host parity
#   ./ci.sh smoke      config + events + ckpt/obs/telemetry + tune + digest
#                      + txn fast paths (tgen-based tune tests stay in
#                      fast/all), plus a tiny tpu-vs-cpu paritytrace bisect
#                      on the rung-1 config: inject a window-8 corruption,
#                      assert the flight recorder localizes it to exactly
#                      window 8; plus the fault-plane smokes: a shortened
#                      churn-scenario cpu-vs-tpu digest parity run
#                      (churnprobe) and corrupt-checkpoint rejection
#                      (integrity digest); plus the overflow-policy smokes:
#                      an under-capped run under --on-overflow retry must
#                      bit-match its big-cap twin's digest stream, and
#                      --on-overflow halt must exit 4 with paste-ready
#                      cap advice (CapacityExceededError); plus the
#                      perf-attribution smokes: the multi-row bench gate
#                      (dense/sparse/fleet ms-per-round vs BENCH_GATE.json),
#                      the opcensus eqn-drift gate (must trip on an
#                      injected extra-op build) and a phaseprobe
#                      attribution with >=90% coverage; plus the fleet
#                      recovery smokes: fleetprobe --retry (under-capped
#                      sweep retry == big-cap fleet per-lane digests,
#                      cpu+tpu), a 3-trial chaosprobe fleet matrix
#                      (kill-anywhere under forced overflow retry +
#                      forced-lane-halt quarantine), and the quarantined
#                      lane's checkpoint resuming solo bit-identically;
#                      plus the serve-plane smokes: a real daemon round
#                      trip (sequential same-shape jobs -> engine-cache
#                      hit with no recompile, over-budget submission
#                      rejected pre-compile with advice, served digest
#                      streams bit-matching solo CLI runs, SIGTERM drain)
#                      and a kill-during-submit chaos pair (no torn spool
#                      records, restart completes bit-identically); plus
#                      the flow-probe smokes: the watched-flow probe
#                      stream on the rung-1 config must be bit-identical
#                      cpu-vs-tpu, and the flowreport stall detectors
#                      must pass their synthetic self-test; plus the
#                      link-telemetry smokes: the rung-1 per-edge link
#                      records must be bit-identical cpu-vs-tpu with the
#                      drop columns reconciling against the global
#                      counters, the netreport weathermap detectors must
#                      pass their synthetic self-test, and the opcensus
#                      gate doubles as the proof that --link-telem off
#                      (the default) adds zero traced ops
#
# Tests force the CPU platform with 8 virtual devices (tests/conftest.py),
# so CI needs no accelerator; the TPU-hardware path is covered separately
# by tests/test_backend_parity.py, which skips cleanly when absent.
set -euo pipefail
cd "$(dirname "$0")"

tier="${1:-fast}"
case "$tier" in
  smoke)
    python -m pytest tests/test_config.py tests/test_events.py tests/test_rng.py tests/test_ckpt_obs.py tests/test_telemetry.py tests/test_tune.py tests/test_digest.py tests/test_txn.py tests/test_fleet.py tests/test_fleet_recover.py tests/test_preempt.py tests/test_perfobs.py tests/test_serve.py tests/test_probes.py tests/test_pcap.py tests/test_links.py -q -m "not slow" -k "not tgen"
    echo "== paritytrace bisect smoke (rung-1, injected corruption) =="
    # CPU platform like the pytest tiers (conftest forces it there; the
    # tool inherits the env) — the smoke must not depend on an accelerator.
    out=$(JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m shadow1_tpu.tools.paritytrace \
          configs/rung1_filexfer.yaml tpu cpu \
          --windows 16 --chunk 8 --inject 8:rng --no-localize 2>/dev/null) && rc=0 || rc=$?
    [ "$rc" -eq 3 ] || { echo "paritytrace: expected divergence exit 3, got $rc" >&2; exit 1; }
    echo "$out" | python -c '
import json, sys
d = json.loads(sys.stdin.read().strip().splitlines()[-1])["first_divergence"]
assert d == {"window": 8, "subsystems": ["rng"]}, d
print("paritytrace localized the injected corruption to", d)
'
    echo "== churn-scenario parity smoke (fault plane, cpu vs tpu) =="
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m shadow1_tpu.tools.churnprobe \
        configs/churn_filexfer.yaml --sides cpu,tpu --windows 40 --chunk 20 \
        2>/dev/null | python -c '
import json, sys
d = json.loads(sys.stdin.read().strip().splitlines()[-1])
assert d["ok"], d
assert d["digest_windows_compared"]["tpu"] == 40, d
print("churnprobe: 40-window digest parity ok;",
      "restarts:", d["counters"]["tpu"]["host_restarts"],
      "down_pkts:", d["counters"]["tpu"]["down_pkts"])
'
    echo "== overflow-retry parity smoke (txn plane) =="
    # A deliberately under-capped PHOLD run under --on-overflow retry must
    # (a) actually retry, (b) produce a digest stream bit-identical to the
    # same config run straight at the final (grown) caps; plus one halt
    # exit-code check (CapacityExceededError → exit 4, advice on stderr).
    of_cfg=$(mktemp /tmp/shadow1_of_XXXX.yaml)
    cat > "$of_cfg" <<'YAML'
general: {seed: 5, stop_time: 40 ms}
engine: {scheduler: tpu, ev_cap: 8}
network: {single_vertex: {latency: 1 ms}}
hosts:
  - {name: h, count: 8}
app:
  model: phold
  params: {mean_delay_ns: 2000000.0, init_events: 6}
YAML
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - "$of_cfg" <<'EOF'
import sys
import shadow1_tpu
from shadow1_tpu.ckpt import run_chunked
from shadow1_tpu.config.experiment import load_experiment
from shadow1_tpu.consts import EngineParams
from shadow1_tpu.core.digest import DIGEST_FIELDS
from shadow1_tpu.core.engine import Engine
from shadow1_tpu.telemetry.ring import drain_ring
from shadow1_tpu.txn import OverflowGuard
import dataclasses

exp, params, _ = load_experiment(sys.argv[1])
params = dataclasses.replace(params, metrics_ring=10, state_digest=1)

def stream(eng, guard=None):
    rows, start = {}, [0]
    def on_chunk(st, _d):
        for r in drain_ring(st, eng.window, start=start[0]):
            if r["type"] == "ring":
                rows[r["window"]] = tuple(r[f] for f in DIGEST_FIELDS)
        start[0] = int(st.metrics.windows)
    st = run_chunked(eng, n_windows=40, chunk=10, guard=guard,
                     on_chunk=on_chunk)
    return rows, st

eng = Engine(exp, params)
guard = OverflowGuard(eng, make_engine=lambda p: Engine(exp, p), mode="retry")
rows_retry, st = stream(eng, guard)
assert guard.chunk_retries >= 1, "under-capped config did not retry"
assert int(st.metrics.ev_overflow) == 0, "committed stream must be clean"
big = guard.final_caps["ev_cap"]
rows_big, _ = stream(Engine(exp, dataclasses.replace(params, ev_cap=big)))
assert rows_retry == rows_big, "retry digest stream != big-cap twin"
print(f"overflow retry: {guard.chunk_retries} chunk(s) replayed, "
      f"ev_cap 8 -> {big}, 40-window digest parity with the big-cap twin")
EOF
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m shadow1_tpu "$of_cfg" \
        --on-overflow halt >/dev/null 2>/tmp/_of_halt.log && rc=0 || rc=$?
    [ "$rc" -eq 4 ] || { echo "halt: expected CapacityExceededError exit 4, got $rc" >&2; exit 1; }
    grep -q "Paste-ready fix" /tmp/_of_halt.log || { echo "halt: advice missing" >&2; exit 1; }
    echo "halt: exit 4 with paste-ready cap advice ok"
    rm -f "$of_cfg" /tmp/_of_halt.log
    echo "== fleet digest-parity smoke (3-experiment sweep vs solo, cpu+tpu) =="
    # A 3-experiment fleet (seed change, loss-rate change, churn schedule)
    # run as ONE vmapped program: every lane's per-window digest stream
    # must be bit-identical to running that experiment alone, on both the
    # solo batched engine and the cpu oracle (the fleet contract,
    # docs/SEMANTICS.md).
    fl_cfg=$(mktemp /tmp/shadow1_fl_XXXX.yaml)
    cat > "$fl_cfg" <<'YAML'
general: {seed: 7, stop_time: 80 ms}
engine: {scheduler: tpu, ev_cap: 32, outbox_cap: 16}
network: {single_vertex: {latency: 10 ms}}
hosts:
  - {name: h, count: 8}
app:
  model: phold
  params: {mean_delay_ns: 2.0e7, init_events: 2}
sweep:
  seeds: [7, 8, 9]
  vary:
    - {}
    - {network: {single_vertex: {loss: 0.05}}}
    - {faults: {hosts: [{group: h, down_at: 30 ms, up_at: 60 ms}]}}
YAML
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m shadow1_tpu.tools.fleetprobe \
        "$fl_cfg" --sides tpu,cpu 2>/dev/null | python -c '
import json, sys
d = json.loads(sys.stdin.read().strip().splitlines()[-1])
assert d["ok"], d
assert d["experiments"] == 3, d
assert d["streams_compared"] == {"tpu": 3, "cpu": 3}, d
print("fleetprobe: 3 experiments x", d["windows"],
      "windows bit-identical fleet<->solo on tpu and cpu sides")
'
    rm -f "$fl_cfg"
    echo "== fleet recovery smoke (transactional retry + lane quarantine) =="
    # The PR 13 acceptance gates. (1) fleetprobe --retry: a deliberately
    # under-capped sweep under --on-overflow retry must actually retry and
    # every lane's committed digest stream must bit-match the straight
    # big-cap fleet run (tpu side) AND the eager oracle at the final caps
    # (cpu side) — the PR 5 solo proof, fleet-wide.
    fr_cfg=$(mktemp /tmp/shadow1_fr_XXXX.yaml)
    cat > "$fr_cfg" <<'YAML'
general: {seed: 5, stop_time: 40 ms}
engine: {scheduler: tpu, ev_cap: 8}
network: {single_vertex: {latency: 1 ms}}
hosts:
  - {name: h, count: 8}
app:
  model: phold
  params: {mean_delay_ns: 2000000.0, init_events: 6}
sweep:
  seeds: [5, 6, 7]
YAML
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m shadow1_tpu.tools.fleetprobe \
        "$fr_cfg" --retry --windows 20 --json-only 2>/dev/null | python -c '
import json, sys
d = json.loads(sys.stdin.read().strip().splitlines()[-1])
assert d["ok"], d
assert d["chunk_retries"] >= 1, d
assert d["mismatches"] == [], d
print("fleetprobe --retry:", d["chunk_retries"], "chunk(s) replayed,",
      "final caps", d["final_caps"], "- per-lane digest parity with the",
      "big-cap fleet (tpu) and the oracle (cpu)")
'
    # (2) Fleet-recovery chaos matrix (3 trials total): kill-anywhere +
    # forced-overflow retry (2 trials), then forced-lane-halt quarantine
    # (1 trial) — each relaunched to completion and bit-compared per
    # surviving lane against the straight run; the quarantine trial must
    # slice out exactly lane 1 on both sides.
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m shadow1_tpu.tools.chaosprobe \
        "$fr_cfg" --fleet --extra "--on-overflow retry" \
        --windows 40 --chunk 10 --trials 2 --seed 1 --json-only 2>/dev/null | python -c '
import json, sys
d = json.loads(sys.stdin.read().strip().splitlines()[-1])
assert d["ok"] and d["trials"] == 2, d
print("chaosprobe fleet-retry matrix:", d["trials"],
      "kill trials bit-identical under forced overflow retry")
'
    fq_cfg=$(mktemp /tmp/shadow1_fq_XXXX.yaml)
    cat > "$fq_cfg" <<'YAML'
general: {seed: 5, stop_time: 40 ms}
engine: {scheduler: tpu, ev_cap: 8}
network: {single_vertex: {latency: 1 ms}}
hosts:
  - {name: h, count: 8}
app:
  model: phold
  params: {mean_delay_ns: 2000000.0, init_events: 6}
sweep:
  seeds: [5, 6, 7]
  vary:
    - {network: {single_vertex: {loss: 0.5}}}
    - {}
    - {network: {single_vertex: {loss: 0.5}}}
YAML
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m shadow1_tpu.tools.chaosprobe \
        "$fq_cfg" --fleet --extra "--on-overflow halt --on-lane-fail quarantine" \
        --expect-quarantine 1 --windows 40 --chunk 10 --trials 1 --seed 2 \
        --json-only 2>/dev/null | python -c '
import json, sys
d = json.loads(sys.stdin.read().strip().splitlines()[-1])
assert d["ok"] and d["trials"] == 1, d
assert d["quarantined"] == [1], d
print("chaosprobe quarantine matrix: lane 1 quarantined, sweep completed",
      "2/3, kill trial bit-identical")
'
    # (3) The quarantined lane's sliced checkpoint must resume SOLO: run
    # the quarantine sweep once (quarantine snapshots land beside the
    # --ckpt path), then load its .q1 snapshot into the solo engine.
    fq_dir=$(mktemp -d /tmp/shadow1_fqd_XXXX)
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m shadow1_tpu \
        "$fq_cfg" --fleet --on-overflow halt --on-lane-fail quarantine \
        --windows 20 --ckpt "$fq_dir/q.npz" --supervised-child \
        >"$fq_dir/q.out" 2>/dev/null
    qck="$fq_dir/q.npz.q1.npz"
    [ -f "$qck" ] || { echo "quarantine ckpt $qck missing" >&2; exit 1; }
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - "$fq_cfg" "$qck" <<'EOF'
import json, sys
import shadow1_tpu
from shadow1_tpu.ckpt import load_state
from shadow1_tpu.core.engine import Engine
from shadow1_tpu.fleet.expand import load_sweep
import numpy as np

plan = load_sweep(sys.argv[1])
exp, params = plan.exps[1], plan.params
solo = Engine(exp, params)
lane = load_state(solo.init_state(), sys.argv[2])
w0 = int(np.asarray(lane.win_start)) // solo.window
st = solo.run(lane, n_windows=20 - w0)
straight = Engine(exp, params).run(n_windows=20)
assert Engine.metrics_dict(st) == Engine.metrics_dict(straight)
print(f"quarantined-lane ckpt resumed solo from window {w0}: final "
      f"metrics bit-match the straight solo run")
EOF
    rm -rf "$fr_cfg" "$fq_cfg" "$fq_dir"
    echo "== preemption smoke (SIGTERM drain + kill-anywhere chaos trials) =="
    # SIGTERM mid-run must commit the in-flight chunk, write a final
    # snapshot and exit the documented preempted code (consts.py taxonomy);
    # rerunning the same command must resume, not restart.
    pre_ck=$(mktemp -u /tmp/shadow1_pre_XXXX.npz)
    window_ns=$(JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -c '
import shadow1_tpu
from shadow1_tpu.config.experiment import load_experiment
print(load_experiment("configs/rung1_filexfer.yaml")[0].window)')
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" SHADOW1_SUPERVISE_BACKOFF_S=0 \
        SHADOW1_OBS_SIGTERM_SELF_AT_NS=$((20 * window_ns)) \
        python -m shadow1_tpu configs/rung1_filexfer.yaml --windows 40 \
        --heartbeat 10 --ckpt-every-s 0 --ckpt "$pre_ck" \
        >/tmp/_pre_drain.out 2>/dev/null && rc=0 || rc=$?
    exp_rc=$(python -c 'from shadow1_tpu.consts import EXIT_PREEMPTED; print(EXIT_PREEMPTED)')
    [ "$rc" -eq "$exp_rc" ] || { echo "drain: expected EXIT_PREEMPTED=$exp_rc, got $rc" >&2; exit 1; }
    python -c '
import json
rec = json.loads(open("/tmp/_pre_drain.out").read().strip().splitlines()[-1])
assert rec["preempted"] is True and rec["signal"] == "SIGTERM", rec
assert rec["win_start"] > 0, rec
print("drain: EXIT_PREEMPTED with parseable record at sim_ns", rec["win_start"])
'
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" SHADOW1_SUPERVISE_BACKOFF_S=0 \
        python -m shadow1_tpu configs/rung1_filexfer.yaml --windows 40 \
        --heartbeat 10 --ckpt-every-s 0 --ckpt "$pre_ck" \
        >/tmp/_pre_resume.out 2>/dev/null
    python -c '
import json
out = json.loads(open("/tmp/_pre_resume.out").read().strip().splitlines()[-1])
assert out["resumed"] is True, out
print("drain: rerun resumed from the preemption snapshot")
'
    rm -f /tmp/_pre_drain.out /tmp/_pre_resume.out "$pre_ck"*
    # Kill-anywhere chaos trials (tools/chaosprobe.py): the first three
    # trial kinds are the deterministic special ones — a mid-run SIGTERM
    # drain, a torn-head mid-checkpoint-write kill, and a corrupt-head
    # lineage fallback — each must end bit-identical to the straight run.
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m shadow1_tpu.tools.chaosprobe \
        configs/rung1_filexfer.yaml --windows 40 --chunk 10 --trials 3 \
        --seed 1 2>/dev/null | python -c '
import json, sys
d = json.loads(sys.stdin.read().strip().splitlines()[-1])
assert d["ok"], d
assert d["trials"] == 3, d
assert d["preempted_exits"] >= 1, d
assert d["lineage_fallbacks"] >= 1, d
print("chaosprobe:", d["trials"], "kill trials bit-identical;",
      d["preempted_exits"], "drain(s),", d["lineage_fallbacks"],
      "lineage fallback(s)")
'
    echo "== memory-plane smoke (pre-flight budget + sub-batch parity) =="
    # The deliberately oversubscribed config must be rejected BEFORE any
    # compile with the dedicated memory exit code, a parseable stdout
    # record and per-plane advice (shadow1_tpu/mem.py; the budget comes
    # from the env override — the CPU backend reports no device memory).
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" SHADOW1_MEM_BYTES=$((8<<30)) \
        python -m shadow1_tpu configs/mem_overbudget.yaml \
        >/tmp/_mem_ob.out 2>/tmp/_mem_ob.err && rc=0 || rc=$?
    exp_rc=$(python -c 'from shadow1_tpu.consts import EXIT_MEMORY; print(EXIT_MEMORY)')
    [ "$rc" -eq "$exp_rc" ] || { echo "mem: expected EXIT_MEMORY=$exp_rc, got $rc" >&2; exit 1; }
    python -c '
import json
d = json.loads(open("/tmp/_mem_ob.out").read().strip().splitlines()[-1])
assert d["error"] == "memory_budget", d
assert d["estimated"] > d["budget"], d
assert d["planes"]["evbuf"] > (16 << 30), d
assert "Remedies" in d["advice"], d
print("mem: pre-flight rejected", round(d["estimated"]/2**30, 1),
      "GiB estimate before compile, advice block present")
'
    grep -q "MemoryBudgetError" /tmp/_mem_ob.err || { echo "mem: stderr advice missing" >&2; exit 1; }
    rm -f /tmp/_mem_ob.out /tmp/_mem_ob.err
    # Sub-batched-fleet == full-fleet bit-exactness (the --on-oom
    # downshift contract): per-lane digest streams and parity counters
    # must be identical when the sweep runs as sequential sub-batches.
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m shadow1_tpu.tools.memprobe \
        configs/sweep_phold.yaml --subbatch --sub 3 --windows 16 \
        --json-only 2>/dev/null | python -c '
import json, sys
d = json.loads(sys.stdin.read().strip().splitlines()[-1])
assert d["ok"], d
sb = d["subbatch"]
assert sb["experiments"] == 4 and sb["streams_compared"] == 4, sb
print("memprobe: 4-lane sweep sub-batched (3+1) bit-identical per lane,",
      sb["windows"], "windows")
'
    echo "== serve-plane smoke (daemon round-trip: cache hit + admission + digest parity + resilience) =="
    # The serve acceptance gates (ISSUE 14 / docs/SEMANTICS.md §"Serving
    # contract"), all in one probe: spawn a real daemon on CPU, submit two
    # same-shape jobs SEQUENTIALLY (second batch must be an engine-cache
    # HIT — no re-trace, no recompile), submit one over-budget job (must
    # be rejected pre-compile with the memory_budget advice record and
    # EXIT_MEMORY while the others run), bit-compare both completed jobs'
    # digest streams against solo CLI runs, and SIGTERM-drain the daemon
    # (EXIT_SERVE_SHUTDOWN). --resilience then runs a SECOND daemon under
    # a squeezed budget (ISSUE 19): one tenant parks in waiting_headroom
    # and later completes bit-exact, a depth-2 queue rejects the fourth
    # submit with queue_full + retry_after_s advice, a --queue-ttl-s
    # tenant expires with deadline_expired, and an injected transient
    # crash is retried to a bit-exact finish.
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m shadow1_tpu.tools.serveprobe \
        configs/serve_phold.yaml --seeds 5,6 \
        --overbudget configs/mem_overbudget.yaml --mem-bytes $((8<<30)) \
        --resilience --json-only 2>/dev/null | python -c '
import json, sys
d = json.loads(sys.stdin.read().strip().splitlines()[-1])
assert d["ok"], d
assert d["jobs"] == 2 and d["cache_hits"] >= 1, d
assert d["rejected_overbudget"] is True, d
assert all(n >= 40 for n in d["windows_compared"].values()), d
r = d["resilience"]
assert r["waiting_headroom"] and r["queue_full"], r
assert r["queue_ttl_expired"] and r["transient_retried"], r
assert r["bit_exact_jobs"] == 3, r
print("serveprobe: 2 jobs bit-identical to solo,", d["cache_hits"],
      "cache hit(s) (no recompile), over-budget job rejected with advice,",
      "daemon drained rc", d["shutdown_rc"])
print("serveprobe --resilience: waiting_headroom + queue_full +",
      "queue-TTL expiry + transient retry,", r["bit_exact_jobs"],
      "jobs bit-identical over", r["windows_compared"], "windows")
'
    # Kill-anywhere chaos for the serve plane: SIGKILL the daemon and
    # assert NO torn spool record (the write_json_atomic / atomic-move
    # contract), restart, and every surviving job must complete
    # bit-identical to the solo run. Beyond the two random-offset kills
    # (covering mid-accept), three aimed kills land at the resilience
    # states of ISSUE 19: a tenant parked in waiting_headroom, a batch
    # inside its retry-backoff window, and just after a queue-TTL expiry
    # (whose terminal deadline_expired record must survive the restart).
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m shadow1_tpu.tools.chaosprobe \
        configs/serve_phold.yaml --serve 5 --seed 3 \
        --serve-kinds random,random,waiting_headroom,retry_backoff,deadline \
        --json-only 2>/dev/null | python -c '
import json, sys
d = json.loads(sys.stdin.read().strip().splitlines()[-1])
assert d["ok"] and d["trials"] == 5, d
assert d["torn_records"] == [], d
assert all(v["ok"] for v in d["verdicts"]), d
kinds = [v["kind"] for v in d["verdicts"]]
assert kinds == ["random", "random", "waiting_headroom",
                 "retry_backoff", "deadline"], kinds
print("chaosprobe --serve:", d["trials"], "daemon-kill trials",
      "(2 random + waiting_headroom + retry_backoff + deadline),",
      "no torn records, jobs bit-identical to solo")
'
    echo "== bench regression gate (BENCH_GATE.json, ms/round per row) =="
    # ROADMAP item 5: the gate now carries THREE rows — dense smoke PHOLD,
    # the sparse rung-1 TCP config and the 4-lane fleet sweep — each gated
    # on >tolerance ms/round regression vs its committed per-backend
    # baseline (a TPU baseline coexists with the CPU one; rows without a
    # baseline for this backend report instead of auto-skipping the gate).
    # Intentional trade-offs override once with
    # SHADOW1_BENCH_GATE_ACCEPT="why" and then re-baseline via --update.
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m shadow1_tpu.tools.benchgate \
        | python -c '
import json, sys
d = json.loads(sys.stdin.read().strip().splitlines()[-1])
assert d["gate"] in ("ok", "no_baseline"), d
for name, r in d.get("rows", {}).items():
    assert r["gate"] in ("ok", "accepted", "no_baseline_for_backend",
                         "skipped_host_mismatch"), (name, r["gate"])
    print("benchgate:", name, r["gate"], "-", r.get("ms_per_round"),
          "ms/round vs", r.get("baseline_ms_per_round"), "baseline")
'
    echo "== op/fusion census drift gate (OPCENSUS.json) =="
    # Performance attribution plane: per-phase traced eqn counts must stay
    # within tolerance of the committed baseline (the static early warning
    # for ROADMAP item 1 kernel work) — and the gate must actually TRIP
    # on an injected extra-op build.
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m shadow1_tpu.tools.opcensus \
        2>/dev/null | python -c '
import json, sys
d = json.loads(sys.stdin.read().strip().splitlines()[-1])
assert d["gate"] in ("ok", "accepted"), d
eq = {k: v["eqns"]["rounds"] for k, v in d["census"].items()}
print("opcensus:", d["gate"], "- rounds-phase eqns", eq)
'
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m shadow1_tpu.tools.opcensus \
        smoke --inject 100 >/dev/null 2>&1 && rc=0 || rc=$?
    [ "$rc" -eq 1 ] || { echo "opcensus: injected drift did not trip the gate (rc=$rc)" >&2; exit 1; }
    echo "opcensus: injected 100-eqn drift tripped the gate (exit 1)"
    echo "== phase attribution smoke (phaseprobe, >=90% coverage) =="
    # The wall-clock half of the attribution plane: the phase split must
    # account for >=90% of the straight run's measured ms/round.
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m shadow1_tpu.tools.phaseprobe \
        smoke --hosts 512 --windows 8 --warmup 4 --reps 2 \
        --min-coverage 0.9 2>/dev/null | python -c '
import json, sys
d = json.loads(sys.stdin.read().strip().splitlines()[-1])
assert d["coverage"] >= 0.9, d
print("phaseprobe: coverage", d["coverage"], "- rounds",
      d["phases"]["rounds"]["pct"], "% of", d["ms_per_round"], "ms/round")
'
    echo "== flow-probe parity smoke (cpu vs tpu) + stall self-test =="
    # The flow probe plane (docs/SEMANTICS.md §"Flow probe contract"):
    # the watched-flow stream on the rung-1 TCP config must be
    # bit-identical between the batched engine's [W,K,F] ring and the
    # eager oracle's per-boundary mirror, and the watched flow must have
    # actually moved (an all-zero parity proves nothing).
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'EOF'
import dataclasses
import shadow1_tpu
from shadow1_tpu.config.experiment import load_experiment, resolve_watchlist
from shadow1_tpu.core.engine import Engine
from shadow1_tpu.cpu_engine import CpuEngine
from shadow1_tpu.telemetry.probes import drain_probes

exp, params, _ = load_experiment("configs/rung1_filexfer.yaml")
watch = resolve_watchlist(["client:0", "server"], exp.dns,
                          params.sockets_per_host)
params = dataclasses.replace(params, probes=watch, metrics_ring=64)
eng = Engine(exp, params)
st = eng.run(n_windows=40)
trows = sorted(drain_probes(st, eng.window, watch),
               key=lambda r: (r["window"], r["host"], r["sock"]))
ceng = CpuEngine(exp, params)
ceng.run(n_windows=40)
crows = sorted(ceng.probe_rows,
               key=lambda r: (r["window"], r["host"], r["sock"]))
assert trows == crows, "probe stream diverged cpu vs tpu"
assert any(r["inflight"] > 0 for r in trows if r["sock"] == 0), \
    "watched flow never moved"
print(f"flow probes: {len(trows)} rows bit-identical cpu<->tpu, 40 windows")
EOF
    # The stall detectors must flag a synthetic RTO storm and must NOT
    # flag its clean prefix (false-positive guard) — flowreport's own
    # self-test.
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m shadow1_tpu.tools.flowreport \
        --selftest | python -c '
import json, sys
d = json.loads(sys.stdin.read().strip().splitlines()[-1])
assert d["selftest"] == "ok", d
assert "rto_storm" in d["storm_flagged"], d
assert d["clean_prefix_flagged"] == [], d
print("flowreport selftest:", d["storm_flagged"], "flagged, clean prefix quiet")
'
    echo "== link-telemetry parity smoke (cpu vs tpu) + weathermap self-test =="
    # The link plane (docs/SEMANTICS.md §"Link telemetry contract"): the
    # rung-1 per-edge cumulative snapshots must be bit-identical between
    # the batched engine's [V,V,F] accumulator and the eager oracle's
    # per-edge mirror, and every drop column must reconcile EXACTLY with
    # its global counter (path-aware attribution loses nothing). The
    # opcensus gate above doubles as the links-off zero-op proof: its
    # committed baseline predates the plane and the default build
    # (--link-telem off) must still match it.
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'EOF'
import dataclasses
import shadow1_tpu
from shadow1_tpu.config.experiment import load_experiment
from shadow1_tpu.core.engine import Engine
from shadow1_tpu.cpu_engine import CpuEngine
from shadow1_tpu.telemetry.links import drain_links

exp, params, _ = load_experiment("configs/rung1_filexfer.yaml")
params = dataclasses.replace(params, link_telem=1)
key = lambda r: (r["src_vertex"], r["dst_vertex"], r["window"])
eng = Engine(exp, params)
st = eng.run(n_windows=40)
trows = sorted(drain_links(st, eng.window), key=key)
tm = Engine.metrics_dict(st)
ceng = CpuEngine(exp, params)
ceng.run(n_windows=40)
assert trows == sorted(ceng.link_rows, key=key), \
    "link records diverged cpu vs tpu"
assert trows and any(r["pkts"] > 0 for r in trows), "no traffic observed"
for rows, m in ((trows, tm), (ceng.link_rows, ceng.metrics)):
    assert sum(r["pkts"] for r in rows) == m["pkts_sent"]
    assert sum(r["loss_drops"] for r in rows) == m["pkts_lost"]
    assert sum(r["link_down_drops"] for r in rows) == m["link_down_pkts"]
    assert sum(r["nic_backlog_drops"] for r in rows) == m["nic_tx_drops"]
print(f"link records: {len(trows)} edge rows bit-identical cpu<->tpu, "
      f"40 windows; pkts=={tm['pkts_sent']} and all drop columns "
      f"reconcile with the global counters")
EOF
    # The weathermap detectors must flag all four synthetic pathologies
    # (loss concentration, egress saturation, dark link, elephant skew)
    # and must NOT flag the clean topology — netreport's own self-test.
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m shadow1_tpu.tools.netreport \
        --selftest | python -c '
import json, sys
d = json.loads(sys.stdin.read().strip().splitlines()[-1])
assert d["selftest"] == "ok", d
print("netreport selftest: all four pathology detectors fired, clean topology quiet")
'
    echo "== corrupt-checkpoint recovery smoke (integrity digest) =="
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -c '
import tempfile, os
import shadow1_tpu
from shadow1_tpu.ckpt import (CorruptCheckpointError, load_state,
                              save_state, verify_file)
from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import MS, EngineParams
from shadow1_tpu.core.engine import Engine
eng = Engine(single_vertex_experiment(
    n_hosts=8, seed=4, end_time=20 * MS, latency_ns=1 * MS, model="phold",
    model_cfg={"mean_delay_ns": float(2 * MS)}), EngineParams())
st = eng.run(n_windows=5)
path = os.path.join(tempfile.mkdtemp(), "snap.npz")
save_state(st, path)
assert verify_file(path)[0]
load_state(eng.init_state(), path)
import numpy as np
with np.load(path) as d:
    arrs = {k: d[k].copy() for k in d.files}
leaf = next(k for k in arrs if k.startswith("leaf_")
            and arrs[k].size and arrs[k].dtype != np.bool_)
arrs[leaf].reshape(-1).view(np.uint8)[0] ^= 0x20
np.savez(path, **arrs)  # payload changed, stored integrity now stale
ok, why = verify_file(path)
assert not ok, "bit flip must not verify"
try:
    load_state(eng.init_state(), path)
except (CorruptCheckpointError, ValueError):
    pass
else:
    raise AssertionError("corrupt snapshot loaded silently")
print("corrupt checkpoint rejected:", why)
'
    ;;
  fast)  exec python -m pytest tests/ -q -m "not slow" ;;
  all)   exec python -m pytest tests/ -q ;;
  *) echo "usage: $0 [smoke|fast|all]" >&2; exit 2 ;;
esac
