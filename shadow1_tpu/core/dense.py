"""Dense per-host update/select primitives — the no-scatter toolkit.

XLA lowers a scatter with dynamic per-row indices (``arr.at[h, col].set``)
to a serialized loop on TPU: measured 4.3 ms for a [4096, 32] single-slot
write and 371 ms for a 131k-element batch scatter — the entire per-window
cost of round 2's engine. Every hot-path "write one slot per host" in this
package therefore goes through these helpers, which express the update as a
one-hot mask + ``where`` (dense, fuses into one cheap elementwise kernel)
instead of a scatter. Reads keep ``take_along_axis`` (gathers are fast).

Layout contract (round-4 rewrite): the HOST axis is the LAST (minor/lane)
axis of every per-host state tensor, the slot/capacity axis is second-to-
last, and any field axes lead: ``[C, H]``, ``[S, H]``, ``[NP, C, H]``.
Rationale, measured on the target chip: TPU tiles the two minor axes
(8 sublanes × 128 lanes), so (a) per-host reductions over a slot axis must
run over the SUBLANE axis to vectorize across hosts (7× faster than the
lane-axis reduction the old host-major layout forced), and (b) a minor
axis of width NP=10 padded to 128 lanes inflated every payload tensor
12.8× in HBM. Host-minor keeps the wide, contiguous axis on the lanes.

These helpers also avoid ``argmin``/``cumsum`` along the slot axis — both
measured ~0.3–2.5 ms per call at [1000, 256] on the chip IN EITHER layout
(they lower to slow cross-lane/sublane sequences). ``first_true`` uses a
min-over-iota reduction instead.

The semantics are exactly those of ``arr.at[..., col, h].set(val)`` with an
out-of-range drop: hosts where ``mask`` is False (or ``col`` out of range)
are untouched.
"""

from __future__ import annotations

import jax.numpy as jnp


def onehot_col(col, cap: int, mask=None) -> jnp.ndarray:
    """bool [C, H]: True at (col[h], h) where mask[h] (and col in range)."""
    sel = jnp.arange(cap, dtype=col.dtype)[:, None] == col[None, :]
    if mask is not None:
        sel = sel & mask[None, :]
    return sel


def _expand(sel, ndim):
    return sel.reshape((1,) * (ndim - sel.ndim) + sel.shape)


def set_col(arr, col, val, mask=None):
    """Dense ``arr[..., col[h], h] = val[..., h] where mask[h]`` for
    [*L, C, H] arrays. ``val`` may be scalar or [H] (or [*L, H])."""
    sel = _expand(onehot_col(col, arr.shape[-2], mask), arr.ndim)
    val = jnp.asarray(val, arr.dtype)
    if val.ndim == 0:
        return jnp.where(sel, val, arr)
    # val [..., H] -> broadcast over the slot axis.
    return jnp.where(sel, jnp.expand_dims(val, -2), arr)


def add_col(arr, col, val, mask=None):
    """Dense ``arr[..., col[h], h] += val[..., h] where mask[h]``."""
    sel = _expand(onehot_col(col, arr.shape[-2], mask), arr.ndim)
    val = jnp.asarray(val, arr.dtype)
    if val.ndim >= 1:
        val = jnp.expand_dims(val, -2)
    return arr + jnp.where(sel, val, jnp.zeros((), arr.dtype))


def get_col(arr, col):
    """Gather ``arr[..., col[h], h]`` → [*L, H] (col clipped into range)."""
    c = jnp.clip(col, 0, arr.shape[-2] - 1)
    idx = c.reshape((1,) * (arr.ndim - 2) + (1,) + c.shape)
    idx = jnp.broadcast_to(idx, arr.shape[:-2] + (1,) + c.shape)
    return jnp.take_along_axis(arr, idx, axis=-2).squeeze(-2)


def extract_col(sel, arr):
    """Value at the one-hot True of ``sel`` per host: [*L, C, H] → [*L, H].

    ``sel`` [C, H] must be at most one-hot per host (the pop-min and
    message-boundary invariants — see core/events.py); hosts with no True
    read 0. Masked-sum reduction over the sublane axis, in the array's own
    dtype (jnp.sum would promote i32 → i64 and silently break the u32
    wrapping-arithmetic contract)."""
    s = _expand(sel, arr.ndim)
    return jnp.where(s, arr, 0).sum(axis=-2, dtype=arr.dtype)


def first_true(m) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-host first True of a bool [C, H]: (any[H], onehot [C, H]).

    min-over-iota reduction, not cumsum (see module docstring)."""
    cap = m.shape[0]
    iota = jnp.arange(cap, dtype=jnp.int32)[:, None]
    any_, first = first_true_idx(m)
    # first is 0 where ~any_, so gate the one-hot on the mask.
    return any_, (iota == first[None, :]) & any_[None, :]


def last_true(m) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-host HIGHEST True of a bool [C, H]: (any[H], index[H]).

    Index is 0 where no True (callers gate on the any-mask)."""
    cap = m.shape[0]
    iota = jnp.arange(cap, dtype=jnp.int32)[:, None]
    last = jnp.max(jnp.where(m, iota, -1), axis=0)
    return m.any(axis=0), jnp.maximum(last, 0).astype(jnp.int32)


def first_true_idx(m) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-host first-True INDEX of a bool [C, H]: (any[H], index[H]).

    Index is 0 where no True (callers gate on the any-mask). The reduction
    replacement for ``argmax(m, axis=slot)`` on bool masks."""
    cap = m.shape[0]
    iota = jnp.arange(cap, dtype=jnp.int32)[:, None]
    first = jnp.min(jnp.where(m, iota, cap), axis=0)
    return m.any(axis=0), jnp.where(first < cap, first, 0).astype(jnp.int32)


def payload(n_hosts: int, *rows) -> jnp.ndarray:
    """Build an [NP, H] i32 payload from per-plane [H] rows (None = zeros).

    ``p.at[i].set(row)`` chains trace to NP scatter primitives per packet
    construction — ~850 scatter eqns in the rung-3 program before XLA
    simplification. Stacking builds the same tensor as one concatenate."""
    from shadow1_tpu.consts import NP

    if len(rows) > NP:
        raise ValueError(f"payload(): {len(rows)} rows > NP={NP} planes")
    zeros = None
    out = []
    for i in range(NP):
        r = rows[i] if i < len(rows) else None
        if r is None:
            if zeros is None:
                zeros = jnp.zeros(n_hosts, jnp.int32)
            r = zeros
        else:
            r = jnp.broadcast_to(jnp.asarray(r, jnp.int32), (n_hosts,))
        out.append(r)
    return jnp.stack(out)
