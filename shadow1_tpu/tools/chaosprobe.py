"""Kill-anywhere chaos harness — randomized preemption/crash trials.

    python -m shadow1_tpu.tools.chaosprobe config.yaml [--windows N]
        [--chunk C] [--trials T] [--seed S] [--fleet] [--no-oracle]
        [--keep K] [--json-only]

The preemption contract (docs/SEMANTICS.md) claims a supervised run is
survivable at ANY real-time instant: SIGKILL or SIGTERM, mid-chunk,
mid-checkpoint-write, with a corrupted snapshot head — the completed run's
final state and per-window digest stream must be BIT-IDENTICAL to a run
nothing ever touched. This probe proves it empirically:

1. one STRAIGHT run records the reference digest stream (the flight
   recorder's per-window ring rows) and final state (.npz, every leaf);
2. ``--trials`` supervised runs are attacked and relaunched to completion:

   * ``sigkill_group`` / ``sigterm_group`` / ``sigkill_child`` /
     ``sigterm_child`` — the signal lands at a random real-time offset,
     on the whole process tree or just the engine child (the supervisor
     then drains/respawns on its own);
   * ``drain`` — a deterministic mid-run SIGTERM (the preemption-notice
     shape): the run must exit EXIT_PREEMPTED after committing, and the
     relaunch must resume, not restart;
   * ``torn_head`` — the lineage injection hook tears the newest snapshot
     mid-write (half-truncated head) and kills the process: resume must
     fall back one generation, not restart;
   * ``corrupt_head`` — the tree is SIGKILLed once checkpoints exist,
     then the newest generation is bit-corrupted on disk before the
     relaunch: lineage fallback again;
   * ``mid_write`` — the hook dies exactly between the head rotation and
     the new-head install (no head on disk at all);

3. every trial's final state is compared leaf-by-leaf against the straight
   run's, and every per-window digest row ever emitted (across kills,
   respawns and resumes, deduplicated by window) must bit-match the
   straight stream. ``--fleet`` runs the whole matrix fleet-shaped
   (per-experiment streams); the cpu oracle cross-check (``--no-oracle``
   to skip, solo only) additionally pins the straight stream to the
   eager reference.

**Fleet recovery matrix** (``--extra``): arbitrary CLI flags ride every
launch, so the same kill matrix runs against the recovery plane — e.g.
``--fleet --extra "--on-overflow retry"`` on an under-capped sweep
(forced overflow: every relaunch must replay its transactional chunks to
the same committed stream) or ``--fleet --extra "--on-overflow halt
--on-lane-fail quarantine"`` on a sweep with one doomed lane (forced
lane-halt: the sweep completes E-1/E; ``--expect-quarantine N`` asserts
the straight run AND every trial quarantined exactly N lanes — the
fleet_quarantine records are counted per trial, deduplicated by lane).
Retry and quarantine are deterministic, so the straight reference stream
already embodies them; a killed+relaunched trial must land bit-identical.

Exit codes follow tools/paritytrace.py: 0 = all trials bit-identical,
3 = divergence (the last stdout line is a JSON verdict either way; on a
mismatch it prints the paritytrace invocation that localizes it).
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import subprocess
import sys
import tempfile
import time

EXIT_DIVERGED = 3
MAX_LAUNCHES = 8

RANDOM_KINDS = ("sigkill_group", "sigterm_group",
                "sigkill_child", "sigterm_child")
SPECIAL_KINDS = ("drain", "torn_head", "corrupt_head", "mid_write")
SERVE_KINDS = ("random", "waiting_headroom", "retry_backoff", "deadline")


def _child_pids(ppid: int) -> list[int]:
    """Direct children of ``ppid`` via /proc (no ps dependency)."""
    kids = []
    for name in os.listdir("/proc"):
        if not name.isdigit():
            continue
        try:
            with open(f"/proc/{name}/stat") as f:
                fields = f.read().rsplit(")", 1)[1].split()
            if int(fields[1]) == ppid:
                kids.append(int(name))
        except (OSError, IndexError, ValueError):
            continue
    return kids


def _collect_stream(stderr_paths, fleet: bool):
    """(exp, window) → digest tuple from every launch's ring records.

    A window re-run after a resume re-emits its row; the rows must agree
    (determinism) — a conflict is itself a divergence."""
    from shadow1_tpu.core.digest import DIGEST_FIELDS

    stream: dict = {}
    conflict = None
    resumes: list[dict] = []
    lineage_events: list[dict] = []
    quarantined: dict = {}  # lane gid -> fleet_quarantine record (deduped:
    #                         a relaunch replays the chunk and re-emits it)
    for path in stderr_paths:
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            t = rec.get("type")
            if t == "resume":
                resumes.append(rec)
            elif t == "lineage":
                lineage_events.append(rec)
            elif t == "fleet_quarantine":
                quarantined[rec.get("exp")] = rec
            elif t == "ring" and DIGEST_FIELDS[0] in rec:
                key = (rec.get("exp") if fleet else None, rec["window"])
                val = tuple(rec[f] for f in DIGEST_FIELDS)
                if key in stream and stream[key] != val and conflict is None:
                    conflict = {"window": key[1], "exp": key[0],
                                "reason": "re-emitted row differs"}
                stream[key] = val
    return stream, conflict, resumes, lineage_events, quarantined


def _npz_equal(a_path: str, b_path: str):
    import numpy as np

    with np.load(a_path) as a, np.load(b_path) as b:
        if set(a.files) != set(b.files):
            return f"member sets differ ({len(a.files)} vs {len(b.files)})"
        for k in a.files:
            if not np.array_equal(a[k], b[k]):
                return f"leaf {k} differs"
    return None


def _corrupt_file(path: str) -> None:
    """Truncate to half — the torn-write shape every filesystem can
    produce; guaranteed to fail the zip/integrity checks."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(size // 2, 1))


def _serve_trials(args) -> int:
    """``--serve N``: the kill-anywhere matrix for the serve daemon.

    Each trial: fresh spool → daemon up → submit a job → SIGKILL the
    daemon at a random real-time offset (covering kill-during-accept,
    kill-mid-validate, kill-mid-batch, kill-after-done) → assert the
    spool holds NO torn record (every ``*.json`` parses — the
    write_json_atomic / atomic-move contract) → restart the daemon on
    the same spool → the job must complete with a digest stream
    bit-identical to the solo CLI run (a from-scratch rerun is
    bit-identical by determinism; a finished job survives untouched).
    The final daemon is SIGTERM-drained (EXIT_SERVE_SHUTDOWN checked).

    ``--serve-kinds`` picks the lifecycle instant per trial (cycled):

    * ``random`` — the classic random-offset kill above;
    * ``waiting_headroom`` — a tight memory budget parks a second tenant
      in waiting_headroom behind a long resident batch; the daemon is
      SIGKILLed in exactly that state — both tenants must complete
      bit-identically after the restart;
    * ``retry_backoff`` — an injected transient crash (countdown file)
      puts the batch into its backoff window; the kill lands mid-backoff
      and the restarted daemon (one crash left on the counter) must
      crash once more, retry, and finish bit-exact;
    * ``deadline`` — a --queue-ttl-s tenant expires mid-run, THEN the
      kill lands: the terminal deadline_expired record must survive the
      restart and the surviving tenant must complete bit-exact."""
    import shadow1_tpu  # noqa: F401
    from shadow1_tpu.consts import EXIT_SERVE_SHUTDOWN
    from shadow1_tpu.serve import client
    from shadow1_tpu.serve.protocol import Spool
    from shadow1_tpu.tools.serveprobe import (
        _served_stream,
        _solo_stream,
        _wait_state,
    )

    rng = random.Random(args.seed)
    work = tempfile.mkdtemp(prefix="chaosserve_")
    say = (lambda *a: None) if args.json_only else (
        lambda *a: print(*a, file=sys.stderr, flush=True))
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    kinds = [k.strip() for k in
             (args.serve_kinds or "random").split(",") if k.strip()]
    unknown = [k for k in kinds if k not in SERVE_KINDS]
    if unknown:
        print(json.dumps({"ok": False, "error":
                          f"unknown --serve-kinds {unknown}; "
                          f"pick from {list(SERVE_KINDS)}"}))
        return 1

    ref = _solo_stream(args.config, args.windows, args.timeout_s, env)
    if not ref:
        print(json.dumps({"ok": False, "error": "solo reference emitted "
                          "no ring digest rows — the config needs "
                          "engine metrics_ring + state_digest"}))
        return 1
    say(f"[chaosprobe --serve] solo reference: {len(ref)} digest rows")
    est = 0
    if "waiting_headroom" in kinds:
        from shadow1_tpu import mem
        from shadow1_tpu.config.experiment import load_experiment

        exp, params, _ = load_experiment(args.config)
        est = mem.estimate(exp, params, n_exp=1).peak_bytes
        if est <= 0:
            print(json.dumps({"ok": False, "error": "memory estimator "
                              "returned no estimate for the config — "
                              "waiting_headroom trials need one"}))
            return 1

    def spawn(spool, err_path, trial_env, extra=()):
        ef = open(err_path, "a")
        p = subprocess.Popen(
            [sys.executable, "-m", "shadow1_tpu", "serve",
             "--spool", spool, "--poll-s", "0.05", *extra],
            env=trial_env, stdout=subprocess.DEVNULL, stderr=ef)
        deadline = time.monotonic() + 60
        while Spool(spool).daemon_alive() is None:
            if p.poll() is not None or time.monotonic() > deadline:
                raise RuntimeError(f"daemon did not start (rc={p.poll()})")
            time.sleep(0.05)
        return p, ef

    verdicts = []
    torn = []
    for ti in range(args.serve):
        kind = kinds[ti % len(kinds)]
        spool = os.path.join(work, f"t{ti}")
        errp = os.path.join(work, f"t{ti}.stderr")
        # Any trial-infrastructure failure (daemon won't start, SIGTERM
        # wait expires, spool IO) must still end in the JSON verdict
        # contract (ci.sh parses the last stdout line), never a raw
        # traceback with empty stdout.
        p = ef = None
        rc = None
        setup_err = None
        # (job_id, expected_state, bit_compare) per tenant of the trial
        jobs: list[tuple[str, str, bool]] = []
        finals: dict[str, dict] = {}
        env1 = env2 = dict(env)
        extra1 = extra2 = ()
        try:
            if kind == "waiting_headroom":
                env1 = env2 = {**env,
                               "SHADOW1_MEM_BYTES": str(int(est * 1.5))}
            elif kind == "retry_backoff":
                crash = os.path.join(work, f"t{ti}.crash")
                with open(crash, "w") as f:
                    f.write("2")  # one crash per daemon incarnation
                env1 = {**env, "SHADOW1_SERVE_CRASH_BATCH": crash,
                        "SHADOW1_SERVE_RETRY_BACKOFF_S": "3.0"}
                env2 = {**env, "SHADOW1_SERVE_CRASH_BATCH": crash,
                        "SHADOW1_SERVE_RETRY_BACKOFF_S": "0"}
                extra1 = extra2 = ("--ckpt-every-s", "0.05")
            p, ef = spawn(spool, errp, env1, extra1)
            if kind == "waiting_headroom":
                j_a = client.submit(spool, args.config, windows=300)
                jobs.append((j_a, "done", True))
                if _wait_state(spool, j_a, ("running",), 120) is None:
                    raise RuntimeError("resident batch never ran")
                j_b = client.submit(spool, args.config)
                jobs.append((j_b, "done", True))
                if _wait_state(spool, j_b, ("waiting_headroom",),
                               120) is None:
                    raise RuntimeError("tenant never reached "
                                       "waiting_headroom")
            elif kind == "retry_backoff":
                jid = client.submit(spool, args.config)
                jobs.append((jid, "done", True))
                deadline = time.monotonic() + 120
                while True:  # kill lands INSIDE the 3s backoff window
                    st = Spool(spool).read_status(jid) or {}
                    if st.get("retrying"):
                        break
                    if time.monotonic() > deadline:
                        raise RuntimeError("batch never entered retry "
                                           "backoff")
                    time.sleep(0.05)
            elif kind == "deadline":
                j1 = client.submit(spool, args.config, windows=300)
                jobs.append((j1, "done", True))
                if _wait_state(spool, j1, ("running",), 120) is None:
                    raise RuntimeError("resident batch never ran")
                j2 = client.submit(spool, args.config, priority=-1,
                                   queue_ttl_s=0.05)
                jobs.append((j2, "failed", False))
                st = _wait_state(spool, j2, ("failed",), 120)
                if st is None or st.get("reason") != "deadline_expired":
                    raise RuntimeError(f"TTL tenant did not expire: {st}")
            else:  # random: the kill offset sweeps the whole lifecycle
                jid = client.submit(spool, args.config)
                jobs.append((jid, "done", True))
                time.sleep(rng.uniform(0.0, args.serve_kill_s))
            p.kill()
            p.wait()
            ef.close()
            # Torn-record sweep: every committed .json must parse whole.
            for root, _, names in os.walk(spool):
                for name in names:
                    if not name.endswith(".json"):
                        continue
                    fp = os.path.join(root, name)
                    try:
                        with open(fp) as f:
                            json.load(f)
                    except ValueError:
                        torn.append(os.path.relpath(fp, spool))
            p, ef = spawn(spool, errp, env2, extra2)
            try:
                for jid, expect, _cmp in jobs:
                    if expect == "done":
                        try:
                            finals[jid] = client.await_job(
                                Spool(spool), jid,
                                timeout_s=args.timeout_s, poll_s=0.05)
                        except TimeoutError as e:
                            finals[jid] = {"state": f"timeout: {e}"}
                    else:
                        finals[jid] = Spool(spool).read_status(jid) or {}
            finally:
                p.send_signal(signal.SIGTERM)
                rc = p.wait(timeout=60)
        except (RuntimeError, OSError,
                subprocess.TimeoutExpired) as e:
            setup_err = str(e)
        finally:
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()
            if ef is not None:
                ef.close()
        states_ok = bool(jobs) and setup_err is None and all(
            (finals.get(jid) or {}).get("state") == expect
            for jid, expect, _cmp in jobs)
        compared = 0
        bad = []
        for jid, _expect, cmp_ in jobs:
            if not cmp_:
                continue
            served = _served_stream(spool, jid)
            common = sorted(set(served) & set(ref))
            compared += len(common)
            bad += [w for w in common if served[w] != ref[w]]
            if not common:
                states_ok = False
        ok = states_ok and not bad and rc == EXIT_SERVE_SHUTDOWN
        verdicts.append({
            "trial": ti, "kind": kind,
            "states": {j: (finals.get(j) or {}).get("state")
                       for j, _e, _c in jobs},
            "windows_compared": compared,
            "first_divergence": bad[:1],
            "error": setup_err,
            "shutdown_rc": rc, "ok": bool(ok)})
        say(f"[chaosprobe --serve] trial {ti} ({kind}): "
            + (f"ERROR {setup_err}" if setup_err else
               f"{compared} windows vs solo"
               + (" — DIVERGED" if bad else ", bit-identical")))
    ok = not torn and all(v["ok"] for v in verdicts)
    print(json.dumps({"ok": ok, "trials": args.serve,
                      "kinds": kinds,
                      "torn_records": torn, "verdicts": verdicts}))
    if torn or not ok:
        return EXIT_DIVERGED if any(v["first_divergence"]
                                    for v in verdicts) else 1
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="shadow1_tpu.tools.chaosprobe")
    ap.add_argument("config", help="YAML experiment file")
    ap.add_argument("--windows", type=int, default=40)
    ap.add_argument("--chunk", type=int, default=10,
                    help="heartbeat/checkpoint chunk (windows)")
    ap.add_argument("--trials", type=int, default=20)
    ap.add_argument("--seed", type=int, default=0,
                    help="RNG seed for kill kinds/offsets")
    ap.add_argument("--keep", type=int, default=3,
                    help="--ckpt-keep lineage depth for the trials")
    ap.add_argument("--fleet", action="store_true",
                    help="run the matrix fleet-shaped (config needs a "
                         "sweep: section)")
    ap.add_argument("--extra", default=None, metavar="FLAGS",
                    help="extra CLI flags for every launch (one shell-"
                         "quoted string), e.g. \"--on-overflow retry\" or "
                         "\"--on-overflow halt --on-lane-fail quarantine\" "
                         "— the fleet recovery trial matrix")
    ap.add_argument("--expect-quarantine", type=int, default=None,
                    metavar="N",
                    help="assert the straight run and every trial "
                         "quarantined exactly N lanes (forced-lane-halt "
                         "matrix)")
    ap.add_argument("--no-oracle", action="store_true",
                    help="skip the cpu-oracle digest cross-check of the "
                         "straight run (solo only; fleet skips it anyway "
                         "— tools/fleetprobe.py covers fleet↔oracle)")
    ap.add_argument("--serve", type=int, default=0, metavar="N",
                    help="run N serve-daemon kill trials instead of the "
                         "supervised matrix: SIGKILL the daemon at a "
                         "random offset after a submission (including "
                         "mid-accept), assert no torn spool record, "
                         "restart, and bit-compare the completed job "
                         "against the solo run (the config needs "
                         "engine metrics_ring + state_digest)")
    ap.add_argument("--serve-kill-s", type=float, default=2.0,
                    help="--serve: max random kill offset after submit")
    ap.add_argument("--serve-kinds", default="random", metavar="K,K,...",
                    help="--serve: comma list cycled across trials — "
                         f"{', '.join(SERVE_KINDS)}. The non-random "
                         "kinds aim the SIGKILL at a specific lifecycle "
                         "instant (tenant parked in waiting_headroom, "
                         "batch inside its retry-backoff window, just "
                         "after a queue-TTL expiry)")
    ap.add_argument("--timeout-s", type=float, default=600.0,
                    help="per-launch wall timeout")
    ap.add_argument("--json-only", action="store_true")
    args = ap.parse_args(argv)

    if args.serve:
        return _serve_trials(args)

    rng = random.Random(args.seed)
    work = tempfile.mkdtemp(prefix="chaosprobe_")
    say = (lambda *a: None) if args.json_only else (
        lambda *a: print(*a, file=sys.stderr, flush=True))

    import shadow1_tpu  # noqa: F401
    from shadow1_tpu.config.experiment import load_experiment
    from shadow1_tpu.consts import EXIT_PREEMPTED

    exp, _, _ = load_experiment(args.config)
    window_ns = exp.window
    env0 = {**os.environ, "SHADOW1_SUPERVISE_BACKOFF_S": "0"}

    base = [sys.executable, "-m", "shadow1_tpu", args.config,
            "--windows", str(args.windows), "--heartbeat", str(args.chunk),
            "--state-digest", "on"]
    if args.fleet:
        base.append("--fleet")
    if args.extra:
        import shlex

        base.extend(shlex.split(args.extra))

    # ---- straight reference run -----------------------------------------
    ref_npz = os.path.join(work, "ref.npz")
    ref_err = os.path.join(work, "ref.stderr")
    t0 = time.perf_counter()
    with open(ref_err, "w") as ef:
        r = subprocess.run([*base, "--save-state", ref_npz], env=env0,
                           stdout=subprocess.DEVNULL, stderr=ef,
                           timeout=args.timeout_s)
    straight_wall = time.perf_counter() - t0
    if r.returncode != 0:
        print(json.dumps({"ok": False, "error": "straight run failed",
                          "rc": r.returncode, "stderr": ref_err}))
        return 1
    ref_stream, conflict, _, _, ref_quar = _collect_stream([ref_err],
                                                           args.fleet)
    assert conflict is None
    if not ref_stream:
        print(json.dumps({"ok": False,
                          "error": "straight run emitted no digest rows"}))
        return 1
    say(f"[chaosprobe] straight run: {len(ref_stream)} digest rows, "
        f"{straight_wall:.1f}s wall"
        + (f", {len(ref_quar)} lane(s) quarantined" if ref_quar else ""))
    if args.expect_quarantine is not None \
            and len(ref_quar) != args.expect_quarantine:
        print(json.dumps({
            "ok": False,
            "error": f"straight run quarantined {len(ref_quar)} lane(s), "
                     f"expected {args.expect_quarantine}",
            "quarantined": sorted(ref_quar)}))
        return 1

    # ---- cpu-oracle cross-check of the straight stream ------------------
    oracle_checked = False
    if not args.no_oracle and not args.fleet:
        orc_err = os.path.join(work, "oracle.stderr")
        with open(orc_err, "w") as ef:
            r = subprocess.run(
                [sys.executable, "-m", "shadow1_tpu", args.config,
                 "--engine", "cpu", "--windows", str(args.windows),
                 "--state-digest", "on"],
                env=env0, stdout=subprocess.DEVNULL, stderr=ef,
                timeout=args.timeout_s)
        if r.returncode != 0:
            print(json.dumps({"ok": False, "error": "oracle run failed",
                              "rc": r.returncode}))
            return 1
        from shadow1_tpu.core.digest import DIGEST_FIELDS

        orc = {}
        with open(orc_err) as f:
            for line in f:
                line = line.strip()
                if line.startswith("{"):
                    rec = json.loads(line)
                    if rec.get("type") == "digest":
                        orc[(None, rec["window"])] = tuple(
                            rec[f] for f in DIGEST_FIELDS)
        if orc != ref_stream:
            bad = sorted(w for w in ref_stream
                         if orc.get(w) != ref_stream[w])[:1]
            print(json.dumps({
                "ok": False, "error": "straight run diverges from the cpu "
                "oracle (not a chaos failure — bisect it first)",
                "first_window": bad,
                "paritytrace": f"python -m shadow1_tpu.tools.paritytrace "
                               f"{args.config} tpu cpu --windows "
                               f"{args.windows}"}))
            return EXIT_DIVERGED
        oracle_checked = True
        say(f"[chaosprobe] oracle cross-check ok ({len(orc)} windows)")

    # ---- trial schedule --------------------------------------------------
    kinds = list(SPECIAL_KINDS[:max(0, min(args.trials, len(SPECIAL_KINDS)))])
    while len(kinds) < args.trials:
        kinds.append(rng.choice(RANDOM_KINDS))
    rng.shuffle(kinds)

    verdicts = []
    total_launches = 0
    total_preempted = 0
    total_fallbacks = 0

    for ti, kind in enumerate(kinds):
        ck = os.path.join(work, f"t{ti}.npz")
        fin = os.path.join(work, f"t{ti}_fin.npz")
        errs = []
        launches = 0
        preempted = 0
        killed = 0
        trial_err = None
        mid_kill_boundary = (args.windows // 2) * window_ns
        while launches < MAX_LAUNCHES:
            env = dict(env0)
            if kind == "drain" and launches == 0:
                # Deterministic preemption notice at the mid boundary.
                env["SHADOW1_OBS_SIGTERM_SELF_AT_NS"] = str(mid_kill_boundary)
            if kind == "torn_head" and launches == 1:
                env["SHADOW1_LINEAGE_TORN_HEAD"] = os.path.join(
                    work, f"t{ti}.torn.flag")
            if kind == "mid_write" and launches == 1:
                env["SHADOW1_LINEAGE_CRASH_BETWEEN"] = os.path.join(
                    work, f"t{ti}.between.flag")
            err_path = os.path.join(work, f"t{ti}_l{launches}.stderr")
            errs.append(err_path)
            with open(err_path, "w") as ef:
                proc = subprocess.Popen(
                    [*base, "--ckpt", ck, "--ckpt-every-s", "0",
                     "--ckpt-keep", str(args.keep), "--save-state", fin],
                    env=env, stdout=subprocess.DEVNULL, stderr=ef,
                    start_new_session=True)
                launches += 1
                want_kill = (launches == 1 and
                             (kind in RANDOM_KINDS
                              or kind in ("corrupt_head", "torn_head",
                                          "mid_write")))
                if want_kill and kind in ("corrupt_head", "torn_head",
                                          "mid_write"):
                    # Kill only once checkpoints exist: poll the progress
                    # sidecar, then SIGKILL the whole tree. The relaunch
                    # then carries the injection env (torn_head/mid_write)
                    # or finds the head corrupted (corrupt_head).
                    deadline = time.time() + args.timeout_s
                    while time.time() < deadline and proc.poll() is None:
                        if os.path.exists(ck + ".progress"):
                            break
                        time.sleep(0.05)
                    if proc.poll() is None:
                        killed += 1
                        os.killpg(proc.pid, signal.SIGKILL)
                elif want_kill:
                    sig = (signal.SIGKILL if kind.startswith("sigkill")
                           else signal.SIGTERM)
                    offset = rng.uniform(0.05, max(straight_wall, 0.5))
                    target_end = time.time() + offset
                    while time.time() < target_end and proc.poll() is None:
                        time.sleep(0.02)
                    if proc.poll() is None:
                        killed += 1
                        if kind.endswith("_child"):
                            kids = _child_pids(proc.pid)
                            if kids:
                                for pid in kids:
                                    try:
                                        os.kill(pid, sig)
                                    except ProcessLookupError:
                                        pass
                            else:  # child not up yet: hit the tree
                                os.killpg(proc.pid, sig)
                        else:
                            os.killpg(proc.pid, sig)
                try:
                    rc = proc.wait(timeout=args.timeout_s)
                except subprocess.TimeoutExpired:
                    os.killpg(proc.pid, signal.SIGKILL)
                    trial_err = "launch timeout"
                    break
            if rc == 0:
                break
            if rc == EXIT_PREEMPTED:
                preempted += 1
                continue
            if rc in (1, 2):
                trial_err = f"launch exited rc={rc} (not a kill)"
                break
            # killed / crashed tree: corrupt the head once for the
            # corrupt_head kind, then relaunch-to-resume.
            if kind == "corrupt_head" and launches == 1 \
                    and os.path.exists(ck):
                _corrupt_file(ck)
        else:
            trial_err = trial_err or f"no clean exit in {MAX_LAUNCHES} launches"
        total_launches += launches
        total_preempted += preempted
        if trial_err is None and not os.path.exists(fin):
            trial_err = "final state was never written"
        stream, conflict, resumes, _events, quar = _collect_stream(
            errs, args.fleet)
        fallbacks = sum(1 for r in resumes if r.get("fallback_skipped"))
        # mid_write leaves no corrupt file to skip — the fallback shows as
        # a resume from a generation older than the torn head's seq.
        if kind in ("torn_head", "mid_write", "corrupt_head"):
            fb_text = any("fall back" in line or "discarding corrupt" in line
                          for p in errs if os.path.exists(p)
                          for line in open(p))
            if fallbacks == 0 and fb_text:
                fallbacks = 1
        total_fallbacks += fallbacks
        mismatch = None
        if trial_err is None:
            mismatch = conflict
            if mismatch is None:
                missing = [k for k in ref_stream if k not in stream]
                if missing:
                    mismatch = {"reason": f"{len(missing)} straight "
                                          f"window(s) never re-emitted",
                                "first": list(missing[0])}
            if mismatch is None:
                for key in sorted(ref_stream):
                    if stream[key] != ref_stream[key]:
                        mismatch = {"exp": key[0], "window": key[1],
                                    "reason": "digest row differs"}
                        break
            if mismatch is None and sorted(quar) != sorted(ref_quar):
                # Quarantine is deterministic: a killed+relaunched trial
                # must slice out exactly the lanes the straight run did.
                mismatch = {"reason": f"quarantined lanes differ: trial "
                                      f"{sorted(quar)} vs straight "
                                      f"{sorted(ref_quar)}"}
            if mismatch is None:
                why = _npz_equal(ref_npz, fin)
                if why:
                    mismatch = {"reason": f"final state differs: {why}"}
        v = {"trial": ti, "kind": kind, "launches": launches,
             "killed": killed, "preempted_exits": preempted,
             "lineage_fallbacks": fallbacks,
             "quarantined": sorted(quar),
             "ok": trial_err is None and mismatch is None}
        if trial_err:
            v["error"] = trial_err
        if mismatch:
            v["mismatch"] = mismatch
        verdicts.append(v)
        say(f"[chaosprobe] trial {ti} ({kind}): "
            f"{'ok' if v['ok'] else 'FAIL'} — {launches} launch(es), "
            f"{preempted} preempted, {fallbacks} fallback(s)")
        if not v["ok"]:
            break

    ok = all(v["ok"] for v in verdicts) and len(verdicts) == args.trials
    summary = {
        "ok": ok,
        "trials": len(verdicts),
        "windows": args.windows,
        "fleet": bool(args.fleet),
        "extra": args.extra,
        "quarantined": sorted(ref_quar),
        "oracle_checked": oracle_checked,
        "digest_rows": len(ref_stream),
        "launches": total_launches,
        "preempted_exits": total_preempted,
        "lineage_fallbacks": total_fallbacks,
        "kinds": {k: sum(1 for v in verdicts if v["kind"] == k)
                  for k in dict.fromkeys(kinds)},
        "straight_wall_s": round(straight_wall, 1),
    }
    if not ok:
        bad = next(v for v in verdicts if not v["ok"])
        summary["first_failure"] = bad
        summary["paritytrace"] = (
            f"python -m shadow1_tpu.tools.paritytrace {args.config} "
            f"tpu tpu+resume --windows {args.windows} "
            f"--chunk {args.chunk}")
    print(json.dumps(summary))
    return 0 if ok else EXIT_DIVERGED


if __name__ == "__main__":
    raise SystemExit(main())
