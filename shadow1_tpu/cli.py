"""Command-line runner: ``python -m shadow1_tpu <config.yaml> [options]``.

The analogue of the reference's ``shadow [options] shadow.config.xml`` entry
point (src/main/main.c + core/support/options.c): one experiment file, an
engine selector, and end-of-run metrics. The ``--engine`` flag overrides the
config's ``engine.scheduler`` the way the reference's CLI flags override its
config values.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


MAX_RESPAWNS = 8

# The CLI exit-code taxonomy lives in consts.py (jax-free) — 0 ok, 2 config,
# 4 capacity halt, 5 preempted drain, 6 watchdog-classified hang; duplicated
# as literals nowhere (docs/SEMANTICS.md "Preemption contract", README).
from shadow1_tpu.consts import (  # noqa: E402 (jax-free module)
    EXIT_CAPACITY,
    EXIT_CODES,
    EXIT_CONFIG,
    EXIT_HUNG,
    EXIT_MEMORY,
    EXIT_OK,
    EXIT_PREEMPTED,
)


def _config_fingerprint(config_path: str) -> str:
    """Identity of the experiment a --ckpt snapshot belongs to. Snapshot
    leaf shapes alone cannot distinguish two configs that differ only in
    scalars (seed, stop_time), so resume safety needs the config bytes."""
    import hashlib

    with open(config_path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _emit_resume_record(ckpt_path, resolved, win_start, lineage=None) -> None:
    """One parseable ``resume`` record on stderr per lineage resume (schema
    in docs/OBSERVABILITY.md): which generation the run continued from,
    how many corrupt newer generations were skipped, and the lineage depth
    on disk — the rows heartbeat_report's lineage section summarizes."""
    rec = {"type": "resume", "ckpt": ckpt_path,
           "generation": resolved.seq, "win_start": int(win_start),
           "fallback_skipped": len(resolved.skipped)}
    if resolved.skipped:
        rec["discarded"] = [s["file"] for s in resolved.skipped]
    if lineage is not None:
        rec["generations_kept"] = len(lineage.generations())
    print(json.dumps(rec), file=sys.stderr, flush=True)


def _resolve_ckpt_lineage(args, log, what="checkpoint"):
    """Child-side resume resolution, shared by the solo and fleet paths:
    walk the --ckpt lineage to the newest VALID generation (deleting
    corrupt newer ones), warn when nothing verifies, and fall back to an
    explicit --resume. Returns (resolved, lineage, resume_path)."""
    if not args.ckpt:
        return None, None, args.resume
    from shadow1_tpu.lineage import Lineage

    lineage = Lineage(args.ckpt, keep=args.ckpt_keep)
    r = lineage.resolve(discard_invalid=True)
    resolved = r if (r is not None and r.path is not None) else None
    if r is not None and resolved is None:
        log.warning(f"discarding corrupt {what}", path=args.ckpt,
                    reason=(r.skipped[0]["reason"] if r.skipped
                            else "no valid generation"))
    return resolved, lineage, (resolved.path if resolved else args.resume)


def _supervise(child_argv, ckpt_path, config_path,
               watchdog_s: float = 0.0) -> int:
    """Parent side of ``--ckpt`` fault tolerance (the ladder's recipe,
    bench_ladder.py): run the CLI in a child process; when it dies with a
    checkpoint showing forward progress, respawn a fresh child that resumes
    from the snapshot — a wedged-runtime fault never survives into the next
    attempt because the next attempt is a new process.

    Failure handling beyond the bare respawn loop:

    * **checkpoint lineage** (lineage.Lineage): the generation set is
      resolved host-side before every spawn — a corrupt HEAD with a valid
      older generation behind it is announced and left for the child to
      fall back on (one generation of progress lost, not the run); only
      when NO generation verifies is the whole set discarded and the run
      restarted from scratch;
    * **preemption** (rc == EXIT_PREEMPTED): a SIGTERM/SIGINT drain is a
      clean-resume exit, not a crash — no backoff, no crash accounting,
      checkpoint kept; the supervisor exits EXIT_PREEMPTED itself so the
      operator (or the next scheduler slot) reruns the same command to
      continue. The supervisor forwards its own SIGTERM/SIGINT to the
      child so signaling either process drains the run.
    * **watchdog** (``--watchdog-s`` / env SHADOW1_WATCHDOG_S): a child
      whose ``.progress`` sidecar mtime goes stale past the deadline is
      killed and classified **hung** — distinct from crashed, with its own
      backoff lane; two consecutive hangs without forward progress abort
      with EXIT_HUNG and point at the no-kill probe playbook
      (tools/faultprobe) — a dead tunnel costs a bounded delay, never an
      unbounded one (the PR 3/4 postmortems recorded 150 s silently lost);
    * **exponential backoff**: the respawn delay doubles on consecutive
      no-progress failures (per lane) and resets when an attempt makes
      forward progress (env SHADOW1_SUPERVISE_BACKOFF_S tunes the base;
      tests set 0);
    * **failure classification**: two consecutive crashes at the same
      ``win_start`` mean the fault is deterministic at that sim time — a
      third identical attempt would burn the respawn budget for nothing,
      so the supervisor aborts with a diagnosis instead.
    * **memory classification** (rc == EXIT_MEMORY, or belt-and-braces: a
      raw ``RESOURCE_EXHAUSTED`` on the child's stderr even when the
      structured taxonomy was bypassed — a crash deep in backend init, an
      allocator abort): device memory exhaustion is a deterministic
      config-vs-device condition, so the supervisor never respawns into
      the same wall; it points at the estimator's advice instead. The
      child's stderr is teed through a scanning thread so heartbeats
      still flow to the parent's stderr unchanged.
    """
    import os
    import signal
    import subprocess
    import threading
    import time as _time

    from shadow1_tpu.lineage import Lineage, write_json_atomic
    from shadow1_tpu.preempt import FORCE_GRACE_S

    sidecar = ckpt_path + ".progress"
    meta_path = ckpt_path + ".meta"
    lineage = Lineage(ckpt_path)

    def _emit_lineage(event: str, **fields) -> None:
        # Parseable lineage records on stderr, beside the [supervise] prose
        # — tools/heartbeat_report.py's "lineage" section reads these.
        print(json.dumps({"type": "lineage", "event": event, **fields}),
              file=sys.stderr, flush=True)

    # A snapshot left by an earlier interrupted run of a DIFFERENT config
    # must not silently hijack this run (same leaf shapes would pass
    # load_state's checks): fingerprint-mismatched leftovers are deleted.
    fp = _config_fingerprint(config_path)
    stale = False
    # ANY lineage candidate counts — a kill between head-rotation and
    # install leaves rotated generations with no head, and those must not
    # hijack a different config's run any more than a head would.
    if any(os.path.exists(p) for p in lineage.sidecar_paths()):
        try:
            with open(meta_path) as f:
                stale = json.load(f).get("config_sha256") != fp
        except (OSError, ValueError):
            stale = True
    if stale:
        print(f"[supervise] discarding stale checkpoint {ckpt_path} "
              f"(different or unknown config)", file=sys.stderr, flush=True)
        lineage.remove_all()
        for p in (sidecar, meta_path):
            if os.path.exists(p):
                os.remove(p)
    write_json_atomic(meta_path, {"config_sha256": fp})
    backoff_base = float(os.environ.get("SHADOW1_SUPERVISE_BACKOFF_S", "1.0"))
    last_progress = -1
    no_progress = 0       # consecutive crashes without forward progress
    no_progress_hung = 0  # consecutive watchdog kills without progress
    rc = 1

    # Signal plane, parent side: forward the first SIGTERM/SIGINT to the
    # child (so signaling only the supervisor still drains the run — group
    # delivery handles the common case, and the child debounces the
    # duplicate); a second one kills the child hard and re-raises.
    proc_box: list = [None]
    sig_seen: list = []

    def _forward(signum, frame):
        now = _time.monotonic()
        child = proc_box[0]
        # Same escalation window as the child's DrainHandler — parent and
        # child must agree on what counts as a duplicate delivery.
        if sig_seen and now - sig_seen[0] >= FORCE_GRACE_S:
            if child is not None and child.poll() is None:
                child.kill()
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)
            return
        if not sig_seen:
            sig_seen.append(now)
            print(f"[supervise] {signal.Signals(signum).name} received — "
                  f"forwarding drain request to the child",
                  file=sys.stderr, flush=True)
            if child is not None and child.poll() is None:
                child.send_signal(signal.SIGTERM)

    prev_handlers = {s: signal.signal(s, _forward)
                     for s in (signal.SIGTERM, signal.SIGINT)}
    try:
        for attempt in range(MAX_RESPAWNS + 1):
            if sig_seen:
                # The drain request landed while no child was alive (e.g.
                # during a backoff sleep, or the child died un-gracefully
                # right after the forward): honor it here — never respawn
                # past a preemption notice.
                print(f"[supervise] preemption requested — not "
                      f"respawning; checkpoint kept; rerun the same "
                      f"command to resume", file=sys.stderr, flush=True)
                _emit_lineage("preempted", rc=EXIT_PREEMPTED)
                return EXIT_PREEMPTED
            res = lineage.resolve()
            if res is not None and res.path is None:
                # Candidates existed but EVERY generation is damaged: same
                # policy as a stale snapshot — restart from scratch. The
                # progress baseline resets with it; the next child
                # legitimately re-earns its first windows.
                why = res.skipped[0]["reason"] if res.skipped else "?"
                print(f"[supervise] discarding corrupt checkpoint "
                      f"{ckpt_path} ({why}; no valid generation of "
                      f"{len(res.skipped)}); restarting from scratch",
                      file=sys.stderr, flush=True)
                _emit_lineage("discard_all", reason=why,
                              generations=len(res.skipped))
                lineage.remove_all()
                if os.path.exists(sidecar):
                    os.remove(sidecar)
                last_progress = -1
            elif res is not None and res.skipped:
                # Corrupt head, valid generation behind it: announce; the
                # child's own resolve falls back (and prunes the damage).
                print(f"[supervise] checkpoint head "
                      f"{res.skipped[0]['file']} is corrupt "
                      f"({res.skipped[0]['reason']}); resume will fall "
                      f"back to generation {res.seq}",
                      file=sys.stderr, flush=True)
                _emit_lineage("corrupt_head", fallback_seq=res.seq,
                              skipped=len(res.skipped),
                              reason=res.skipped[0]["reason"])
            cmd = [sys.executable, "-m", "shadow1_tpu", *child_argv,
                   "--supervised-child"]
            # stdout inherited: final JSON flows. stderr is TEED through a
            # scanning thread (heartbeats still reach the parent's stderr
            # line-for-line) so a raw RESOURCE_EXHAUSTED crash — one that
            # bypassed the structured EXIT_MEMORY taxonomy entirely — is
            # still classified as deterministic memory exhaustion below
            # instead of crash-looping through the backoff ladder. Popen
            # (not run) so the watchdog can poll the progress sidecar
            # while waiting.
            proc = subprocess.Popen(cmd, stderr=subprocess.PIPE, text=True,
                                    errors="replace")
            # [line_count, line index of the last RESOURCE_EXHAUSTED] — the
            # classifier below requires the marker NEAR death, so a
            # non-fatal allocator warning early in a long run (GPU
            # autotuners log these and continue) cannot misclassify an
            # unrelated later crash as memory exhaustion.
            oom_seen = [0, None]

            def _tee(pipe=proc.stderr, seen=oom_seen):
                for line in iter(pipe.readline, ""):
                    seen[0] += 1
                    if "RESOURCE_EXHAUSTED" in line:
                        seen[1] = seen[0]
                    sys.stderr.write(line)
                sys.stderr.flush()
                pipe.close()

            tee = threading.Thread(target=_tee, daemon=True)
            tee.start()
            proc_box[0] = proc
            if sig_seen and proc.poll() is None:
                # A drain request that landed between the top-of-loop check
                # and this assignment had no child to forward to — deliver
                # it now rather than letting the child run to completion
                # past a preemption notice.
                proc.send_signal(signal.SIGTERM)
            spawn_wall = _time.time()
            hung = False
            hung_stale = 0.0
            poll_s = (max(0.1, min(1.0, watchdog_s / 5))
                      if watchdog_s > 0 else 1.0)
            while True:
                try:
                    rc = proc.wait(timeout=poll_s)
                    break
                except subprocess.TimeoutExpired:
                    pass
                if watchdog_s <= 0:
                    continue
                try:
                    beat = os.path.getmtime(sidecar)
                except OSError:
                    beat = None
                beaten = beat is not None and beat > spawn_wall
                ref = beat if beaten else spawn_wall
                # Before THIS attempt's first beat the child is importing
                # and compiling (no sidecar ticks yet) — allow 3× the
                # deadline for startup; after a beat, the configured S.
                deadline = watchdog_s if beaten else 3 * watchdog_s
                stale_s = _time.time() - ref
                if stale_s > deadline:
                    print(f"[supervise] child hung: progress sidecar "
                          f"stale {stale_s:.1f}s > watchdog "
                          f"{deadline:.1f}s — killing pid {proc.pid}",
                          file=sys.stderr, flush=True)
                    proc.kill()
                    rc = proc.wait()
                    hung = True
                    hung_stale = stale_s
                    break
            proc_box[0] = None
            tee.join(timeout=10.0)  # drain the scan before classifying
            # Raw-marker classification is deliberately narrow: a plain
            # crash exit (rc > 0, not a structured taxonomy code, not a
            # signal death, not a watchdog kill) whose stderr ENDED with
            # the marker — the dying traceback — within the last 50
            # lines. Everything else falls through to the PR 4/7
            # crash/hung classifiers and keeps its recovery path.
            raw_oom = (rc > 0 and rc not in EXIT_CODES and not hung
                       and oom_seen[1] is not None
                       and oom_seen[0] - oom_seen[1] <= 50)
            if rc == EXIT_MEMORY or raw_oom:
                # Memory exhaustion (structured pre-flight/runtime exit,
                # or — belt and braces — a raw RESOURCE_EXHAUSTED crash
                # the taxonomy never saw): deterministic config-vs-device
                # condition; a respawn replays the identical allocation
                # and burns the budget for nothing.
                how = ("rc=EXIT_MEMORY" if rc == EXIT_MEMORY
                       else f"rc={rc}, raw RESOURCE_EXHAUSTED on stderr")
                print(f"[supervise] child exhausted device memory ({how}) "
                      f"— deterministic config-vs-device condition; not "
                      f"respawning. Apply the memory advice above, rerun "
                      f"with --on-oom downshift, or probe the feasible "
                      f"envelope: python -m shadow1_tpu.tools.memprobe "
                      f"{config_path} --maxfit",
                      file=sys.stderr, flush=True)
                return EXIT_MEMORY
            if rc == EXIT_CAPACITY:
                # Capacity halt (--on-overflow halt →
                # CapacityExceededError): a deterministic config condition,
                # not a device fault — a respawn would replay the identical
                # overflow and burn the budget. The child already printed
                # the structured advice.
                print(f"[supervise] child halted on a capacity policy "
                      f"(rc={rc}, CapacityExceededError) — deterministic "
                      f"config condition; not respawning. Apply the "
                      f"engine: cap advice above, or rerun with "
                      f"--on-overflow retry.", file=sys.stderr, flush=True)
                return rc
            if rc == EXIT_PREEMPTED:
                # Clean-resume classification: the child committed its
                # in-flight chunk and wrote a final snapshot before
                # exiting. No backoff, no crash accounting, checkpoint
                # KEPT — rerunning the same command resumes bit-exactly.
                print(f"[supervise] child drained after a preemption "
                      f"signal (rc={rc}) — checkpoint kept; rerun the "
                      f"same command to resume", file=sys.stderr, flush=True)
                _emit_lineage("preempted", rc=rc)
                return EXIT_PREEMPTED
            if rc == EXIT_OK:
                # A finished run's snapshot must not silently resume a
                # later invocation of the same command into a no-op.
                lineage.remove_all()
                for p in (sidecar, meta_path):
                    if os.path.exists(p):
                        os.remove(p)
                return EXIT_OK
            progress = -1
            if os.path.exists(sidecar):
                try:
                    with open(sidecar) as f:
                        progress = json.load(f).get("win_start", -1)
                except (OSError, ValueError):
                    progress = -1
            if hung:
                # Every kill gets its record (including one that triggers
                # the EXIT_HUNG classification below), with the OBSERVED
                # staleness, not the configured deadline.
                _emit_lineage("watchdog_kill", stale_s=round(hung_stale, 1),
                              sim_ns=max(progress, 0), attempt=attempt)
            if progress > last_progress:
                no_progress = 0
                no_progress_hung = 0
                last_progress = progress
            elif hung:
                no_progress_hung += 1
                if no_progress_hung >= 2:
                    print(
                        f"[supervise] two consecutive watchdog kills with "
                        f"no forward progress at sim_ns={max(progress, 0)} "
                        f"— the hang is deterministic at that point "
                        f"(wedged dispatch, dead tunnel), further respawns "
                        f"would repeat it. Follow the no-kill probe "
                        f"playbook: `python -m shadow1_tpu.tools."
                        f"faultprobe` (device liveness without killing the "
                        f"session), then `python -m shadow1_tpu.tools."
                        f"paritytrace {config_path} tpu cpu` once the "
                        f"device answers.", file=sys.stderr, flush=True)
                    return EXIT_HUNG
            else:
                no_progress += 1
                if no_progress >= 2:
                    print(
                        f"[supervise] two consecutive crashes (rc={rc}) "
                        f"with no forward progress at "
                        f"sim_ns={max(progress, 0)} — the fault is "
                        f"deterministic at that point, further respawns "
                        f"would repeat it. Diagnose with "
                        f"`python -m shadow1_tpu.tools.faultprobe` "
                        f"(device/kernel faults) or `python -m shadow1_tpu."
                        f"tools.paritytrace {config_path} tpu cpu` (state "
                        f"divergence).", file=sys.stderr, flush=True)
                    return rc
            if attempt == MAX_RESPAWNS:
                return rc
            # Base delay after an attempt that made progress, doubled per
            # consecutive no-progress failure IN ITS LANE (hangs and
            # crashes back off independently — a wedged tunnel and a
            # crashing kernel are different pathologies); the classifiers
            # above bound the exponent, not this formula.
            delay = backoff_base * (2 ** (no_progress_hung if hung
                                          else no_progress))
            kind = "hung (watchdog kill)" if hung else f"died rc={rc}"
            print(f"[supervise] child {kind} at sim_ns={progress}; "
                  f"respawning ({attempt + 1}/{MAX_RESPAWNS}) "
                  f"after {delay:.1f}s backoff",
                  file=sys.stderr, flush=True)
            if delay > 0:
                _time.sleep(delay)
    finally:
        for s, h in prev_handlers.items():
            signal.signal(s, h)
    return rc


def _fleet_main(args, params, plan, log, t0, capacity_exit,
                preempted_exit, memory_exit=None, sub_batch=None,
                auto_caps=False, pre_downshift_retry=False) -> int:
    """The --fleet execution path: one FleetEngine run over the expanded
    sweep, per-experiment final records + a fleet summary on stdout
    (docs/OBSERVABILITY.md §"Fleet records"). ``sub_batch`` (set by the
    --on-oom downshift planner) routes to the sequential sub-batched
    runner instead; ``memory_exit`` maps a runtime RESOURCE_EXHAUSTED to
    the structured EXIT_MEMORY taxonomy. The recovery plane (retry /
    quarantine / lane finalize / auto-caps) is driven by ``params`` inside
    fleet.run.run_fleet; this layer resolves resume state — including a
    lineage generation whose ``lanes`` meta says the sweep had already
    quarantined/finalized lanes (rebuild exactly that sub-fleet) and
    snapshots carrying retry/auto-caps-grown caps (rebuild at the
    snapshot's caps, mirroring the solo path)."""
    import os as _os

    import jax
    import numpy as np

    from shadow1_tpu import mem
    from shadow1_tpu.fleet.engine import FleetEngine
    from shadow1_tpu.fleet.run import final_records, run_fleet
    from shadow1_tpu.preempt import DrainHandler, PreemptedExit
    from shadow1_tpu.txn import CapacityExceededError

    if sub_batch and sub_batch < len(plan.exps):
        return _fleet_subbatched(args, params, plan, log, t0, capacity_exit,
                                 preempted_exit, memory_exit, sub_batch)
    # Resume resolution FIRST: a lineage generation carries the surviving
    # lane ids (``lanes`` manifest meta) when the sweep had already
    # quarantined or finalized lanes — the engine must be built for
    # exactly that sub-fleet or the [E', ...] snapshot cannot load.
    resolved, ckpt_lineage, resume_path = _resolve_ckpt_lineage(
        args, log, what="fleet checkpoint")
    exps, labels, max_rounds = plan.exps, plan.labels, plan.max_rounds
    meta_lanes = (resolved.meta or {}).get("lanes") if resolved else None
    sub_applied = False
    if meta_lanes is not None and \
            list(meta_lanes) != [l["exp"] for l in plan.labels]:
        sub = plan.subset(meta_lanes)
        exps, labels, max_rounds = sub.exps, sub.labels, sub.max_rounds
        sub_applied = True
        log.info("resuming partially-recovered sweep",
                 lanes=list(meta_lanes), of=len(plan.exps))

    def _build(p):
        eng = FleetEngine(exps, p, max_rounds)
        eng.exp_ids = [l["exp"] for l in labels]
        return eng

    try:
        eng = _build(params)
    except Exception as e:
        if memory_exit is not None and mem.is_oom(e):
            return memory_exit(e, phase="init")
        raise
    log.info("fleet expanded", experiments=eng.n_exp,
             hosts=eng.exp.n_hosts, window_ns=eng.window)
    st = None
    metrics0_by_gid = None
    if resume_path:
        from shadow1_tpu.ckpt import (
            CorruptCheckpointError,
            load_state,
            snapshot_caps,
        )

        params0, eng0 = params, eng
        try:
            template = eng.init_state()
            if (auto_caps or params.on_overflow == "retry"
                    or pre_downshift_retry):
                # Retry/auto-caps runs checkpoint at whatever cap they had
                # grown to — rebuild the fleet engine at the snapshot's
                # caps before loading (the solo-path recipe; a shrink-on-
                # load that would drop events refuses otherwise).
                snap = snapshot_caps(template, resume_path)
                if snap and snap != (params.ev_cap, params.outbox_cap):
                    import dataclasses

                    params = dataclasses.replace(
                        params, ev_cap=snap[0], outbox_cap=snap[1])
                    eng = _build(params)
                    template = eng.init_state()
            st = load_state(template, resume_path)
        except CorruptCheckpointError as e:
            # Same policy as the solo path: a supervised child must not
            # crash-loop the respawn budget on a snapshot corrupted after
            # the parent's pre-spawn verification — fall back to a fresh
            # start. An explicit --resume keeps failing loudly. A fresh
            # start means the FULL sweep: the discarded generation's
            # ``lanes`` subset (and its quarantine ledger) dies with it —
            # every lane re-earns its fate from window 0.
            if resolved is None:
                raise
            log.warning("discarding corrupt fleet checkpoint",
                        path=resume_path, reason=str(e))
            st, resume_path, resolved = None, None, None
            params = params0
            if sub_applied:
                exps, labels, max_rounds = (plan.exps, plan.labels,
                                            plan.max_rounds)
                eng = _build(params)
            else:
                eng = eng0
        else:
            metrics0_by_gid = {l["exp"]: m for l, m in
                               zip(labels, eng.metrics_per_exp(st))}
            done = int(np.asarray(st.win_start).max()) // eng.window
            if resolved is not None:
                _emit_resume_record(args.ckpt, resolved,
                                    int(np.asarray(st.win_start).max()),
                                    ckpt_lineage)
            if args.windows is None:
                args.windows = max(eng.n_windows - done, 0)
            elif resolved is not None:
                # Supervised respawn: --windows is the TOTAL for the whole
                # supervised run, not N more on top of the snapshot.
                args.windows = max(args.windows - done, 0)
    ring_w = params.metrics_ring
    drain = DrainHandler().install()
    # Quarantined-lane snapshots land beside the fleet checkpoint; a
    # checkpoint-less run keeps them beside the config (full path kept —
    # never the process cwd).
    qbase = args.ckpt or _os.path.splitext(args.config)[0] + ".lane"
    hb = None
    try:
        if _os.environ.get("SHADOW1_MEM_INJECT_OOM") == "run":
            raise RuntimeError("RESOURCE_EXHAUSTED: injected (test hook)")
        st, hb = run_fleet(
            eng, st, n_windows=args.windows,
            every_windows=args.heartbeat or (ring_w or None),
            stream=None if (args.heartbeat or ring_w
                            or params.link_telem) else False,
            ckpt_path=args.ckpt, ckpt_every_s=args.ckpt_every_s,
            emit_heartbeat=bool(args.heartbeat),
            emit_ring=bool(ring_w or params.link_telem),
            selfcheck=bool(params.selfcheck),
            labels=labels,
            ckpt_keep=args.ckpt_keep,
            drain=drain,
            auto_caps=auto_caps,
            quarantine_base=qbase,
            recovery_seed=({"quarantined":
                            (resolved.meta or {}).get("quarantined", []),
                            "finished":
                            (resolved.meta or {}).get("finished", [])}
                           if resolved is not None and st is not None
                           else None),
            # Quarantine / early-finalize records print to stdout the
            # moment the lane leaves the fleet — its fleet_exp would
            # otherwise never appear.
            emit_record=lambda rec: print(json.dumps(rec), flush=True),
        )
        jax.block_until_ready(st)
    except CapacityExceededError as e:
        return capacity_exit(e)
    except PreemptedExit as e:
        return preempted_exit(e, resumed=bool(resume_path))
    except Exception as e:
        if memory_exit is not None and mem.is_oom(e):
            return memory_exit(e)
        raise
    if args.save_state:
        from shadow1_tpu.ckpt import save_state

        save_state(st, args.save_state)
    wall = time.perf_counter() - t0
    n_windows = args.windows if args.windows is not None else eng.n_windows
    # The live fleet shape (lanes may have left mid-sweep) is on the
    # heartbeat; rate baselines re-align by global id.
    metrics0 = ([metrics0_by_gid.get(l["exp"], {}) for l in hb.labels]
                if metrics0_by_gid is not None else None)
    recs, summary = final_records(hb.engine, st, hb.labels, n_windows, wall,
                                  resumed=bool(resume_path),
                                  metrics0=metrics0,
                                  recovery=hb.recovery)
    for r in recs:
        print(json.dumps(r))
    print(json.dumps(summary))
    return 0


def _fleet_subbatched(args, params, plan, log, t0, capacity_exit,
                      preempted_exit, memory_exit, sub: int) -> int:
    """Memory-downshifted fleet: the sweep's E lanes run as SEQUENTIAL
    sub-batches of ≤ ``sub`` lanes, each its own vmapped FleetEngine run
    (cli --on-oom downshift; mem.downshift sized ``sub`` so one batch fits
    the device budget).

    Bit-exactness: lanes are independent — counter-based RNG keyed per
    (seed, host, ctr), per-lane fault tables, per-lane selects in the
    batched while_loop — so lane e's digest stream and metrics are
    IDENTICAL whether it runs beside 2 or 200 other lanes
    (tools/memprobe.py --subbatch is the per-invocation proof, the fleet
    contract's fleetprobe idiom). Each batch prints its fleet_exp records
    with SWEEP-GLOBAL experiment ids (FleetEngine.exp_base) as it
    finishes; one merged fleet_summary closes the run.

    ``--ckpt`` composes: each batch checkpoints ITS OWN [k, ...] state,
    with the sub-batch cursor riding the lineage manifest entry
    (``batch`` + ``batch_summaries`` — completed batches' summaries, so
    the merged fleet_summary survives a crash) beside the batch's
    ``lanes``. A resume rebuilds the engine for the recorded batch, loads
    its snapshot, finishes it and continues the remaining batches —
    completed batches never re-run. (--resume/--save-state still refuse
    at the downshift planner: an explicit snapshot path has no cursor.)
    A drain request stops the sweep at a batch/chunk boundary — finished
    lanes keep their records."""
    import os as _os

    import numpy as np
    import jax

    from shadow1_tpu import mem
    from shadow1_tpu.fleet.engine import FleetEngine
    from shadow1_tpu.fleet.run import final_records, run_fleet
    from shadow1_tpu.preempt import DrainHandler, PreemptedExit
    from shadow1_tpu.telemetry.registry import gauge_names
    from shadow1_tpu.txn import CapacityExceededError

    E = len(plan.exps)
    n_batches = -(-E // sub)
    log.info("fleet sub-batched for memory", experiments=E,
             lanes_per_batch=sub, batches=n_batches)
    drain = DrainHandler().install()
    ring_w = params.metrics_ring
    summaries: list[dict] = []
    windows_done = args.windows
    lanes_run = 0
    # Per-batch resume: the newest lineage generation names the batch it
    # snapshots and carries the summaries of every COMPLETED batch.
    resolved, ckpt_lineage, resume_path = _resolve_ckpt_lineage(
        args, log, what="sub-batched fleet checkpoint")
    start_batch = 0
    if resume_path and resolved is not None and resolved.meta:
        start_batch = int(resolved.meta.get("batch", 0))
        summaries = list(resolved.meta.get("batch_summaries", []))
        lanes_run = start_batch * sub
    for bi, i in enumerate(range(0, E, sub)):
        if bi < start_batch:
            continue  # completed pre-crash; its summary rode the manifest
        exps = plan.exps[i:i + sub]
        labels = plan.labels[i:i + sub]
        max_rounds = plan.max_rounds[i:i + sub]
        recovery_seed = None
        st = None
        batch_resumed = False
        resuming_here = resume_path and bi == start_batch
        if resuming_here and resolved is not None and resolved.meta:
            # The generation may snapshot a batch that already
            # quarantined/finalized lanes — rebuild exactly that
            # sub-batch or the [k', ...] snapshot cannot load
            # (the _fleet_main lanes-meta recipe, per batch).
            meta_lanes = resolved.meta.get("lanes")
            if meta_lanes is not None and \
                    list(meta_lanes) != [l["exp"] for l in labels]:
                by_gid = {l["exp"]: j for j, l in enumerate(labels)}
                keep = [by_gid[g] for g in meta_lanes if g in by_gid]
                exps = [exps[j] for j in keep]
                max_rounds = [max_rounds[j] for j in keep]
                labels = [labels[j] for j in keep]
            recovery_seed = {
                "quarantined": resolved.meta.get("quarantined", []),
                "finished": resolved.meta.get("finished", [])}
        try:
            eng = FleetEngine(exps, params, max_rounds)
            eng.exp_base = i
            eng.exp_ids = [l["exp"] for l in labels]
            n_windows = (args.windows if args.windows is not None
                         else eng.n_windows)
            remaining = n_windows
            if resuming_here:
                from shadow1_tpu.ckpt import (
                    CorruptCheckpointError,
                    load_state,
                )

                try:
                    st = load_state(eng.init_state(), resume_path)
                except CorruptCheckpointError as e:
                    if resolved is None:
                        raise
                    log.warning("discarding corrupt sub-batch checkpoint",
                                path=resume_path, reason=str(e))
                    st = None
                    recovery_seed = None
                    # Fresh restart of THIS batch = the full batch again.
                    exps = plan.exps[i:i + sub]
                    labels = plan.labels[i:i + sub]
                    eng = FleetEngine(exps, params,
                                      plan.max_rounds[i:i + sub])
                    eng.exp_base = i
                else:
                    batch_resumed = True
                    done = (int(np.asarray(st.win_start).max())
                            // eng.window)
                    remaining = max(n_windows - done, 0)
                    _emit_resume_record(args.ckpt, resolved,
                                        int(np.asarray(st.win_start).max()),
                                        ckpt_lineage)
            st, hb = run_fleet(
                eng, st, n_windows=remaining,
                every_windows=args.heartbeat or (ring_w or None),
                stream=None if (args.heartbeat or ring_w
                                or params.link_telem) else False,
                ckpt_path=args.ckpt, ckpt_every_s=args.ckpt_every_s,
                emit_heartbeat=bool(args.heartbeat),
                emit_ring=bool(ring_w or params.link_telem),
                selfcheck=bool(params.selfcheck),
                labels=labels,
                ckpt_keep=args.ckpt_keep,
                drain=drain,
                quarantine_base=(args.ckpt or _os.path.splitext(
                    args.config)[0] + ".lane"),
                emit_record=lambda rec: print(json.dumps(rec), flush=True),
                resume_meta={"batch": bi, "batch_summaries": summaries},
                recovery_seed=recovery_seed,
            )
            jax.block_until_ready(st)
        except CapacityExceededError as e:
            return capacity_exit(e)
        except PreemptedExit as e:
            return preempted_exit(e, resumed=batch_resumed)
        except Exception as e:
            if memory_exit is not None and mem.is_oom(e):
                return memory_exit(e)
            raise
        windows_done = n_windows
        # The LIVE batch shape: quarantine/finalize policies ride params
        # into run_fleet and may have shrunk the batch mid-run.
        recs, summary = final_records(hb.engine, st, hb.labels, n_windows,
                                      time.perf_counter() - t0,
                                      resumed=batch_resumed,
                                      recovery=hb.recovery)
        for r in recs:
            print(json.dumps(r))
        summaries.append(summary)
        lanes_run += len(exps)
        if drain.requested and lanes_run < E:
            # done_windows keeps its documented unit (windows committed
            # this invocation — preempt.py), matching the full-fleet
            # drain for the same run; finished lanes already printed
            # their fleet_exp records above.
            return preempted_exit(PreemptedExit(
                st=None, signame=drain.signame,
                done_windows=n_windows,
                win_start=int(summary.get("sim_seconds", 0) * 1e9)),
                resumed=batch_resumed)
    # Merged fleet_summary: counters sum, gauges (and the lockstep
    # windows/rounds) max across batches — the same aggregation rule as
    # FleetEngine.metrics_dict, applied one level up.
    maxed = set(gauge_names()) | {"windows", "rounds"}
    agg: dict[str, int] = {}
    for s in summaries:
        for k, v in s["metrics"].items():
            agg[k] = (max(agg.get(k, 0), int(v)) if k in maxed
                      else agg.get(k, 0) + int(v))
    wall = time.perf_counter() - t0
    s0 = summaries[0]
    sim_s = s0["sim_seconds"]
    merged = {
        "type": "fleet_summary",
        "engine": "fleet",
        "experiments": E,
        "hosts": s0["hosts"],
        "window_ns": s0["window_ns"],
        "windows": windows_done,
        "sim_seconds": sim_s,
        "wall_seconds": round(wall, 3),
        "sim_per_wall": round(sim_s / wall, 3) if wall > 0 else None,
        "events_per_sec": (round(agg.get("events", 0) / wall, 1)
                           if wall > 0 else None),
        "events_per_exp": [e for s in summaries
                           for e in s["events_per_exp"]],
        "resumed": False,
        "caps": s0["caps"],
        "metrics": agg,
        # The downshift audit: how the sweep was split for memory.
        "sub_batches": n_batches,
        "lanes_per_batch": sub,
    }
    print(json.dumps(merged))
    return 0


def main(argv=None) -> int:
    # Serve-plane subcommands (shadow1_tpu/serve/): `serve` starts the
    # persistent multi-tenant daemon, `submit` is its client. Dispatched
    # before the solo argparse so the solo surface (positional config +
    # flags) stays byte-compatible for every existing caller.
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    if argv and argv[0] == "serve":
        from shadow1_tpu.serve.daemon import main as serve_main

        return serve_main(argv[1:])
    if argv and argv[0] == "submit":
        from shadow1_tpu.serve.client import main as submit_main

        return submit_main(argv[1:])
    ap = argparse.ArgumentParser(
        prog="shadow1_tpu",
        description="TPU-native discrete-event network simulator",
    )
    ap.add_argument("config", help="YAML experiment file")
    ap.add_argument("--engine", choices=["cpu", "tpu", "sharded"], default=None,
                    help="override engine.scheduler from the config")
    ap.add_argument("--windows", type=int, default=None,
                    help="run only this many conservative windows")
    ap.add_argument("--summary", action="store_true",
                    help="also print per-host model summary totals")
    ap.add_argument("--heartbeat", type=int, default=None, metavar="W",
                    help="emit a heartbeat line to stderr every W windows")
    ap.add_argument("--save-state", default=None, metavar="PATH",
                    help="snapshot final engine state to PATH (.npz)")
    ap.add_argument("--ckpt", default=None, metavar="PATH",
                    help="fault-tolerant run: snapshot state to PATH at "
                         "heartbeat boundaries and supervise the run in a "
                         "child process — on a device fault the child is "
                         "respawned resuming from PATH (the ladder's "
                         "chunk+resume recipe; tunneled TPUs wedge whole "
                         "processes)")
    ap.add_argument("--ckpt-every-s", type=float, default=120.0,
                    metavar="S", help="throttle --ckpt snapshots to ~S "
                                      "seconds of wall (saves cost host "
                                      "transfer + npz write)")
    ap.add_argument("--ckpt-keep", type=int, default=3, metavar="K",
                    help="checkpoint lineage depth: keep the newest K "
                         "snapshot generations (the newest at the --ckpt "
                         "path, older rotated to PATH.gNNNNNN with a "
                         "PATH.lineage manifest); resume uses the newest "
                         "generation that passes its integrity digest, so "
                         "a torn/bit-flipped head costs one generation of "
                         "progress instead of the whole run")
    ap.add_argument("--watchdog-s", type=float, default=None, metavar="S",
                    help="supervisor watchdog: kill a child whose "
                         ".progress sidecar has not been refreshed for S "
                         "seconds of wall and classify the attempt as "
                         "'hung' (distinct backoff lane from crashes; two "
                         "consecutive no-progress hangs abort with the "
                         "dedicated exit code). Default: env "
                         "SHADOW1_WATCHDOG_S, else off. Size it above one "
                         "chunk's wall; startup (imports + compile, before "
                         "an attempt's first beat) gets 3x the deadline")
    ap.add_argument("--supervised-child", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--resume", default=None, metavar="PATH",
                    help="resume from a state snapshot (batched engines)")
    ap.add_argument("--tracker", default=None, metavar="PATH",
                    help="write final per-host tracker records (JSON lines)")
    ap.add_argument("--profile", default=None, metavar="DIR",
                    help="capture a jax.profiler trace of the run into DIR "
                         "(open with TensorBoard; reference: heartbeat/"
                         "tracker profiling hooks, SURVEY §5); also writes "
                         "DIR/phases.trace.json (the --trace phase spans)")
    ap.add_argument("--trace", default=None, metavar="PATH",
                    help="write a Chrome trace-event JSON of the host-side "
                         "phases (compile/init/run-chunk/drain/checkpoint) "
                         "to PATH — load in Perfetto or chrome://tracing")
    ap.add_argument("--auto-caps", action="store_true",
                    help="occupancy-driven capacity autotuning: at chunk "
                         "boundaries, grow ev_cap before overflow and shrink "
                         "it after sustained low occupancy (measured via the "
                         "on-device fill gauges), migrating state bit-exactly "
                         "and re-jitting at ladder-quantized caps "
                         "(shadow1_tpu/tune/; overrides engine.auto_caps)")
    ap.add_argument("--metrics-ring", type=int, default=None, metavar="W",
                    help="keep a W-window on-device telemetry ring and emit "
                         "one per-window JSONL record to stderr per window "
                         "(drained at chunk boundaries; overrides "
                         "engine.metrics_ring from the config)")
    ap.add_argument("--watch", action="append", default=None,
                    metavar="HOST[:SOCK]",
                    help="watch a flow or host (repeatable): sample its "
                         "state columns (TCP cwnd/ssthresh/srtt/rto/"
                         "inflight..., NIC backlog/bytes, pending events) "
                         "at every window boundary into an on-device probe "
                         "ring, drained as per-window 'flow' JSONL records "
                         "on stderr (telemetry/probes.py; a ring is enabled "
                         "automatically on the batched engines). HOST is a "
                         "config host name (group[i] or group-i for "
                         "members) or a numeric id; omit :SOCK for the "
                         "host-level view. Merges with the config's "
                         "'probes:' section. Render with tools/flowreport.py")
    ap.add_argument("--state-digest", choices=["on", "off"], default=None,
                    metavar="on|off",
                    help="determinism flight recorder (core/digest.py): "
                         "compute per-window order-independent state digests "
                         "(evbuf/outbox/tcp/nic/rng words) inside the window "
                         "loop and carry them as telemetry-ring columns "
                         "(batched engines; a ring is enabled automatically) "
                         "or as per-window 'digest' JSONL records on stderr "
                         "(cpu oracle). off (default) traces zero digest "
                         "ops. Compare streams with tools/paritytrace.py")
    ap.add_argument("--link-telem", choices=["on", "off"], default=None,
                    metavar="on|off",
                    help="per-link telemetry plane (telemetry/links.py): "
                         "accumulate per-edge packet/byte/drop/queued "
                         "counters in a device-resident [V,V] tensor inside "
                         "the window loop, drained at chunk boundaries as "
                         "cumulative 'link' JSONL records on stderr (the cpu "
                         "oracle mirrors them bit-exactly). off (default) "
                         "traces zero link ops. Render with "
                         "tools/netreport.py")
    ap.add_argument("--on-overflow", choices=["drop", "retry", "halt"],
                    default=None, metavar="drop|retry|halt",
                    help="overflow policy at chunk boundaries "
                         "(shadow1_tpu/txn.py; overrides engine.on_overflow). "
                         "drop (default) = counted-but-lossy; retry = "
                         "TRANSACTIONAL chunks: discard the tainted chunk, "
                         "grow the offending cap one ladder step (bit-exact "
                         "migration + re-jit) and replay it — the digest "
                         "stream bit-matches a straight run at the final "
                         "caps; halt = raise CapacityExceededError with "
                         "paste-ready cap advice (exit code 4)")
    ap.add_argument("--on-oom", choices=["halt", "downshift"],
                    default="halt", metavar="halt|downshift",
                    help="memory-budget policy (shadow1_tpu/mem.py): the "
                         "pre-flight byte estimator compares the engine "
                         "state planes + known transient peaks against the "
                         "device's reported memory (env SHADOW1_MEM_BYTES "
                         "overrides) BEFORE compiling. halt (default) = "
                         "reject an oversubscribed config with a "
                         "structured MemoryBudgetError (per-plane bytes + "
                         "paste-ready advice, exit code 7); downshift = "
                         "degrade gracefully in bit-exactness-preserving "
                         "order: drop the txn rollback copy (retry demotes "
                         "to halt), shrink the telemetry ring, split a "
                         "fleet into sequential sub-batches (per-lane "
                         "digest streams stay bit-identical)")
    ap.add_argument("--on-lane-fail", choices=["halt", "quarantine"],
                    default=None, metavar="halt|quarantine",
                    help="fleet lane-failure policy (shadow1_tpu/fleet/"
                         "run.py; overrides engine.on_lane_fail). halt "
                         "(default) = a deterministically failing lane "
                         "(capacity halt / retry-ladder exhaustion / "
                         "per-lane selfcheck violation) kills the whole "
                         "sweep with the solo exit taxonomy; quarantine = "
                         "slice the lane out of the chunk-start state into "
                         "a solo-resumable checkpoint + a fleet_quarantine "
                         "record, repack the survivors (bit-exact streams) "
                         "and finish the sweep at E-k/E")
    ap.add_argument("--lane-finalize", action="store_true",
                    help="fleet mid-sweep lane lifecycle: lanes whose "
                         "event buffer fully drains (per-lane stop "
                         "horizon passed, nothing can ever fire again) "
                         "emit their fleet_exp record immediately and are "
                         "sliced out at chunk boundaries, shrinking the "
                         "device program to the lanes still doing work "
                         "(overrides engine.lane_finalize)")
    ap.add_argument("--selfcheck", action="store_true",
                    help="verify the drop-accounting identity (every sent "
                         "packet reaches exactly one counted fate) at every "
                         "chunk/window boundary; violation = structured "
                         "SelfCheckError naming the non-closing counters")
    ap.add_argument("--faults", choices=["on", "off"], default="on",
                    metavar="on|off",
                    help="fault plane (config `faults:` section — host "
                         "down/up cycles, link outage windows, timed loss "
                         "ramps; docs/SEMANTICS.md §'Fault plane'). "
                         "`off` runs the same experiment with the schedule "
                         "stripped (the healthy-world A/B); the legacy "
                         "per-group stop_time churn is unaffected")
    ap.add_argument("--fleet", action="store_true",
                    help="batched experiment sweep (shadow1_tpu/fleet/): "
                         "expand the config's `sweep:` section into E "
                         "experiment variants (seeds, loss rates, fault "
                         "schedules — one topology shape class) and run "
                         "them as ONE vmapped device program. Emits one "
                         "fleet_exp JSON record per experiment plus a "
                         "fleet_summary line; per-experiment digest "
                         "streams are bit-identical to solo runs "
                         "(docs/SEMANTICS.md §'Fleet contract')")
    ap.add_argument("--log-level", default="message",
                    choices=["error", "warning", "message", "info", "debug"],
                    help="stderr log verbosity (reference --log-level analogue)")
    args = ap.parse_args(argv)

    import shadow1_tpu  # noqa: F401  (x64 before jax arrays)
    from shadow1_tpu.config.experiment import WatchlistError, load_experiment

    try:
        exp, params, scheduler = load_experiment(args.config)
    except WatchlistError as e:
        # Structured config rejection (EXIT_CONFIG via argparse), not a
        # traced-shape crash deep in the engine: a typo'd probe target is
        # a config error like any other.
        ap.error(str(e))
    if args.faults == "off":
        exp.faults = None
    if args.watch:
        # CLI watch targets resolve through the SAME path as the config's
        # 'probes:' section (names via exp.dns), then merge with it —
        # duplicates collapse, config entries keep first-seen order.
        import dataclasses

        from shadow1_tpu.config.experiment import resolve_watchlist

        try:
            extra = resolve_watchlist(list(args.watch), exp.dns,
                                      params.sockets_per_host)
        except WatchlistError as e:
            ap.error(str(e))
        merged = list(params.probes)
        merged += [p for p in extra if p not in merged]
        params = dataclasses.replace(params, probes=tuple(merged))
    if args.metrics_ring is not None:
        import dataclasses

        params = dataclasses.replace(params, metrics_ring=args.metrics_ring)
    engine_kind = args.engine or scheduler
    if args.state_digest is not None:
        import dataclasses

        params = dataclasses.replace(
            params, state_digest=int(args.state_digest == "on"))
    if args.link_telem is not None:
        import dataclasses

        params = dataclasses.replace(
            params, link_telem=int(args.link_telem == "on"))
    if (params.state_digest and params.metrics_ring <= 0
            and args.metrics_ring is None and engine_kind != "cpu"):
        # The digest words are ring columns on the batched engines; give the
        # stream a transport when neither config nor flags did (depth = the
        # heartbeat chunk keeps the drain gap-free). An EXPLICIT
        # --metrics-ring 0 is honored and fails loudly in the engine's
        # state_digest-needs-a-ring check instead.
        import dataclasses

        params = dataclasses.replace(
            params, metrics_ring=args.heartbeat or 64)
    if (params.probes and params.metrics_ring <= 0
            and args.metrics_ring is None and engine_kind != "cpu"):
        # Probe rows ride their own [W, K, F] ring but reuse the telemetry
        # ring's depth knob — same auto-enable rule as --state-digest
        # (explicit --metrics-ring 0 fails loudly in check_probe_params).
        import dataclasses

        params = dataclasses.replace(
            params, metrics_ring=args.heartbeat or 64)
    if args.on_overflow is not None:
        import dataclasses

        params = dataclasses.replace(params, on_overflow=args.on_overflow)
    if args.selfcheck:
        import dataclasses

        params = dataclasses.replace(params, selfcheck=1)
    if args.on_lane_fail is not None or args.lane_finalize:
        import dataclasses

        if not args.fleet:
            ap.error("--on-lane-fail/--lane-finalize are fleet lane "
                     "policies; they need --fleet")
        repl = {}
        if args.on_lane_fail is not None:
            repl["on_lane_fail"] = args.on_lane_fail
        if args.lane_finalize:
            repl["lane_finalize"] = 1
        params = dataclasses.replace(params, **repl)
    auto_caps = bool(args.auto_caps or params.auto_caps)
    if engine_kind == "cpu" and (args.save_state or args.resume
                                 or args.heartbeat or args.tracker
                                 or args.profile or args.ckpt
                                 or args.trace or args.metrics_ring
                                 or args.auto_caps
                                 or args.on_overflow == "retry"
                                 or args.on_oom == "downshift"):
        ap.error("--save-state/--resume/--heartbeat/--tracker/--profile/"
                 "--ckpt/--trace/--metrics-ring/--auto-caps/"
                 "--on-overflow retry/--on-oom downshift require a batched "
                 "engine (tpu or sharded)")
    if args.fleet:
        bad = [f for f, v in (("--tracker", args.tracker),
                              ("--summary", args.summary),
                              ("--profile", args.profile),
                              ("--trace", args.trace)) if v]
        if bad:
            ap.error(f"--fleet does not support {', '.join(bad)}: "
                     f"per-experiment tracker/summary/phase traces are a "
                     f"follow-up; use the fleet_exp records and "
                     f"--metrics-ring (per-experiment rows)")
        from shadow1_tpu.fleet.expand import FleetConfigError

        def _fleet_config_exit(e: FleetConfigError) -> int:
            """Structured fleet rejection: message on stderr, one
            parseable JSON record on stdout, config exit code."""
            print(f"FleetConfigError: {e}", file=sys.stderr, flush=True)
            print(json.dumps({"error": "fleet_config", "kind": e.kind,
                              "knob": e.knob, "message": str(e)}))
            return EXIT_CONFIG
        if engine_kind != "tpu":
            return _fleet_config_exit(FleetConfigError(
                f"--fleet batches the single-device tpu engine; "
                f"engine={engine_kind!r} is not composable with the "
                f"experiment axis yet (run the sweep's experiments solo "
                f"on that engine, or drop --engine)", kind="mode",
                knob="engine"))
        # --auto-caps and --on-overflow retry were kind="mode" rejections
        # through PR 12 — both now run fleet-wide (the [E, ...] pytree is
        # the transaction unit; docs/SEMANTICS.md §"Fleet recovery
        # contract"), so the only remaining mode rejection is the engine
        # selector above.
        # Validate the sweep BEFORE any supervision/backend work: a
        # malformed sweep must fail once in the parent, not crash-loop
        # supervised children.
        from shadow1_tpu.fleet.expand import load_sweep

        try:
            fleet_plan = load_sweep(args.config)
        except FleetConfigError as e:
            return _fleet_config_exit(e)
        if args.faults == "off":
            # Healthy-world A/B, fleet-shaped: strip every experiment's
            # fault schedule (including ones a vary[] entry added) exactly
            # like the solo path strips exp.faults; legacy per-group
            # stop_time churn stays, same as solo.
            for fexp in fleet_plan.exps:
                fexp.faults = None
    if args.ckpt and args.resume and args.windows is not None:
        # Under supervision --windows is the TOTAL for the whole run; under
        # --resume it means N MORE windows. Combining all three makes a
        # respawned child's remaining-window arithmetic ambiguous — refuse.
        ap.error("--ckpt with both --resume and --windows is ambiguous "
                 "(total or N-more?); drop one of them")
    if args.ckpt_keep < 1:
        ap.error("--ckpt-keep must be >= 1")
    if args.ckpt and not args.supervised_child:
        # Parent side of fault tolerance: never init the accelerator here —
        # all device work happens in supervised children.
        import os as _os

        watchdog_s = (args.watchdog_s if args.watchdog_s is not None
                      else float(_os.environ.get("SHADOW1_WATCHDOG_S", "0")))
        return _supervise(argv if argv is not None else sys.argv[1:],
                          args.ckpt, args.config, watchdog_s=watchdog_s)
    # Survive a dead/hanging accelerator backend. The CPU oracle needs jax
    # too (it mirrors the RNG streams), but never an accelerator — force
    # CPU directly and skip the probe cost.
    from shadow1_tpu.platform import ensure_live_platform, force_cpu

    if engine_kind == "cpu":
        force_cpu(1)
    else:
        ensure_live_platform(min_devices=1)
    from shadow1_tpu.log import SimLogger

    log = SimLogger(level=args.log_level)
    log.info("experiment loaded", hosts=exp.n_hosts, engine=engine_kind,
             window_ns=exp.window)
    t0 = time.perf_counter()
    metrics0: dict[str, int] = {}
    resume_path = None
    controller = None
    guard = None

    from shadow1_tpu import mem
    from shadow1_tpu.txn import CapacityExceededError

    def _memory_exit(e: mem.MemoryBudgetError) -> int:
        """Pre-flight budget rejection: full advice on stderr, one
        parseable JSON error record on stdout, the dedicated exit code the
        supervisor classifies as deterministic (no respawn) — the exact
        shape of the capacity-halt taxonomy (docs/SEMANTICS.md)."""
        print(f"MemoryBudgetError: {e}", file=sys.stderr, flush=True)
        print(json.dumps({
            "error": "memory_budget",
            "estimated": e.estimated,
            "budget": e.budget,
            "budget_source": e.budget_source,
            "planes": e.planes,
            "peaks": e.peaks,
            "advice": e.advice,
        }))
        return EXIT_MEMORY

    def _memory_exit_runtime(e, phase: str = "run") -> int:
        """Runtime OOM taxonomy: a RESOURCE_EXHAUSTED that slipped past
        the estimate (transients beyond the model, concurrent tenants)
        still exits structured — phase-tagged record on stdout, pointer at
        the estimator's advice on stderr, EXIT_MEMORY for the supervisor's
        deterministic classification."""
        phase = getattr(e, "shadow1_oom_phase", phase)
        print(f"[mem] device memory exhausted during {phase}: "
              f"{str(e)[:2000]}", file=sys.stderr, flush=True)
        print(f"[mem] deterministic config-vs-device condition — rerun "
              f"with --on-oom downshift, shrink the dominant plane (the "
              f"mem record above attributes bytes per plane), or probe "
              f"the feasible envelope: python -m "
              f"shadow1_tpu.tools.memprobe {args.config} --maxfit",
              file=sys.stderr, flush=True)
        print(json.dumps({
            "error": "memory_exhausted",
            "phase": phase,
            "message": str(e)[:500],
        }))
        return EXIT_MEMORY

    # ---- pre-flight memory budget (shadow1_tpu/mem.py) -------------------
    # Estimate the full device-byte footprint from the config ALONE (an
    # abstract trace — no state-sized allocation) and compare it against
    # the backend's reported memory before any compile is attempted. The
    # one parseable ``mem`` record per run feeds heartbeat_report's memory
    # section; the estimate failing soft (warning) can never block a
    # runnable config.
    sub_batch = None
    pre_downshift_retry = False  # retry demoted by a memory downshift
    if engine_kind != "cpu":
        import os as _osm

        n_exp = len(fleet_plan.exps) if args.fleet else 1
        n_dev = 1
        if engine_kind == "sharded":
            import jax as _jaxm

            n_dev = len(_jaxm.devices())
        est_exp = fleet_plan.exps[0] if args.fleet else exp
        budget, budget_src = mem.device_budget()
        mem_est = None
        try:
            mem_est = mem.estimate(est_exp, params, n_exp=n_exp,
                                   n_dev=n_dev)
        except Exception as est_err:  # noqa: BLE001 — estimator fails soft
            log.warning("memory estimate unavailable", error=repr(est_err))
        if mem_est is not None:
            print(json.dumps(mem_est.record(budget, budget_src,
                                            engine=engine_kind)),
                  file=sys.stderr, flush=True)
            if budget is not None and mem_est.peak_bytes > budget:
                if args.on_oom == "downshift":
                    try:
                        pre_downshift_retry = params.on_overflow == "retry"
                        # --save-state gates like --ckpt/--resume: a
                        # shrunk ring would write a snapshot no
                        # same-config engine could load back. Sub-batching
                        # DOES compose with --ckpt (per-batch snapshots +
                        # the batch cursor in the lineage manifest) but
                        # not with an explicit --resume/--save-state path,
                        # which has no cursor and no all-lane state.
                        params, sub_batch, ds_actions = mem.downshift(
                            est_exp, params, n_exp, budget, n_dev=n_dev,
                            resumable=bool(args.ckpt or args.resume
                                           or args.save_state),
                            subbatch_resumable=bool(
                                args.ckpt and not args.resume
                                and not args.save_state))
                    except mem.MemoryBudgetError as e:
                        return _memory_exit(e)
                    ds_est = mem.estimate(est_exp, params,
                                          n_exp=sub_batch or n_exp,
                                          n_dev=n_dev)
                    print(json.dumps(mem.downshift_record(
                        ds_actions, ds_est.peak_bytes, budget)),
                        file=sys.stderr, flush=True)
                    for a in ds_actions:
                        log.warning("memory downshift", **a)
                else:
                    try:
                        mem.check_budget(mem_est, budget, budget_src)
                    except mem.MemoryBudgetError as e:
                        return _memory_exit(e)
        if _osm.environ.get("SHADOW1_MEM_INJECT_OOM") == "raw":
            # Test hook for the supervisor's belt-and-braces stderr scan:
            # die like a crash the structured taxonomy never saw — raw
            # marker on stderr, generic nonzero exit.
            print("FATAL: RESOURCE_EXHAUSTED: injected raw OOM (test hook)",
                  file=sys.stderr, flush=True)
            raise SystemExit(1)

    def _capacity_exit(e: CapacityExceededError) -> int:
        """Structured halt: full advice on stderr, one parseable JSON error
        record on stdout, the dedicated exit code the supervisor classifies
        as deterministic (no respawn)."""
        print(f"CapacityExceededError: {e}", file=sys.stderr, flush=True)
        print(json.dumps({
            "error": "capacity_exceeded",
            "knob": e.knob,
            "counter": e.counter,
            "cap": e.cap,
            "overflow": e.overflow,
            "windows": list(e.window_range),
            "recommended": e.recommended,
        }))
        return EXIT_CAPACITY

    def _preempted_exit(e, resumed=False) -> int:
        """Graceful-drain exit: the in-flight chunk was committed (and the
        final snapshot written when the run carries --ckpt) before this
        point — print the parseable stdout record and exit the dedicated
        code the supervisor classifies as clean-resume (no backoff, no
        crash accounting; the preemption contract, docs/SEMANTICS.md)."""
        e.ckpt = args.ckpt  # the chunk runner below this layer doesn't know
        print(f"[preempt] drain complete after {e.signame}: "
              f"{e.done_windows} window(s) committed, "
              f"sim_ns={e.win_start}"
              + (f", snapshot {args.ckpt}" if args.ckpt
                 else ", no checkpoint path"),
              file=sys.stderr, flush=True)
        print(json.dumps({
            "preempted": True,
            "signal": e.signame,
            "windows_done": e.done_windows,
            "win_start": e.win_start,
            "ckpt": args.ckpt,
            "resumed": bool(resumed),
        }))
        return EXIT_PREEMPTED

    if args.fleet:
        from shadow1_tpu.fleet.expand import FleetConfigError

        try:
            return _fleet_main(args, params, fleet_plan, log, t0,
                               _capacity_exit, _preempted_exit,
                               _memory_exit_runtime, sub_batch=sub_batch,
                               auto_caps=auto_caps,
                               pre_downshift_retry=pre_downshift_retry)
        except FleetConfigError as e:
            # Late rejections (FleetEngine construction) use the same
            # structured exit as the early validation block above.
            return _fleet_config_exit(e)

    if engine_kind == "cpu":
        from shadow1_tpu.cpu_engine import CpuEngine

        if auto_caps:
            # Config-level engine.auto_caps follows the engine.metrics_ring
            # precedent — inert on the oracle (so a shared config still runs
            # under --engine cpu) — but say so; the explicit --auto-caps
            # flag errors above like every other batched-only flag.
            log.warning("engine.auto_caps ignored: the cpu oracle runs "
                        "eagerly per event, there is no chunked window loop "
                        "to retune")
        if params.on_overflow == "retry":
            # Same precedent: config-level retry stays inert on the oracle
            # (it cannot re-run a window); the explicit flag errors above.
            log.warning("engine.on_overflow=retry ignored: the eager cpu "
                        "oracle cannot replay a window; halt and "
                        "--selfcheck apply as boundary checks")
        eng = CpuEngine(exp, params)
        try:
            metrics = eng.run(n_windows=args.windows)
        except CapacityExceededError as e:
            return _capacity_exit(e)
        summary = eng.summary()
        n_windows = args.windows if args.windows is not None else eng.n_windows
        if params.state_digest:
            # The oracle's per-window digest stream (REC_DIGEST rows) — the
            # comparand for the batched engines' ring dg_* columns.
            for rec in eng.digest_rows:
                print(json.dumps(rec), file=sys.stderr)
        if eng.work_rows:
            # The oracle's per-window wasted-work stream (REC_WORK rows) —
            # the comparand for the batched engines' RING_WORK columns.
            # Enabled by a config-level engine.metrics_ring (the --metrics-
            # ring FLAG stays batched-only: there is no on-device ring
            # here, only its per-window mirror).
            for rec in eng.work_rows:
                print(json.dumps(rec), file=sys.stderr)
        if eng.probe_rows:
            # The oracle's per-window flow-probe stream (REC_FLOW rows) —
            # the comparand for the batched engines' probe-ring records
            # (--watch works here directly: no ring needed, the oracle
            # samples the boundary state straight into rows).
            for rec in eng.probe_rows:
                print(json.dumps(rec), file=sys.stderr)
        if eng.link_rows:
            # The oracle's cumulative per-edge link stream (REC_LINK rows)
            # — the comparand for the batched engines' link records.
            for rec in eng.link_rows:
                print(json.dumps(rec), file=sys.stderr)
    else:
        import jax

        if engine_kind == "sharded":
            from shadow1_tpu.shard.engine import ShardedEngine as Eng
        else:
            from shadow1_tpu.core.engine import Engine as Eng
        try:
            eng = Eng(exp, params)
        except Exception as e:
            # Engine construction allocates the ctx constants (and the
            # restart capture) — an exhaustion here maps to the memory
            # taxonomy like everything later.
            if mem.is_oom(e):
                return _memory_exit_runtime(e, phase="init")
            raise
        st = None
        # A --ckpt snapshot on disk wins over --resume: it is the newer
        # state a supervised respawn must continue from. The lineage
        # resolve walks head → older generations and lands on the newest
        # one that passes its integrity digest (discarding corrupt newer
        # ones so they can never rotate back into the set).
        import os

        resolved, ckpt_lineage, resume_path = _resolve_ckpt_lineage(args, log)
        if resume_path:
            from shadow1_tpu.ckpt import (
                CorruptCheckpointError,
                load_state,
                snapshot_caps,
            )

            params0, eng0 = params, eng
            try:
                template = eng.init_state()
                if (auto_caps or params.on_overflow == "retry"
                        or pre_downshift_retry):
                    # pre_downshift_retry: snapshots from BEFORE a memory
                    # downshift demoted retry→halt may still carry
                    # retry-grown caps — keep the cap-migration path
                    # alive across the demotion.
                    # An --auto-caps run checkpoints at whatever cap it had
                    # grown to — and so does an --on-overflow retry run
                    # (retry-driven grows stick); a host may hold more
                    # events than the config's static cap, so the respawned
                    # engine must START at the snapshot's caps (the
                    # controller re-shrinks later if the occupancy allows)
                    # — otherwise every respawn would die in the
                    # shrink-refuses-to-drop-events check.
                    snap = snapshot_caps(template, resume_path)
                    if snap and snap != (params.ev_cap, params.outbox_cap):
                        import dataclasses

                        params = dataclasses.replace(
                            params, ev_cap=snap[0], outbox_cap=snap[1])
                        eng = Eng(exp, params)
                        template = eng.init_state()
                st = load_state(template, resume_path)
            except CorruptCheckpointError as e:
                # Supervised child: a damaged snapshot must not crash-loop
                # the respawn budget — fall back to a fresh start (the
                # supervisor pre-verifies too; this covers corruption in
                # between, at no extra hashing on the healthy path). An
                # explicit --resume keeps failing loudly instead.
                if resolved is None:
                    raise
                log.warning("discarding corrupt checkpoint",
                            path=resume_path, reason=str(e))
                st, params, eng = None, params0, eng0
                resume_path, resolved = None, None
            else:
                metrics0 = Eng.metrics_dict(st)
                done = int(st.win_start) // exp.window
                if resolved is not None:
                    _emit_resume_record(args.ckpt, resolved,
                                        int(st.win_start), ckpt_lineage)
                if args.windows is None:
                    # Complete the configured run: only the windows
                    # remaining after the checkpoint, not n_windows again
                    # on top of it.
                    args.windows = max(eng.n_windows - done, 0)
                elif resolved is not None:
                    # Supervised respawn: --windows is the TOTAL for the
                    # whole supervised run, not N more on top of the
                    # snapshot.
                    args.windows = max(args.windows - done, 0)
        import contextlib

        prof = (jax.profiler.trace(args.profile) if args.profile
                else contextlib.nullcontext())
        phases = None
        if args.trace or args.profile:
            from shadow1_tpu.telemetry import PhaseProfiler

            phases = PhaseProfiler()
        ring_w = params.metrics_ring
        if auto_caps:
            from shadow1_tpu.tune import CapController

            controller = CapController(eng, lambda p: Eng(exp, p),
                                       log=log.info, initial_state=st)
        if params.on_overflow in ("retry", "halt"):
            from shadow1_tpu.txn import OverflowGuard

            # Shares the controller's engine cache when --auto-caps is on,
            # and reports retry-driven grows to its lossless floor — the
            # two planes never double-grow or oscillate (tune/autocap.py).
            guard = OverflowGuard(eng, make_engine=lambda p: Eng(exp, p),
                                  mode=params.on_overflow,
                                  controller=controller, log=log.info)
        from shadow1_tpu.preempt import DrainHandler, PreemptedExit

        try:
            with prof:
                if os.environ.get("SHADOW1_MEM_INJECT_OOM") == "run":
                    raise RuntimeError(
                        "RESOURCE_EXHAUSTED: injected (test hook)")
                # phases covers --profile too: its phases.trace.json must
                # carry real spans, so any profiled run routes through the
                # instrumented chunk runner. --auto-caps needs the chunked
                # path too (resizes happen at chunk boundaries), as do the
                # overflow policy and --selfcheck (both are chunk-boundary
                # checks).
                if (args.heartbeat or args.ckpt or ring_w
                        or params.link_telem
                        or phases is not None or controller is not None
                        or guard is not None or params.selfcheck):
                    from shadow1_tpu.obs import run_with_heartbeat

                    # Signal plane: SIGTERM/SIGINT request a graceful
                    # drain, honored at the next chunk boundary (only the
                    # chunked path has boundaries to drain at — a plain
                    # eng.run keeps the default die-on-signal behavior).
                    drain = DrainHandler().install()
                    st, _hb = run_with_heartbeat(
                        eng, st, n_windows=args.windows,
                        # Ring-only runs chunk at the ring depth so the
                        # drain keeps up with the overwrites: gap-free
                        # per-window records without --heartbeat.
                        every_windows=args.heartbeat or (ring_w or None),
                        # --ckpt/--trace without --heartbeat chunk the run
                        # but emit no heartbeat lines; ring records always
                        # flow when the ring is on.
                        stream=None if (args.heartbeat or ring_w
                                        or params.link_telem) else False,
                        ckpt_path=args.ckpt, ckpt_every_s=args.ckpt_every_s,
                        profiler=phases,
                        emit_heartbeat=bool(args.heartbeat),
                        emit_ring=bool(ring_w or params.link_telem),
                        controller=controller,
                        guard=guard,
                        selfcheck=bool(params.selfcheck),
                        ckpt_keep=args.ckpt_keep,
                        drain=drain,
                    )
                else:
                    st = eng.run(st, n_windows=args.windows)
                jax.block_until_ready(st)
        except CapacityExceededError as e:
            return _capacity_exit(e)
        except PreemptedExit as e:
            return _preempted_exit(e, resumed=bool(resume_path))
        except Exception as e:
            # Runtime OOM taxonomy: the compile warmup tags its own phase
            # (obs.py); anything later is a run-time allocation failure.
            if mem.is_oom(e):
                return _memory_exit_runtime(e)
            raise
        if phases is not None:
            if args.trace:
                phases.write(args.trace)
            if args.profile:
                os.makedirs(args.profile, exist_ok=True)
                phases.write(os.path.join(args.profile, "phases.trace.json"))
        if args.save_state:
            from shadow1_tpu.ckpt import save_state

            save_state(st, args.save_state)
        # Close the memory loop: when the backend reports its measured
        # allocation high-water (TPU/GPU memory_stats), one final mem
        # record pairs it with the pre-flight estimate —
        # heartbeat_report's memory section prints estimated vs reported.
        peak_in_use = mem.device_peak_in_use()
        if peak_in_use is not None:
            print(json.dumps({
                "type": "mem", "event": "final",
                "peak_in_use": peak_in_use,
                "estimated_peak": (mem_est.peak_bytes
                                   if mem_est is not None else None),
            }), file=sys.stderr, flush=True)
        metrics = Eng.metrics_dict(st)
        summary = eng.model_summary(st)
        n_windows = args.windows if args.windows is not None else eng.n_windows
        if args.tracker:
            from shadow1_tpu.log import tracker_records

            with open(args.tracker, "w") as f:
                for rec in tracker_records(eng, st):
                    f.write(json.dumps(rec) + "\n")

    wall = time.perf_counter() - t0
    sim_s = n_windows * exp.window / 1e9
    if guard is not None:
        # The retry plane's host-side counters ride the same metrics
        # namespace (telemetry.registry HOST_FIELDS).
        metrics = {**metrics, "chunk_retries": guard.chunk_retries,
                   "retry_windows_rerun": guard.retry_windows_rerun}
    # Rates cover THIS invocation: under --resume, cumulative checkpointed
    # metrics are baselined out.
    ev_run = metrics["events"] - metrics0.get("events", 0)
    out = {
        "engine": engine_kind,
        "hosts": exp.n_hosts,
        "window_ns": exp.window,
        "windows": n_windows,
        "sim_seconds": round(sim_s, 6),
        "wall_seconds": round(wall, 3),
        "sim_per_wall": round(sim_s / wall, 3) if wall > 0 else None,
        "events_per_sec": round(ev_run / wall, 1) if wall > 0 else None,
        "resumed": bool(resume_path),
        # The caps the run STARTED at — with metrics.ev_max_fill etc. this
        # is what tools/captune.py computes over-provisioning factors from.
        "caps": {
            "ev_cap": params.ev_cap,
            "outbox_cap": params.outbox_cap,
            "compact_cap": params.compact_cap,
        },
        "metrics": {k: int(v) for k, v in metrics.items()},
    }
    # Drop accounting, grouped by reason (telemetry.registry.DROP_FIELDS) —
    # the same structured block heartbeat records carry, with run totals.
    from shadow1_tpu.telemetry.registry import DROP_FIELDS

    drops = {f: int(metrics.get(f, 0)) for f in DROP_FIELDS}
    out["drops"] = {"total": sum(drops.values()), **drops}
    # Wasted-work accounting run totals (performance attribution plane):
    # the per-window boundary samples summed over the run, with the
    # denominators for utilization fractions — the heartbeat ``work`` block
    # with run scope (tools/heartbeat_report.py reads n_hosts from here).
    work = {f: int(metrics.get(f, 0))
            for f in ("active_hosts", "elig_events", "outbox_hosts")}
    n_win_total = int(metrics.get("windows", 0))
    if any(work.values()):
        out["work"] = {**work, "n_hosts": exp.n_hosts}
        if n_win_total:
            out["work"]["active_frac"] = round(
                work["active_hosts"] / (n_win_total * exp.n_hosts), 6)
    # Fault plane run totals (schema mirrors the heartbeat ``faults`` block).
    restarts = int(metrics.get("host_restarts", 0))
    fault_drops = {k: drops[k] for k in
                   ("down_events", "down_pkts", "link_down_pkts")}
    if restarts or any(fault_drops.values()):
        out["faults"] = {"host_restarts": restarts, **fault_drops}
    if guard is not None:
        # Run totals of the transactional plane: always present under a
        # non-drop policy (chunk_retries == 0 is the explicit "no chunk
        # was ever tainted" signal), with the per-retry audit log.
        out["retries"] = {**guard.report(), "resizes": guard.resizes}
    if controller is not None:
        out["auto_caps"] = {
            "resizes": controller.resizes,
            "final": controller.final_caps or {"ev_cap": params.ev_cap,
                                               "outbox_cap": params.outbox_cap},
        }
    if args.summary:
        out["summary"] = {
            k: int(v) for k, v in summary.items()
            if getattr(v, "ndim", 1) == 0 or isinstance(v, (int, float))
        }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
