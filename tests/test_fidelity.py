"""Fidelity-knob parity: jitter, churn, virtual CPU, finite NIC queues.

Each knob is exercised with a value that actually fires (jitter shifts
arrivals, hosts stop mid-run, CPU busy-time defers executions, queues
drop), and the batched engine must still match the CPU oracle exactly —
the knobs are deterministic model features, not noise (SURVEY §5 fault
injection; reference: topology edge jitter, config churn,
src/main/routing/router.c drop-tail, src/main/host/cpu.c).
"""

import numpy as np

from shadow1_tpu.config.compiled import NO_STOP, single_vertex_experiment
from shadow1_tpu.consts import MS, SEC, EngineParams
from shadow1_tpu.core.engine import Engine
from shadow1_tpu.cpu_engine import CpuEngine

KEYS = [
    "events", "pkts_sent", "pkts_delivered", "pkts_lost",
    "ev_overflow", "ob_overflow", "down_events", "down_pkts",
    "nic_tx_drops", "nic_rx_drops", "nic_aqm_drops",
]


def _both(exp, params=None):
    params = params or EngineParams()
    cm = CpuEngine(exp, params).run()
    st = Engine(exp, params).run()
    tm = Engine.metrics_dict(st)
    for k in KEYS:
        assert tm[k] == cm[k], (k, tm[k], cm[k])
    return tm


def _phold(n=64, end=80 * MS, **kw):
    return single_vertex_experiment(
        n_hosts=n, seed=5, end_time=end, latency_ns=2 * MS,
        model="phold",
        model_cfg={"mean_delay_ns": float(3 * MS), "init_events": 2}, **kw,
    )


def test_jitter_parity():
    base = _phold()
    jit = _phold(jitter_ns=1 * MS)
    assert jit.window == 1 * MS and base.window == 2 * MS  # runahead shrinks
    m0 = _both(base)
    m1 = _both(jit)
    assert m1["events"] > 0 and m1["events"] != m0["events"]  # jitter acted


def test_churn_stop_time_parity():
    stop = np.full(64, NO_STOP, np.int64)
    stop[::2] = 30 * MS  # half the hosts die mid-run
    m = _both(_phold(stop_time=stop))
    assert m["down_events"] > 0
    assert m["down_pkts"] > 0
    mfull = _both(_phold())
    assert m["events"] < mfull["events"]


def test_virtual_cpu_parity():
    cost = np.zeros(64, np.int64)
    cost[:32] = 500_000  # 0.5 ms of virtual CPU per event on half the hosts
    m = _both(_phold(cpu_ns_per_event=cost))
    mfree = _both(_phold())
    # busy hosts serialize their events: fewer hops fit in the same sim time
    assert m["events"] < mfree["events"]


def _filexfer(n=6, qlen=0):
    role = np.full(n, 1, np.int64)
    role[0] = 0
    fid = {}
    if qlen:
        fid = {"tx_qlen_bytes": np.full(n, qlen, np.int64),
               "rx_qlen_bytes": np.full(n, qlen, np.int64)}
    return single_vertex_experiment(
        n_hosts=n, seed=9, end_time=30 * SEC, latency_ns=10 * MS,
        bw_bits=10**6, model="net",
        model_cfg={
            "app": "filexfer",
            "role": role,
            "server": np.zeros(n, np.int64),
            "flow_bytes": np.full(n, 60_000, np.int64),
            "start_time": np.full(n, 1 * MS, np.int64),
            "flow_count": np.where(role == 1, 1, 0),
        },
        **fid,
    )


def test_nic_queue_drops_parity():
    """Tiny drop-tail NIC queues on a slow shared server link: drops fire,
    engines agree exactly, and TCP retransmission still completes flows."""
    params = EngineParams(ev_cap=256)
    m = _both(_filexfer(qlen=3000), params)
    assert m["nic_tx_drops"] + m["nic_rx_drops"] > 0
    eng = Engine(_filexfer(qlen=3000), params)
    st = eng.run()
    s = eng.model_summary(st)
    assert int(s["total_flows_done"]) == 5  # all flows survive the drops


def test_red_aqm_parity():
    """RED early-drop on the uplink (router.c upstream AQM): with thresholds
    well inside the congested server's backlog, probabilistic drops fire —
    and the coin is the shared counter RNG, so both engines drop the exact
    same packets. Flows still complete via retransmission."""
    params = EngineParams(ev_cap=256)
    m = _both(_red_exp(), params)
    assert m["nic_aqm_drops"] > 0, m
    # RED alone (no drop-tail): the tail counter must stay untouched.
    assert m["nic_tx_drops"] == 0 and m["nic_rx_drops"] == 0

    eng = Engine(_filexfer(), params)
    base = Engine.metrics_dict(eng.run())
    assert base["nic_aqm_drops"] == 0  # off by default
    eng3 = Engine(_red_exp(), params)
    s = eng3.model_summary(eng3.run())
    assert int(s["total_flows_done"]) == 5  # flows survive RED drops


def _red_exp(n=6):
    exp = _filexfer(n)
    exp.aqm_min_bytes = np.full(n, 2_000, np.int64)
    exp.aqm_max_bytes = np.full(n, 12_000, np.int64)
    exp.aqm_pmax = np.full(n, 0.3, np.float64)
    return exp
