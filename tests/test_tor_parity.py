"""Tor circuit-model parity: batched engine vs CPU oracle (BASELINE 3/4).

A small Tor net: weighted relays (guard/exit subsets), dirauths serving the
consensus, clients bootstrapping then building telescoped circuits and
streaming through them. Parity must be exact: same circuits, same cells,
same completion times — including under loss.
"""

import numpy as np
import pytest

from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import MS, SEC, EngineParams
from tests.parity import assert_parity, run_both

TOR_KEYS = (
    "streams_done", "cells_rx", "bootstrap_time", "done_time",
    "cells_fwd", "ct_overflow", "cell_retries",
)


def tor_exp(seed=31, loss=0.0, end=30 * SEC, n_circuits=2, n_streams=2,
            mean_cells=20.0, bw=10**7):
    n = 24
    role = np.full(n, 1, np.int64)          # clients by default
    role[0:8] = 0                           # 8 relays
    role[8:10] = 2                          # 2 dirauths
    role[22:24] = 3                         # 2 idle
    is_guard = np.zeros(n, bool)
    is_guard[0:3] = True
    is_exit = np.zeros(n, bool)
    is_exit[5:8] = True
    weight = np.zeros(n, np.int64)
    weight[0:8] = 100 + 10 * np.arange(8)
    return single_vertex_experiment(
        n_hosts=n,
        seed=seed,
        end_time=end,
        latency_ns=10 * MS,
        loss=loss,
        bw_bits=bw,
        model="net",
        model_cfg={
            "app": "tor",
            "role": role,
            "relay_weight": weight,
            "is_guard": is_guard,
            "is_exit": is_exit,
            "n_circuits": np.where(role == 1, n_circuits, 0),
            "n_streams": np.full(n, n_streams, np.int64),
            "mean_stream_cells": np.full(n, mean_cells, np.float64),
            "mean_think_ns": np.full(n, 100 * MS, np.float64),
            "start_time": np.full(n, 1 * MS, np.int64),
            "ct_cap": 64,
        },
    )


PARAMS = EngineParams(ev_cap=256, sockets_per_host=32)


def test_tor_circuits_parity_fast():
    """Tier-1 wall sibling (PR 9 budget pass): one circuit and one stream
    per client on a shorter horizon — the same full bootstrap → telescope →
    stream → completion parity contract as the slow original below."""
    exp = tor_exp(end=20 * SEC, n_circuits=1, n_streams=1, mean_cells=10.0)
    cm, cs, tm, ts = run_both(exp, PARAMS)
    n_clients = 12
    assert int(ts["clients_done"]) == n_clients
    assert int(ts["total_streams_done"]) == n_clients * 1 * 1
    assert int(ts["total_cells_rx"]) > 0
    assert int(ts["total_cells_fwd"]) > 0
    assert int(ts["total_ct_overflow"]) == 0
    assert_parity(cm, cs, tm, ts, keys=TOR_KEYS)


@pytest.mark.slow  # tier-1 wall budget (PR 9): the full 2-circuit/2-stream
# matrix; the fast sibling above keeps the contract in the fast tier.
def test_tor_circuits_parity():
    exp = tor_exp()
    cm, cs, tm, ts = run_both(exp, PARAMS)
    n_clients = 12
    # Every client bootstraps and completes all circuits/streams.
    assert int(ts["clients_done"]) == n_clients
    assert int(ts["total_streams_done"]) == n_clients * 2 * 2
    assert int(ts["total_cells_rx"]) > 0
    assert int(ts["total_cells_fwd"]) > 0
    assert int(ts["total_ct_overflow"]) == 0
    assert_parity(cm, cs, tm, ts, keys=TOR_KEYS)


@pytest.mark.slow  # tier-1 wall budget (PR 4): heaviest of its family;
# a faster sibling keeps the coverage in the fast tier; ./ci.sh all runs it.
def test_tor_under_loss_parity():
    exp = tor_exp(seed=5, loss=0.01, end=60 * SEC)
    cm, cs, tm, ts = run_both(exp, PARAMS)
    assert int(ts["clients_done"]) == 12
    assert tm["tcp_rto"] + tm["tcp_fast_rtx"] > 0
    assert_parity(cm, cs, tm, ts, keys=TOR_KEYS)
