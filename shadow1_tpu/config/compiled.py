"""Compiled experiment artifact — the common input to both engines.

The reference parses an XML experiment file plus a GraphML topology at
startup (src/main/core/support/configuration.c, src/main/routing/topology.c)
and builds igraph structures queried lazily. We instead *compile* the
experiment on the host into dense numpy tensors once; both the CPU oracle
engine and the TPU engine consume this identical artifact, which is the
cross-validation seam mandated by BASELINE.json ("CPU and TPU engines are
selected from the same config file").

Topology representation: Tor/Bitcoin experiment graphs have few *network*
vertices (points of presence) with many attached hosts, so we precompute
all-pairs shortest-path latency/loss over vertices (SURVEY §7.1) and keep a
host→vertex attachment vector. lat_vv must be strictly positive everywhere:
its minimum IS the conservative window (the reference computes the same
runahead bound from minimum link latency in src/main/core/master.c).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np


@dataclasses.dataclass
class CompiledExperiment:
    n_hosts: int
    seed: int
    end_time: int                 # ns
    lat_vv: np.ndarray            # i64 [V,V] path latency ns, all > 0
    loss_vv: np.ndarray           # f32 [V,V] end-to-end path loss prob
    host_vertex: np.ndarray       # i32 [H] vertex each host attaches to
    bw_up: np.ndarray             # i64 [H] uplink bits/s
    bw_dn: np.ndarray             # i64 [H] downlink bits/s
    model: str = "phold"          # workload model name
    model_cfg: dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def window(self) -> int:
        """Conservative lookahead window = min path latency (runahead)."""
        return int(self.lat_vv.min())

    def validate(self) -> None:
        assert self.lat_vv.min() > 0, "zero-latency paths break the conservative window"
        assert self.lat_vv.shape == self.loss_vv.shape
        assert self.host_vertex.max() < self.lat_vv.shape[0]
        assert (self.bw_up > 0).all() and (self.bw_dn > 0).all()
        assert self.end_time > 0


def single_vertex_experiment(
    n_hosts: int,
    seed: int,
    end_time: int,
    latency_ns: int,
    loss: float = 0.0,
    bw_bits: int = 10**9,
    model: str = "phold",
    model_cfg: dict | None = None,
) -> CompiledExperiment:
    """Minimal topology: every host on one vertex, uniform latency/loss.

    Mirrors the reference's minimal example configs (resource/examples/).
    """
    return CompiledExperiment(
        n_hosts=n_hosts,
        seed=seed,
        end_time=end_time,
        lat_vv=np.full((1, 1), latency_ns, np.int64),
        loss_vv=np.full((1, 1), loss, np.float32),
        host_vertex=np.zeros(n_hosts, np.int32),
        bw_up=np.full(n_hosts, bw_bits, np.int64),
        bw_dn=np.full(n_hosts, bw_bits, np.int64),
        model=model,
        model_cfg=model_cfg or {},
    )
