"""Per-PR perf regression gate — the BENCH trajectory, enforced, per row.

    python -m shadow1_tpu.tools.benchgate            # gate vs BENCH_GATE.json
    python -m shadow1_tpu.tools.benchgate --update   # re-baseline (this backend)
    python -m shadow1_tpu.tools.benchgate --rows phold_smoke

The telemetry ring and phase profiler RECORD everything, but until PR 8
nothing ENFORCED the perf trajectory (ROADMAP item 5) — and the PR 8 gate
watched a single dense smoke PHOLD row, so the sparse TCP rounds the
ROADMAP most wants to speed up (and fleet mode's batched economics) could
regress silently. The gate now carries three rows:

* ``phold_smoke``  — dense PHOLD (bench.py's smoke shape): the per-round
  fixed cost that is the paper's whole economics;
* ``sparse_rung1`` — the rung-1 filexfer config: sparse TCP-heavy rounds,
  the regime ROADMAP item 1's bucketed-queue/megafusion work targets —
  any step of that rewrite shows up here per-PR;
* ``fleet_smoke``  — the configs/sweep_phold.yaml 4-lane sweep as one
  vmapped program: the fleet axis's per-round cost (ROADMAP items 2–3).

Each row gates **ms per inner round** (minimum over timed chunks, after a
full compile warmup — stable on a shared container where means are not)
against the committed ``BENCH_GATE.json``. Baselines are recorded PER
BACKEND per row: a TPU baseline coexists with the committed CPU one
instead of a backend mismatch auto-skipping the gate entirely — on either
backend, rows with a matching baseline gate and the others report
``no_baseline_for_backend`` (commit one from that machine with --update,
which merges: other backends' entries are preserved).

* measured > baseline × (1 + tolerance) → exit 1 (the gate fails CI);
* intentional trade-off? the one-line override:
  ``SHADOW1_BENCH_GATE_ACCEPT="why" ./ci.sh smoke`` turns the failure
  into a warning — then commit the new baseline with ``--update``;
* a big improvement prints a reminder to re-baseline (non-fatal —
  ratchets tighten deliberately, not by timing luck).

Tolerance rides per row in the baseline file so a noisy row can be
widened deliberately without loosening the others.

Always prints exactly one JSON line on stdout (the bench.py contract).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

BASELINE = os.path.join(os.path.dirname(__file__), "..", "..",
                        "BENCH_GATE.json")

# bench.py's CPU-smoke shape — big enough that the round path dominates,
# small enough for seconds of CI wall.
N_HOSTS = 2048
CHUNK = 20
N_CHUNKS = 4
TOLERANCE = 0.05
ACCEPT_ENV = "SHADOW1_BENCH_GATE_ACCEPT"

SPARSE_CONFIG = "configs/rung1_filexfer.yaml"
FLEET_CONFIG = "configs/sweep_phold.yaml"
_REPO = os.path.join(os.path.dirname(__file__), "..", "..")


def host_fingerprint() -> str:
    """CPU model + logical core count — a wall-clock baseline only gates
    meaningfully on the machine class it was measured on."""
    model = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("model name"):
                    model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    return f"{model} x{os.cpu_count()}"


def _time_chunks(eng, st, n_chunks: int, chunk: int,
                 rounds_of) -> tuple[list, list, object]:
    """(walls, rounds-per-chunk, final state) of ``n_chunks`` timed chunks."""
    import jax

    walls, rounds = [], []
    for _ in range(n_chunks):
        r0 = rounds_of(st)
        t0 = time.perf_counter()
        st = eng.run(st, n_windows=chunk)
        jax.block_until_ready(st)
        walls.append(time.perf_counter() - t0)
        rounds.append(rounds_of(st) - r0)
    return walls, rounds, st


def _best_row(walls, rounds) -> tuple[int, float]:
    """Gate on the minimum PER-ROUND cost, not the minimum-wall chunk: a
    chunk can post the smallest wall simply by running fewer rounds."""
    best = min(range(len(walls)), key=lambda i: walls[i] / max(rounds[i], 1))
    return best, walls[best] * 1000 / max(rounds[best], 1)


def measure_phold_smoke() -> dict:
    import jax

    from shadow1_tpu.config.compiled import single_vertex_experiment
    from shadow1_tpu.consts import MS, EngineParams
    from shadow1_tpu.core.engine import Engine

    exp = single_vertex_experiment(
        n_hosts=N_HOSTS, seed=1234, end_time=(N_CHUNKS + 1) * CHUNK * MS,
        latency_ns=1 * MS, model="phold",
        model_cfg={"mean_delay_ns": float(2 * MS), "init_events": 16},
    )
    eng = Engine(exp, EngineParams(ev_cap=48, outbox_cap=24,
                                   max_rounds=128))
    t0 = time.perf_counter()
    st = eng.init_state()
    jax.block_until_ready(eng.run(st, n_windows=CHUNK))
    compile_wall = time.perf_counter() - t0

    def rounds_of(s):
        return int(s.metrics.rounds)

    walls, rounds, st = _time_chunks(eng, st, N_CHUNKS, CHUNK, rounds_of)
    best, ms = _best_row(walls, rounds)
    return {
        "metric": "phold_smoke_ms_per_round",
        "ms_per_round": round(ms, 4),
        "hosts": N_HOSTS,
        "chunk_windows": CHUNK,
        "chunks_timed": N_CHUNKS,
        "rounds_per_chunk": rounds[best],
        "events": int(st.metrics.events),
        "compile_wall_s": round(compile_wall, 3),
        "chunk_walls_s": [round(w, 4) for w in walls],
    }


def measure_sparse_rung1() -> dict:
    """The sparse TCP row: rung-1 filexfer, the op-count-bound round regime
    (docs/R6_NOTES.md). Few hosts, many rounds/window — ms/round here is
    pure round-body cost, the number ROADMAP item 1 attacks."""
    import jax

    from shadow1_tpu.config.experiment import load_experiment
    from shadow1_tpu.core.engine import Engine

    exp, params, _ = load_experiment(os.path.join(_REPO, SPARSE_CONFIG))
    eng = Engine(exp, params)
    # Short chunks, MANY of them: each timed chunk is only ~0.1 s of wall
    # on this config, so single-sample noise is large — the min over 8
    # samples is what's stable run-to-run (measured ±1.5% vs ±20% for the
    # raw samples on the shared container).
    chunk, n_chunks = 30, 8
    t0 = time.perf_counter()
    st = eng.init_state()
    jax.block_until_ready(eng.run(st, n_windows=chunk))
    compile_wall = time.perf_counter() - t0

    def rounds_of(s):
        return int(s.metrics.rounds)

    walls, rounds, st = _time_chunks(eng, st, n_chunks, chunk, rounds_of)
    best, ms = _best_row(walls, rounds)
    return {
        "metric": "sparse_rung1_ms_per_round",
        "config": SPARSE_CONFIG,
        "ms_per_round": round(ms, 4),
        "hosts": exp.n_hosts,
        "chunk_windows": chunk,
        "chunks_timed": n_chunks,
        "rounds_per_chunk": rounds[best],
        "events": int(st.metrics.events),
        "compile_wall_s": round(compile_wall, 3),
        "chunk_walls_s": [round(w, 4) for w in walls],
    }


def measure_fleet_smoke() -> dict:
    """The fleet row: the 4-lane sweep_phold sweep as ONE vmapped program.
    ms/round over the aggregate (all-lane) round count — the batched
    economics fleet mode exists for (BENCH_r06)."""
    import jax

    from shadow1_tpu.fleet.engine import FleetEngine
    from shadow1_tpu.fleet.expand import load_sweep

    plan = load_sweep(os.path.join(_REPO, FLEET_CONFIG))
    eng = FleetEngine(plan.exps, plan.params, plan.max_rounds)
    # Short chunks, many min samples — same noise discipline as the sparse
    # row (each timed chunk is ~0.1 s of wall).
    chunk, n_chunks = 8, 8
    t0 = time.perf_counter()
    st = eng.init_state()
    jax.block_until_ready(eng.run(st, n_windows=chunk))
    compile_wall = time.perf_counter() - t0

    import numpy as np

    def rounds_of(s):
        return int(np.asarray(s.metrics.rounds).sum())

    walls, rounds, st = _time_chunks(eng, st, n_chunks, chunk, rounds_of)
    best, ms = _best_row(walls, rounds)
    return {
        "metric": "fleet_smoke_ms_per_round",
        "config": FLEET_CONFIG,
        "ms_per_round": round(ms, 4),
        "experiments": len(plan.exps),
        "hosts": plan.exps[0].n_hosts,
        "chunk_windows": chunk,
        "chunks_timed": n_chunks,
        "rounds_per_chunk": rounds[best],
        "events": int(np.asarray(st.metrics.events).sum()),
        "compile_wall_s": round(compile_wall, 3),
        "chunk_walls_s": [round(w, 4) for w in walls],
    }


ROWS = {
    "phold_smoke": measure_phold_smoke,
    "sparse_rung1": measure_sparse_rung1,
    "fleet_smoke": measure_fleet_smoke,
}

# Per-row default tolerances written at --update time. The sparse rung-1
# row times ~0.4 ms/round on a 2-host config — small enough that shared-
# container scheduling state moves even the min-of-8 several percent
# run-to-run with no code change (observed spread 0.369–0.397), so it
# gates at 15% deliberately: the regressions this row exists to catch
# (ROADMAP item 1's round-body rewrites) are multiples, not percents.
# The fleet row's ~0.1 s chunks get the same treatment at 10%. The dense
# row (3+ s chunks, stable) holds the tight 5% ratchet.
ROW_TOLERANCE = {"sparse_rung1": 0.15, "fleet_smoke": 0.10}


def gate_row(name: str, row: dict, base_entry: dict | None,
             host: str, accept: str | None) -> dict:
    """One row's verdict dict (pure — unit-tested without measuring).
    ``base_entry`` is the baseline for THIS backend (already selected), or
    None when that backend has no committed baseline yet."""
    if base_entry is None:
        return {**row, "gate": "no_baseline_for_backend",
                "hint": f"commit one from this machine: python -m "
                        f"shadow1_tpu.tools.benchgate --update "
                        f"--rows {name}"}
    if base_entry.get("host") and base_entry["host"] != host:
        # A wall-clock baseline from another CPU would fail every PR on a
        # slower box (or wave real regressions through on a faster one)
        # with no code change at all. Re-baseline per machine.
        return {**row, "gate": "skipped_host_mismatch",
                "baseline_host": base_entry["host"]}
    tol = float(base_entry.get("tolerance", TOLERANCE))
    ref = float(base_entry["ms_per_round"])
    ratio = row["ms_per_round"] / ref if ref else 1.0
    verdict = {**row, "baseline_ms_per_round": ref,
               "ratio": round(ratio, 4), "tolerance": tol}
    if ratio > 1 + tol:
        if accept:
            return {**verdict, "gate": "accepted", "reason": accept}
        return {**verdict, "gate": "failed"}
    if ratio < 1 - 2 * tol:
        verdict["note"] = "improvement — consider re-baselining (--update)"
    return {**verdict, "gate": "ok"}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="shadow1_tpu.tools.benchgate")
    ap.add_argument("--update", action="store_true",
                    help="write the measured rows as the committed baseline "
                         "for THIS backend (BENCH_GATE.json; other "
                         "backends' entries are preserved)")
    ap.add_argument("--rows", default=None,
                    help="comma-separated row subset (default: all of "
                         f"{','.join(ROWS)})")
    ap.add_argument("--baseline", default=BASELINE,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    import shadow1_tpu  # noqa: F401  (x64 before jax arrays)
    from shadow1_tpu.platform import ensure_live_platform

    ensure_live_platform(min_devices=1)
    import jax

    backend = jax.default_backend()
    host = host_fingerprint()
    names = list(ROWS) if not args.rows else args.rows.split(",")
    for n in names:
        if n not in ROWS:
            print(json.dumps({"error": f"unknown row {n!r}",
                              "rows": list(ROWS)}))
            return 2
    measured = {}
    for n in names:
        measured[n] = {**ROWS[n](), "backend": backend, "host": host}

    try:
        with open(args.baseline) as f:
            base = json.load(f)
    except OSError:
        base = {}
    base_rows = base.get("rows", {})

    if args.update:
        for n, row in measured.items():
            base_rows.setdefault(n, {})[backend] = {
                **row, "tolerance": ROW_TOLERANCE.get(n, TOLERANCE)}
        out = {
            "tolerance": TOLERANCE,
            "note": "benchgate baselines, per row per backend — the gate "
                    "fails CI when a row's measured ms_per_round exceeds "
                    "its baseline by > tolerance on the same backend+host; "
                    f"override once with {ACCEPT_ENV}, then re-baseline "
                    "with --update (merges: other backends kept)",
            "rows": base_rows,
        }
        with open(args.baseline, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
            f.write("\n")
        print(json.dumps({"gate": "updated", "baseline": args.baseline,
                          "backend": backend, "rows": measured}))
        return 0

    if not base_rows:
        print(json.dumps({"gate": "no_baseline", "rows": measured,
                          "hint": "commit one with --update"}))
        return 0
    accept = os.environ.get(ACCEPT_ENV)
    verdicts = {}
    failed = False
    for n, row in measured.items():
        entry = base_rows.get(n, {}).get(backend)
        v = gate_row(n, row, entry, host, accept)
        verdicts[n] = v
        if v["gate"] == "failed":
            failed = True
            print(f"[benchgate] PERF REGRESSION ({n}): "
                  f"{v['ms_per_round']} vs baseline "
                  f"{v['baseline_ms_per_round']} ms/round "
                  f"(+{(v['ratio'] - 1) * 100:.1f}% > "
                  f"{v['tolerance'] * 100:.0f}% tolerance). If "
                  f"intentional, override once: {ACCEPT_ENV}='why' — then "
                  f"re-baseline with --update.", file=sys.stderr,
                  flush=True)
        elif v["gate"] == "accepted":
            print(f"[benchgate] REGRESSION ACCEPTED ({n}: {accept}): "
                  f"{v['ms_per_round']} vs baseline "
                  f"{v['baseline_ms_per_round']} ms/round — commit the new "
                  f"baseline: python -m shadow1_tpu.tools.benchgate "
                  f"--update", file=sys.stderr, flush=True)
        elif v.get("note"):
            print(f"[benchgate] {n}: {v['note']}", file=sys.stderr,
                  flush=True)
    print(json.dumps({"gate": "failed" if failed else "ok",
                      "backend": backend, "rows": verdicts}))
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
