"""YAML experiment files → CompiledExperiment (the engine-neutral artifact).

The reference's experiment file is XML: <topology> (GraphML), <host> specs
with quantity/bandwidth/start times, and plugin args
(src/main/core/support/configuration.c). This YAML schema preserves those
concepts — network / hosts / app / engine sections — and adds the
`engine.scheduler: cpu|tpu|sharded` selector mandated by BASELINE.json
("CPU and TPU engines are selected from the same config file").

Schema:

    general:
      seed: 1
      stop_time: 60 s            # durations: "<num> <ns|us|ms|s>" or int ns
    engine:
      scheduler: tpu             # cpu | tpu | sharded
      ev_cap: 256                # any EngineParams field
    network:
      graphml: path.graphml      # or:
      single_vertex: {latency: 10 ms, loss: 0.01}
    hosts:                       # expanded in order into host ids 0..H-1
      - name: relay
        count: 8
        vertex: 0                # attachment PoP (int id or GraphML node id,
                                 # or "spread" = round-robin over vertices)
        bandwidth_up: 100 Mbit   # "<num> <bit|Kbit|Mbit|Gbit>"/s
        bandwidth_down: 100 Mbit
    app:
      model: tgen                # tgen|tor|bitcoin|filexfer|dgram|phold
      params: {...}              # global scalars (engine-level knobs)
      defaults: {...}            # per-host params, broadcast to all hosts
      groups:                    # per-host params, per host group
        relay: {...}
    faults:                      # deterministic fault plane (fault/schedule.py;
      hosts:                     #   docs/SEMANTICS.md "Fault plane")
        - {group: relay, down_at: 2 s, up_at: 3 s}   # churn cycles
      links:
        - {src_vertex: pop_a, dst_vertex: pop_b, down_at: 4 s, up_at: 5 s}
      loss:
        - {src_vertex: pop_a, dst_vertex: pop_b, from: 6 s, until: 7 s,
           loss: 0.2}

Per-host values may be scalars or lists of length == group count. Durations
and bandwidths accept the unit strings above anywhere.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

from shadow1_tpu.config.compiled import CompiledExperiment
from shadow1_tpu.config.dns import Dns
from shadow1_tpu.config.topology import compile_paths, load_graphml
from shadow1_tpu.consts import MS, NS, SEC, US, EngineParams

_TIME_UNITS = {"ns": NS, "us": US, "ms": MS, "s": SEC, "sec": SEC}
_BW_UNITS = {"bit": 1, "kbit": 10**3, "mbit": 10**6, "gbit": 10**9}


def parse_time_ns(v) -> int:
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return int(v)
    s = str(v).strip().lower()
    parts = s.split()
    if len(parts) == 2 and parts[1] in _TIME_UNITS:
        return int(float(parts[0]) * _TIME_UNITS[parts[1]])
    for unit in ("ns", "us", "ms", "sec", "s"):
        if s.endswith(unit):
            return int(float(s[: -len(unit)]) * _TIME_UNITS[unit])
    return int(float(s))


def parse_bw_bits(v) -> int:
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return int(v)
    s = str(v).strip().lower().replace("/s", "")
    parts = s.split()
    if len(parts) == 2 and parts[1] in _BW_UNITS:
        return int(float(parts[0]) * _BW_UNITS[parts[1]])
    for unit in ("kbit", "mbit", "gbit", "bit"):
        if s.endswith(unit):
            return int(float(s[: -len(unit)]) * _BW_UNITS[unit])
    return int(float(s))


@dataclasses.dataclass
class HostGroup:
    name: str
    count: int
    start: int          # first global host id
    vertex_spec: Any
    bw_up: int
    bw_dn: int
    stop_time: int      # ns the host halts (churn); NO_STOP = never
    cpu_ns_per_event: int
    tx_qlen_bytes: int  # NIC uplink queue bound (0 = unbounded)
    rx_qlen_bytes: int
    aqm_min_bytes: int  # RED uplink AQM thresholds (aqm_max_bytes 0 = off)
    aqm_max_bytes: int
    aqm_pmax: float

    @property
    def ids(self) -> np.ndarray:
        return np.arange(self.start, self.start + self.count)


# Per-host app parameter schemas: name -> (dtype, default, parser).
# A parser of parse_time_ns lets YAML say "100 ms" for per-host times.
_T = parse_time_ns
_APP_PARAMS: dict[str, dict[str, tuple]] = {
    "filexfer": {
        "role": (np.int64, 2, None),
        "server": (np.int64, 0, None),
        "flow_bytes": (np.int64, 0, None),
        "start_time": (np.int64, 0, _T),
        "flow_count": (np.int64, 0, None),
    },
    "dgram": {
        "dst": (np.int64, 0, None),
        "payload": (np.int64, 0, None),
        "interval": (np.int64, 0, _T),
        "count": (np.int64, 0, None),
        "start_time": (np.int64, 0, _T),
    },
    "tgen": {
        "active": (np.int64, 0, None),
        "streams": (np.int64, 0, None),
        "mean_bytes": (np.float64, 0.0, None),
        "mean_think_ns": (np.float64, 0.0, _T),
        "start_time": (np.int64, 0, _T),
    },
    "tor": {
        "role": (np.int64, 3, None),
        "relay_weight": (np.int64, 0, None),
        "is_guard": (bool, False, None),
        "is_exit": (bool, False, None),
        "n_circuits": (np.int64, 0, None),
        "n_streams": (np.int64, 0, None),
        "mean_stream_cells": (np.float64, 0.0, None),
        "mean_think_ns": (np.float64, 0.0, _T),
        "start_time": (np.int64, 0, _T),
    },
    "bitcoin": {},  # graph-structured config passes through `params`
    "phold": {},
}


def _reject_unknown(section: str, have, allowed) -> None:
    """Unknown-key rejection, fault/schedule.py-style, for every config
    section: a typo like ``ev_capp:`` or ``stop_tme:`` must fail fast at
    load instead of silently running the experiment on defaults."""
    unknown = set(have) - set(allowed)
    assert not unknown, (
        f"unknown {section} keys: {sorted(map(str, unknown))} "
        f"(allowed: {sorted(allowed)})"
    )


# Host-group entry schema (the per-group knobs _expand_hosts reads).
_HOST_KEYS = ("name", "count", "vertex", "bandwidth_up", "bandwidth_down",
              "stop_time", "cpu_per_event", "tx_queue_bytes",
              "rx_queue_bytes", "aqm_min_bytes", "aqm_max_bytes", "aqm_pmax")


def _expand_hosts(spec: list[dict]) -> list[HostGroup]:
    from shadow1_tpu.config.compiled import NO_STOP

    groups, start = [], 0
    for g in spec:
        _reject_unknown(f"hosts[{g.get('name', start)}]", g, _HOST_KEYS)
        count = int(g.get("count", 1))
        groups.append(HostGroup(
            name=g["name"],
            count=count,
            start=start,
            vertex_spec=g.get("vertex", 0),
            bw_up=parse_bw_bits(g.get("bandwidth_up", "1 Gbit")),
            bw_dn=parse_bw_bits(g.get("bandwidth_down", "1 Gbit")),
            stop_time=(
                parse_time_ns(g["stop_time"]) if "stop_time" in g else NO_STOP
            ),
            cpu_ns_per_event=(
                parse_time_ns(g["cpu_per_event"]) if "cpu_per_event" in g else 0
            ),
            tx_qlen_bytes=int(g.get("tx_queue_bytes", 0)),
            rx_qlen_bytes=int(g.get("rx_queue_bytes", 0)),
            aqm_min_bytes=int(g.get("aqm_min_bytes", 0)),
            aqm_max_bytes=int(g.get("aqm_max_bytes", 0)),
            aqm_pmax=float(g.get("aqm_pmax", 0.1)),
        ))
        start += count
    return groups


def _vertex_assignment(groups, vertex_names, n_hosts) -> np.ndarray:
    n_v = max(len(vertex_names), 1)
    name_idx = {str(n): i for i, n in enumerate(vertex_names)}
    hv = np.zeros(n_hosts, np.int32)
    for g in groups:
        if g.vertex_spec == "spread":
            hv[g.start:g.start + g.count] = np.arange(g.count) % n_v
        elif isinstance(g.vertex_spec, int):
            hv[g.start:g.start + g.count] = g.vertex_spec
        else:
            hv[g.start:g.start + g.count] = name_idx[str(g.vertex_spec)]
    assert hv.max(initial=0) < n_v, "host attached to missing vertex"
    return hv


def _per_host_array(name, dtype, default, parser, groups, defaults, group_cfg, h):
    arr = np.full(h, default, dtype)
    conv = parser or (lambda x: x)
    if name in defaults:
        arr[:] = _group_values(name, defaults[name], conv, h, np.arange(h))
    for g in groups:
        block = group_cfg.get(g.name, {})
        if name in block:
            val = block[name]
            arr[g.ids] = _group_values(name, val, conv, g.count,
                                       np.arange(g.count))
    return arr


def _group_values(name, val, conv, count, idx):
    """One app-param value spec → per-host values for a group of ``count``.

    Three forms: a scalar (broadcast), a list (one per host), or a stagger
    dict ``{start: X, interval: Y}`` → ``start + i·interval`` for host i in
    the group — the idiom for spreading e.g. client bootstrap times so a
    10k-client rung does not burst every dirauth in one window (the
    reference's example configs stagger client start times the same way)."""
    if isinstance(val, dict):
        extra = set(val) - {"start", "interval"}
        assert not extra, f"unknown stagger keys for {name}: {extra}"
        return conv(val.get("start", 0)) + idx * conv(val.get("interval", 0))
    if isinstance(val, list):
        assert len(val) == count, (name, count)
        return [conv(x) for x in val]
    return conv(val)


def _gen_bitcoin_cfg(model_cfg: dict, h: int, seed: int) -> None:
    """Expand bitcoin's generator specs into concrete arrays.

    ``graph: {kind: ring_chord, k: K}`` → symmetric K-regular peer graph
    (ring ±1 plus power-of-4 chords); ``tx: {count, start, interval}`` →
    staggered transactions at config-RNG-chosen origins. Explicit ``peers``
    / ``tx_origin`` / ``tx_time`` arrays may be given instead.
    """
    if "peers" not in model_cfg:
        gspec = model_cfg.pop("graph", {})
        k = int(gspec.get("k", 8))
        assert k % 2 == 0 and k >= 2
        chords = [1]
        while len(chords) < k // 2:
            chords.append(chords[-1] * 4)
        peers = np.zeros((h, k), np.int32)
        for ci, c in enumerate(chords):
            peers[:, 2 * ci] = (np.arange(h) - c) % h
            peers[:, 2 * ci + 1] = (np.arange(h) + c) % h
        model_cfg["peers"] = peers
    if "tx_origin" not in model_cfg:
        tspec = model_cfg.pop("tx", {})
        count = int(tspec.get("count", 50))
        start = parse_time_ns(tspec.get("start", "1 s"))
        interval = parse_time_ns(tspec.get("interval", "200 ms"))
        rs = np.random.RandomState(seed ^ 0xB17C01)  # config-gen only
        model_cfg["tx_origin"] = rs.randint(0, h, count).astype(np.int64)
        model_cfg["tx_time"] = (start + np.arange(count) * interval).astype(np.int64)


class WatchlistError(ValueError):
    """A probe watchlist entry (``probes:`` section / ``--watch``) failed to
    resolve: unknown host/group name, bad index, or out-of-range socket.
    Raised at CONFIG time — the CLI maps it onto the standard structured
    config error path (``ap.error`` → EXIT_CONFIG) so a typo'd target can
    never reach the traced engine as a shape crash."""


def _resolve_probe_host(spec, dns) -> int:
    """One watchlist host spec → global host id.

    Accepted forms: an int host id; ``"name"`` / ``"@name"`` (any hostname
    or group name the Dns registry knows — bare group name = its first
    host); ``"name[i]"`` (the group's i-th host, via the registry's
    ``name-i`` convention)."""
    txt = str(spec).strip()
    if isinstance(spec, int) or txt.lstrip("-").isdigit():
        hid = int(txt)
        if not 0 <= hid < len(dns):
            raise WatchlistError(
                f"probe host id {hid} out of range (hosts 0..{len(dns) - 1})")
        return hid
    name = txt[1:] if txt.startswith("@") else txt
    if name.endswith("]") and "[" in name:
        base, _, idx_s = name[:-1].partition("[")
        try:
            idx = int(idx_s)
        except ValueError:
            raise WatchlistError(
                f"probe target {txt!r}: index {idx_s!r} is not an integer"
            ) from None
        name = base if (idx == 0 and f"{base}-0" not in dns._by_name) \
            else f"{base}-{idx}"
    try:
        return dns.resolve(name)
    except KeyError:
        import difflib

        close = difflib.get_close_matches(name, dns._by_name, n=3)
        hint = f" — did you mean {', '.join(map(repr, close))}?" if close \
            else ""
        raise WatchlistError(
            f"unknown probe target {txt!r}: no host or group by that "
            f"name{hint}") from None


def resolve_watchlist(entries, dns, sockets_per_host: int) -> tuple:
    """Watchlist specs → the EngineParams.probes tuple of (host, sock) ints.

    ``entries`` come from the ``probes:`` config section (list of
    ``"host[:sock]"`` strings, int ids, or ``{host:, sock:}`` dicts) or
    repeated ``--watch`` flags. sock defaults to −1 (the host-only
    NIC/event view). Duplicates collapse, first occurrence wins the order.
    Every failure raises WatchlistError with a typo-grade message."""
    if isinstance(entries, (str, int, dict)):
        entries = [entries]
    if not isinstance(entries, (list, tuple)):
        raise WatchlistError(
            f"probes: must be a list of host[:sock] targets, "
            f"got {type(entries).__name__}")
    probes: list[tuple[int, int]] = []
    for e in entries:
        if isinstance(e, dict):
            unknown = set(e) - {"host", "sock"}
            if unknown:
                raise WatchlistError(
                    f"unknown probe entry keys {sorted(map(str, unknown))} "
                    f"(allowed: host, sock)")
            if "host" not in e:
                raise WatchlistError(f"probe entry {e!r} is missing 'host'")
            spec, sock_s = e["host"], e.get("sock", -1)
        else:
            txt = str(e)
            spec, sep, tail = txt.rpartition(":")
            if not sep:
                spec, sock_s = txt, -1
            else:
                sock_s = tail
        host = _resolve_probe_host(spec, dns)
        try:
            sock = int(sock_s)
        except (TypeError, ValueError):
            raise WatchlistError(
                f"probe target {e!r}: socket {sock_s!r} is not an integer"
            ) from None
        if not -1 <= sock < sockets_per_host:
            raise WatchlistError(
                f"probe target {e!r}: socket {sock} out of range "
                f"(-1 = host view, else 0..{sockets_per_host - 1})")
        pr = (host, sock)
        if pr not in probes:
            probes.append(pr)
    return tuple(probes)


def resolve_edges(entries, vertex_names) -> tuple:
    """``"VS:VD"`` edge specs → tuple of (src_vertex, dst_vertex) int pairs.

    Each half is a topology vertex name (GraphML node id) or a numeric
    vertex id — the same namespace link records report. Used by the
    pcapdump ``--edge`` filter. Duplicates collapse, first occurrence wins
    the order. Every failure raises WatchlistError with a typo-grade
    message (the CLI maps it onto ap.error → EXIT_CONFIG, like the probe
    watchlist)."""
    names = [str(n) for n in (vertex_names or [])]
    idx = {n: i for i, n in enumerate(names)}
    n_v = len(names)

    def one(txt, whole):
        txt = txt.strip()
        if txt.lstrip("-").isdigit():
            v = int(txt)
            if not (0 <= v < n_v if n_v else v >= 0):
                raise WatchlistError(
                    f"edge {whole!r}: vertex id {v} out of range "
                    f"(vertices 0..{n_v - 1})")
            return v
        if txt in idx:
            return idx[txt]
        import difflib

        close = difflib.get_close_matches(txt, names, n=3)
        hint = f" — did you mean {', '.join(map(repr, close))}?" \
            if close else ""
        raise WatchlistError(
            f"edge {whole!r}: unknown vertex {txt!r}{hint}")

    edges: list[tuple[int, int]] = []
    for e in entries:
        txt = str(e)
        src, sep, dst = txt.partition(":")
        if not sep or not src.strip() or not dst.strip():
            raise WatchlistError(
                f"edge {txt!r}: expected SRC_VERTEX:DST_VERTEX")
        pr = (one(src, txt), one(dst, txt))
        if pr not in edges:
            edges.append(pr)
    return tuple(edges)


def build_experiment(doc: dict, base_dir: str = ".") -> tuple[CompiledExperiment, EngineParams, str]:
    """YAML document → (CompiledExperiment, EngineParams, scheduler)."""
    import os

    _reject_unknown("top-level config", doc,
                    ("general", "engine", "network", "hosts", "app",
                     "faults", "sweep", "probes"))
    # ``sweep:`` belongs to fleet mode (shadow1_tpu/fleet/expand.py): a solo
    # run of a sweep config runs the BASE experiment; its section schema is
    # validated there, at --fleet expansion time.
    gen = doc.get("general", {})
    _reject_unknown("general:", gen, ("seed", "stop_time"))
    seed = int(gen.get("seed", 1))
    end_time = parse_time_ns(gen.get("stop_time", "10 s"))

    # -- engine ------------------------------------------------------------
    eng = dict(doc.get("engine", {}))
    scheduler = eng.pop("scheduler", "tpu")
    fields = {f.name: f for f in dataclasses.fields(EngineParams)}
    unknown = set(eng) - set(fields)
    assert not unknown, f"unknown engine params: {unknown}"
    # The probe watchlist is NOT an engine: knob — it needs host-name
    # resolution (the top-level ``probes:`` section / --watch own it), and
    # the scalar coercion below would mangle the target list anyway.
    assert "probes" not in eng, (
        "engine.probes is not settable — use the top-level 'probes:' "
        "section (host[:sock] targets) or the --watch flag"
    )
    # Coerce by the DECLARED field type (a quoted "256" in YAML must still
    # become an int; only genuinely-str fields like pop_extract stay str).
    params = EngineParams(**{
        k: str(v) if fields[k].type in (str, "str") else int(v)
        for k, v in eng.items()
    })

    # -- network -----------------------------------------------------------
    net = doc.get("network", {})
    _reject_unknown("network:", net, ("graphml", "single_vertex", "jitter"))
    if "single_vertex" in net:
        _reject_unknown("network.single_vertex:", net["single_vertex"],
                        ("latency", "loss"))
    if "graphml" in net:
        path = net["graphml"]
        if not os.path.isabs(path):
            path = os.path.join(base_dir, path)
        names, lat_e, loss_e, directed = load_graphml(path)
        lat_vv, loss_vv = compile_paths(lat_e, loss_e, directed=directed)
    else:
        sv = net.get("single_vertex", {})
        names = ["v0"]
        lat_vv = np.full((1, 1), parse_time_ns(sv.get("latency", "10 ms")), np.int64)
        loss_vv = np.full((1, 1), float(sv.get("loss", 0.0)), np.float32)
    # Per-packet path-latency jitter amplitude (± ns), uniform over all
    # paths. (Per-edge graphml jitter attributes are NOT read yet — a
    # config must set network.jitter explicitly.)
    jitter = net.get("jitter")
    jitter_vv = (
        np.full_like(lat_vv, parse_time_ns(jitter)) if jitter is not None else None
    )

    # -- hosts -------------------------------------------------------------
    groups = _expand_hosts(doc.get("hosts", [{"name": "host", "count": 1}]))
    h = sum(g.count for g in groups)
    host_vertex = _vertex_assignment(groups, names, h)
    bw_up = np.zeros(h, np.int64)
    bw_dn = np.zeros(h, np.int64)
    stop_time = np.zeros(h, np.int64)
    cpu_ns = np.zeros(h, np.int64)
    tx_qlen = np.zeros(h, np.int64)
    rx_qlen = np.zeros(h, np.int64)
    aqm_min = np.zeros(h, np.int64)
    aqm_max = np.zeros(h, np.int64)
    aqm_pmax = np.zeros(h, np.float64)
    for g in groups:
        bw_up[g.ids] = g.bw_up
        bw_dn[g.ids] = g.bw_dn
        stop_time[g.ids] = g.stop_time
        cpu_ns[g.ids] = g.cpu_ns_per_event
        tx_qlen[g.ids] = g.tx_qlen_bytes
        rx_qlen[g.ids] = g.rx_qlen_bytes
        aqm_min[g.ids] = g.aqm_min_bytes
        aqm_max[g.ids] = g.aqm_max_bytes
        aqm_pmax[g.ids] = g.aqm_pmax if g.aqm_max_bytes else 0.0

    # -- app ---------------------------------------------------------------
    appsec = doc.get("app", {"model": "phold"})
    _reject_unknown("app:", appsec, ("model", "params", "defaults", "groups"))
    app = appsec["model"]
    model_cfg: dict[str, Any] = dict(appsec.get("params", {}))
    schema = _APP_PARAMS.get(app)
    assert schema is not None, f"unknown app model {app!r}"

    # Group-name references: "@name" → first host id of that group (e.g.
    # filexfer's `server: "@server"`), resolved before array building.
    by_name = {g.name: g for g in groups}

    def resolve(tree):
        if isinstance(tree, str) and tree.startswith("@"):
            return by_name[tree[1:]].start
        if isinstance(tree, dict):
            return {k: resolve(v) for k, v in tree.items()}
        if isinstance(tree, list):
            return [resolve(v) for v in tree]
        return tree

    defaults = resolve(appsec.get("defaults", {}))
    group_cfg = resolve(appsec.get("groups", {}))
    model_cfg = resolve(model_cfg)
    # Typos fail loudly, like the engine section: every defaults/groups key
    # must name a schema parameter, every groups key a host group.
    if schema:
        allowed = set(schema)
        assert set(defaults) <= allowed, \
            f"unknown app.defaults params: {set(defaults) - allowed}"
        host_names = {g.name for g in groups}
        assert set(group_cfg) <= host_names, \
            f"unknown app.groups host groups: {set(group_cfg) - host_names}"
        for gname, block in group_cfg.items():
            assert set(block) <= allowed, \
                f"unknown params in app.groups.{gname}: {set(block) - allowed}"
    for pname, (dtype, default, parser) in schema.items():
        model_cfg[pname] = _per_host_array(
            pname, dtype, default, parser, groups, defaults, group_cfg, h
        )

    # -- faults ------------------------------------------------------------
    from shadow1_tpu.fault.schedule import parse_faults

    faults = parse_faults(doc.get("faults"), groups, names)

    if app == "bitcoin":
        _gen_bitcoin_cfg(model_cfg, h, seed)
    if app == "phold":
        model_cfg.setdefault("mean_delay_ns", float(10 * MS))
        model = "phold"
    else:
        model_cfg["app"] = app
        model = "net"

    exp = CompiledExperiment(
        n_hosts=h,
        seed=seed,
        end_time=end_time,
        lat_vv=lat_vv,
        loss_vv=loss_vv,
        host_vertex=host_vertex,
        bw_up=bw_up,
        bw_dn=bw_dn,
        model=model,
        model_cfg=model_cfg,
        jitter_vv=jitter_vv,
        stop_time=stop_time,
        cpu_ns_per_event=cpu_ns,
        tx_qlen_bytes=tx_qlen,
        rx_qlen_bytes=rx_qlen,
        aqm_min_bytes=aqm_min,
        aqm_max_bytes=aqm_max,
        aqm_pmax=aqm_pmax,
        faults=faults,
        dns=Dns.from_groups(groups, host_vertex),
        vertex_names=[str(n) for n in names],
    )
    exp.validate()
    # -- probes ------------------------------------------------------------
    # Flow-probe watchlist: resolved through the same name registry app
    # references use, landing as static (host, sock) int pairs in
    # EngineParams.probes (telemetry/probes.py samples them per window).
    watch = doc.get("probes")
    if watch is not None:
        params = dataclasses.replace(
            params,
            probes=resolve_watchlist(watch, exp.dns,
                                     params.sockets_per_host))
    return exp, params, scheduler


def load_experiment(path: str):
    """Load a YAML experiment file → (CompiledExperiment, EngineParams,
    scheduler)."""
    import os

    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f)
    return build_experiment(doc, base_dir=os.path.dirname(os.path.abspath(path)))


def apply_engine_overrides(params: EngineParams, spec: str | None) -> EngineParams:
    """Apply a ``k=v,k=v`` override list to an EngineParams (bench/CLI A/B
    without config-file edits; e.g. ``compact_cap=384,pop_extract=gather``).
    Values coerce to the field's current type (str fields stay str)."""
    if not spec:
        return params
    repl = {}
    fields = {f.name: f for f in dataclasses.fields(EngineParams)}
    for item in spec.split(","):
        k, sep, v = item.partition("=")
        k = k.strip()
        assert sep and k in fields, f"bad engine override {item!r}"
        repl[k] = v.strip() if fields[k].type in (str, "str") else int(v)
    return dataclasses.replace(params, **repl)
