"""pcap dump CLI: run an experiment on the fidelity (oracle) engine and
capture every delivered packet to a .pcap file.

    python -m shadow1_tpu.tools.pcapdump config.yaml out.pcap [--windows N]
        [--host NAME[:SOCK]]... [--sock N] [--edge VS:VD]...

The capture engine is the sequential oracle (it sees every packet at
routing time); for large configs bound the run with --windows. --host
narrows the capture to packets touching the named endpoints — targets
resolve exactly like the probe plane's --watch flag (config host names,
group[i] / group-i members, numeric ids, optional :SOCK), so the pcap of
a misbehaving flow and its probe stream point at the same entity. --edge
narrows it to packets crossing a topology edge (vertex names or ids,
directional) — the same edges the link-telemetry records key on, so the
pcap of a hot edge and its netreport row point at the same object.
"""

from __future__ import annotations

import argparse


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="shadow1_tpu.tools.pcapdump")
    ap.add_argument("config")
    ap.add_argument("out")
    ap.add_argument("--windows", type=int, default=None)
    ap.add_argument("--snaplen", type=int, default=128)
    ap.add_argument("--host", action="append", default=None,
                    metavar="NAME[:SOCK]",
                    help="capture only packets whose src or dst matches "
                         "(repeatable; --watch target syntax — omit :SOCK "
                         "for every socket on the host)")
    ap.add_argument("--sock", type=int, default=None, metavar="N",
                    help="with --host entries that omit :SOCK, narrow them "
                         "to socket N")
    ap.add_argument("--edge", action="append", default=None,
                    metavar="VS:VD",
                    help="capture only packets crossing this topology edge "
                         "(repeatable; vertex names or numeric ids, "
                         "directional — the namespace link records use). "
                         "Combines with --host as OR")
    args = ap.parse_args(argv)

    import shadow1_tpu  # noqa: F401
    from shadow1_tpu.platform import force_cpu

    force_cpu(1)  # the oracle needs no accelerator
    from shadow1_tpu.config.experiment import (
        WatchlistError,
        load_experiment,
        resolve_edges,
        resolve_watchlist,
    )
    from shadow1_tpu.cpu_engine import CpuEngine
    from shadow1_tpu.tools.pcap import FilteredPcap, PcapWriter

    if args.sock is not None and not args.host:
        ap.error("--sock narrows --host entries; give at least one --host")
    try:
        exp, params, _ = load_experiment(args.config)
        watchlist: tuple = ()
        if args.host:
            entries = [h if (":" in h or args.sock is None)
                       else f"{h}:{args.sock}" for h in args.host]
            watchlist = resolve_watchlist(entries, exp.dns,
                                          params.sockets_per_host)
        edges: tuple = ()
        if args.edge:
            edges = resolve_edges(args.edge, exp.vertex_names)
    except WatchlistError as e:
        ap.error(str(e))
    with FilteredPcap(PcapWriter(args.out, snaplen=args.snaplen),
                      watchlist, edges=edges,
                      host_vertex=exp.host_vertex) as w:
        eng = CpuEngine(exp, params, capture=w)
        m = eng.run(n_windows=args.windows)
        print(f"{w.n_packets} packets captured to {args.out}; metrics: {m}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
