"""Between-chunk adaptive capacity controller (the ``--auto-caps`` brain).

At every chunk boundary — where state is already fetched to host for the
heartbeat/ring drain — the controller compares the run-max fill gauges
(``Metrics.ev_max_fill`` / ``ob_max_fill``) against the current caps:

* **grow before overflow**: a high-water above ``grow_frac · cap`` means the
  buffer is nearing its ceiling; the next chunk runs at the ladder step
  covering ``high_water × headroom``. Because the gauges are window-end
  samples, growth triggers while headroom still exists — on workloads whose
  occupancy ramps over windows (TCP slow-start), the controller stays ahead
  of the curve and the overflow counters stay 0 where a static cap would
  have dropped events.
* **shrink after sustained low occupancy**: once the quantized target
  ``quantize(high_water × headroom)`` has sat below the current cap for
  ``shrink_patience`` consecutive chunks (the high-water is cumulative, so
  early chunks cannot trigger a premature cut), the cap drops to the
  target. The floor is the RUN-MAX fill — never the current fill — so a
  workload's past peak keeps its headroom and the controller cannot
  oscillate (caps form a monotone-convergent sequence per direction).

Resizes migrate the state planes bit-exactly (tune/resize.py) and swap to
an engine compiled at the new static shape. Engines are cached per cap
pair and caps are ladder-quantized (tune/ladder.py), so total recompiles
are bounded by the ladder span, not the chunk count.

``outbox_cap`` tuning is OFF by default: outbox space is a semantic knob
for TCP (tcp_flush paces sends on ``outbox_space``; the CPU oracle honours
the same bound), so resizing it mid-run changes the event stream. Enable
``CapPolicy(tune_outbox=True)`` only for models whose outbox use is
drop-counted rather than flow-controlled (e.g. PHOLD).
"""

from __future__ import annotations

import dataclasses

from shadow1_tpu.tune.ladder import HEADROOM, next_step, quantize_cap


def _peak(x) -> int:
    """A gauge as one fleet-global int: max over the lane axis (identity on
    solo 0-d gauges). Caps are fleet-uniform, so the controller sizes for
    the BUSIEST lane — any smaller cap would overflow it."""
    import numpy as np

    return int(np.asarray(x).max())


def _total(x) -> int:
    """A counter as one fleet-global int: sum over the lane axis (identity
    on solo 0-d counters) — the psum idiom the sharded engine uses."""
    import numpy as np

    return int(np.asarray(x).sum())


@dataclasses.dataclass(frozen=True)
class CapPolicy:
    grow_frac: float = 0.75    # grow when high_water > grow_frac * cap
    headroom: float = HEADROOM # target cap = quantize(high_water * headroom)
    shrink_patience: int = 2   # consecutive low chunks before a shrink
    min_cap: int = 8           # never shrink below (ladder anchor)
    max_cap: int = 1 << 20     # never grow beyond (runaway guard)
    tune_outbox: bool = False  # semantic for TCP — see module docstring


class CapController:
    """The ``retune`` hook for ckpt.run_chunked: ``(engine, st) -> (engine,
    st)``. Construct with the running engine and a factory that builds its
    sibling at different caps (``params -> engine``)."""

    def __init__(self, engine, make_engine, policy: CapPolicy | None = None,
                 log=None, initial_state=None):
        self.policy = policy or CapPolicy()
        self._make_engine = make_engine
        self._engines = {self._key(engine.params): engine}
        self._low_chunks = {"ev_cap": 0, "outbox_cap": 0}
        # Overflow backstop baselines (cumulative counters at last check).
        # A RESUMED state carries its pre-snapshot history in the cumulative
        # counters; baseline from it (``initial_state``) so a respawn does
        # not mistake old losses for a fresh lossy chunk and force a
        # spurious grow + re-jit on every restart. Counters sum over the
        # lane axis on a FleetEngine state (_total) — caps are
        # fleet-uniform, so ANY lane's loss is the fleet's loss.
        self._overflow_seen = {
            "ev_cap": (_total(initial_state.metrics.ev_overflow)
                       if initial_state is not None else 0),
            "outbox_cap": (_total(initial_state.metrics.ob_overflow)
                           if initial_state is not None else 0),
        }
        # Lossless floor: once a cap has overflowed, shrinking back to it
        # would just re-drop events — the shrink target ratchets above the
        # largest cap ever seen lossy (prevents grow/shrink oscillation on
        # workloads whose mid-window bursts hide from the window-end gauge).
        self._floor = {"ev_cap": self.policy.min_cap,
                       "outbox_cap": self.policy.min_cap}
        self.resizes: list[dict] = []   # audit log (CLI output / tests)
        self._log = log

    @staticmethod
    def _key(params):
        return (params.ev_cap, params.outbox_cap)

    def _engine_for(self, params):
        k = self._key(params)
        eng = self._engines.get(k)
        if eng is None:
            eng = self._engines[k] = self._make_engine(params)
        return eng

    # Public alias: the overflow-retry guard (txn.OverflowGuard) builds its
    # grown-cap engines through the controller's cache so the two planes
    # share one jit cache per (ev_cap, outbox_cap) instead of compiling the
    # same program twice.
    engine_for = _engine_for

    def note_lossy(self, knob: str, grown_cap: int) -> None:
        """Absorb a retry-driven grow (txn.OverflowGuard): the pre-grow cap
        is PROVEN lossy — the tainted chunk overflowed it — so the shrink
        floor ratchets to the grown cap and the low-occupancy streak
        resets. The controller can then never shrink back into a cap the
        retry plane just had to grow away from (grow/retry/shrink
        oscillation). The ``_overflow_seen`` baseline is deliberately NOT
        advanced: the tainted chunk's counters were discarded with its
        state, so the committed stream shows no fresh overflow and the
        backstop cannot double-grow on top of the guard's grow."""
        self._floor[knob] = max(self._floor[knob], int(grown_cap))
        self._low_chunks[knob] = 0

    def _decide(self, knob: str, high_water: int, cap: int) -> int:
        import math

        p = self.policy
        if high_water <= 0:
            return cap
        target = min(max(quantize_cap(math.ceil(high_water * p.headroom)),
                         p.min_cap, self._floor[knob]), p.max_cap)
        if high_water > p.grow_frac * cap:
            self._low_chunks[knob] = 0
            return max(target, min(next_step(cap), p.max_cap))
        if target < cap:
            self._low_chunks[knob] += 1
            if self._low_chunks[knob] >= p.shrink_patience:
                self._low_chunks[knob] = 0
                return target
            return cap
        self._low_chunks[knob] = 0
        return cap

    def _overflow_grow(self, knob: str, total: int, cap: int, decided: int) -> int:
        """Backstop on the authoritative guard: the fill gauges are
        window-END samples, so a buffer can overflow mid-window (burst push
        that drains before the sample) while the gauge sits below the grow
        threshold. Any NEW overflow since the last check forces at least one
        ladder step up — lossy chunks must never go unanswered."""
        fresh = total - self._overflow_seen[knob]
        self._overflow_seen[knob] = total
        if fresh <= 0:
            return decided
        self._low_chunks[knob] = 0
        grown = min(next_step(cap), self.policy.max_cap)
        self._floor[knob] = max(self._floor[knob], grown)  # ``cap`` is lossy
        return max(decided, grown)

    def __call__(self, engine, st):
        import dataclasses as _dc

        import jax
        import numpy as np

        params = engine.params
        # The gauges ride the metrics fetch the chunk drain already paid.
        # On a FleetEngine state they are [E] vectors: the controller is fed
        # the FLEET-GLOBAL view — max fill across lanes (caps are uniform,
        # so the busiest lane sets the floor), summed overflow counters.
        ev_hw = _peak(st.metrics.ev_max_fill)
        ob_hw = _peak(st.metrics.ob_max_fill)
        new_ev = self._decide("ev_cap", ev_hw, params.ev_cap)
        new_ev = self._overflow_grow("ev_cap", _total(st.metrics.ev_overflow),
                                     params.ev_cap, new_ev)
        new_ob = (self._decide("outbox_cap", ob_hw, params.outbox_cap)
                  if self.policy.tune_outbox else params.outbox_cap)
        if self.policy.tune_outbox:
            new_ob = self._overflow_grow("outbox_cap",
                                         _total(st.metrics.ob_overflow),
                                         params.outbox_cap, new_ob)
        if (new_ev, new_ob) == (params.ev_cap, params.outbox_cap):
            return engine, st
        from shadow1_tpu.tune.resize import resize_state

        host_st = jax.tree.map(np.asarray, st)
        host_st = resize_state(host_st, ev_cap=new_ev, outbox_cap=new_ob)
        new_params = _dc.replace(params, ev_cap=new_ev, outbox_cap=new_ob)
        new_engine = self._engine_for(new_params)
        rec = {
            "windows_done": _peak(st.metrics.windows),
            "ev_cap": [params.ev_cap, new_ev],
            "outbox_cap": [params.outbox_cap, new_ob],
            "ev_max_fill": ev_hw,
            "ob_max_fill": ob_hw,
        }
        self.resizes.append(rec)
        if self._log is not None:
            self._log("auto-caps resize", **rec)
        return new_engine, new_engine.place_state(host_st)

    @property
    def final_caps(self) -> dict:
        last = self.resizes[-1] if self.resizes else None
        return ({"ev_cap": last["ev_cap"][1], "outbox_cap": last["outbox_cap"][1]}
                if last else {})
