"""Serve plane (shadow1_tpu/serve/) — daemon, cache, admission, packing.

The serving contract under test (docs/SEMANTICS.md §"Serving contract"):

* engine-cache keying: a same-shape repeat batch HITS (rebind, zero new
  jit traces); a changed cap MISSES (different state shapes);
* admission control rejects an over-budget submission BEFORE any engine
  is built, with the standard memory_budget advice record;
* lane-packed jobs produce digest streams bit-identical to their solo
  runs; a halting tenant quarantines without touching cohabitants;
* a higher-priority submission evicts the running batch through the
  preemption plane and the evicted job resumes bit-identically;
* daemon SIGTERM drains, persists the queue, and a restart finishes the
  work (slow, subprocess).

In-process tests drive the daemon through ``ServeDaemon.step()`` — the
exact scheduler iteration the live loop runs — so the fast tier needs no
subprocesses.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

import shadow1_tpu  # noqa: F401
from shadow1_tpu.ckpt import run_chunked
from shadow1_tpu.config.experiment import load_experiment
from shadow1_tpu.consts import (
    EXIT_CAPACITY,
    EXIT_CONFIG,
    EXIT_MEMORY,
    EXIT_OK,
    EXIT_SERVE_SHUTDOWN,
)
from shadow1_tpu.core.digest import DIGEST_FIELDS
from shadow1_tpu.core.engine import Engine
from shadow1_tpu.serve import client
from shadow1_tpu.serve.cache import EngineCache, shape_class_key
from shadow1_tpu.serve.daemon import ServeDaemon
from shadow1_tpu.serve.protocol import Spool
from shadow1_tpu.telemetry.ring import drain_ring

BASE = """
general: {{seed: {seed}, stop_time: {stop} ms}}
engine: {{scheduler: tpu, ev_cap: {ev_cap}, metrics_ring: 10,
          state_digest: 1{extra_engine}}}
network: {{single_vertex: {{latency: 1 ms{loss}}}}}
hosts:
  - {{name: h, count: 8}}
app:
  model: phold
  params: {{mean_delay_ns: 2000000.0, init_events: {init_events}}}
"""


def write_cfg(tmp_path, name, seed=5, stop=40, ev_cap=32, loss=None,
              init_events=3, extra_engine=""):
    p = tmp_path / name
    p.write_text(BASE.format(
        seed=seed, stop=stop, ev_cap=ev_cap, init_events=init_events,
        loss=(f", loss: {loss}" if loss is not None else ""),
        extra_engine=extra_engine))
    return str(p)


def solo_stream(cfg_path) -> dict[int, tuple]:
    """window → digest words of the straight solo run (full stream:
    chunked at the ring depth so no row is overwritten undrained)."""
    exp, params, _ = load_experiment(cfg_path)
    eng = Engine(exp, params)
    rows: dict[int, tuple] = {}
    start = [0]

    def on_chunk(st, _d):
        for r in drain_ring(st, eng.window, start=start[0]):
            if r["type"] == "ring":
                rows[r["window"]] = tuple(r[f] for f in DIGEST_FIELDS)
        start[0] = int(st.metrics.windows)

    run_chunked(eng, n_windows=eng.n_windows, chunk=params.metrics_ring,
                on_chunk=on_chunk)
    return rows


def served_stream(spool_dir, job_id) -> dict[int, tuple]:
    return {r["window"]: tuple(r[f] for f in DIGEST_FIELDS)
            for r in Spool(spool_dir).read_results(job_id)
            if r.get("type") == "ring"}


@pytest.fixture
def daemon(tmp_path):
    d = ServeDaemon(str(tmp_path / "spool"), poll_s=0.05,
                    ckpt_every_s=1e9)
    d.start()
    yield d
    d.close()


# ---------------------------------------------------------------------------
# engine cache
# ---------------------------------------------------------------------------

def test_cache_same_shape_hit_no_retrace(tmp_path):
    cache = EngineCache()
    exp_a = load_experiment(write_cfg(tmp_path, "a.yaml", seed=5))[0]
    exp_b, params, _ = load_experiment(write_cfg(tmp_path, "b.yaml",
                                                 seed=9))
    eng1, out1 = cache.get([exp_a], params)
    assert out1 == "miss"
    eng1.run(n_windows=4)
    n_traces = eng1._run_jit._cache_size()
    eng2, out2 = cache.get([exp_b], params)
    assert out2 == "hit" and eng2 is eng1
    assert eng2.exp.seed == 9  # rebound to the new lane set
    eng2.run(n_windows=4)
    # THE cache contract: a hit never traces or compiles anything new.
    assert eng2._run_jit._cache_size() == n_traces
    assert cache.counters()["cache_hits"] == 1


def test_cache_changed_cap_misses(tmp_path):
    import dataclasses

    cache = EngineCache()
    exp, params, _ = load_experiment(write_cfg(tmp_path, "a.yaml"))
    _, out1 = cache.get([exp], params)
    _, out2 = cache.get([exp], dataclasses.replace(params, ev_cap=64))
    assert (out1, out2) == ("miss", "miss")
    assert cache.counters() == {"cache_hits": 0, "cache_misses": 2,
                                "cache_evictions": 0, "cache_entries": 2}
    # and the keys really differ only by the cap
    k1 = shape_class_key(exp, params, 1)
    k2 = shape_class_key(exp, dataclasses.replace(params, ev_cap=64), 1)
    assert k1[0] == k2[0] and k1 != k2


def test_rebind_refuses_trace_incompatible_sets(tmp_path):
    from shadow1_tpu.fleet.engine import FleetEngine
    from shadow1_tpu.fleet.expand import FleetConfigError

    exp, params, _ = load_experiment(write_cfg(tmp_path, "a.yaml"))
    eng = FleetEngine([exp], params)
    # A uniform max_rounds is baked into the compiled program — a
    # different uniform value cannot ride a rebind (silent wrong results
    # otherwise: the metadata would claim R2 while the executable runs R).
    with pytest.raises(FleetConfigError, match="max_rounds"):
        eng.rebind([exp], max_rounds=[params.max_rounds + 1])
    # A shape-class field differing from the COMPILED engine's (not just
    # within the new set) is refused — it is closed over as a constant.
    cfg2 = tmp_path / "lat.yaml"
    cfg2.write_text((tmp_path / "a.yaml").read_text().replace(
        "latency: 1 ms", "latency: 2 ms"))
    exp2 = load_experiment(str(cfg2))[0]
    with pytest.raises(FleetConfigError, match="lat_vv|window"):
        eng.rebind([exp2])
    assert eng.exp is exp  # failed rebinds roll back cleanly


def test_restart_sweeps_stale_batch_lineage_keeps_quarantines(tmp_path):
    spool = Spool(str(tmp_path / "s")).ensure()
    for name, body in (("b000003.npz", "stale"),
                       ("b000003.npz.lineage", "{}"),
                       ("b000003.npz.q1.npz", "deliverable")):
        with open(os.path.join(spool.batches, name), "w") as f:
            f.write(body)
    d = ServeDaemon(str(tmp_path / "s"))
    d.start()
    try:
        # The dead incarnation's lineage is swept (a torn head there
        # would make a NEW batch's resolve fall back onto a different
        # batch's snapshot) ...
        assert not os.path.exists(
            os.path.join(spool.batches, "b000003.npz"))
        assert not os.path.exists(
            os.path.join(spool.batches, "b000003.npz.lineage"))
        # ... the quarantined tenant's solo-resumable ckpt is kept ...
        assert os.path.exists(
            os.path.join(spool.batches, "b000003.npz.q1.npz"))
        # ... and new batch ids start past every name ever seen.
        assert d._batch_seq == 4
    finally:
        d.close()


def test_cache_lru_evicts(tmp_path):
    import dataclasses

    cache = EngineCache(capacity=1)
    exp, params, _ = load_experiment(write_cfg(tmp_path, "a.yaml"))
    cache.get([exp], params)
    cache.get([exp], dataclasses.replace(params, ev_cap=64))
    assert cache.counters()["cache_evictions"] == 1
    assert len(cache) == 1


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------

def test_admission_rejects_overbudget_before_compile(tmp_path, daemon,
                                                     monkeypatch):
    monkeypatch.setenv("SHADOW1_MEM_BYTES", str(1 << 20))  # 1 MiB
    big = tmp_path / "big.yaml"
    big.write_text(BASE.format(seed=5, stop=40, ev_cap=64,
                               init_events=3, loss="",
                               extra_engine="").replace(
        "count: 8", "count: 4096"))
    jid = client.submit(daemon.spool.root, str(big))
    daemon.step()
    st = daemon.spool.read_status(jid)
    assert st["state"] == "rejected", st
    err = st["error"]
    assert err["error"] == "memory_budget"
    assert err["estimated"] > err["budget"] == (1 << 20)
    assert "Remedies" in err["advice"]
    # rejected BEFORE any engine was built: the cache never saw a miss
    assert daemon.cache.counters()["cache_misses"] == 0
    assert client.exit_code_for(st) == EXIT_MEMORY


def test_admission_rejects_bad_configs(tmp_path, daemon):
    bad = tmp_path / "bad.yaml"
    bad.write_text("general: {seed: 1}\nnot_a_section: {x: 1}\n")
    jid = client.submit(daemon.spool.root, str(bad))
    sweep = tmp_path / "sweep.yaml"
    sweep.write_text(BASE.format(seed=5, stop=40, ev_cap=32,
                                 init_events=3, loss="",
                                 extra_engine="") + "sweep: {count: 2}\n")
    jid2 = client.submit(daemon.spool.root, str(sweep))
    daemon.step()
    for j in (jid, jid2):
        st = daemon.spool.read_status(j)
        assert st["state"] == "rejected", st
        assert st["error"]["error"] == "config"
        assert client.exit_code_for(st) == EXIT_CONFIG
    assert daemon.ledger_dict()["jobs_rejected"] == 2


# ---------------------------------------------------------------------------
# lane packing ≡ solo bit-exactness
# ---------------------------------------------------------------------------

def test_packed_jobs_bitexact_vs_solo(tmp_path, daemon):
    cfgs = [write_cfg(tmp_path, f"j{i}.yaml", seed=5 + i)
            for i in range(3)]
    jids = [client.submit(daemon.spool.root, c) for c in cfgs]
    assert daemon.step()
    # one batch, three lanes, one compile
    assert daemon.ledger_dict()["batches_run"] == 1
    assert daemon.cache.counters()["cache_misses"] == 1
    for jid, cfg in zip(jids, cfgs):
        st = daemon.spool.read_status(jid)
        assert st["state"] == "done", st
        assert st["lanes"] == 3
        served = served_stream(daemon.spool.root, jid)
        solo = solo_stream(cfg)
        assert served == solo  # every window, every digest word
        assert client.exit_code_for(st) == EXIT_OK


def test_incompatible_shapes_batch_separately(tmp_path, daemon):
    a = client.submit(daemon.spool.root,
                      write_cfg(tmp_path, "a.yaml", seed=5))
    b = client.submit(daemon.spool.root,
                      write_cfg(tmp_path, "b.yaml", seed=6, ev_cap=64))
    daemon.step()
    daemon.step()
    assert daemon.ledger_dict()["batches_run"] == 2
    for j in (a, b):
        assert daemon.spool.read_status(j)["state"] == "done"


# ---------------------------------------------------------------------------
# quarantine isolation
# ---------------------------------------------------------------------------

def test_quarantine_isolates_halting_tenant(tmp_path, daemon):
    # Shared shape class at ev_cap 8 under on_overflow=halt: the lossless
    # tenant overflows (every phold hop survives), the lossy one fits.
    halting = write_cfg(tmp_path, "halting.yaml", seed=5, ev_cap=8,
                        init_events=6,
                        extra_engine=", on_overflow: halt")
    ok = write_cfg(tmp_path, "ok.yaml", seed=6, ev_cap=8, loss=0.5,
                   init_events=6, extra_engine=", on_overflow: halt")
    j_bad = client.submit(daemon.spool.root, halting)
    j_ok = client.submit(daemon.spool.root, ok)
    daemon.step()
    st_bad = daemon.spool.read_status(j_bad)
    assert st_bad["state"] == "failed", st_bad
    assert st_bad["reason"] == "capacity"
    assert client.exit_code_for(st_bad) == EXIT_CAPACITY
    quar = [r for r in Spool(daemon.spool.root).read_results(j_bad)
            if r.get("type") == "fleet_quarantine"]
    assert quar and os.path.exists(quar[0]["ckpt"])
    st_ok = daemon.spool.read_status(j_ok)
    assert st_ok["state"] == "done", st_ok
    assert served_stream(daemon.spool.root, j_ok) == solo_stream(ok)


# ---------------------------------------------------------------------------
# priority eviction → requeue → bit-identical resume
# ---------------------------------------------------------------------------

def test_eviction_requeues_and_resumes_bitexact(tmp_path, daemon):
    lo_cfg = write_cfg(tmp_path, "lo.yaml", seed=5, stop=200)
    hi_cfg = write_cfg(tmp_path, "hi.yaml", seed=6, stop=40)
    j_lo = client.submit(daemon.spool.root, lo_cfg, priority=0)

    def late_submit():
        time.sleep(0.3)  # lands mid-batch; the latch sees it at a boundary
        client.submit(daemon.spool.root, hi_cfg, priority=5)

    t = threading.Thread(target=late_submit)
    t.start()
    daemon.step()   # j_lo's batch — evicted when the hi-pri job arrives
    t.join()
    st_lo = daemon.spool.read_status(j_lo)
    assert st_lo["state"] == "queued" and st_lo.get("resumed"), st_lo
    assert daemon.ledger_dict()["jobs_evicted"] == 1
    assert daemon.resume, "evicted batch must leave a resume cursor"
    daemon.step()   # the high-priority batch
    daemon.step()   # the evicted batch resumes from its checkpoint
    st_lo = daemon.spool.read_status(j_lo)
    assert st_lo["state"] == "done", st_lo
    # The stream spans eviction + resume and still bit-matches solo.
    assert served_stream(daemon.spool.root, j_lo) == solo_stream(lo_cfg)
    hi_jobs = [r for r in Spool(daemon.spool.root).scan_inbox()]
    assert hi_jobs == []  # everything drained


# ---------------------------------------------------------------------------
# spool protocol
# ---------------------------------------------------------------------------

def test_spool_submit_is_atomic_and_accept_moves_once(tmp_path):
    spool = Spool(str(tmp_path / "s")).ensure()
    jid = spool.submit({"config_yaml": "x: 1", "priority": 0})
    (path, job), = spool.scan_inbox()
    assert job["id"] == jid
    # a .tmp from an in-flight atomic write is invisible
    open(os.path.join(spool.inbox, "zz.json.tmp"), "w").write("{")
    assert len(spool.scan_inbox()) == 1
    spool.accept(path, job)
    assert spool.scan_inbox() == []
    with open(spool.job_path(jid)) as f:
        assert json.load(f)["id"] == jid


def test_unparseable_submission_rejected_not_fatal(tmp_path, daemon):
    with open(os.path.join(daemon.spool.inbox, "hand.json"), "w") as f:
        f.write("{torn")
    daemon._intake()
    assert daemon.ledger_dict()["jobs_rejected"] == 1
    assert os.path.exists(os.path.join(daemon.spool.inbox,
                                       "hand.json.bad"))


def test_spool_refuses_second_daemon(tmp_path, daemon):
    from shadow1_tpu.serve.daemon import SpoolError

    d2 = ServeDaemon(daemon.spool.root)
    with pytest.raises(SpoolError):
        d2.start()


# ---------------------------------------------------------------------------
# registry / report surfaces
# ---------------------------------------------------------------------------

def test_serve_registry_and_prometheus():
    from shadow1_tpu.telemetry.registry import (
        RECORD_TYPES,
        REC_SERVE,
        REC_SERVE_JOB,
        SERVE_SPECS,
        to_prometheus,
    )

    assert REC_SERVE in RECORD_TYPES and REC_SERVE_JOB in RECORD_TYPES
    text = to_prometheus({"jobs_done": 3, "jobs_queued": 2},
                         prefix="shadow1_serve", specs=SERVE_SPECS)
    assert "shadow1_serve_jobs_done_total 3" in text      # counter
    assert "shadow1_serve_jobs_queued 2" in text          # gauge, no _total
    assert "shadow1_serve_jobs_queued_total" not in text


def test_report_serve_section(capsys):
    import io

    from shadow1_tpu.tools.heartbeat_report import summarize

    recs = [
        {"type": "serve", "event": "batch_start", "batch": "b0",
         "cache": "miss", "lanes": 2},
        {"type": "serve", "event": "batch_start", "batch": "b1",
         "cache": "hit", "lanes": 1},
        {"type": "serve", "event": "evict", "batch": "b0", "jobs": ["a"]},
        {"type": "serve_job", "job": "a", "state": "queued", "t": 1.0},
        {"type": "serve_job", "job": "a", "state": "running", "lane": 0,
         "lanes": 2, "cache": "miss", "t": 2.0},
        {"type": "serve_job", "job": "a", "state": "evicted", "t": 3.0},
        {"type": "serve_job", "job": "a", "state": "done", "t": 9.0},
        {"type": "serve_job", "job": "b", "state": "rejected", "t": 1.0},
    ]
    out = io.StringIO()
    summary = summarize(recs, out=out)
    s = summary["serve"]
    assert s["jobs"] == 2 and s["batches"] == 2
    assert s["cache_hits"] == 1 and s["cache_misses"] == 1
    assert s["evictions"] == 1
    text = out.getvalue()
    assert "serve (daemon job ledger)" in text
    assert "evicted x1" in text and "wall 8.0s" in text


def test_client_exit_taxonomy():
    assert client.exit_code_for({"state": "done"}) == EXIT_OK
    assert client.exit_code_for(
        {"state": "rejected",
         "error": {"error": "memory_budget"}}) == EXIT_MEMORY
    assert client.exit_code_for(
        {"state": "rejected", "error": {"error": "config"}}) == EXIT_CONFIG
    assert client.exit_code_for(
        {"state": "failed", "reason": "capacity"}) == EXIT_CAPACITY
    assert client.exit_code_for(
        {"state": "failed", "reason": "memory_exhausted"}) == EXIT_MEMORY


# ---------------------------------------------------------------------------
# daemon subprocess: SIGTERM drain + queue persistence (slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_daemon_sigterm_drains_and_restart_finishes(tmp_path):
    spool = str(tmp_path / "spool")
    cfg = write_cfg(tmp_path, "long.yaml", seed=5, stop=400)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}

    def spawn():
        p = subprocess.Popen(
            [sys.executable, "-m", "shadow1_tpu", "serve",
             "--spool", spool, "--poll-s", "0.05"],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
            text=True)
        deadline = time.monotonic() + 60
        while Spool(spool).daemon_alive() is None:
            assert p.poll() is None, p.stderr.read()[-800:]
            assert time.monotonic() < deadline
            time.sleep(0.1)
        return p

    p = spawn()
    try:
        jid = client.submit(spool, cfg)
        # wait until the batch is actually running, then preempt the daemon
        deadline = time.monotonic() + 120
        while (Spool(spool).read_status(jid) or {}).get("state") \
                != "running":
            assert time.monotonic() < deadline
            time.sleep(0.1)
        p.send_signal(signal.SIGTERM)
        rc = p.wait(timeout=120)
        assert rc == EXIT_SERVE_SHUTDOWN, p.stderr.read()[-800:]
        # the queue persisted: the drained batch left a resume cursor
        with open(os.path.join(spool, "queue.json")) as f:
            q = json.load(f)
        assert q["queued"] or q["resume"], q
    finally:
        if p.poll() is None:
            p.kill()
    p = spawn()
    try:
        final = client.await_job(Spool(spool), jid, timeout_s=300,
                                 poll_s=0.1)
        assert final["state"] == "done", final
        assert served_stream(spool, jid) == solo_stream(cfg)
    finally:
        p.send_signal(signal.SIGTERM)
        assert p.wait(timeout=60) == EXIT_SERVE_SHUTDOWN
