"""Performance attribution plane (ISSUE 11): wasted-work gauges, phase
attribution, op/fusion census, multi-row bench gate.

Contracts under test:

* the RING_WORK gauge streams (active_hosts / elig_events / outbox_hosts)
  are bit-identical cpu↔tpu↔sharded(8), per window and as run totals, on a
  phold-with-loss config and a TCP (rung-1 filexfer) config;
* the ring schema widened in order (counters, work, gauges, digests) and
  CKPT_FORMAT bumped, with stale-version snapshots rejected;
* tools/opcensus.py: two census runs → identical counts; the drift gate
  trips on an injected extra-op build and on >tolerance baseline drift;
* tools/phaseprobe.py: the phase split reproduces window_step bit-exactly
  and accounts for ≥90% of the straight run's measured ms/round;
* tools/benchgate.py: per-row/per-backend gating logic (pure, unmeasured).
"""

import io
import json

import jax
import numpy as np
import pytest

from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import MS, EngineParams
from shadow1_tpu.core.engine import Engine, Metrics
from shadow1_tpu.cpu_engine import CpuEngine
from shadow1_tpu.telemetry.registry import (
    METRIC_SPECS,
    RING_COUNTERS,
    RING_DIGESTS,
    RING_FIELDS,
    RING_GAUGES,
    RING_WORK,
)
from shadow1_tpu.telemetry.ring import drain_ring

WORK = ("active_hosts", "elig_events", "outbox_hosts")


def phold_exp(n_hosts=32, seed=17, end_time=100 * MS, loss=0.0):
    return single_vertex_experiment(
        n_hosts=n_hosts, seed=seed, end_time=end_time, latency_ns=1 * MS,
        loss=loss, model="phold",
        model_cfg={"mean_delay_ns": float(2 * MS), "init_events": 2},
    )


def rung1_exp():
    import os

    from shadow1_tpu.config.experiment import load_experiment

    cfg = os.path.join(os.path.dirname(__file__), "..", "configs",
                       "rung1_filexfer.yaml")
    return load_experiment(cfg)


# ---------------------------------------------------------------------------
# schema
# ---------------------------------------------------------------------------

def test_work_schema_and_ckpt_format():
    from shadow1_tpu.ckpt import CKPT_FORMAT

    # The work counters are canonical Metrics counters and their ring
    # columns sit between the counter deltas and the gauges.
    assert set(RING_WORK) <= set(METRIC_SPECS)
    assert set(RING_WORK) <= set(Metrics._fields)
    assert RING_FIELDS == RING_COUNTERS + RING_WORK + RING_GAUGES + \
        RING_DIGESTS
    # Widened ring row + new Metrics leaves = snapshot layout change
    # (v10); the flow-probe ring leaf bumped it again (v11), and the
    # link-telemetry accumulator leaf once more (v12).
    assert CKPT_FORMAT == 12


def test_stale_ckpt_format_rejected(tmp_path):
    from shadow1_tpu import ckpt

    eng = Engine(phold_exp(end_time=20 * MS), EngineParams(metrics_ring=4))
    st = eng.run(n_windows=5)
    path = str(tmp_path / "snap.npz")
    ckpt.save_state(st, path)
    with np.load(path) as d:
        arrs = {k: d[k].copy() for k in d.files}
    arrs["format"][0] = ckpt.CKPT_FORMAT - 1  # the previous layout
    np.savez(path, **arrs)
    with pytest.raises(ValueError, match=f"format v{ckpt.CKPT_FORMAT - 1}"
                                         f".*reads v{ckpt.CKPT_FORMAT}"):
        ckpt.load_state(eng.init_state(), path)


# ---------------------------------------------------------------------------
# gauge parity cpu <-> tpu <-> sharded
# ---------------------------------------------------------------------------

def _assert_work_parity(exp, params, n_windows):
    eng = Engine(exp, params)
    st = eng.run(n_windows=n_windows)
    rows = drain_ring(st, exp.window)
    cpu = CpuEngine(exp, params)
    cm = cpu.run(n_windows)
    tm = Engine.metrics_dict(st)
    assert tm["ev_overflow"] == 0 and tm["ob_overflow"] == 0
    assert len(rows) == len(cpu.work_rows) == n_windows
    for r, w in zip(rows, cpu.work_rows):
        assert w["type"] == "work"
        assert r["window"] == w["window"]
        for f in WORK:
            assert r[f] == w[f], (r["window"], f, r[f], w[f])
    for f in WORK:
        assert tm[f] == cm[f], (f, tm[f], cm[f])
    # The gauges actually observe the pathology signal: some window had
    # fewer active hosts than the plane width.
    assert tm["active_hosts"] > 0
    assert min(r["active_hosts"] for r in rows) <= exp.n_hosts
    return rows, tm


def test_work_gauge_parity_phold_loss():
    rows, tm = _assert_work_parity(
        phold_exp(loss=0.05), EngineParams(metrics_ring=128), 100)
    # elig_events >= active_hosts per window (>=1 event per active host).
    assert all(r["elig_events"] >= r["active_hosts"] for r in rows)


def test_work_gauge_parity_net_tcp():
    exp, params, _ = rung1_exp()
    import dataclasses

    params = dataclasses.replace(params, metrics_ring=64)
    rows, tm = _assert_work_parity(exp, params, 30)
    # The rung-1 flow is SPARSE: most windows touch a strict host subset —
    # the exact wasted-work signal the plane exists to surface.
    assert min(r["active_hosts"] for r in rows) < exp.n_hosts


def test_work_gauge_sharded_bitexact():
    from shadow1_tpu.shard.engine import ShardedEngine

    exp = phold_exp(n_hosts=64, seed=7, end_time=50 * MS)
    params = EngineParams(metrics_ring=64)
    st1 = Engine(exp, params).run(n_windows=50)
    sh = ShardedEngine(exp, params)
    assert sh.n_dev == 8, "conftest must provide 8 virtual devices"
    st8 = sh.run(n_windows=50)
    r1 = drain_ring(st1, exp.window)
    r8 = drain_ring(st8, exp.window)
    for a, b in zip(r1, r8):
        for f in WORK:
            assert a[f] == b[f], (a["window"], f)
    m1, m8 = Engine.metrics_dict(st1), Engine.metrics_dict(st8)
    for f in WORK:
        assert m1[f] == m8[f], f


def test_work_gauges_resume_bitexact(tmp_path):
    """The work-gauge stream is a pure boundary function: a checkpointed +
    resumed run carries the identical per-window rows."""
    from shadow1_tpu.ckpt import load_state, save_state

    eng = Engine(phold_exp(), EngineParams(metrics_ring=64))
    ref = eng.run(n_windows=60)
    st = eng.run(n_windows=25)
    path = str(tmp_path / "work.npz")
    save_state(st, path)
    final = eng.run(load_state(eng.init_state(), path), n_windows=35)
    ra, rb = drain_ring(ref, eng.window), drain_ring(final, eng.window)
    for a, b in zip(ra, rb):
        for f in WORK:
            assert a[f] == b[f]


def test_oracle_work_accounting_gated_on_ring():
    """Pay-for-use on the oracle: without a ring the per-boundary heap
    scans never run (the batched engines record per-window values only via
    the ring, so there is nothing to mirror)."""
    cpu = CpuEngine(phold_exp(), EngineParams())
    cm = cpu.run(20)
    assert not cpu.work_rows
    assert cm["active_hosts"] == 0 and cm["elig_events"] == 0


def test_fleet_lane_work_columns_match_solo():
    """Fleet ring rows carry the same per-lane work columns a solo run of
    that experiment records (the fleet contract extends to the new
    columns)."""
    from shadow1_tpu.fleet.engine import FleetEngine, slice_experiment

    exps = [phold_exp(seed=5, end_time=20 * MS),
            phold_exp(seed=6, end_time=20 * MS)]
    params = EngineParams(metrics_ring=32)
    fleet = FleetEngine(exps, params)
    stf = fleet.run(n_windows=20)
    for e, exp in enumerate(exps):
        lane = slice_experiment(stf, e)
        solo = Engine(exp, params).run(n_windows=20)
        ra = drain_ring(lane, exp.window)
        rb = drain_ring(solo, exp.window)
        for a, b in zip(ra, rb):
            for f in WORK:
                assert a[f] == b[f], (e, a["window"], f)


# ---------------------------------------------------------------------------
# heartbeat + report
# ---------------------------------------------------------------------------

def test_heartbeat_work_block_and_report(capsys):
    from shadow1_tpu.obs import run_with_heartbeat
    from shadow1_tpu.tools import heartbeat_report as hr

    eng = Engine(phold_exp(), EngineParams(metrics_ring=32))
    buf = io.StringIO()
    run_with_heartbeat(eng, n_windows=60, every_windows=20, stream=buf)
    recs = [json.loads(x) for x in buf.getvalue().splitlines()]
    hbs = [r for r in recs if r["type"] == "heartbeat"]
    rings = [r for r in recs if r["type"] == "ring"]
    # The chunk's work block: summed window samples + denominators, and the
    # samples leave ``delta`` like the fill gauges.
    for i, h in enumerate(hbs):
        assert "active_hosts" not in h["delta"]
        w = h["work"]
        assert w["n_hosts"] == 32
        chunk = [r for r in rings if i * 20 <= r["window"] < (i + 1) * 20]
        for f in WORK:
            assert w[f] == sum(r[f] for r in chunk), f
        assert 0 < w["active_frac"] <= 1
    summary = hr.summarize(recs)
    out = capsys.readouterr().out
    ws = summary["work"]
    assert ws["windows"] == 60 and ws["n_hosts"] == 32
    for key in ("active_frac", "pop_scan_eff", "outbox_frac"):
        d = ws[key]
        assert 0 <= d["min"] <= d["p50"] <= d["p95"] <= 1, (key, d)
    assert "== work efficiency (wasted-work accounting) ==" in out
    # The utilization samples stay OUT of the occupancy percentile table.
    assert "active_hosts" not in summary["ring"]
    ring_section = out.split("per-window occupancy (ring)")[1] \
                      .split("== work efficiency")[0]
    assert "active_hosts" not in ring_section


def test_report_on_oracle_work_rows(tmp_path):
    from shadow1_tpu.tools import heartbeat_report as hr

    params = EngineParams(metrics_ring=16)
    cpu = CpuEngine(phold_exp(), params)
    cpu.run(20)
    log = tmp_path / "cpu.log"
    log.write_text("\n".join(json.dumps(r) for r in cpu.work_rows) + "\n")
    summary = hr.summarize(hr.load_records(str(log)), out=io.StringIO())
    assert summary["work"]["windows"] == 20
    assert "active_hosts" in summary["work"]  # absolute stats (no n_hosts)


# ---------------------------------------------------------------------------
# opcensus
# ---------------------------------------------------------------------------

def _small_engine():
    return Engine(phold_exp(n_hosts=16, end_time=20 * MS),
                  EngineParams(ev_cap=16, outbox_cap=8))


def test_opcensus_deterministic():
    from shadow1_tpu.tools.opcensus import census

    a = census(_small_engine(), sources=True)
    b = census(_small_engine(), sources=True)
    assert a == b
    assert a["eqns"]["rounds"] > a["eqns"]["pop"] > 0
    # Source attribution reaches the library layers (the round-5 census's
    # grouping).
    assert any(s.startswith("events.") for s in a["sources"]["rounds"])


def test_opcensus_gate_logic():
    from shadow1_tpu.tools.opcensus import gate_config

    base = {"eqns": {"rounds": 400, "deliver": 250}}
    ok = {"eqns": {"rounds": 420, "deliver": 250}}       # +5% — inside
    assert gate_config(ok, base, 0.10) == []
    drift = {"eqns": {"rounds": 480, "deliver": 250}}    # +20% — drift
    fails = gate_config(drift, base, 0.10)
    assert len(fails) == 1 and "rounds" in fails[0]
    gone = {"eqns": {"deliver": 250}}
    assert any("vanished" in f for f in gate_config(gone, base, 0.10))
    new = {"eqns": {"rounds": 400, "deliver": 250, "extra": 9}}
    assert any("new phase" in f for f in gate_config(new, base, 0.10))


def test_opcensus_injected_ops_trip_gate():
    from shadow1_tpu.tools.opcensus import census, gate_config

    eng = _small_engine()
    clean = census(eng)
    injected = census(eng, inject=max(60, clean["eqns"]["rounds"] // 2))
    assert injected["eqns"]["rounds"] > clean["eqns"]["rounds"]
    fails = gate_config(injected, clean, 0.10)
    assert fails and "rounds" in fails[0]
    # Other phases untouched by the injection.
    assert injected["eqns"]["deliver"] == clean["eqns"]["deliver"]


# ---------------------------------------------------------------------------
# phaseprobe
# ---------------------------------------------------------------------------

def test_phaseprobe_coverage_smoke_phold():
    """The acceptance bound: the phase split accounts for ≥90% of the
    straight run's measured ms/round (attribution() also asserts the staged
    composition reproduced window_step's metrics bit-exactly)."""
    from shadow1_tpu.tools.phaseprobe import attribution, build_engine

    eng, label = build_engine("smoke", hosts=256)
    att = attribution(eng, n_windows=6, warmup=3, reps=2)
    assert label == "smoke_phold"
    assert set(att["phases"]) == {"prepare", "rounds", "deliver", "telem"}
    assert att["coverage"] >= 0.9, att
    assert att["phases"]["rounds"]["pct"] > 50  # rounds dominate phold
    assert "rounds.pop_est" in att["subphases"]


def test_window_phases_compose_to_window_step():
    """The staged composition IS window_step — bit-for-bit, ring included."""
    from shadow1_tpu.core.engine import window_frame, window_phases

    eng = Engine(phold_exp(n_hosts=16, end_time=20 * MS),
                 EngineParams(metrics_ring=8))
    st_a = eng.run(n_windows=10)
    st_b = eng.init_state()
    phases = window_phases(eng.ctx, eng._handlers, None, eng._pre_window,
                           eng._model.make_handlers, None)
    jitted = {n: jax.jit(f) for n, f in phases}
    for _ in range(10):
        fr = window_frame(st_b, eng.ctx)
        for n, _f in phases:
            fr = jitted[n](fr)
        st_b = fr.st
    la = jax.tree_util.tree_leaves(st_a)
    lb = jax.tree_util.tree_leaves(st_b)
    for a, b in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_device_trace_context(tmp_path):
    from shadow1_tpu.telemetry import PhaseProfiler, device_trace

    prof = PhaseProfiler()
    eng = Engine(phold_exp(n_hosts=16, end_time=20 * MS), EngineParams())
    st = eng.run(n_windows=2)
    with device_trace(str(tmp_path / "dt"), profiler=prof):
        jax.block_until_ready(eng.run(st, n_windows=2))
    assert "device-trace" in prof.span_names()


# ---------------------------------------------------------------------------
# benchgate rows
# ---------------------------------------------------------------------------

def test_benchgate_row_logic():
    from shadow1_tpu.tools.benchgate import gate_row

    host = "cpuX x8"
    row = {"ms_per_round": 10.5, "backend": "cpu", "host": host}
    base = {"ms_per_round": 10.0, "tolerance": 0.05, "host": host}
    assert gate_row("r", row, base, host, None)["gate"] == "ok"
    slow = {**row, "ms_per_round": 11.0}                  # +10% > 5%
    assert gate_row("r", slow, base, host, None)["gate"] == "failed"
    assert gate_row("r", slow, base, host, "why")["gate"] == "accepted"
    # Missing baseline for THIS backend: the row reports, never auto-skips
    # the whole gate (a TPU baseline can coexist with the CPU one).
    v = gate_row("r", row, None, host, None)
    assert v["gate"] == "no_baseline_for_backend"
    v = gate_row("r", row, {**base, "host": "other"}, host, None)
    assert v["gate"] == "skipped_host_mismatch"
    # Per-row tolerance honoured.
    wide = {**base, "tolerance": 0.20}
    assert gate_row("r", slow, wide, host, None)["gate"] == "ok"


def test_benchgate_rows_registry():
    from shadow1_tpu.tools import benchgate

    assert set(benchgate.ROWS) == {"phold_smoke", "sparse_rung1",
                                   "fleet_smoke"}
