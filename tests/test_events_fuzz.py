"""Randomized differential test of the batched event buffer.

A few hundred random push/pop/rebase/deliver operations run against a plain
Python heap model; every pop's (mask, time, kind, tb, payload) and the
final buffer census must match exactly, for BOTH pop/push implementations
(XLA reductions and the fused Pallas kernels, interpret mode on CPU).

This is the unstructured counterpart of tests/test_events.py: the
structured tests pin the documented contracts; the fuzz sweep hunts the
interactions nobody thought to write down (epoch advances between pushes,
same-time tb ties across push/deliver sources, overflow under load,
past-due leftovers, eligibility-counter drift). The heap model is ~40
lines of obviously-correct Python — the judge's "real OS as oracle" trick
(SURVEY §4) scaled down to the data structure.
"""

import heapq

import jax.numpy as jnp
import numpy as np
import pytest

from shadow1_tpu.consts import NP, TB_PACKET_BASE
from shadow1_tpu.core import events as ev
from shadow1_tpu.core.popk import (
    pop_until_fused,
    push_back_fused,
    push_local_fused,
)


class HeapModel:
    """Per-host (time, tb) heaps with the engine's exact semantics."""

    def __init__(self, n_hosts, cap):
        self.h = [[] for _ in range(n_hosts)]
        self.cap = cap
        self.self_ctr = [0] * n_hosts

    def push_local(self, mask, time, kind, p):
        over = []
        for i, m in enumerate(mask):
            if not m:
                over.append(False)
                continue
            if len(self.h[i]) >= self.cap:
                over.append(True)
                continue
            heapq.heappush(
                self.h[i], (int(time[i]), self.self_ctr[i], int(kind[i]),
                            tuple(int(x) for x in p[:, i]))
            )
            self.self_ctr[i] += 1
            over.append(False)
        return over

    def push_back(self, mask, time, tb, kind, p):
        over = []
        for i, m in enumerate(mask):
            if not m:
                over.append(False)
                continue
            if len(self.h[i]) >= self.cap:
                over.append(True)
                continue
            heapq.heappush(
                self.h[i], (int(time[i]), int(tb[i]), int(kind[i]),
                            tuple(int(x) for x in p[:, i]))
            )
            over.append(False)
        return over

    def deliver(self, dst, time, tb, kind, p, mask):
        n_over = 0
        for j, m in enumerate(mask):
            if not m:
                continue
            d = int(dst[j])
            if len(self.h[d]) >= self.cap:
                n_over += 1
                continue
            heapq.heappush(
                self.h[d], (int(time[j]), int(tb[j]), int(kind[j]),
                            tuple(int(x) for x in p[:, j]))
            )
        return n_over

    def pop_until(self, until):
        out = []
        for i, hp in enumerate(self.h):
            if hp and hp[0][0] < until:
                out.append(heapq.heappop(hp))
            else:
                out.append(None)
        return out

    def census(self):
        return [sorted(hp) for hp in self.h]


def buf_census(buf):
    """Live events per host as sorted (time, tb, kind, payload) lists."""
    kind = np.asarray(buf.kind)
    t = np.asarray(buf.abs_time())
    tb = np.asarray(ev.tb_join(buf.tb_hi, buf.tb_lo))
    p = np.asarray(buf.p)
    cap, n = kind.shape
    out = []
    for i in range(n):
        rows = [
            (int(t[c, i]), int(tb[c, i]), int(kind[c, i]),
             tuple(int(x) for x in p[:, c, i]))
            for c in range(cap) if kind[c, i] != 0
        ]
        out.append(sorted(rows))
    return out


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_event_core_fuzz_vs_heap_model(impl):
    rng = np.random.default_rng(20260731)
    H, C = 6, 10
    buf = ev.evbuf_init(H, C)
    model = HeapModel(H, C)
    epoch = 0
    until_bound = 10_000
    pkt_ctr = 0

    def do_rebase(e, u):
        nonlocal buf, epoch
        epoch = e
        # Engine convention: the eligibility bound is epoch-relative
        # (win_end = win_start + W); pops below use until ≤ e + u.
        buf = ev.rebase(buf, e, e + u)

    do_rebase(0, until_bound)
    for step in range(300):
        op = rng.choice(["push", "pop", "pop", "rebase", "deliver",
                         "pushback"])
        if op == "push":
            mask = rng.random(H) < 0.7
            # Narrow time range forces (time, tb) ties; occasional far
            # future and past-due (pre-epoch) values exercise the clamps.
            t = epoch + rng.integers(-50, 200, H)
            t = np.maximum(t, 0)
            if rng.random() < 0.1:
                t = t + 5 * 10**9          # beyond the i32 horizon
            kind = rng.integers(1, 5, H)
            p = rng.integers(0, 100, (NP, H))
            over_m = model.push_local(mask, t, kind, p)
            buf, over = ev.push_local(
                buf, jnp.asarray(mask), jnp.asarray(t, jnp.int64),
                jnp.asarray(kind, jnp.int32), jnp.asarray(p, jnp.int32),
            ) if impl == "xla" else push_local_fused(
                buf, jnp.asarray(mask), jnp.asarray(t, jnp.int64),
                jnp.asarray(kind, jnp.int32), jnp.asarray(p, jnp.int32),
            )
            assert np.asarray(over).tolist() == over_m, step
        elif op == "pop":
            until = epoch + int(rng.integers(0, until_bound))
            got = model.pop_until(until)
            if impl == "xla":
                buf, pe = ev.pop_until(buf, jnp.int64(until))
            else:
                buf, pe = pop_until_fused(buf, jnp.int64(until))
            for i, exp in enumerate(got):
                if exp is None:
                    assert not bool(pe.mask[i]), (step, i)
                else:
                    assert bool(pe.mask[i]), (step, i)
                    assert int(pe.time[i]) == exp[0], (step, i)
                    assert int(pe.tb[i]) == exp[1], (step, i)
                    assert int(pe.kind[i]) == exp[2], (step, i)
                    assert tuple(int(x) for x in pe.p[:, i]) == exp[3]
        elif op == "pushback":
            # Re-insert events with EXPLICIT (caller-owned) tie-breaks —
            # the cpu-model defer/requeue path (events.push_back).
            mask = rng.random(H) < 0.5
            t = epoch + rng.integers(0, 300, H)
            tb = TB_PACKET_BASE + pkt_ctr + np.arange(H)
            pkt_ctr += H
            kind = rng.integers(1, 5, H)
            p = rng.integers(0, 100, (NP, H))
            over_m = model.push_back(mask, t, tb, kind, p)
            fn = ev.push_back if impl == "xla" else push_back_fused
            buf, over = fn(
                buf, jnp.asarray(mask), jnp.asarray(t, jnp.int64),
                jnp.asarray(tb, jnp.int64), jnp.asarray(kind, jnp.int32),
                jnp.asarray(p, jnp.int32),
            )
            assert np.asarray(over).tolist() == over_m, step
        elif op == "rebase":
            # Epoch only advances (window starts are monotone).
            do_rebase(epoch + int(rng.integers(0, 300)), until_bound)
        else:  # deliver (window-granularity: rebase precedes next pops)
            n = int(rng.integers(1, 8))
            dst = rng.integers(0, H, n)
            t = epoch + rng.integers(0, 500, n)
            tb = TB_PACKET_BASE + np.arange(pkt_ctr, pkt_ctr + n)
            pkt_ctr += n
            kind = rng.integers(1, 5, n)
            p = rng.integers(0, 100, (NP, n))
            mask = rng.random(n) < 0.9
            n_over_m = model.deliver(dst, t, tb, kind, p, mask)
            buf, n_over = ev.deliver_batch(
                buf, jnp.asarray(dst, jnp.int32), jnp.asarray(t, jnp.int64),
                jnp.asarray(tb, jnp.int64), jnp.asarray(kind, jnp.int32),
                jnp.asarray(p, jnp.int32), jnp.asarray(mask),
            )
            assert int(n_over) == n_over_m, step
            do_rebase(epoch, until_bound)

    assert buf_census(buf) == model.census()


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_n_elig_counter_matches_plane_scan(impl):
    """After an arbitrary op sequence the maintained eligibility counters
    equal a fresh plane scan (the invariant any_eligible/compaction rely
    on) — for both implementations."""
    rng = np.random.default_rng(7)
    H, C = 5, 8
    buf = ev.evbuf_init(H, C)
    buf = ev.rebase(buf, 0, 1000)
    k = jnp.full(H, 1, jnp.int32)
    push = ev.push_local if impl == "xla" else push_local_fused
    pop = ev.pop_until if impl == "xla" else pop_until_fused
    for _ in range(40):
        m = jnp.asarray(rng.random(H) < 0.6)
        t = jnp.asarray(rng.integers(0, 2000, H), jnp.int64)  # some inelig
        buf, _ = push(buf, m, t, k, jnp.zeros((NP, H), jnp.int32))
        if rng.random() < 0.5:
            buf, _ = pop(buf, jnp.int64(1000))
        scan = ((np.asarray(buf.kind) != 0)
                & (np.asarray(buf.t32) < int(buf.u32))).sum(axis=0)
        assert np.asarray(buf.n_elig).tolist() == scan.tolist()
