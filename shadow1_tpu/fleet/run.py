"""Chunked fleet runner — heartbeats, rings, checkpoints, recovery, records.

The fleet twin of ``obs.run_with_heartbeat`` + the CLI's final-JSON
assembly, built per-experiment from the ground up:

* the telemetry ring drains PER EXPERIMENT (``type: "ring"`` records with
  an ``exp`` field — the per-window series and digest words of lane e are
  exactly a solo run's, docs/OBSERVABILITY.md §"Fleet records");
* heartbeats carry the fleet-aggregate deltas plus a compact per-
  experiment events vector (one record per chunk, not E);
* ``--on-overflow halt`` and ``--selfcheck`` run their boundary checks
  per experiment — a CapacityExceededError names the experiment (and its
  seed) whose cap overflowed;
* checkpoints snapshot the WHOLE fleet state (one .npz, every leaf with
  its leading [E] axis) at heartbeat boundaries, same atomic write +
  progress sidecar as the solo path — a resumed fleet continues
  bit-identically, and ``fleet.engine.slice_experiment`` extracts any one
  lane as a solo-resumable state.

**The fleet recovery plane** (docs/SEMANTICS.md §"Fleet recovery
contract") lives in this module's chunk loop — ``ckpt.run_chunked``
cannot express a mid-loop change of E, so the loop is owned here with the
same boundary semantics (commit before heartbeat/snapshot, drain latch
sampled before the save, a window never split):

* **transactional retry** (``--on-overflow retry``): the whole ``[E, ...]``
  pytree is the rollback point; any lane's fresh overflow taints the
  chunk (txn.OverflowGuard sums the [E] counters — the psum idiom), the
  fleet-uniform cap grows one ladder step via the leading-axis-aware
  ``tune/resize.py`` migration, and the SAME chunk replays bit-exactly,
  so every committed chunk is overflow-free in every lane and per-lane
  digest streams match the straight big-cap fleet run
  (tools/fleetprobe.py --retry);
* **lane quarantine** (``--on-lane-fail quarantine``): a lane that fails
  DETERMINISTICALLY (capacity halt / retry-ladder exhaustion attributed
  to it, per-lane selfcheck violation) is sliced out of the chunk-START
  state into a solo-resumable checkpoint plus a structured
  ``fleet_quarantine`` record, the survivors repack into an E-1 fleet
  (re-jit; survivor streams provably unchanged — lanes are
  vmap-independent) and the chunk replays — the sweep finishes at E-k/E.
  When every lane quarantines, the last failure re-raises so the exit
  taxonomy is preserved (capacity → EXIT_CAPACITY);
* **mid-sweep finalization** (``--lane-finalize``): lanes whose event
  buffer has fully drained are finalized at committed boundaries — their
  ``fleet_exp`` final record (``finished_early: true``) emits
  immediately and they are sliced out the quarantine way.

Every repacked-fleet snapshot carries the surviving global lane ids in
its lineage manifest entry (``lanes``), so a resume mid-quarantined-sweep
rebuilds exactly the surviving sub-fleet (cli._fleet_main).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

from shadow1_tpu.consts import SEC
from shadow1_tpu.telemetry.registry import DROP_FIELDS, normalize


class FleetHeartbeat:
    """Per-chunk fleet heartbeat: aggregate deltas + per-experiment events.

    One record per chunk boundary (type ``heartbeat`` with a ``fleet``
    block), so existing consumers (tools/heartbeat_report.py) read the
    aggregate series unchanged while fleet-aware ones use the block.

    The runner mutates ``engine``/``labels`` live (cap-grow re-jits,
    quarantine/finalize repacks) and carries its recovery ledger on
    ``recovery`` — callers keep unpacking ``(st, hb)`` and read the final
    fleet shape off the heartbeat."""

    def __init__(self, engine, stream=None, initial_state=None,
                 emit_heartbeat=True, emit_ring=True, guard=None):
        self.engine = engine
        self.stream = stream if stream is not None else sys.stderr
        self.emit_heartbeat = emit_heartbeat
        self.emit_ring = emit_ring
        self.guard = guard  # txn.OverflowGuard — source of the retries block
        self.t_start = time.perf_counter()
        self.t_last = self.t_start
        self.last = (normalize(engine.metrics_dict(initial_state))
                     if initial_state is not None else {})
        self.last_per_exp = (engine.metrics_per_exp(initial_state)
                             if initial_state is not None else None)
        self._ring_next = self.last.get("windows", 0)
        self.records: list[dict] = []
        self.ring_records: list[dict] = []
        self.labels: list[dict] = []        # live per-lane identity
        self.recovery: dict = {"quarantined": [], "finished": [],
                               "retry_records": []}

    def rebase(self, engine, st) -> None:
        """Re-baseline after the runner swapped the engine AND the state no
        longer continues the last-seen one (a rollback replay or a lane
        repack): the next delta must cover exactly the committed chunk."""
        self.engine = engine
        self.last = normalize(engine.metrics_dict(st))
        self.last_per_exp = engine.metrics_per_exp(st)
        self._ring_next = self.last.get("windows", 0)

    def _emit(self, rec: dict) -> None:
        if self.stream:
            print(json.dumps(rec), file=self.stream, flush=True)

    def __call__(self, st, done_windows: int, per_exp=None) -> None:
        now = time.perf_counter()
        m = normalize(self.engine.metrics_dict(st))
        # The chunk runner already fetched the per-experiment dicts for its
        # halt/selfcheck boundary checks — reuse them, don't re-sync.
        if per_exp is None:
            per_exp = self.engine.metrics_per_exp(st)
        ring_recs = self.engine.drain_rings(st, start=self._ring_next)
        self._ring_next = m.get("windows", 0)
        delta = {k: v - self.last.get(k, 0) for k, v in m.items()
                 if isinstance(v, int)}
        dt = now - self.t_last
        d_windows = delta.get("windows", 0)
        ev_per_exp = [int(d["events"]) for d in per_exp]
        if (self.last_per_exp is not None
                and len(self.last_per_exp) == len(per_exp)):
            ev_per_exp = [e - int(l["events"]) for e, l in
                          zip(ev_per_exp, self.last_per_exp)]
        rec = {
            "type": "heartbeat",
            "sim_time_s": round(int(np.asarray(st.win_start).max()) / SEC, 6),
            "wall_s": round(now - self.t_start, 3),
            "windows": done_windows,
            "events_per_sec": round(delta.get("events", 0) / dt, 1)
            if dt > 0 else None,
            "rounds_per_window": round(delta.get("rounds", 0) / d_windows, 2)
            if d_windows else None,
            "delta": delta,
            "fleet": {
                "experiments": self.engine.n_exp,
                # Global ids beside the vector: after a quarantine/finalize
                # the surviving positions are non-contiguous.
                "exps": [l.get("exp") for l in self.labels]
                if self.labels else list(range(self.engine.n_exp)),
                "events_per_exp": ev_per_exp,
            },
        }
        drops = {f: delta.pop(f, 0) for f in DROP_FIELDS}
        rec["drops"] = {"total": sum(drops.values()), **drops}
        # Host-side retry counters never ride engine deltas (registry
        # HOST_FIELDS); the retries block carries them cumulatively.
        from shadow1_tpu.telemetry.registry import HOST_FIELDS

        for f in HOST_FIELDS:
            delta.pop(f, None)
        if self.guard is not None and self.guard.chunk_retries:
            rec["retries"] = self.guard.report()
        self.records.append(rec)
        if self.emit_heartbeat:
            self._emit(rec)
        for r in ring_recs:
            self.ring_records.append(r)
            if self.emit_ring:
                self._emit(r)
        self.t_last = now
        self.last = m
        self.last_per_exp = per_exp


def _check_halt(engine, plan_labels, per_exp, prev_per_exp, done, step):
    """Per-experiment overflow halt: the first lane with fresh overflow
    raises a CapacityExceededError that names it (``lanes`` carries the
    local index for the quarantine policy)."""
    from shadow1_tpu.txn import CapacityExceededError
    from shadow1_tpu.tune.ladder import recommend_cap

    checks = (("ev_overflow", "ev_cap", "ev_max_fill"),
              ("ob_overflow", "outbox_cap", "ob_max_fill"))
    for e, m in enumerate(per_exp):
        prev = prev_per_exp[e] if prev_per_exp else {}
        for counter, knob, gauge in checks:
            fresh = int(m.get(counter, 0)) - int(prev.get(counter, 0))
            if fresh > 0:
                label = plan_labels[e] if plan_labels else {"exp": e}
                gv = int(m.get(gauge, 0))
                raise CapacityExceededError(
                    knob=knob, counter=counter,
                    cap=getattr(engine.params, knob), overflow=fresh,
                    window_range=(done, done + step),
                    recommended=recommend_cap(gv) if gv else None,
                    detail=(f" (fleet experiment {label.get('exp', e)}, "
                            f"seed {label.get('seed', '?')})"),
                    lanes=[e],
                )


def lane_record(engine, st, i: int, label: dict, windows: int,
                m: dict | None = None) -> dict:
    """One ``fleet_exp`` final record for lane ``i`` of a fleet state —
    the unit final_records() assembles and the early-finalize path emits
    immediately (docs/OBSERVABILITY.md §"Fleet records"). ``m`` reuses an
    already-fetched per-experiment metrics dict."""
    if m is None:
        m = engine.metrics_per_exp(st)[i]
    params = engine.params
    drops = {f: int(m.get(f, 0)) for f in DROP_FIELDS}
    rec = {
        "type": "fleet_exp",
        **label,
        "engine": "fleet",
        "hosts": engine.exp.n_hosts,
        "window_ns": engine.window,
        "windows": windows,
        "caps": {"ev_cap": params.ev_cap, "outbox_cap": params.outbox_cap,
                 "compact_cap": params.compact_cap},
        "metrics": m,
        "drops": {"total": sum(drops.values()), **drops},
    }
    restarts = int(m.get("host_restarts", 0))
    fault_drops = {k: drops[k] for k in
                   ("down_events", "down_pkts", "link_down_pkts")}
    if restarts or any(fault_drops.values()):
        rec["faults"] = {"host_restarts": restarts, **fault_drops}
    return rec


def run_fleet(engine, st=None, n_windows=None, every_windows=None,
              stream=None, ckpt_path=None, ckpt_every_s=120.0,
              emit_heartbeat=True, emit_ring=True, selfcheck=False,
              labels=None, ckpt_keep=3, drain=None, auto_caps=False,
              quarantine_base=None, emit_record=None, resume_meta=None,
              recovery_seed=None):
    """Run the fleet in chunks. Returns (final_state, FleetHeartbeat).

    Mirrors ``obs.run_with_heartbeat`` (compile excluded from the first
    chunk's rate, checkpoints rotated through a ``ckpt_keep``-deep
    lineage.Lineage generation set throttled to ``ckpt_every_s``, the
    ``.progress`` sidecar refreshed atomically at EVERY chunk boundary,
    a pending ``drain`` request forcing the snapshot then raising
    preempt.PreemptedExit) — plus the fleet recovery plane described in
    the module docstring, driven by ``engine.params``:

    * ``on_overflow == "retry"`` → a txn.OverflowGuard makes chunks
      transactional over the whole [E, ...] pytree;
    * ``on_overflow == "halt"`` → the per-lane boundary check raises a
      CapacityExceededError naming the experiment;
    * ``on_lane_fail == "quarantine"`` → deterministic per-lane failures
      slice the lane out (checkpoint at ``quarantine_base``.q<exp>.npz,
      default the --ckpt path or "fleet_lane") and the sweep continues;
    * ``lane_finalize`` → drained lanes emit their final record and leave
      the fleet at committed boundaries;
    * ``auto_caps`` → a tune.CapController retunes caps between chunks
      from the fleet-global fill gauges.

    ``emit_record`` (callable) receives each immediately-final stdout
    record (``fleet_quarantine``, early ``fleet_exp``) so the CLI can
    print them as they happen; ``resume_meta`` keys ride every lineage
    manifest entry (the sub-batch cursor); ``recovery_seed``
    ({"quarantined": [gids], "finished": [gids]} from a resumed
    generation's meta) pre-populates the ledger so a respawned process's
    final summary still reports lanes that left the fleet before the
    crash. The heartbeat's ``engine`` / ``labels`` / ``recovery``
    attributes expose the live fleet shape."""
    import jax

    from shadow1_tpu import ckpt as _ckpt
    from shadow1_tpu.fleet.engine import (
        FleetEngine,
        select_lanes,
        slice_experiment,
    )
    from shadow1_tpu.lineage import Lineage, write_json_atomic
    from shadow1_tpu.preempt import PreemptedExit, run_injection_hooks
    from shadow1_tpu.txn import (
        CapacityExceededError,
        OverflowGuard,
        SelfCheckError,
        check_boundary_identity,
    )

    params = engine.params
    total = n_windows if n_windows is not None else engine.n_windows
    if every_windows is None:
        every_windows = max(total // 10, 1)
    if st is None:
        st = engine.init_state()
    labels = ([dict(l) for l in labels] if labels else
              [{"exp": i + engine.exp_base, "seed": int(e.seed)}
               for i, e in enumerate(engine.exps)])
    engine.exp_ids = [l.get("exp", i) for i, l in enumerate(labels)]
    try:
        jax.block_until_ready(engine.run(st, n_windows=0))
    except Exception as e:
        from shadow1_tpu import mem

        # OOM taxonomy: this warmup is the compile — tag exhaustion here
        # so the CLI's memory record reports the phase (mem.py).
        if mem.is_oom(e):
            e.shadow1_oom_phase = "compile"
        raise

    halt = params.on_overflow == "halt"
    retry = params.on_overflow == "retry"
    quarantine = params.on_lane_fail == "quarantine"
    finalize = bool(params.lane_finalize)
    qbase = quarantine_base or ckpt_path or "fleet_lane"

    # Engine factories close over the LIVE lane set; a quarantine/finalize
    # repack replaces the policies wholesale (their engine caches hold
    # stale-E programs), carrying the counters/floors over.
    def _make_factory():
        exps = list(engine.exps)
        mr = list(engine.max_rounds)
        ids = list(engine.exp_ids or range(len(exps)))
        base = engine.exp_base

        def make(p):
            eng = FleetEngine(exps, p, mr)
            eng.exp_base = base
            eng.exp_ids = ids
            return eng

        return make

    controller = None
    if auto_caps:
        from shadow1_tpu.tune import CapController

        controller = CapController(engine, _make_factory(),
                                   initial_state=st)
    guard = (OverflowGuard(engine, make_engine=_make_factory(),
                           mode="retry", controller=controller)
             if retry else None)
    hb = FleetHeartbeat(engine, stream=stream, initial_state=st,
                        emit_heartbeat=emit_heartbeat, emit_ring=emit_ring,
                        guard=guard)
    hb.labels = labels
    recovery = hb.recovery
    if recovery_seed:
        # Lanes that left the fleet before the snapshot this run resumed
        # from: their full records were emitted by the earlier process;
        # the bare gids keep the final summary truthful across respawns.
        recovery["quarantined"] = [{"exp": int(g), "resumed": True}
                                   for g in
                                   recovery_seed.get("quarantined", [])]
        recovery["finished"] = [{"exp": int(g), "resumed": True}
                                for g in recovery_seed.get("finished", [])]
    if guard is not None:
        guard.bind(engine, st)
        guard.on_engine_swap = lambda eng_new: setattr(hb, "engine", eng_new)
    prev_per_exp = engine.metrics_per_exp(st)
    lineage = Lineage(ckpt_path, keep=ckpt_keep) if ckpt_path else None
    last_save = time.perf_counter()
    last_seq = [None]
    retry_seen = 0

    def _record(rec: dict) -> None:
        """An immediately-final record: stderr log line (the stream every
        report tool reads) plus the caller's stdout hook."""
        if stream is not False:
            print(json.dumps(rec), file=stream or sys.stderr, flush=True)
        if emit_record is not None:
            emit_record(rec)

    def _drain_retry_records(discarded: bool = False) -> None:
        """Emit one fleet_retry log record per new guard grow, with lane
        attribution mapped to sweep-global ids through the CURRENT labels.
        Must run BEFORE any repack shrinks ``labels`` — stale local
        indices would remap onto the wrong experiment. ``discarded`` marks
        grows whose attempt ended in a quarantine: the caps (and the
        chunk) were rolled back, so the record is audit-only."""
        nonlocal retry_seen
        if guard is None or len(guard.resizes) <= retry_seen:
            return
        for rz in guard.resizes[retry_seen:]:
            rrec = {"type": "fleet_retry", **rz}
            if "lanes" in rrec:
                rrec["lanes"] = {
                    c: [labels[i].get("exp", i) for i in idxs
                        if i < len(labels)]
                    for c, idxs in rrec["lanes"].items()}
            if discarded:
                rrec["discarded"] = True
            recovery["retry_records"].append(rrec)
            # Log-stream only (unlike quarantine/early-final records): a
            # retry is an audit event, not a per-lane result — the stdout
            # contract stays fleet_exp/.../fleet_summary.
            if stream is not False:
                print(json.dumps(rrec), file=stream or sys.stderr,
                      flush=True)
        retry_seen = len(guard.resizes)

    def _repack(keep: list[int], st_from):
        """Survivors of ``st_from`` as a fresh E'=len(keep) fleet: rebuild
        the engine at the CURRENT committed params, refresh the policies
        (stale-E caches dropped, counters/floors carried), re-baseline the
        heartbeat. Returns the repacked state."""
        nonlocal engine, guard, controller, prev_per_exp
        st_new = select_lanes(st_from, keep)
        labels[:] = [labels[i] for i in keep]
        new_eng = FleetEngine([engine.exps[i] for i in keep], params_live(),
                              [engine.max_rounds[i] for i in keep])
        new_eng.exp_base = engine.exp_base
        new_eng.exp_ids = [l.get("exp") for l in labels]
        engine = new_eng
        st_new = engine.place_state(st_new)
        if controller is not None:
            old = controller
            controller = type(old)(engine, _make_factory(),
                                   policy=old.policy, initial_state=st_new)
            controller._floor = dict(old._floor)
            controller.resizes = old.resizes
        if guard is not None:
            old = guard
            guard = OverflowGuard(engine, make_engine=_make_factory(),
                                  mode="retry", controller=controller)
            guard.chunk_retries = old.chunk_retries
            guard.retry_windows_rerun = old.retry_windows_rerun
            guard.resizes = old.resizes
            guard.bind(engine, st_new)
            guard.on_engine_swap = \
                lambda eng_new: setattr(hb, "engine", eng_new)
            hb.guard = guard
        hb.rebase(engine, st_new)
        prev_per_exp = hb.last_per_exp  # rebase just fetched it
        return st_new

    def params_live():
        # The last COMMITTED params: grows from failed (quarantined)
        # attempts are discarded with the tainted chunk.
        return engine.params

    def _quarantine(fail_lanes: list[int], reason: str, err, st_roll,
                    w0: int, retries_discarded: bool):
        """Slice deterministic failures out of the chunk-start state; the
        quarantined lane checkpoint is written FIRST, then the survivors
        repack (the survivors' own snapshot — with the shrunken ``lanes``
        manifest — follows at this boundary's save). Raises ``err`` when
        no lane survives, preserving the exit taxonomy.

        ``retries_discarded``: grows from a guard.commit attempt that
        RAISED were rolled back with the tainted chunk (the outer engine
        never swapped) — audit-only records. Grows COMMITTED earlier in
        the same boundary (a halt/selfcheck quarantine after a successful
        retry) persist: the repack migrates ``st_roll`` onto the live
        caps below, so their records stay real."""
        fail_lanes = sorted(set(fail_lanes))
        # Flush grow audit records against the CURRENT labels before the
        # repack shrinks them — stale local indices would remap onto the
        # wrong experiment.
        _drain_retry_records(discarded=retries_discarded)
        survivors = engine.n_exp - len(fail_lanes)
        for i in fail_lanes:
            label = labels[i]
            gid = label.get("exp", i)
            qpath = f"{qbase}.q{gid}.npz"
            _ckpt.save_state(slice_experiment(st_roll, i), qpath)
            rec = {
                "type": "fleet_quarantine",
                "exp": gid,
                "seed": label.get("seed"),
                "reason": reason,
                "window": w0,
                "ckpt": qpath,
                "survivors": survivors,
                "error": str(err)[:400],
            }
            for f in ("knob", "counter"):
                if getattr(err, f, None):
                    rec[f] = getattr(err, f)
            recovery["quarantined"].append(rec)
            _record(rec)
        if survivors == 0:
            raise err
        keep = [i for i in range(engine.n_exp) if i not in fail_lanes]
        # A grow COMMITTED at this same boundary (before a halt/selfcheck
        # quarantine) leaves the live params at bigger caps than the
        # chunk-start state's planes — migrate before the repack (grow is
        # bit-exact, tune/resize.py), so state shapes and engine caps
        # never diverge. The quarantined-lane checkpoints above stay at
        # the ORIGINAL caps: load_state cap-migrates on the solo side.
        p = params_live()
        if (int(np.asarray(st_roll.evbuf.kind).shape[-2]) != p.ev_cap
                or int(np.asarray(st_roll.outbox.dst).shape[-2])
                != p.outbox_cap):
            from shadow1_tpu.tune.resize import resize_state

            host = jax.tree.map(np.asarray, st_roll)
            st_roll = resize_state(host, ev_cap=p.ev_cap,
                                   outbox_cap=p.outbox_cap)
        return _repack(keep, st_roll)

    done = 0
    while done < total and engine.n_exp > 0:
        step = min(every_windows, total - done)
        # Rollback point: jax states are immutable and run() never donates,
        # so holding the reference is free until the commit drops it.
        st0 = st if (guard is not None or quarantine) else None
        w0 = int(np.asarray(st.win_start).max()) // engine.window
        st_new = (OverflowGuard.run_guarded(engine, st, step)
                  if guard is not None
                  else engine.run(st, n_windows=step))
        if guard is not None:
            try:
                engine, st_new = guard.commit(engine, st0, st_new, done,
                                              step)
            except CapacityExceededError as err:
                if not (quarantine and err.lanes):
                    raise
                # Ladder-top / repeated-overflow exhaustion attributed to
                # specific lanes: quarantine them from the chunk-start
                # state and replay the chunk with the survivors (the
                # raised commit rolled its grows back with the chunk).
                st = _quarantine(err.lanes, "capacity", err, st0, w0,
                                 retries_discarded=True)
                continue
            hb.engine = engine
        per_exp = engine.metrics_per_exp(st_new)
        if halt:
            try:
                _check_halt(engine, labels, per_exp, prev_per_exp, done,
                            step)
            except CapacityExceededError as err:
                if not (quarantine and err.lanes):
                    raise
                st = _quarantine(err.lanes, "capacity", err, st0, w0,
                                 retries_discarded=False)
                continue
        if selfcheck:
            violations: list[tuple[int, SelfCheckError]] = []
            for e, m in enumerate(per_exp):
                try:
                    check_boundary_identity(
                        m, where=(f"fleet experiment "
                                  f"{labels[e].get('exp', e)}, chunk "
                                  f"boundary, window "
                                  f"{m.get('windows', 0)}"))
                except SelfCheckError as err:
                    if not quarantine:
                        raise
                    violations.append((e, err))
            if violations:
                st = _quarantine([e for e, _ in violations], "selfcheck",
                                 violations[0][1], st0, w0,
                                 retries_discarded=False)
                continue
        # ---- chunk COMMITTED -------------------------------------------
        st = st_new
        done += step
        # One parseable fleet_retry record per committed grow+replay
        # (schema in docs/OBSERVABILITY.md) — heartbeat_report's recovery
        # section and the per-lane retry table read these.
        _drain_retry_records()
        prev_per_exp = per_exp
        hb(st, done, per_exp=per_exp)
        sim_ns = int(np.asarray(st.win_start).max())
        # Fault/preemption/hang injection (preempt.run_injection_hooks) —
        # the same chunk-boundary contract as obs.run_with_heartbeat, so
        # the supervisor, drain and watchdog paths are all testable
        # fleet-shaped too. Inert without the env vars.
        run_injection_hooks(sim_ns)
        # ---- mid-sweep lane lifecycle ----------------------------------
        if finalize and done < total and engine.n_exp > 1:
            flags = type(engine).lane_done(st)
            done_lanes = [i for i in range(engine.n_exp) if flags[i]]
            # All-drained fleets just run out their remaining (no-op)
            # windows like a solo run would — finalize only a strict
            # subset, so the normal end-of-run path stays intact.
            if done_lanes and len(done_lanes) < engine.n_exp:
                for i in done_lanes:
                    m = per_exp[i]
                    rec = lane_record(engine, st, i, labels[i],
                                      int(m.get("windows", done)), m=m)
                    rec["finished_early"] = True
                    rec["windows_configured"] = total
                    recovery["finished"].append(rec)
                    _record(rec)
                keep = [i for i in range(engine.n_exp)
                        if i not in done_lanes]
                st = _repack(keep, st)
        # ---- between-chunk retune (fleet --auto-caps) ------------------
        if controller is not None and done < total and engine.n_exp > 0:
            new_engine, st = controller(engine, st)
            if new_engine is not engine:
                engine = new_engine
                hb.engine = engine
                if guard is not None:
                    guard.engine = engine
        # ---- snapshot / progress / drain -------------------------------
        now = time.perf_counter()
        draining = drain is not None and drain.requested
        saved = False
        if lineage is not None and engine.n_exp > 0 and (
                done >= total or draining
                or now - last_save > ckpt_every_s):
            meta = {"win_start": sim_ns, "done_windows": done,
                    "lanes": [l.get("exp") for l in labels]}
            if recovery["quarantined"]:
                meta["quarantined"] = [r["exp"] for r in
                                       recovery["quarantined"]]
            if recovery["finished"]:
                meta["finished"] = [r["exp"] for r in recovery["finished"]]
            if resume_meta:
                meta.update(resume_meta)
            last_seq[0] = lineage.save(st, meta)
            last_save = now
            saved = True
        if ckpt_path:
            write_json_atomic(ckpt_path + ".progress",
                              {"done_windows": done, "total": total,
                               "win_start": sim_ns, "seq": last_seq[0]})
        crash_at = os.environ.get("SHADOW1_OBS_CRASH_AT_NS")
        if saved and crash_at is not None and sim_ns == int(crash_at):
            os._exit(41)
        if draining and done < total:
            raise PreemptedExit(st=st, signame=drain.signame,
                                done_windows=done, win_start=sim_ns)
    return st, hb


def final_records(engine, st, labels, n_windows, wall, resumed=False,
                  metrics0=None, recovery=None):
    """The CLI's end-of-run output: one ``fleet_exp`` record per STILL-
    RUNNING experiment plus one ``fleet_summary`` — schemas in
    docs/OBSERVABILITY.md §"Fleet records". ``metrics0`` (per-exp dicts
    from a resumed snapshot) baselines rates to THIS invocation like the
    solo CLI; ``recovery`` (FleetHeartbeat.recovery) folds quarantined /
    early-finished lanes into the summary — their own records were
    emitted when they left the fleet."""
    per_exp = engine.metrics_per_exp(st) if engine.n_exp else []
    sim_s = n_windows * engine.window / 1e9
    recs = []
    ev_run_total = 0
    for e, m in enumerate(per_exp):
        label = labels[e] if labels else {"exp": e}
        ev0 = metrics0[e].get("events", 0) if metrics0 else 0
        ev_run_total += m["events"] - ev0
        recs.append(lane_record(engine, st, e, label, n_windows, m=m))
    agg = engine.metrics_dict(st) if engine.n_exp else {}
    params = engine.params
    summary = {
        "type": "fleet_summary",
        "engine": "fleet",
        "experiments": engine.n_exp,
        "hosts": engine.exp.n_hosts,
        "window_ns": engine.window,
        "windows": n_windows,
        "sim_seconds": round(sim_s, 6),
        "wall_seconds": round(wall, 3),
        "sim_per_wall": round(sim_s / wall, 3) if wall > 0 else None,
        # Aggregate sweep throughput — the fleet-mode headline: events
        # executed across ALL experiments per wall second.
        "events_per_sec": round(ev_run_total / wall, 1) if wall > 0 else None,
        "events_per_exp": [int(m["events"]) for m in per_exp],
        "resumed": bool(resumed),
        "caps": {"ev_cap": params.ev_cap, "outbox_cap": params.outbox_cap,
                 "compact_cap": params.compact_cap},
        "metrics": agg,
    }
    if recovery:
        if recovery.get("quarantined"):
            summary["quarantined"] = [r["exp"] for r in
                                      recovery["quarantined"]]
        if recovery.get("finished"):
            summary["finished_early"] = [r["exp"] for r in
                                         recovery["finished"]]
        if recovery.get("quarantined") or recovery.get("finished"):
            summary["experiments_initial"] = (
                engine.n_exp + len(recovery.get("quarantined", []))
                + len(recovery.get("finished", [])))
    return recs, summary
