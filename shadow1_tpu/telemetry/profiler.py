"""Host-side phase profiler — Chrome trace-event export.

The reference's heartbeat rows carry wall time next to sim time so the
sim/wall ratio and its phases are derivable from the log (SURVEY §5); the
batched rebuild's phases are coarser — compile, init, run-chunk, drain,
checkpoint — and the question a perf PR actually asks is "where did the
wall clock go between heartbeats?". This profiler answers it with near-zero
overhead: a ``with profiler.span("run-chunk"):`` records one complete
("ph": "X") trace event; ``write(path)`` emits Chrome trace-event JSON
that chrome://tracing and Perfetto (https://ui.perfetto.dev) load directly.

Not a replacement for ``--profile`` (the jax/XLA op-level profiler): this
is the cheap always-on layer above it, one event per phase rather than per
op, safe to leave enabled on production runs.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time

# Canonical phase names (docs/OBSERVABILITY.md) — free-form names are
# allowed, but the wired-in call sites use these.
PH_COMPILE = "compile"
PH_INIT = "init"
PH_RUN_CHUNK = "run-chunk"
PH_DRAIN = "drain"
PH_CHECKPOINT = "checkpoint"
# Device-trace span (the jax.profiler capture window — see device_trace).
PH_DEVICE_TRACE = "device-trace"


class PhaseProfiler:
    """Collects complete-span trace events; thread-safe, append-only."""

    def __init__(self, process_name: str = "shadow1_tpu"):
        self.t0 = time.perf_counter()
        self.process_name = process_name
        self.events: list[dict] = []
        self._lock = threading.Lock()

    def _now_us(self) -> float:
        return (time.perf_counter() - self.t0) * 1e6

    @contextlib.contextmanager
    def span(self, name: str, **args):
        """Time a phase: ``with prof.span("run-chunk", windows=128): ...``"""
        t_start = self._now_us()
        try:
            yield self
        finally:
            t_end = self._now_us()
            ev = {
                "name": name,
                "ph": "X",
                "ts": round(t_start, 1),
                "dur": round(t_end - t_start, 1),
                "pid": os.getpid(),
                "tid": threading.get_ident() & 0xFFFFFFFF,
            }
            if args:
                ev["args"] = args
            with self._lock:
                self.events.append(ev)

    def instant(self, name: str, **args) -> None:
        """Mark a point in time (``"ph": "i"`` instant event)."""
        ev = {
            "name": name,
            "ph": "i",
            "s": "p",  # process-scoped instant
            "ts": round(self._now_us(), 1),
            "pid": os.getpid(),
            "tid": threading.get_ident() & 0xFFFFFFFF,
        }
        if args:
            ev["args"] = args
        with self._lock:
            self.events.append(ev)

    def chrome_trace(self) -> dict:
        """The Chrome trace-event JSON object (dict form)."""
        meta = [{
            "name": "process_name",
            "ph": "M",
            "pid": os.getpid(),
            "tid": 0,
            "args": {"name": self.process_name},
        }]
        with self._lock:
            events = meta + list(self.events)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def write(self, path: str) -> None:
        """Write the trace JSON (atomic: tmp + rename, like ckpt saves)."""
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.chrome_trace(), f)
        os.replace(tmp, path)

    def span_names(self) -> list[str]:
        with self._lock:
            return [e["name"] for e in self.events if e.get("ph") == "X"]


def maybe_span(profiler: PhaseProfiler | None, name: str, **args):
    """``profiler.span(...)`` or a nullcontext — call sites stay branchless."""
    if profiler is None:
        return contextlib.nullcontext()
    return profiler.span(name, **args)


@contextlib.contextmanager
def device_trace(log_dir: str, profiler: PhaseProfiler | None = None,
                 perfetto: bool = True):
    """The op-level zoom under the host-side phase spans: a ``jax.profiler``
    device trace scoped over the with-body, written to ``log_dir``.

    The engine's window program is annotated with
    ``jax.named_scope("phase:...")`` spans (core/engine.window_phases:
    prepare / rounds (pop, h_<kind>) / route / exchange / deliver / telem
    — plus ``phase:tcp_flush`` inside the TCP send path), so the captured
    trace shows exactly which window phase each device op belongs to.
    With ``perfetto=True`` jax also writes a ``*.perfetto-trace`` file
    under ``log_dir/plugins/profile/<run>/`` that https://ui.perfetto.dev
    loads directly (the TensorBoard profile plugin reads the same
    directory). A ``device-trace`` host span marks the capture window in
    the PhaseProfiler's own Chrome trace so the two zoom levels line up.

    Degrades gracefully: if the installed jax cannot start a profiler
    session (no profiler support, or a session already active), the body
    still runs and a warning names the reason — attribution tools must
    never fail a run over a missing trace backend."""
    import jax

    started = False
    try:
        try:
            jax.profiler.start_trace(log_dir,
                                     create_perfetto_trace=perfetto)
            started = True
        except Exception as e:  # profiler backend unavailable — not fatal
            import warnings

            warnings.warn(f"jax device trace unavailable ({e}); phases "
                          "still carry jax.named_scope annotations but no "
                          "device trace was captured")
        with maybe_span(profiler, PH_DEVICE_TRACE, log_dir=log_dir):
            yield
    finally:
        if started:
            jax.profiler.stop_trace()
