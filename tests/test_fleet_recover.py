"""Fleet-grade recovery: transactional sweep retry, lane quarantine,
mid-sweep lane lifecycle (docs/SEMANTICS.md "Fleet recovery contract").

The contracts under test:

* **fleet transactional retry** — an under-capped sweep under
  ``on_overflow=retry`` rolls the whole [E, ...] pytree back, grows the
  fleet-uniform cap, replays, and every lane's committed digest stream
  bit-matches (a) the straight fleet run at the final caps and (b) the
  cpu oracle at those caps; resume rebuilds at the snapshot's grown caps;
* **fleet auto-caps** — the controller is fed fleet-global gauges; an
  over-provisioned sweep shrinks bit-exactly;
* **lane quarantine** — a deterministically failing lane is sliced out of
  the chunk-start state into a solo-resumable checkpoint + a structured
  fleet_quarantine record, survivors bit-match an E-1-from-scratch sweep,
  and the sweep completes E-1/E (all-lanes-quarantined preserves the
  error/exit taxonomy);
* **mid-sweep lane lifecycle** — drained lanes finalize early (immediate
  fleet_exp record, fleet shrinks); sub-batched downshift composes with
  per-batch checkpointing;
* **rejection lift** — the PR 6 ``kind="mode"`` rejections for
  --auto-caps / --on-overflow retry under --fleet are gone
  (tests/test_fleet.py asserts the CLI side).
"""

import dataclasses
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import shadow1_tpu.txn as txn
from shadow1_tpu.ckpt import load_state, snapshot_caps
from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import EXIT_CAPACITY, MS, EngineParams
from shadow1_tpu.core.digest import SUBSYSTEMS
from shadow1_tpu.core.engine import Engine
from shadow1_tpu.cpu_engine import CpuEngine
from shadow1_tpu.fleet.engine import (
    FleetEngine,
    fleet_metrics_per_exp,
    select_lanes,
    slice_experiment,
)
from shadow1_tpu.fleet.run import final_records, run_fleet
from shadow1_tpu.lineage import Lineage
from shadow1_tpu.telemetry.ring import drain_ring

N = 20
UNDER = EngineParams(ev_cap=8, metrics_ring=N, state_digest=1)


def mk(seed, loss=0.0, stop=None, n_hosts=8):
    kw = {"stop_time": np.full(n_hosts, stop, np.int64)} if stop else {}
    return single_vertex_experiment(
        n_hosts=n_hosts, seed=seed, end_time=40 * MS, latency_ns=1 * MS,
        loss=loss, model="phold",
        model_cfg={"mean_delay_ns": float(2 * MS), "init_events": 6}, **kw)


def stream(st, window):
    return {r["window"]: tuple(r[f"dg_{s}"] for s in SUBSYSTEMS)
            for r in drain_ring(st, window) if r["type"] == "ring"}


def lane_streams(eng, st):
    return [stream(slice_experiment(st, e), eng.window)
            for e in range(eng.n_exp)]


# ---------------------------------------------------------------------------
# fleet transactional retry
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def retry_run():
    """One shared under-capped 3-lane retry run (compile amortized)."""
    exps = [mk(5), mk(6), mk(7, loss=0.1)]
    params = dataclasses.replace(UNDER, on_overflow="retry")
    eng = FleetEngine(exps, params)
    st, hb = run_fleet(eng, n_windows=N, every_windows=5, stream=False)
    return exps, st, hb


def test_fleet_retry_digest_parity_vs_big_cap_fleet(retry_run):
    """The acceptance gate: a forced-overflow retry sweep's per-lane
    digest streams bit-match the straight fleet run at the final grown
    caps (ci.sh runs the same proof via fleetprobe --retry)."""
    exps, st, hb = retry_run
    guard = hb.guard
    assert guard.chunk_retries >= 1, "under-capped sweep never retried"
    # Committed stream is overflow-free in EVERY lane (the txn contract).
    assert int(np.asarray(st.metrics.ev_overflow).sum()) == 0
    assert int(np.asarray(st.metrics.ob_overflow).sum()) == 0
    big = dataclasses.replace(UNDER, ev_cap=guard.final_caps["ev_cap"],
                              outbox_cap=guard.final_caps["outbox_cap"])
    eng_big = FleetEngine(exps, big)
    st_big = eng_big.run(n_windows=N)
    assert lane_streams(hb.engine, st) == lane_streams(eng_big, st_big)
    # Retry records carry sweep-global lane attribution.
    assert hb.recovery["retry_records"]
    assert all(r["type"] == "fleet_retry"
               for r in hb.recovery["retry_records"])


def test_fleet_retry_matches_cpu_oracle_at_final_caps(retry_run):
    """The cpu side of the proof: each lane's committed stream equals the
    eager oracle run at the final caps (the PR 5 solo proof, fleet-wide)."""
    exps, st, hb = retry_run
    big = dataclasses.replace(UNDER, ev_cap=hb.guard.final_caps["ev_cap"],
                              outbox_cap=hb.guard.final_caps["outbox_cap"])
    streams = lane_streams(hb.engine, st)
    for e, exp in enumerate(exps):
        cpu = CpuEngine(exp, big)
        cpu.run(n_windows=N)
        oracle = {r["window"]: tuple(r[f"dg_{s}"] for s in SUBSYSTEMS)
                  for r in cpu.digest_rows}
        assert oracle == streams[e], f"exp {e} vs cpu oracle"


def test_fleet_retry_ckpt_resumes_at_grown_caps(retry_run, tmp_path):
    """A retry sweep's snapshot carries the GROWN caps; the resume path
    reads them (ckpt.snapshot_caps, leading-axis aware), rebuilds the
    fleet engine there and continues to the identical final streams."""
    exps, st_ref, hb_ref = retry_run
    ck = str(tmp_path / "fleet.npz")
    params = dataclasses.replace(UNDER, on_overflow="retry")
    eng = FleetEngine(exps, params)
    st_half, hb = run_fleet(eng, n_windows=10, every_windows=5,
                            stream=False, ckpt_path=ck, ckpt_every_s=0)
    grown = hb.guard.final_caps["ev_cap"]
    assert grown > UNDER.ev_cap
    # The cli recipe: probe the snapshot's caps, rebuild, load, continue.
    p2 = dataclasses.replace(params, ev_cap=grown)
    eng2 = FleetEngine(exps, p2)
    snap = snapshot_caps(eng2.init_state(), ck)
    assert snap == (grown, UNDER.outbox_cap)
    st = load_state(eng2.init_state(), ck)
    st, hb2 = run_fleet(eng2, st, n_windows=10, every_windows=5,
                        stream=False)
    ref = lane_streams(hb_ref.engine, st_ref)
    got = lane_streams(hb2.engine, st)
    for e in range(len(exps)):
        tail = {w: v for w, v in ref[e].items() if w in got[e]}
        assert got[e] == tail, f"exp {e} resumed tail diverged"


def test_fleet_auto_caps_shrinks_bit_exactly():
    """Fleet --auto-caps: the controller reads fleet-global gauges and an
    over-provisioned sweep shrinks between chunks — digest streams stay
    bit-identical to the straight over-provisioned run (the tune/resize
    exactness argument, fleet-shaped)."""
    exps = [mk(5), mk(6)]
    params = dataclasses.replace(UNDER, ev_cap=64)
    eng = FleetEngine(exps, params)
    st, hb = run_fleet(eng, n_windows=N, every_windows=4, stream=False,
                       auto_caps=True)
    assert hb.engine.params.ev_cap < 64, "never shrank"
    eng_ref = FleetEngine(exps, params)
    st_ref = eng_ref.run(n_windows=N)
    assert lane_streams(hb.engine, st) == lane_streams(eng_ref, st_ref)


# ---------------------------------------------------------------------------
# lane quarantine
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def quarantine_run(tmp_path_factory):
    """Shared quarantine run: lane 1 (lossless) overflows ev_cap=8 under
    halt; lanes 0/2 (50% loss) survive."""
    work = tmp_path_factory.mktemp("quar")
    exps = [mk(5, loss=0.5), mk(6), mk(7, loss=0.5)]
    params = dataclasses.replace(UNDER, on_overflow="halt",
                                 on_lane_fail="quarantine")
    eng = FleetEngine(exps, params)
    st, hb = run_fleet(eng, n_windows=N, every_windows=5, stream=False,
                       quarantine_base=str(work / "lane"))
    return exps, params, st, hb


def test_quarantine_slices_failing_lane(quarantine_run):
    exps, params, st, hb = quarantine_run
    q = hb.recovery["quarantined"]
    assert len(q) == 1
    rec = q[0]
    assert rec["exp"] == 1 and rec["seed"] == 6
    assert rec["reason"] == "capacity" and rec["knob"] == "ev_cap"
    assert rec["survivors"] == 2 and os.path.exists(rec["ckpt"])
    assert hb.engine.n_exp == 2
    assert [l["exp"] for l in hb.labels] == [0, 2]
    recs, summary = final_records(hb.engine, st, hb.labels, N, 1.0,
                                  recovery=hb.recovery)
    assert [r["exp"] for r in recs] == [0, 2]
    assert summary["quarantined"] == [1]
    assert summary["experiments"] == 2
    assert summary["experiments_initial"] == 3


def test_quarantine_survivors_match_e1_sweep(quarantine_run):
    """Survivor streams are provably unchanged: they bit-match an
    (E-1)-from-scratch sweep of just the surviving experiments."""
    exps, params, st, hb = quarantine_run
    scratch = FleetEngine([exps[0], exps[2]],
                          dataclasses.replace(params,
                                              on_lane_fail="halt"))
    st2 = scratch.run(n_windows=N)
    assert lane_streams(hb.engine, st) == lane_streams(scratch, st2)


def test_quarantined_ckpt_resumes_solo_bit_identically(quarantine_run):
    """The quarantined lane's sliced checkpoint (chunk-start state) loads
    into a SOLO engine and continues exactly the solo straight run."""
    exps, params, st, hb = quarantine_run
    rec = hb.recovery["quarantined"][0]
    solo_p = dataclasses.replace(params, on_overflow="drop",
                                 on_lane_fail="halt")
    straight = Engine(exps[1], solo_p).run(n_windows=N)
    solo = Engine(exps[1], solo_p)
    lane = load_state(solo.init_state(), rec["ckpt"])
    w0 = int(np.asarray(lane.win_start)) // solo.window
    assert w0 == rec["window"]
    resumed = solo.run(lane, n_windows=N - w0)
    a, b = stream(straight, solo.window), stream(resumed, solo.window)
    assert {w: a[w] for w in b} == b
    assert Engine.metrics_dict(straight) == Engine.metrics_dict(resumed)


def test_quarantine_selfcheck_violation(monkeypatch, tmp_path):
    """A per-lane selfcheck violation quarantines the violating lane with
    reason="selfcheck" instead of killing the sweep."""
    exps = [mk(5, loss=0.5), mk(6, loss=0.5)]
    params = dataclasses.replace(UNDER, ev_cap=32,
                                 on_lane_fail="quarantine")
    real = txn.check_boundary_identity

    def fake(metrics, where=""):
        if "fleet experiment 1" in where:
            raise txn.SelfCheckError({"pkts_sent": 1}, 1, where=where)
        return real(metrics, where)

    monkeypatch.setattr(txn, "check_boundary_identity", fake)
    eng = FleetEngine(exps, params)
    st, hb = run_fleet(eng, n_windows=10, every_windows=5, stream=False,
                       selfcheck=True,
                       quarantine_base=str(tmp_path / "lane"))
    q = hb.recovery["quarantined"]
    assert len(q) == 1 and q[0]["exp"] == 1
    assert q[0]["reason"] == "selfcheck"
    assert hb.engine.n_exp == 1 and hb.labels[0]["exp"] == 0


def test_quarantine_after_committed_grow_migrates_rollback(monkeypatch,
                                                           tmp_path):
    """retry + quarantine + selfcheck compose: when a chunk COMMITS a cap
    grow and the boundary selfcheck then quarantines a lane, the repack
    must migrate the chunk-start rollback state onto the grown caps —
    state shapes and engine caps never diverge, and the committed grow's
    retry records are NOT marked discarded."""
    exps = [mk(5), mk(6), mk(7)]   # all under-capped: the chunk grows
    params = dataclasses.replace(UNDER, on_overflow="retry",
                                 on_lane_fail="quarantine")
    real = txn.check_boundary_identity
    tripped = []

    def fake(metrics, where=""):
        if "fleet experiment 2" in where and not tripped:
            tripped.append(where)
            raise txn.SelfCheckError({"pkts_sent": 1}, 1, where=where)
        return real(metrics, where)

    monkeypatch.setattr(txn, "check_boundary_identity", fake)
    eng = FleetEngine(exps, params)
    st, hb = run_fleet(eng, n_windows=N, every_windows=5, stream=False,
                       selfcheck=True,
                       quarantine_base=str(tmp_path / "lane"))
    assert tripped, "selfcheck hook never fired"
    assert [r["exp"] for r in hb.recovery["quarantined"]] == [2]
    assert hb.engine.n_exp == 2
    # The grow committed at that boundary persisted through the repack:
    # state planes and engine caps agree, and the sweep stayed clean.
    grown = hb.engine.params.ev_cap
    assert grown > UNDER.ev_cap
    assert int(np.asarray(st.evbuf.kind).shape[-2]) == grown
    assert int(np.asarray(st.metrics.ev_overflow).sum()) == 0
    committed = [r for r in hb.recovery["retry_records"]
                 if not r.get("discarded")]
    assert committed, "committed grow was mislabeled discarded"


def test_quarantine_all_lanes_preserves_error(tmp_path):
    """When every lane quarantines, the last failure re-raises — the CLI
    then maps it to the solo exit taxonomy (EXIT_CAPACITY)."""
    exps = [mk(6)]  # the lossless overflowing lane, alone
    params = dataclasses.replace(UNDER, on_overflow="halt",
                                 on_lane_fail="quarantine")
    eng = FleetEngine(exps, params)
    with pytest.raises(txn.CapacityExceededError):
        run_fleet(eng, n_windows=N, every_windows=5, stream=False,
                  quarantine_base=str(tmp_path / "lane"))


def test_retry_ladder_top_quarantines(monkeypatch, tmp_path):
    """Retry exhaustion (cap cannot grow past the ladder top) attributed
    to a lane quarantines it instead of killing the sweep — the
    retry-recovery and quarantine planes compose."""
    class TinyGuard(txn.OverflowGuard):
        def __init__(self, *a, **k):
            super().__init__(*a, **k)
            self.max_cap = 8  # == ev_cap: the first grow already exceeds

    monkeypatch.setattr(txn, "OverflowGuard", TinyGuard)
    exps = [mk(5, loss=0.5), mk(6), mk(7, loss=0.5)]
    params = dataclasses.replace(UNDER, on_overflow="retry",
                                 on_lane_fail="quarantine")
    eng = FleetEngine(exps, params)
    st, hb = run_fleet(eng, n_windows=10, every_windows=5, stream=False,
                       quarantine_base=str(tmp_path / "lane"))
    q = hb.recovery["quarantined"]
    assert [r["exp"] for r in q] == [1]
    assert q[0]["reason"] == "capacity"
    assert hb.engine.n_exp == 2


def test_resume_mid_quarantined_sweep(tmp_path):
    """A fleet snapshot taken AFTER a quarantine carries the surviving
    lane ids in its lineage manifest; rebuilding exactly that sub-fleet
    and resuming continues bit-identically (the cli._fleet_main recipe)."""
    exps = [mk(5, loss=0.5), mk(6), mk(7, loss=0.5)]
    params = dataclasses.replace(UNDER, on_overflow="halt",
                                 on_lane_fail="quarantine")
    ck = str(tmp_path / "fleet.npz")
    labels = [{"exp": i, "seed": int(e.seed)} for i, e in enumerate(exps)]
    eng = FleetEngine(exps, params)
    st_half, hb = run_fleet(eng, n_windows=10, every_windows=5,
                            stream=False, ckpt_path=ck, ckpt_every_s=0,
                            labels=labels,
                            quarantine_base=str(tmp_path / "lane"))
    assert [r["exp"] for r in hb.recovery["quarantined"]] == [1]
    res = Lineage(ck).resolve()
    assert res is not None and res.meta["lanes"] == [0, 2]
    assert res.meta["quarantined"] == [1]
    # Rebuild exactly the surviving sub-fleet, load, continue.
    keep = [0, 2]
    eng2 = FleetEngine([exps[i] for i in keep], params)
    eng2.exp_ids = keep
    st = load_state(eng2.init_state(), res.path)
    st, hb2 = run_fleet(eng2, st, n_windows=10, every_windows=5,
                        stream=False,
                        labels=[labels[i] for i in keep],
                        recovery_seed={"quarantined":
                                       res.meta["quarantined"],
                                       "finished": []})
    # Straight quarantine run of the full horizon for comparison.
    eng3 = FleetEngine(exps, params)
    st3, hb3 = run_fleet(eng3, n_windows=N, every_windows=5, stream=False,
                         quarantine_base=str(tmp_path / "lane3"))
    ref = lane_streams(hb3.engine, st3)
    got = lane_streams(hb2.engine, st)
    for i in range(2):
        tail = {w: v for w, v in ref[i].items() if w in got[i]}
        assert got[i] == tail, f"survivor {i} resumed tail diverged"
    _, summary = final_records(hb2.engine, st, hb2.labels, N, 1.0,
                               recovery=hb2.recovery)
    assert summary["quarantined"] == [1]
    assert summary["experiments_initial"] == 3


# ---------------------------------------------------------------------------
# mid-sweep lane lifecycle
# ---------------------------------------------------------------------------

def test_lane_finalize_early():
    """A lane whose hosts all stop (legacy stop_time churn) drains and is
    finalized mid-sweep: immediate fleet_exp record with the window count
    it actually ran, fleet shrinks, survivor streams unchanged, and the
    finalized lane's parity metrics equal the straight run's (a dead lane
    accrues nothing but window ticks)."""
    exps = [mk(5, loss=0.5), mk(6, loss=0.5, stop=10 * MS)]
    params = dataclasses.replace(UNDER, ev_cap=32, lane_finalize=1)
    eng = FleetEngine(exps, params)
    st, hb = run_fleet(eng, n_windows=N, every_windows=5, stream=False)
    fin = hb.recovery["finished"]
    assert len(fin) == 1
    rec = fin[0]
    assert rec["exp"] == 1 and rec["finished_early"] is True
    assert rec["windows"] < N and rec["windows_configured"] == N
    assert hb.engine.n_exp == 1
    straight = FleetEngine(exps, dataclasses.replace(params,
                                                     lane_finalize=0))
    st2 = straight.run(n_windows=N)
    assert stream(slice_experiment(st, 0), eng.window) == \
        stream(slice_experiment(st2, 0), straight.window)
    m2 = fleet_metrics_per_exp(st2)[1]
    for k in ("events", "pkts_sent", "pkts_delivered", "down_events",
              "down_pkts"):
        assert rec["metrics"][k] == m2[k], k
    _, summary = final_records(hb.engine, st, hb.labels, N, 1.0,
                               recovery=hb.recovery)
    assert summary["finished_early"] == [1]


def test_fleet_plan_subset():
    """The resume-side twin of select_lanes: a plan subset keeps global
    ids/seeds/max_rounds aligned and ignores stale ids."""
    from shadow1_tpu.fleet.expand import expand_sweep

    doc = {
        "general": {"seed": 7, "stop_time": "60 ms"},
        "engine": {"scheduler": "tpu"},
        "network": {"single_vertex": {"latency": "10 ms"}},
        "hosts": [{"name": "h", "count": 8}],
        "app": {"model": "phold",
                "params": {"mean_delay_ns": 2.0e7, "init_events": 2}},
        "sweep": {"seeds": [7, 8, 9],
                  "vary": [{}, {"engine": {"max_rounds": 128}}, {}]},
    }
    plan = expand_sweep(doc)
    sub = plan.subset([2, 0, 99])
    assert [l["exp"] for l in sub.labels] == [2, 0]
    assert [e.seed for e in sub.exps] == [9, 7]
    assert sub.max_rounds == [256, 256]
    assert plan.subset([1]).max_rounds == [128]


def test_select_lanes_is_lane_exact():
    """The repack primitive: running a selected sub-fleet state forward
    equals the same lanes of the full fleet run forward."""
    exps = [mk(5, loss=0.5), mk(6), mk(7, loss=0.5)]
    params = dataclasses.replace(UNDER, ev_cap=32)
    eng = FleetEngine(exps, params)
    st_half = eng.run(n_windows=10)
    full = eng.run(st_half, n_windows=N - 10)
    sub_eng = FleetEngine([exps[0], exps[2]], params)
    sub = sub_eng.run(select_lanes(st_half, [0, 2]), n_windows=N - 10)
    for i, e in enumerate([0, 2]):
        np.testing.assert_array_equal(
            np.asarray(slice_experiment(full, e).evbuf.kind),
            np.asarray(slice_experiment(sub, i).evbuf.kind))
    assert stream(slice_experiment(full, 0), eng.window) == \
        stream(slice_experiment(sub, 0), sub_eng.window)


# ---------------------------------------------------------------------------
# records / report tooling
# ---------------------------------------------------------------------------

def test_heartbeat_report_fleet_recovery_section(tmp_path, capsys):
    from shadow1_tpu.tools import heartbeat_report

    recs = [
        {"type": "fleet_retry", "retry": 1, "windows": [0, 5],
         # Two counters, one chunk, same lane — counts as ONE taint.
         "lanes": {"ev_overflow": [1, 2], "ob_overflow": [1]},
         "ev_cap": [8, 12]},
        {"type": "fleet_retry", "retry": 2, "windows": [0, 5],
         "lanes": {"ev_overflow": [1]}, "ev_cap": [12, 16]},
        # Rolled back by a quarantine: audit-only, out of every count.
        {"type": "fleet_retry", "retry": 3, "windows": [5, 10],
         "lanes": {"ev_overflow": [3]}, "ev_cap": [16, 24],
         "discarded": True},
        # quarantine record duplicated (stdout + stderr capture) — the
        # report must dedupe by lane.
        {"type": "fleet_quarantine", "exp": 3, "seed": 9,
         "reason": "capacity", "knob": "ev_cap", "window": 5,
         "ckpt": "x.q3.npz", "survivors": 2},
        {"type": "fleet_quarantine", "exp": 3, "seed": 9,
         "reason": "capacity", "knob": "ev_cap", "window": 5,
         "ckpt": "x.q3.npz", "survivors": 2},
        {"type": "fleet_exp", "exp": 0, "seed": 7, "windows": 12,
         "windows_configured": 20, "finished_early": True,
         "metrics": {"events": 10}, "drops": {"total": 0}},
        {"type": "fleet_summary", "experiments": 2,
         "experiments_initial": 4, "quarantined": [3],
         "metrics": {}},
    ]
    log = tmp_path / "rec.log"
    with open(log, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    out = heartbeat_report.summarize(heartbeat_report.load_records(
        str(log)))
    printed = capsys.readouterr().out
    fr = out["fleet_recovery"]
    assert fr["chunk_retries"] == 2
    assert fr["quarantined"] == 1          # deduped
    assert fr["finished_early"] == 1
    assert fr["retries_by_exp"] == {1: 2, 2: 1}
    assert "fleet recovery" in printed
    assert "finished early" in printed
    assert "solo-resumable ckpt" in printed


# ---------------------------------------------------------------------------
# CLI (subprocess; heavy cases slow with fast in-process siblings above)
# ---------------------------------------------------------------------------

def _repo_env(extra=None):
    """Subprocess env that keeps shadow1_tpu importable when the child
    runs with a tmp cwd (quarantine ckpts and .lane files land there,
    never in the repo)."""
    import shadow1_tpu as pkg

    root = os.path.dirname(os.path.dirname(os.path.abspath(pkg.__file__)))
    env = {**os.environ,
           "PYTHONPATH": root + os.pathsep
           + os.environ.get("PYTHONPATH", "")}
    if extra:
        env.update(extra)
    return env


def _quar_sweep_cfg(tmp_path):
    cfg = tmp_path / "sweep.yaml"
    cfg.write_text(
        "general: {seed: 5, stop_time: 40 ms}\n"
        "engine: {scheduler: tpu, ev_cap: 8}\n"
        "network: {single_vertex: {latency: 1 ms}}\n"
        "hosts: [{name: h, count: 8}]\n"
        "app: {model: phold, params: {mean_delay_ns: 2000000.0, "
        "init_events: 6}}\n"
        "sweep:\n"
        "  seeds: [5, 6, 7]\n"
        "  vary:\n"
        "    - {network: {single_vertex: {loss: 0.5}}}\n"
        "    - {}\n"
        "    - {network: {single_vertex: {loss: 0.5}}}\n"
    )
    return cfg


def test_cli_quarantine_records_and_exit(tmp_path):
    """--on-lane-fail quarantine: the sweep completes E-1/E with exit 0,
    a fleet_quarantine stdout record, the ledger in the summary — and the
    all-lanes-fail sibling keeps the capacity exit code."""
    cfg = _quar_sweep_cfg(tmp_path)
    out = subprocess.run(
        [sys.executable, "-m", "shadow1_tpu", str(cfg), "--fleet",
         "--on-overflow", "halt", "--on-lane-fail", "quarantine",
         "--windows", "10", "--ckpt", str(tmp_path / "f.npz"),
         "--supervised-child"],
        capture_output=True, text=True, cwd=tmp_path,
        env=_repo_env())
    assert out.returncode == 0, out.stderr[-800:]
    recs = [json.loads(l) for l in out.stdout.strip().splitlines()]
    q = [r for r in recs if r.get("type") == "fleet_quarantine"]
    assert len(q) == 1 and q[0]["exp"] == 1
    assert os.path.exists(q[0]["ckpt"])
    summary = [r for r in recs if r.get("type") == "fleet_summary"][-1]
    assert summary["quarantined"] == [1]
    assert summary["experiments"] == 2
    # All lanes fail -> the structured capacity exit survives quarantine.
    solo = tmp_path / "solo_sweep.yaml"
    solo.write_text(cfg.read_text().replace(
        "  seeds: [5, 6, 7]\n  vary:\n"
        "    - {network: {single_vertex: {loss: 0.5}}}\n"
        "    - {}\n"
        "    - {network: {single_vertex: {loss: 0.5}}}\n",
        "  seeds: [6]\n"))
    out = subprocess.run(
        [sys.executable, "-m", "shadow1_tpu", str(solo), "--fleet",
         "--on-overflow", "halt", "--on-lane-fail", "quarantine",
         "--windows", "10"],
        capture_output=True, text=True, cwd=tmp_path,
        env=_repo_env())
    assert out.returncode == EXIT_CAPACITY, out.stderr[-500:]
    err = json.loads(out.stdout.strip().splitlines()[-1])
    assert err["error"] == "capacity_exceeded"


def test_cli_lane_flags_require_fleet(tmp_path):
    cfg = _quar_sweep_cfg(tmp_path)
    out = subprocess.run(
        [sys.executable, "-m", "shadow1_tpu", str(cfg),
         "--on-lane-fail", "quarantine"],
        capture_output=True, text=True)
    assert out.returncode == 2
    assert "--fleet" in out.stderr


@pytest.mark.slow
def test_cli_supervised_quarantine_crash_resume(tmp_path):
    """Supervised fleet: quarantine happens, the child crashes at a later
    committed boundary, the respawn resumes the E-1 sub-fleet from the
    lanes manifest and the final per-lane metrics equal the straight
    quarantine run's."""
    cfg = _quar_sweep_cfg(tmp_path)
    straight = subprocess.run(
        [sys.executable, "-m", "shadow1_tpu", str(cfg), "--fleet",
         "--on-overflow", "halt", "--on-lane-fail", "quarantine"],
        capture_output=True, text=True, cwd=tmp_path,
        env=_repo_env())
    assert straight.returncode == 0, straight.stderr[-800:]
    env = {**os.environ, "SHADOW1_OBS_CRASH_AT_NS": "20000000",
           "SHADOW1_SUPERVISE_BACKOFF_S": "0"}
    sup = subprocess.run(
        [sys.executable, "-m", "shadow1_tpu", str(cfg), "--fleet",
         "--on-overflow", "halt", "--on-lane-fail", "quarantine",
         "--ckpt", str(tmp_path / "q.npz"), "--ckpt-every-s", "0",
         "--heartbeat", "5"],
        capture_output=True, text=True, env=_repo_env(env),
        cwd=tmp_path)
    assert sup.returncode == 0, sup.stderr[-800:]
    assert "respawning" in sup.stderr

    def per_exp(out):
        return {r["exp"]: r["metrics"] for r in
                map(json.loads, out.strip().splitlines())
                if r.get("type") == "fleet_exp"}

    a, b = per_exp(straight.stdout), per_exp(sup.stdout)
    assert set(a) == set(b) == {0, 2}
    assert a == b
    summary = [json.loads(l) for l in sup.stdout.strip().splitlines()
               if '"fleet_summary"' in l][-1]
    assert summary["quarantined"] == [1]


@pytest.mark.slow
def test_cli_subbatch_downshift_with_ckpt_crash_resume(tmp_path):
    """--on-oom downshift sub-batching now composes with --ckpt: a
    mid-batch crash respawns, the batch cursor in the lineage manifest
    resumes the right batch, and per-lane results equal the straight
    full-fleet run (the lifted refusal, end to end)."""
    from shadow1_tpu import mem
    from shadow1_tpu.fleet.expand import load_sweep

    cfg = tmp_path / "sweep.yaml"
    cfg.write_text(
        "general: {seed: 7, stop_time: 60 ms}\n"
        "engine: {scheduler: tpu, ev_cap: 32, outbox_cap: 16}\n"
        "network: {single_vertex: {latency: 10 ms}}\n"
        "hosts: [{name: h, count: 8}]\n"
        "app: {model: phold, params: {mean_delay_ns: 2.0e7, "
        "init_events: 2}}\n"
        "sweep: {seeds: [7, 8, 9, 10]}\n"
    )
    plan = load_sweep(str(cfg))
    e2 = mem.estimate(plan.exps[0], plan.params, n_exp=2)
    e4 = mem.estimate(plan.exps[0], plan.params, n_exp=4)
    budget = (e2.peak_bytes + e4.peak_bytes) // 2
    env = {**os.environ, mem.MEM_BYTES_ENV: str(budget),
           "SHADOW1_OBS_CRASH_AT_NS": "40000000",
           "SHADOW1_SUPERVISE_BACKOFF_S": "0"}
    sup = subprocess.run(
        [sys.executable, "-m", "shadow1_tpu", str(cfg), "--fleet",
         "--on-oom", "downshift", "--ckpt", str(tmp_path / "sb.npz"),
         "--ckpt-every-s", "0", "--heartbeat", "2"],
        capture_output=True, text=True, env=_repo_env(env),
        cwd=tmp_path, timeout=600)
    assert sup.returncode == 0, sup.stderr[-800:]
    assert "respawning" in sup.stderr
    straight = subprocess.run(
        [sys.executable, "-m", "shadow1_tpu", str(cfg), "--fleet"],
        capture_output=True, text=True, cwd=tmp_path,
        env=_repo_env(), timeout=600)
    assert straight.returncode == 0

    def ev(out):
        return {r["exp"]: r["metrics"]["events"] for r in
                map(json.loads, out.strip().splitlines())
                if r.get("type") == "fleet_exp"}

    assert ev(sup.stdout) == ev(straight.stdout)
    merged = [json.loads(l) for l in sup.stdout.strip().splitlines()
              if '"fleet_summary"' in l][-1]
    assert merged["experiments"] == 4
    assert merged["sub_batches"] >= 2
