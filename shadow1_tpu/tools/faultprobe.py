"""Device-fault bisection probes for the tunneled TPU.

    python -m shadow1_tpu.tools.faultprobe [probe ...]

Round-3/4 postmortem tooling: large net-model programs can fault the
tunneled device ("TPU worker process crashed"), after which
``ensure_live_platform`` silently degrades to CPU — so every probe here
prints the backend it actually ran on, and exits nonzero if the default
backend is not TPU (a CPU "ok" tells you nothing about the fault).

Probes isolate the round-4 layout's structurally-new device code paths:

* ``sort0``   — the arrival-batching 2-key lax.sort along axis 0
* ``scan0``   — the max-plus associative_scan along axis 0
* ``pop``     — pop_until/push_local cycle on a [C, H] event buffer
* ``phold``   — 60 engine windows at [1000, 256] (times ms/round)
* ``tor N``   — N windows of the rung-3 Tor config (the known fault
                reproducer; default 50)

Run probes in order; the first to kill the worker identifies the
culprit. Each run is one process — after a fault, re-run from a fresh
process (the wedged runtime poisons subsequent calls).
"""

from __future__ import annotations

import json
import sys
import time


def main() -> int:
    import shadow1_tpu  # noqa: F401
    from shadow1_tpu.platform import ensure_live_platform

    ensure_live_platform(min_devices=1)
    import jax
    import jax.numpy as jnp
    import numpy as np

    backend = jax.default_backend()
    print(json.dumps({"backend": backend}), flush=True)
    if backend != "tpu":
        print(json.dumps({"error": "default backend is not tpu; probes "
                                   "would not exercise the device"}))
        return 1

    args = sys.argv[1:] or ["sort0", "scan0", "pop", "phold"]
    # Parse positionally: "tor" may be followed by a numeric window count
    # anywhere in the list; mixed probe lists are fine.
    todo: list[tuple[str, int]] = []
    i = 0
    while i < len(args):
        name, n = args[i], 0
        if name == "tor" and i + 1 < len(args) and args[i + 1].isdigit():
            n = int(args[i + 1])
            i += 1
        todo.append((name, n))
        i += 1
    H, C = 1000, 256

    for probe, probe_n in todo:
        t0 = time.perf_counter()
        if probe == "sort0":
            t = jnp.asarray(np.random.randint(0, 1 << 40, (C, H)), jnp.int64)
            tb = jnp.asarray(np.random.randint(0, 1 << 40, (C, H)), jnp.int64)
            idx = jnp.broadcast_to(
                jnp.arange(C, dtype=jnp.int32)[:, None], (C, H)
            )
            f = jax.jit(lambda a, b, c: jax.lax.sort(
                (a, b, c), dimension=0, num_keys=2))
            jax.block_until_ready(f(t, tb, idx))
        elif probe == "scan0":
            a = jnp.asarray(np.random.randint(0, 1 << 30, (C, H)), jnp.int64)
            f = jax.jit(lambda x: jax.lax.associative_scan(
                lambda p, q: (p[0] + q[0], jnp.maximum(p[1] + q[0], q[1])),
                (x, x), axis=0))
            jax.block_until_ready(f(a))
        elif probe == "pop":
            from shadow1_tpu.core.events import evbuf_init, pop_until, push_local

            buf = evbuf_init(H, C)
            k = jnp.full(H, 1, jnp.int32)
            p = jnp.zeros((10, H), jnp.int32)
            m = jnp.ones(H, bool)

            def cyc(b):
                b, _ = push_local(b, m, jnp.zeros(H, jnp.int64), k, p)
                b, _ev = pop_until(b, jnp.int64(10))
                return b

            jax.block_until_ready(jax.jit(cyc)(buf))
        elif probe == "phold":
            from shadow1_tpu.config.compiled import single_vertex_experiment
            from shadow1_tpu.consts import MS, EngineParams
            from shadow1_tpu.core.engine import Engine

            exp = single_vertex_experiment(
                n_hosts=H, seed=77, end_time=10**12, latency_ns=30 * MS,
                model="phold",
                model_cfg={"mean_delay_ns": float(60 * MS), "init_events": 4},
            )
            eng = Engine(exp, EngineParams(ev_cap=C))
            st = eng.run(eng.init_state(), n_windows=20)
            jax.block_until_ready(st)
            m0 = Engine.metrics_dict(st)
            t1 = time.perf_counter()
            st = eng.run(st, n_windows=40)
            jax.block_until_ready(st)
            m1 = Engine.metrics_dict(st)
            r = m1["rounds"] - m0["rounds"]
            print(json.dumps({
                "probe": "phold",
                "ms_per_round": round(
                    1000 * (time.perf_counter() - t1) / max(r, 1), 3),
            }), flush=True)
            continue
        elif probe == "tor":
            from shadow1_tpu.config.experiment import load_experiment
            from shadow1_tpu.core.engine import Engine

            n = probe_n or 50
            exp, params, _ = load_experiment("configs/rung3_tor1k.yaml")
            eng = Engine(exp, params)
            st = eng.run(eng.init_state(), n_windows=n)
            jax.block_until_ready(st)
            print(json.dumps({
                "probe": "tor", "windows": n,
                "events": Engine.metrics_dict(st)["events"],
                "wall_s": round(time.perf_counter() - t0, 2),
            }), flush=True)
            continue
        else:
            print(json.dumps({"error": f"unknown probe {probe!r}"}))
            return 2
        print(json.dumps({
            "probe": probe, "ok": True,
            "wall_s": round(time.perf_counter() - t0, 2),
        }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
