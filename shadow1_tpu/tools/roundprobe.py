"""Round-path primitive ablation — where do the ms/round actually go?

    python -m shadow1_tpu.tools.roundprobe [probe ...] [--iters N]
        [--hosts H] [--cap C]

Round-5 context: the round-4 host-minor rewrite was justified by per-OP
microbenchmarks (min-reduce 7× faster, payload HBM 12.8× smaller —
docs/PERF.md) but the COMPOSITE engine round measured several times slower
on-chip (phold ms/round 1.4 → 8.4; rung3/rung5 throughput down 2.6-4×).
This tool times the actual engine primitives in isolation, warm, as jitted
``fori_loop`` bodies carrying the buffer through the loop — the same data
dependence the real round loop has — so the per-iteration cost attributes
ms/round to a specific primitive instead of a shape microbenchmark.

Probes (each prints us/iter):

* ``pop``      — ``pop_until`` alone (the two min-reductions + one-hot
                 extraction of kind, tb and the [NP,C,H] payload)
* ``pop_nop``  — ``pop_until`` variant WITHOUT payload extraction (splits
                 the extract_col cost out of ``pop``)
* ``pop_gat``  — ``pop_until`` variant extracting kind/tb/payload by
                 index-gather (first_true_idx + get_col) instead of the
                 masked-sum ``extract_col`` — the round-3 extraction style
                 on the round-4 layout (A/B for the regression hunt)
* ``push``     — ``push_local`` alone (first-free search + 4 wheres)
* ``cycle``    — push then pop (the minimal self-sustaining round kernel)
* ``wcycle``   — the same cycle under ``lax.while_loop`` with the engine's
                 ``any_eligible`` cond (isolates loop-structure cost:
                 wcycle − cycle ≈ what the while/cond machinery adds)
* ``rng``      — the phold handler's two hash draws + exponential + randint
                 (the non-event-buffer half of a phold round)
* ``obox``     — ``outbox_append`` alone (5 ``set_col`` one-hot writes)
* ``phold_win``— the full phold ``window_step`` (fori over windows), the
                 composite these primitives should sum to
* ``deliver``  — ``deliver_batch`` of H packets (the per-window merge)

One JSON line per probe. Compare ``pop + push`` against ``phold_win``'s
per-round cost: a large residual means the cost is in the round loop
structure (cond gating, metrics plumbing, while_loop carry), not the event
primitives.
"""

from __future__ import annotations

import argparse
import json
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("probes", nargs="*",
                    default=["pop", "pop_nop", "pop_gat", "push", "cycle",
                             "wcycle", "rng", "obox", "phold_win", "deliver"])
    # 5000, not 50: each probe times ONE XLA execution, and the tunnel adds
    # ~70 ms of fixed RTT per execution — at 50 iters the measurement is
    # ~100% RTT (docs/PERF.md round-5 correction). 5000 iters leaves
    # ~14 us/iter of residual RTT; subtract runs at two counts to net it out.
    # At iters > cap the pop-family probes drain the seeded buffer and push
    # probes saturate it — harmless for TIMING on this engine (every
    # primitive is a fixed set of data-independent tensor passes; an empty
    # pop or overflowed push runs the same ops as a live one), but the
    # nominal workload mix no longer matches the probe name; pass
    # --cap >= --iters when that distinction matters.
    ap.add_argument("--iters", type=int, default=5000)
    ap.add_argument("--hosts", type=int, default=1000)
    ap.add_argument("--cap", type=int, default=256)
    ap.add_argument("--allow-cpu", action="store_true",
                    help="run even on the CPU backend (smoke/compile check "
                         "only — CPU timings do not attribute TPU cost)")
    ap.add_argument("--pallas", action="store_true",
                    help="phold_win probe: run the engine with "
                         "pop_impl=push_impl='pallas' (core/popk.py); the "
                         "primitive-level fused probes are pop_f/push_f/"
                         "cycle_f/obox_f")
    ap.add_argument("--metrics-ring", type=int, default=0,
                    help="phold_win probe: run with a W-deep telemetry "
                         "ring (the ring-write cost per window)")
    ap.add_argument("--state-digest", action="store_true",
                    help="phold_win probe: run with the determinism flight "
                         "recorder on (implies a ring; the acceptance "
                         "budget is ≤5%% ms/round vs the plain ring — "
                         "docs/PERF.md)")
    args = ap.parse_args()

    import shadow1_tpu  # noqa: F401
    from shadow1_tpu.platform import ensure_live_platform

    ensure_live_platform(min_devices=1)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from shadow1_tpu.consts import MS, NP
    from shadow1_tpu.core import events as ev

    H, C, iters = args.hosts, args.cap, args.iters
    print(json.dumps({"backend": jax.default_backend(), "hosts": H,
                      "cap": C, "iters": iters}), flush=True)
    if jax.default_backend() == "cpu" and not args.allow_cpu:
        print(json.dumps({"error": "cpu backend — not the platform under "
                                   "test"}))
        return 1

    rng = np.random.default_rng(7)

    def seeded_buf(fill: int) -> ev.EventBuf:
        """A buffer with ``fill`` live events per host at random times.

        Times stay under the i32 rebase horizon (epoch 0) so the seeded
        keys are exact under the round-5 i32 round path (core/events.py)."""
        buf = ev.evbuf_init(H, C)
        t = jnp.asarray(rng.integers(0, 1 << 30, (C, H)), jnp.int64)
        tb = jnp.asarray(rng.integers(0, 1 << 40, (C, H)), jnp.int64)
        thi, tlo = ev.tb_split(t)
        hi, lo = ev.tb_split(tb)
        live = jnp.asarray(np.arange(C)[:, None] < fill, bool)
        buf = buf._replace(
            time_hi=jnp.where(live, thi, buf.time_hi),
            time_lo=jnp.where(live, tlo, buf.time_lo),
            tb_hi=jnp.where(live, hi, buf.tb_hi),
            tb_lo=jnp.where(live, lo, buf.tb_lo),
            kind=jnp.where(live, 1, buf.kind),
        )
        return ev.rebase(buf, 0)

    def timeit(name, make_step, carry0):
        """us/iter of ``carry = step(carry)`` over ``iters`` fori rounds."""
        def loop(carry, n):
            return jax.lax.fori_loop(0, n, lambda _, c: make_step(c), carry)

        f = jax.jit(loop, static_argnums=1)
        # Warm with the SAME static iter count: jit caches per static arg,
        # so warming with n=1 would leave the timed call paying a fresh
        # compile of the n=iters program (seconds on the tunnel — it would
        # swamp the microseconds under measurement).
        jax.block_until_ready(f(carry0, iters))
        t0 = time.perf_counter()
        jax.block_until_ready(f(carry0, iters))
        wall = time.perf_counter() - t0
        print(json.dumps({"probe": name,
                          "us_per_iter": round(1e6 * wall / iters, 1)}),
              flush=True)

    until = jnp.int64(1 << 30)                      # everything eligible
    until_i32 = jnp.int32(1 << 30)

    for probe in args.probes:
        if probe == "pop":
            def step(buf):
                buf, p = ev.pop_until(buf, until)
                # keep the pop results live without re-inserting (the buffer
                # drains over iters; seeded C slots >> iters keeps it warm)
                return buf._replace(self_ctr=buf.self_ctr + p.time)

            timeit("pop", step, seeded_buf(C))
        elif probe == "pop_nop":
            def step(buf):
                # pop_until minus the payload/kind extraction: the i32
                # lexicographic min chain and the buffer clear only.
                elig = (buf.kind != 0) & (buf.t32 < until_i32)
                t_masked = jnp.where(elig, buf.t32, ev.I32_FREE)
                min_t = t_masked.min(axis=0)
                tie = elig & (t_masked == min_t[None, :])
                hi_masked = jnp.where(tie, buf.tb_hi, ev.I32_MAX)
                min_hi = hi_masked.min(axis=0)
                tie2 = tie & (hi_masked == min_hi[None, :])
                lo_masked = jnp.where(tie2, buf.tb_lo, ev.I32_MAX)
                min_lo = lo_masked.min(axis=0)
                sel = tie2 & (lo_masked == min_lo[None, :])
                return buf._replace(
                    kind=jnp.where(sel, 0, buf.kind),
                    t32=jnp.where(sel, ev.I32_FREE, buf.t32),
                    self_ctr=buf.self_ctr + min_t.astype(jnp.int64),
                )

            timeit("pop_nop", step, seeded_buf(C))
        elif probe == "pop_gat":
            def step(buf):
                buf, p = ev.pop_until(buf, until, extract="gather")
                return buf._replace(self_ctr=buf.self_ctr + p.time)

            timeit("pop_gat", step, seeded_buf(C))
        elif probe == "pop_f":
            from shadow1_tpu.core.popk import pop_until_fused

            def step(buf):
                buf, p = pop_until_fused(buf, until)
                return buf._replace(self_ctr=buf.self_ctr + p.time)

            timeit("pop_f", step, seeded_buf(C))
        elif probe == "push_f":
            from shadow1_tpu.core.popk import push_local_fused

            k = jnp.ones(H, jnp.int32)
            pay = jnp.zeros((NP, H), jnp.int32)
            m = jnp.ones(H, bool)

            def step(buf):
                buf2, _over = push_local_fused(
                    buf, m, buf.self_ctr + 1, k, pay
                )
                return buf2._replace(kind=buf.kind)

            timeit("push_f", step, seeded_buf(C // 2))
        elif probe == "cycle_f":
            from shadow1_tpu.core.popk import pop_until_fused, push_local_fused

            k = jnp.ones(H, jnp.int32)
            pay = jnp.zeros((NP, H), jnp.int32)
            m = jnp.ones(H, bool)

            def step(buf):
                buf, p = pop_until_fused(buf, until)
                buf, _over = push_local_fused(buf, p.mask & m, p.time + 7, k,
                                              pay)
                return buf

            timeit("cycle_f", step, seeded_buf(C // 2))
        elif probe == "obox_f":
            from shadow1_tpu.core import outbox as ob
            from shadow1_tpu.core.popk import outbox_append_fused

            dst = jnp.ones(H, jnp.int32)
            k = jnp.ones(H, jnp.int32)
            pay = jnp.zeros((NP, H), jnp.int32)
            m = jnp.ones(H, bool)

            def step(box):
                box2, _ok = outbox_append_fused(
                    box, m, dst, k, box.pkt_ctr + 7, pay
                )
                return box2._replace(cnt=box.cnt)

            timeit("obox_f", step, ob.outbox_init(H, 64))
        elif probe == "wcycle":
            k = jnp.ones(H, jnp.int32)
            pay = jnp.zeros((NP, H), jnp.int32)
            m = jnp.ones(H, bool)

            def wloop(buf, n):
                def cond(carry):
                    b, r = carry
                    return (r < n) & ev.any_eligible(b, until)

                def body(carry):
                    b, r = carry
                    b, p = ev.pop_until(b, until)
                    b, _over = ev.push_local(b, p.mask & m, p.time + 7, k,
                                             pay)
                    return b, r + 1

                buf, _ = jax.lax.while_loop(
                    cond, body, (buf, jnp.zeros((), jnp.int32))
                )
                return buf

            f = jax.jit(wloop, static_argnums=1)
            carry0 = seeded_buf(C // 2)
            jax.block_until_ready(f(carry0, iters))
            t0 = time.perf_counter()
            jax.block_until_ready(f(carry0, iters))
            wall = time.perf_counter() - t0
            print(json.dumps({"probe": "wcycle",
                              "us_per_iter": round(1e6 * wall / iters, 1)}),
                  flush=True)
        elif probe == "rng":
            from shadow1_tpu import rng as prng
            from shadow1_tpu.consts import R_PHOLD_DELAY, R_PHOLD_DST

            key = prng.base_key(7)
            hosts = jnp.arange(H, dtype=jnp.int32)

            def step(ctr):
                delay = prng.exponential_ns(
                    prng.bits_v(key, R_PHOLD_DELAY, hosts, ctr), 1e6
                )
                dst = prng.randint(
                    prng.bits_v(key, R_PHOLD_DST, hosts, ctr), H
                )
                return ctr + 1 + (delay % 2) + dst.astype(jnp.int64)

            timeit("rng", step, jnp.zeros(H, jnp.int64))
        elif probe == "obox":
            from shadow1_tpu.core import outbox as ob

            dst = jnp.ones(H, jnp.int32)
            k = jnp.ones(H, jnp.int32)
            pay = jnp.zeros((NP, H), jnp.int32)
            m = jnp.ones(H, bool)

            def step(box):
                box2, _ok = ob.outbox_append(
                    box, m, dst, k, box.pkt_ctr + 7, pay
                )
                # hold occupancy so the append never saturates over iters
                return box2._replace(cnt=box.cnt)

            timeit("obox", step, ob.outbox_init(H, 64))
        elif probe == "push":
            k = jnp.ones(H, jnp.int32)
            pay = jnp.zeros((NP, H), jnp.int32)
            m = jnp.ones(H, bool)

            def step(buf):
                buf2, _over = ev.push_local(
                    buf, m, buf.self_ctr + 1, k, pay
                )
                # keep occupancy constant: restore kind so the buffer never
                # fills (cost of the where is part of the probe's point)
                return buf2._replace(kind=buf.kind)

            timeit("push", step, seeded_buf(C // 2))
        elif probe == "cycle":
            k = jnp.ones(H, jnp.int32)
            pay = jnp.zeros((NP, H), jnp.int32)
            m = jnp.ones(H, bool)

            def step(buf):
                buf, p = ev.pop_until(buf, until)
                buf, _over = ev.push_local(buf, p.mask & m, p.time + 7, k,
                                           pay)
                return buf

            timeit("cycle", step, seeded_buf(C // 2))
        elif probe == "phold_win":
            from shadow1_tpu.config.compiled import single_vertex_experiment
            from shadow1_tpu.consts import EngineParams
            from shadow1_tpu.core.engine import Engine

            exp = single_vertex_experiment(
                n_hosts=H, seed=77, end_time=10**15, latency_ns=30 * MS,
                model="phold",
                model_cfg={"mean_delay_ns": float(60 * MS),
                           "init_events": 4},
            )
            impl = "pallas" if args.pallas else "xla"
            ring = args.metrics_ring or (256 if args.state_digest else 0)
            eng = Engine(exp, EngineParams(ev_cap=C, pop_impl=impl,
                                           push_impl=impl,
                                           metrics_ring=ring,
                                           state_digest=int(args.state_digest)))
            st0 = eng.run(eng.init_state(), n_windows=10)  # warm state
            jax.block_until_ready(st0)
            m0 = Engine.metrics_dict(st0)
            t0 = time.perf_counter()
            st1 = eng.run(st0, n_windows=iters)
            jax.block_until_ready(st1)
            wall = time.perf_counter() - t0
            m1 = Engine.metrics_dict(st1)
            rounds = m1["rounds"] - m0["rounds"]
            print(json.dumps({
                "probe": "phold_win",
                "us_per_window": round(1e6 * wall / iters, 1),
                "rounds_per_window": round(rounds / iters, 2),
                "us_per_round": round(1e6 * wall / max(rounds, 1), 1),
            }), flush=True)
        elif probe == "deliver":
            dst = jnp.asarray(rng.integers(0, H, H), jnp.int32)
            t = jnp.asarray(rng.integers(0, 1 << 40, H), jnp.int64)
            tb = jnp.asarray(rng.integers(0, 1 << 40, H), jnp.int64)
            k = jnp.ones(H, jnp.int32)
            pay = jnp.zeros((NP, H), jnp.int32)
            m = jnp.ones(H, bool)

            def step(buf):
                buf2, _over = ev.deliver_batch(buf, dst, t, tb, k, pay, m)
                # hold occupancy: keep the timing honest across iters
                return buf2._replace(kind=buf.kind, time_hi=buf.time_hi,
                                     time_lo=buf.time_lo, t32=buf.t32)

            timeit("deliver", step, seeded_buf(C // 2))
        else:
            print(json.dumps({"error": f"unknown probe {probe!r}"}))
            return 2
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
