"""bitcoin — inv/getdata/tx gossip over a P2P graph (BASELINE rung 5).

The model-application analogue of the reference's bitcoin plugin
(shadow-plugin-bitcoin, SURVEY §2.4/§7.1: "Bitcoin = inv/getdata/tx gossip
state machine"). Nodes hold persistent TCP connections along the edges of a
configured peer graph; a transaction created at its origin is announced with
small INV messages, fetched with GETDATA, transferred as a tx-sized payload,
and re-announced by each node on first receipt — the classic flood. The
instrumented output is propagation: which nodes saw each tx, and when.

Wire model: INV/GETDATA/TX are message boundaries on the TCP byte stream
(meta = cmd<<20 | txid), so loss/recovery/queueing all ride the real virtual
TCP machinery.

Batched-engine shape note: fan-out (dial K neighbors, announce a tx on K
conns) is expressed as K self-scheduled events at the same timestamp rather
than K inline transport calls — the event core serializes them in
deterministic (time, tb) order, the CPU oracle schedules the identical
events, and the traced round body instantiates the TCP send path once
instead of K times (the SIMD analogue of the reference queueing work items
rather than deep call chains).

model_cfg:
  peers      i32 [H, K] neighbor ids, -1 = unused slot (edges must be
             symmetric: n in peers[h] ⇔ h in peers[n])
  tx_origin  i32 [T] origin host per transaction
  tx_time    i64 [T] creation time per transaction (leave ≥ a few RTT after
             connect_time so the conn mesh is up)
  tx_size    int, payload bytes of a transaction (default 400)
  inv_size   int, bytes of INV/GETDATA messages (default 36)
  connect_time  int ns, when the conn mesh is dialed (default 0)
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from shadow1_tpu.core.dense import get_col, set_col
from shadow1_tpu.consts import (
    K_APP,
    N_ACCEPTED,
    N_MSG,
    NP,
    TCP_LISTEN,
)
from shadow1_tpu.core.engine import push_local_event
from shadow1_tpu.core.events import push_local
from shadow1_tpu.tcp import tcp as T

OP_CONNECT_ONE = 1   # p1 = neighbor slot j
OP_TX_CREATE = 2     # p1 = txid
OP_TX_MSG = 3        # p1 = socket, p2 = meta, p3 = nbytes

CMD_INV = 1
CMD_GET = 2
CMD_TX = 3

TXID_BITS = 20
TXID_MASK = (1 << TXID_BITS) - 1


def _meta(cmd, txid):
    return (cmd << TXID_BITS) | txid


def init(ctx, evbuf, tcpd):
    cfg = ctx.model_cfg
    peers = jnp.asarray(cfg["peers"], jnp.int32).T        # [K, H] host-minor
    tx_origin = np.asarray(cfg["tx_origin"], np.int64)    # [T] (host-side)
    tx_time = np.asarray(cfg["tx_time"], np.int64)
    n_tx = len(tx_origin)
    assert n_tx <= TXID_MASK
    k_max, h = peers.shape
    app = {
        "peers": peers,
        # Socket reaching neighbor j (outbound = 1+j at dial time; inbound
        # learned on N_ACCEPTED); -1 = no conn yet.
        "nbr_sock": jnp.full((k_max, h), -1, jnp.int32),
        "seen": jnp.zeros((n_tx, h), bool),
        "req": jnp.zeros((n_tx, h), bool),
        "seen_time": jnp.zeros((n_tx, h), jnp.int64),
        "tx_rx": jnp.zeros(h, jnp.int64),   # tx payloads received
        "msg_retries": jnp.zeros(h, jnp.int64),
    }
    tcpd = dict(tcpd)
    tcpd["st"] = tcpd["st"].at[0].set(TCP_LISTEN)
    # Dial the conn mesh: one OP_CONNECT_ONE per outbound neighbor slot.
    connect_time = jnp.full(ctx.n_hosts, int(cfg.get("connect_time", 0)), jnp.int64)
    kk = jnp.full(ctx.n_hosts, K_APP, jnp.int32)
    n_over = jnp.zeros((), jnp.int64)
    for j in range(k_max):
        m = peers[j] > ctx.hosts
        p = jnp.zeros((NP, ctx.n_hosts), jnp.int32)
        p = p.at[0].set(OP_CONNECT_ONE).at[1].set(j)
        evbuf, over = push_local(evbuf, m, connect_time, kk, p)
        n_over = n_over + over.sum(dtype=jnp.int64)
    # Seed tx-creation wakeups, one masked push per transaction.
    for t in range(n_tx):
        mask = ctx.hosts == int(tx_origin[t])
        p = jnp.zeros((NP, ctx.n_hosts), jnp.int32)
        p = p.at[0].set(OP_TX_CREATE).at[1].set(t)
        evbuf, over = push_local(
            evbuf, mask, jnp.full(ctx.n_hosts, int(tx_time[t]), jnp.int64), kk, p
        )
        n_over = n_over + over.sum(dtype=jnp.int64)
    return app, evbuf, n_over, tcpd


def _push_msg(st, ctx, mask, sock, meta, nbytes, now):
    """Queue a protocol message send (admission-checked in OP_TX_MSG)."""
    return push_local_event(
        st, ctx, mask, now, K_APP, p0=OP_TX_MSG, p1=sock, p2=meta, p3=nbytes
    )


def _announce(st, ctx, mask, txid, skip_sock, now):
    """Queue one INV per live neighbor conn except ``skip_sock``."""
    inv_size = int(ctx.model_cfg.get("inv_size", 36))
    app = st.model.app
    for j in range(app["peers"].shape[0]):
        ns = app["nbr_sock"][j]
        m = mask & (ns >= 0) & (ns != skip_sock)
        st = _push_msg(st, ctx, m, ns, _meta(CMD_INV, txid), inv_size, now)
    return st


def _mark_seen(app, mask, txid, now):
    t_safe = jnp.where(mask, txid, 0)
    was = get_col(app["seen"], t_safe)
    new = mask & ~was
    # Dense one-hot writes, not .at[] scatters (core/dense.py: XLA
    # serializes dynamic-index scatters on TPU; this runs per gossip round).
    app["seen"] = set_col(app["seen"], t_safe, True, new)
    app["seen_time"] = set_col(app["seen_time"], t_safe, now, new)
    return app, new


def on_wakeup(st, ctx, ev, mask):
    op = ev.p[0]
    app = st.model.app
    k_max = app["peers"].shape[0]
    zero = jnp.zeros(ctx.n_hosts, jnp.int32)

    # OP_CONNECT_ONE: dial neighbor slot j = p1 on socket 1+j. Startup-only
    # (one per neighbor edge) but carries a tcp_connect — lax.cond keeps it
    # out of steady-state gossip rounds (exact: all writes masked).
    conn = mask & (op == OP_CONNECT_ONE)

    def _op_conn(st):
        app = st.model.app
        j = jnp.where(conn, ev.p[1], 0)
        peer = get_col(app["peers"], j)
        sock = (1 + j).astype(jnp.int32)
        napp = dict(app)
        napp["nbr_sock"] = set_col(napp["nbr_sock"], j, sock, conn)
        st = st._replace(model=st.model._replace(app=napp))
        return T.tcp_connect(st, ctx, conn, sock, peer, zero, ev.time)

    st = jax.lax.cond(conn.any(), _op_conn, lambda s: s, st)

    # OP_TX_CREATE: origin marks the tx seen and queues the announcements
    # (a few hundred per run — cond-gated).
    create = mask & (op == OP_TX_CREATE)

    def _op_create(st):
        txid = ev.p[1]
        app = dict(st.model.app)
        app, new = _mark_seen(app, create, txid, ev.time)
        st = st._replace(model=st.model._replace(app=app))
        none = jnp.full(ctx.n_hosts, -1, jnp.int32)
        return _announce(st, ctx, new, txid, none, ev.time)

    st = jax.lax.cond(create.any(), _op_create, lambda s: s, st)

    # OP_TX_MSG: the single transport-send site. Admission: the message must
    # fit the send buffer and a boundary slot must be free, else retry at the
    # next window start — a congested conn defers gossip instead of losing
    # its framing (same shape as tor.py's OP_TX_CELL).
    tx = mask & (op == OP_TX_MSG)
    sock, meta, nbytes = ev.p[1], ev.p[2], ev.p[3]
    tcp = st.model.tcp
    sk = jnp.where(tx, sock, 0)
    snd_una = get_col(tcp["snd_una"], sk)
    app_end = get_col(tcp["app_end"], sk)
    buffered = (app_end - snd_una) - (snd_una == 0).astype(jnp.int32)
    fits = (ctx.params.sndbuf - buffered) >= nbytes
    mq_ok = ~get_col(tcp["mq_valid"], sk).all(axis=0)
    can = tx & fits & mq_ok
    retry = tx & ~can
    st, _acc = T.tcp_send(st, ctx, can, sock, nbytes, meta, ev.time)
    napp = dict(st.model.app)
    napp["msg_retries"] = napp["msg_retries"] + retry.astype(jnp.int64)
    st = st._replace(model=st.model._replace(app=napp))
    t_retry = (ev.time // ctx.window + 1) * ctx.window
    return push_local_event(
        st, ctx, retry, t_retry, K_APP, p0=OP_TX_MSG, p1=sock, p2=meta, p3=nbytes
    )


def on_notify(st, ctx, nf: T.Notif, now, mask):
    f = nf.flags
    sock = nf.sock
    tx_size = int(ctx.model_cfg.get("tx_size", 400))
    inv_size = int(ctx.model_cfg.get("inv_size", 36))

    # Inbound conn accepted: bind it to its neighbor slot (startup-only;
    # the k-slot scan is cond-gated out of steady-state gossip rounds).
    acc = mask & ((f & N_ACCEPTED) != 0)

    def _accepted(st):
        app = dict(st.model.app)
        peer = get_col(st.model.tcp["peer_host"], jnp.where(acc, sock, 0))
        for j in range(app["peers"].shape[0]):
            m = acc & (app["peers"][j] == peer) & (app["nbr_sock"][j] < 0)
            app["nbr_sock"] = app["nbr_sock"].at[j].set(
                jnp.where(m, sock, app["nbr_sock"][j])
            )
        return st._replace(model=st.model._replace(app=app))

    st = jax.lax.cond(acc.any(), _accepted, lambda s: s, st)

    # Protocol messages (one boundary per host-round at most).
    msg = mask & ((f & N_MSG) != 0)
    cmd = nf.meta >> TXID_BITS
    txid = nf.meta & TXID_MASK
    app = st.model.app
    t_safe = jnp.where(msg, txid, 0)
    seen = get_col(app["seen"], t_safe)
    req = get_col(app["req"], t_safe)

    # INV for an unknown tx → GETDATA back on the same conn.
    want = msg & (cmd == CMD_INV) & ~seen & ~req
    napp = dict(app)
    napp["req"] = set_col(napp["req"], t_safe, True, want)
    st = st._replace(model=st.model._replace(app=napp))

    # GETDATA for a tx we hold → send the payload. The two responses are
    # mutually exclusive per host-round, so they share one queued send.
    give = msg & (cmd == CMD_GET) & seen
    resp = want | give
    nbytes = jnp.where(give, tx_size, inv_size).astype(jnp.int32)
    rmeta = jnp.where(
        give, _meta(CMD_TX, txid), _meta(CMD_GET, jnp.where(msg, txid, 0))
    )
    st = _push_msg(st, ctx, resp, sock, rmeta, nbytes, now)

    # TX payload → first sight: record + queue announcements everywhere else.
    got = msg & (cmd == CMD_TX)
    app = dict(st.model.app)
    app["tx_rx"] = app["tx_rx"] + got.astype(jnp.int64)
    app, new = _mark_seen(app, got, txid, now)
    st = st._replace(model=st.model._replace(app=app))
    return _announce(st, ctx, new, txid, sock, now)


def summary(app) -> dict:
    seen = app["seen"]                        # [T, H] internally
    return {
        "seen": seen.T,                       # [H, T] — oracle orientation
        "seen_time": app["seen_time"].T,
        "tx_rx": app["tx_rx"],
        "reach": seen.sum(axis=1),            # nodes reached per tx
        "msg_retries": app["msg_retries"],
        "total_seen": seen.sum(),
        "total_tx_rx": app["tx_rx"].sum(),
    }
