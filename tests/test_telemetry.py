"""Telemetry plane: on-device ring, phase profiler, unified registry.

The ring's contract (ISSUE 1 acceptance): one record per window whose
per-window deltas sum (within the ring horizon) to the heartbeat's chunk
deltas, recorded with ZERO host↔device syncs inside the window loop; the
profiler's contract: Chrome trace-event JSON that parses cleanly and
carries the compile / run-chunk / drain spans on both batched engines.
"""

import io
import json
import types
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import MS, EngineParams
from shadow1_tpu.core.engine import Engine, Metrics
from shadow1_tpu.obs import Heartbeat, run_with_heartbeat
from shadow1_tpu.telemetry import (
    METRIC_SPECS,
    RING_COUNTERS,
    RING_FIELDS,
    ExpositionServer,
    PhaseProfiler,
    normalize,
    to_prometheus,
)
from shadow1_tpu.telemetry.ring import drain_ring


def phold_exp(n_hosts=32, seed=17, end_time=100 * MS):
    return single_vertex_experiment(
        n_hosts=n_hosts,
        seed=seed,
        end_time=end_time,
        latency_ns=1 * MS,
        model="phold",
        model_cfg={"mean_delay_ns": float(2 * MS), "init_events": 2},
    )


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_registry_in_sync_with_engine_metrics():
    """The canonical namespace IS the engine's Metrics fields — the guard
    that keeps the tpu/sharded/cpu schemas from drifting apart again.
    The declared HOST_FIELDS (overflow-retry counters, maintained by the
    chunk runner on the host) are the one sanctioned extension."""
    from shadow1_tpu.telemetry.registry import HOST_FIELDS

    assert set(HOST_FIELDS) <= set(METRIC_SPECS)
    assert set(METRIC_SPECS) - set(HOST_FIELDS) == set(Metrics._fields)
    # Every ring counter is a canonical counter (deltas of real metrics).
    assert set(RING_COUNTERS) <= set(METRIC_SPECS)


def test_registry_link_schema_pinned():
    """The link-record schema is part of the sync contract: the oracle and
    the traced scatter address columns by position, so the declared order
    is load-bearing — a reorder is a schema break, not a refactor."""
    from shadow1_tpu.telemetry.registry import (
        LINK_FIELDS,
        LINK_MAX_COL,
        RECORD_TYPES,
        REC_LINK,
        REC_LINK_GAP,
        SERVE_SPECS,
    )

    assert REC_LINK in RECORD_TYPES and REC_LINK_GAP in RECORD_TYPES
    assert LINK_FIELDS == (
        "pkts", "bytes", "loss_drops", "link_down_drops",
        "nic_backlog_drops", "queued_ns_sum", "queued_ns_max")
    # The gauge column is last: the additive prefix buf[..., :LINK_MAX_COL]
    # is what shard/engine.py psums; the max column pmax-reduces.
    assert LINK_MAX_COL == len(LINK_FIELDS) - 1
    # The serve ledger exports the hot-edge gauges under SERVE_SPECS.
    assert SERVE_SPECS["top_edge_bytes"][0] == "gauge"
    assert SERVE_SPECS["top_edge_drops"][0] == "gauge"


def test_normalize_fills_missing_and_keeps_extras():
    d = normalize({"events": 7, "custom_counter": 3})
    assert d["events"] == 7
    assert d["windows"] == 0 and d["tcp_rto"] == 0  # filled, no KeyError
    assert d["custom_counter"] == 3
    assert list(d)[: len(METRIC_SPECS)] == list(METRIC_SPECS)  # canonical order


def test_prometheus_exposition_format():
    text = to_prometheus({"events": 41, "x2x_max_fill": 9},
                         labels={"engine": "tpu"})
    assert '# TYPE shadow1_events_total counter' in text
    assert 'shadow1_events_total{engine="tpu"} 41' in text
    # Gauges are exported without the counter suffix.
    assert '# TYPE shadow1_x2x_max_fill gauge' in text
    assert 'shadow1_x2x_max_fill{engine="tpu"} 9' in text
    assert text.endswith("\n")


def test_exposition_server_scrape():
    srv = ExpositionServer(lambda: {"events": 5}, port=0).start()
    try:
        url = f"http://127.0.0.1:{srv.port}/metrics"
        with urllib.request.urlopen(url, timeout=5) as resp:
            body = resp.read().decode()
            assert resp.headers["Content-Type"].startswith("text/plain")
        assert "shadow1_events_total 5" in body
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# on-device ring
# ---------------------------------------------------------------------------

def test_ring_one_record_per_window_sums_to_heartbeat_deltas():
    eng = Engine(phold_exp(), EngineParams(metrics_ring=32))
    buf = io.StringIO()
    st, hb = run_with_heartbeat(eng, n_windows=100, every_windows=25,
                                stream=buf)
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    rings = [r for r in lines if r["type"] == "ring"]
    hbs = [r for r in lines if r["type"] == "heartbeat"]
    # One record per window, in window order, none lost (ring depth 32 > 25).
    assert [r["window"] for r in rings] == list(range(100))
    assert not [r for r in lines if r["type"] == "ring_gap"]
    # Ring deltas sum to the heartbeat chunk deltas — same counters, finer
    # resolution (the acceptance identity).
    for i, h in enumerate(hbs):
        chunk = [r for r in rings if i * 25 <= r["window"] < (i + 1) * 25]
        for field in ("events", "rounds", "pkts_sent", "pkts_delivered"):
            assert sum(r[field] for r in chunk) == h["delta"][field], field
        # Drop counters ride the structured ``drops`` block (same deltas).
        for field in ("pkts_lost", "ev_overflow"):
            assert sum(r[field] for r in chunk) == h["drops"][field], field
        assert h["drops"]["total"] == sum(
            v for k, v in h["drops"].items() if k != "total")
    # The gauge actually observes occupancy.
    assert max(r["evbuf_fill"] for r in rings) > 0
    assert int(st.metrics.events) == sum(r["events"] for r in rings)


def test_ring_no_host_sync_inside_window_loop():
    """The acceptance's zero-sync clause, proven two ways: (1) the whole
    window loop with ring recording traces to a jaxpr (any host fetch of a
    traced value would raise ConcretizationTypeError); (2) running chunks
    performs no block_until_ready at all beyond the explicit warmup."""
    eng = Engine(phold_exp(), EngineParams(metrics_ring=16))
    st = eng.init_state()
    jaxpr = jax.make_jaxpr(eng._make_run())(st, jnp.asarray(8, jnp.int32))
    assert jaxpr is not None  # traced end-to-end: device-resident recording

    calls = {"n": 0}
    real = jax.block_until_ready

    def counting(x):
        calls["n"] += 1
        return real(x)

    from shadow1_tpu.ckpt import run_chunked

    jax.block_until_ready(eng.run(st, n_windows=0))  # warmup outside count
    try:
        jax.block_until_ready = counting
        run_chunked(eng, st, n_windows=32, chunk=8)
    finally:
        jax.block_until_ready = real
    assert calls["n"] == 0


def test_ring_gap_is_reported_not_silent():
    """A chunk longer than the ring keeps the LAST W windows and says so."""
    eng = Engine(phold_exp(), EngineParams(metrics_ring=8))
    buf = io.StringIO()
    run_with_heartbeat(eng, n_windows=40, every_windows=20, stream=buf)
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    rings = [r for r in lines if r["type"] == "ring"]
    gaps = [r for r in lines if r["type"] == "ring_gap"]
    # Each 20-window chunk recovers its last 8 windows + one gap record.
    assert [r["window"] for r in rings] == list(range(12, 20)) + list(range(32, 40))
    assert [g["windows_lost"] for g in gaps] == [12, 12]


def test_ring_sharded_parity_and_global_reduction():
    """The sharded ring must record the same global per-window series the
    single-device engine records (counters psum'd, fill max'd across the
    8-device mesh) — the ring analogue of the shard parity invariant.
    ``rounds`` is excluded like the metric itself (per-shard loops);
    ``x2x_max_fill`` only exists under sharding; ``compact_max_fill``
    counts the LOCAL block's active hosts (the per-shard bucket is the
    resource it sizes), so like ``rounds`` it is per-shard by design."""
    from shadow1_tpu.shard.engine import ShardedEngine

    exp = phold_exp(n_hosts=64, seed=7, end_time=50 * MS)
    params = EngineParams(metrics_ring=64)
    st1 = Engine(exp, params).run(n_windows=50)
    sh = ShardedEngine(exp, params)
    assert sh.n_dev == 8, "conftest must provide 8 virtual devices"
    st8 = sh.run(n_windows=50)
    r1 = drain_ring(st1, exp.window)
    r8 = drain_ring(st8, exp.window)
    assert len(r1) == len(r8) == 50
    skip = {"rounds", "x2x_max_fill", "compact_max_fill"}
    for a, b in zip(r1, r8):
        for field in RING_FIELDS:
            if field not in skip:
                assert a[field] == b[field], (a["window"], field)
    assert max(r["x2x_max_fill"] for r in r8) > 0  # exchange actually observed


def test_ring_survives_checkpoint_resume(tmp_path):
    """The ring is engine state: a checkpointed+resumed run carries the
    identical ring rows an uninterrupted run produces."""
    from shadow1_tpu.ckpt import load_state, save_state

    eng = Engine(phold_exp(), EngineParams(metrics_ring=16))
    ref = eng.run(n_windows=60)
    st = eng.run(n_windows=25)
    path = str(tmp_path / "ring.npz")
    save_state(st, path)
    st2 = load_state(eng.init_state(), path)
    final = eng.run(st2, n_windows=35)
    np.testing.assert_array_equal(
        np.asarray(ref.telem.buf), np.asarray(final.telem.buf)
    )
    assert drain_ring(ref, eng.window) == drain_ring(final, eng.window)


def test_ring_off_keeps_legacy_state_layout():
    """metrics_ring=0 must not grow the SimState pytree — checkpoints and
    sharding specs of ring-less runs stay exactly as before."""
    eng_off = Engine(phold_exp(), EngineParams())
    st = eng_off.init_state()
    assert st.telem is None
    n_leaves = len(jax.tree_util.tree_leaves(st))
    eng_on = Engine(phold_exp(), EngineParams(metrics_ring=4))
    assert len(jax.tree_util.tree_leaves(eng_on.init_state())) == n_leaves + 1


# ---------------------------------------------------------------------------
# phase profiler
# ---------------------------------------------------------------------------

def test_profiler_chrome_trace_roundtrip(tmp_path):
    prof = PhaseProfiler()
    with prof.span("compile"):
        with prof.span("inner", detail=3):
            pass
    prof.instant("fault", rc=41)
    path = str(tmp_path / "trace.json")
    prof.write(path)
    with open(path) as f:
        doc = json.load(f)  # must parse cleanly (the acceptance clause)
    names = [e["name"] for e in doc["traceEvents"]]
    assert "compile" in names and "inner" in names and "fault" in names
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert all(e["dur"] >= 0 and e["ts"] >= 0 for e in spans)
    inner = next(e for e in spans if e["name"] == "inner")
    assert inner["args"] == {"detail": 3}


@pytest.mark.parametrize("engine_kind", ["tpu", "sharded"])
def test_profiler_spans_cover_run_phases(engine_kind):
    """compile / run-chunk / drain spans on both batched engines (the
    acceptance's span set), via the same hook the CLI --trace uses."""
    if engine_kind == "sharded":
        from shadow1_tpu.shard.engine import ShardedEngine as Eng

        eng = Eng(phold_exp(n_hosts=64, seed=7, end_time=20 * MS),
                  EngineParams(metrics_ring=8))
    else:
        eng = Engine(phold_exp(end_time=20 * MS),
                     EngineParams(metrics_ring=8))
    prof = PhaseProfiler()
    run_with_heartbeat(eng, n_windows=20, every_windows=10, stream=False,
                       profiler=prof)
    names = set(prof.span_names())
    assert {"init", "compile", "run-chunk", "drain"} <= names


def test_profiler_checkpoint_span(tmp_path):
    eng = Engine(phold_exp(end_time=20 * MS), EngineParams())
    prof = PhaseProfiler()
    run_with_heartbeat(eng, n_windows=20, every_windows=10, stream=False,
                       ckpt_path=str(tmp_path / "ck.npz"), ckpt_every_s=0.0,
                       profiler=prof)
    assert "checkpoint" in prof.span_names()


# ---------------------------------------------------------------------------
# heartbeat robustness (satellite: alternate engines reuse it unchanged)
# ---------------------------------------------------------------------------

def test_heartbeat_tolerates_engines_without_canonical_fields():
    fake_engine = types.SimpleNamespace(window=1000, n_windows=4)
    buf = io.StringIO()
    hb = Heartbeat(fake_engine, stream=buf)
    st = types.SimpleNamespace(
        metrics={"custom": 3},  # no events/windows/rounds anywhere
        win_start=2000,
        telem=None,
    )
    hb(st, 2)  # must not KeyError
    rec = json.loads(buf.getvalue().splitlines()[0])
    assert rec["delta"]["events"] == 0
    assert rec["rounds_per_window"] is None
    assert rec["delta"]["custom"] == 3


def test_log_level_validation():
    from shadow1_tpu.log import SimLogger

    with pytest.raises(ValueError, match="error.*warning.*message.*info.*debug"):
        SimLogger(level="verbose")
    log = SimLogger(stream=io.StringIO())
    with pytest.raises(ValueError, match="valid levels"):
        log.log("loud", "boom")


# ---------------------------------------------------------------------------
# CLI: --trace + --metrics-ring end to end (tpu and sharded engines)
# ---------------------------------------------------------------------------

def _run_cli(args, env):
    import subprocess
    import sys

    return subprocess.run([sys.executable, "-m", "shadow1_tpu", *args],
                          env=env, capture_output=True, text=True,
                          timeout=600)


def _cli_env():
    import os

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
    return env


def _assert_trace_and_ring(r, trace_path, n_windows):
    assert r.returncode == 0, (r.stdout[-400:], r.stderr[-800:])
    with open(trace_path) as f:
        doc = json.load(f)  # acceptance: json.loads cleanly
    spans = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert {"compile", "run-chunk", "drain"} <= spans, spans
    rings = [json.loads(x) for x in r.stderr.splitlines()
             if x.startswith("{") and '"type": "ring"' in x]
    assert [rec["window"] for rec in rings] == list(range(n_windows))
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["metrics"]["events"] == sum(rec["events"] for rec in rings)


def test_cli_trace_and_metrics_ring_tpu(tmp_path):
    import os

    cfg = os.path.join(os.path.dirname(__file__), "..", "configs",
                       "rung1_filexfer.yaml")
    trace = str(tmp_path / "tpu.trace.json")
    r = _run_cli([cfg, "--windows", "12", "--metrics-ring", "6",
                  "--trace", trace], _cli_env())
    _assert_trace_and_ring(r, trace, 12)


def test_cli_trace_and_metrics_ring_sharded(tmp_path):
    cfg = tmp_path / "phold8.yaml"
    cfg.write_text(
        "general: {seed: 3, stop_time: 20 ms}\n"
        "engine: {scheduler: sharded, ev_cap: 64}\n"
        "network: {single_vertex: {latency: 1 ms}}\n"
        "hosts:\n"
        "  - {name: h, count: 8}\n"
        "app:\n"
        "  model: phold\n"
        "  params: {mean_delay_ns: 2000000.0, init_events: 2}\n"
    )
    trace = str(tmp_path / "sharded.trace.json")
    r = _run_cli([str(cfg), "--windows", "10", "--metrics-ring", "5",
                  "--trace", trace], _cli_env())
    _assert_trace_and_ring(r, trace, 10)


# ---------------------------------------------------------------------------
# tools/heartbeat_report.py (satellite: synthetic-log coverage)
# ---------------------------------------------------------------------------

def _synthetic_log(tmp_path):
    lines = [
        "booting the simulator...",                       # non-JSON noise
        '{"truncated": ',                                 # broken JSON
        json.dumps({"type": "heartbeat", "sim_time_s": 0.5, "wall_s": 1.0,
                    "windows": 5, "events_per_sec": 100.0,
                    "sim_per_wall": 0.5,
                    "delta": {"events": 100, "pkts_delivered": 40,
                              "tcp_rto": 1, "tcp_fast_rtx": 2}}),
        json.dumps({"type": "heartbeat", "sim_time_s": 1.0, "wall_s": 2.0,
                    "windows": 10, "events_per_sec": 300.0,
                    "sim_per_wall": 0.5,
                    "delta": {"events": 300, "pkts_delivered": 60}}),
        json.dumps({"type": "ring_gap", "windows_lost": 2,
                    "first_window": 0, "ring_slots": 4}),
        json.dumps({"type": "tracker", "sim_s": 1.0, "host": 0,
                    "nic_tx_bytes": 999, "nic_rx_bytes": 10,
                    "pending_events": 3}),
        json.dumps({"type": "tracker", "sim_s": 1.0, "host": 1,
                    "nic_tx_bytes": 5, "nic_rx_bytes": 700,
                    "pending_events": 0}),
        "still not json {",
    ]
    for w, (ev, fill) in enumerate([(10, 2), (20, 8), (30, 4), (40, 6)],
                                   start=2):
        lines.append(json.dumps({
            "type": "ring", "window": w, "sim_time_s": (w + 1) * 1e-3,
            "events": ev, "rounds": 3, "pkts_sent": ev, "pkts_delivered": ev,
            "pkts_lost": 0, "ev_overflow": 0, "ob_overflow": 0,
            "x2x_overflow": 0, "down_events": 0, "down_pkts": 0,
            "evbuf_fill": fill, "x2x_max_fill": 0,
        }))
    path = tmp_path / "run.log"
    path.write_text("\n".join(lines) + "\n")
    return str(path)


def test_heartbeat_report_summary_and_csv(tmp_path, capsys):
    from shadow1_tpu.tools import heartbeat_report as hr

    log = _synthetic_log(tmp_path)
    recs = hr.load_records(log)
    assert len(recs) == 9  # garbage lines skipped, records kept
    summary = hr.summarize(recs)
    out = capsys.readouterr().out
    assert summary["heartbeats"] == 2
    assert summary["tracker_records"] == 2
    assert summary["ring_records"] == 4
    assert summary["events"] == 400
    assert summary["retransmits"] == 3
    assert summary["ring"]["events"] == {"p50": 20, "p95": 40, "max": 40}
    assert summary["ring"]["evbuf_fill"]["max"] == 8
    assert summary["ring_windows_lost"] == 2
    assert "== run summary ==" in out
    assert "== per-window occupancy (ring) ==" in out
    assert "WINDOWS LOST TO RING OVERWRITE: 2" in out
    assert "host 0: tx 999 B" in out

    csv_path = str(tmp_path / "hb.csv")
    ring_csv = str(tmp_path / "ring.csv")
    rc = hr.main([log, "--csv", csv_path, "--ring-csv", ring_csv])
    assert rc == 0
    with open(csv_path) as f:
        rows = f.read().splitlines()
    assert rows[0].startswith("sim_time_s,wall_s")
    assert len(rows) == 3 and rows[1].split(",")[4] == "100"
    with open(ring_csv) as f:
        rrows = [line.split(",") for line in f.read().splitlines()]
    assert rrows[0][:3] == ["window", "sim_time_s", "events"]
    assert len(rrows) == 5
    assert rrows[2][0] == "3" and rrows[2][2] == "20"


def test_heartbeat_report_empty_log(tmp_path):
    from shadow1_tpu.tools import heartbeat_report as hr

    p = tmp_path / "empty.log"
    p.write_text("nothing json here\n")
    assert hr.main([str(p)]) == 1
