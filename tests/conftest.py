"""Test harness setup.

Tests run on the CPU platform with 8 virtual devices so multi-chip sharding
paths compile and execute without TPU hardware (the driver separately
dry-runs them via __graft_entry__.dryrun_multichip). Must run before jax
imports anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

# Package import path comes from pyproject.toml [tool.pytest.ini_options]
# pythonpath — no sys.path surgery here.
import shadow1_tpu  # noqa: E402,F401  (enables x64 before any jax array exists)
import jax  # noqa: E402

# The environment pre-sets JAX_PLATFORMS=axon (the TPU plugin) in a way that
# wins over os.environ mutation; the config route reliably forces CPU.
jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the TCP round body is a large program; caching
# compiles across test runs cuts suite time substantially.
jax.config.update("jax_compilation_cache_dir", "/tmp/shadow1_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

# CLI subprocess tests (supervise/trace/auto-caps) inherit os.environ: give
# their children the same persistent cache via the env-var config route, so
# every spawned `python -m shadow1_tpu` reuses compiles instead of paying
# the multi-second engine trace per process (tier-1 wall budget, PR 4).
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/shadow1_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1.0")
