"""Event-buffer occupancy audit — size ev_cap from measurement, not guess.

    python -m shadow1_tpu.tools.occprobe [--windows N] [config.yaml ...]

Every pop/push/rebase is a full [ev_cap, H] plane pass, so ev_cap is the
plane height of the hottest tensors in the round (docs/PERF.md round-5
fusion-kernel analysis); a cap sized far above the workload's real peak
occupancy taxes every round for headroom it never uses. This tool runs a
config on the CPU platform in --step-window chunks and reports the peak
per-host event-slot occupancy OBSERVED AT CHUNK BOUNDARIES — a LOWER
BOUND on the true peak (occupancy peaks mid-window, while delivered
packets coexist with freshly pushed events, and the snapshot sees only
the leftovers). Size caps as boundary-peak + generous margin and treat a
cap change as validated only by an overflow-free full run: ev_overflow
(in every measurement row) is the authoritative guard, and this tool
exits nonzero when the audited run itself overflowed (the reported peak
is then meaningless — events that were dropped never occupied a slot).
Round-5 audit: dense_tgen boundary peak 66 → cap 96, validated by an
overflow-free bit-identical 60-window run; rung3 boundary peak 129 of
cap 256 over the full 2000 windows.

Runs on CPU: occupancy is backend-invariant (bit-identical engines), and
the window-by-window readback would thrash the TPU tunnel's ~70 ms
per-execution RTT.
"""

from __future__ import annotations

import argparse
import json


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("configs", nargs="+")
    ap.add_argument("--windows", type=int, default=0,
                    help="windows to audit (0 = the config's full run)")
    ap.add_argument("--step", type=int, default=10,
                    help="windows per device call between readbacks")
    args = ap.parse_args()
    if args.step < 1:
        ap.error("--step must be >= 1")
    if args.windows < 0:
        ap.error("--windows must be >= 0")

    import shadow1_tpu  # noqa: F401
    from shadow1_tpu.platform import force_cpu

    force_cpu(1)
    import numpy as np

    from shadow1_tpu.config.experiment import load_experiment
    from shadow1_tpu.core.engine import Engine

    bad = False
    for cfg in args.configs:
        exp, params, _ = load_experiment(cfg)
        eng = Engine(exp, params)
        nw = args.windows or eng.n_windows
        st = eng.init_state()
        # Count the seeded initial state too — a model that seeds a burst
        # draining inside the first chunk would otherwise be missed.
        peak = int((np.asarray(st.evbuf.kind) != 0).sum(axis=0).max())
        done = 0
        while done < nw:
            step = min(args.step, nw - done)
            st = eng.run(st, n_windows=step)
            done += step
            peak = max(peak, int(
                (np.asarray(st.evbuf.kind) != 0).sum(axis=0).max()
            ))
        m = Engine.metrics_dict(st)
        row = {
            "config": cfg, "windows": done, "ev_cap": params.ev_cap,
            "boundary_peak_occupancy": peak,
            "ev_overflow": int(m["ev_overflow"]),
            "headroom": round(params.ev_cap / max(peak, 1), 2),
        }
        if row["ev_overflow"]:
            row["invalid"] = ("run overflowed — dropped events never "
                              "occupied a slot; the peak is meaningless")
            bad = True
        print(json.dumps(row), flush=True)
    return 1 if bad else 0


if __name__ == "__main__":
    raise SystemExit(main())
