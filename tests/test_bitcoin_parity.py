"""bitcoin gossip parity: batched engine vs CPU oracle (BASELINE rung 5).

A ring-with-chords P2P graph; transactions created at staggered times flood
via inv/getdata/tx over persistent TCP conns. Parity must be exact: same
seen matrices, same first-seen times, same packet counters.
"""

import numpy as np

from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import MS, SEC, EngineParams
from tests.parity import assert_parity, run_both

BTC_KEYS = ("seen", "seen_time", "tx_rx", "reach")


def ring_chord_peers(n: int) -> np.ndarray:
    """Symmetric 4-regular graph: ring ±1 plus chords ±4."""
    peers = np.zeros((n, 4), np.int64)
    for h in range(n):
        peers[h] = [(h - 1) % n, (h + 1) % n, (h - 4) % n, (h + 4) % n]
    return peers


def btc_exp(n_hosts=16, seed=13, loss=0.0, n_tx=6, end=5 * SEC, bw=10**7):
    rs = np.random.RandomState(seed)  # config-gen only, not sim randomness
    return single_vertex_experiment(
        n_hosts=n_hosts,
        seed=seed,
        end_time=end,
        latency_ns=10 * MS,
        loss=loss,
        bw_bits=bw,
        model="net",
        model_cfg={
            "app": "bitcoin",
            "peers": ring_chord_peers(n_hosts),
            "tx_origin": rs.randint(0, n_hosts, n_tx).astype(np.int64),
            "tx_time": (200 * MS + np.arange(n_tx) * 150 * MS).astype(np.int64),
            "tx_size": 400,
        },
    )


def test_bitcoin_flood_parity():
    exp = btc_exp()
    cm, cs, tm, ts = run_both(exp, EngineParams(ev_cap=256))
    # Every node learns every tx (connected graph, no loss).
    assert np.asarray(ts["reach"]).tolist() == [16] * 6
    assert_parity(cm, cs, tm, ts, keys=BTC_KEYS)


def test_bitcoin_flood_under_loss_parity():
    exp = btc_exp(seed=2, loss=0.02, end=8 * SEC)
    cm, cs, tm, ts = run_both(exp, EngineParams(ev_cap=256))
    # TCP recovers lost gossip; propagation completes despite loss.
    assert np.asarray(ts["reach"]).tolist() == [16] * 6
    assert tm["pkts_lost"] > 0
    assert_parity(cm, cs, tm, ts, keys=BTC_KEYS)
