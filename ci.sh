#!/usr/bin/env bash
# CI driver — the `./setup test` analogue (reference: setup + cmake + ctest).
#
#   ./ci.sh            fast tier: full suite minus the slow mid-scale tier
#   ./ci.sh all        everything, including 512–1024-host parity
#   ./ci.sh smoke      config + events + ckpt/obs/telemetry + tune fast paths
#                      (tgen-based tune tests stay in the fast/all tiers)
#
# Tests force the CPU platform with 8 virtual devices (tests/conftest.py),
# so CI needs no accelerator; the TPU-hardware path is covered separately
# by tests/test_backend_parity.py, which skips cleanly when absent.
set -euo pipefail
cd "$(dirname "$0")"

tier="${1:-fast}"
case "$tier" in
  smoke) exec python -m pytest tests/test_config.py tests/test_events.py tests/test_rng.py tests/test_ckpt_obs.py tests/test_telemetry.py tests/test_tune.py -q -m "not slow" -k "not tgen" ;;
  fast)  exec python -m pytest tests/ -q -m "not slow" ;;
  all)   exec python -m pytest tests/ -q ;;
  *) echo "usage: $0 [smoke|fast|all]" >&2; exit 2 ;;
esac
