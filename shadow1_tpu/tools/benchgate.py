"""Per-PR perf regression gate — the BENCH trajectory, enforced.

    python -m shadow1_tpu.tools.benchgate            # gate vs BENCH_GATE.json
    python -m shadow1_tpu.tools.benchgate --update   # re-baseline

The telemetry ring and phase profiler RECORD everything, but until now
nothing ENFORCED the perf trajectory (ROADMAP item 5): a PR could regress
the round path and tier-1 would stay green. This runs one smoke-sized
PHOLD row (bench.py's smoke shape: dense windows, chunked) and compares
**ms per inner round** — the per-round fixed cost that is the paper's
whole economics — against the committed ``BENCH_GATE.json`` baseline:

* measured > baseline × (1 + tolerance) → exit 1 (the gate fails CI);
* intentional trade-off? the one-line override:
  ``SHADOW1_BENCH_GATE_ACCEPT="why" ./ci.sh smoke`` turns the failure
  into a warning — then commit the new baseline with ``--update`` so the
  next PR gates against the accepted cost;
* a big improvement prints a reminder to re-baseline (non-fatal —
  ratchets tighten deliberately, not by timing luck).

Noise control: the gate times N_CHUNKS chunks after a full compile warmup
and gates on the MINIMUM chunk wall (per-round), which is stable on a
shared container where means are not. The tolerance (default 5%) rides in
the baseline file so a re-baseline can widen it deliberately.

Always prints exactly one JSON line on stdout (the bench.py contract).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

BASELINE = os.path.join(os.path.dirname(__file__), "..", "..",
                        "BENCH_GATE.json")

# bench.py's CPU-smoke shape — big enough that the round path dominates,
# small enough for seconds of CI wall.
N_HOSTS = 2048
CHUNK = 20
N_CHUNKS = 4
TOLERANCE = 0.05
ACCEPT_ENV = "SHADOW1_BENCH_GATE_ACCEPT"


def host_fingerprint() -> str:
    """CPU model + logical core count — a wall-clock baseline only gates
    meaningfully on the machine class it was measured on."""
    model = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("model name"):
                    model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    return f"{model} x{os.cpu_count()}"


def measure() -> dict:
    import jax

    from shadow1_tpu.config.compiled import single_vertex_experiment
    from shadow1_tpu.consts import MS, EngineParams
    from shadow1_tpu.core.engine import Engine

    exp = single_vertex_experiment(
        n_hosts=N_HOSTS, seed=1234, end_time=(N_CHUNKS + 1) * CHUNK * MS,
        latency_ns=1 * MS, model="phold",
        model_cfg={"mean_delay_ns": float(2 * MS), "init_events": 16},
    )
    eng = Engine(exp, EngineParams(ev_cap=48, outbox_cap=24,
                                   max_rounds=128))
    t0 = time.perf_counter()
    st = eng.init_state()
    jax.block_until_ready(eng.run(st, n_windows=CHUNK))
    compile_wall = time.perf_counter() - t0
    walls, rounds = [], []
    for _ in range(N_CHUNKS):
        r0 = int(st.metrics.rounds)
        t0 = time.perf_counter()
        st = eng.run(st, n_windows=CHUNK)
        jax.block_until_ready(st)
        walls.append(time.perf_counter() - t0)
        rounds.append(int(st.metrics.rounds) - r0)
    # Gate on the minimum PER-ROUND cost, not the minimum-wall chunk: a
    # chunk can post the smallest wall simply by running fewer rounds.
    best = min(range(N_CHUNKS),
               key=lambda i: walls[i] / max(rounds[i], 1))
    return {
        "metric": "phold_smoke_ms_per_round",
        "ms_per_round": round(walls[best] * 1000 / max(rounds[best], 1), 4),
        "hosts": N_HOSTS,
        "chunk_windows": CHUNK,
        "chunks_timed": N_CHUNKS,
        "rounds_per_chunk": rounds[best],
        "events": int(st.metrics.events),
        "compile_wall_s": round(compile_wall, 3),
        "chunk_walls_s": [round(w, 4) for w in walls],
        "backend": jax.default_backend(),
        "host": host_fingerprint(),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="shadow1_tpu.tools.benchgate")
    ap.add_argument("--update", action="store_true",
                    help="write the measured row as the new committed "
                         "baseline (BENCH_GATE.json)")
    ap.add_argument("--baseline", default=BASELINE,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    import shadow1_tpu  # noqa: F401  (x64 before jax arrays)
    from shadow1_tpu.platform import ensure_live_platform

    ensure_live_platform(min_devices=1)
    row = measure()
    if args.update:
        base = {**row, "tolerance": TOLERANCE,
                "note": "benchgate baseline — gate fails CI when measured "
                        "ms_per_round exceeds this by > tolerance; "
                        "override once with SHADOW1_BENCH_GATE_ACCEPT, "
                        "then re-baseline with --update"}
        with open(args.baseline, "w") as f:
            json.dump(base, f, indent=1)
            f.write("\n")
        print(json.dumps({**row, "gate": "updated",
                          "baseline": args.baseline}))
        return 0
    try:
        with open(args.baseline) as f:
            base = json.load(f)
    except OSError:
        print(json.dumps({**row, "gate": "no_baseline",
                          "hint": "commit one with --update"}))
        return 0
    tol = float(base.get("tolerance", TOLERANCE))
    ref = float(base["ms_per_round"])
    ratio = row["ms_per_round"] / ref if ref else 1.0
    verdict = {**row, "baseline_ms_per_round": ref,
               "ratio": round(ratio, 4), "tolerance": tol}
    if base.get("backend") != row["backend"]:
        # A baseline timed on another backend gates nothing meaningful.
        print(json.dumps({**verdict, "gate": "skipped_backend_mismatch"}))
        return 0
    if base.get("host") and base["host"] != row["host"]:
        # Same rule for the machine class: a wall-clock baseline from
        # another CPU would fail every PR on a slower box (or wave real
        # regressions through on a faster one) with no code change at
        # all. Re-baseline per machine with --update.
        print(f"[benchgate] baseline host {base['host']!r} != this host "
              f"{row['host']!r} — gate skipped; re-baseline here with "
              f"--update", file=sys.stderr, flush=True)
        print(json.dumps({**verdict, "gate": "skipped_host_mismatch"}))
        return 0
    if ratio > 1 + tol:
        accept = os.environ.get(ACCEPT_ENV)
        if accept:
            print(f"[benchgate] REGRESSION ACCEPTED ({accept}): "
                  f"{row['ms_per_round']} vs baseline {ref} ms/round "
                  f"(+{(ratio - 1) * 100:.1f}%) — commit the new baseline: "
                  f"python -m shadow1_tpu.tools.benchgate --update",
                  file=sys.stderr, flush=True)
            print(json.dumps({**verdict, "gate": "accepted",
                              "reason": accept}))
            return 0
        print(f"[benchgate] PERF REGRESSION: {row['ms_per_round']} vs "
              f"baseline {ref} ms/round (+{(ratio - 1) * 100:.1f}% > "
              f"{tol * 100:.0f}% tolerance). If intentional, override "
              f"once: {ACCEPT_ENV}='why' — then re-baseline with "
              f"--update.", file=sys.stderr, flush=True)
        print(json.dumps({**verdict, "gate": "failed"}))
        return 1
    if ratio < 1 - 2 * tol:
        print(f"[benchgate] improvement: {row['ms_per_round']} vs "
              f"baseline {ref} ms/round ({(1 - ratio) * 100:.1f}% faster) "
              f"— consider tightening the ratchet with --update",
              file=sys.stderr, flush=True)
    print(json.dumps({**verdict, "gate": "ok"}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
