"""Hot compiled-engine cache — the daemon's reason to stay alive.

Engine trace + compile dominates short-job wall time (BENCH_r06: 3.3 s
compile once vs 25.3 s paid per-seed across 16 sequential solos). A
long-lived daemon amortizes it by keeping compiled ``FleetEngine``
programs hot, keyed by everything that affects the TRACE:

* the **shape class** of the experiment — every ``CompiledExperiment``
  field outside the fleet-variable set (host count, topology latency /
  jitter / bandwidth tables, model + model_cfg, fidelity knobs, horizon):
  exactly the fields ``fleet.expand.check_uniform`` pins, because they
  either pick tensor shapes or are closed over as device constants;
* the **EngineParams** (caps, ring width, policies, kernel impls) — a
  frozen dataclass, hashable as-is;
* the **lane count** E (state shapes carry the leading [E] axis);
* the **backend** (compiled executables are device-specific).

A hit REBINDS the new batch's per-job variants (seed keys, loss
thresholds, fault tables) into the cached engine — ``FleetEngine.rebind``
— and runs through the already-compiled executable: the jitted ``run``
takes the variant pytree as a traced ARGUMENT, so same shapes ⇒ zero new
traces (``tests/test_serve.py`` asserts ``_run_jit._cache_size()`` stays
flat across a hit). A rebind the trace structure can't absorb (fault
table shapes / has-flags changed) falls back to a fresh build and counts
as a miss: the contract is "a hit never recompiles", not "equal keys
never miss".

Capacity is a small LRU (compiled fleet programs hold device constants;
an unbounded cache would leak HBM across tenants). Hit/miss/evict
counters feed the daemon's Prometheus ledger (SERVE_SPECS).
"""

from __future__ import annotations

import dataclasses
import hashlib
from collections import OrderedDict

import numpy as np

from shadow1_tpu.fleet.expand import _VARIABLE_EXP, FleetConfigError


def _fold(h, x) -> None:
    """Feed one config value into the fingerprint hash."""
    if isinstance(x, np.ndarray):
        h.update(f"nd{x.shape}{x.dtype}".encode())
        h.update(np.ascontiguousarray(x).tobytes())
    elif isinstance(x, dict):
        for k in sorted(x):
            h.update(str(k).encode())
            _fold(h, x[k])
    elif isinstance(x, (list, tuple)):
        for v in x:
            _fold(h, v)
    else:
        h.update(repr(x).encode())


def shape_class_key(exp, params, n_exp: int, backend: str = "cpu") -> tuple:
    """The engine-cache key for a batch of ``n_exp`` lanes of ``exp``'s
    shape class under ``params``. Two batches with equal keys differ at
    most in the fleet-variable knobs (seed / loss / faults / stop_time /
    per-lane max_rounds), which ride the variant pytree — never the
    compiled program."""
    h = hashlib.sha256()
    for f in dataclasses.fields(type(exp)):
        if f.name in _VARIABLE_EXP:
            continue
        h.update(f.name.encode())
        _fold(h, getattr(exp, f.name))
    return (h.hexdigest(), params, int(n_exp), backend)


class EngineCache:
    """LRU cache of compiled FleetEngines with hit/miss/evict counters."""

    def __init__(self, capacity: int = 4):
        assert capacity >= 1, capacity
        self.capacity = capacity
        self._entries: OrderedDict[tuple, object] = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def counters(self) -> dict[str, int]:
        return {"cache_hits": self.hits, "cache_misses": self.misses,
                "cache_evictions": self.evictions,
                "cache_entries": len(self._entries)}

    def get(self, exps: list, params, max_rounds=None,
            backend: str = "cpu"):
        """(engine, "hit"|"miss") for a batch of experiments.

        On a hit the cached engine is rebound to the new experiment set
        (no re-jit); a rebind refused by the trace structure rebuilds and
        REPLACES the entry (counted as a miss — the old program could not
        serve this batch, so keeping it would just pin dead HBM)."""
        from shadow1_tpu.fleet.engine import FleetEngine

        key = shape_class_key(exps[0], params, len(exps), backend)
        eng = self._entries.get(key)
        if eng is not None:
            try:
                eng.rebind(exps, max_rounds)
            except FleetConfigError:
                del self._entries[key]
            else:
                self._entries.move_to_end(key)
                self.hits += 1
                return eng, "hit"
        eng = FleetEngine(exps, params, max_rounds)
        self._entries[key] = eng
        self._entries.move_to_end(key)
        self.misses += 1
        while len(self._entries) > self.capacity:
            self._entries.popitem(last=False)
            self.evictions += 1
        return eng, "miss"
