"""pcap dump CLI: run an experiment on the fidelity (oracle) engine and
capture every delivered packet to a .pcap file.

    python -m shadow1_tpu.tools.pcapdump config.yaml out.pcap [--windows N]

The capture engine is the sequential oracle (it sees every packet at
routing time); for large configs bound the run with --windows.
"""

from __future__ import annotations

import argparse


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="shadow1_tpu.tools.pcapdump")
    ap.add_argument("config")
    ap.add_argument("out")
    ap.add_argument("--windows", type=int, default=None)
    ap.add_argument("--snaplen", type=int, default=128)
    args = ap.parse_args(argv)

    import shadow1_tpu  # noqa: F401
    from shadow1_tpu.platform import force_cpu

    force_cpu(1)  # the oracle needs no accelerator
    from shadow1_tpu.config.experiment import load_experiment
    from shadow1_tpu.cpu_engine import CpuEngine
    from shadow1_tpu.tools.pcap import PcapWriter

    exp, params, _ = load_experiment(args.config)
    with PcapWriter(args.out, snaplen=args.snaplen) as w:
        eng = CpuEngine(exp, params, capture=w)
        m = eng.run(n_windows=args.windows)
        print(f"{w.n_packets} packets captured to {args.out}; metrics: {m}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
