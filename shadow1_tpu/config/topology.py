"""GraphML topology loader + vertex-level path compilation.

The reference loads a GraphML network graph with igraph and answers
latency/reliability queries with lazily-cached Dijkstra runs
(src/main/routing/topology.c getLatency/getReliability). Published
Shadow/Tor topology files therefore work here unchanged. We instead compile
the whole graph ONCE on the host into dense vertex-level tensors
(SURVEY §7.1: exploit the vertex/host split — topologies have few network
vertices with many attached hosts):

* ``lat_vv``  — all-pairs latency (ns) along minimum-latency paths;
* ``loss_vv`` — end-to-end loss probability along those same paths
  (1 - Π(1-loss_e), the reference's per-edge reliability product).

Edge attributes honored (reference GraphML schema): ``latency`` (float,
*milliseconds* — Shadow convention) or ``latency_ns``; ``packetloss``
(probability). Vertices are the points of presence hosts attach to.
"""

from __future__ import annotations

import numpy as np

from shadow1_tpu.consts import MS


def load_graphml(path: str):
    """Returns (vertex_ids, lat_e, loss_e, directed): node id list (stable
    order) and dense [V, V] edge matrices (np.inf / 0 where no edge).

    Directed GraphML (Shadow's published Tor topologies use
    edgedefault="directed" with possibly asymmetric latencies) keeps each
    direction separate; undirected input is symmetrized."""
    import networkx as nx

    g = nx.read_graphml(path)
    directed = g.is_directed()
    nodes = list(g.nodes())
    index = {n: i for i, n in enumerate(nodes)}
    v = len(nodes)
    lat = np.full((v, v), np.inf)
    loss = np.zeros((v, v))
    for a, b, data in g.edges(data=True):
        if "latency_ns" in data:
            l_ns = float(data["latency_ns"])
        elif "latency" in data:
            l_ns = float(data["latency"]) * MS  # Shadow: milliseconds
        else:
            raise ValueError(f"edge {a}-{b} missing latency attribute")
        p = float(data.get("packetloss", data.get("loss", 0.0)))
        i, j = index[a], index[b]
        # Self-loop edges (Shadow convention) give the intra-PoP latency of
        # hosts attached to the same vertex.
        lat[i, j] = l_ns
        loss[i, j] = p
        if not directed:
            lat[j, i] = l_ns
            loss[j, i] = p
    return nodes, lat, loss, directed


def compile_paths(lat_e: np.ndarray, loss_e: np.ndarray,
                  self_latency_ns: int | None = None,
                  directed: bool = False) -> tuple[np.ndarray, np.ndarray]:
    """All-pairs min-latency paths → (lat_vv i64 ns, loss_vv f32).

    Loss accumulates along each chosen latency-shortest path via the
    predecessor matrix (vectorized back-walk, ≤V steps). The diagonal
    (same-vertex host pairs) uses the vertex's GraphML self-loop latency
    where present, else ``self_latency_ns``, else the minimum edge latency —
    it must stay positive, since the conservative window is min(lat_vv)
    (the reference computes runahead the same way, src/main/core/master.c).
    """
    from scipy.sparse.csgraph import dijkstra

    v = lat_e.shape[0]
    self_lat = np.diag(lat_e).copy()          # self-loops (inf = absent)
    lat_e = lat_e.copy()
    np.fill_diagonal(lat_e, np.inf)
    finite = lat_e[np.isfinite(lat_e)]
    min_edge = float(finite.min()) if finite.size else float(self_latency_ns or 0)
    default_self = self_latency_ns if self_latency_ns is not None else min_edge
    self_lat = np.where(np.isfinite(self_lat), self_lat, default_self)
    assert (self_lat > 0).all(), "intra-vertex latency must be positive"
    graph = np.where(np.isinf(lat_e), 0.0, lat_e)
    dist, pred = dijkstra(
        graph, directed=directed, return_predecessors=True, unweighted=False
    )
    if np.isinf(dist).any():
        bad = int(np.isinf(dist).sum())
        raise ValueError(f"topology is disconnected ({bad} unreachable pairs)")
    # Walk predecessors for all (src, dst) pairs at once, multiplying edge
    # reliability (1 - loss) per hop.
    rel = np.ones((v, v))
    rel_e = 1.0 - loss_e
    src = np.broadcast_to(np.arange(v)[:, None], (v, v)).copy()
    cur = np.broadcast_to(np.arange(v)[None, :], (v, v)).copy()
    for _ in range(v):
        prev = pred[src, cur]
        active = prev >= 0
        if not active.any():
            break
        p_safe = np.where(active, prev, 0)
        rel *= np.where(active, rel_e[p_safe, cur], 1.0)
        cur = np.where(active, p_safe, cur)
    lat_vv = np.rint(dist).astype(np.int64)
    np.fill_diagonal(lat_vv, np.rint(self_lat).astype(np.int64))
    loss_vv = (1.0 - rel).astype(np.float32)
    np.fill_diagonal(loss_vv, 0.0)
    assert (lat_vv > 0).all(), "zero-latency path would break the window"
    return lat_vv, loss_vv
