"""Shared end-of-run parity harness for the oracle-vs-batched test files.

Every parity test used to copy-paste the same block: run both engines,
assert the overflow guards, compare the semantic counters, compare summary
vectors. This module is the single implementation — and on a mismatch it
prints the ``tools/paritytrace.py`` invocation that would localize the
divergence to an exact (window, subsystem) instead of leaving a bare
end-of-run key mismatch (the determinism flight recorder,
docs/SEMANTICS.md §"State digest").

Not a test file itself (no ``test_`` prefix): pytest collects nothing here.
"""

from __future__ import annotations

import numpy as np

from shadow1_tpu.consts import EngineParams

# Counters that must be bit-identical between the CPU oracle and the
# batched engines (per-kind pops included: they guard the rx fast-path
# split staying symmetric between engines).
PARITY_KEYS = [
    "events", "pkts_sent", "pkts_delivered", "pkts_lost",
    "ev_overflow", "ob_overflow", "tcp_fast_rtx", "tcp_rto", "tcp_ooo_drops",
    "pops_pkt", "pops_deliver", "pops_timer", "pops_txr", "pops_app",
]

_HINT = (
    "\nlocalize the first divergent (window, subsystem) with the "
    "determinism flight recorder:\n"
    "    python -m shadow1_tpu.tools.paritytrace <experiment.yaml> "
    "{a} {b}\n"
    "(write the in-test experiment as a YAML config, or call "
    "shadow1_tpu.tools.paritytrace.make_side/bisect directly on the "
    "CompiledExperiment)"
)


def run_both(exp, params: EngineParams | None = None):
    """Run ``exp`` on the CPU oracle and the single-device batched engine.

    Returns (cpu_metrics, cpu_summary, tpu_metrics, tpu_summary)."""
    from shadow1_tpu.core.engine import Engine
    from shadow1_tpu.cpu_engine import CpuEngine

    params = params or EngineParams()
    cpu = CpuEngine(exp, params)
    cm = cpu.run()
    cs = cpu.summary()
    eng = Engine(exp, params)
    st = eng.run()
    tm = Engine.metrics_dict(st)
    ts = eng.model_summary(st)
    return cm, cs, tm, ts


def assert_parity(cm, cs, tm, ts, keys=("rx_bytes", "flows_done", "done_time"),
                  metric_keys=PARITY_KEYS, sides=("tpu", "cpu")):
    """The canonical oracle-vs-batched parity gate.

    ``cm``/``tm`` are metric dicts, ``cs``/``ts`` model summaries; ``keys``
    are the summary vectors to compare elementwise. The overflow guards run
    first: parity is only defined for overflow-free runs (which packets
    drop on overflow is layout-defined — docs/SEMANTICS.md)."""
    hint = _HINT.format(a=sides[0], b=sides[1])
    assert tm["ev_overflow"] == 0 and tm["ob_overflow"] == 0, (
        f"overflow run: parity undefined (ev={tm['ev_overflow']}, "
        f"ob={tm['ob_overflow']}) — raise the caps" + hint
    )
    assert tm["round_cap_hits"] == 0, (
        "round cap hit: windows truncated — raise max_rounds" + hint
    )
    for k in metric_keys:
        assert tm[k] == cm[k], (
            f"counter {k!r} diverged: {sides[0]}={tm[k]} {sides[1]}={cm[k]}"
            + hint
        )
    for k in keys:
        np.testing.assert_array_equal(
            np.asarray(ts[k]), np.asarray(cs[k]),
            err_msg=f"summary {k!r} diverged" + hint,
        )
