"""Sweep expansion — the ``sweep:`` YAML section → per-experiment tables.

Fleet mode (shadow1_tpu/fleet/) answers a whole parameter sweep as ONE
device program: E experiment variants ride a leading experiment axis
through one jitted window loop (``fleet/engine.py``). This module is the
jax-free config half: it expands a base experiment document plus a
``sweep:`` section into E concrete :class:`CompiledExperiment` artifacts
and validates the *fleet contract* (docs/SEMANTICS.md §"Fleet contract"):

* **may vary per experiment** — ``general.seed`` (per-experiment RNG
  streams), path loss probabilities (``network.single_vertex.loss`` /
  anything that changes only ``loss_vv``), the whole ``faults:`` section
  (host churn / link outages / loss ramps — tables pad to a common shape
  with inert entries), the legacy per-group ``stop_time`` churn, and
  ``engine.max_rounds`` (a traced scalar in the round loop);
* **must be shape-uniform** — host count, topology latencies (the
  conservative window derives from them), ``stop_time`` horizon
  (``general.stop_time``), every capacity knob and every other
  ``engine:`` field: these pick tensor shapes or trace-time structure, so
  a variant that changes them cannot ride the same compiled program.
  Violations raise :class:`FleetConfigError` with ``kind="shape"`` and a
  message naming the knob.

Schema::

    sweep:
      count: 16            # E experiments; seeds default base_seed + i
      base_seed: 1         # default: the base doc's general.seed
      seeds: [1, 2, 3]     # explicit per-experiment general.seed list
      vary:                # per-experiment override documents, deep-merged
        - {}               #   onto the base doc (general/network/faults/
        - {network: {single_vertex: {loss: 0.02}}}   # engine.max_rounds)
        - {faults: {hosts: [{group: h, down_at: 1 s, up_at: 2 s}]}}

``count``/``seeds``/``vary`` may appear together; every one present must
agree on E. Unknown ``sweep:`` keys are rejected like every other config
section (the PR 5 ``_reject_unknown`` pattern); typos *inside* a ``vary``
entry fail in ``build_experiment``'s own section validation, since each
merged document is compiled through the one standard path.

Deliberately jax-free: tools (fleetprobe, captune) and tests expand sweeps
without paying an accelerator import.
"""

from __future__ import annotations

import copy
import dataclasses

import numpy as np

from shadow1_tpu.config.experiment import _reject_unknown, build_experiment
from shadow1_tpu.consts import EngineParams


class FleetConfigError(ValueError):
    """A sweep/fleet configuration the batched-experiment plane cannot run.

    ``kind`` classifies the rejection:

    * ``"schema"`` — malformed ``sweep:`` section (unknown keys, length
      mismatches);
    * ``"shape"``  — a swept knob would change plane shapes or trace-time
      structure mid-fleet (differing host counts, latencies, caps, ...);
    * ``"uniform"`` — a swept knob is not in the fleet-variable set but
      differs between experiments;
    * ``"mode"``   — a runtime mode the fleet plane rejects by contract
      (sharded/cpu engines, --auto-caps, --on-overflow retry).

    ``knob`` names the offending field when one is identifiable.
    """

    def __init__(self, msg: str, kind: str = "schema", knob: str | None = None):
        super().__init__(msg)
        self.kind = kind
        self.knob = knob


_SWEEP_KEYS = ("count", "base_seed", "seeds", "vary")


def _deep_merge(base, over):
    """Recursive dict merge: ``over`` wins; non-dict values replace."""
    if isinstance(base, dict) and isinstance(over, dict):
        out = dict(base)
        for k, v in over.items():
            out[k] = _deep_merge(base.get(k), v) if k in base else v
        return out
    return over


def expand_sweep_docs(doc: dict) -> list[dict]:
    """Base document (with a ``sweep:`` section) → E per-experiment docs.

    Pure dict surgery — each returned doc is a standalone experiment file
    (no ``sweep:`` key) that compiles through ``build_experiment``
    unchanged; experiment i is the base with ``seeds[i]`` and ``vary[i]``
    applied. Raises FleetConfigError on schema problems."""
    sweep = doc.get("sweep")
    if not isinstance(sweep, dict):
        raise FleetConfigError(
            "fleet mode needs a `sweep:` section in the config "
            "(see docs/SEMANTICS.md §'Fleet contract')")
    try:
        _reject_unknown("sweep:", sweep, _SWEEP_KEYS)
    except AssertionError as e:
        raise FleetConfigError(str(e)) from None
    seeds = sweep.get("seeds")
    vary = sweep.get("vary")
    count = sweep.get("count")
    # Type hardening BEFORE any len()/int(): a malformed sweep must fail
    # as a structured FleetConfigError (the CLI's fleet_config record),
    # never a raw TypeError traceback.
    if seeds is not None and not isinstance(seeds, (list, tuple)):
        raise FleetConfigError(
            f"sweep.seeds must be a list, got {type(seeds).__name__}")
    if vary is not None and not isinstance(vary, (list, tuple)):
        raise FleetConfigError(
            f"sweep.vary must be a list of override mappings, got "
            f"{type(vary).__name__}")
    sizes = {}
    if seeds is not None:
        sizes["seeds"] = len(seeds)
    if vary is not None:
        sizes["vary"] = len(vary)
    if count is not None:
        try:
            sizes["count"] = int(count)
        except (TypeError, ValueError):
            raise FleetConfigError(
                f"sweep.count must be an integer, got {count!r}") from None
    if not sizes:
        raise FleetConfigError(
            "sweep: needs at least one of count / seeds / vary")
    if len(set(sizes.values())) > 1:
        raise FleetConfigError(
            f"sweep: count/seeds/vary disagree on the experiment count: "
            f"{sizes}")
    n = next(iter(sizes.values()))
    if n < 1:
        raise FleetConfigError(f"sweep: needs >= 1 experiment, got {n}")
    base = {k: v for k, v in doc.items() if k != "sweep"}
    if seeds is None:
        base_seed = int(sweep.get(
            "base_seed", base.get("general", {}).get("seed", 1)))
        seeds = [base_seed + i for i in range(n)]
    docs = []
    for i in range(n):
        d = copy.deepcopy(base)
        over = vary[i] if vary is not None else None
        if over is not None and not isinstance(over, dict):
            raise FleetConfigError(
                f"sweep.vary[{i}] must be a mapping, got "
                f"{type(over).__name__}")
        over = over or {}  # a YAML `- ~` / bare `-` entry means "no override"
        if over:
            d = _deep_merge(d, copy.deepcopy(over))
        gen = dict(d.get("general", {}))
        # Explicit vary[i].general.seed wins over the seeds list.
        if "seed" not in (over.get("general") or {}):
            try:
                gen["seed"] = int(seeds[i])
            except (TypeError, ValueError):
                raise FleetConfigError(
                    f"sweep.seeds[{i}] must be an integer, got "
                    f"{seeds[i]!r}") from None
        d["general"] = gen
        docs.append(d)
    return docs


@dataclasses.dataclass
class FleetPlan:
    """E compiled experiments sharing one shape class, ready for the
    batched engine. ``params`` is the fleet-uniform EngineParams (with
    experiment 0's max_rounds); ``max_rounds`` is the per-experiment list
    (the one engine knob the fleet contract lets vary)."""

    exps: list                 # list[CompiledExperiment], len E
    params: EngineParams
    max_rounds: list[int]
    scheduler: str
    labels: list[dict]         # per-experiment identity for records

    @property
    def n_exp(self) -> int:
        return len(self.exps)

    def subset(self, exp_ids) -> "FleetPlan":
        """The sub-fleet plan holding only the sweep-global experiment
        ids ``exp_ids``, in that order — what a resume builds when a
        lineage generation's ``lanes`` meta says the sweep had already
        quarantined/finalized lanes (cli._fleet_main; ids absent from
        the plan are ignored so a stale manifest cannot crash the
        resume)."""
        by_gid = {l["exp"]: i for i, l in enumerate(self.labels)}
        keep = [by_gid[g] for g in exp_ids if g in by_gid]
        return FleetPlan(
            exps=[self.exps[i] for i in keep],
            params=self.params,
            max_rounds=[self.max_rounds[i] for i in keep],
            scheduler=self.scheduler,
            labels=[self.labels[i] for i in keep],
        )


# EngineParams fields allowed to differ between fleet experiments. Every
# other field is shape-affecting or trace-structural (caps pick tensor
# shapes; impls/policies pick traced code paths) and must be uniform.
_VARIABLE_PARAMS = ("max_rounds",)

# CompiledExperiment fields allowed to differ (the fleet-variable set);
# everything else must compare equal. ``stop_time`` is legacy churn and
# compiles into the same per-experiment fault tables as ``faults``.
_VARIABLE_EXP = ("seed", "loss_vv", "faults", "stop_time", "dns")

# Fields whose divergence means a different SHAPE CLASS (the error must say
# so: these change tensor shapes or the conservative window, not just
# values).
_SHAPE_EXP = ("n_hosts", "lat_vv", "jitter_vv", "host_vertex", "end_time")


def _np_equal(a, b) -> bool:
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        return (np.asarray(a).shape == np.asarray(b).shape
                and np.array_equal(np.asarray(a), np.asarray(b)))
    if isinstance(a, dict) and isinstance(b, dict):
        return set(a) == set(b) and all(_np_equal(a[k], b[k]) for k in a)
    return a == b


def check_uniform(exps: list, params_list: list[EngineParams],
                  schedulers: list[str] | None = None
                  ) -> tuple[EngineParams, list[int]]:
    """Enforce the fleet contract across compiled experiments.

    Returns (uniform EngineParams, per-experiment max_rounds list); raises
    FleetConfigError naming the first offending knob."""
    base = exps[0]
    if schedulers and len(set(schedulers)) > 1:
        raise FleetConfigError(
            f"sweep varies engine.scheduler ({sorted(set(schedulers))}) — "
            f"the whole fleet runs one engine", kind="shape",
            knob="scheduler")
    for i, exp in enumerate(exps[1:], start=1):
        for f in _SHAPE_EXP:
            if not _np_equal(getattr(base, f), getattr(exp, f)):
                raise FleetConfigError(
                    f"sweep experiment {i} changes {f!r} — that changes "
                    f"plane shapes (or the conservative window) mid-fleet; "
                    f"fleet experiments must share one topology shape "
                    f"class (docs/SEMANTICS.md §'Fleet contract')",
                    kind="shape", knob=f)
        for f in (fld.name for fld in dataclasses.fields(type(base))):
            if f in _VARIABLE_EXP or f in _SHAPE_EXP:
                continue
            if not _np_equal(getattr(base, f), getattr(exp, f)):
                raise FleetConfigError(
                    f"sweep experiment {i} varies {f!r}, which is outside "
                    f"the fleet-variable set (seed / loss / faults / "
                    f"stop_time / engine.max_rounds)", kind="uniform",
                    knob=f)
    p0 = params_list[0]
    for i, p in enumerate(params_list[1:], start=1):
        for f in (fld.name for fld in dataclasses.fields(EngineParams)):
            if f in _VARIABLE_PARAMS:
                continue
            if getattr(p0, f) != getattr(p, f):
                raise FleetConfigError(
                    f"sweep experiment {i} changes engine.{f} — engine "
                    f"capacities and implementation knobs are shape- or "
                    f"trace-structural and must be fleet-uniform (only "
                    f"engine.max_rounds may vary)", kind="shape",
                    knob=f"engine.{f}")
    return p0, [int(p.max_rounds) for p in params_list]


def expand_sweep(doc: dict, base_dir: str = ".") -> FleetPlan:
    """Base document with ``sweep:`` → validated FleetPlan."""
    docs = expand_sweep_docs(doc)
    exps, params_list, scheds = [], [], []
    for d in docs:
        exp, params, scheduler = build_experiment(d, base_dir=base_dir)
        exps.append(exp)
        params_list.append(params)
        scheds.append(scheduler)
    params, max_rounds = check_uniform(exps, params_list, scheds)
    labels = [{"exp": i, "seed": int(e.seed)} for i, e in enumerate(exps)]
    return FleetPlan(exps=exps, params=params, max_rounds=max_rounds,
                     scheduler=scheds[0], labels=labels)


def load_sweep(path: str) -> FleetPlan:
    """Load a YAML experiment file with a ``sweep:`` section → FleetPlan."""
    import os

    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f)
    return expand_sweep(doc, base_dir=os.path.dirname(os.path.abspath(path)))
