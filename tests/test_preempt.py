"""Preemption-safe execution: signal drain, checkpoint lineage, watchdog.

The preemption contract (docs/SEMANTICS.md): a run is survivable at any
real-time instant — SIGTERM drains (commit, snapshot, EXIT_PREEMPTED),
a corrupt snapshot head falls back one lineage generation instead of
restarting the run, a wedged child is killed and classified within the
watchdog deadline — and every recovery path ends bit-identical to a run
nothing ever touched. tools/chaosprobe.py proves the same contract under
randomized kills; these tests pin the mechanisms deterministically.
"""

import io
import json
import os
import subprocess
import sys

import pytest

import jax
import numpy as np

from shadow1_tpu.ckpt import load_state, run_chunked
from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import (
    EXIT_CAPACITY,
    EXIT_CODES,
    EXIT_CONFIG,
    EXIT_HUNG,
    EXIT_OK,
    EXIT_PREEMPTED,
    MS,
    EngineParams,
)
from shadow1_tpu.core.engine import Engine
from shadow1_tpu.lineage import Lineage, write_json_atomic
from shadow1_tpu.preempt import DrainHandler, PreemptedExit

CFG_DIR = os.path.join(os.path.dirname(__file__), "..", "configs")
RUNG1 = os.path.join(CFG_DIR, "rung1_filexfer.yaml")


def phold_engine(n_hosts=16):
    return Engine(single_vertex_experiment(
        n_hosts=n_hosts, seed=11, end_time=200 * MS, latency_ns=1 * MS,
        model="phold", model_cfg={"mean_delay_ns": float(2 * MS),
                                  "init_events": 2}), EngineParams())


def state_equal(a, b) -> bool:
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


def _truncate(path):
    """The torn-write corruption shape: guaranteed to fail verification
    (a mid-file bit flip can land in zip padding and slip through)."""
    with open(path, "r+b") as f:
        f.truncate(max(os.path.getsize(path) // 2, 1))


# ---------------------------------------------------------------------------
# exit-code taxonomy (consts.py — the one table everything asserts against)
# ---------------------------------------------------------------------------

def test_exit_code_taxonomy():
    from shadow1_tpu.consts import (
        EXIT_DEADLINE,
        EXIT_MEMORY,
        EXIT_QUEUE_FULL,
        EXIT_SERVE_SHUTDOWN,
        EXIT_SERVE_SPOOL,
    )

    codes = (EXIT_OK, EXIT_CONFIG, EXIT_CAPACITY, EXIT_PREEMPTED,
             EXIT_HUNG, EXIT_MEMORY, EXIT_SERVE_SHUTDOWN,
             EXIT_SERVE_SPOOL, EXIT_QUEUE_FULL, EXIT_DEADLINE)
    assert len(set(codes)) == len(codes), "codes must be distinct"
    assert set(EXIT_CODES) == set(codes), "every code documented"
    # Codes must stay clear of shell/signal conventions: 1 is a generic
    # crash, 126-128 shell-reserved, >=128 signal deaths.
    assert all(0 <= c < 126 for c in codes)
    # txn re-exports the capacity code from the same table.
    from shadow1_tpu.txn import EXIT_CAPACITY as TXN_CAP

    assert TXN_CAP is EXIT_CAPACITY


def test_write_json_atomic(tmp_path):
    p = str(tmp_path / "x.json")
    write_json_atomic(p, {"a": 1})
    assert json.load(open(p)) == {"a": 1}
    assert not os.path.exists(p + ".tmp"), "no tmp residue"


# ---------------------------------------------------------------------------
# lineage: rotation, pruning, newest-valid resolution, fallback exactness
# ---------------------------------------------------------------------------

def test_lineage_rotation_prune_and_resolve(tmp_path):
    eng = phold_engine(8)
    path = str(tmp_path / "ck.npz")
    lin = Lineage(path, keep=3)
    st = eng.init_state()
    for i in range(5):
        st = eng.run(st, n_windows=10)
        seq = lin.save(st, {"win_start": int(st.win_start),
                            "done_windows": (i + 1) * 10})
        assert seq == i  # monotonic sequence numbers
    gens = lin.generations()
    assert [g["seq"] for g in gens] == [2, 3, 4], gens  # pruned to keep=3
    assert gens[-1]["file"] == path  # newest generation IS the bare path
    assert gens[-1]["win_start"] == int(st.win_start)
    assert {"ev_cap", "outbox_cap"} <= set(gens[-1]["caps"])
    res = lin.resolve()
    assert res.path == path and res.seq == 4 and not res.skipped


def test_lineage_fallback_costs_one_generation(tmp_path):
    """Corrupt the newest generation: resume must land on the previous one
    and the continued run must bit-match a straight run — the acceptance
    shape (one generation of progress lost, never the run)."""
    eng = phold_engine(8)
    path = str(tmp_path / "ck.npz")
    lin = Lineage(path, keep=3)
    st = eng.init_state()
    for i in range(5):
        st = eng.run(st, n_windows=10)
        lin.save(st, {"win_start": int(st.win_start),
                      "done_windows": (i + 1) * 10})
    _truncate(path)  # torn head (generation 4)
    res = lin.resolve()
    assert res.seq == 3 and res.path.endswith(".g000003")
    assert res.skipped and res.skipped[0]["file"] == path
    # Child mode discards the corrupt head so it can't rotate back in.
    res = lin.resolve(discard_invalid=True)
    assert not os.path.exists(path)
    st2 = load_state(eng.init_state(), res.path)
    assert int(st2.win_start) == 40 * eng.window  # one generation behind
    final = eng.run(st2, n_windows=10)
    assert state_equal(final, st)
    # Lineage continues monotonically past the repaired head.
    assert lin.save(final, {"win_start": int(final.win_start),
                            "done_windows": 60}) == 5
    assert lin.resolve().seq == 5


def test_lineage_keep1_crash_mid_write_keeps_a_snapshot(tmp_path):
    """Even at --ckpt-keep 1 a kill between head-rotation and install must
    leave a resumable generation on disk (the old head is rotated, never
    deleted, and pruned only AFTER the new head installs)."""
    script = (
        "import os, shadow1_tpu\n"
        "from shadow1_tpu.config.compiled import single_vertex_experiment\n"
        "from shadow1_tpu.consts import MS, EngineParams\n"
        "from shadow1_tpu.core.engine import Engine\n"
        "from shadow1_tpu.lineage import Lineage\n"
        "eng = Engine(single_vertex_experiment(n_hosts=8, seed=4,\n"
        "    end_time=100 * MS, latency_ns=1 * MS, model='phold',\n"
        "    model_cfg={'mean_delay_ns': float(2 * MS)}), EngineParams())\n"
        "lin = Lineage(os.environ['CK'], keep=1)\n"
        "st = eng.run(n_windows=5)\n"
        "lin.save(st, {'win_start': int(st.win_start), 'done_windows': 5})\n"
        "os.environ['SHADOW1_LINEAGE_CRASH_BETWEEN'] = os.environ['FLAG']\n"
        "lin.save(eng.run(st, n_windows=5), {'win_start': 0})\n"
    )
    ck = str(tmp_path / "ck.npz")
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "CK": ck,
           "FLAG": str(tmp_path / "between.flag")}
    r = subprocess.run([sys.executable, "-c", script], env=env,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 137, (r.returncode, r.stderr[-500:])
    res = Lineage(ck, keep=1).resolve()
    assert res is not None and res.path is not None, \
        "a kill mid-checkpoint-write left zero snapshots"
    assert res.seq == 0  # the rotated previous generation survived


def test_lineage_fallback_fleet(tmp_path):
    """Same fallback exactness fleet-shaped: the snapshot holds [E, ...]
    leaves, the head is torn, resume lands a generation back and the
    continued fleet bit-matches the straight fleet run."""
    from shadow1_tpu.fleet.engine import FleetEngine
    from shadow1_tpu.fleet.expand import expand_sweep

    doc = {
        "general": {"seed": 7, "stop_time": "150 ms"},
        "engine": {"scheduler": "tpu", "ev_cap": 32, "outbox_cap": 16},
        "network": {"single_vertex": {"latency": "10 ms"}},
        "hosts": [{"name": "h", "count": 8}],
        "app": {"model": "phold",
                "params": {"mean_delay_ns": 2.0e7, "init_events": 2}},
        "sweep": {"seeds": [7, 8]},
    }
    plan = expand_sweep(doc)
    eng = FleetEngine(plan.exps, plan.params, plan.max_rounds)
    path = str(tmp_path / "fleet.npz")
    lin = Lineage(path, keep=2)
    st = eng.init_state()
    for i in range(3):
        st = eng.run(st, n_windows=4)
        lin.save(st, {"win_start": int(np.asarray(st.win_start).max()),
                      "done_windows": (i + 1) * 4})
    _truncate(path)
    res = lin.resolve(discard_invalid=True)
    assert res.seq == 1
    st2 = load_state(eng.init_state(), res.path)
    final = eng.run(st2, n_windows=4)
    assert state_equal(final, st)


# ---------------------------------------------------------------------------
# drain semantics in the chunk runner (in-process, no signals)
# ---------------------------------------------------------------------------

def test_run_chunked_drain_commits_inflight_chunk():
    """A drain requested mid-run stops AFTER the in-flight chunk commits:
    the carried state equals a straight run of exactly the committed
    windows — the when-work-is-lost half of the preemption contract.
    The latch is sampled BEFORE on_chunk at each boundary, so a request
    landing inside on_chunk (as the injection hooks do) is honored one
    boundary later — never without the forced snapshot."""
    eng = phold_engine(8)
    drain = DrainHandler()  # not installed: no real signals in-process

    def on_chunk(st, done):
        if done == 20:
            drain.signame = "SIGTERM"  # latch mid-on_chunk

    with pytest.raises(PreemptedExit) as ei:
        run_chunked(eng, n_windows=50, chunk=10, on_chunk=on_chunk,
                    drain=drain)
    e = ei.value
    assert e.done_windows == 30 and e.signame == "SIGTERM"
    assert e.win_start == 30 * eng.window
    assert state_equal(e.st, eng.run(n_windows=30))


def test_drain_on_final_chunk_is_a_normal_exit():
    eng = phold_engine(8)
    drain = DrainHandler()

    def on_chunk(st, done):
        if done == 30:  # the last chunk: nothing left to preempt
            drain.signame = "SIGINT"

    st = run_chunked(eng, n_windows=30, chunk=10, on_chunk=on_chunk,
                     drain=drain)
    assert state_equal(st, eng.run(n_windows=30))


# ---------------------------------------------------------------------------
# CLI end-to-end: SIGTERM drain → EXIT_PREEMPTED → bit-identical resume
# ---------------------------------------------------------------------------

def test_cli_sigterm_drain_preempted_then_resume(tmp_path):
    """The acceptance run: SIGTERM mid-run exits EXIT_PREEMPTED after
    committing the in-flight chunk (parseable stdout record, supervisor
    classifies clean-resume and KEEPS the checkpoint), and rerunning the
    same command resumes to a final state bit-identical to an
    uninterrupted run."""
    from shadow1_tpu.config.experiment import load_experiment

    exp, _, _ = load_experiment(RUNG1)
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "SHADOW1_SUPERVISE_BACKOFF_S": "0"}
    ref_npz = str(tmp_path / "ref.npz")
    fin_npz = str(tmp_path / "fin.npz")
    ck = str(tmp_path / "ck.npz")
    base = [sys.executable, "-m", "shadow1_tpu", RUNG1, "--windows", "40",
            "--heartbeat", "10", "--ckpt-every-s", "0"]
    r = subprocess.run([*base, "--save-state", ref_npz], env=env,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == EXIT_OK, r.stderr[-800:]

    env2 = {**env, "SHADOW1_OBS_SIGTERM_SELF_AT_NS": str(20 * exp.window)}
    r = subprocess.run([*base, "--ckpt", ck], env=env2,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == EXIT_PREEMPTED, (r.returncode, r.stderr[-800:])
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["preempted"] is True and rec["signal"] == "SIGTERM"
    # The hook delivers SIGTERM inside the window-20 boundary's on_chunk,
    # so the drain is honored (with its forced snapshot) one chunk later.
    assert rec["win_start"] == 30 * exp.window
    assert "child drained" in r.stderr  # supervisor: clean-resume class
    assert "respawning" not in r.stderr  # no crash accounting, no backoff
    assert os.path.exists(ck), "checkpoint must be KEPT for the resume"

    # Rerun the SAME command: resumes (resume record names the generation)
    # and finishes; final state bit-matches the uninterrupted run.
    r = subprocess.run([*base, "--ckpt", ck, "--save-state", fin_npz],
                       env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == EXIT_OK, r.stderr[-800:]
    resumes = [json.loads(line) for line in r.stderr.splitlines()
               if line.startswith("{")
               and json.loads(line).get("type") == "resume"]
    assert resumes and resumes[0]["win_start"] == 30 * exp.window
    assert resumes[0]["fallback_skipped"] == 0
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["resumed"] is True
    with np.load(ref_npz) as a, np.load(fin_npz) as b:
        assert set(a.files) == set(b.files)
        for k in a.files:
            np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def test_cli_watchdog_kills_and_recovers_hung_child(tmp_path):
    """The stale-progress acceptance: a child whose sidecar stops ticking
    (the dead-tunnel shape) is killed within the watchdog deadline,
    classified 'hung' (not crashed), respawned, and the finished run
    bit-matches an uninterrupted one."""
    from shadow1_tpu.config.experiment import load_experiment

    exp, _, _ = load_experiment(RUNG1)
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "SHADOW1_SUPERVISE_BACKOFF_S": "0"}
    ref_npz = str(tmp_path / "ref.npz")
    fin_npz = str(tmp_path / "fin.npz")
    ck = str(tmp_path / "ck.npz")
    base = [sys.executable, "-m", "shadow1_tpu", RUNG1, "--windows", "40",
            "--heartbeat", "10", "--ckpt-every-s", "0"]
    r = subprocess.run([*base, "--save-state", ref_npz], env=env,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == EXIT_OK, r.stderr[-800:]

    env2 = {**env, "SHADOW1_OBS_HANG_AT_NS": str(20 * exp.window),
            "SHADOW1_OBS_HANG_ONCE_FLAG": str(tmp_path / "hung.flag")}
    r = subprocess.run([*base, "--ckpt", ck, "--watchdog-s", "5",
                        "--save-state", fin_npz],
                       env=env2, capture_output=True, text=True, timeout=600)
    assert r.returncode == EXIT_OK, (r.returncode, r.stderr[-1500:])
    assert "child hung" in r.stderr  # classified hung, not crashed
    assert "watchdog_kill" in r.stderr  # parseable lineage event
    assert "respawning" in r.stderr
    with np.load(ref_npz) as a, np.load(fin_npz) as b:
        for k in a.files:
            np.testing.assert_array_equal(a[k], b[k], err_msg=k)


@pytest.mark.slow  # ~25s: two watchdog deadlines back to back; the
# recoverable-hang sibling above keeps fast-tier watchdog coverage
def test_cli_watchdog_classifies_deterministic_hang(tmp_path):
    """Two consecutive watchdog kills with no forward progress abort with
    the dedicated EXIT_HUNG code and the no-kill probe playbook — not a
    burned respawn budget."""
    from shadow1_tpu.config.experiment import load_experiment

    exp, _, _ = load_experiment(RUNG1)
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "SHADOW1_SUPERVISE_BACKOFF_S": "0",
           "SHADOW1_OBS_HANG_AT_NS": str(10 * exp.window)}
    r = subprocess.run(
        [sys.executable, "-m", "shadow1_tpu", RUNG1, "--windows", "40",
         "--heartbeat", "10", "--ckpt-every-s", "0",
         "--ckpt", str(tmp_path / "ck.npz"), "--watchdog-s", "4"],
        env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == EXIT_HUNG, (r.returncode, r.stderr[-1500:])
    assert "watchdog kills" in r.stderr and "faultprobe" in r.stderr
    assert r.stderr.count("respawning") == 1  # classified after 2, not 8


# ---------------------------------------------------------------------------
# reporting: heartbeat_report lineage section
# ---------------------------------------------------------------------------

def test_heartbeat_report_lineage_section():
    from shadow1_tpu.tools.heartbeat_report import summarize

    recs = [
        {"type": "resume", "ckpt": "ck.npz", "generation": 4,
         "win_start": 800, "fallback_skipped": 1,
         "discarded": ["ck.npz"], "generations_kept": 3},
        {"type": "lineage", "event": "watchdog_kill", "stale_s": 5.0,
         "sim_ns": 400, "attempt": 1},
        {"type": "lineage", "event": "preempted", "rc": EXIT_PREEMPTED},
    ]
    buf = io.StringIO()
    summary = summarize(recs, out=buf)
    text = buf.getvalue()
    assert summary["lineage"] == {
        "resumes": 1, "fallback_skipped": 1, "watchdog_kills": 1,
        "preempted_drains": 1, "generations_kept": 3}
    assert "lineage (preemption/resume)" in text
    assert "resume: generation 4" in text
    assert "corrupt newer generation(s) skipped" in text
    assert "watchdog kill" in text
