"""Heartbeat/tracker log analysis — the tools/ plotting-scripts analogue.

The reference ships helper scripts that parse heartbeat logs into
throughput/RTT tables and plots (SURVEY §2.6 tools/). This reads the JSON
lines the CLI emits (--heartbeat → engine heartbeats on stderr; --tracker →
per-host records) and prints summary tables plus an optional CSV for
plotting.

    python -m shadow1_tpu.tools.heartbeat_report run.log [--csv out.csv]
"""

from __future__ import annotations

import argparse
import csv
import json
import sys


def load_records(path: str) -> list[dict]:
    recs = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                recs.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return recs


def summarize(recs: list[dict], out=sys.stdout) -> dict:
    hb = [r for r in recs if r.get("type") == "heartbeat"]
    tr = [r for r in recs if r.get("type") == "tracker"]
    summary: dict = {"heartbeats": len(hb), "tracker_records": len(tr)}
    if hb:
        eps = [r["events_per_sec"] for r in hb if r.get("events_per_sec")]
        spw = [r["sim_per_wall"] for r in hb if r.get("sim_per_wall")]
        summary.update(
            sim_time_s=hb[-1]["sim_time_s"],
            wall_s=hb[-1]["wall_s"],
            events=sum(r["delta"]["events"] for r in hb),
            events_per_sec_mean=round(sum(eps) / len(eps), 1) if eps else None,
            sim_per_wall_mean=round(sum(spw) / len(spw), 4) if spw else None,
            pkts_delivered=sum(r["delta"].get("pkts_delivered", 0) for r in hb),
            retransmits=sum(
                r["delta"].get("tcp_rto", 0) + r["delta"].get("tcp_fast_rtx", 0)
                for r in hb
            ),
        )
        print("== run summary ==", file=out)
        for k, v in summary.items():
            print(f"  {k}: {v}", file=out)
    if tr:
        last_per_host: dict[int, dict] = {}
        for r in tr:
            last_per_host[r["host"]] = r
        tx = sorted(
            last_per_host.values(), key=lambda r: -r.get("nic_tx_bytes", 0)
        )[:10]
        print("== top talkers (final tracker snapshot) ==", file=out)
        for r in tx:
            print(
                f"  host {r['host']}: tx {r.get('nic_tx_bytes', 0)} B, "
                f"rx {r.get('nic_rx_bytes', 0)} B, "
                f"pending {r.get('pending_events', 0)}",
                file=out,
            )
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="shadow1_tpu.tools.heartbeat_report")
    ap.add_argument("log")
    ap.add_argument("--csv", default=None,
                    help="write the heartbeat series as CSV for plotting")
    args = ap.parse_args(argv)
    recs = load_records(args.log)
    if not recs:
        print("no JSON records found", file=sys.stderr)
        return 1
    summarize(recs)
    if args.csv:
        hb = [r for r in recs if r.get("type") == "heartbeat"]
        with open(args.csv, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["sim_time_s", "wall_s", "events_per_sec",
                        "sim_per_wall", "events", "pkts_delivered"])
            for r in hb:
                w.writerow([
                    r["sim_time_s"], r["wall_s"], r.get("events_per_sec"),
                    r.get("sim_per_wall"), r["delta"]["events"],
                    r["delta"].get("pkts_delivered", 0),
                ])
        print(f"wrote {args.csv}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
