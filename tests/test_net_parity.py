"""Net-stack parity: NIC + TCP/UDP + apps, batched engine vs CPU oracle.

The 2-host file transfer is BASELINE ladder rung 1 (the reference's minimal
tgen example, resource/examples/). Parity must be exact: same packets, same
byte counts, same retransmit counters, same completion times.
"""

import numpy as np

from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import MS, SEC, EngineParams
from tests.parity import PARITY_KEYS, assert_parity, run_both  # noqa: F401
# (re-exported: older satellite tests imported the harness from here)


def filexfer_exp(n_hosts=2, seed=11, loss=0.0, flow=100_000, end=20 * SEC, bw=10**7):
    role = np.full(n_hosts, 1, np.int64)
    role[0] = 0
    server = np.zeros(n_hosts, np.int64)
    return single_vertex_experiment(
        n_hosts=n_hosts,
        seed=seed,
        end_time=end,
        latency_ns=10 * MS,
        loss=loss,
        bw_bits=bw,
        model="net",
        model_cfg={
            "app": "filexfer",
            "role": role,
            "server": server,
            "flow_bytes": np.full(n_hosts, flow, np.int64),
            "start_time": np.full(n_hosts, 1 * MS, np.int64),
            "flow_count": np.where(role == 1, 1, 0),
        },
    )


def test_two_host_transfer_completes_and_parity():
    exp = filexfer_exp()
    cm, cs, tm, ts = run_both(exp)
    assert int(ts["total_flows_done"]) == 1
    assert int(ts["total_rx_bytes"]) == 100_000
    assert_parity(cm, cs, tm, ts)


def test_transfer_under_loss_parity():
    exp = filexfer_exp(seed=5, loss=0.02, flow=150_000, end=60 * SEC)
    cm, cs, tm, ts = run_both(exp)
    assert int(ts["total_flows_done"]) == 1
    assert int(ts["total_rx_bytes"]) == 150_000
    assert tm["tcp_rto"] + tm["tcp_fast_rtx"] > 0  # loss actually exercised recovery
    assert_parity(cm, cs, tm, ts)


def test_multi_client_multi_flow_parity():
    exp = filexfer_exp(n_hosts=5, seed=3, flow=40_000, end=30 * SEC)
    exp.model_cfg["flow_count"] = np.where(np.arange(5) >= 1, 2, 0)
    # 4 concurrent senders into one server: size the event buffer for the
    # aggregate in-flight packet count (the provisioning knob, SEMANTICS.md).
    cm, cs, tm, ts = run_both(exp, EngineParams(ev_cap=256))
    assert int(ts["total_flows_done"]) == 8  # 4 clients x 2 flows
    assert int(ts["total_rx_bytes"]) == 8 * 40_000
    assert_parity(cm, cs, tm, ts)


def test_dgram_parity():
    n = 8
    exp = single_vertex_experiment(
        n_hosts=n,
        seed=9,
        end_time=3 * SEC,
        latency_ns=5 * MS,
        loss=0.1,
        model="net",
        model_cfg={
            "app": "dgram",
            "dst": (np.arange(n) + 1) % n,
            "payload": np.full(n, 500, np.int64),
            "interval": np.full(n, 20 * MS, np.int64),
            "count": np.full(n, 50, np.int64),
            "start_time": np.zeros(n, np.int64),
        },
    )
    cm, cs, tm, ts = run_both(exp)
    assert int(ts["total_rx"]) > 0
    assert tm["pkts_lost"] > 0
    assert_parity(cm, cs, tm, ts, keys=("rx_count", "rx_bytes"))
