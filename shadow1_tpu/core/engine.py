"""The TPU engine: conservative-window batched discrete-event execution.

This is the tensor re-expression of the reference's scheduler stack
(src/main/core/master.c runahead loop + src/main/core/scheduler/*.c barrier
rounds + src/main/core/worker.c event loop, SURVEY §3.1–3.2):

* outer loop  — one iteration per conservative window [T, T+W), W = min
  topology latency, exactly the reference's Master round loop;
* inner loop  — rounds: every host pops its minimum-(time, tb) event and the
  masked vectorized handlers run; a round is the SIMD analogue of "each
  worker runs the next event of one host"; hosts interact only through
  packets, which conservative lookahead guarantees land ≥ one window later;
* window end  — the buffered packet outboxes are routed (latency gather over
  the vertex matrix, Bernoulli loss draws) and scattered into destination
  event buffers: the one cross-host exchange per window.

The whole run is a single jitted program (fori over windows, while over
rounds); there is no host↔device traffic until metrics are fetched.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from shadow1_tpu import rng
from shadow1_tpu.config.compiled import CompiledExperiment
from shadow1_tpu.consts import (
    KIND_METRIC_FIELDS,
    KIND_NAMES,
    K_NONE,
    R_JITTER,
    R_LOSS,
    EngineParams,
    packet_tb,
)
from shadow1_tpu.core.events import (
    EventBuf,
    Popped,
    any_eligible,
    deliver_batch,
    evbuf_init,
    pop_until,
    push_back,
)
from shadow1_tpu.core.outbox import Outbox, outbox_clear, outbox_init


class Metrics(NamedTuple):
    events: jnp.ndarray          # events executed
    rounds: jnp.ndarray          # inner rounds run
    windows: jnp.ndarray         # windows completed
    pkts_sent: jnp.ndarray
    pkts_delivered: jnp.ndarray
    pkts_lost: jnp.ndarray       # dropped by path loss
    ev_overflow: jnp.ndarray     # events dropped: full event buffer
    ob_overflow: jnp.ndarray     # packets dropped: full outbox
    round_cap_hits: jnp.ndarray  # windows that hit the max_rounds safety cap
    tcp_fast_rtx: jnp.ndarray    # fast-retransmit (3 dup-ACK) episodes
    tcp_rto: jnp.ndarray         # retransmit-timeout episodes
    tcp_ooo_drops: jnp.ndarray   # out-of-order segments dropped (GBN receiver)
    x2x_overflow: jnp.ndarray    # packets dropped: all_to_all bucket full
                                 # (sharded engine only; parity needs 0)
    x2x_max_fill: jnp.ndarray    # high-water DEMANDED per-destination bucket
                                 # fill across the run (sharded exchange;
                                 # pmax-replicated, so excluded from the
                                 # cross-shard psum like ``windows``) — the
                                 # quantity that rationally pins x2x_cap
    # Capacity high-water gauges (shadow1_tpu/tune/): run-max window-end
    # fill of each bounded structure, maintained inside the jitted window
    # path at one ``max`` per already-computed fill count. These are what
    # the between-chunk cap controller (tune/autocap.py) and the offline
    # tuner (tools/captune.py) size caps from. Window-END samples are a
    # LOWER bound on the true mid-window peak (like tools/occprobe.py) —
    # the overflow counters stay the authoritative guard. Under sharding
    # they are max-globalized at chunk end (shard/engine.py), so they match
    # the single-device values bit-exactly; ``compact_max_fill`` is the one
    # exception (per-shard bucket demand, like ``rounds``).
    ev_max_fill: jnp.ndarray      # busiest host's event-slot fill (vs ev_cap)
    ob_max_fill: jnp.ndarray      # busiest host's per-window outbox fill
                                  # (vs outbox_cap)
    compact_max_fill: jnp.ndarray # busiest window's active-host count — the
                                  # demanded compaction-bucket lanes (vs
                                  # compact_cap), recorded compaction on OR
                                  # off so the knob can be sized before it
                                  # is enabled
    down_events: jnp.ndarray     # events discarded: host stopped (churn)
    down_pkts: jnp.ndarray       # packets dropped: destination host stopped
    nic_tx_drops: jnp.ndarray    # packets dropped: NIC uplink queue full
    nic_rx_drops: jnp.ndarray    # packets dropped: NIC downlink queue full
    nic_aqm_drops: jnp.ndarray   # packets dropped: RED early-drop (uplink)
    # Per-kind pop occupancy (performance observability: which handler
    # passes the rounds actually feed; parity-exact like events).
    pops_pkt: jnp.ndarray        # K_PKT (rx drop-tail path only)
    pops_deliver: jnp.ndarray    # K_PKT_DELIVER
    pops_timer: jnp.ndarray      # K_TCP_TIMER
    pops_txr: jnp.ndarray        # K_TX_RESUME
    pops_app: jnp.ndarray        # K_APP
    # Rounds in which each handler pass FIRED (its lax.cond took the real
    # branch) — the per-round cost drivers. Batch-engine-only counters,
    # excluded from parity like ``rounds``.
    fires_pkt: jnp.ndarray
    fires_deliver: jnp.ndarray
    fires_timer: jnp.ndarray
    fires_txr: jnp.ndarray
    fires_app: jnp.ndarray
    # Fault plane (shadow1_tpu/fault/): deterministic link outages and host
    # restarts (docs/SEMANTICS.md §"Fault plane").
    link_down_pkts: jnp.ndarray  # packets dropped: link outage window
    host_restarts: jnp.ndarray   # host restart resets applied (churn up)
    # Wasted-work accounting (performance attribution plane): per-window
    # boundary samples accumulated as running sums so the telemetry ring's
    # delta columns recover the per-window values. All three sample
    # engine-independent boundary quantities (the window-start pending set
    # and the per-window send set are identical on every engine — the state
    # digest's argument), so they are parity-exact cpu↔tpu↔sharded: each
    # shard counts its local host block and the psum is the global value.
    # active_hosts/n_hosts per window is exactly the ROADMAP's "rung-3/4
    # rounds touch ~0.1% of hosts but pay full [cap, H] plane passes"
    # pathology as a live signal.
    active_hosts: jnp.ndarray    # Σ_w hosts with ≥1 eligible event at start
    elig_events: jnp.ndarray     # Σ_w events eligible at window start
    outbox_hosts: jnp.ndarray    # Σ_w hosts with ≥1 outbox slot used


def _metrics_init() -> Metrics:
    z = jnp.zeros((), jnp.int64)
    return Metrics(*([z] * len(Metrics._fields)))


class SimState(NamedTuple):
    win_start: jnp.ndarray  # i64 scalar
    evbuf: EventBuf
    outbox: Outbox
    model: Any              # workload-model pytree
    metrics: Metrics
    cpu_busy: jnp.ndarray   # i64 [H] virtual CPU free-at (host/cpu.c model)
    # On-device telemetry ring (telemetry/ring.TelemetryRing) or None when
    # EngineParams.metrics_ring == 0 — None contributes no pytree leaves,
    # so a ring-less state keeps the historic leaf layout.
    telem: Any = None
    # Flow-probe ring (telemetry/probes.ProbeRing, [W, K, F]) or None when
    # EngineParams.probes is empty — same None-leaf rule as the telemetry
    # ring, so a probe-less state keeps the historic layout.
    probes: Any = None
    # Link-telemetry accumulator (telemetry/links.LinkAccum, [V, V, F]) or
    # None when EngineParams.link_telem == 0 — same None-leaf rule again;
    # never digested, so carrying it is digest-neutral by construction.
    links: Any = None


@dataclasses.dataclass(frozen=True)
class Ctx:
    """Trace-time context handed to model handler builders.

    Shard-awareness: the engine state lives on a (possibly sharded) host
    axis. ``n_hosts`` is the LOCAL block size (every [H, ...] tensor shape),
    ``hosts`` holds the GLOBAL host ids of this block (a contiguous range),
    and ``n_total`` is the global host count. Anything semantic — RNG
    streams keyed by host, packet src/dst fields, random destination draws —
    uses global ids; anything shape-like uses ``n_hosts``. On a single
    device the two views coincide (hosts == arange(n_hosts)).
    """

    n_hosts: int            # local host-axis block size
    n_total: int            # global host count
    params: EngineParams
    window: int
    key: jax.Array          # base PRNG key (device)
    lat_vv: jax.Array       # i64 [V, V]
    loss_vv: jax.Array      # f32 [V, V]
    host_vertex: jax.Array  # i32 [n_total] — indexed by GLOBAL host id
    bw_up: jax.Array        # i64 [H] local
    bw_dn: jax.Array        # i64 [H] local
    model_cfg: dict
    hosts: jax.Array = None  # i32 [H] global host ids of this block
    loss_thr_vv: jax.Array = None  # u64 [V, V] Bernoulli thresholds
    # Fidelity knobs (all local [H] / [V,V]; see CompiledExperiment). The
    # has_* flags are TRACE-TIME booleans so disabled features compile to
    # nothing.
    jitter_vv: jax.Array = None    # i64 [V, V]
    # Host churn intervals (fault/schedule.host_interval_tensors): host h is
    # DOWN at time t iff any k has fault_down[k,h] <= t < fault_up[k,h].
    # The legacy single stop_time compiles to one [stop, NO_STOP) interval.
    fault_down: jax.Array = None   # i64 [K, H]
    fault_up: jax.Array = None     # i64 [K, H] (window-quantized; NO_STOP)
    link_fault: Any = None         # (src, dst, t0, t1) [L] tables or None
    loss_ramp: Any = None          # (src, dst, t0, t1, thr) [R] or None
    init_model: Any = None         # post-init model pytree (restart target)
    cpu_cost: jax.Array = None     # i64 [H] virtual CPU ns per event
    tx_qlen_ns: jax.Array = None   # i64 [H] uplink queue bound (ns of backlog)
    rx_qlen_ns: jax.Array = None   # i64 [H]
    aqm_min_ns: jax.Array = None   # i64 [H] RED min threshold (backlog ns)
    aqm_span_ns: jax.Array = None  # i64 [H] RED max − min (≥1 where enabled)
    aqm_pmax_thr: jax.Array = None # u64 [H] Bernoulli threshold at pmax
    has_jitter: bool = False
    has_stop: bool = False         # any host down interval exists
    has_restart: bool = False      # any finite up time (restart resets)
    has_link_fault: bool = False
    has_loss_ramp: bool = False
    has_cpu: bool = False
    has_tx_qlen: bool = False
    has_rx_qlen: bool = False
    has_aqm: bool = False

    def __post_init__(self):
        if self.hosts is None:
            # Single-device default: the block IS the whole host range.
            object.__setattr__(self, "hosts", jnp.arange(self.n_hosts, dtype=jnp.int32))
        if self.loss_thr_vv is None:
            # Integer loss thresholds, computed host-side once (numpy) so no
            # float op survives in the per-window path (round-2 postmortem).
            object.__setattr__(
                self,
                "loss_thr_vv",
                jnp.asarray(rng.prob_threshold(np.asarray(self.loss_vv))),
            )


Handler = Callable[[SimState, Popped], SimState]


def push_local_event(st: SimState, ctx: Ctx, mask, time, kind,
                     p0=None, p1=None, p2=None, p3=None) -> SimState:
    """Push one local event per host where ``mask``, counting overflow.

    The engine-state-level convenience over events.push_local used by all
    handler layers (transport timers, app wakeups)."""
    from shadow1_tpu.core.dense import payload
    from shadow1_tpu.core.events import push_local

    p = payload(ctx.n_hosts, p0, p1, p2, p3)
    k = jnp.full(ctx.n_hosts, kind, jnp.int32)
    evbuf, over = push_local(st.evbuf, mask, time, k, p)
    m = st.metrics
    return st._replace(
        evbuf=evbuf,
        metrics=m._replace(ev_overflow=m.ev_overflow + over.sum(dtype=jnp.int64)),
    )


# --------------------------------------------------------------------------
# Window-step building blocks, shared by the single-device Engine and the
# sharded engine (shard/engine.py). All take the local-block view: state
# tensors sized [ctx.n_hosts, ...], global host ids in ctx.hosts.
# --------------------------------------------------------------------------

class FlatPackets(NamedTuple):
    """One window's routed packets, flattened to a single axis.

    ``dst`` is a GLOBAL host id; ``keep`` marks packets that survived the
    loss draw. Flat order is slot-major over the [P, H] outbox — an
    engine-internal layout detail: per-(src,dst) pair it equals send order,
    and event pop order is decided by the (time, tb) keys alone, so results
    are layout-independent. Under sharding the per-window all_to_all
    concatenates received buckets in source-shard order.
    """

    dst: jnp.ndarray      # i32 [N] global dst host
    arrival: jnp.ndarray  # i64 [N]
    tb: jnp.ndarray       # i64 [N]
    kind: jnp.ndarray     # i32 [N]
    p: jnp.ndarray        # i32 [NP, N]
    keep: jnp.ndarray     # bool [N]


def run_round(st: SimState, ctx: Ctx, handlers: dict, win_end) -> SimState:
    """One inner round: per-host pop-min + the handler passes.

    Two fidelity gates apply between pop and dispatch (both compile to
    nothing when the knobs are off):

    * **churn** (fault plane / config host stop times): an event whose
      time falls inside a down interval of its host is discarded (counted
      in ``down_events``) — the batch analogue of the reference halting a
      host's processes; events timed after a restart execute against the
      reset state (docs/SEMANTICS.md §"Fault plane");
    * **virtual CPU** (src/main/host/cpu.c): execution time is
      ``eff = max(time, cpu_busy[h])``; if eff crosses the window boundary
      the event re-queues at (eff, original tb) unexecuted, else it
      executes with ``now = eff`` and charges ``cpu_busy = eff + cost``.
      Both engines apply the identical rule in identical per-host order,
      so the busy clocks evolve identically (docs/SEMANTICS.md).

    Each kind's pass is wrapped in ``lax.cond`` on "any host popped this
    kind this round" — most rounds touch 1–2 of the 5 kinds, so skipping
    the dead passes cuts the round cost correspondingly (handlers draw RNG
    and advance counters only where masked, so an all-false pass is a
    no-op by construction and skipping it is exact)."""
    with jax.named_scope("phase:pop"):
        if ctx.params.pop_impl == "pallas":
            from shadow1_tpu.core.popk import pop_until_fused

            evbuf, ev = pop_until_fused(st.evbuf, win_end)
        else:
            evbuf, ev = pop_until(st.evbuf, win_end,
                                  extract=ctx.params.pop_extract)
    st = st._replace(evbuf=evbuf)
    m = st.metrics
    n_down = jnp.zeros((), jnp.int64)
    if ctx.has_stop:
        from shadow1_tpu.fault.plane import hosts_down_at

        supp = ev.mask & hosts_down_at(ctx.fault_down, ctx.fault_up, ev.time)
        n_down = supp.sum(dtype=jnp.int64)
        ev = ev._replace(mask=ev.mask & ~supp,
                         kind=jnp.where(supp, 0, ev.kind))
    if ctx.has_cpu:
        eff = jnp.maximum(ev.time, st.cpu_busy)
        defer = ev.mask & (eff >= win_end)
        run = ev.mask & ~defer
        evbuf, over = push_back(
            st.evbuf, defer, eff, ev.tb, ev.kind, ev.p
        )
        st = st._replace(
            evbuf=evbuf,
            cpu_busy=jnp.where(run, eff + ctx.cpu_cost, st.cpu_busy),
        )
        m = m._replace(ev_overflow=m.ev_overflow + over.sum(dtype=jnp.int64))
        ev = ev._replace(mask=run, time=jnp.where(run, eff, ev.time),
                         kind=jnp.where(defer, 0, ev.kind))
    pops = {
        f[0]: getattr(m, f[0]) + (ev.mask & (ev.kind == k)).sum(dtype=jnp.int64)
        for k, f in KIND_METRIC_FIELDS.items() if k in handlers
    }
    st = st._replace(
        metrics=m._replace(
            events=m.events + ev.mask.sum(dtype=jnp.int64),
            rounds=m.rounds + 1,
            down_events=m.down_events + n_down,
            **pops,
        ),
    )
    items = sorted(handlers.items())
    for kind, fn in items:
        scope = f"phase:h_{KIND_NAMES.get(kind, kind)}"
        if len(items) == 1:
            with jax.named_scope(scope):
                st = fn(st, ev)
        else:
            present = (ev.mask & (ev.kind == kind)).any()
            if kind in KIND_METRIC_FIELDS:
                fires = KIND_METRIC_FIELDS[kind][1]
                m2 = st.metrics
                st = st._replace(metrics=m2._replace(**{
                    fires: getattr(m2, fires) + present.astype(jnp.int64)
                }))
            with jax.named_scope(scope):
                st = jax.lax.cond(present, fn, lambda s, _e: s, st, ev)
    return st


def route_outbox(ctx: Ctx, ob: Outbox, links=None, win_start=None):
    """Route this block's outbox: latency gather + fault gates + loss draws.

    The tensor analogue of the reference's topology path lookup at send time
    (src/main/routing/topology.c getLatency/getReliability, SURVEY §3.3),
    plus the fault plane's source-side gates (docs/SEMANTICS.md §"Fault
    plane"), in canonical order: a packet departing inside a link-outage
    window is dropped deterministically (counted ``link_down_pkts``, never
    in ``pkts_lost``); otherwise the Bernoulli loss draw applies at the
    path's threshold — replaced by an active timed loss ramp's, same coin
    bits either way. Returns (flat_packets, n_sent, n_lost, n_linkdown).

    With the link plane on (``links`` a LinkAccum, ``win_start`` the window
    start), every offered packet's edge contribution — counts, wire bytes,
    drop partition, NIC queueing ns (depart − win_start) — is scatter-added
    here, at the routing attribution point, and the updated accumulator is
    returned as a fifth element (docs/SEMANTICS.md §"Link telemetry
    contract")."""
    cap, h = ob.dst.shape
    mask = jnp.arange(cap)[:, None] < ob.cnt[None, :]
    src = jnp.broadcast_to(ctx.hosts[None, :], (cap, h))

    def flat(x):
        return x.reshape(x.shape[:-2] + (cap * h,))

    fmask, fsrc, fdst = flat(mask), flat(src), flat(ob.dst)
    fdst_safe = jnp.where(fmask, fdst, 0)
    # The i32 outbox planes widen once here, at window granularity
    # (core/outbox.py layout note); fctr is exact below 2**31 pkts/host.
    fdep = flat(ob.abs_depart())
    fctr = flat(ob.ctr).astype(jnp.int64)
    vs = ctx.host_vertex[fsrc]
    vd = ctx.host_vertex[fdst_safe]
    arrival = fdep + ctx.lat_vv[vs, vd]
    if ctx.has_jitter:
        # Per-packet edge jitter in [-J, +J] (reference: topology edge
        # jitter attribute); J < lat so the conservative window holds.
        jit = ctx.jitter_vv[vs, vd]
        jbits = rng.bits_v(ctx.key, R_JITTER, fsrc, fctr)
        arrival = arrival + rng.randint(jbits, 2 * jit + 1).astype(jnp.int64) - jit
    linkdown = jnp.zeros_like(fmask)
    if ctx.has_link_fault:
        from shadow1_tpu.fault.plane import link_down_mask

        linkdown = fmask & link_down_mask(ctx.link_fault, vs, vd, fdep)
    thr = ctx.loss_thr_vv[vs, vd]
    if ctx.has_loss_ramp:
        from shadow1_tpu.fault.plane import ramp_loss_thr

        thr = ramp_loss_thr(ctx.loss_ramp, vs, vd, fdep, thr)
    bits = rng.bits_v(ctx.key, R_LOSS, fsrc, fctr)
    # Integer Bernoulli on precomputed thresholds (rng.prob_threshold) —
    # shared with the CPU oracle, backend-exact by construction.
    lost = fmask & ~linkdown & rng.uniform_lt(bits, thr)
    keep = fmask & ~lost & ~linkdown
    tb = packet_tb(fsrc.astype(jnp.int64), fctr)
    fp = FlatPackets(
        dst=fdst_safe, arrival=arrival, tb=tb, kind=flat(ob.kind), p=flat(ob.p),
        keep=keep,
    )
    out = (fp, fmask.sum(dtype=jnp.int64), lost.sum(dtype=jnp.int64),
           linkdown.sum(dtype=jnp.int64))
    if links is None:
        return out
    from shadow1_tpu.consts import WIRE_OVERHEAD
    from shadow1_tpu.telemetry.links import link_route_accum

    links = link_route_accum(
        links, vs, vd, fmask, lost, linkdown,
        queued=fdep - win_start,
        wire=flat(ob.p)[4].astype(jnp.int64) + WIRE_OVERHEAD,
    )
    return out + (links,)


def deliver_flat(evbuf, ctx: Ctx, fp: FlatPackets):
    """Scatter (possibly gathered) packets into this block's event buffers.

    Maps global dst ids onto the local block (contiguous range starting at
    ctx.hosts[0]); packets for other blocks are masked out; packets whose
    arrival falls inside a down interval of the destination are dropped
    here (churn — counted, never delivered, so a dead host's buffers stay
    clean). Returns (evbuf, n_delivered, n_overflow, n_down) counting only
    this block's packets."""
    base = ctx.hosts[0].astype(fp.dst.dtype)
    local = fp.dst - base
    mine = fp.keep & (local >= 0) & (local < ctx.n_hosts)
    local = jnp.where(mine, local, 0)
    n_down = jnp.zeros((), jnp.int64)
    if ctx.has_stop:
        from shadow1_tpu.fault.plane import hosts_down_at_idx

        to_down = mine & hosts_down_at_idx(
            ctx.fault_down, ctx.fault_up, local, fp.arrival
        )
        n_down = to_down.sum(dtype=jnp.int64)
        mine = mine & ~to_down
    evbuf, n_over = deliver_batch(
        evbuf, local, fp.arrival, fp.tb, fp.kind, fp.p, mine
    )
    return evbuf, mine.sum(dtype=jnp.int64) - n_over, n_over, n_down


def deliver_window(st: SimState, ctx: Ctx, exchange=None) -> SimState:
    """Window-end packet exchange: route, (all_to_all under sharding), scatter.

    ``exchange`` maps FlatPackets → (FlatPackets, n_dropped, fill_high_water)
    across the mesh (identity on a single device; a bucketed all_to_all over
    the host axis when sharded — the one collective per window, SURVEY §2.5)."""
    from shadow1_tpu.core.outbox import outbox_fill

    with jax.named_scope("phase:route"):
        links = st.links
        if links is not None:
            fp, n_sent, n_lost, n_linkdown, links = route_outbox(
                ctx, st.outbox, links=links, win_start=st.win_start)
        else:
            fp, n_sent, n_lost, n_linkdown = route_outbox(ctx, st.outbox)
    # Maintained [H] counters — read before the window-end clear. ob_hosts
    # is the wasted-work gauge's numerator: hosts that actually used the
    # [P, H] outbox planes this window (the oracle mirrors per-window send
    # sets exactly, so this is parity-exact).
    ob_fill = outbox_fill(st.outbox)
    ob_hosts = (st.outbox.cnt > 0).sum(dtype=jnp.int64)
    n_x2x = x2x_hw = jnp.zeros((), jnp.int64)
    if exchange is not None:
        with jax.named_scope("phase:exchange"):
            fp, n_x2x, x2x_hw = exchange(fp)
    with jax.named_scope("phase:deliver"):
        evbuf, n_deliv, n_over, n_down = deliver_flat(st.evbuf, ctx, fp)
    m = st.metrics
    return st._replace(
        evbuf=evbuf,
        outbox=outbox_clear(st.outbox),
        links=links,
        metrics=m._replace(
            pkts_sent=m.pkts_sent + n_sent,
            pkts_delivered=m.pkts_delivered + n_deliv,
            pkts_lost=m.pkts_lost + n_lost,
            ev_overflow=m.ev_overflow + n_over,
            x2x_overflow=m.x2x_overflow + n_x2x,
            x2x_max_fill=jnp.maximum(m.x2x_max_fill, x2x_hw),
            ob_max_fill=jnp.maximum(m.ob_max_fill, ob_fill),
            down_pkts=m.down_pkts + n_down,
            link_down_pkts=m.link_down_pkts + n_linkdown,
            outbox_hosts=m.outbox_hosts + ob_hosts,
        ),
    )


def run_rounds(st: SimState, ctx: Ctx, handlers: dict, win_end):
    """The inner round loop to quiescence (or the safety cap).

    Returns (st, cap_hit). Shared by the full-width path and the compacted
    path (core/compact.py), which calls it at bucket width."""
    max_rounds = ctx.params.max_rounds

    def cond(carry):
        s, r = carry
        return (r < max_rounds) & any_eligible(s.evbuf, win_end)

    def body(carry):
        s, r = carry
        return run_round(s, ctx, handlers, win_end), r + 1

    st, r = jax.lax.while_loop(cond, body, (st, jnp.zeros((), jnp.int32)))
    return st, (r >= max_rounds) & any_eligible(st.evbuf, win_end)


class WindowFrame(NamedTuple):
    """The intra-window carry threaded through the ``window_phases`` stages.

    ``window_step`` is the composition of the stage list over this frame;
    ``tools/phaseprobe.py`` jits the stages INDIVIDUALLY (on frames captured
    from a real run) so wall time attributes per phase instead of per
    window. Every leaf is a jittable array, so a frame crosses a jit
    boundary unchanged."""

    st: SimState
    m_entry: Metrics        # metrics at window entry (ring delta baseline)
    win_end: jnp.ndarray    # i64 scalar
    cap_hit: jnp.ndarray    # bool scalar (set by the rounds phase)
    dg_ob: jnp.ndarray      # i64 outbox digest word (digest runs only)
    l_entry: Any = None     # link accumulator at window entry (the sharded
                            # per-window psum's delta baseline; link runs only)


def window_frame(st: SimState, ctx: Ctx) -> WindowFrame:
    """The entry frame of one conservative window."""
    return WindowFrame(
        st=st,
        m_entry=st.metrics,
        win_end=st.win_start + ctx.window,
        cap_hit=jnp.zeros((), bool),
        dg_ob=jnp.zeros((), jnp.int64),
        l_entry=st.links,
    )


def window_phases(ctx: Ctx, handlers: dict, exchange=None, pre_window=None,
                  make_handlers=None, telem_reduce=None, probe_reduce=None,
                  link_reduce=None):
    """The ordered (name, frame → frame) stage list of one window.

    The phase decomposition of the jitted ``window_step`` (performance
    attribution plane): ``prepare`` (restart resets, work gauges, the net
    model's NIC arrival batch, rebase/clear), ``rounds`` (the pop + handler
    while-loop — sub-annotated ``phase:pop`` / ``phase:h_<kind>`` in
    run_round), ``deliver`` (route + optional exchange collective + the
    destination scatter + outbox clear — sub-annotated in deliver_window),
    ``telem`` (occupancy gauges, window counters, the telemetry-ring row).
    Each stage is wrapped in ``jax.named_scope("phase:<name>")`` by
    window_step, so device traces (``jax.profiler`` via
    telemetry/profiler.device_trace) carry the phases as spans, and
    ``tools/opcensus.py`` censuses each stage's jaxpr separately."""
    from shadow1_tpu.core.events import push_impl_ctx, rebase

    digest_on = bool(ctx.params.state_digest)

    def ph_prepare(fr: WindowFrame) -> WindowFrame:
        st, win_end = fr.st, fr.win_end
        if ctx.has_restart:
            # Host restart (fault plane): hosts whose window-quantized up
            # time IS this window's start get their model columns (tcp
            # socks, nic clocks/counters, app state) restored to the
            # post-init capture and their virtual-CPU clock zeroed — BEFORE
            # this window's rounds, so events timed at/after the restart
            # execute against fresh state. The event buffer is deliberately
            # untouched: stale events are a pure function of time
            # (dead-interval ones discard at pop), so the oracle's eager
            # heap and this batched reset stay bit-equal.
            from shadow1_tpu.fault.plane import (
                reset_host_columns,
                restart_mask,
            )

            rs = restart_mask(ctx.fault_up, st.win_start)
            mr = st.metrics
            st = st._replace(
                model=reset_host_columns(st.model, ctx.init_model, rs,
                                         ctx.n_hosts),
                cpu_busy=jnp.where(rs, 0, st.cpu_busy),
                metrics=mr._replace(
                    host_restarts=mr.host_restarts + rs.sum(dtype=jnp.int64)),
            )
        n_act = n_el = None
        if pre_window is not None:
            # Wasted-work gauges BEFORE the NIC arrival batch rewrites
            # event times: the RAW window-start pending set (events with
            # time < win_end) is the engine-independent quantity the CPU
            # oracle mirrors from its heap at the same boundary — the
            # post-conversion eligibility (queue-cleared times) is not.
            abs_t = st.evbuf.abs_time()
            live = (st.evbuf.kind != K_NONE) & (abs_t < win_end)
            n_act = live.any(axis=0).sum(dtype=jnp.int64)
            n_el = live.sum(dtype=jnp.int64)
            st = pre_window(st, ctx, win_end)
        # Advance the i32 pop-key epoch to this window's start
        # (core/events.py: the round loop runs i64-free; pre_window and
        # last window's delivery write absolute times only, repaired here).
        st = st._replace(evbuf=rebase(st.evbuf, st.win_start, win_end))
        # Compaction-bucket demand gauge: this window's active-host count
        # (the lanes compact_cap must cover), read off the just-rebased [H]
        # eligibility counters — recorded whether or not compaction is on,
        # so the knob can be sized BEFORE enabling it, and the compacted
        # and plain engines stay bit-identical (tests/test_compact.py).
        # Local-block count under sharding (the per-shard bucket is the
        # resource), like rounds.
        n_active = (st.evbuf.n_elig > 0).sum(dtype=jnp.int64)
        if n_act is None:
            # No pre-window time rewrite: the just-rebased eligibility
            # counters ARE the raw window-start set — the work gauges cost
            # zero extra plane passes on this path.
            n_act = n_active
            n_el = st.evbuf.n_elig.sum(dtype=jnp.int64)
        m0 = st.metrics
        st = st._replace(metrics=m0._replace(
            compact_max_fill=jnp.maximum(m0.compact_max_fill, n_active),
            active_hosts=m0.active_hosts + n_act,
            elig_events=m0.elig_events + n_el,
        ))
        return fr._replace(st=st)

    def ph_rounds(fr: WindowFrame) -> WindowFrame:
        st = fr.st
        ccap = ctx.params.compact_cap
        # push_impl scopes over the round tracing: every handler-layer
        # push_local/push_back below dispatches to the selected
        # implementation (trace-time — see events.push_impl_ctx).
        with push_impl_ctx(ctx.params.push_impl):
            if ccap and ccap < ctx.n_hosts and make_handlers is not None:
                from shadow1_tpu.core.compact import compact_window_rounds

                st, cap_hit = compact_window_rounds(
                    st, ctx, handlers, make_handlers, run_rounds,
                    fr.win_end, ccap
                )
            else:
                st, cap_hit = run_rounds(st, ctx, handlers, fr.win_end)
        return fr._replace(st=st, cap_hit=cap_hit)

    def ph_deliver(fr: WindowFrame) -> WindowFrame:
        st, dg_ob = fr.st, fr.dg_ob
        if digest_on and st.telem is not None:
            # The outbox still holds this window's sends here — the
            # delivery below routes and clears it, so its digest word is
            # taken first.
            from shadow1_tpu.core.digest import digest_outbox

            dg_ob = digest_outbox(st.outbox, ctx.hosts)
        st = deliver_window(st, ctx, exchange)
        return fr._replace(st=st, dg_ob=dg_ob)

    def ph_telem(fr: WindowFrame) -> WindowFrame:
        # Window-end event-slot occupancy: computed ONCE here (one [C, H]
        # pass per window, off the round path) and shared by the run-max
        # gauge and the telemetry ring's per-window column.
        from shadow1_tpu.core.events import evbuf_fill

        st = fr.st
        ev_fill = evbuf_fill(st.evbuf)
        m = st.metrics
        st = st._replace(
            win_start=fr.win_end,
            metrics=m._replace(
                windows=m.windows + 1,
                round_cap_hits=m.round_cap_hits
                + fr.cap_hit.astype(jnp.int64),
                ev_max_fill=jnp.maximum(m.ev_max_fill, ev_fill),
            ),
        )
        if st.telem is not None:
            from shadow1_tpu.telemetry.ring import ring_record

            digests = None
            if digest_on:
                # Everything but the outbox digests the post-delivery
                # window-boundary state — exactly the pending/live sets the
                # CPU oracle sees when its next event crosses this boundary.
                from shadow1_tpu.core.digest import state_digests

                digests = state_digests(st, ctx, fr.dg_ob)
            st = st._replace(telem=ring_record(
                st.telem, fr.m_entry, st.metrics, ev_fill, telem_reduce,
                digests=digests,
            ))
        if st.probes is not None:
            # Flow-probe samples: the same post-delivery window-boundary
            # state the digests hash, gathered per watched entity
            # (telemetry/probes.py). ``fr.win_end`` anchors the NIC backlog
            # columns; the window-entry metrics pick the ring slot.
            # ``probe_reduce`` psums the owned-shard one-hot rows under
            # sharding; identity elsewhere.
            from shadow1_tpu.telemetry.probes import probe_record, probe_sample

            row = probe_sample(st, ctx, fr.win_end, ctx.params.probes)
            if probe_reduce is not None:
                row = probe_reduce(row)
            st = st._replace(probes=probe_record(st.probes, fr.m_entry, row))
        if st.links is not None and link_reduce is not None:
            # Link-accumulator globalization (sharded runs only): psum this
            # window's per-shard counter deltas onto the entry baseline and
            # pmax the high-water column, so every shard carries the exact
            # single-device tensor at the boundary (shard/engine.py
            # link_reduce; identity is link_reduce=None elsewhere — zero
            # per-window overhead off the sharded path).
            st = st._replace(links=link_reduce(fr.l_entry, st.links))
        return fr._replace(st=st)

    return [("prepare", ph_prepare), ("rounds", ph_rounds),
            ("deliver", ph_deliver), ("telem", ph_telem)]


def window_step(st: SimState, ctx: Ctx, handlers: dict, exchange=None,
                pre_window=None, make_handlers=None,
                telem_reduce=None, probe_reduce=None,
                link_reduce=None) -> SimState:
    """One conservative window: inner rounds to quiescence, then delivery.

    The batched form of the reference's barrier round
    (scheduler_continueNextRound in src/main/core/scheduler/scheduler.c):
    the while_loop plays the worker event loop, the delivery plays the
    cross-thread event push that the barrier makes safe.

    ``pre_window(st, ctx, win_end)`` is an optional model hook that runs
    before the rounds — the net model uses it to batch-process every NIC
    arrival of the window in one scan instead of one round per packet.

    When ``params.compact_cap`` is set (and ``make_handlers`` provided),
    sparse windows run their rounds on a gathered active-host bucket
    (core/compact.py) — bit-identical results, narrow tensors.

    When the state carries a telemetry ring (``st.telem``), the window's
    metric deltas are recorded into it here, still inside the trace —
    ``telem_reduce`` globalizes the row under sharding (telemetry/ring.py).

    Structured as the composition of the ``window_phases`` stage list, each
    under a ``jax.named_scope("phase:<name>")`` — the performance
    attribution plane's decomposition (tools/phaseprobe.py times the stages
    individually; tools/opcensus.py censuses their jaxprs; device traces
    carry them as spans)."""
    fr = window_frame(st, ctx)
    for name, fn in window_phases(ctx, handlers, exchange, pre_window,
                                  make_handlers, telem_reduce, probe_reduce,
                                  link_reduce):
        with jax.named_scope(f"phase:{name}"):
            fr = fn(fr)
    return fr.st


_QLEN_INF = 1 << 62


def qlen_ns_np(qlen_bytes: np.ndarray, bw_bits: np.ndarray) -> np.ndarray:
    """NIC queue bound in serialization-time ns (0 bytes = unbounded)."""
    from shadow1_tpu.consts import SEC

    q = np.asarray(qlen_bytes, np.int64)
    bw = np.asarray(bw_bits, np.int64)
    return np.where(q > 0, (q * 8 * SEC + bw - 1) // bw, _QLEN_INF)


def aqm_tables_np(exp) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """RED per-host tables (min_ns, span_ns, pmax_thr) — computed ONCE here,
    in numpy, and consumed by both engines so their drop decisions compare
    identical integers. Thresholds convert bytes → uplink backlog-time ns
    (same ceil math as the drop-tail bound, but 0 bytes means 0 ns here, not
    "unbounded"); span is clamped ≥1 so the traced division is always safe;
    pmax_thr is 0 wherever AQM is off."""
    from shadow1_tpu.consts import SEC

    bw = np.asarray(exp.bw_up, np.int64)

    def to_ns(b):
        return (np.asarray(b, np.int64) * 8 * SEC + bw - 1) // bw

    min_ns = to_ns(exp.aqm_min_bytes)
    max_ns = to_ns(exp.aqm_max_bytes)
    on = np.asarray(exp.aqm_max_bytes) > 0
    min_ns = np.where(on, min_ns, 0)
    span_ns = np.maximum(np.where(on, max_ns - min_ns, 1), 1)
    from shadow1_tpu import rng

    pmax_thr = np.where(on, rng.prob_threshold(exp.aqm_pmax), np.uint64(0))
    return min_ns.astype(np.int64), span_ns.astype(np.int64), pmax_thr


def fidelity_ctx_kwargs(exp) -> dict:
    """The Ctx fidelity fields + static has_* flags from a CompiledExperiment
    (shared by Engine and ShardedEngine; everything numpy → device const).
    The fault plane compiles here too: host down/up intervals (legacy
    stop_time merged in), link-outage and loss-ramp tables — one builder
    (fault/schedule.py) shared with the CPU oracle."""
    from shadow1_tpu.config.compiled import NO_STOP
    from shadow1_tpu.fault.schedule import (
        host_interval_tensors,
        link_tables,
        ramp_tables,
    )

    aqm_min_ns, aqm_span_ns, aqm_pmax_thr = aqm_tables_np(exp)
    fault_down, fault_up = host_interval_tensors(exp)
    lf = link_tables(exp)
    rt = ramp_tables(exp)
    return dict(
        jitter_vv=jnp.asarray(exp.jitter_vv, jnp.int64),
        fault_down=jnp.asarray(fault_down),
        fault_up=jnp.asarray(fault_up),
        link_fault=(tuple(jnp.asarray(a) for a in lf)
                    if lf is not None else None),
        loss_ramp=(tuple(jnp.asarray(a) for a in rt)
                   if rt is not None else None),
        cpu_cost=jnp.asarray(exp.cpu_ns_per_event, jnp.int64),
        tx_qlen_ns=jnp.asarray(qlen_ns_np(exp.tx_qlen_bytes, exp.bw_up)),
        rx_qlen_ns=jnp.asarray(qlen_ns_np(exp.rx_qlen_bytes, exp.bw_dn)),
        aqm_min_ns=jnp.asarray(aqm_min_ns),
        aqm_span_ns=jnp.asarray(aqm_span_ns),
        aqm_pmax_thr=jnp.asarray(aqm_pmax_thr),
        has_jitter=bool(exp.jitter_vv.max() > 0),
        has_stop=bool(fault_down.min() < NO_STOP),
        has_restart=bool((fault_up < NO_STOP).any()),
        has_link_fault=lf is not None,
        has_loss_ramp=rt is not None,
        has_cpu=bool(exp.cpu_ns_per_event.max() > 0),
        has_tx_qlen=bool(exp.tx_qlen_bytes.max() > 0),
        has_rx_qlen=bool(exp.rx_qlen_bytes.max() > 0),
        has_aqm=bool(np.asarray(exp.aqm_max_bytes).max() > 0),
    )


def build_base_ctx(exp: CompiledExperiment, params: EngineParams,
                   window: int | None = None) -> Ctx:
    """The single-device Ctx for a CompiledExperiment — topology constants,
    fidelity tables, fault plane, per-experiment RNG key. Shared by Engine
    below and the batched-experiment FleetEngine (shadow1_tpu/fleet/),
    which swaps the per-experiment leaves (key, loss thresholds, fault
    tables) per vmapped lane."""
    return Ctx(
        n_hosts=exp.n_hosts,
        n_total=exp.n_hosts,
        params=params,
        window=window if window is not None else exp.window,
        key=rng.base_key(exp.seed),
        lat_vv=jnp.asarray(exp.lat_vv, jnp.int64),
        loss_vv=jnp.asarray(exp.loss_vv, jnp.float32),
        host_vertex=jnp.asarray(exp.host_vertex, jnp.int32),
        bw_up=jnp.asarray(exp.bw_up, jnp.int64),
        bw_dn=jnp.asarray(exp.bw_dn, jnp.int64),
        model_cfg=exp.model_cfg,
        **fidelity_ctx_kwargs(exp),
    )


def check_digest_params(params: EngineParams) -> None:
    """state_digest needs a telemetry ring to carry the words on the
    batched engines (the CPU oracle keeps its own rows and has no ring)."""
    if params.state_digest and params.metrics_ring <= 0:
        raise ValueError(
            "state_digest=1 requires metrics_ring > 0 on the batched "
            "engines — the per-window digest words are ring columns "
            "(CLI --state-digest sets a ring automatically)"
        )


def check_probe_params(params: EngineParams) -> None:
    """The probe ring reuses the telemetry ring's depth knob: watched
    flows need metrics_ring > 0 on the batched engines (the CPU oracle
    keeps its own probe_rows and has no ring)."""
    if params.probes and params.metrics_ring <= 0:
        raise ValueError(
            "probes require metrics_ring > 0 on the batched engines — "
            "the [W, K, F] probe ring depth is the metrics_ring window "
            "count (CLI --watch sets a ring automatically)"
        )


def _model_module(name: str):
    if name == "phold":
        from shadow1_tpu.core import phold

        return phold
    if name == "net":
        from shadow1_tpu import net

        return net
    raise ValueError(f"unknown model {name!r}")


def _resolve_kernel_impls(params: EngineParams, n_hosts: int) -> EngineParams:
    """Downgrade pop_impl/push_impl='pallas' to 'xla' when the gridless fused
    kernels cannot hold the plane set in VMEM at this (cap, n_hosts) — the
    kernels would otherwise raise mid-trace (core/popk.py _check_vmem).
    Logged, not silent: the selection is a measured perf knob."""
    if "pallas" not in (params.pop_impl, params.push_impl):
        return params
    from shadow1_tpu.core import popk

    try:
        popk.preflight(params.ev_cap, params.outbox_cap, n_hosts,
                       pop_pallas=params.pop_impl == "pallas",
                       push_pallas=params.push_impl == "pallas")
    except ValueError as e:
        import warnings

        warnings.warn(f"pallas kernels unavailable at this shape ({e}); "
                      "falling back to pop_impl=push_impl='xla'")
        import dataclasses

        params = dataclasses.replace(params, pop_impl="xla", push_impl="xla")
    return params


class Engine:
    """Batched engine for one CompiledExperiment.

    The model module supplies ``init(ctx) -> (model_state, evbuf)`` (initial
    events seeded) and ``make_handlers(ctx) -> dict[kind, Handler]``.
    """

    def __init__(self, exp: CompiledExperiment, params: EngineParams | None = None):
        exp.validate()
        self.exp = exp
        self.params = params or EngineParams()
        check_digest_params(self.params)
        check_probe_params(self.params)
        from shadow1_tpu.telemetry.links import check_link_params

        check_link_params(self.params, np.asarray(exp.lat_vv).shape[0])
        self.params = _resolve_kernel_impls(self.params, exp.n_hosts)
        self.window = exp.window
        self.n_windows = int(-(-exp.end_time // self.window))
        self.ctx = build_base_ctx(exp, self.params, window=self.window)
        self._model = _model_module(exp.model)
        if self.ctx.has_restart:
            # Restart target: the model pytree exactly as init() builds it
            # (tcp listen sockets included), materialized once and closed
            # over as device constants — window_step restores restarted
            # hosts' columns from it (fault/plane.reset_host_columns).
            model0, _, _ = self._model.init(
                self.ctx, evbuf_init(exp.n_hosts, self.params.ev_cap)
            )
            self.ctx = dataclasses.replace(
                self.ctx,
                init_model=jax.tree.map(lambda x: jnp.asarray(np.asarray(x)),
                                        model0),
            )
        self._handlers = self._model.make_handlers(self.ctx)
        self._pre_window = getattr(self._model, "make_pre_window", lambda c: None)(
            self.ctx
        )
        # No donation: the initial state contains aliased zero-buffers (XLA
        # rejects donating one buffer twice) and run() is called once per sim,
        # so the single input copy is negligible. n_windows is a TRACED
        # argument (fori_loop lowers to while_loop): engine round bodies take
        # minutes to compile, and a dynamic bound means one compiled program
        # serves every chunk size / heartbeat / resume window count.
        self._run_jit = jax.jit(self._make_run())

    # -- state ------------------------------------------------------------
    def init_state(self) -> SimState:
        from shadow1_tpu.telemetry.links import link_init
        from shadow1_tpu.telemetry.probes import probe_init
        from shadow1_tpu.telemetry.ring import ring_init

        evbuf = evbuf_init(self.exp.n_hosts, self.params.ev_cap)
        model, evbuf, seed_over = self._model.init(self.ctx, evbuf)
        metrics = _metrics_init()
        return SimState(
            win_start=jnp.zeros((), jnp.int64),
            evbuf=evbuf,
            outbox=outbox_init(self.exp.n_hosts, self.params.outbox_cap),
            model=model,
            metrics=metrics._replace(ev_overflow=metrics.ev_overflow + seed_over),
            cpu_busy=jnp.zeros(self.exp.n_hosts, jnp.int64),
            telem=ring_init(self.params.metrics_ring),
            probes=probe_init(self.params.metrics_ring, self.params.probes),
            links=link_init(self.params.link_telem,
                            np.asarray(self.exp.lat_vv).shape[0]),
        )

    def place_state(self, st: SimState) -> SimState:
        """Put a (host-built) state pytree on this engine's devices — the
        hook the cap controller uses after a tune/resize.py migration.
        Single-device: a plain transfer."""
        return jax.device_put(st)

    # -- window step pieces ----------------------------------------------
    def _window_step(self, st: SimState) -> SimState:
        return window_step(st, self.ctx, self._handlers,
                           pre_window=self._pre_window,
                           make_handlers=self._model.make_handlers)

    def _make_run(self):
        def run(st: SimState, n_windows) -> SimState:
            return jax.lax.fori_loop(
                0, n_windows, lambda _, s: self._window_step(s), st
            )

        return run

    # -- public -----------------------------------------------------------
    def run(self, st: SimState | None = None, n_windows: int | None = None) -> SimState:
        if st is None:
            st = self.init_state()
        n = n_windows if n_windows is not None else self.n_windows
        return self._run_jit(st, jnp.asarray(n, jnp.int32))

    @staticmethod
    def metrics_dict(st: SimState) -> dict[str, int]:
        return {k: int(v) for k, v in st.metrics._asdict().items()}

    def model_summary(self, st: SimState) -> dict[str, Any]:
        return jax.tree.map(np.asarray, self._model.summary(st.model, self.ctx))
