"""Vectorized virtual TCP — every socket of every host updated in SIMD.

The tensor re-expression of the reference's biggest state machine
(src/main/host/descriptor/tcp.c, SURVEY §2.3): 3-way handshake, sliding
window, Reno-style congestion control (slow start, AIMD, fast retransmit on
3 dup-ACKs, RTO with exponential backoff), RFC6298 integer RTT estimation,
FIN teardown. State lives in a dict of ``[S, H]`` arrays (socket-major,
host-minor — core/dense.py layout contract); every operation
is a masked gather/scatter over the (host, socket) plane — one packet per
host per round, all hosts in parallel.

Deliberate model simplifications vs the reference (docs/SEMANTICS.md §tcp):

* Go-Back-N loss recovery: the receiver accepts only in-order segments (no
  out-of-order reassembly buffer / SACK); on retransmit the sender rewinds
  ``snd_nxt`` to ``snd_una``. Identical in both engines, so parity is exact;
  fidelity differs from the reference only under loss.
* Immediate ACKs (no delayed-ACK timer).
* Byte counts only — payload contents are never materialized (apps are
  models); message boundaries ride packets as (end_seq, meta) pairs, at
  most one per segment.

Sequence space: u32 wrapping (i32 arrays, natural overflow). ISN = 0: SYN
occupies seq 0, stream byte k is seq 1+k, FIN occupies the seq after the
last byte.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from shadow1_tpu.consts import (
    F_ACK,
    F_FIN,
    F_SYN,
    K_PKT,
    K_TCP_TIMER,
    K_TX_RESUME,
    N_ACCEPTED,
    N_CLOSED,
    N_DATA,
    N_ESTABLISHED,
    N_MSG,
    N_PEER_FIN,
    N_SPACE,
    NP,
    TCP_CLOSE_WAIT,
    TCP_CLOSING,
    TCP_ESTABLISHED,
    TCP_FIN_WAIT_1,
    TCP_FIN_WAIT_2,
    TCP_FREE,
    TCP_LAST_ACK,
    TCP_LISTEN,
    TCP_SYN_RCVD,
    TCP_SYN_SENT,
    WIRE_OVERHEAD,
)
from shadow1_tpu.consts import (  # noqa: F811 — shared tuning/state sets
    CWND_MAX,
    SSTHRESH_INIT,
    TCP_CONN_STATES,
    TCP_RCV_STATES,
    TCP_SENDABLE_STATES,
)
from shadow1_tpu.core.dense import (
    extract_col,
    payload,
    first_true_idx,
    get_col,
    last_true,
    onehot_col,
    set_col,
)
from shadow1_tpu.core.events import tb_join, tb_split
from shadow1_tpu.core.outbox import outbox_append, outbox_space
from shadow1_tpu.net.nic import ctx_aqm, tx_stamp

# Fields of the TCP state dict, all [S, H] unless noted.
_FIELDS_I32 = (
    "st", "peer_host", "peer_sock",
    "snd_una", "snd_nxt", "snd_max", "rcv_nxt", "app_end",  # seq (u32 wrap)
    "fin_pend", "cwnd", "ssthresh", "peer_wnd",
    "dupacks", "recover", "ts_seq", "txr",
)
# ``snd_max`` is the highest sequence ever sent (RFC 793's SND.NXT before
# any Go-Back-N rewind). Cumulative-ACK acceptance must test against it,
# not the rewound snd_nxt: after an RTO rewinds snd_nxt to snd_una, the
# receiver may legitimately ACK data it got BEFORE the loss event — with
# random loss the window's every ACK dying is vanishingly rare, but a
# fault-plane link outage makes it certain, and rejecting that ACK
# deadlocks the connection (the retransmitted low segment is below the
# receiver's rcv_nxt forever). Found by the PR-4 outage tests.
# Time-valued fields with i64 SEMANTICS (RTT estimator state, retransmit
# deadline, RTT-sample stamp — values up to rto_max·backoff / absolute sim
# time). Stored as order-preserving i32 (hi, lo) plane pairs (core/events.py
# tb_split): the chip has no native i64, and these planes see a one-hot
# full-plane write per set (core/dense.py set_col) on the round path.
# Sock.g/Sock.s and the tcp_flush helpers join/split at the [H]-vector
# level, so all arithmetic still happens on exact i64 values.
_FIELDS_I64 = ("srtt", "rttvar", "rto", "rtx_t", "ts_time")
_I64_SET = frozenset(_FIELDS_I64)
_FIELDS_BOOL = ("timer_armed", "ts_act")


def tcp_init(n_hosts: int, n_socks: int, mq_cap: int, params) -> dict:
    zhi, zlo = tb_split(jnp.zeros((), jnp.int64))
    d = {}
    for f in _FIELDS_I32:
        d[f] = jnp.zeros((n_socks, n_hosts), jnp.int32)
    for f in _FIELDS_I64:
        d[f + "_hi"] = jnp.full((n_socks, n_hosts), zhi, jnp.int32)
        d[f + "_lo"] = jnp.full((n_socks, n_hosts), zlo, jnp.int32)
    for f in _FIELDS_BOOL:
        d[f] = jnp.zeros((n_socks, n_hosts), bool)
    d["mq_valid"] = jnp.zeros((mq_cap, n_socks, n_hosts), bool)
    d["mq_end"] = jnp.zeros((mq_cap, n_socks, n_hosts), jnp.int32)
    d["mq_meta"] = jnp.zeros((mq_cap, n_socks, n_hosts), jnp.int32)
    return d


class Sock:
    """Masked (host → socket) view over the TCP dict: readable sequential
    code, functional updates underneath. All reads/writes are [H] vectors at
    [sock, h]; writes apply only where the (possibly narrowed) mask holds."""

    def __init__(self, tcp: dict, sock, mask):
        self.d = dict(tcp)
        self.S = tcp["st"].shape[0]
        self.sock = sock
        self.mask = mask

    def g(self, k):
        col = jnp.where(self.mask, self.sock, 0)
        if k in _I64_SET:
            return tb_join(get_col(self.d[k + "_hi"], col),
                           get_col(self.d[k + "_lo"], col))
        return get_col(self.d[k], col)

    def s(self, k, val, where=None):
        m = self.mask if where is None else (self.mask & where)
        if k in _I64_SET:
            hi, lo = tb_split(jnp.asarray(val, jnp.int64))
            self.d[k + "_hi"] = set_col(self.d[k + "_hi"], self.sock, hi, m)
            self.d[k + "_lo"] = set_col(self.d[k + "_lo"], self.sock, lo, m)
            return
        self.d[k] = set_col(self.d[k], self.sock, val, m)


class Notif(NamedTuple):
    """Per-round, per-host transport→app notification (descriptor status
    bits analogue)."""

    sock: jnp.ndarray   # i32 [H]
    flags: jnp.ndarray  # i32 [H] bitmask of N_*
    meta: jnp.ndarray   # i32 [H] message meta (N_MSG / N_DGRAM)
    meta2: jnp.ndarray  # i32 [H] second dgram meta
    dlen: jnp.ndarray   # i32 [H] stream/dgram bytes delivered
    space: jnp.ndarray  # i32 [H] send-buffer space (N_SPACE)


def notif_none(n_hosts: int) -> Notif:
    z = jnp.zeros(n_hosts, jnp.int32)
    return Notif(z, z, z, z, z, z)


def _notify(nf: Notif, mask, sock, flag, meta=None, meta2=None, dlen=None, space=None) -> Notif:
    upd = lambda cur, v: jnp.where(mask, jnp.asarray(v, jnp.int32), cur)
    return Notif(
        sock=upd(nf.sock, sock),
        flags=jnp.where(mask, nf.flags | flag, nf.flags),
        meta=nf.meta if meta is None else upd(nf.meta, meta),
        meta2=nf.meta2 if meta2 is None else upd(nf.meta2, meta2),
        dlen=nf.dlen if dlen is None else upd(nf.dlen, dlen),
        space=nf.space if space is None else upd(nf.space, space),
    )


# --------------------------------------------------------------------------
# Packet emission
# --------------------------------------------------------------------------
def pack_meta(src_sock, dst_sock, flags):
    return (
        jnp.asarray(src_sock, jnp.int32)
        | (jnp.asarray(dst_sock, jnp.int32) << 8)
        | (jnp.asarray(flags, jnp.int32) << 16)
    )


def _emit(st, ctx, r: Sock, mask, flags, seq, length, mend, mmeta, now):
    """Emit one segment per host where mask: NIC stamp + outbox append.

    Caller must have established outbox space. Returns engine state.
    """
    p = payload(
        ctx.n_hosts, ctx.hosts, pack_meta(r.sock, r.g("peer_sock"), flags),
        seq, r.g("rcv_nxt"), jnp.asarray(length, jnp.int32),
        ctx.params.rcvbuf, mend, mmeta,
    )
    wire = jnp.asarray(length, jnp.int64) + WIRE_OVERHEAD
    nic, depart, sent, red = tx_stamp(
        st.model.nic, mask, wire, now, ctx.bw_up,
        ctx.tx_qlen_ns if ctx.has_tx_qlen else None,
        aqm=ctx_aqm(ctx),
    )
    k = jnp.full(ctx.n_hosts, K_PKT, jnp.int32)
    # A queue-dropped segment (tail or RED) behaves exactly like path loss:
    # sequence state advanced, packet never routed — retransmission recovers.
    outbox, ok = outbox_append(st.outbox, sent, r.g("peer_host"), k, depart, p)
    m = st.metrics
    st = st._replace(
        model=st.model._replace(nic=nic), outbox=outbox,
        metrics=m._replace(
            nic_tx_drops=m.nic_tx_drops
            + (mask & ~sent & ~red).sum(dtype=jnp.int64),
            nic_aqm_drops=m.nic_aqm_drops + red.sum(dtype=jnp.int64),
            # tcp_flush checks outbox_space before every segment, so this
            # "cannot" fire — but a vanishing segment with no counter would
            # be the worst possible failure mode, and the oracle counts it.
            ob_overflow=m.ob_overflow + (sent & ~ok).sum(dtype=jnp.int64),
        ),
    )
    if st.links is not None:
        # Link plane: egress-edge attribution of the drop-tail losses that
        # never reach route_outbox (same rule as net.udp_send).
        from shadow1_tpu.telemetry.links import link_nic_drops

        st = st._replace(links=link_nic_drops(
            st.links, ctx, mask & ~sent & ~red, r.g("peer_host")))
    return st


from shadow1_tpu.core.engine import push_local_event as _push_local  # noqa: E402


# --------------------------------------------------------------------------
# Flush: packetize [snd_nxt, limit) — data, SYN, FIN — up to send_burst segs.
# --------------------------------------------------------------------------
_SENDABLE = TCP_SENDABLE_STATES


def _state_in(state, states):
    m = jnp.zeros_like(state, dtype=bool)
    for s in states:
        m = m | (state == s)
    return m


def tcp_flush(st, ctx, mask, sock, now):
    """Send as many pending segments of ``sock`` as burst/window/outbox
    allow; schedule K_TX_RESUME to continue if still pending — annotated
    ``phase:tcp_flush`` for the performance attribution plane (the flush
    machine is the single largest source in the deliver-pass op census,
    docs/PERF.md; the scope makes it visible in device traces and in
    tools/opcensus.py's per-source table)."""
    with jax.named_scope("phase:tcp_flush"):
        return _tcp_flush(st, ctx, mask, sock, now)


def _tcp_flush(st, ctx, mask, sock, now):
    """Send as many pending segments of ``sock`` as burst/window/outbox
    allow; schedule K_TX_RESUME to continue if still pending.

    Bit-exact vectorization of the former per-segment loop (round-4 op-count
    trim): socket state is gathered ONCE, the burst recurrence (sequence
    advance, window/outbox budget, message-boundary truncation, NIC clock,
    RED coins) runs as cheap [H]-vector arithmetic per lane, and every heavy
    tensor write — the outbox append, the TCP field writes, the timer-event
    push — happens ONCE for the whole burst. Per-segment outputs (depart
    stamps, packet counters, RNG draws, drop decisions) replicate the loop's
    order exactly, so results are identical to the reference per-segment
    semantics (src/main/host/descriptor/tcp.c tcp_flush, SURVEY §3.4) and to
    the CPU oracle.
    """
    pr = ctx.params
    B = pr.send_burst
    H = ctx.n_hosts
    tcp = st.model.tcp
    sock_safe = jnp.where(mask, sock, 0)

    def g(f):
        return get_col(tcp[f], sock_safe)

    def g64(f):
        return tb_join(get_col(tcp[f + "_hi"], sock_safe),
                       get_col(tcp[f + "_lo"], sock_safe))

    state = g("st")
    sendable = mask & _state_in(state, _SENDABLE)
    snd_una = g("snd_una")
    nxt0 = g("snd_nxt")
    app_end, fin_p = g("app_end"), g("fin_pend")
    limit = jnp.minimum(g("cwnd"), g("peer_wnd"))
    rcv_nxt = g("rcv_nxt")
    peer_host, peer_sock = g("peer_host"), g("peer_sock")
    rto = g64("rto")
    mqv, mqe, mqm = g("mq_valid"), g("mq_end"), g("mq_meta")  # [MQ, H]
    is_synrcvd = state == TCP_SYN_RCVD

    # --- burst recurrence: cheap per-lane arithmetic, heavy ops deferred ---
    nxt = nxt0
    space = outbox_space(st.outbox)
    nic_run = st.model.nic  # threaded through tx_stamp lane by lane
    aqm = ctx_aqm(ctx)
    qlen = ctx.tx_qlen_ns if ctx.has_tx_qlen else None
    now64 = jnp.asarray(now, jnp.int64)
    ts_taken = g("ts_act")
    rtx_armed = g64("rtx_t") != 0
    lanes = []  # per-lane (sent, depart, seq, length, flags, mend, mmeta)
    n_tx_drop = jnp.zeros((), jnp.int64)
    n_red = jnp.zeros((), jnp.int64)
    # Link plane: per-HOST drop-tail counts across the burst lanes (each
    # host flushes one sock per call, so peer_host is the egress edge for
    # every lane). None when the plane is off — zero extra traced ops.
    tx_drop_h = jnp.zeros(H, jnp.int64) if st.links is not None else None
    ts_seq = g("ts_seq")
    ts_time = g64("ts_time")
    ts_first = jnp.zeros(H, bool)  # any lane took the RTT sample
    arm_any = jnp.zeros(H, bool)
    for _ in range(B):
        pending = (nxt - (app_end + fin_p)) < 0
        flight = nxt - snd_una
        can = sendable & pending & (flight < limit) & (space > 0)
        seg_syn = can & (nxt == 0)
        seg_fin = can & ~seg_syn & (nxt == app_end) & (fin_p == 1)
        seg_data = can & ~seg_syn & ~seg_fin
        length = jnp.where(
            seg_data,
            jnp.minimum(jnp.minimum(pr.mss, app_end - nxt), limit - flight),
            0,
        )
        flags = jnp.where(
            seg_syn,
            jnp.where(is_synrcvd, F_SYN | F_ACK, F_SYN),
            jnp.where(seg_fin, F_FIN | F_ACK, F_ACK),
        )
        # Message boundary riding this segment (truncating segmentation —
        # see tcp_send): min mq end in (nxt, nxt+len].
        seg_hi = nxt + length
        inrange = (
            mqv & ((mqe - nxt[None, :]) > 0) & ((mqe - seg_hi[None, :]) <= 0)
        )
        has_m = seg_data & inrange.any(axis=0)
        dist = jnp.where(inrange, mqe - nxt[None, :], jnp.int32(2**31 - 1))
        # Nearest boundary via min-reduce + equality one-hot (no argmin —
        # core/dense.py). Ends are distinct while valid, so `near` is
        # one-hot among inrange slots.
        dmin = dist.min(axis=0)
        near = inrange & (dist == dmin[None, :])
        mend = jnp.where(has_m, extract_col(near, mqe), 0)
        mmeta = jnp.where(has_m, extract_col(near, mqm), 0)
        length = jnp.where(has_m, dmin, length)
        # NIC uplink reservation per lane — tx_stamp itself (pure [H]-vector
        # arithmetic) threaded on a running NicState, so RED/drop-tail
        # semantics have exactly one source of truth (net/nic.py).
        wire = length.astype(jnp.int64) + WIRE_OVERHEAD
        nic_run, depart, sent, red = tx_stamp(
            nic_run, can, wire, now64, ctx.bw_up, qlen, aqm=aqm
        )
        n_tx_drop = n_tx_drop + (can & ~sent & ~red).sum(dtype=jnp.int64)
        n_red = n_red + red.sum(dtype=jnp.int64)
        if tx_drop_h is not None:
            tx_drop_h = tx_drop_h + (can & ~sent & ~red)
        lanes.append((sent, depart, nxt, length, flags, mend, mmeta))
        new_nxt = nxt + length + jnp.where(seg_syn | seg_fin, 1, 0)
        # RTT sample (Karn): first sample-taking segment of the burst wins.
        take_ts = can & ~ts_taken
        ts_seq = jnp.where(take_ts, new_nxt, ts_seq)
        ts_time = jnp.where(take_ts, now64, ts_time)
        ts_taken = ts_taken | take_ts
        ts_first = ts_first | take_ts
        arm_any = arm_any | (can & ~rtx_armed)
        rtx_armed = rtx_armed | can
        nxt = jnp.where(can, new_nxt, nxt)
        space = space - sent.astype(jnp.int32)
    # NOTE: `sent` excludes RED/queue drops, but `can` advanced nxt — a
    # dropped segment behaves exactly like path loss (state advanced,
    # packet never routed; retransmission recovers), as before.

    # --- one batched outbox append for the whole burst -------------------
    ob = st.outbox
    cap = ob.dst.shape[0]
    sent_l = jnp.stack([l[0] for l in lanes])                 # [B, H]
    rank = jnp.cumsum(sent_l, axis=0) - sent_l.astype(jnp.int32)
    pos = ob.cnt[None, :] + rank                              # [B, H]
    ok_l = sent_l & (pos < cap)
    n_new = sent_l.sum(axis=0, dtype=jnp.int32)
    slots = jnp.arange(cap, dtype=jnp.int32)[None, :, None]   # [1, P, 1]
    sel = ok_l[:, None, :] & (pos[:, None, :] == slots)       # [B, P, H]
    written = sel.any(axis=0)                                 # [P, H]

    def merge(old, lane_vals, dt):
        lv = jnp.stack(lane_vals).astype(dt)                  # [B, (NP,) H]
        if lv.ndim == 2:
            new = (sel * lv[:, None, :]).sum(axis=0, dtype=dt)
            return jnp.where(written, new, old)
        # payload lanes [B, NP, H] -> [NP, P, H]
        new = (sel[:, None, :, :] * lv[:, :, None, :]).sum(axis=0, dtype=dt)
        return jnp.where(written[None], new, old)

    dstL = [jnp.where(l[0], peer_host, 0) for l in lanes]
    # Departure times split to the outbox's i32 (hi, lo) planes at the
    # [H]-vector level (core/outbox.py layout; lo is sign-flipped but the
    # one-hot masked-sum merge is sign-agnostic). The ctr plane is the low
    # word of pkt_ctr (exact below 2**31 pkts/host).
    depL = [tb_split(l[1]) for l in lanes]
    dhiL = [d[0] for d in depL]
    dloL = [d[1] for d in depL]
    ctrL = [ob.pkt_ctr.astype(jnp.int32) + rank[i] for i in range(B)]
    pL = []
    p1 = pack_meta(sock, peer_sock, 0)
    for (snt, dep, seq, length, flags, mend, mmeta) in lanes:
        pL.append(payload(
            H, ctx.hosts, p1 | (flags << 16), seq, rcv_nxt, length,
            pr.rcvbuf, mend, mmeta,
        ))
    ob = ob._replace(
        dst=merge(ob.dst, dstL, jnp.int32),
        kind=jnp.where(written, K_PKT, ob.kind),
        depart_hi=merge(ob.depart_hi, dhiL, jnp.int32),
        depart_lo=merge(ob.depart_lo, dloL, jnp.int32),
        ctr=merge(ob.ctr, ctrL, jnp.int32),
        p=merge(ob.p, pL, jnp.int32),
        cnt=ob.cnt + n_new,
        pkt_ctr=ob.pkt_ctr + n_new.astype(jnp.int64),
    )
    n_ob_over = (sent_l & ~ok_l).sum(dtype=jnp.int64)

    # --- one batched write-back of the TCP fields ------------------------
    # nxt advanced where any lane's `can` held — including RED/queue-dropped
    # segments (can & ~sent). Track it directly:
    adv = nxt != nxt0
    d = dict(tcp)
    d["snd_nxt"] = set_col(d["snd_nxt"], sock, nxt, mask & adv)
    smax0 = g("snd_max")
    d["snd_max"] = set_col(
        d["snd_max"], sock, jnp.where((nxt - smax0) > 0, nxt, smax0),
        mask & adv,
    )
    d["ts_act"] = set_col(d["ts_act"], sock, True, mask & ts_first)
    d["ts_seq"] = set_col(d["ts_seq"], sock, ts_seq, mask & ts_first)
    tshi, tslo = tb_split(ts_time)
    d["ts_time_hi"] = set_col(d["ts_time_hi"], sock, tshi, mask & ts_first)
    d["ts_time_lo"] = set_col(d["ts_time_lo"], sock, tslo, mask & ts_first)
    rthi, rtlo = tb_split(now64 + rto)
    d["rtx_t_hi"] = set_col(d["rtx_t_hi"], sock, rthi, mask & arm_any)
    d["rtx_t_lo"] = set_col(d["rtx_t_lo"], sock, rtlo, mask & arm_any)
    timer_armed0 = get_col(tcp["timer_armed"], sock_safe)
    need_ev = arm_any & ~timer_armed0
    d["timer_armed"] = set_col(d["timer_armed"], sock, True, mask & need_ev)

    m = st.metrics
    st = st._replace(
        model=st.model._replace(tcp=d, nic=nic_run),
        outbox=ob,
        metrics=m._replace(
            nic_tx_drops=m.nic_tx_drops + n_tx_drop,
            nic_aqm_drops=m.nic_aqm_drops + n_red,
            ob_overflow=m.ob_overflow + n_ob_over,
        ),
    )
    if tx_drop_h is not None:
        from shadow1_tpu.telemetry.links import link_nic_drops

        st = st._replace(links=link_nic_drops(
            st.links, ctx, tx_drop_h, peer_host))
    st = _push_local(st, ctx, need_ev, now64 + rto, K_TCP_TIMER, p0=sock)

    # Still pending but couldn't send → one TX_RESUME per sock (deduped).
    total_end = app_end + fin_p
    pending = (nxt - total_end) < 0
    wnd_ok = (nxt - snd_una) < limit
    blocked_outbox = outbox_space(st.outbox) <= 0
    txr0 = get_col(st.model.tcp["txr"], sock_safe)
    more = sendable & pending & wnd_ok & (txr0 == 0)
    # Outbox-blocked sends resume at the next window start (after drain);
    # burst-limited sends resume immediately (same timestamp, next round).
    t_resume = jnp.where(
        blocked_outbox, (now // ctx.window + 1) * ctx.window, now
    )
    d2 = dict(st.model.tcp)
    d2["txr"] = set_col(d2["txr"], sock, 1, more)
    st = st._replace(model=st.model._replace(tcp=d2))
    return _push_local(st, ctx, more, t_resume, K_TX_RESUME, p0=sock)


def _ack_now(st, ctx, mask, sock, now):
    """Emit an immediate pure ACK (no data, no seq consumption)."""
    r = Sock(st.model.tcp, sock, mask)
    can = mask & (outbox_space(st.outbox) > 0)
    z = jnp.zeros(ctx.n_hosts, jnp.int32)
    return _emit(st, ctx, r, can, jnp.full(ctx.n_hosts, F_ACK, jnp.int32),
                 r.g("snd_nxt"), z, z, z, now)


# --------------------------------------------------------------------------
# App-facing API (vectorized, masked)
# --------------------------------------------------------------------------
def tcp_listen(st, ctx, mask, sock):
    r = Sock(st.model.tcp, sock, mask)
    r.s("st", TCP_LISTEN)
    return st._replace(model=st.model._replace(tcp=r.d))


def _init_conn(r: Sock, ctx, mask, peer_host, peer_sock, state, rcv_nxt):
    pr = ctx.params
    r.s("st", state, mask)
    r.s("peer_host", peer_host, mask)
    r.s("peer_sock", peer_sock, mask)
    r.s("snd_una", 0, mask)
    r.s("snd_nxt", 0, mask)
    r.s("snd_max", 0, mask)
    r.s("rcv_nxt", rcv_nxt, mask)
    r.s("app_end", 1, mask)
    r.s("fin_pend", 0, mask)
    r.s("cwnd", pr.init_cwnd_mss * pr.mss, mask)
    r.s("ssthresh", SSTHRESH_INIT, mask)
    r.s("peer_wnd", pr.mss, mask)  # lets the SYN go out; real wnd learned on first ACK
    r.s("srtt", 0, mask)
    r.s("rttvar", 0, mask)
    r.s("rto", pr.rto_init, mask)
    r.s("rtx_t", 0, mask)
    r.s("dupacks", 0, mask)
    r.s("recover", 0, mask)
    r.s("ts_act", False, mask)
    r.s("txr", 0, mask)
    mq = jnp.where(mask[None, :], False, r.g("mq_valid"))
    r.s("mq_valid", mq, mask)


def tcp_connect(st, ctx, mask, sock, dst_host, dst_sock, now):
    r = Sock(st.model.tcp, sock, mask)
    _init_conn(r, ctx, mask, dst_host, dst_sock, TCP_SYN_SENT, 0)
    st = st._replace(model=st.model._replace(tcp=r.d))
    return tcp_flush(st, ctx, mask, sock, now)


def tcp_send(st, ctx, mask, sock, nbytes, meta, now):
    """Queue up to ``nbytes`` on the socket (clamped to send-buffer space);
    attach ``meta`` as a message boundary at the end iff fully queued and
    meta != 0. Returns (st, accepted[H])."""
    pr = ctx.params
    r = Sock(st.model.tcp, sock, mask)
    snd_una, app_end = r.g("snd_una"), r.g("app_end")
    buffered = (app_end - snd_una) - (snd_una == 0).astype(jnp.int32)
    space = jnp.maximum(pr.sndbuf - buffered, 0)
    accepted = jnp.clip(jnp.asarray(nbytes, jnp.int32), 0, space)
    accepted = jnp.where(mask, accepted, 0)
    new_end = app_end + accepted
    r.s("app_end", new_end, accepted > 0)
    # Message boundary bookkeeping.
    want_meta = mask & (accepted > 0) & (accepted == nbytes) & (jnp.asarray(meta, jnp.int32) != 0)
    mqv = r.g("mq_valid")                       # [MQ, H]
    has_free, slot = first_true_idx(~mqv)
    ok = want_meta & has_free
    # Dense (slot, sock, host) one-hot write — no 3D scatter (core/dense.py).
    sel = (
        onehot_col(slot, mqv.shape[0])[:, None, :]
        & onehot_col(r.sock, r.S, ok)[None, :, :]
    )
    r.d["mq_valid"] = r.d["mq_valid"] | sel
    r.d["mq_end"] = jnp.where(sel, new_end[None, None, :], r.d["mq_end"])
    r.d["mq_meta"] = jnp.where(
        sel, jnp.asarray(meta, jnp.int32)[None, None, :], r.d["mq_meta"]
    )
    st = st._replace(model=st.model._replace(tcp=r.d))
    st = tcp_flush(st, ctx, mask & (accepted > 0), sock, now)
    return st, accepted


def tcp_close(st, ctx, mask, sock, now):
    r = Sock(st.model.tcp, sock, mask)
    state = r.g("st")
    est = mask & (state == TCP_ESTABLISHED)
    cw = mask & (state == TCP_CLOSE_WAIT)
    r.s("st", TCP_FIN_WAIT_1, est)
    r.s("st", TCP_LAST_ACK, cw)
    r.s("fin_pend", 1, est | cw)
    st = st._replace(model=st.model._replace(tcp=r.d))
    return tcp_flush(st, ctx, est | cw, sock, now)


# --------------------------------------------------------------------------
# Receive path — one packet per host per round, all hosts in parallel.
# Mirrors the sequencing of the reference's tcp_processPacket (SURVEY §3.4):
# connection demux → ACK processing (cwnd/RTT/retransmit) → payload →
# FIN → immediate ACK, then app notifications.
# --------------------------------------------------------------------------
_CONN_STATES = TCP_CONN_STATES
_RCV_STATES = TCP_RCV_STATES


def tcp_rx(st, ctx, mask, p, now):
    """Process one arrived TCP segment per host where ``mask``.

    Returns (st, Notif). ``now`` is the per-host event time vector.
    """
    pr = ctx.params
    H = ctx.n_hosts
    src = p[0]
    packed = p[1]
    ss = packed & 0xFF
    ds = (packed >> 8) & 0xFF
    flags = (packed >> 16) & 0xFF
    seq, ackno, length = p[2], p[3], p[4]
    wnd, mend, mmeta = p[5], p[6], p[7]
    is_syn = (flags & F_SYN) != 0
    is_ack = (flags & F_ACK) != 0
    is_fin = (flags & F_FIN) != 0
    nf = notif_none(H)

    # ---- passive open: SYN → LISTEN socket spawns a child (tcp.c accept
    # path). Whole block under lax.cond: bare SYNs exist only while
    # connections open, and the block carries a full tcp_flush (the SYN|ACK
    # emit) — dead weight in every steady-state deliver round otherwise.
    tcp = st.model.tcp
    r0 = Sock(tcp, ds, mask)
    syn_to_listen = mask & is_syn & ~is_ack & (r0.g("st") == TCP_LISTEN)

    def _accept(st):
        tcp = st.model.tcp
        dup = (
            (tcp["peer_host"] == src[None, :])
            & (tcp["peer_sock"] == ss[None, :])
            & (tcp["st"] != TCP_FREE)
            & (tcp["st"] != TCP_LISTEN)
        ).any(axis=0)
        free = tcp["st"] == TCP_FREE
        # Children take the HIGHEST free slot: low slots are app-owned (0 =
        # listener, 1 = client socket on dual-role hosts) and may be
        # TCP_FREE between uses — allocating from the top keeps them
        # unclobbered. Max-reduce, not argmax (core/dense.py).
        new_conn0, child = last_true(free)
        new_conn = syn_to_listen & ~dup & new_conn0
        rc = Sock(tcp, child, new_conn)
        _init_conn(rc, ctx, new_conn, src, ss, TCP_SYN_RCVD, 1)
        rc.s("peer_wnd", wnd, new_conn)
        st = st._replace(model=st.model._replace(tcp=rc.d))
        return tcp_flush(st, ctx, new_conn, child, now)  # emits SYN|ACK

    st = jax.lax.cond(syn_to_listen.any(), _accept, lambda s: s, st)

    # ---- established-path demux: peer must match (guards stale/reused slots)
    r = Sock(st.model.tcp, ds, mask)
    state = r.g("st")
    # A client in SYN_SENT connected to the *listener*; the SYN|ACK arrives
    # from the freshly-spawned child socket — accept it by host only and
    # learn the true peer socket from it.
    learn_peer = (state == TCP_SYN_SENT) & is_syn & is_ack
    v = (
        mask
        & ~syn_to_listen
        & _state_in(state, _CONN_STATES)
        & (r.g("peer_host") == src)
        & ((r.g("peer_sock") == ss) | learn_peer)
    )
    r.s("peer_sock", ss, v & learn_peer)
    r.s("peer_wnd", jnp.maximum(wnd, 1), v & is_ack)

    # ---- ACK processing. Acceptance tests against snd_max (highest ever
    # sent), NOT the possibly-rewound snd_nxt — see the snd_max note at
    # _FIELDS_I32 (outage-recovery deadlock otherwise).
    a = v & is_ack
    snd_una, snd_nxt = r.g("snd_una"), r.g("snd_nxt")
    snd_max = r.g("snd_max")
    new_ack = a & ((ackno - snd_una) > 0) & ((ackno - snd_max) <= 0)
    # RTT sample (RFC6298, integer ns; err>>3 is floor division by 8).
    ts_ok = new_ack & r.g("ts_act") & ((ackno - r.g("ts_seq")) >= 0)
    rtt = jnp.maximum(now - r.g("ts_time"), 1)
    first = r.g("srtt") == 0
    err = rtt - r.g("srtt")
    srtt_n = jnp.where(first, rtt, r.g("srtt") + (err >> 3))
    rttvar_n = jnp.where(first, rtt // 2, r.g("rttvar") + ((jnp.abs(err) - r.g("rttvar")) >> 2))
    rto_n = jnp.clip(srtt_n + jnp.maximum(4 * rttvar_n, 1_000_000), pr.rto_min, pr.rto_max)
    r.s("srtt", srtt_n, ts_ok)
    r.s("rttvar", rttvar_n, ts_ok)
    r.s("rto", rto_n, ts_ok)
    r.s("ts_act", False, ts_ok)
    # cwnd growth: slow start below ssthresh, else AIMD (tcp_cong_reno.c).
    cwnd = r.g("cwnd")
    grow = jnp.where(
        cwnd < r.g("ssthresh"), pr.mss, jnp.maximum((pr.mss * pr.mss) // jnp.maximum(cwnd, 1), 1)
    )
    r.s("cwnd", jnp.minimum(cwnd + grow, CWND_MAX), new_ack)
    r.s("snd_una", ackno, new_ack)
    # An ACK beyond the rewound snd_nxt pulls it forward: those bytes were
    # sent (snd_max proves it) and are now acked — never resend them.
    r.s("snd_nxt", ackno, new_ack & ((ackno - snd_nxt) > 0))
    r.s("dupacks", 0, new_ack)
    # Retire message boundaries the peer has fully acked.
    keep = r.g("mq_valid") & ((r.g("mq_end") - ackno[None, :]) > 0)
    r.s("mq_valid", keep, new_ack)
    # Restart (or clear) the retransmit deadline.
    outstanding = (snd_max - ackno) > 0
    r.s("rtx_t", jnp.where(outstanding, now + r.g("rto"), 0), new_ack)

    # State transitions driven by this ACK.
    est_sr = new_ack & (state == TCP_SYN_RCVD)
    r.s("st", TCP_ESTABLISHED, est_sr)
    nf = _notify(nf, est_sr, ds, N_ACCEPTED)
    est_ss = a & is_syn & (state == TCP_SYN_SENT) & (ackno == 1)
    r.s("st", TCP_ESTABLISHED, est_ss)
    r.s("rcv_nxt", 1, est_ss)
    nf = _notify(nf, est_ss, ds, N_ESTABLISHED)
    total_end = r.g("app_end") + r.g("fin_pend")
    fin_acked = new_ack & (r.g("fin_pend") == 1) & (ackno == total_end)
    r.s("st", TCP_FIN_WAIT_2, fin_acked & (state == TCP_FIN_WAIT_1))
    closed_by_ack = fin_acked & ((state == TCP_CLOSING) | (state == TCP_LAST_ACK))
    nf = _notify(nf, closed_by_ack, ds, N_CLOSED)
    sp = new_ack & ((state == TCP_ESTABLISHED) | (state == TCP_CLOSE_WAIT)) & ~closed_by_ack
    space = pr.sndbuf - (r.g("app_end") - ackno)
    nf = _notify(nf, sp, ds, N_SPACE, space=space)

    # Duplicate ACKs → fast retransmit (Go-Back-N rewind) at the threshold.
    dup_a = a & ~new_ack & (ackno == snd_una) & outstanding & (length == 0) & ~is_syn & ~is_fin
    dp = r.g("dupacks") + 1
    r.s("dupacks", dp, dup_a)
    frx = dup_a & (dp == pr.dupack_thresh) & ((snd_una - r.g("recover")) >= 0)
    flight = snd_nxt - snd_una
    ssth = jnp.maximum(flight // 2, 2 * pr.mss)
    r.s("ssthresh", ssth, frx)
    r.s("cwnd", ssth, frx)
    r.s("recover", snd_nxt, frx)
    r.s("snd_nxt", snd_una, frx)
    r.s("ts_act", False, frx)

    st = st._replace(model=st.model._replace(tcp=r.d))
    met = st.metrics
    st = st._replace(metrics=met._replace(
        tcp_fast_rtx=met.tcp_fast_rtx + frx.sum(dtype=jnp.int64)))
    st = tcp_flush(st, ctx, new_ack | frx, ds, now)

    # ---- payload (in-order only: Go-Back-N receiver) and FIN
    r = Sock(st.model.tcp, ds, mask)
    state2 = r.g("st")
    can_rcv = v & _state_in(state2, _RCV_STATES)
    has_data = can_rcv & (length > 0)
    in_order = has_data & (seq == r.g("rcv_nxt"))
    r.s("rcv_nxt", r.g("rcv_nxt") + length, in_order)
    nf = _notify(nf, in_order, ds, N_DATA, dlen=length)
    msg = in_order & (mend != 0)
    nf = _notify(nf, msg, ds, N_MSG, meta=mmeta)
    # FIN: in order once preceding data (if any) is consumed. The teardown
    # block runs under lax.cond — FINs appear only at stream close, and
    # every deliver round otherwise paid its state machinery for nothing.
    def _fin(rd, nf):
        r2 = Sock(rd, ds, mask)
        fin_here = v & is_fin & ((seq + length) == r2.g("rcv_nxt")) & _state_in(
            state2, (TCP_ESTABLISHED, TCP_FIN_WAIT_1, TCP_FIN_WAIT_2)
        )
        r2.s("rcv_nxt", r2.g("rcv_nxt") + 1, fin_here)
        to_cw = fin_here & (state2 == TCP_ESTABLISHED)
        r2.s("st", TCP_CLOSE_WAIT, to_cw)
        nf2 = _notify(nf, to_cw, ds, N_PEER_FIN)
        to_closing = fin_here & (state2 == TCP_FIN_WAIT_1)
        r2.s("st", TCP_CLOSING, to_closing)
        closed_by_fin = fin_here & (state2 == TCP_FIN_WAIT_2)
        nf2 = _notify(nf2, closed_by_fin, ds, N_CLOSED)
        return r2.d, nf2, closed_by_fin

    rd, nf, closed_by_fin = jax.lax.cond(
        (v & is_fin).any(), _fin,
        lambda rd, nf: (rd, nf, jnp.zeros_like(v)),
        dict(r.d), nf,
    )
    r = Sock(rd, ds, mask)

    # Free fully-closed sockets (slot reuse; stale packets are dropped by the
    # peer-match guard above).
    freed = closed_by_ack | closed_by_fin
    r.s("st", TCP_FREE, freed)
    r.s("rtx_t", 0, freed)

    # Immediate ACK policy: ack any data (dup-ACK for OOO), any FIN (in or
    # out of order), and the final step of the client handshake.
    need_ack = has_data | (v & is_fin) | est_ss
    st = st._replace(model=st.model._replace(tcp=r.d))
    st = _ack_now(st, ctx, need_ack, ds, now)
    met = st.metrics
    st = st._replace(metrics=met._replace(
        tcp_ooo_drops=met.tcp_ooo_drops + (has_data & ~in_order).sum(dtype=jnp.int64)))
    return st, nf


# --------------------------------------------------------------------------
# Timer + TX-resume event handlers
# --------------------------------------------------------------------------
def on_tcp_timer(st, ctx, ev):
    """K_TCP_TIMER: lazy single-event-per-socket retransmit timer.

    The event is a *check*: if the deadline moved into the future (ACKs
    restarted it) the event re-arms itself at the new deadline; if the
    deadline is gone it dies; else → RTO: multiplicative backoff, cwnd to
    one segment, Go-Back-N rewind, retransmit (tcp.c retransmit timer).
    """
    pr = ctx.params
    m = ev.mask & (ev.kind == K_TCP_TIMER)
    sock = ev.p[0]
    now = ev.time
    r = Sock(st.model.tcp, sock, m)
    r.s("timer_armed", False, m)
    deadline = r.g("rtx_t")
    live = m & (deadline != 0)
    future = live & (now < deadline)
    r.s("timer_armed", True, future)
    fire = live & ~future
    outstanding = (r.g("snd_max") - r.g("snd_una")) > 0
    rto_fire = fire & outstanding & _state_in(r.g("st"), _SENDABLE)
    flight = r.g("snd_nxt") - r.g("snd_una")
    r.s("ssthresh", jnp.maximum(flight // 2, 2 * pr.mss), rto_fire)
    r.s("cwnd", pr.mss, rto_fire)
    rto_n = jnp.minimum(r.g("rto") * 2, pr.rto_max)
    r.s("rto", rto_n, rto_fire)
    r.s("snd_nxt", r.g("snd_una"), rto_fire)
    r.s("ts_act", False, rto_fire)
    r.s("dupacks", 0, rto_fire)
    r.s("recover", r.g("snd_una"), rto_fire)
    r.s("rtx_t", now + rto_n, rto_fire)
    r.s("timer_armed", True, rto_fire)
    r.s("rtx_t", 0, fire & ~rto_fire)
    st = st._replace(model=st.model._replace(tcp=r.d))
    met = st.metrics
    st = st._replace(metrics=met._replace(
        tcp_rto=met.tcp_rto + rto_fire.sum(dtype=jnp.int64)))
    # One pending event per socket: re-push at whichever deadline applies.
    repush = future | rto_fire
    t_ev = jnp.where(future, deadline, now + rto_n)
    st = _push_local(st, ctx, repush, t_ev, K_TCP_TIMER, p0=sock)
    return tcp_flush(st, ctx, rto_fire, sock, now)


def on_tx_resume(st, ctx, ev):
    """K_TX_RESUME: continue a burst- or outbox-bounded flush."""
    m = ev.mask & (ev.kind == K_TX_RESUME)
    sock = ev.p[0]
    r = Sock(st.model.tcp, sock, m)
    r.s("txr", 0, m)
    st = st._replace(model=st.model._replace(tcp=r.d))
    return tcp_flush(st, ctx, m, sock, ev.time)
