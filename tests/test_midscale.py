"""Mid-scale tier: 512–1024-host parity + capacity validation (slow).

VERDICT r2 #8: parity/capacity testing stopped at 24 hosts while the rung
configs run at 1k–10k — rung scale was first exercised by the benchmark.
This tier puts a capacity-validated, engine-vs-oracle-exact run at 512+
hosts in CI (marked slow; deselect with ``-m 'not slow'``).
"""

import numpy as np
import pytest

from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import MS, SEC, EngineParams
from shadow1_tpu.core.engine import Engine
from shadow1_tpu.cpu_engine import CpuEngine

pytestmark = pytest.mark.slow

KEYS = [
    "events", "pkts_sent", "pkts_delivered", "pkts_lost",
    "ev_overflow", "ob_overflow", "tcp_fast_rtx", "tcp_rto", "tcp_ooo_drops",
]


def _check(exp, params, summary_keys=()):
    cpu = CpuEngine(exp, params)
    cm = cpu.run()
    cs = cpu.summary()
    eng = Engine(exp, params)
    st = eng.run()
    tm = Engine.metrics_dict(st)
    ts = eng.model_summary(st)
    # capacity contract: the configured knobs must be overflow-free
    assert tm["ev_overflow"] == 0 and tm["ob_overflow"] == 0, tm
    assert tm["round_cap_hits"] == 0
    for k in KEYS:
        assert tm[k] == cm[k], (k, tm[k], cm[k])
    for k in summary_keys:
        np.testing.assert_array_equal(np.asarray(ts[k]), np.asarray(cs[k]),
                                      err_msg=k)
    return tm, ts


def test_tgen_512_parity():
    n = 512
    exp = single_vertex_experiment(
        n_hosts=n, seed=21, end_time=10 * SEC, latency_ns=20 * MS,
        loss=0.002, bw_bits=10**7, model="net",
        model_cfg={
            "app": "tgen",
            "active": np.ones(n, np.int64),
            "streams": np.full(n, 2, np.int64),
            "mean_bytes": np.full(n, 30_000.0, np.float64),
            "mean_think_ns": np.full(n, float(500 * MS), np.float64),
            "start_time": np.full(n, 1 * MS, np.int64),
        },
    )
    tm, ts = _check(exp, EngineParams(ev_cap=256, sockets_per_host=32),
                    summary_keys=("rx_bytes", "streams_done", "done_time"))
    assert int(ts["total_streams_done"]) == 2 * n  # workload completed
    assert tm["tcp_rto"] + tm["tcp_fast_rtx"] > 0  # loss actually exercised


def test_bitcoin_1k_parity():
    n = 1024
    exp_doc = {
        # 7 s: the flood needs ~4.5 s after the last tx injection (the
        # round-3 arrival-tb semantics shifted propagation by one window
        # for a handful of (tx, node) pairs, which 6 s just missed).
        "general": {"seed": 55, "stop_time": "7 s"},
        "engine": {"scheduler": "tpu", "ev_cap": 256, "sockets_per_host": 32,
                   "msgq_cap": 64},
        "network": {"single_vertex": {"latency": "50 ms"}},
        "hosts": [{"name": "node", "count": n,
                   "bandwidth_up": "50 Mbit", "bandwidth_down": "50 Mbit"}],
        "app": {"model": "bitcoin",
                "params": {"graph": {"kind": "ring_chord", "k": 8},
                           "tx": {"count": 12, "start": "2 s",
                                  "interval": "200 ms"},
                           "tx_size": 400}},
    }
    from shadow1_tpu.config.experiment import build_experiment

    exp, params, _ = build_experiment(exp_doc)
    tm, ts = _check(exp, params, summary_keys=("reach",))
    # every tx reaches every node (full flood propagation at this scale)
    assert int(ts["total_seen"]) == 12 * n
