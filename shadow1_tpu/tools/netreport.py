"""Network report — render and diagnose the link-telemetry stream as a
topology weathermap.

The reference's per-link capture (interface counters + pcap hooks,
src/main/routing/) is what operators read when the NETWORK — not a flow —
misbehaves: which edge is hot, which edge is losing, where drops
concentrate. This consumes the ``link`` JSONL records the link plane emits
(--link-telem on, telemetry/links.py; docs/OBSERVABILITY.md "Link
records") and produces:

* a per-edge table — offered packets/bytes, loss / outage / NIC-backlog
  drops, mean and max queued latency, sorted hottest-first;
* a V×V byte heatmap (terminal glyphs or --heatmap csv);
* the hottest path: a greedy max-byte walk across the edge graph;
* a DIAGNOSIS: loss concentration, persistent egress saturation,
  dark links (offered traffic 100% outage-dropped), elephant-edge skew
  — each naming the edges and the evidence.

Link records are CUMULATIVE snapshots (running totals per drain
boundary), so totals come from each edge's last row and rates from
consecutive-row deltas; a ``link_gap`` row marks a counter rebase (fleet
lane rebind) and resets the delta baseline.

jax-free by design (log analysis must run anywhere), like flowreport.
``--selftest`` feeds the detectors synthesized pathologies and fails
loudly if any is missed — ci.sh runs it as the network observability
smoke gate.

    python -m shadow1_tpu.tools.netreport run.log [--csv edges.csv]
        [--json] [--heatmap ascii|csv|off] [--top N]
    python -m shadow1_tpu.tools.netreport --selftest
"""

from __future__ import annotations

import argparse
import csv
import json
import sys

from shadow1_tpu.telemetry.registry import (
    LINK_FIELDS,
    REC_LINK,
    REC_LINK_GAP,
)

# Heatmap glyph ramp (log-scaled) — degrades to ASCII with --ascii.
_SHADES = " ░▒▓█"
_ASCII_SHADES = " .:*#"

# Egress saturation threshold: queued latency beyond this many windows of
# runahead means the NIC schedule spills far past the horizon.
SATURATION_WINDOWS = 4

# Elephant skew: the top edge carries ≥ this multiple of the median
# nonzero edge AND ≥ this fraction of all bytes.
ELEPHANT_RATIO = 10
ELEPHANT_SHARE = 0.25

# Loss concentration: one edge holds ≥ this fraction of all loss drops
# while at least one other edge also carries traffic.
LOSS_SHARE = 0.5


def load_records(path: str) -> list[dict]:
    recs = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                recs.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return recs


def group_edges(recs: list[dict]) -> dict[tuple, list[dict]]:
    """REC_LINK records → {(exp, src, dst): rows sorted by window}.

    Fleet logs tag rows with ``exp``; solo logs leave it absent (None
    key). Duplicate windows (a resumed run replaying a drained boundary)
    collapse to the LAST occurrence — snapshots are cumulative and
    deterministic, so replays carry identical values anyway."""
    by_key: dict[tuple, dict[int, dict]] = {}
    for r in recs:
        if r.get("type") != REC_LINK:
            continue
        key = (r.get("exp"), r.get("src_vertex"), r.get("dst_vertex"))
        by_key.setdefault(key, {})[r.get("window", 0)] = r
    return {
        k: [w[i] for i in sorted(w)]
        for k, w in sorted(
            by_key.items(),
            key=lambda kv: (kv[0][0] is not None, kv[0][0] or 0,
                            kv[0][1] or 0, kv[0][2] or 0))
    }


def _window_ns(rows: list[dict]) -> int | None:
    """The window length, recovered from one row's (window, sim_time_s)
    pair: sim_time_s = (window + 1) * window_ns / 1e9 exactly (the same
    drain convention every plane shares)."""
    for r in rows:
        w = r.get("window")
        t = r.get("sim_time_s")
        if w is not None and t is not None:
            return round(t * 1e9 / (w + 1))
    return None


def edge_deltas(rows: list[dict]) -> list[dict]:
    """Per-boundary counter deltas from one edge's cumulative snapshots.

    A counter running BACKWARD between consecutive rows marks a rebase
    (fleet lane rebind emitted a link_gap; the fresh lane restarted its
    totals) — the later row then IS the delta since its own start."""
    out = []
    prev = None
    for r in rows:
        if prev is not None and r.get("pkts", 0) >= prev.get("pkts", 0):
            d = {f: r.get(f, 0) - prev.get(f, 0) for f in LINK_FIELDS}
        else:
            d = {f: r.get(f, 0) for f in LINK_FIELDS}
        # queued_ns_max is a high-water gauge, never a rate — carry the
        # snapshot value through.
        d["queued_ns_max"] = r.get("queued_ns_max", 0)
        d["window"] = r.get("window")
        out.append(d)
        prev = r
    return out


def edge_totals(edges: dict[tuple, list[dict]]) -> dict[tuple, dict]:
    """{edge key: final cumulative counters + derived fields}."""
    out = {}
    for key, rows in edges.items():
        last = rows[-1]
        t = {f: last.get(f, 0) for f in LINK_FIELDS}
        drops = (t["loss_drops"] + t["link_down_drops"]
                 + t["nic_backlog_drops"])
        t["drops"] = drops
        t["queued_ns_avg"] = (t["queued_ns_sum"] // t["pkts"]
                              if t["pkts"] else 0)
        t["last_window"] = last.get("window")
        out[key] = t
    return out


# -- diagnosis --------------------------------------------------------------
#
# Each detector takes the grouped edge streams (plus totals) and returns a
# finding dict {"kind", "edges", ...detail} or None. They key off the
# LINK_FIELDS columns only — anything derivable from the stream, nothing
# needing the config.

def _fmt_edge(key: tuple) -> str:
    exp, src, dst = key
    tag = f"exp {exp} " if exp is not None else ""
    return f"{tag}{src}->{dst}"


def detect_loss_concentration(totals: dict[tuple, dict]) -> dict | None:
    """One edge holds the majority of all random-loss drops while other
    edges also carry traffic — the loss is topological (one bad link),
    not ambient."""
    total_loss = sum(t["loss_drops"] for t in totals.values())
    if total_loss == 0:
        return None
    top_key, top = max(totals.items(), key=lambda kv: kv[1]["loss_drops"])
    others_carry = any(t["pkts"] > 0 for k, t in totals.items()
                      if k != top_key)
    share = top["loss_drops"] / total_loss
    if share >= LOSS_SHARE and others_carry:
        return {"kind": "loss_concentration",
                "edges": [_fmt_edge(top_key)],
                "loss_drops": top["loss_drops"],
                "share": round(share, 3)}
    return None


def detect_egress_saturation(edges: dict[tuple, list[dict]],
                             window_ns: int | None) -> dict | None:
    """An edge whose max queued latency exceeds SATURATION_WINDOWS windows
    of runahead: its egress NIC is scheduled far past the horizon — the
    host behind it is offering more than the line rate drains."""
    if not window_ns:
        return None
    thresh = SATURATION_WINDOWS * window_ns
    hit = []
    for key, rows in edges.items():
        worst = max((r.get("queued_ns_max", 0) for r in rows), default=0)
        if worst > thresh:
            hit.append((key, worst))
    if not hit:
        return None
    hit.sort(key=lambda kw: -kw[1])
    return {"kind": "egress_saturation",
            "edges": [_fmt_edge(k) for k, _ in hit],
            "queued_ns_max": hit[0][1],
            "threshold_ns": thresh}


def detect_dark_links(edges: dict[tuple, list[dict]]) -> dict | None:
    """An edge where, over some snapshot interval, EVERY offered packet
    was dropped by a link outage (fault plane link_down) — the link went
    fully dark while traffic kept arriving."""
    dark = []
    for key, rows in edges.items():
        for d in edge_deltas(rows):
            if d["pkts"] > 0 and d["link_down_drops"] >= d["pkts"]:
                dark.append((key, d["window"], d["link_down_drops"]))
                break
    if not dark:
        return None
    return {"kind": "dark_link",
            "edges": [_fmt_edge(k) for k, _, _ in dark],
            "first_window": min(w for _, w, _ in dark),
            "link_down_drops": sum(n for _, _, n in dark)}


def detect_elephant_skew(totals: dict[tuple, dict]) -> dict | None:
    """The hottest edge carries an outsized share of all bytes relative to
    the median busy edge — one flow (or one aggregation point) dominates
    the fabric."""
    busy = sorted(t["bytes"] for t in totals.values() if t["bytes"] > 0)
    if len(busy) < 2:
        return None
    median = busy[len(busy) // 2]
    top_key, top = max(totals.items(), key=lambda kv: kv[1]["bytes"])
    total = sum(busy)
    if (median > 0 and top["bytes"] >= ELEPHANT_RATIO * median
            and top["bytes"] >= ELEPHANT_SHARE * total):
        return {"kind": "elephant_edge",
                "edges": [_fmt_edge(top_key)],
                "bytes": top["bytes"],
                "median_bytes": median,
                "share": round(top["bytes"] / total, 3)}
    return None


def diagnose_links(edges: dict[tuple, list[dict]],
                   window_ns: int | None = None) -> list[dict]:
    """All network findings for the grouped edge streams."""
    if window_ns is None:
        window_ns = next((w for w in
                          (_window_ns(rows) for rows in edges.values())
                          if w), None)
    totals = edge_totals(edges)
    findings = [
        detect_loss_concentration(totals),
        detect_egress_saturation(edges, window_ns),
        detect_dark_links(edges),
        detect_elephant_skew(totals),
    ]
    return [f for f in findings if f is not None]


def hottest_path(totals: dict[tuple, dict],
                 max_hops: int = 16) -> list[tuple]:
    """Greedy max-byte walk: start at the hottest edge, repeatedly follow
    the heaviest out-edge of the current head, stop on a revisit or a
    dead end. The spine elephant traffic rides through the topology."""
    if not totals:
        return []
    start_key = max(totals, key=lambda k: totals[k]["bytes"])
    if totals[start_key]["bytes"] == 0:
        return []
    exp = start_key[0]
    path = [start_key]
    seen = {start_key[1], start_key[2]}
    head = start_key[2]
    for _ in range(max_hops):
        nxt = [(k, t) for k, t in totals.items()
               if k[0] == exp and k[1] == head and t["bytes"] > 0]
        if not nxt:
            break
        k, _t = max(nxt, key=lambda kt: kt[1]["bytes"])
        if k[2] in seen:
            break
        path.append(k)
        seen.add(k[2])
        head = k[2]
    return path


# -- rendering --------------------------------------------------------------

def _shade(v: int, hi: int, ramp: str) -> str:
    if v <= 0 or hi <= 0:
        return ramp[0]
    import math

    frac = math.log1p(v) / math.log1p(hi)
    return ramp[min(int(frac * (len(ramp) - 1)) + (1 if v else 0),
                    len(ramp) - 1)]


def heatmap_lines(totals: dict[tuple, dict],
                  ascii_only: bool = False) -> list[str]:
    """V×V byte grid, one glyph per directed edge (log-scaled)."""
    verts = sorted({k[1] for k in totals} | {k[2] for k in totals},
                   key=lambda v: (isinstance(v, str), v))
    if not verts:
        return []
    ramp = _ASCII_SHADES if ascii_only else _SHADES
    hi = max((t["bytes"] for t in totals.values()), default=0)
    by_sd: dict[tuple, int] = {}
    for (_e, s, d), t in totals.items():
        by_sd[(s, d)] = by_sd.get((s, d), 0) + t["bytes"]
    wid = max(len(str(v)) for v in verts)
    lines = [" " * (wid + 2)
             + " ".join(f"{str(v)[-1]}" for v in verts)
             + "   (dst; bytes, log shade)"]
    for s in verts:
        row = " ".join(_shade(by_sd.get((s, d), 0), hi, ramp)
                       for d in verts)
        lines.append(f"{str(s):>{wid}}  {row}")
    return lines


def write_heatmap_csv(totals: dict[tuple, dict], out) -> None:
    verts = sorted({k[1] for k in totals} | {k[2] for k in totals},
                   key=lambda v: (isinstance(v, str), v))
    by_sd: dict[tuple, int] = {}
    for (_e, s, d), t in totals.items():
        by_sd[(s, d)] = by_sd.get((s, d), 0) + t["bytes"]
    w = csv.writer(out)
    w.writerow(["src\\dst", *verts])
    for s in verts:
        w.writerow([s, *[by_sd.get((s, d), 0) for d in verts]])


def report(edges: dict[tuple, list[dict]], out=None, top: int = 20,
           heatmap: str = "ascii", ascii_only: bool = False) -> dict:
    out = out if out is not None else sys.stdout
    totals = edge_totals(edges)
    findings = diagnose_links(edges)
    ranked = sorted(totals.items(), key=lambda kv: -kv[1]["bytes"])
    print(f"== link weathermap: {len(totals)} active edges ==", file=out)
    hdr = (f"{'edge':>16} {'pkts':>10} {'bytes':>12} {'loss':>8} "
           f"{'down':>8} {'nic':>8} {'q_avg_ns':>10} {'q_max_ns':>10}")
    print(hdr, file=out)
    for key, t in ranked[:top]:
        print(f"{_fmt_edge(key):>16} {t['pkts']:>10} {t['bytes']:>12} "
              f"{t['loss_drops']:>8} {t['link_down_drops']:>8} "
              f"{t['nic_backlog_drops']:>8} {t['queued_ns_avg']:>10} "
              f"{t['queued_ns_max']:>10}", file=out)
    if len(ranked) > top:
        print(f"  ... {len(ranked) - top} more edges (--top)", file=out)
    if heatmap == "ascii":
        print("", file=out)
        for line in heatmap_lines(totals, ascii_only):
            print("  " + line, file=out)
    path = hottest_path(totals)
    if path:
        verts = [str(path[0][1])] + [str(k[2]) for k in path]
        print(f"\n  hottest path: {' -> '.join(verts)}  "
              f"({sum(totals[k]['bytes'] for k in path)} bytes)", file=out)
    print("", file=out)
    if findings:
        for f in findings:
            detail = {k: v for k, v in f.items()
                      if k not in ("kind", "edges")}
            print(f"  VERDICT {f['kind']}: {', '.join(f['edges'])}  "
                  f"{detail}", file=out)
    else:
        print("  no network pathologies detected", file=out)
    return {"edges": {_fmt_edge(k): t for k, t in ranked},
            "verdicts": findings}


def write_csv(edges: dict[tuple, list[dict]], path: str) -> None:
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["exp", "src_vertex", "dst_vertex", "window",
                    "sim_time_s", *LINK_FIELDS])
        for (exp, s, d), rows in edges.items():
            for r in rows:
                w.writerow([exp, s, d, r.get("window"),
                            r.get("sim_time_s"),
                            *[r.get(field) for field in LINK_FIELDS]])


# -- self-test --------------------------------------------------------------

def _synth_row(w, s, d, window_ns=1_000_000, **kw) -> dict:
    base = {f: 0 for f in LINK_FIELDS}
    base.update({"type": REC_LINK, "window": w,
                 "sim_time_s": round((w + 1) * window_ns / 1e9, 9),
                 "src_vertex": s, "dst_vertex": d})
    base.update(kw)
    return base


def _synth_pathologies() -> list[dict]:
    """Cumulative snapshots carrying all four pathologies: edge 0->1 is an
    elephant with concentrated loss, edge 1->2 goes fully dark mid-run,
    edge 2->3 saturates its egress queue. Edges 0->2 / 3->0 are healthy
    background so the concentration/skew detectors have contrast."""
    recs = []
    for i, w in enumerate((9, 19), start=1):
        recs.append(_synth_row(w, 0, 1, pkts=5000 * i, bytes=7_500_000 * i,
                               loss_drops=400 * i, queued_ns_sum=5000 * i,
                               queued_ns_max=2000))
        # Dark from the second interval: pkts advance, every one dropped.
        recs.append(_synth_row(w, 1, 2, pkts=100 * i, bytes=150_000,
                               link_down_drops=0 if i == 1 else 100,
                               queued_ns_max=1000))
        recs.append(_synth_row(w, 2, 3, pkts=200 * i, bytes=300_000 * i,
                               queued_ns_sum=900_000_000 * i,
                               queued_ns_max=9_000_000))
        recs.append(_synth_row(w, 0, 2, pkts=300 * i, bytes=450_000 * i,
                               loss_drops=10 * i, queued_ns_max=1500))
        recs.append(_synth_row(w, 3, 0, pkts=280 * i, bytes=420_000 * i,
                               queued_ns_max=1200))
    return recs


def _synth_clean() -> list[dict]:
    """Uniform healthy traffic: no drops, shallow queues, even load — no
    detector may fire."""
    recs = []
    for i, w in enumerate((9, 19), start=1):
        for s, d in ((0, 1), (1, 2), (2, 3), (3, 0)):
            recs.append(_synth_row(w, s, d, pkts=1000 * i,
                                   bytes=1_500_000 * i,
                                   queued_ns_sum=10_000 * i,
                                   queued_ns_max=1000))
    return recs


def selftest(out=None) -> int:
    """Detector smoke: every injected pathology must be flagged, and the
    clean fabric must flag NOTHING (ci.sh network observability gate)."""
    out = out if out is not None else sys.stdout
    bad = diagnose_links(group_edges(_synth_pathologies()))
    kinds = {f["kind"] for f in bad}
    want = {"loss_concentration", "egress_saturation", "dark_link",
            "elephant_edge"}
    clean = diagnose_links(group_edges(_synth_clean()))
    ok = kinds == want and not clean
    print(json.dumps({"selftest": "ok" if ok else "FAIL",
                      "pathologies_flagged": sorted(kinds),
                      "clean_flagged": sorted(f["kind"] for f in clean)}),
          file=out)
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="shadow1_tpu.tools.netreport")
    ap.add_argument("log", nargs="?",
                    help="JSONL log carrying 'link' records "
                         "(CLI --link-telem on stderr, or a heartbeat "
                         "log)")
    ap.add_argument("--csv", default=None,
                    help="write the per-edge snapshot series as CSV")
    ap.add_argument("--json", action="store_true",
                    help="print the edge totals + verdicts as JSON "
                         "instead of the terminal report")
    ap.add_argument("--heatmap", choices=["ascii", "csv", "off"],
                    default="ascii",
                    help="V x V byte heatmap style (csv writes to stdout)")
    ap.add_argument("--top", type=int, default=20, metavar="N",
                    help="edge-table row limit (default 20)")
    ap.add_argument("--ascii", action="store_true",
                    help="ASCII heatmap shades (no Unicode blocks)")
    ap.add_argument("--selftest", action="store_true",
                    help="run the network-detector self-test and exit")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    if not args.log:
        ap.error("a log path is required (or --selftest)")
    recs = load_records(args.log)
    edges = group_edges(recs)
    if not edges:
        print("no 'link' records found — run with --link-telem on",
              file=sys.stderr)
        return 1
    gaps = sum(1 for r in recs if r.get("type") == REC_LINK_GAP)
    if gaps:
        print(f"note: {gaps} link_gap rebase marker(s) — counters "
              f"restarted mid-stream (fleet lane rebind)",
              file=sys.stderr)
    if args.json:
        totals = edge_totals(edges)
        print(json.dumps(
            {"edges": {_fmt_edge(k): t for k, t in sorted(
                totals.items(), key=lambda kv: -kv[1]["bytes"])},
             "verdicts": diagnose_links(edges)}, indent=2))
    elif args.heatmap == "csv":
        write_heatmap_csv(edge_totals(edges), sys.stdout)
    else:
        report(edges, top=args.top, heatmap=args.heatmap,
               ascii_only=args.ascii)
    if args.csv:
        write_csv(edges, args.csv)
        print(f"wrote {args.csv}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
