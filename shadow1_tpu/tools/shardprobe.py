"""Sharding performance probes: exchange overhead and mesh scaling shape.

    python -m shadow1_tpu.tools.shardprobe overhead [--config PATH] [--windows N]
    python -m shadow1_tpu.tools.shardprobe scale [--devices 1,2,4,8]
        [--windows N] [--json PATH]

The 20× north star names a v5e-8; host-axis sharding is the only 8× lever
(SURVEY §2.5), so its costs need numbers, not design notes:

* ``overhead`` — same experiment, plain ``Engine`` vs a **1-device-mesh**
  ``ShardedEngine``, on the DEFAULT backend (the real chip when alive).
  The delta is the price of the shard_map program structure + the bucketed
  all_to_all exchange with no actual cross-device traffic — the fixed cost
  sharding must amortize.
* ``scale`` — the sharded engine on 1/2/4/8 **virtual CPU devices** (the
  same ``xla_force_host_platform_device_count`` recipe as tests/conftest),
  each device count in a fresh child process. CPU-mesh walls say nothing
  about TPU walls, but the SHAPE (how throughput moves as the same work
  spreads over more shards, exchange included) is the first scaling datum
  this repo can produce without multi-chip hardware.

Workloads: the rung-3 Tor net (sparse rounds — the hard case) and the
dense tgen mesh at 2k hosts (the design-point case), both overflow-free.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

TOR_CFG = "configs/rung3_tor1k.yaml"


def _dense_exp(n_hosts: int = 2000):
    from shadow1_tpu.config.experiment import build_experiment
    from shadow1_tpu.tools.crossover import dense_doc

    return build_experiment(dense_doc(n_hosts))


def _timed_run(make_engine, windows: int, chunk: int):
    """(compile_s, wall_s, metrics) with compile excluded via 0-window call."""
    import jax

    eng = make_engine()
    t0 = time.perf_counter()
    jax.block_until_ready(eng.run(eng.init_state(), n_windows=0))
    compile_s = time.perf_counter() - t0
    st = eng.init_state()
    done = 0
    t0 = time.perf_counter()
    while done < windows:
        step = min(chunk, windows - done)
        st = eng.run(st, n_windows=step)
        jax.block_until_ready(st)
        done += step
    wall = time.perf_counter() - t0
    return compile_s, wall, type(eng).metrics_dict(st)


def overhead_main(config: str, windows: int) -> int:
    import shadow1_tpu  # noqa: F401
    from shadow1_tpu.platform import ensure_live_platform

    ensure_live_platform(min_devices=1)
    import jax

    from shadow1_tpu.config.experiment import load_experiment
    from shadow1_tpu.core.engine import Engine
    from shadow1_tpu.shard.engine import ShardedEngine

    exp, params, _ = load_experiment(config)
    out = {"mode": "overhead", "config": config, "windows": windows,
           "backend": jax.default_backend()}
    for name, mk in (
        ("plain", lambda: Engine(exp, params)),
        ("mesh1", lambda: ShardedEngine(exp, params,
                                        devices=jax.devices()[:1])),
    ):
        c, w, m = _timed_run(mk, windows, chunk=20)
        out[name] = {
            "compile_s": round(c, 2), "wall_s": round(w, 3),
            "events": m["events"],
            "events_per_sec": round(m["events"] / w, 1) if w else None,
        }
        if name == "mesh1":
            out["x2x_max_fill"] = m["x2x_max_fill"]
    if out["plain"]["wall_s"] and out["mesh1"]["wall_s"]:
        out["mesh1_over_plain_wall"] = round(
            out["mesh1"]["wall_s"] / out["plain"]["wall_s"], 3
        )
        # Same experiment, same windows: the parity contract applies.
        out["events_match"] = out["plain"]["events"] == out["mesh1"]["events"]
    print(json.dumps(out), flush=True)
    return 0


def scale_child(n_dev: int, windows: int) -> int:
    # Env-only JAX_PLATFORMS mutation loses to the environment's preset axon
    # plugin (tests/conftest.py) — force CPU via the config route, which
    # also raises the virtual device count pre-init.
    import shadow1_tpu  # noqa: F401
    from shadow1_tpu.platform import force_cpu

    force_cpu(n_dev)
    import jax

    from shadow1_tpu.config.experiment import load_experiment
    from shadow1_tpu.shard.engine import ShardedEngine

    assert jax.default_backend() == "cpu" and len(jax.devices()) >= n_dev
    rows = {}
    tor = load_experiment(TOR_CFG)
    dense = _dense_exp()
    for name, (exp, params, _s) in (("tor1k", tor), ("dense2k", dense)):
        c, w, m = _timed_run(
            lambda: ShardedEngine(exp, params, devices=jax.devices()[:n_dev]),
            windows, chunk=20,
        )
        rows[name] = {
            "compile_s": round(c, 2), "wall_s": round(w, 3),
            "events": m["events"],
            "events_per_sec": round(m["events"] / w, 1) if w else None,
            "x2x_max_fill": m["x2x_max_fill"],
            "ev_overflow": m["ev_overflow"],
        }
    print(json.dumps({"mode": "scale", "n_dev": n_dev, "windows": windows,
                      **rows}))
    return 0


def scale_main(devices: list[int], windows: int, json_path: str | None) -> int:
    rows = []
    for n in devices:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "host_platform_device_count" not in f]
        env["XLA_FLAGS"] = " ".join(
            flags + [f"--xla_force_host_platform_device_count={max(n, 1)}"]
        )
        try:
            r = subprocess.run(
                [sys.executable, "-m", "shadow1_tpu.tools.shardprobe",
                 "scale-child", str(n), "--windows", str(windows)],
                capture_output=True, text=True, env=env, timeout=3000,
            )
            row = json.loads(r.stdout.strip().splitlines()[-1])
        except subprocess.TimeoutExpired:
            row = {"mode": "scale", "n_dev": n, "error": "child >3000s"}
        except (IndexError, ValueError):
            row = {"mode": "scale", "n_dev": n,
                   "error": r.stderr[-300:] or f"rc={r.returncode}"}
        rows.append(row)
        print(json.dumps(row), flush=True)
    if json_path:
        with open(json_path, "w") as f:
            json.dump(rows, f, indent=1)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("mode", choices=["overhead", "scale", "scale-child"])
    ap.add_argument("n_dev", nargs="?", type=int, default=None)
    ap.add_argument("--config", default=TOR_CFG)
    ap.add_argument("--windows", type=int, default=200)
    ap.add_argument("--devices", default="1,2,4,8")
    ap.add_argument("--json", default=None)
    args = ap.parse_args()
    if args.mode == "overhead":
        return overhead_main(args.config, args.windows)
    if args.mode == "scale-child":
        return scale_child(args.n_dev, args.windows)
    return scale_main([int(x) for x in args.devices.split(",")],
                      args.windows, args.json)


if __name__ == "__main__":
    sys.exit(main())
