"""Transactional overflow plane — recoverable capacity overflow.

The determinism contract (docs/SEMANTICS.md "Capacities") holds only for
overflow-free runs: WHICH events drop when a bounded buffer fills is
layout-defined, so one burst past ``ev_cap`` silently forks a run away
from its big-cap truth. This module turns that counted-but-corrupting
condition into a *policy* applied at chunk boundaries, where state is
already fetched to host (``ckpt.run_chunked``):

* ``drop`` (default) — today's behavior: overflow is counted, the run
  continues, parity claims are void for the lossy stretch.
* ``retry`` — chunk execution becomes **transactional**. The chunk runner
  keeps the chunk-start state pytree (immutable — jax arrays, never
  donated); when a chunk's fresh overflow deltas are non-zero the tainted
  result is discarded, the offending cap grows one ladder step
  (tune/ladder.py; bit-exact plane migration via tune/resize.py; the
  sharded exchange bucket escalates to its guaranteed-fit cap), and the
  SAME chunk re-runs from the saved state on the re-jitted engine.
  Counter-based RNG and window-indexed fault tables make the replay
  exact, so a retried run's digest stream bit-matches a straight run at
  the final (grown) caps — every *committed* chunk is overflow-free, and
  overflow-free execution is cap-independent (the tune/resize.py
  exactness argument). Caveat: growing ``outbox_cap`` restores
  bit-exactness only for models whose outbox use is drop-counted rather
  than flow-controlled (TCP paces on ``outbox_space`` and never drops —
  same boundary as ``tune.autocap.CapPolicy.tune_outbox``).
* ``halt`` — raise :class:`CapacityExceededError`, a structured error
  carrying the offending knob, window range, and paste-ready cap advice
  (the captune idiom). The CLI maps it to :data:`EXIT_CAPACITY` and the
  supervisor classifies that exit as deterministic — it never burns the
  respawn budget replaying a config-capacity condition.

Also here: the **in-run self-check** (``--selfcheck``) — churnprobe's
drop-accounting identity lifted into a reusable boundary check
(:func:`check_boundary_identity`) that ``run_chunked`` applies to every
committed chunk and the CPU oracle to every window boundary, so the
identity guards every run instead of only probe invocations.

Deliberately light: numpy/jax are imported lazily inside the retry path,
so report tools can import the error types without an accelerator runtime.
"""

from __future__ import annotations

# CLI exit code for a CapacityExceededError halt (distinct from generic
# crashes so cli._supervise can classify it without parsing stderr).
# Canonically defined in the consts.py exit-code taxonomy; re-exported here
# for the existing importers.
from shadow1_tpu.consts import EXIT_CAPACITY  # noqa: F401

# Overflow counter → the capacity knob whose growth recovers it.
OVERFLOW_KNOBS: dict[str, str] = {
    "ev_overflow": "ev_cap",
    "ob_overflow": "outbox_cap",
    "x2x_overflow": "x2x_cap",
}

# knob → the high-water gauge that lower-bounds the demanded capacity.
_KNOB_GAUGE = {
    "ev_cap": "ev_max_fill",
    "outbox_cap": "ob_max_fill",
    "x2x_cap": "x2x_max_fill",
}


class CapacityExceededError(RuntimeError):
    """A capacity knob overflowed under a policy that forbids silent loss.

    Structured: ``knob`` (the EngineParams field), ``counter`` (the
    overflow metric), ``cap`` (the value that overflowed), ``overflow``
    (fresh drops attributed to it), ``window_range`` (``[w0, w1)`` window
    indices of the tainted chunk), ``recommended`` (ladder-quantized cap
    that would have held, from the measured gauge when available) and
    ``advice`` (a paste-ready ``engine:`` YAML block)."""

    def __init__(self, knob: str, counter: str, cap: int, overflow: int,
                 window_range: tuple[int, int], recommended: int | None = None,
                 detail: str = "", remedy: str | None = None,
                 lanes: list[int] | None = None):
        self.knob = knob
        self.counter = counter
        self.cap = int(cap)
        self.overflow = int(overflow)
        self.window_range = (int(window_range[0]), int(window_range[1]))
        # Fleet attribution: LOCAL lane indices whose counter overflowed
        # (None on solo engines) — what --on-lane-fail quarantine slices.
        self.lanes = list(lanes) if lanes is not None else None
        if recommended is None:
            from shadow1_tpu.tune.ladder import next_step

            recommended = next_step(cap)
        self.recommended = int(recommended)
        self.advice = f"engine:\n  {knob}: {self.recommended}"
        super().__init__(
            f"{counter}: {self.overflow} overflow drop(s) in windows "
            f"[{self.window_range[0]}, {self.window_range[1]}) at "
            f"{knob}={self.cap}{detail} — which items drop on overflow is "
            f"layout-defined, so the run has forked from its big-cap truth "
            f"(docs/SEMANTICS.md 'Capacities'). Paste-ready fix:\n"
            f"{self.advice}\n"
            + (remedy if remedy is not None else
               "or rerun with --on-overflow retry (transactional "
               "grow+replay) / --auto-caps; size precisely from a recorded "
               "run: python -m shadow1_tpu.tools.captune <run.log>")
        )


class SelfCheckError(RuntimeError):
    """The drop-accounting identity failed at a chunk/window boundary.

    Structured: ``terms`` (every counter in the identity with its value),
    ``gap`` (signed packets unaccounted: positive = ``pkts_sent`` exceeds
    every accounted sink, negative = the sinks over-explain), ``where``
    (boundary description). A violation means a routing/drop path changed
    without its counter — the probe-only invariant churnprobe checked now
    guards every ``--selfcheck`` run."""

    IDENTITY = ("pkts_sent == pkts_delivered + pkts_lost + link_down_pkts "
                "+ down_pkts + x2x_overflow (+ delivery share of ev_overflow)")

    def __init__(self, terms: dict, gap: int, where: str = ""):
        self.terms = {k: int(v) for k, v in terms.items()}
        self.gap = int(gap)
        self.where = where
        if gap > 0:
            culprit = (f"pkts_sent exceeds every accounted sink by {gap} — "
                       f"a drop/delivery path went uncounted")
        else:
            culprit = (f"the accounted sinks exceed pkts_sent by {-gap} — "
                       f"a packet was counted twice")
        span = f" at {where}" if where else ""
        super().__init__(
            f"drop-accounting self-check violated{span}: {culprit}. "
            f"Identity: {self.IDENTITY}. Terms: {self.terms}. "
            f"Bisect the window with tools/paritytrace.py; "
            f"cross-engine verdict: tools/churnprobe.py"
        )


def accounting(m: dict) -> dict:
    """The drop-accounting identity: where every sent packet went.
    ``ev_overflow`` counts event-buffer drops from both local pushes and
    deliveries; only the delivery share belongs here, so the identity is
    checked as sent ≤ explained ≤ sent + ev_overflow (exact when
    ev_overflow == 0 — overflow-free runs are the parity contract).
    Shared by tools/churnprobe.py and the ``--selfcheck`` boundary check."""
    explained = (m["pkts_delivered"] + m["pkts_lost"] + m["link_down_pkts"]
                 + m["down_pkts"] + m.get("x2x_overflow", 0))
    lo, hi = explained, explained + m["ev_overflow"]
    return {
        "pkts_sent": m["pkts_sent"],
        "explained": explained,
        "ev_overflow": m["ev_overflow"],
        "closes": lo <= m["pkts_sent"] <= hi,
    }


_IDENTITY_TERMS = ("pkts_sent", "pkts_delivered", "pkts_lost",
                   "link_down_pkts", "down_pkts", "x2x_overflow",
                   "ev_overflow")


def check_boundary_identity(metrics: dict, where: str = "") -> None:
    """Raise :class:`SelfCheckError` if the cumulative counters in
    ``metrics`` fail the drop-accounting identity. Missing counters read
    as 0 (engine field subsets — same tolerance as registry.normalize)."""
    m = {k: int(metrics.get(k, 0)) for k in _IDENTITY_TERMS}
    acc = accounting(m)
    if acc["closes"]:
        return
    raise SelfCheckError(m, m["pkts_sent"] - acc["explained"], where=where)


class OverflowGuard:
    """The chunk-boundary transactional brain (``--on-overflow``).

    Construct with the running engine, a ``params -> engine`` factory
    (sibling engines at grown caps), and the policy mode. ``run_chunked``
    calls :meth:`bind` once (overflow baselines from the possibly-resumed
    state) and :meth:`commit` after every chunk; commit either accepts the
    chunk (no fresh overflow), replays it at grown caps (``retry``), or
    raises :class:`CapacityExceededError` (``halt``, ladder exhaustion, or
    the repeated-overflow classifier).

    When a ``tune.autocap.CapController`` is attached, the guard shares
    its engine cache and reports every retry-driven grow via
    ``controller.note_lossy`` — the controller's lossless floor then
    ratchets above the proven-overflowing cap, so the two planes can never
    double-grow or oscillate against each other.
    """

    COUNTERS = OVERFLOW_KNOBS

    def __init__(self, engine, make_engine=None, mode: str = "retry",
                 controller=None, log=None, max_cap: int = 1 << 20,
                 max_retries_per_chunk: int = 12):
        assert mode in ("retry", "halt"), mode
        self.mode = mode
        self.engine = engine
        self._make_engine = make_engine
        self._controller = controller
        self._engines: dict = {}
        self._seen: dict[str, int] | None = None
        self._log = log
        self.max_cap = max_cap
        self.max_retries = max_retries_per_chunk
        # Counters (host-side; ride the registry namespace — HOST_FIELDS).
        self.chunk_retries = 0
        self.retry_windows_rerun = 0
        self.resizes: list[dict] = []  # audit log (CLI retries block / tests)
        self.on_engine_swap = None     # hook: heartbeat tracks the live engine

    # -- lifecycle ---------------------------------------------------------
    def bind(self, engine, st) -> None:
        """Baseline the overflow counters from ``st`` (resume-aware: a
        resumed state carries its pre-snapshot history — old losses must
        not read as a fresh lossy chunk, mirroring CapController)."""
        self.engine = engine
        self._seen = self._counters(st)

    @staticmethod
    def _counters(st) -> dict:
        """Cumulative overflow counters as numpy arrays — 0-d on solo
        engines, [E] on a FleetEngine state, so one guard serves both: the
        fleet's psum-equivalent is the lane sum, and per-lane deltas stay
        available for attribution (fresh_by_lane)."""
        import numpy as np

        return {c: np.asarray(getattr(st.metrics, c)).astype(np.int64)
                for c in OVERFLOW_KNOBS}

    @staticmethod
    def run_guarded(engine, st, n_windows: int):
        """Run one chunk under guard supervision. The sharded engine's
        eager x2x escalate/raise (its guard-less safety net) must stand
        down — the guard owns the overflow response — so it is told a
        guard is watching via check_x2x=False. ``ckpt.run_chunked`` and
        the retry replay both go through here."""
        if hasattr(engine, "grow_x2x"):
            return engine.run(st, n_windows=n_windows, check_x2x=False)
        return engine.run(st, n_windows=n_windows)

    def _fresh(self, st) -> dict[str, int]:
        cur = self._counters(st)
        out = {}
        for c, v in cur.items():
            d = int((v - self._seen[c]).sum())
            if d > 0:
                out[c] = d
        return out

    def fresh_by_lane(self, st) -> dict[str, list[int]]:
        """Fleet attribution: LOCAL lane indices with fresh overflow per
        counter since the last bind/commit ({} on solo engines — 0-d
        counters carry no lane axis)."""
        import numpy as np

        cur = self._counters(st)
        out: dict[str, list[int]] = {}
        for c, v in cur.items():
            if v.ndim == 0:
                continue
            lanes = np.nonzero(v - self._seen[c] > 0)[0]
            if lanes.size:
                out[c] = [int(e) for e in lanes]
        return out

    # -- the transaction ---------------------------------------------------
    def commit(self, engine, st0, st, done: int, step: int):
        """Accept / replay / refuse one chunk. ``st0`` is the chunk-start
        state (the rollback point), ``st`` the just-produced result.
        Returns the committed ``(engine, state)``."""
        if self._seen is None:
            self.bind(engine, st0)
        fresh = self._fresh(st)
        attempts = 0
        while fresh:
            import numpy as np

            # max over lanes == the scalar on solo engines; fleet lanes
            # advance in lockstep, so any lane's clock is the chunk's.
            w0 = int(np.asarray(st0.win_start).max()) // engine.window
            if self.mode == "halt":
                raise self._error(engine, fresh, w0, w0 + step, st)
            attempts += 1
            if attempts > self.max_retries:
                raise self._error(
                    engine, fresh, w0, w0 + step, st,
                    detail=(f" after {attempts - 1} grow+replay attempts at "
                            f"the same chunk — growing caps is not fixing "
                            f"it; diagnose with tools/occprobe.py or "
                            f"tools/paritytrace.py"))
            self.chunk_retries += 1
            self.retry_windows_rerun += step
            engine, st0 = self._grow(engine, st0, fresh, w0, w0 + step, st,
                                     lanes=self.fresh_by_lane(st) or None)
            st = self.run_guarded(engine, st0, step)
            fresh = self._fresh(st)
        self._seen = self._counters(st)
        self.engine = engine
        return engine, st

    # -- growth ------------------------------------------------------------
    def _engine_for(self, params):
        if self._controller is not None:
            return self._controller.engine_for(params)
        key = (params.ev_cap, params.outbox_cap)
        eng = self._engines.get(key)
        if eng is None:
            if self._make_engine is None:
                raise ValueError(
                    "OverflowGuard(mode='retry') needs a make_engine "
                    "factory (or an attached CapController) to re-jit at "
                    "grown caps"
                )
            eng = self._engines[key] = self._make_engine(params)
        return eng

    def _grow(self, engine, st0, fresh, w0, w1, st_tainted, lanes=None):
        import dataclasses

        from shadow1_tpu.tune.ladder import next_step

        params = engine.params
        repl: dict[str, int] = {}
        rec: dict = {"windows": [w0, w1], "retry": self.chunk_retries}
        if lanes:
            # Fleet retry audit: which lanes' counters tainted this chunk
            # (heartbeat_report's per-lane retry table reads these).
            rec["lanes"] = lanes
        for ctr, knob in OVERFLOW_KNOBS.items():
            if ctr not in fresh:
                continue
            if knob == "x2x_cap":
                # The exchange bucket is not a state shape: escalate to the
                # engine's guaranteed-fit cap (a bucket physically cannot
                # need more than the shard's whole outbox — shard/engine.py)
                # and replay; no plane migration involved.
                old = getattr(engine, "_x2x_cap", None)
                if not getattr(engine, "grow_x2x", lambda: False)():
                    raise self._error(engine, {ctr: fresh[ctr]}, w0, w1,
                                      st_tainted,
                                      detail=" (exchange bucket already at "
                                             "its guaranteed-fit cap)")
                rec["x2x_cap"] = [old, engine._x2x_cap]
                continue
            cap = getattr(params, knob)
            new = next_step(cap)
            if new > self.max_cap:
                raise self._error(
                    engine, {ctr: fresh[ctr]}, w0, w1, st_tainted,
                    detail=f" (ladder top: cannot grow past {self.max_cap})")
            repl[knob] = new
            rec[knob] = [cap, new]
            if self._controller is not None:
                self._controller.note_lossy(knob, new)
        if repl:
            import jax
            import numpy as np

            from shadow1_tpu.tune.resize import resize_state

            new_params = dataclasses.replace(params, **repl)
            engine = self._engine_for(new_params)
            host_st = jax.tree.map(np.asarray, st0)
            host_st = resize_state(host_st, ev_cap=new_params.ev_cap,
                                   outbox_cap=new_params.outbox_cap)
            st0 = engine.place_state(host_st)
        self.resizes.append(rec)
        if self.on_engine_swap is not None:
            self.on_engine_swap(engine)
        if self._log is not None:
            self._log("overflow retry: chunk discarded, caps grown", **rec)
        return engine, st0

    def _error(self, engine, fresh, w0, w1, st, detail=""):
        import numpy as np

        from shadow1_tpu.tune.ladder import next_step, recommend_cap

        counter = max(fresh, key=lambda c: fresh[c])
        knob = OVERFLOW_KNOBS[counter]
        cap = (getattr(engine, "_x2x_cap", 0) if knob == "x2x_cap"
               else getattr(engine.params, knob))
        # max over lanes == the scalar on solo engines (gauges are maxes).
        peak = int(np.asarray(getattr(st.metrics, _KNOB_GAUGE[knob], 0)).max())
        rec = max(next_step(cap), recommend_cap(peak) if peak else 0)
        lanes = self.fresh_by_lane(st).get(counter) if self._seen else None
        if lanes:
            detail = f" (fleet lane(s) {lanes})" + detail
        return CapacityExceededError(
            knob=knob, counter=counter, cap=cap, overflow=fresh[counter],
            window_range=(w0, w1), recommended=rec, detail=detail,
            lanes=lanes)

    # -- reporting ---------------------------------------------------------
    @property
    def final_caps(self) -> dict:
        caps = {"ev_cap": self.engine.params.ev_cap,
                "outbox_cap": self.engine.params.outbox_cap}
        x2x = getattr(self.engine, "_x2x_cap", None)
        if x2x:
            caps["x2x_cap"] = x2x
        return caps

    def report(self) -> dict:
        """The ``retries`` block (heartbeat / final JSON —
        docs/OBSERVABILITY.md)."""
        return {
            "policy": self.mode,
            "chunk_retries": self.chunk_retries,
            "retry_windows_rerun": self.retry_windows_rerun,
            "caps": self.final_caps,
        }
