"""Run the five-rung BASELINE benchmark ladder and record the results.

    python bench_ladder.py [rung ...] [--windows N] [--budget-s S] [--json PATH]

For each rung config (configs/rung*.yaml): run the batched engine on the
default backend (TPU when alive) with chunked timing — compile excluded,
overflow counters recorded (the parity contract requires them to be 0; a
nonzero count means the rung's capacity knobs need retuning, and the row
says so) — and the sequential CPU oracle on a bounded slice of the same
experiment for the events/sec comparison (the oracle is O(events) Python;
its slice and the extrapolation basis are recorded in the row).

Fault tolerance (round-2/3 postmortems): the tunneled axon device kernel-
faults on long executions AND occasionally wedges the whole process (after
a fault, even fresh small programs fail until re-init). So every rung runs
in a CHILD process that checkpoints engine state to disk after each chunk;
on a fault the child exits and the parent respawns a fresh child that
resumes from the checkpoint — determinism makes the resumed run identical
to an uninterrupted one (docs/SEMANTICS.md; tests/test_ckpt_obs.py). Timed
walls accumulate across children; every child's compile time is excluded
and reported separately. ``--budget-s`` bounds each rung's *timed* wall:
the rung stops at a chunk boundary once exceeded and the row records how
many of the configured windows were measured (status "budget").

Output: one JSON line per rung on stdout (plus a human table on stderr),
and with ``--json`` the rows are also written to a file. BASELINE.md's
results table is generated from these rows.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

# rung -> (config, initial chunk). Heavy net rungs start with small chunks:
# the tunneled device faults on long single executions, and tor/bitcoin
# windows are orders of magnitude heavier than phold/tgen ones.
RUNGS = {
    "rung1": ("configs/rung1_filexfer.yaml", 100),
    "rung2": ("configs/rung2_tgen100.yaml", 100),
    "rung3": ("configs/rung3_tor1k.yaml", 20),
    "rung4": ("configs/rung4_tor10k.yaml", 5),
    "rung5": ("configs/rung5_bitcoin5k.yaml", 10),
    # Not a SURVEY rung: the dense-scale crossover exhibit (50k-host tgen
    # mesh, ~5e5 events/window). Run SLICED (--windows 100): the full 20 s
    # sim is hours of eager-engine wall; throughput is the metric.
    "dense": ("configs/dense_tgen50k.yaml", 10),
}
ORACLE_EVENT_BUDGET = 200_000  # stop the oracle slice near this many events
SAVE_EVERY_S = 300.0           # checkpoint throttle (timed-wall seconds).
                               # At rung-4 scale a save costs ~38 s over the
                               # tunnel (the 55-min run: 1057 s of saves =
                               # 24% of total wall at a 120 s cadence);
                               # 300 s caps the overhead at ~11% while
                               # risking ≤5 min of re-execution per fault.
MAX_RESPAWNS = 8               # fresh-process resumes per rung (each pays
                               # a full recompile; the budget bounds only
                               # the timed wall)
RC_FAULT = 3                   # child: device fault, checkpoint is resumable


# --------------------------------------------------------------------------
# Child: run one rung (possibly resuming), checkpoint each chunk, report.
# --------------------------------------------------------------------------
def child_main(name: str, path: str, state_path: str, report_path: str,
               total_override: int | None, chunk0: int, budget_s: float,
               engine_spec: str | None = None) -> int:
    import shadow1_tpu  # noqa: F401
    from shadow1_tpu.platform import ensure_live_platform

    ensure_live_platform(min_devices=1)
    import jax

    from shadow1_tpu import ckpt
    from shadow1_tpu.config.experiment import load_experiment
    from shadow1_tpu.core.engine import Engine

    from shadow1_tpu.config.experiment import apply_engine_overrides

    exp, params, _scheduler = load_experiment(path)
    params = apply_engine_overrides(params, engine_spec)
    eng = Engine(exp, params)
    total = total_override or eng.n_windows

    # n_windows is traced, so a zero-window call compiles the exact program
    # every chunk reuses — compile never rides a long device execution.
    t0 = time.perf_counter()
    jax.block_until_ready(eng.run(eng.init_state(), n_windows=0))
    compile_wall = time.perf_counter() - t0

    st = eng.init_state()
    if os.path.exists(state_path):
        st = ckpt.load_state(st, state_path)
    done = int(st.win_start) // exp.window
    status, chunk, faults = "done", chunk0, 0

    def snapshot(s) -> dict:
        """Host-side metrics/summary stash — taken at every checkpoint so a
        fault report never reads from a wedged device."""
        return {
            "metrics": Engine.metrics_dict(s),
            "summary": {
                k: int(v) for k, v in eng.model_summary(s).items()
                if getattr(v, "ndim", 1) == 0
            },
        }

    snap = snapshot(st)

    def report(timed_wall: float, ckpt_wall: float) -> None:
        rec = {
            "status": status, "done": done, "ckpt_done": ckpt_done,
            "total": total,
            "wall_s": timed_wall, "ckpt_s": ckpt_wall,
            "compile_s": compile_wall,
            "chunk_final": chunk, "faults_recovered": faults,
            "backend": jax.default_backend(), **snap,
        }
        with open(report_path, "w") as f:
            json.dump(rec, f)

    # Timed wall covers ONLY the device execution; checkpoint saves (host
    # transfer + npz write, fault-tolerance overhead) are metered separately
    # in ckpt_s so throughput numbers stay comparable to an unchunked run.
    # Saves are throttled (~every SAVE_EVERY_S of timed wall): at rung-4
    # scale the state is ~10^2 MB and a per-chunk save over the tunnel would
    # dwarf the run. On a fault, up to SAVE_EVERY_S of windows re-execute
    # from the last save — deterministically identical, wall double-counted
    # (events are not), so throughput errs toward underreporting.
    timed = ckpt_s = last_save = 0.0
    ckpt_done = done
    while done < total:
        step = min(chunk, total - done)
        try:
            t0 = time.perf_counter()
            nxt = eng.run(st, n_windows=step)
            jax.block_until_ready(nxt)
            timed += time.perf_counter() - t0
            st, done = nxt, done + step
            if done >= total or timed - last_save > SAVE_EVERY_S:
                t0 = time.perf_counter()
                ckpt.save_state(st, state_path)
                ckpt_s += time.perf_counter() - t0
                last_save = timed
                ckpt_done = done
                snap = snapshot(st)
        except Exception:  # noqa: BLE001 — jax runtime faults
            faults += 1
            if chunk <= 5 or faults > 4:
                # Process may be wedged: report resumable and bail out.
                # Windows/metrics roll back to the last checkpoint (what the
                # resume will continue from); the wall spent past it stays
                # counted, erring toward underreported throughput.
                status = "fault"
                done = ckpt_done
                report(timed, ckpt_s)
                return RC_FAULT
            chunk = max(5, chunk // 4)
            continue
        if timed > budget_s and done < total:
            status = "budget"
            if ckpt_done < done:
                t0 = time.perf_counter()
                ckpt.save_state(st, state_path)
                ckpt_s += time.perf_counter() - t0
                ckpt_done = done
                snap = snapshot(st)
            break
    report(timed, ckpt_s)
    return 0


# --------------------------------------------------------------------------
# Parent: respawn children across faults, aggregate walls, add the oracle.
# --------------------------------------------------------------------------
def run_rung(name: str, path: str, windows_override: int | None,
             chunk0: int, budget_s: float, workdir: str, rep: int = 0,
             engine_spec: str | None = None) -> dict:
    state_path = os.path.join(workdir, f"{name}.r{rep}.state.npz")
    report_path = os.path.join(workdir, f"{name}.r{rep}.report.json")
    wall = compile_total = ckpt_total = 0.0
    faults_total = respawns = 0
    rec = None
    last_done = -1
    for attempt in range(MAX_RESPAWNS + 1):
        # Each child gets only the budget remaining after its predecessors,
        # so a faulting rung's AGGREGATE timed wall honors --budget-s: once
        # it is spent, no further child runs (round-3 advisor: the old 30 s
        # floor let a repeatedly-faulting rung overshoot the budget by up to
        # (MAX_RESPAWNS+1)*30 s).
        remaining = budget_s - wall
        if remaining <= 0:
            if rec is None:
                raise RuntimeError(
                    f"--budget-s {budget_s} leaves no time for any child run"
                )
            # Only a faulted child re-enters this loop, so rec["status"] is
            # "fault" here; keep it — the run ended on an unrecovered fault.
            break
        cmd = [sys.executable, __file__, "--child", name,
               "--state", state_path, "--report", report_path,
               "--chunk", str(chunk0),
               "--budget-s", str(remaining)]
        if windows_override:
            cmd += ["--windows", str(windows_override)]
        if engine_spec:
            cmd += ["--engine", engine_spec]
        r = subprocess.run(cmd, capture_output=True, text=True)
        if not os.path.exists(report_path):
            raise RuntimeError(
                f"child died without a report (rc={r.returncode}): "
                f"{r.stderr[-800:]}"
            )
        with open(report_path) as f:
            rec = json.load(f)
        os.remove(report_path)
        wall += rec["wall_s"]
        compile_total += rec["compile_s"]
        ckpt_total += rec["ckpt_s"]
        faults_total += rec["faults_recovered"]
        if rec["status"] != "fault":
            break
        if rec["done"] <= last_done:
            # No forward progress across a whole process: stop grinding.
            break
        last_done = rec["done"]
        if attempt == MAX_RESPAWNS:
            break
        respawns += 1
        print(f"[{name}] device fault at {rec['done']}/{rec['total']} "
              f"windows — respawning ({respawns})", file=sys.stderr, flush=True)
    if rec["status"] == "fault":
        # The rung ENDED on a fault: its last counted fault was terminal,
        # not recovered — subtract it so the row is honest (r3 advisor).
        # Faults inside children that a later respawn resumed past stay
        # counted as recovered.
        faults_total = max(faults_total - 1, 0)
    if rec["status"] in ("fault", "budget"):
        # Keep the checkpoint: it is the only resumable artifact — a rerun
        # against a recovered device (fault) or with a deeper budget
        # (budget; round-3 advisor) continues from it instead of starting
        # over.
        print(f"[{name}] status={rec['status']}; resumable checkpoint kept "
              f"at {state_path}", file=sys.stderr, flush=True)
    elif os.path.exists(state_path):
        os.remove(state_path)

    from shadow1_tpu.config.experiment import load_experiment
    from shadow1_tpu.consts import SEC

    exp, _params, _ = load_experiment(path)
    m = rec["metrics"]
    done = rec["done"]
    sim_s = done * exp.window / SEC
    row = {
        "rung": name,
        "config": path,
        "commit": _git_head(),
        "engine_overrides": engine_spec,
        "status": rec["status"],
        "n_hosts": exp.n_hosts,
        "windows": done,
        "windows_configured": rec["total"],
        "sim_s": round(sim_s, 3),
        "backend": rec["backend"],
        "engine": "tpu-batched",
        "events": m["events"],
        "events_per_sec": round(m["events"] / wall, 1) if wall else None,
        "sim_per_wall": round(sim_s / wall, 4) if wall else None,
        "wall_s": round(wall, 2),
        "ckpt_s": round(ckpt_total, 2),
        "compile_s": round(compile_total, 2),
        "ev_overflow": m["ev_overflow"],
        "ob_overflow": m["ob_overflow"],
        "round_cap_hits": m["round_cap_hits"],
        "rounds_per_window": round(m["rounds"] / max(m["windows"], 1), 2),
        "device_faults_recovered": faults_total,
        "process_respawns": respawns,
    }
    if rec["status"] in ("fault", "budget"):
        row["resume_checkpoint"] = state_path
    for k in ("total_flows_done", "total_streams_done", "clients_done",
              "total_cells_fwd", "total_rx_bytes", "total_seen"):
        if k in rec["summary"]:
            row[k] = rec["summary"][k]
    return row


def _git_head() -> str:
    """Commit the measurement ran at — recorded in each row so renders never
    misattribute numbers to a later HEAD."""
    r = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                       capture_output=True, text=True,
                       cwd=os.path.dirname(os.path.abspath(__file__)))
    return r.stdout.strip() or "?"


def run_cpp_comparator(name: str, path: str, tpu_row: dict,
                       engine_spec: str | None = None) -> dict:
    """The honest thread-per-core C++ baseline on the same rung, same window
    count — its counters bit-match both engines (tests/test_native_
    comparator.py), so its wall clock is the denominator of the north-star
    claim (BASELINE.json; SURVEY §7.3.5)."""
    import os as _os

    from shadow1_tpu import native
    from shadow1_tpu.config.experiment import apply_engine_overrides, load_experiment

    exp, params, _ = load_experiment(path)
    params = apply_engine_overrides(params, engine_spec)
    windows = tpu_row["windows"]
    if not windows:
        return {"cpp_skipped": "no measured windows"}
    try:
        r = native.run_net(exp, params, windows,
                           n_threads=_os.cpu_count() or 1)
    except native.NativeUnavailable as e:
        return {"cpp_skipped": str(e)[:200]}
    out = {
        "cpp_events": r["events"],
        "cpp_wall_s": round(r["wall_s"], 3),
        "cpp_events_per_sec": r["events_per_sec"],
        "cpp_threads": r["n_threads"],
        # Cross-validation: same windows -> the counters must bit-match the
        # batched engine's row (strong evidence nothing drifted in prod).
        "cpp_events_match": r["events"] == tpu_row["events"],
    }
    if tpu_row.get("events_per_sec") and r["events_per_sec"]:
        out["vs_cpp"] = round(
            tpu_row["events_per_sec"] / r["events_per_sec"], 3
        )
        sim_s = tpu_row["sim_s"]
        if r["wall_s"] > 0:
            out["cpp_sim_per_wall"] = round(sim_s / r["wall_s"], 4)
    return out


def run_oracle_slice(name: str, path: str, tpu_row: dict,
                     engine_spec: str | None = None) -> dict:
    """Bounded oracle run: whole windows until the event budget is hit."""
    from shadow1_tpu.config.experiment import apply_engine_overrides, load_experiment
    from shadow1_tpu.cpu_engine import CpuEngine

    exp, params, _ = load_experiment(path)
    params = apply_engine_overrides(params, engine_spec)
    if exp.n_hosts * params.sockets_per_host > 500_000:
        # The eager oracle allocates one Python object per socket; at rung-4
        # scale that is >1M objects — skip rather than swap the box.
        return {"oracle_skipped": f"{exp.n_hosts} hosts x "
                                  f"{params.sockets_per_host} sockets"}
    cpu = CpuEngine(exp, params)
    t0 = time.perf_counter()
    done = 0
    cm = {"events": 0}
    while done < tpu_row["windows"]:
        step = max(1, tpu_row["windows"] // 50)
        cm = cpu.run(n_windows=done + step)
        done += step
        if cm["events"] >= ORACLE_EVENT_BUDGET or time.perf_counter() - t0 > 120:
            break
    wall = time.perf_counter() - t0
    return {
        "oracle_windows": done,
        "oracle_events": cm["events"],
        "oracle_wall_s": round(wall, 2),
        "oracle_events_per_sec": round(cm["events"] / wall, 1) if wall else None,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("rungs", nargs="*", default=None)
    ap.add_argument("--windows", type=int, default=None)
    ap.add_argument("--budget-s", type=float, default=900.0,
                    help="per-rung timed-wall budget (chunk-boundary stop)")
    ap.add_argument("--json", default=None)
    ap.add_argument("--no-oracle", action="store_true")
    ap.add_argument("--engine", default=None,
                    help="EngineParams overrides, e.g. "
                         "'compact_cap=384,pop_extract=gather' (A/B knob)")
    ap.add_argument("--repeats", type=int, default=1,
                    help="measure each rung N times (fresh state per rep); "
                         "the row reports the median-throughput rep plus "
                         "min/median/max across reps — the tunnel shows "
                         "±2-3x wall variance between identical runs "
                         "(BASELINE.md), so single-run rows are labeled n=1")
    # child-mode flags (internal)
    ap.add_argument("--child", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--state", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--report", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--chunk", type=int, default=None, help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.child:
        path, _chunk0 = RUNGS[args.child]
        sys.exit(child_main(args.child, path, args.state, args.report,
                            args.windows, args.chunk, args.budget_s,
                            engine_spec=args.engine))

    import shadow1_tpu  # noqa: F401
    from shadow1_tpu.platform import ensure_live_platform

    ensure_live_platform(min_devices=1)

    # "dense" is opt-in (sliced runs only — see RUNGS comment).
    names = args.rungs or [n for n in RUNGS if n != "dense"]
    rows = []
    workdir = tempfile.mkdtemp(prefix="ladder_")
    for name in names:
        path, chunk0 = RUNGS[name]
        try:
            reps = []
            for rep in range(max(args.repeats, 1)):
                r = run_rung(name, path, args.windows, chunk0,
                             args.budget_s, workdir, rep=rep,
                             engine_spec=args.engine)
                reps.append(r)
                if args.repeats > 1:
                    eps_s = (f"{r['events_per_sec']:,.0f} ev/s"
                             if r["events_per_sec"] is not None else "(no wall)")
                    print(f"[{name}] rep {rep + 1}/{args.repeats}: {eps_s}",
                          file=sys.stderr, flush=True)
            # Median-throughput rep is the headline row (lower middle for
            # even N — conservative under the tunnel's wall variance); the
            # spread fields record what variance did to the rest.
            scored = sorted(reps, key=lambda r: r["events_per_sec"] or 0)
            row = scored[(len(scored) - 1) // 2]
            row["repeats"] = len(reps)
            if len(reps) > 1:
                eps = [r["events_per_sec"] for r in reps]
                spw = [r["sim_per_wall"] for r in reps]
                row["events_per_sec_reps"] = eps
                row["sim_per_wall_reps"] = spw
            if not args.no_oracle:
                row.update(run_oracle_slice(name, path, row,
                                            engine_spec=args.engine))
                if row.get("oracle_events_per_sec") and row["events_per_sec"]:
                    row["vs_oracle"] = round(
                        row["events_per_sec"] / row["oracle_events_per_sec"], 2
                    )
            row.update(run_cpp_comparator(name, path, row,
                                          engine_spec=args.engine))
        except Exception as e:  # noqa: BLE001 — record the failure, keep going
            import traceback

            row = {"rung": name, "config": path, "error": repr(e)[:400],
                   "traceback": traceback.format_exc()[-1500:]}
        rows.append(row)
        print(json.dumps(row), flush=True)
        if "error" in row:
            line = f"FAILED: {row['error']}"
        else:
            eps = row["events_per_sec"]
            spw = row["sim_per_wall"]
            line = (
                f"{eps:>12,.0f} ev/s  " if eps is not None else "  (no wall)  "
            ) + (
                f"sim/wall {spw:.3f}  " if spw is not None else ""
            ) + (
                f"wall {row['wall_s']}s  "
                f"windows {row['windows']}/{row['windows_configured']}  "
                f"overflow {row['ev_overflow']}+{row['ob_overflow']}  "
                f"respawns {row['process_respawns']}  status {row['status']}"
            )
        print(f"[{name}] {line}", file=sys.stderr, flush=True)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(rows, f, indent=1)


if __name__ == "__main__":
    main()
