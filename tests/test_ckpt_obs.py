"""Checkpoint/resume exactness + heartbeat stream.

Determinism makes checkpointing exact: run A→(save)→resume→B must equal an
uninterrupted A+B run bit-for-bit — the engine-state analogue of the
reference's determinism diff-test (SURVEY §4).
"""

import io
import json

import jax
import numpy as np

from shadow1_tpu.ckpt import load_state, run_chunked, save_state
from shadow1_tpu.config.compiled import single_vertex_experiment
from shadow1_tpu.consts import MS, EngineParams
from shadow1_tpu.core.engine import Engine
from shadow1_tpu.obs import run_with_heartbeat


def phold_engine():
    exp = single_vertex_experiment(
        n_hosts=32,
        seed=17,
        end_time=100 * MS,
        latency_ns=1 * MS,
        model="phold",
        model_cfg={"mean_delay_ns": float(2 * MS), "init_events": 2},
    )
    return Engine(exp, EngineParams())


def state_equal(a, b) -> bool:
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return all(np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb))


def test_checkpoint_resume_bit_exact(tmp_path):
    eng = phold_engine()
    # Uninterrupted 100-window run.
    ref = eng.run(n_windows=100)
    # 40 windows → snapshot → load → 60 more.
    st = eng.run(n_windows=40)
    path = str(tmp_path / "snap.npz")
    save_state(st, path)
    st2 = load_state(eng.init_state(), path)
    final = eng.run(st2, n_windows=60)
    assert state_equal(ref, final)


def test_checkpoint_rejects_config_mismatch(tmp_path):
    eng = phold_engine()
    st = eng.run(n_windows=10)
    path = str(tmp_path / "snap.npz")
    save_state(st, path)
    other = Engine(
        single_vertex_experiment(
            n_hosts=64, seed=17, end_time=100 * MS, latency_ns=1 * MS,
            model="phold", model_cfg={"mean_delay_ns": float(2 * MS)},
        ),
        EngineParams(),
    )
    try:
        load_state(other.init_state(), path)
        raise AssertionError("expected ValueError on shape mismatch")
    except ValueError as e:
        assert "config mismatch" in str(e)


def test_run_chunked_matches_straight_run():
    eng = phold_engine()
    ref = eng.run(n_windows=100)
    chunked = run_chunked(eng, n_windows=100, chunk=17)  # uneven tail chunk
    assert state_equal(ref, chunked)


def test_heartbeat_stream():
    eng = phold_engine()
    buf = io.StringIO()
    st, hb = run_with_heartbeat(eng, n_windows=100, every_windows=25, stream=buf)
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    assert len(lines) == 4
    assert lines[-1]["windows"] == 100
    assert sum(r["delta"]["events"] for r in lines) == int(st.metrics.events)
    assert all(r["type"] == "heartbeat" for r in lines)
