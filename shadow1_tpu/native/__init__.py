"""Native thread-per-core comparator: build + run harness.

Builds ``phold_comparator.cpp`` with the system g++ on first use (cached
under ``build/native/`` at the repo root) and runs it on the same
experiment parameters the JAX engine and Python oracle consume. The Q32
log2 table is dumped from shadow1_tpu.rng's numpy source of truth so the
C++ fixed-point exponential is bit-identical to both engines (no libm
rounding drift can enter).

This is the honest baseline mandated by BASELINE.json ("thread-per-core
CPU scheduler", reference scheduler-policy-host-steal.c): an optimized
multi-core C++ DES, not the interpreted oracle. tests/test_native_
comparator.py asserts counter equality against the oracle, which is what
entitles bench.py to use its wall clock as ``vs_baseline``.
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys

import numpy as np

_DIR = pathlib.Path(__file__).resolve().parent
_REPO = _DIR.parent.parent
_BUILD = _REPO / "build" / "native"
_BIN = _BUILD / "phold_comparator"
_TABLE = _BUILD / "log2_q32.tbl"


class NativeUnavailable(RuntimeError):
    pass


def _dump_table() -> None:
    from shadow1_tpu import rng

    tbl = np.asarray(rng._LOG_TBL_NP, np.uint64)
    assert tbl.shape == (2**rng._LOG_BITS + 1,)
    with open(_TABLE, "wb") as f:
        f.write(tbl.tobytes())
        f.write(np.uint64(rng._LN2_Q32).tobytes())


def ensure_built(force: bool = False) -> pathlib.Path:
    src = _DIR / "phold_comparator.cpp"
    rng_src = _REPO / "shadow1_tpu" / "rng.py"
    _BUILD.mkdir(parents=True, exist_ok=True)
    # Re-dump when rng.py is newer than the table: a stale table would make
    # the comparator silently non-identical to the jnp/numpy engines.
    if force or not _TABLE.exists() or _TABLE.stat().st_mtime < rng_src.stat().st_mtime:
        _dump_table()
    if not force and _BIN.exists() and _BIN.stat().st_mtime >= src.stat().st_mtime:
        return _BIN
    cmd = ["g++", "-O2", "-std=c++17", "-pthread", "-o", str(_BIN), str(src)]
    try:
        out = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    except (FileNotFoundError, subprocess.TimeoutExpired) as e:
        raise NativeUnavailable(f"g++ unavailable: {e!r}") from e
    if out.returncode != 0:
        raise NativeUnavailable(f"g++ failed: {out.stderr[-800:]}")
    return _BIN


def run_phold(
    n_hosts: int,
    seed: int,
    n_windows: int,
    window_ns: int,
    mean_delay_ns: float,
    init_events: int,
    ev_cap: int,
    outbox_cap: int,
    n_threads: int | None = None,
    timeout_s: float = 900.0,
) -> dict:
    """Run the comparator; returns its counters + wall_s + events_per_sec."""
    binary = ensure_built()
    if n_threads is None:
        n_threads = os.cpu_count() or 1
    cmd = [
        str(binary), str(_TABLE), str(n_hosts), str(seed), str(n_windows),
        str(window_ns), str(int(round(mean_delay_ns))), str(init_events),
        str(ev_cap), str(outbox_cap), str(n_threads),
    ]
    out = subprocess.run(cmd, capture_output=True, text=True, timeout=timeout_s)
    if out.returncode != 0:
        raise NativeUnavailable(
            f"comparator rc={out.returncode}: {out.stderr[-500:]}"
        )
    return json.loads(out.stdout.strip().splitlines()[-1])


_NET_BIN = _BUILD / "net_comparator"


def ensure_net_built(force: bool = False) -> pathlib.Path:
    src = _DIR / "net_comparator.cpp"
    rng_src = _REPO / "shadow1_tpu" / "rng.py"
    _BUILD.mkdir(parents=True, exist_ok=True)
    if force or not _TABLE.exists() or _TABLE.stat().st_mtime < rng_src.stat().st_mtime:
        _dump_table()
    if not force and _NET_BIN.exists() and _NET_BIN.stat().st_mtime >= src.stat().st_mtime:
        return _NET_BIN
    cmd = ["g++", "-O2", "-std=c++17", "-pthread", "-o", str(_NET_BIN), str(src)]
    try:
        out = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    except (FileNotFoundError, subprocess.TimeoutExpired) as e:
        raise NativeUnavailable(f"g++ unavailable: {e!r}") from e
    if out.returncode != 0:
        raise NativeUnavailable(f"g++ failed: {out.stderr[-800:]}")
    return _NET_BIN


_NET_MAGIC = 0x53484457434D5032


def dump_net_config(exp, params, n_windows: int, path: str) -> None:
    """Serialize a net-model CompiledExperiment for the C++ comparator.

    Refuses configs using fidelity knobs the comparator does not mirror
    (stop/cpu/qlen/aqm) — silent divergence would be worse than no
    baseline. The layout matches read_config in net_comparator.cpp."""
    from shadow1_tpu import rng

    for knob, name in (
        (np.asarray(exp.stop_time).min() < (1 << 62), "host stop times"),
        (getattr(exp, "faults", None) is not None, "fault schedule"),
        (np.asarray(exp.cpu_ns_per_event).max() > 0, "virtual CPU"),
        (np.asarray(exp.tx_qlen_bytes).max() > 0, "tx queue bound"),
        (np.asarray(exp.rx_qlen_bytes).max() > 0, "rx queue bound"),
        (np.asarray(exp.aqm_max_bytes).max() > 0, "RED AQM"),
    ):
        if knob:
            raise NativeUnavailable(
                f"net comparator does not model {name}; config refused"
            )
    assert exp.model == "net"
    cfg = exp.model_cfg
    app = cfg["app"]
    h = exp.n_hosts
    pr = params
    lat = np.asarray(exp.lat_vv, np.int64)
    V = lat.shape[0]
    jit = np.asarray(exp.jitter_vv, np.int64)
    loss_thr = rng.prob_threshold(np.asarray(exp.loss_vv))
    z = np.zeros(0, np.int64)
    u0 = np.zeros(0, np.uint64)

    def rounded_mean(x):
        return np.round(np.asarray(x, np.float64)).astype(np.uint64)

    a = {i: z for i in range(5)}
    m0 = m1 = u0
    s = [0, 0, 0, 0, 0]
    tids = [z, z, z, z]
    tcum = [z, z, z]
    peers = z
    if app == "filexfer":
        app_id = 1
        a = {0: cfg["role"], 1: cfg["server"], 2: cfg["flow_bytes"],
             3: cfg["start_time"], 4: cfg["flow_count"]}
    elif app == "tgen":
        app_id = 2
        mb = np.asarray(cfg["mean_bytes"], np.float64)
        a = {0: cfg["active"], 1: cfg["streams"], 2: z, 3: cfg["start_time"],
             4: np.maximum(mb.astype(np.int64), 1)}
        m0, m1 = rounded_mean(mb), rounded_mean(cfg["mean_think_ns"])
        s[0] = 1 if cfg.get("fixed_size") else 0
    elif app == "tor":
        from shadow1_tpu.apps.tor import tables

        app_id = 3
        t = tables(cfg)
        a = {0: cfg["role"], 1: cfg["n_circuits"], 2: cfg["n_streams"],
             3: cfg["start_time"], 4: z}
        m0 = rounded_mean(cfg["mean_stream_cells"])
        m1 = rounded_mean(cfg["mean_think_ns"])
        s[0] = int(cfg.get("consensus_bytes", 2048))
        s[1] = int(cfg.get("cells_max", 120))
        s[2] = int(cfg.get("ct_cap", 64))
        tids = [t["guard_ids"], t["exit_ids"], t["relay_ids"], t["dir_ids"]]
        tcum = [t["guard_cum"], t["exit_cum"], t["relay_cum"]]
    elif app == "bitcoin":
        app_id = 4
        p2 = np.asarray(cfg["peers"], np.int64)
        a = {0: cfg["tx_origin"], 1: cfg["tx_time"], 2: z, 3: z, 4: z}
        s[0] = int(cfg.get("tx_size", 400))
        s[1] = int(cfg.get("inv_size", 36))
        s[2] = int(cfg.get("connect_time", 0))
        s[3] = p2.shape[1]
        s[4] = len(np.asarray(cfg["tx_origin"]))
        peers = p2.reshape(-1)
    else:
        raise NativeUnavailable(f"net comparator: unknown app {app!r}")

    def w_i64(f, x):
        f.write(np.asarray(x, np.int64).tobytes())

    def w_vec(f, x, dt=np.int64):
        arr = np.asarray(x, dt)
        w_i64(f, arr.size)
        f.write(arr.tobytes())

    with open(path, "wb") as f:
        f.write(np.uint64(_NET_MAGIC).tobytes())
        for v in (h, exp.seed, exp.window, n_windows, pr.ev_cap,
                  pr.outbox_cap, pr.sockets_per_host, pr.msgq_cap,
                  pr.send_burst, pr.mss, pr.init_cwnd_mss, pr.sndbuf,
                  pr.rcvbuf, pr.rto_min, pr.rto_max, pr.rto_init,
                  pr.dupack_thresh, V, int(jit.max() > 0), app_id):
            w_i64(f, v)
        w_vec(f, lat.reshape(-1))
        w_vec(f, jit.reshape(-1))
        w_vec(f, loss_thr.reshape(-1), np.uint64)
        w_vec(f, exp.host_vertex)
        w_vec(f, exp.bw_up)
        w_vec(f, exp.bw_dn)
        for i in range(5):
            w_vec(f, a[i])
        w_vec(f, m0, np.uint64)
        w_vec(f, m1, np.uint64)
        for v in s:
            w_i64(f, v)
        w_vec(f, tids[0]); w_vec(f, tcum[0])
        w_vec(f, tids[1]); w_vec(f, tcum[1])
        w_vec(f, tids[2]); w_vec(f, tcum[2])
        w_vec(f, tids[3])
        w_vec(f, peers)


def run_net(exp, params, n_windows: int, n_threads: int | None = None,
            timeout_s: float = 3600.0) -> dict:
    """Run the net comparator on a CompiledExperiment; returns counters +
    wall_s + events_per_sec (bit-identical counters to both engines)."""
    import tempfile

    binary = ensure_net_built()
    if n_threads is None:
        n_threads = os.cpu_count() or 1
    with tempfile.NamedTemporaryFile(suffix=".blob", delete=False) as tf:
        blob = tf.name
    try:
        dump_net_config(exp, params, n_windows, blob)
        cmd = [str(binary), str(_TABLE), blob, str(n_threads)]
        try:
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=timeout_s)
        except subprocess.TimeoutExpired as e:
            raise NativeUnavailable(
                f"net comparator exceeded {timeout_s:.0f}s"
            ) from e
        if out.returncode != 0:
            raise NativeUnavailable(
                f"net comparator rc={out.returncode}: {out.stderr[-500:]}"
            )
        try:
            return json.loads(out.stdout.strip().splitlines()[-1])
        except (ValueError, IndexError) as e:
            raise NativeUnavailable(
                f"net comparator produced no result line: {e!r}"
            ) from e
    finally:
        os.unlink(blob)


if __name__ == "__main__":
    print(json.dumps(run_phold(*map(int, sys.argv[1:]))))
