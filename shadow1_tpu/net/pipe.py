"""Intra-host pipes — the local-IPC analogue of channel.c.

The reference gives co-located processes pipes/socketpairs
(src/main/host/descriptor/channel.c): a byte FIFO between two descriptors
on one host, with readable/writable status feeding epoll. The tensor
analogue keeps a fixed table of pipes per host as SoA columns — byte
counts + a small message FIFO, exactly the modeling convention of the TCP
stack (lengths + metas, no real bytes) — and delivery is the same-host
self-event pattern every app layer already uses: the writer pushes a K_APP
wakeup for the reader at ``now`` (the next round), the batch analogue of
the descriptor-status → epoll → callback chain.

FIFO order is carried by per-message sequence stamps (slot indices are
storage, not order). All updates are dense one-hot ops (core/dense.py);
everything is masked per host, so apps drive any subset of hosts per round.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from shadow1_tpu.core.dense import get_col, onehot_col

_SEQ_MAX = jnp.int32(2**31 - 1)


class PipeTable(NamedTuple):
    buffered: jnp.ndarray  # i32 [H, P] bytes queued
    mq_len: jnp.ndarray    # i32 [H, P, M] message lengths (0 = free slot)
    mq_meta: jnp.ndarray   # i32 [H, P, M]
    mq_seq: jnp.ndarray    # i32 [H, P, M] FIFO stamp of each message
    next_seq: jnp.ndarray  # i32 [H, P]
    written: jnp.ndarray   # i64 [H, P] lifetime bytes written
    drained: jnp.ndarray   # i64 [H, P] lifetime bytes read


def pipe_init(n_hosts: int, n_pipes: int, mq_cap: int = 8) -> PipeTable:
    z32 = lambda *s: jnp.zeros(s, jnp.int32)  # noqa: E731
    return PipeTable(
        buffered=z32(n_hosts, n_pipes),
        mq_len=z32(n_hosts, n_pipes, mq_cap),
        mq_meta=z32(n_hosts, n_pipes, mq_cap),
        mq_seq=z32(n_hosts, n_pipes, mq_cap),
        next_seq=z32(n_hosts, n_pipes),
        written=jnp.zeros((n_hosts, n_pipes), jnp.int64),
        drained=jnp.zeros((n_hosts, n_pipes), jnp.int64),
    )


def pipe_write(pt: PipeTable, mask, pipe, nbytes, meta, capacity: int):
    """Write one message per host where ``mask``: accepted only if the
    bytes fit ``capacity`` AND a message slot is free (all-or-nothing, like
    tcp_send's boundary admission). Returns (pt, ok[H])."""
    nbytes = jnp.asarray(nbytes, jnp.int32)
    cur = get_col(pt.buffered, pipe)
    fits = (cur + nbytes) <= capacity
    mq = get_col(pt.mq_len, pipe)            # [H, M]
    free = mq == 0
    has_free = free.any(axis=1)
    slot = jnp.argmax(free, axis=1).astype(jnp.int32)
    ok = mask & fits & has_free
    seq = get_col(pt.next_seq, pipe)
    sel = onehot_col(pipe, pt.buffered.shape[1], ok)
    sel3 = sel[:, :, None] & onehot_col(slot, pt.mq_len.shape[2])[:, None, :]
    return pt._replace(
        buffered=jnp.where(sel, cur[:, None] + nbytes[:, None], pt.buffered),
        mq_len=jnp.where(sel3, nbytes[:, None, None], pt.mq_len),
        mq_meta=jnp.where(sel3, jnp.asarray(meta, jnp.int32)[:, None, None],
                          pt.mq_meta),
        mq_seq=jnp.where(sel3, seq[:, None, None], pt.mq_seq),
        next_seq=pt.next_seq + sel.astype(jnp.int32),
        written=pt.written + jnp.where(sel, nbytes[:, None].astype(jnp.int64), 0),
    ), ok


def pipe_read(pt: PipeTable, mask, pipe):
    """Read the OLDEST pending message of the pipe (min sequence stamp).
    Returns (pt, got[H], nbytes[H], meta[H])."""
    mq = get_col(pt.mq_len, pipe)             # [H, M]
    pending = mq != 0
    has = pending.any(axis=1)
    seqs = jnp.where(pending, get_col(pt.mq_seq, pipe), _SEQ_MAX)
    slot = jnp.argmin(seqs, axis=1).astype(jnp.int32)
    got = mask & has
    nbytes = jnp.where(got, get_col(mq, slot), 0)
    meta = jnp.where(got, get_col(get_col(pt.mq_meta, pipe), slot), 0)
    sel = onehot_col(pipe, pt.buffered.shape[1], got)
    sel3 = sel[:, :, None] & onehot_col(slot, pt.mq_len.shape[2])[:, None, :]
    return pt._replace(
        buffered=jnp.where(sel, pt.buffered - nbytes[:, None], pt.buffered),
        mq_len=jnp.where(sel3, 0, pt.mq_len),
        drained=pt.drained + jnp.where(sel, nbytes[:, None].astype(jnp.int64), 0),
    ), got, nbytes, meta


def pipe_readable(pt: PipeTable, pipe):
    """bool [H]: the pipe has a pending message."""
    return (get_col(pt.mq_len, pipe) != 0).any(axis=1)
