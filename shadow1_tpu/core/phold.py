"""PHOLD — the classic parallel-DES stress workload, in tensors.

The reference exercises its scheduler policies with a PHOLD-style benchmark
(SURVEY §4, src/test/phold/): every host holds live events; executing one
draws an exponential delay and a uniformly random destination and schedules
the next hop there. It stresses exactly the machinery this engine batches —
pop-min, cross-host push, window barriers — with no network stack on top.

model_cfg: ``mean_delay_ns`` (float), ``init_events`` (events seeded per
host at t=0, default 1).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from shadow1_tpu import rng
from shadow1_tpu.consts import K_PHOLD, NP, R_PHOLD_DELAY, R_PHOLD_DST
from shadow1_tpu.core.events import EventBuf, Popped, push_local
from shadow1_tpu.core.outbox import outbox_append


class PholdState(NamedTuple):
    hops: jnp.ndarray  # i64 [H] events executed per host
    ctr: jnp.ndarray   # i64 [H] per-host draw counter


def init(ctx, evbuf: EventBuf):
    n = int(ctx.model_cfg.get("init_events", 1))
    zero_p = jnp.zeros((NP, ctx.n_hosts), jnp.int32)
    all_hosts = jnp.ones(ctx.n_hosts, bool)
    t0 = jnp.zeros(ctx.n_hosts, jnp.int64)
    k = jnp.full(ctx.n_hosts, K_PHOLD, jnp.int32)
    seed_over = jnp.zeros((), jnp.int64)
    for _ in range(n):
        evbuf, over = push_local(evbuf, all_hosts, t0, k, zero_p)
        seed_over = seed_over + over.sum(dtype=jnp.int64)
    state = PholdState(
        hops=jnp.zeros(ctx.n_hosts, jnp.int64),
        ctr=jnp.zeros(ctx.n_hosts, jnp.int64),
    )
    return state, evbuf, seed_over


def make_handlers(ctx):
    mean = float(ctx.model_cfg["mean_delay_ns"])
    hosts = ctx.hosts

    def on_phold(st, ev: Popped):
        m = ev.mask & (ev.kind == K_PHOLD)
        model: PholdState = st.model
        delay = rng.exponential_ns(
            rng.bits_v(ctx.key, R_PHOLD_DELAY, hosts, model.ctr), mean
        )
        dst = rng.randint(rng.bits_v(ctx.key, R_PHOLD_DST, hosts, model.ctr), ctx.n_total)
        t_next = ev.time + delay
        zero_p = jnp.zeros((NP, ctx.n_hosts), jnp.int32)
        k = jnp.full(ctx.n_hosts, K_PHOLD, jnp.int32)
        local = m & (dst == hosts)
        evbuf, over = push_local(st.evbuf, local, t_next, k, zero_p)
        remote = m & ~local
        outbox, ok = outbox_append(st.outbox, remote, dst, k, t_next, zero_p)
        met = st.metrics
        return st._replace(
            evbuf=evbuf,
            outbox=outbox,
            model=PholdState(
                hops=model.hops + m.astype(jnp.int64),
                ctr=model.ctr + m.astype(jnp.int64),
            ),
            metrics=met._replace(
                ev_overflow=met.ev_overflow + over.sum(dtype=jnp.int64),
                ob_overflow=met.ob_overflow + (remote & ~ok).sum(dtype=jnp.int64),
            ),
        )

    return {K_PHOLD: on_phold}


def summary(model: PholdState, ctx=None) -> dict:
    return {"hops": model.hops, "total_hops": model.hops.sum()}
