"""Heartbeat/tracker/ring log analysis — the tools/ plotting-scripts analogue.

The reference ships helper scripts that parse heartbeat logs into
throughput/RTT tables and plots (SURVEY §2.6 tools/). This reads the JSON
lines the CLI emits (--heartbeat → engine heartbeats on stderr; --tracker →
per-host records; --metrics-ring → per-window telemetry rows) and prints
summary tables plus optional CSVs for plotting. Record schemas are the
telemetry registry's (docs/OBSERVABILITY.md) — one namespace, not three.

    python -m shadow1_tpu.tools.heartbeat_report run.log [--csv out.csv]
        [--ring-csv ring.csv]
"""

from __future__ import annotations

import argparse
import csv
import json
import math
import sys

from shadow1_tpu.telemetry.registry import (
    DROP_SPECS,
    REC_FLEET_EXP,
    REC_FLEET_QUARANTINE,
    REC_FLEET_RETRY,
    REC_FLEET_SUMMARY,
    REC_FLOW,
    REC_HEARTBEAT,
    REC_LINEAGE,
    REC_LINK,
    REC_MEM,
    REC_RESUME,
    REC_RING,
    REC_RING_GAP,
    REC_SERVE,
    REC_SERVE_DEADLINE,
    REC_SERVE_JOB,
    REC_SERVE_QUEUE,
    REC_SERVE_RETRY,
    REC_TRACKER,
    REC_WORK,
    RING_COUNTERS,
    RING_FIELDS,
    RING_GAUGES,
)

# jax-free by design (mem.py imports jax only inside the estimator
# functions) — one byte formatter for every surface, never two drifting.
from shadow1_tpu.mem import fmt_bytes as _fmt_bytes


def load_records(path: str) -> list[dict]:
    recs = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                recs.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return recs


def percentile(values: list, q: float):
    """Nearest-rank percentile on a small series (no numpy dependency)."""
    if not values:
        return None
    s = sorted(values)
    idx = math.ceil(q / 100 * len(s)) - 1
    return s[min(len(s) - 1, max(0, idx))]


def work_summary(rows: list[dict], n_hosts: int | None) -> dict | None:
    """Work-efficiency distribution (performance attribution plane).

    ``rows`` are per-window records carrying the RING_WORK columns — ring
    records from the batched engines, or the CPU oracle's ``work`` rows.
    With ``n_hosts`` known (the heartbeat ``work`` block or the final CLI
    JSON carries it) the stats are FRACTIONS: active-host fraction
    (active_hosts/H — the live signal for "0.1% of hosts pay full [cap, H]
    plane passes"), pop-scan efficiency (events popped per pop lane
    scanned, events/(rounds·H); sharded logs sum per-shard rounds, so
    treat it as per-shard-lane efficiency there) and outbox-host fraction.
    p50/p95/min — MIN because the worst window is the wasted-work story.
    These stats are deliberately NOT part of ring_summary: the RING_WORK
    columns are utilization samples, not counter deltas, and stay out of
    the occupancy percentile table (the digest/retry-column rule)."""
    series: dict[str, list] = {"active_frac": [], "pop_scan_eff": [],
                               "outbox_frac": [], "active_hosts": [],
                               "elig_events": []}
    for r in rows:
        if "active_hosts" not in r:
            continue
        series["active_hosts"].append(r["active_hosts"])
        if "elig_events" in r:
            series["elig_events"].append(r["elig_events"])
        if n_hosts:
            series["active_frac"].append(r["active_hosts"] / n_hosts)
            if "outbox_hosts" in r:
                series["outbox_frac"].append(r["outbox_hosts"] / n_hosts)
            if r.get("rounds"):
                series["pop_scan_eff"].append(
                    r.get("events", 0) / (r["rounds"] * n_hosts))
    if not series["active_hosts"]:
        return None
    out: dict = {"windows": len(series["active_hosts"])}
    if n_hosts:
        out["n_hosts"] = n_hosts
    for name, vals in series.items():
        if not vals:
            continue
        rnd = (lambda v: round(v, 6)) if n_hosts else (lambda v: v)
        out[name] = {
            "p50": rnd(percentile(vals, 50)),
            "p95": rnd(percentile(vals, 95)),
            "min": rnd(min(vals)),
        }
    return out


def _print_work(ws: dict, out) -> None:
    print(f"  windows: {ws['windows']}"
          + (f"  n_hosts: {ws['n_hosts']}" if "n_hosts" in ws else ""),
          file=out)
    labels = {
        "active_frac": "active-host fraction (active_hosts/H)",
        "pop_scan_eff": "pop-scan efficiency (events/(rounds*H))",
        "outbox_frac": "outbox-host fraction (outbox_hosts/H)",
        "active_hosts": "active hosts (absolute)",
        "elig_events": "eligible events (absolute)",
    }
    # Fractions when the host count is known; absolute counts otherwise.
    keys = (("active_frac", "pop_scan_eff", "outbox_frac")
            if "active_frac" in ws else ("active_hosts", "elig_events"))
    for key in keys:
        if key in ws:
            d = ws[key]
            print(f"  {labels[key]}: p50 {d['p50']}  p95 {d['p95']}  "
                  f"min {d['min']}", file=out)


def _log_n_hosts(recs: list[dict]) -> int | None:
    """The host count, from the heartbeat ``work`` block (preferred) or the
    CLI's final JSON record — the denominator the fractions need."""
    for r in recs:
        w = r.get("work")
        if isinstance(w, dict) and w.get("n_hosts"):
            return int(w["n_hosts"])
    for r in recs:
        if r.get("hosts") and isinstance(r.get("metrics"), dict):
            return int(r["hosts"])
    return None


def ring_summary(rings: list[dict]) -> dict:
    """Per-window occupancy distribution: p50/p95/max for each ring field.

    This is the table the rung-cap sizing debates need (docs/R6_NOTES.md):
    the chunk-averaged heartbeat hides the spikes; the ring records them."""
    out: dict = {"windows": len(rings)}
    # Digest columns (RING_DIGESTS) are identity words, not magnitudes —
    # percentiles over them are noise, so only counters/gauges rank here.
    for field in RING_COUNTERS + RING_GAUGES:
        series = [r[field] for r in rings if field in r]
        if not series:
            continue
        out[field] = {
            "p50": percentile(series, 50),
            "p95": percentile(series, 95),
            "max": max(series),
        }
    return out


def summarize(recs: list[dict], out=None) -> dict:
    # sys.stdout resolved at call time, not def time — a def-time default
    # pins whatever stream an importer (e.g. pytest capture) had installed.
    out = out if out is not None else sys.stdout
    hb = [r for r in recs if r.get("type") == REC_HEARTBEAT]
    tr = [r for r in recs if r.get("type") == REC_TRACKER]
    rings = [r for r in recs if r.get("type") == REC_RING]
    gaps = [r for r in recs if r.get("type") == REC_RING_GAP]
    works = [r for r in recs if r.get("type") == REC_WORK]
    # Early-finalized lanes' fleet_exp records reach stdout AND the stderr
    # log stream — a combined capture holds them twice: dedupe by lane.
    fleet_exp = list({r.get("exp"): r for r in recs
                      if r.get("type") == REC_FLEET_EXP}.values())
    summary: dict = {
        "heartbeats": len(hb),
        "tracker_records": len(tr),
        "ring_records": len(rings),
    }
    if fleet_exp:
        # Fleet final records: one row per experiment (events, drops,
        # restarts) — the sweep's result table. Early-finished lanes
        # (--lane-finalize) are flagged inline with the window count they
        # actually ran.
        summary["fleet_experiments"] = len(fleet_exp)
        print("== fleet experiments ==", file=out)
        for r in sorted(fleet_exp, key=lambda r: r.get("exp", 0)):
            m = r.get("metrics", {})
            drops = r.get("drops", {})
            early = (f"  [finished early at window {r.get('windows')}]"
                     if r.get("finished_early") else "")
            print(f"  exp {r.get('exp')}: seed {r.get('seed')}  "
                  f"events {m.get('events')}  "
                  f"delivered {m.get('pkts_delivered')}  "
                  f"drops {drops.get('total', 0)}  "
                  f"restarts {m.get('host_restarts', 0)}{early}",
                  file=out)
    # Fleet recovery plane (fleet/run.py): per-retry and per-quarantine
    # events plus the summary ledger. These are their OWN record types —
    # chunk-level events, not per-window rows — so like the digest/retry
    # columns they never enter the ring percentile math below.
    # Quarantine records reach BOTH stdout (the CLI result stream) and
    # stderr (the log stream) — a combined capture holds each twice, and a
    # killed+relaunched run re-emits them on replay: dedupe by lane.
    fq = list({r.get("exp"): r for r in recs
               if r.get("type") == REC_FLEET_QUARANTINE}.values())
    fr = [r for r in recs if r.get("type") == REC_FLEET_RETRY]
    early = [r for r in fleet_exp if r.get("finished_early")]
    fsum = [r for r in recs if r.get("type") == REC_FLEET_SUMMARY]
    if fq or fr or early:
        rec_sum: dict = {"chunk_retries": sum(1 for r in fr
                                              if not r.get("discarded")),
                         "quarantined": len(fq),
                         "finished_early": len(early)}
        # Per-lane retry counts: how many chunks each experiment's
        # overflow tainted (sweep-global ids from the fleet_retry
        # records). One count per RECORD even when a lane overflowed
        # several counters in the same chunk; grows rolled back by a
        # quarantine (``discarded``) are audit-only and stay out.
        per_lane: dict = {}
        for r in fr:
            if r.get("discarded"):
                continue
            gids = {g for gl in (r.get("lanes") or {}).values()
                    for g in gl}
            for g in gids:
                per_lane[g] = per_lane.get(g, 0) + 1
        if per_lane:
            rec_sum["retries_by_exp"] = per_lane
        summary["fleet_recovery"] = rec_sum
        print("== fleet recovery ==", file=out)
        print(f"  chunk retries: {rec_sum['chunk_retries']}"
              f"  quarantined lanes: {len(fq)}"
              f"  early-finished lanes: {len(early)}", file=out)
        for g, n in sorted(per_lane.items()):
            print(f"  exp {g}: tainted {n} chunk(s)", file=out)
        for r in fr:
            grown = {k: v for k, v in r.items()
                     if k in ("ev_cap", "outbox_cap", "x2x_cap")}
            tag = ("  [discarded: rolled back by a quarantine]"
                   if r.get("discarded") else "")
            print(f"  retry {r.get('retry')}: windows {r.get('windows')}"
                  f"  grown {grown}{tag}", file=out)
        for r in fq:
            print(f"  quarantine: exp {r.get('exp')} (seed "
                  f"{r.get('seed')}) — {r.get('reason')}"
                  + (f" on {r.get('knob')}" if r.get("knob") else "")
                  + f" at window {r.get('window')}; solo-resumable ckpt "
                    f"{r.get('ckpt')}", file=out)
        for r in early:
            print(f"  finished early: exp {r.get('exp')} at window "
                  f"{r.get('windows')} of {r.get('windows_configured')}",
                  file=out)
        if fsum and fsum[-1].get("quarantined"):
            print(f"  sweep completed "
                  f"{fsum[-1].get('experiments')}+"
                  f"{len(fsum[-1]['quarantined'])}q/"
                  f"{fsum[-1].get('experiments_initial')}", file=out)
    if hb:
        eps = [r["events_per_sec"] for r in hb if r.get("events_per_sec")]
        spw = [r["sim_per_wall"] for r in hb if r.get("sim_per_wall")]
        summary.update(
            sim_time_s=hb[-1]["sim_time_s"],
            wall_s=hb[-1]["wall_s"],
            events=sum(r["delta"].get("events", 0) for r in hb),
            events_per_sec_mean=round(sum(eps) / len(eps), 1) if eps else None,
            sim_per_wall_mean=round(sum(spw) / len(spw), 4) if spw else None,
            pkts_delivered=sum(r["delta"].get("pkts_delivered", 0) for r in hb),
            retransmits=sum(
                r["delta"].get("tcp_rto", 0) + r["delta"].get("tcp_fast_rtx", 0)
                for r in hb
            ),
        )
        print("== run summary ==", file=out)
        for k, v in summary.items():
            print(f"  {k}: {v}", file=out)
    if hb:
        # Drop-reason table: the heartbeat ``drops`` blocks summed over the
        # run, one labeled row per reason (telemetry.registry.DROP_SPECS).
        # Pre-drops-block logs fall back to the flat delta counters.
        drop_totals = {f: 0 for f in DROP_SPECS}
        for r in hb:
            src = r.get("drops") if isinstance(r.get("drops"), dict) else \
                r.get("delta", {})
            for f in DROP_SPECS:
                v = src.get(f)
                if isinstance(v, (int, float)):
                    drop_totals[f] += int(v)
        total_drops = sum(drop_totals.values())
        summary["drops"] = {"total": total_drops, **drop_totals}
        print("== drops by reason ==", file=out)
        if total_drops == 0:
            print("  none (clean run: every modeled event/packet survived "
                  "its bounds)", file=out)
        for f, reason in DROP_SPECS.items():
            if drop_totals[f]:
                print(f"  {f}: {drop_totals[f]}  ({reason})", file=out)
    if hb:
        # Overflow-retry plane (shadow1_tpu/txn.py): heartbeat ``retries``
        # blocks carry CUMULATIVE host-side counters, so the last block is
        # the run total. Chunk-level counters, not per-window rows — they
        # are excluded from the ring percentile stats below by the same
        # rule that keeps the digest identity columns out (PR 3): only
        # RING_COUNTERS/RING_GAUGES rank there.
        rt = [r["retries"] for r in hb if isinstance(r.get("retries"), dict)]
        if rt:
            last = rt[-1]
            summary["retries"] = {
                k: last.get(k)
                for k in ("policy", "chunk_retries", "retry_windows_rerun")
            }
            print("== overflow retries (transactional chunks) ==", file=out)
            print(f"  chunk_retries: {last.get('chunk_retries')}  "
                  f"windows re-run: {last.get('retry_windows_rerun')}",
                  file=out)
            if isinstance(last.get("caps"), dict):
                caps = "  ".join(f"{k}: {v}"
                                 for k, v in last["caps"].items())
                print(f"  final caps: {caps}", file=out)
    resumes = [r for r in recs if r.get("type") == REC_RESUME]
    lineage = [r for r in recs if r.get("type") == REC_LINEAGE]
    if resumes or lineage:
        # Preemption/lineage plane: how the run survived — resumes taken
        # (which generation each landed on), corrupt heads skipped,
        # watchdog kills, drains (docs/OBSERVABILITY.md §"Resume and
        # lineage records").
        wk = [r for r in lineage if r.get("event") == "watchdog_kill"]
        drains = [r for r in lineage if r.get("event") == "preempted"]
        summary["lineage"] = {
            "resumes": len(resumes),
            "fallback_skipped": sum(r.get("fallback_skipped", 0)
                                    for r in resumes),
            "watchdog_kills": len(wk),
            "preempted_drains": len(drains),
        }
        if resumes:
            summary["lineage"]["generations_kept"] = \
                resumes[-1].get("generations_kept")
        print("== lineage (preemption/resume) ==", file=out)
        for k, v in summary["lineage"].items():
            print(f"  {k}: {v}", file=out)
        for r in resumes:
            extra = (f"  ({r['fallback_skipped']} corrupt newer "
                     f"generation(s) skipped)"
                     if r.get("fallback_skipped") else "")
            print(f"  resume: generation {r.get('generation')} at "
                  f"sim_ns {r.get('win_start')}{extra}", file=out)
        for r in wk:
            print(f"  watchdog kill: sidecar stale > {r.get('stale_s')}s "
                  f"at sim_ns {r.get('sim_ns')} (attempt "
                  f"{r.get('attempt')})", file=out)
    mems = [r for r in recs if r.get("type") == REC_MEM]
    ooms = [r for r in recs if r.get("error") in ("memory_budget",
                                                  "memory_exhausted")]
    if mems or ooms:
        # Memory plane (shadow1_tpu/mem.py): estimated vs reported peak
        # bytes, per-plane attribution, applied downshifts, OOM/budget
        # errors. Mem records are their own type — like the digest and
        # retry columns, none of these fields ever enter the ring
        # percentile math below (only RING_COUNTERS/RING_GAUGES rank).
        est = next((r for r in mems if r.get("event") == "estimate"), None)
        downs = [r for r in mems if r.get("event") == "downshift"]
        final = next((r for r in reversed(mems)
                      if r.get("event") == "final"), None)
        msum: dict = {}
        print("== memory (estimate vs device) ==", file=out)
        if est is not None:
            msum.update(estimated_state=est.get("estimated_state"),
                        estimated_peak=est.get("estimated_peak"),
                        budget=est.get("budget"),
                        headroom=est.get("headroom"))
            print(f"  estimated: state {_fmt_bytes(est.get('estimated_state'))}"
                  f"  resident {_fmt_bytes(est.get('estimated_resident'))}"
                  f"  peak {_fmt_bytes(est.get('estimated_peak'))}",
                  file=out)
            budget = est.get("budget")
            if budget is not None:
                print(f"  budget: {_fmt_bytes(budget)} "
                      f"({est.get('budget_source')})  headroom: "
                      f"{_fmt_bytes(est.get('headroom'))}", file=out)
            planes = {**est.get("planes", {}), **est.get("peaks", {})}
            for k, v in sorted(planes.items(), key=lambda kv: -kv[1]):
                if v:
                    print(f"  {k}: {_fmt_bytes(v)}", file=out)
        if final is not None and final.get("peak_in_use") is not None:
            msum["peak_in_use"] = final["peak_in_use"]
            print(f"  reported peak in use: "
                  f"{_fmt_bytes(final['peak_in_use'])} (backend) vs "
                  f"estimated {_fmt_bytes(final.get('estimated_peak'))}",
                  file=out)
        for r in downs:
            msum.setdefault("downshifts", []).extend(r.get("actions", []))
            acts = ", ".join(a.get("action", "?") for a in
                             r.get("actions", []))
            print(f"  downshift applied: {acts} → peak "
                  f"{_fmt_bytes(r.get('estimated_peak'))} within "
                  f"{_fmt_bytes(r.get('budget'))}", file=out)
        for r in ooms:
            msum.setdefault("errors", []).append(r["error"])
            where = (f" during {r['phase']}" if r.get("phase") else "")
            if r["error"] == "memory_budget":
                detail = (f"estimated {_fmt_bytes(r.get('estimated'))} vs "
                          f"budget {_fmt_bytes(r.get('budget'))}")
            else:  # memory_exhausted carries the runtime message instead
                detail = str(r.get("message", ""))[:160]
            print(f"  ERROR {r['error']}{where}: {detail}", file=out)
        summary["memory"] = msum
    serve_ev = [r for r in recs if r.get("type") == REC_SERVE]
    serve_jobs = [r for r in recs if r.get("type") == REC_SERVE_JOB]
    if serve_ev or serve_jobs:
        # Serve plane (shadow1_tpu/serve/): the daemon's job ledger from
        # its serve.log stream. Daemon-level events, never per-window rows
        # — the same digest/retry-column rule keeps every serve field out
        # of the ring percentile math below.
        by_job: dict[str, list[dict]] = {}
        for r in serve_jobs:
            by_job.setdefault(r.get("job", "?"), []).append(r)
        batches = [r for r in serve_ev if r.get("event") == "batch_start"]
        evicts = [r for r in serve_ev if r.get("event") == "evict"]
        deadlines = [r for r in recs
                     if r.get("type") == REC_SERVE_DEADLINE]
        retries = [r for r in recs if r.get("type") == REC_SERVE_RETRY]
        cache = {"hit": 0, "miss": 0}
        for b in batches:
            if b.get("cache") in cache:
                cache[b["cache"]] += 1
        # Queue wait = admission → first batch_start, read off each job's
        # own status transitions (the spool timeline, not the ledger):
        # the backpressure plane's effect as tenants actually felt it.
        waits = []
        for rows in by_job.values():
            q_t = next((r.get("t") for r in rows
                        if r.get("state") in ("queued",
                                              "waiting_headroom")), None)
            r_t = next((r.get("t") for r in rows
                        if r.get("state") == "running"), None)
            if q_t is not None and r_t is not None and r_t >= q_t:
                waits.append(r_t - q_t)
        retry_by_job: dict[str, int] = {}
        for r in retries:
            if r.get("event") == "retry":
                for j in r.get("jobs", []):
                    retry_by_job[j] = retry_by_job.get(j, 0) + 1
        ssum = {
            "jobs": len(by_job),
            "batches": len(batches),
            "cache_hits": cache["hit"],
            "cache_misses": cache["miss"],
            "evictions": len(evicts),
            "deadline_expiries": len(deadlines),
            "batch_retries": sum(1 for r in retries
                                 if r.get("event") == "retry"),
        }
        if waits:
            ssum["queue_wait_p50_s"] = round(percentile(waits, 50), 3)
            ssum["queue_wait_p95_s"] = round(percentile(waits, 95), 3)
        if retry_by_job:
            ssum["retries_by_job"] = retry_by_job
        shutdown = next((r for r in reversed(serve_ev)
                         if r.get("event") == "shutdown"), None)
        if shutdown and isinstance(shutdown.get("ledger"), dict):
            ssum["ledger"] = shutdown["ledger"]
        summary["serve"] = ssum
        print("== serve (daemon job ledger) ==", file=out)
        print(f"  jobs: {len(by_job)}  batches: {len(batches)}  "
              f"engine cache: {cache['hit']} hit / {cache['miss']} miss"
              f"  evictions: {len(evicts)}", file=out)
        if waits:
            print(f"  queue wait: p50 {ssum['queue_wait_p50_s']}s  "
                  f"p95 {ssum['queue_wait_p95_s']}s", file=out)
        if deadlines:
            ttl = sum(1 for r in deadlines
                      if r.get("kind") == "queue_ttl")
            print(f"  deadline expiries: {len(deadlines)} "
                  f"(queue_ttl x{ttl}, running x{len(deadlines) - ttl})",
                  file=out)
        if retries:
            bisects = sum(1 for r in retries
                          if r.get("event") == "bisect")
            exhausted = sum(1 for r in retries
                            if r.get("event") == "exhausted")
            print(f"  batch retries: {ssum['batch_retries']}  "
                  f"bisections: {bisects}  exhausted: {exhausted}",
                  file=out)
        for job_id in sorted(by_job):
            rows = by_job[job_id]
            last = rows[-1]
            run = next((r for r in rows if r.get("state") == "running"
                        and "lane" in r), None)
            t0 = next((r.get("t") for r in rows
                       if r.get("t") is not None), None)
            t1 = next((r.get("t") for r in reversed(rows)
                       if r.get("t") is not None), None)
            wall = (f"  wall {t1 - t0:.1f}s"
                    if t0 is not None and t1 is not None and t1 > t0
                    else "")
            lane = (f"  lane {run['lane']}/{run['lanes']}"
                    if run is not None else "")
            cached = (f"  cache {run['cache']}"
                      if run is not None and run.get("cache") else "")
            ev = sum(1 for r in rows if r.get("state") == "evicted")
            evs = f"  evicted x{ev}" if ev else ""
            rt = (f"  retries x{retry_by_job[job_id]}"
                  if job_id in retry_by_job else "")
            fin = "  [finished early]" if last.get("finished_early") else ""
            print(f"  {job_id}: {last.get('state')}{lane}{cached}{evs}"
                  f"{rt}{wall}{fin}", file=out)
    if rings:
        # Fleet runs tag each ring row with its experiment id (``exp``):
        # group the per-window stats PER EXPERIMENT — mixing lanes would
        # blend E unrelated distributions into one meaningless percentile.
        # The id itself is a grouping key only; it never enters the math
        # (only RING_COUNTERS/RING_GAUGES rank in ring_summary).
        by_exp: dict = {}
        for r in rings:
            by_exp.setdefault(r.get("exp"), []).append(r)
        # Gap records carry the same per-experiment tag: losses attribute
        # to their own lane, never summed into another's section.
        gaps_by_exp: dict = {}
        for g in gaps:
            gaps_by_exp.setdefault(g.get("exp"), []).append(g)
        if gaps:
            summary["ring_windows_lost"] = sum(
                g.get("windows_lost", 0) for g in gaps)
        if set(by_exp) == {None}:
            groups = [(None, rings)]
        else:
            groups = sorted(by_exp.items(),
                            key=lambda kv: (kv[0] is None, kv[0]))
            summary["ring_experiments"] = len(groups)
        for exp_id, group in groups:
            rs = ring_summary(group)
            if exp_id is None:
                summary["ring"] = rs
                print("== per-window occupancy (ring) ==", file=out)
            else:
                summary.setdefault("ring_by_exp", {})[exp_id] = rs
                print(f"== per-window occupancy (ring, experiment "
                      f"{exp_id}) ==", file=out)
            print(f"  windows recorded: {rs['windows']}", file=out)
            lane_lost = sum(g.get("windows_lost", 0)
                            for g in gaps_by_exp.get(exp_id, []))
            if lane_lost:
                print(f"  WINDOWS LOST TO RING OVERWRITE: {lane_lost} "
                      f"(chunk exceeded ring depth)", file=out)
            for field in RING_FIELDS:
                if field in rs:
                    d = rs[field]
                    print(f"  {field}: p50 {d['p50']}  p95 {d['p95']}  "
                          f"max {d['max']}", file=out)
        # Work-efficiency section (performance attribution plane): the
        # RING_WORK columns as utilization distributions, per experiment
        # under --fleet. Deliberately OUTSIDE ring_summary — utilization
        # samples never enter the occupancy percentile table (the
        # digest/retry-column rule).
        n_hosts = _log_n_hosts(recs)
        for exp_id, group in groups:
            ws = work_summary(group, n_hosts)
            if ws is None:
                continue
            tag = "" if exp_id is None else f", experiment {exp_id}"
            if exp_id is None:
                summary["work"] = ws
            else:
                summary.setdefault("work_by_exp", {})[exp_id] = ws
            print(f"== work efficiency (wasted-work accounting{tag}) ==",
                  file=out)
            _print_work(ws, out)
    elif works:
        # CPU-oracle logs: the per-window ``work`` rows carry the same
        # columns (no rounds, so no pop-scan efficiency).
        ws = work_summary(works, _log_n_hosts(recs))
        if ws is not None:
            summary["work"] = ws
            print("== work efficiency (wasted-work accounting) ==",
                  file=out)
            _print_work(ws, out)
    # Capacity advisory (tools/captune.py): measured peaks vs the caps the
    # records carry — the actionable line the cap-sizing debates need.
    from shadow1_tpu.tools import captune

    peaks, caps, overflow = captune.peaks_from_records(recs)
    advice = captune.advise(peaks, caps, overflow)
    if advice:
        summary["captune"] = advice
        print("== captune recommendation ==", file=out)
        for line in captune.advise_lines(advice):
            print(f"  {line}", file=out)
    if tr:
        last_per_host: dict[int, dict] = {}
        for r in tr:
            last_per_host[r["host"]] = r
        tx = sorted(
            last_per_host.values(), key=lambda r: -r.get("nic_tx_bytes", 0)
        )[:10]
        print("== top talkers (final tracker snapshot) ==", file=out)
        for r in tx:
            print(
                f"  host {r['host']}: tx {r.get('nic_tx_bytes', 0)} B, "
                f"rx {r.get('nic_rx_bytes', 0)} B, "
                f"pending {r.get('pending_events', 0)}",
                file=out,
            )
        intervals = tracker_intervals(tr)
        if intervals:
            # Interval deltas between successive tracker snapshots: the
            # tracker stream carries lifetime absolutes (log.py), so a log
            # holding several snapshots (one --tracker file per chunk, or
            # a concatenated series of runs) yields per-host RATES here —
            # the reference Tracker's interval view.
            summary["tracker_intervals"] = len(intervals)
            print("== tracker interval deltas (per-host) ==", file=out)
            for iv in intervals:
                print(f"  interval sim_s {iv['from_sim_s']} -> "
                      f"{iv['to_sim_s']}:", file=out)
                top = sorted(iv["hosts"].items(),
                             key=lambda kv: -kv[1].get("nic_tx_bytes", 0)
                             )[:10]
                for h, d in top:
                    parts = "  ".join(f"{k} +{v}" for k, v in d.items()
                                      if v)
                    print(f"    host {h}: {parts or '(no change)'}",
                          file=out)
    flows_recs = [r for r in recs if r.get("type") == REC_FLOW]
    if flows_recs:
        # Flow-probe plane (--watch / probes:): one line per watched
        # entity with its headline stats and stall findings. The full
        # series/sparkline view is tools/flowreport.py's job — this
        # section is the triage index.
        from shadow1_tpu.tools.flowreport import (
            _flow_label,
            diagnose_flow,
            flow_stats,
            group_flows,
        )

        groups = group_flows(flows_recs)
        fsum: dict = {}
        print("== flows (probe plane) ==", file=out)
        for key, rows in groups.items():
            label = _flow_label(key)
            stats = flow_stats(rows)
            stalls = diagnose_flow(rows)
            fsum[label] = {**stats, "stalls": [s["kind"] for s in stalls]}
            stall_txt = ("  STALLS: " + ", ".join(s["kind"] for s in stalls)
                         if stalls else "")
            print(f"  {label}: windows {stats['windows']}  "
                  f"state {stats['tcp_state_last']}  "
                  f"cwnd {stats['cwnd_last']}  "
                  f"inflight_max {stats['inflight_max']}  "
                  f"backlog_max {stats['nic_tx_backlog_ns_max']} ns"
                  f"{stall_txt}", file=out)
        summary["flows"] = fsum
    link_recs = [r for r in recs if r.get("type") == REC_LINK]
    if link_recs:
        # Link-telemetry plane (--link-telem on): top edges by bytes and
        # by drops plus the netreport verdicts. The full weathermap
        # (heatmap, hottest path, per-edge series) is tools/netreport.py's
        # job — this section is the triage index. Link rows are their own
        # record type — like the flow/digest/retry records they never
        # enter the ring percentile math above (only REC_RING rows rank).
        from shadow1_tpu.tools.netreport import (
            _fmt_edge,
            diagnose_links,
            edge_totals,
            group_edges,
        )

        edges = group_edges(link_recs)
        totals = edge_totals(edges)
        verdicts = diagnose_links(edges)
        lsum: dict = {"edges": len(totals),
                      "verdicts": [v["kind"] for v in verdicts]}
        print("== links (telemetry plane) ==", file=out)
        by_bytes = sorted(totals.items(), key=lambda kv: -kv[1]["bytes"])
        for key, t in by_bytes[:5]:
            print(f"  {_fmt_edge(key)}: pkts {t['pkts']}  "
                  f"bytes {t['bytes']}  drops {t['drops']}  "
                  f"q_max {t['queued_ns_max']} ns", file=out)
        droppy = [(k, t) for k, t in totals.items() if t["drops"]]
        droppy.sort(key=lambda kv: -kv[1]["drops"])
        for key, t in droppy[:5]:
            if (key, t) in by_bytes[:5]:
                continue
            print(f"  {_fmt_edge(key)}: drops {t['drops']} "
                  f"(loss {t['loss_drops']} down {t['link_down_drops']} "
                  f"nic {t['nic_backlog_drops']})", file=out)
        for v in verdicts:
            detail = {k: x for k, x in v.items()
                      if k not in ("kind", "edges")}
            print(f"  VERDICT {v['kind']}: {', '.join(v['edges'])}  "
                  f"{detail}", file=out)
        summary["links"] = lsum
    return summary


def tracker_intervals(tr: list[dict]) -> list[dict]:
    """Per-host counter deltas between successive tracker snapshots.

    Snapshots are the groups of records sharing one ``sim_s``; counters
    are every numeric field (cpu_busy_ns, nic bytes, app counters...).
    ``pending_events`` is a gauge, not a counter — its delta is still the
    honest "queue grew/shrank by N" signal, so it is kept, sign and all.
    Returns [] when the log holds fewer than two snapshots."""
    by_time: dict[float, dict[int, dict]] = {}
    for r in tr:
        by_time.setdefault(r.get("sim_s", 0), {})[r.get("host", 0)] = r
    times = sorted(by_time)
    out = []
    skip = ("host", "sim_s")
    for t0, t1 in zip(times, times[1:]):
        hosts: dict[int, dict] = {}
        for h, cur in sorted(by_time[t1].items()):
            prev = by_time[t0].get(h, {})
            d = {}
            for k, v in cur.items():
                if k in skip or not isinstance(v, (int, float)) \
                        or isinstance(v, bool):
                    continue
                p = prev.get(k, 0)
                if isinstance(p, (int, float)):
                    d[k] = v - p
            hosts[h] = d
        out.append({"from_sim_s": t0, "to_sim_s": t1, "hosts": hosts})
    return out


def write_heartbeat_csv(recs: list[dict], path: str) -> None:
    hb = [r for r in recs if r.get("type") == REC_HEARTBEAT]
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["sim_time_s", "wall_s", "events_per_sec",
                    "sim_per_wall", "events", "pkts_delivered"])
        for r in hb:
            delta = r.get("delta", {})
            w.writerow([
                r["sim_time_s"], r["wall_s"], r.get("events_per_sec"),
                r.get("sim_per_wall"), delta.get("events", 0),
                delta.get("pkts_delivered", 0),
            ])


def write_ring_csv(recs: list[dict], path: str) -> None:
    rings = [r for r in recs if r.get("type") == REC_RING]
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["window", "sim_time_s", *RING_FIELDS])
        for r in rings:
            w.writerow([r.get("window"), r.get("sim_time_s"),
                        *[r.get(field) for field in RING_FIELDS]])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="shadow1_tpu.tools.heartbeat_report")
    ap.add_argument("log")
    ap.add_argument("--csv", default=None,
                    help="write the heartbeat series as CSV for plotting")
    ap.add_argument("--ring-csv", default=None,
                    help="write the per-window ring series as CSV")
    args = ap.parse_args(argv)
    recs = load_records(args.log)
    if not recs:
        print("no JSON records found", file=sys.stderr)
        return 1
    summarize(recs)
    if args.csv:
        write_heartbeat_csv(recs, args.csv)
        print(f"wrote {args.csv}")
    if args.ring_csv:
        write_ring_csv(recs, args.ring_csv)
        print(f"wrote {args.ring_csv}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
