"""Serve plane (shadow1_tpu/serve/) — daemon, cache, admission, packing.

The serving contract under test (docs/SEMANTICS.md §"Serving contract"):

* engine-cache keying: a same-shape repeat batch HITS (rebind, zero new
  jit traces); a changed cap MISSES (different state shapes);
* admission control rejects an over-budget submission BEFORE any engine
  is built, with the standard memory_budget advice record;
* lane-packed jobs produce digest streams bit-identical to their solo
  runs; a halting tenant quarantines without touching cohabitants;
* a higher-priority submission evicts the running batch through the
  preemption plane and the evicted job resumes bit-identically;
* daemon SIGTERM drains, persists the queue, and a restart finishes the
  work (slow, subprocess).

In-process tests drive the daemon through ``ServeDaemon.step()`` — the
exact scheduler iteration the live loop runs — so the fast tier needs no
subprocesses.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

import shadow1_tpu  # noqa: F401
from shadow1_tpu.ckpt import run_chunked
from shadow1_tpu.config.experiment import load_experiment
from shadow1_tpu.consts import (
    EXIT_CAPACITY,
    EXIT_CONFIG,
    EXIT_DEADLINE,
    EXIT_MEMORY,
    EXIT_OK,
    EXIT_QUEUE_FULL,
    EXIT_SERVE_SHUTDOWN,
)
from shadow1_tpu.core.digest import DIGEST_FIELDS
from shadow1_tpu.core.engine import Engine
from shadow1_tpu.serve import client
from shadow1_tpu.serve.cache import EngineCache, shape_class_key
from shadow1_tpu.serve.daemon import ServeDaemon
from shadow1_tpu.serve.protocol import Spool
from shadow1_tpu.telemetry.ring import drain_ring

BASE = """
general: {{seed: {seed}, stop_time: {stop} ms}}
engine: {{scheduler: tpu, ev_cap: {ev_cap}, metrics_ring: 10,
          state_digest: 1{extra_engine}}}
network: {{single_vertex: {{latency: 1 ms{loss}}}}}
hosts:
  - {{name: h, count: 8}}
app:
  model: phold
  params: {{mean_delay_ns: 2000000.0, init_events: {init_events}}}
"""


def write_cfg(tmp_path, name, seed=5, stop=40, ev_cap=32, loss=None,
              init_events=3, extra_engine=""):
    p = tmp_path / name
    p.write_text(BASE.format(
        seed=seed, stop=stop, ev_cap=ev_cap, init_events=init_events,
        loss=(f", loss: {loss}" if loss is not None else ""),
        extra_engine=extra_engine))
    return str(p)


def solo_stream(cfg_path) -> dict[int, tuple]:
    """window → digest words of the straight solo run (full stream:
    chunked at the ring depth so no row is overwritten undrained)."""
    exp, params, _ = load_experiment(cfg_path)
    eng = Engine(exp, params)
    rows: dict[int, tuple] = {}
    start = [0]

    def on_chunk(st, _d):
        for r in drain_ring(st, eng.window, start=start[0]):
            if r["type"] == "ring":
                rows[r["window"]] = tuple(r[f] for f in DIGEST_FIELDS)
        start[0] = int(st.metrics.windows)

    run_chunked(eng, n_windows=eng.n_windows, chunk=params.metrics_ring,
                on_chunk=on_chunk)
    return rows


def served_stream(spool_dir, job_id) -> dict[int, tuple]:
    return {r["window"]: tuple(r[f] for f in DIGEST_FIELDS)
            for r in Spool(spool_dir).read_results(job_id)
            if r.get("type") == "ring"}


@pytest.fixture
def daemon(tmp_path):
    d = ServeDaemon(str(tmp_path / "spool"), poll_s=0.05,
                    ckpt_every_s=1e9)
    d.start()
    yield d
    d.close()


# ---------------------------------------------------------------------------
# engine cache
# ---------------------------------------------------------------------------

def test_cache_same_shape_hit_no_retrace(tmp_path):
    cache = EngineCache()
    exp_a = load_experiment(write_cfg(tmp_path, "a.yaml", seed=5))[0]
    exp_b, params, _ = load_experiment(write_cfg(tmp_path, "b.yaml",
                                                 seed=9))
    eng1, out1 = cache.get([exp_a], params)
    assert out1 == "miss"
    eng1.run(n_windows=4)
    n_traces = eng1._run_jit._cache_size()
    eng2, out2 = cache.get([exp_b], params)
    assert out2 == "hit" and eng2 is eng1
    assert eng2.exp.seed == 9  # rebound to the new lane set
    eng2.run(n_windows=4)
    # THE cache contract: a hit never traces or compiles anything new.
    assert eng2._run_jit._cache_size() == n_traces
    assert cache.counters()["cache_hits"] == 1


def test_cache_changed_cap_misses(tmp_path):
    import dataclasses

    cache = EngineCache()
    exp, params, _ = load_experiment(write_cfg(tmp_path, "a.yaml"))
    _, out1 = cache.get([exp], params)
    _, out2 = cache.get([exp], dataclasses.replace(params, ev_cap=64))
    assert (out1, out2) == ("miss", "miss")
    assert cache.counters() == {"cache_hits": 0, "cache_misses": 2,
                                "cache_evictions": 0, "cache_entries": 2}
    # and the keys really differ only by the cap
    k1 = shape_class_key(exp, params, 1)
    k2 = shape_class_key(exp, dataclasses.replace(params, ev_cap=64), 1)
    assert k1[0] == k2[0] and k1 != k2


def test_rebind_refuses_trace_incompatible_sets(tmp_path):
    from shadow1_tpu.fleet.engine import FleetEngine
    from shadow1_tpu.fleet.expand import FleetConfigError

    exp, params, _ = load_experiment(write_cfg(tmp_path, "a.yaml"))
    eng = FleetEngine([exp], params)
    # A uniform max_rounds is baked into the compiled program — a
    # different uniform value cannot ride a rebind (silent wrong results
    # otherwise: the metadata would claim R2 while the executable runs R).
    with pytest.raises(FleetConfigError, match="max_rounds"):
        eng.rebind([exp], max_rounds=[params.max_rounds + 1])
    # A shape-class field differing from the COMPILED engine's (not just
    # within the new set) is refused — it is closed over as a constant.
    cfg2 = tmp_path / "lat.yaml"
    cfg2.write_text((tmp_path / "a.yaml").read_text().replace(
        "latency: 1 ms", "latency: 2 ms"))
    exp2 = load_experiment(str(cfg2))[0]
    with pytest.raises(FleetConfigError, match="lat_vv|window"):
        eng.rebind([exp2])
    assert eng.exp is exp  # failed rebinds roll back cleanly


def test_restart_sweeps_stale_batch_lineage_keeps_quarantines(tmp_path):
    spool = Spool(str(tmp_path / "s")).ensure()
    for name, body in (("b000003.npz", "stale"),
                       ("b000003.npz.lineage", "{}"),
                       ("b000003.npz.q1.npz", "deliverable")):
        with open(os.path.join(spool.batches, name), "w") as f:
            f.write(body)
    d = ServeDaemon(str(tmp_path / "s"))
    d.start()
    try:
        # The dead incarnation's lineage is swept (a torn head there
        # would make a NEW batch's resolve fall back onto a different
        # batch's snapshot) ...
        assert not os.path.exists(
            os.path.join(spool.batches, "b000003.npz"))
        assert not os.path.exists(
            os.path.join(spool.batches, "b000003.npz.lineage"))
        # ... the quarantined tenant's solo-resumable ckpt is kept ...
        assert os.path.exists(
            os.path.join(spool.batches, "b000003.npz.q1.npz"))
        # ... and new batch ids start past every name ever seen.
        assert d._batch_seq == 4
    finally:
        d.close()


def test_cache_lru_evicts(tmp_path):
    import dataclasses

    cache = EngineCache(capacity=1)
    exp, params, _ = load_experiment(write_cfg(tmp_path, "a.yaml"))
    cache.get([exp], params)
    cache.get([exp], dataclasses.replace(params, ev_cap=64))
    assert cache.counters()["cache_evictions"] == 1
    assert len(cache) == 1


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------

def test_admission_rejects_overbudget_before_compile(tmp_path, daemon,
                                                     monkeypatch):
    monkeypatch.setenv("SHADOW1_MEM_BYTES", str(1 << 20))  # 1 MiB
    big = tmp_path / "big.yaml"
    big.write_text(BASE.format(seed=5, stop=40, ev_cap=64,
                               init_events=3, loss="",
                               extra_engine="").replace(
        "count: 8", "count: 4096"))
    jid = client.submit(daemon.spool.root, str(big))
    daemon.step()
    st = daemon.spool.read_status(jid)
    assert st["state"] == "rejected", st
    err = st["error"]
    assert err["error"] == "memory_budget"
    assert err["estimated"] > err["budget"] == (1 << 20)
    assert "Remedies" in err["advice"]
    # rejected BEFORE any engine was built: the cache never saw a miss
    assert daemon.cache.counters()["cache_misses"] == 0
    assert client.exit_code_for(st) == EXIT_MEMORY


def test_admission_rejects_bad_configs(tmp_path, daemon):
    bad = tmp_path / "bad.yaml"
    bad.write_text("general: {seed: 1}\nnot_a_section: {x: 1}\n")
    jid = client.submit(daemon.spool.root, str(bad))
    sweep = tmp_path / "sweep.yaml"
    sweep.write_text(BASE.format(seed=5, stop=40, ev_cap=32,
                                 init_events=3, loss="",
                                 extra_engine="") + "sweep: {count: 2}\n")
    jid2 = client.submit(daemon.spool.root, str(sweep))
    daemon.step()
    for j in (jid, jid2):
        st = daemon.spool.read_status(j)
        assert st["state"] == "rejected", st
        assert st["error"]["error"] == "config"
        assert client.exit_code_for(st) == EXIT_CONFIG
    assert daemon.ledger_dict()["jobs_rejected"] == 2


# ---------------------------------------------------------------------------
# lane packing ≡ solo bit-exactness
# ---------------------------------------------------------------------------

def test_packed_jobs_bitexact_vs_solo(tmp_path, daemon):
    cfgs = [write_cfg(tmp_path, f"j{i}.yaml", seed=5 + i)
            for i in range(3)]
    jids = [client.submit(daemon.spool.root, c) for c in cfgs]
    assert daemon.step()
    # one batch, three lanes, one compile
    assert daemon.ledger_dict()["batches_run"] == 1
    assert daemon.cache.counters()["cache_misses"] == 1
    for jid, cfg in zip(jids, cfgs):
        st = daemon.spool.read_status(jid)
        assert st["state"] == "done", st
        assert st["lanes"] == 3
        served = served_stream(daemon.spool.root, jid)
        solo = solo_stream(cfg)
        assert served == solo  # every window, every digest word
        assert client.exit_code_for(st) == EXIT_OK


def test_incompatible_shapes_batch_separately(tmp_path, daemon):
    a = client.submit(daemon.spool.root,
                      write_cfg(tmp_path, "a.yaml", seed=5))
    b = client.submit(daemon.spool.root,
                      write_cfg(tmp_path, "b.yaml", seed=6, ev_cap=64))
    daemon.step()
    daemon.step()
    assert daemon.ledger_dict()["batches_run"] == 2
    for j in (a, b):
        assert daemon.spool.read_status(j)["state"] == "done"


# ---------------------------------------------------------------------------
# quarantine isolation
# ---------------------------------------------------------------------------

def test_quarantine_isolates_halting_tenant(tmp_path, daemon):
    # Shared shape class at ev_cap 8 under on_overflow=halt: the lossless
    # tenant overflows (every phold hop survives), the lossy one fits.
    halting = write_cfg(tmp_path, "halting.yaml", seed=5, ev_cap=8,
                        init_events=6,
                        extra_engine=", on_overflow: halt")
    ok = write_cfg(tmp_path, "ok.yaml", seed=6, ev_cap=8, loss=0.5,
                   init_events=6, extra_engine=", on_overflow: halt")
    j_bad = client.submit(daemon.spool.root, halting)
    j_ok = client.submit(daemon.spool.root, ok)
    daemon.step()
    st_bad = daemon.spool.read_status(j_bad)
    assert st_bad["state"] == "failed", st_bad
    assert st_bad["reason"] == "capacity"
    assert client.exit_code_for(st_bad) == EXIT_CAPACITY
    quar = [r for r in Spool(daemon.spool.root).read_results(j_bad)
            if r.get("type") == "fleet_quarantine"]
    assert quar and os.path.exists(quar[0]["ckpt"])
    st_ok = daemon.spool.read_status(j_ok)
    assert st_ok["state"] == "done", st_ok
    assert served_stream(daemon.spool.root, j_ok) == solo_stream(ok)


# ---------------------------------------------------------------------------
# priority eviction → requeue → bit-identical resume
# ---------------------------------------------------------------------------

def test_eviction_requeues_and_resumes_bitexact(tmp_path, daemon):
    lo_cfg = write_cfg(tmp_path, "lo.yaml", seed=5, stop=200)
    hi_cfg = write_cfg(tmp_path, "hi.yaml", seed=6, stop=40)
    j_lo = client.submit(daemon.spool.root, lo_cfg, priority=0)

    def late_submit():
        time.sleep(0.3)  # lands mid-batch; the latch sees it at a boundary
        client.submit(daemon.spool.root, hi_cfg, priority=5)

    t = threading.Thread(target=late_submit)
    t.start()
    daemon.step()   # j_lo's batch — evicted when the hi-pri job arrives
    t.join()
    st_lo = daemon.spool.read_status(j_lo)
    assert st_lo["state"] == "queued" and st_lo.get("resumed"), st_lo
    assert daemon.ledger_dict()["jobs_evicted"] == 1
    assert daemon.resume, "evicted batch must leave a resume cursor"
    daemon.step()   # the high-priority batch
    daemon.step()   # the evicted batch resumes from its checkpoint
    st_lo = daemon.spool.read_status(j_lo)
    assert st_lo["state"] == "done", st_lo
    # The stream spans eviction + resume and still bit-matches solo.
    assert served_stream(daemon.spool.root, j_lo) == solo_stream(lo_cfg)
    hi_jobs = [r for r in Spool(daemon.spool.root).scan_inbox()]
    assert hi_jobs == []  # everything drained


# ---------------------------------------------------------------------------
# spool protocol
# ---------------------------------------------------------------------------

def test_spool_submit_is_atomic_and_accept_moves_once(tmp_path):
    spool = Spool(str(tmp_path / "s")).ensure()
    jid = spool.submit({"config_yaml": "x: 1", "priority": 0})
    (path, job), = spool.scan_inbox()
    assert job["id"] == jid
    # a .tmp from an in-flight atomic write is invisible
    open(os.path.join(spool.inbox, "zz.json.tmp"), "w").write("{")
    assert len(spool.scan_inbox()) == 1
    spool.accept(path, job)
    assert spool.scan_inbox() == []
    with open(spool.job_path(jid)) as f:
        assert json.load(f)["id"] == jid


def test_unparseable_submission_rejected_not_fatal(tmp_path, daemon):
    with open(os.path.join(daemon.spool.inbox, "hand.json"), "w") as f:
        f.write("{torn")
    daemon._intake()
    assert daemon.ledger_dict()["jobs_rejected"] == 1
    assert os.path.exists(os.path.join(daemon.spool.inbox,
                                       "hand.json.bad"))


def test_spool_refuses_second_daemon(tmp_path, daemon):
    from shadow1_tpu.serve.daemon import SpoolError

    d2 = ServeDaemon(daemon.spool.root)
    with pytest.raises(SpoolError):
        d2.start()


# ---------------------------------------------------------------------------
# registry / report surfaces
# ---------------------------------------------------------------------------

def test_serve_registry_and_prometheus():
    from shadow1_tpu.telemetry.registry import (
        RECORD_TYPES,
        REC_SERVE,
        REC_SERVE_DEADLINE,
        REC_SERVE_JOB,
        REC_SERVE_QUEUE,
        REC_SERVE_RETRY,
        SERVE_SPECS,
        to_prometheus,
    )

    assert REC_SERVE in RECORD_TYPES and REC_SERVE_JOB in RECORD_TYPES
    for rec in (REC_SERVE_QUEUE, REC_SERVE_DEADLINE, REC_SERVE_RETRY):
        assert rec in RECORD_TYPES
    text = to_prometheus({"jobs_done": 3, "jobs_queued": 2,
                          "queue_depth": 4, "oldest_wait_s": 1.5,
                          "batch_retries": 2, "jobs_expired": 1},
                         prefix="shadow1_serve", specs=SERVE_SPECS)
    assert "shadow1_serve_jobs_done_total 3" in text      # counter
    assert "shadow1_serve_jobs_queued 2" in text          # gauge, no _total
    assert "shadow1_serve_jobs_queued_total" not in text
    # backpressure gauges stay gauges; retry/expiry surfaces are counters
    assert "shadow1_serve_queue_depth 4" in text
    assert "shadow1_serve_queue_depth_total" not in text
    assert "shadow1_serve_oldest_wait_s 1.5" in text
    assert "shadow1_serve_batch_retries_total 2" in text
    assert "shadow1_serve_jobs_expired_total 1" in text


def test_report_serve_section(capsys):
    import io

    from shadow1_tpu.tools.heartbeat_report import summarize

    recs = [
        {"type": "serve", "event": "batch_start", "batch": "b0",
         "cache": "miss", "lanes": 2},
        {"type": "serve", "event": "batch_start", "batch": "b1",
         "cache": "hit", "lanes": 1},
        {"type": "serve", "event": "evict", "batch": "b0", "jobs": ["a"]},
        {"type": "serve_job", "job": "a", "state": "queued", "t": 1.0},
        {"type": "serve_job", "job": "a", "state": "running", "lane": 0,
         "lanes": 2, "cache": "miss", "t": 2.0},
        {"type": "serve_job", "job": "a", "state": "evicted", "t": 3.0},
        {"type": "serve_job", "job": "a", "state": "done", "t": 9.0},
        {"type": "serve_job", "job": "b", "state": "rejected", "t": 1.0},
        {"type": "serve_queue", "event": "enqueue", "job": "a",
         "depth": 1, "bytes": 100, "t": 1.0},
        {"type": "serve_deadline", "job": "c", "kind": "queue_ttl",
         "waited_s": 0.4, "t": 4.0},
        {"type": "serve_retry", "event": "retry", "batch": "b1",
         "jobs": ["a"], "attempt": 1, "backoff_s": 0.5, "t": 5.0},
        {"type": "serve_retry", "event": "bisect", "batch": "b1",
         "jobs": ["a", "c"], "attempt": 2, "t": 6.0},
    ]
    out = io.StringIO()
    summary = summarize(recs, out=out)
    s = summary["serve"]
    assert s["jobs"] == 2 and s["batches"] == 2
    assert s["cache_hits"] == 1 and s["cache_misses"] == 1
    assert s["evictions"] == 1
    assert s["deadline_expiries"] == 1 and s["batch_retries"] == 1
    assert s["queue_wait_p50_s"] == 1.0  # job a: queued t=1 -> running t=2
    assert s["retries_by_job"] == {"a": 1}
    text = out.getvalue()
    assert "serve (daemon job ledger)" in text
    assert "evicted x1" in text and "wall 8.0s" in text
    assert "queue wait: p50 1.0s" in text
    assert "deadline expiries: 1 (queue_ttl x1, running x0)" in text
    assert "batch retries: 1  bisections: 1" in text
    assert "retries x1" in text


def test_client_exit_taxonomy():
    assert client.exit_code_for({"state": "done"}) == EXIT_OK
    assert client.exit_code_for(
        {"state": "rejected",
         "error": {"error": "memory_budget"}}) == EXIT_MEMORY
    assert client.exit_code_for(
        {"state": "rejected", "error": {"error": "config"}}) == EXIT_CONFIG
    assert client.exit_code_for(
        {"state": "failed", "reason": "capacity"}) == EXIT_CAPACITY
    assert client.exit_code_for(
        {"state": "failed", "reason": "memory_exhausted"}) == EXIT_MEMORY
    assert client.exit_code_for(
        {"state": "rejected",
         "error": {"error": "queue_full",
                   "retry_after_s": 0.5}}) == EXIT_QUEUE_FULL
    assert client.exit_code_for(
        {"state": "failed", "reason": "deadline_expired",
         "error": {"error": "deadline_expired",
                   "kind": "queue_ttl"}}) == EXIT_DEADLINE
    assert client.exit_code_for(
        {"state": "failed", "reason": "deadline_expired",
         "error": {"error": "deadline_expired",
                   "kind": "running"}}) == EXIT_DEADLINE


# ---------------------------------------------------------------------------
# two-tier admission: waiting_headroom + queue_full backpressure
# ---------------------------------------------------------------------------

def _serve_events(spool_root) -> list[dict]:
    try:
        with open(Spool(spool_root).log_path) as f:
            return [json.loads(line) for line in f if line.strip()]
    except OSError:
        return []


def test_waiting_headroom_admitted_then_bitexact(tmp_path, daemon,
                                                 monkeypatch):
    a_cfg = write_cfg(tmp_path, "a.yaml", seed=5, stop=200)
    b_cfg = write_cfg(tmp_path, "b.yaml", seed=6, stop=40)
    with open(b_cfg) as f:
        est = daemon._validate({"id": "probe",
                                "config_yaml": f.read()}).est_peak
    assert est > 0
    # Budget fits ONE resident tenant with headroom to spare, but not
    # two: the second submission fits an idle device, so it must queue
    # as waiting_headroom — never be rejected for someone else's load.
    monkeypatch.setenv("SHADOW1_MEM_BYTES", str(int(est * 1.5)))
    j_a = client.submit(daemon.spool.root, a_cfg)

    seen = {}

    def late_submit():
        time.sleep(0.3)  # lands mid-batch; intake runs at a boundary
        jid = client.submit(daemon.spool.root, b_cfg)
        seen["b"] = jid

    t = threading.Thread(target=late_submit)
    t.start()
    daemon.step()   # A's batch; B admitted waiting_headroom mid-flight
    t.join()
    j_b = seen["b"]
    st_b = daemon.spool.read_status(j_b)
    assert st_b["state"] == "waiting_headroom", st_b
    assert daemon.ledger_dict()["jobs_waiting"] == 1
    daemon.step()   # A drained the device; B is schedulable now
    assert daemon.spool.read_status(j_a)["state"] == "done"
    assert daemon.spool.read_status(j_b)["state"] == "done"
    assert served_stream(daemon.spool.root, j_a) == solo_stream(a_cfg)
    assert served_stream(daemon.spool.root, j_b) == solo_stream(b_cfg)
    # the queue plane narrated the wait
    assert any(e.get("type") == "serve_queue"
               and e.get("event") == "waiting_headroom"
               for e in _serve_events(daemon.spool.root))


def test_queue_full_depth_cap_rejects_with_retry_advice(tmp_path):
    d = ServeDaemon(str(tmp_path / "sp"), poll_s=0.05, ckpt_every_s=1e9,
                    queue_depth=1)
    d.start()
    try:
        j1 = client.submit(d.spool.root, write_cfg(tmp_path, "a.yaml",
                                                   seed=5))
        j2 = client.submit(d.spool.root, write_cfg(tmp_path, "b.yaml",
                                                   seed=6))
        d._intake()
        assert (d.spool.read_status(j1) or {}).get("state") == "queued"
        st2 = d.spool.read_status(j2)
        assert st2["state"] == "rejected", st2
        err = st2["error"]
        assert err["error"] == "queue_full" and err["cap"] == "depth"
        assert err["queue_depth"] == err["queue_depth_cap"] == 1
        assert err["retry_after_s"] > 0
        assert client.exit_code_for(st2) == EXIT_QUEUE_FULL
        led = d.ledger_dict()
        assert led["jobs_queue_full"] == 1 and led["queue_depth"] == 1
    finally:
        d.close()


def test_queue_full_bytes_cap_rejects(tmp_path):
    probe = ServeDaemon(str(tmp_path / "probe_sp"))
    with open(write_cfg(tmp_path, "a.yaml", seed=5)) as f:
        est = probe._validate({"id": "p", "config_yaml": f.read()}).est_peak
    assert est > 0
    d = ServeDaemon(str(tmp_path / "sp"), poll_s=0.05, ckpt_every_s=1e9,
                    queue_bytes=est)
    d.start()
    try:
        j1 = client.submit(d.spool.root, write_cfg(tmp_path, "c.yaml",
                                                   seed=7))
        j2 = client.submit(d.spool.root, write_cfg(tmp_path, "d.yaml",
                                                   seed=8))
        d._intake()
        assert (d.spool.read_status(j1) or {}).get("state") == "queued"
        st2 = d.spool.read_status(j2)
        assert st2["state"] == "rejected", st2
        assert st2["error"]["error"] == "queue_full"
        assert st2["error"]["cap"] == "bytes"
        assert st2["error"]["queue_bytes_cap"] == est
    finally:
        d.close()


# ---------------------------------------------------------------------------
# deadlines: queue TTL + running wall-time bound (committed prefix)
# ---------------------------------------------------------------------------

def test_queue_ttl_expires_waiting_job(tmp_path, daemon):
    a_cfg = write_cfg(tmp_path, "a.yaml", seed=5, stop=100)
    # different shape class (ev_cap) — never packs into A's batch, so it
    # sits queued while A runs and crosses its TTL at a chunk boundary
    t_cfg = write_cfg(tmp_path, "t.yaml", seed=6, ev_cap=64)
    j_a = client.submit(daemon.spool.root, a_cfg)
    j_t = client.submit(daemon.spool.root, t_cfg, queue_ttl_s=0.01)
    daemon.step()
    st_t = daemon.spool.read_status(j_t)
    assert st_t["state"] == "failed", st_t
    assert st_t["reason"] == "deadline_expired"
    assert st_t["error"]["kind"] == "queue_ttl"
    assert st_t["error"]["waited_s"] > 0.01
    assert client.exit_code_for(st_t) == EXIT_DEADLINE
    assert daemon.ledger_dict()["jobs_expired"] == 1
    recs = Spool(daemon.spool.root).read_results(j_t)
    assert any(r.get("type") == "serve_deadline"
               and r.get("kind") == "queue_ttl" for r in recs)
    assert daemon.spool.read_status(j_a)["state"] == "done"


def test_running_deadline_keeps_prefix_cohabitant_unharmed(tmp_path,
                                                           daemon):
    a_cfg = write_cfg(tmp_path, "a.yaml", seed=5, stop=200)
    b_cfg = write_cfg(tmp_path, "b.yaml", seed=6, stop=200)
    j_a = client.submit(daemon.spool.root, a_cfg, deadline_s=0.05)
    j_b = client.submit(daemon.spool.root, b_cfg)
    daemon.step()   # shared batch; A expires at the first chunk boundary
    st_a = daemon.spool.read_status(j_a)
    assert st_a["state"] == "failed", st_a
    assert st_a["reason"] == "deadline_expired"
    assert st_a["error"]["kind"] == "running"
    assert st_a["error"]["ran_s"] > 0.05
    assert client.exit_code_for(st_a) == EXIT_DEADLINE
    # the committed prefix survives, bit-identical to the same prefix of
    # the straight solo run — a deadline is a bound, not a rollback
    solo_a = solo_stream(a_cfg)
    prefix = served_stream(daemon.spool.root, j_a)
    assert prefix and len(prefix) < len(solo_a)
    assert all(solo_a[w] == row for w, row in prefix.items())
    daemon.step()   # B resumes from the sliced snapshot
    st_b = daemon.spool.read_status(j_b)
    assert st_b["state"] == "done", st_b
    assert served_stream(daemon.spool.root, j_b) == solo_stream(b_cfg)
    # the cohabitant resumed from the lane-sliced checkpoint, not a
    # from-scratch rerun
    assert not any(e.get("event") == "cursor_discarded"
                   for e in _serve_events(daemon.spool.root))


# ---------------------------------------------------------------------------
# transient-failure retry + blast-radius bisection
# ---------------------------------------------------------------------------

def test_transient_crash_retries_bitexact(tmp_path, daemon, monkeypatch):
    import shadow1_tpu.fleet.run as fleet_run

    real = fleet_run.run_fleet
    calls = []

    def flaky(engine, st=None, **kw):
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError("injected transport flake")
        return real(engine, st, **kw)

    monkeypatch.setattr(fleet_run, "run_fleet", flaky)
    monkeypatch.setenv("SHADOW1_SERVE_RETRY_BACKOFF_S", "0")
    cfg = write_cfg(tmp_path, "a.yaml", seed=5)
    jid = client.submit(daemon.spool.root, cfg)
    assert daemon.step()   # crash -> scheduled for retry, NOT failed
    st = daemon.spool.read_status(jid)
    assert st["state"] == "queued" and st.get("retrying"), st
    assert daemon.ledger_dict()["batch_retries"] == 1
    assert daemon.resume, "transient crash must leave a retry cursor"
    assert daemon.step()   # the retry attempt
    assert daemon.spool.read_status(jid)["state"] == "done"
    assert served_stream(daemon.spool.root, jid) == solo_stream(cfg)
    assert any(e.get("type") == "serve_retry" and e.get("event") == "retry"
               for e in _serve_events(daemon.spool.root))


def test_deterministic_failure_not_retried(tmp_path, daemon, monkeypatch):
    import shadow1_tpu.fleet.run as fleet_run
    from shadow1_tpu.txn import SelfCheckError

    def det(engine, st=None, **kw):
        raise SelfCheckError({"pkts_sent": 1}, 1, "injected")

    monkeypatch.setattr(fleet_run, "run_fleet", det)
    jid = client.submit(daemon.spool.root,
                        write_cfg(tmp_path, "a.yaml", seed=5))
    assert daemon.step()
    st = daemon.spool.read_status(jid)
    # determinism: a self-check violation reproduces on retry, so the
    # job fails immediately — no backoff cursor, no retry counter
    assert st["state"] == "failed" and st["reason"] == "runtime", st
    assert daemon.ledger_dict()["batch_retries"] == 0
    assert not daemon.resume


def test_bisection_isolates_poisonous_tenant(tmp_path, daemon,
                                             monkeypatch):
    import shadow1_tpu.fleet.run as fleet_run

    real = fleet_run.run_fleet

    def poisoned(engine, st=None, **kw):
        if any(l.get("seed") == 5 for l in (kw.get("labels") or [])):
            raise RuntimeError("poisonous tenant aboard")
        return real(engine, st, **kw)

    monkeypatch.setattr(fleet_run, "run_fleet", poisoned)
    monkeypatch.setenv("SHADOW1_SERVE_RETRY_BACKOFF_S", "0")
    bad_cfg = write_cfg(tmp_path, "bad.yaml", seed=5)
    ok_cfg = write_cfg(tmp_path, "ok.yaml", seed=6)
    j_bad = client.submit(daemon.spool.root, bad_cfg)
    j_ok = client.submit(daemon.spool.root, ok_cfg)
    assert daemon.step()   # crash #1: the pair retries together
    assert daemon.ledger_dict()["batch_retries"] == 1
    assert daemon.step()   # crash #2: bisect the suspects into solos
    assert daemon.ledger_dict()["jobs_bisected"] == 2
    for j in (j_bad, j_ok):
        st = daemon.spool.read_status(j)
        assert st["state"] == "queued" and st.get("solo"), st
    assert daemon.step()   # j_bad solo -> crash #3 -> retries exhausted
    st_bad = daemon.spool.read_status(j_bad)
    assert st_bad["state"] == "failed", st_bad
    assert st_bad["reason"] == "retry_exhausted"
    assert len(st_bad["error"]["crashes"]) == 3  # the full crash ledger
    assert client.exit_code_for(st_bad) == 1
    assert daemon.step()   # j_ok solo runs clean, bit-exact
    st_ok = daemon.spool.read_status(j_ok)
    assert st_ok["state"] == "done", st_ok
    assert served_stream(daemon.spool.root, j_ok) == solo_stream(ok_cfg)
    assert any(e.get("type") == "serve_retry"
               and e.get("event") == "bisect"
               for e in _serve_events(daemon.spool.root))


# ---------------------------------------------------------------------------
# NFS-safe spool locking: stale-lock reclaim vs live-lock refusal
# ---------------------------------------------------------------------------

def test_stale_lock_reclaimed_live_lock_refused(tmp_path):
    import socket as socketlib

    from shadow1_tpu.serve.daemon import SpoolError

    spool = Spool(str(tmp_path / "s")).ensure()
    # a SIGKILLed same-host daemon: dead pid, heartbeat still fresh —
    # the pid check is authoritative on the same host
    p = subprocess.Popen([sys.executable, "-c", "pass"])
    p.wait()
    with open(spool.daemon_path, "w") as f:
        json.dump({"pid": p.pid, "host": socketlib.gethostname(),
                   "started_at": time.time(),
                   "heartbeat_at": time.time()}, f)
    assert spool.daemon_alive() is None
    d = ServeDaemon(str(tmp_path / "s"))
    d.start()   # reclaims instead of refusing
    try:
        assert any(e.get("event") == "lock_reclaimed"
                   for e in _serve_events(spool.root))
    finally:
        d.close()
    # a live cross-host holder (NFS spool: its flock is not visible
    # here) with a fresh heartbeat: refused
    with open(spool.daemon_path, "w") as f:
        json.dump({"pid": 1, "host": "elsewhere.example",
                   "heartbeat_at": time.time()}, f)
    with pytest.raises(SpoolError):
        ServeDaemon(str(tmp_path / "s")).start()
    # the same cross-host holder gone silent past STALE_AFTER_S:
    # reclaimable (mtime pushed back too — it IS the heartbeat)
    old = time.time() - 3600
    with open(spool.daemon_path, "w") as f:
        json.dump({"pid": 1, "host": "elsewhere.example",
                   "heartbeat_at": old, "started_at": old}, f)
    os.utime(spool.daemon_path, (old, old))
    d3 = ServeDaemon(str(tmp_path / "s"))
    d3.start()
    d3.close()


# ---------------------------------------------------------------------------
# client reconnect hardening
# ---------------------------------------------------------------------------

def test_client_request_retry_reconnects(tmp_path, capsys):
    import socket as socketlib

    sock_path = str(tmp_path / "x.sock")
    srv = socketlib.socket(socketlib.AF_UNIX, socketlib.SOCK_STREAM)
    srv.bind(sock_path)
    srv.listen(2)

    def flap_then_serve():
        c1, _ = srv.accept()
        c1.close()          # the flap: drop the first connection cold
        c2, _ = srv.accept()
        f = c2.makefile("rw", encoding="utf-8")
        f.readline()
        f.write('{"ok": true}\n')
        f.flush()
        c2.close()

    t = threading.Thread(target=flap_then_serve, daemon=True)
    t.start()
    out = client.request_retry(sock_path, {"op": "ping"}, attempts=4,
                               base_s=0.01)
    t.join(timeout=5)
    srv.close()
    assert out == {"ok": True}
    assert '"reconnected"' in capsys.readouterr().err


def test_client_watch_falls_back_on_dead_socket(tmp_path):
    # exhausted reconnect budget -> None, the await_job polling fallback
    assert client.watch(str(tmp_path / "absent.sock"), "j", attempts=2,
                        base_s=0.01, timeout_s=2.0) is None


# ---------------------------------------------------------------------------
# daemon subprocess: SIGTERM drain + queue persistence (slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_daemon_sigterm_drains_and_restart_finishes(tmp_path):
    spool = str(tmp_path / "spool")
    cfg = write_cfg(tmp_path, "long.yaml", seed=5, stop=400)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}

    def spawn():
        p = subprocess.Popen(
            [sys.executable, "-m", "shadow1_tpu", "serve",
             "--spool", spool, "--poll-s", "0.05"],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
            text=True)
        deadline = time.monotonic() + 60
        while Spool(spool).daemon_alive() is None:
            assert p.poll() is None, p.stderr.read()[-800:]
            assert time.monotonic() < deadline
            time.sleep(0.1)
        return p

    p = spawn()
    try:
        jid = client.submit(spool, cfg)
        # wait until the batch is actually running, then preempt the daemon
        deadline = time.monotonic() + 120
        while (Spool(spool).read_status(jid) or {}).get("state") \
                != "running":
            assert time.monotonic() < deadline
            time.sleep(0.1)
        p.send_signal(signal.SIGTERM)
        rc = p.wait(timeout=120)
        assert rc == EXIT_SERVE_SHUTDOWN, p.stderr.read()[-800:]
        # the queue persisted: the drained batch left a resume cursor
        with open(os.path.join(spool, "queue.json")) as f:
            q = json.load(f)
        assert q["queued"] or q["resume"], q
    finally:
        if p.poll() is None:
            p.kill()
    p = spawn()
    try:
        final = client.await_job(Spool(spool), jid, timeout_s=300,
                                 poll_s=0.1)
        assert final["state"] == "done", final
        assert served_stream(spool, jid) == solo_stream(cfg)
    finally:
        p.send_signal(signal.SIGTERM)
        assert p.wait(timeout=60) == EXIT_SERVE_SHUTDOWN
