"""North-star benchmark: batched-engine event throughput vs the CPU oracle.

Runs the PHOLD engine-stress workload (SURVEY §4 — the reference's scheduler
benchmark, src/test/phold/) on the batched TPU engine and on the sequential
CPU reference engine, and prints ONE JSON line:

    {"metric": "phold_events_per_sec", "value": N, "unit": "events/s",
     "vs_baseline": tpu_events_per_sec / baseline_events_per_sec, ...}

``vs_baseline`` divides by the honest thread-per-core C++ DES
(detail.cpp_thread_per_core, SURVEY §7.3.5) run on the same achieved
config when it builds; else by the interpreted Python oracle
(detail.python_oracle). ``detail.baseline_kind`` says which.

Robustness contract (round-1/2 postmortems):
* ALWAYS exactly one JSON line on stdout.
* The accelerator is probed in a subprocess with a deadline
  (shadow1_tpu.platform) before any in-process backend init.
* The timed loop runs in CHUNKS of <=50 windows via ckpt.run_chunked — no
  single device program runs for minutes (the round-2 fault was one
  monolithic 2000-window XLA program; 50-window programs complete in
  seconds on this chip).
* On a runtime fault the run retries at half scale, and finally on the
  forced-CPU platform — a measurement is always produced; compile time is
  reported separately from timed walls. A run that lands on the CPU
  fallback emits ``value: null`` + ``invalid`` in the headline fields (the
  fallback numbers stay under ``detail``): a CPU wall is not a TPU datum.

The Python oracle is measured on a smaller host count (the eager oracle is
O(events) Python; PHOLD cost/event is scale-stable) — see
``detail.python_oracle`` for its exact config.
"""

from __future__ import annotations

import json
import time

# Benchmark workload: dense PHOLD at TPU-native scale (classic PHOLD uses
# ~10+ live events per LP; denser windows amortize the per-round fixed cost
# across more hosts — that IS the engine's design point).
N_HOSTS = 65536
INIT_EVENTS = 16
MEAN_DELAY_MS = 2
WINDOW_MS = 1
SIM_WINDOWS = 500
CHUNK = 50

CPU_HOSTS = 1024
CPU_WINDOWS = 2

# CPU-fallback sizing: a run that lands on the CPU backend publishes
# ``value: null`` + ``invalid`` anyway (a CPU wall is not a TPU datum), so
# burning minutes on it buys nothing — BENCH_r05 spent 269 s producing an
# invalid row at 8192 hosts × 250 windows. Smoke scale keeps the row (the
# fallback numbers remain under ``detail`` for debugging) at seconds of
# wall.
SMOKE_HOSTS = 2048
SMOKE_WINDOWS = 60


# Fleet sweep row (bench.py --fleet): E phold seed variants answered as one
# vmapped program vs E sequential solo runs — the sweep-throughput claim
# (ROADMAP item 1; ISSUE 8 acceptance: fleet wall < 0.5x sequential wall on
# this container). The fleet's win is FIXED-COST amortization: one
# compile/launch bill for all E lanes instead of 16 (a solo engine
# re-traces per seed — the key is a closed-over constant). Sized to the
# regime where that fixed cost matters (small planes, many windows): at
# large H on the CPU fallback the vectorized run's 16x flops swamp the
# saving (measured: H=256 ratio 0.81, H=32 ratio ~0.39). The run-only
# ratio is reported alongside, unspun: on CPU it is > 1; on a TPU the
# per-round launch overhead is the fixed cost the same mechanism
# amortizes (the paper's round economics).
FLEET_E = 16
FLEET_HOSTS = 32
FLEET_WINDOWS = 200


def _experiment(n_hosts: int, windows: int):
    from shadow1_tpu.config.compiled import single_vertex_experiment
    from shadow1_tpu.consts import MS

    return single_vertex_experiment(
        n_hosts=n_hosts,
        seed=1234,
        end_time=windows * WINDOW_MS * MS,
        latency_ns=WINDOW_MS * MS,
        model="phold",
        model_cfg={"mean_delay_ns": float(MEAN_DELAY_MS * MS), "init_events": INIT_EVENTS},
    )


def _params():
    from shadow1_tpu.consts import EngineParams

    return EngineParams(ev_cap=48, outbox_cap=24, max_rounds=128)


def run_tpu(n_hosts: int, windows: int) -> dict:
    import jax

    from shadow1_tpu import ckpt
    from shadow1_tpu.consts import SEC
    from shadow1_tpu.core.engine import Engine

    eng = Engine(_experiment(n_hosts, windows), _params())
    # Compile both chunk sizes (full chunk + any ragged tail) before timing.
    t0 = time.perf_counter()
    warm = eng.run(eng.init_state(), n_windows=CHUNK)
    tail = windows % CHUNK
    if tail:
        warm = eng.run(eng.init_state(), n_windows=tail)
    jax.block_until_ready(warm)
    compile_wall = time.perf_counter() - t0

    chunk_walls: list[float] = []
    last = time.perf_counter()

    def on_chunk(st, done):
        nonlocal last
        jax.block_until_ready(st)
        now = time.perf_counter()
        chunk_walls.append(now - last)
        last = now

    t0 = time.perf_counter()
    st = ckpt.run_chunked(eng, n_windows=windows, chunk=CHUNK, on_chunk=on_chunk)
    jax.block_until_ready(st)
    wall = time.perf_counter() - t0
    m = Engine.metrics_dict(st)
    return {
        "events": m["events"],
        "wall_s": wall,
        "events_per_sec": m["events"] / wall,
        "sim_sec_per_wall_sec": (windows * WINDOW_MS / 1000.0) / wall,
        "compile_wall_s": compile_wall,
        "n_chunks": len(chunk_walls),
        "chunk_wall_min_s": min(chunk_walls),
        "chunk_wall_max_s": max(chunk_walls),
        "ev_overflow": m["ev_overflow"],
        "ob_overflow": m["ob_overflow"],
        "rounds_per_window": m["rounds"] / max(m["windows"], 1),
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "n_hosts": n_hosts,
        "windows": windows,
    }


def run_cpu_oracle() -> dict:
    from shadow1_tpu.cpu_engine import CpuEngine

    cpu = CpuEngine(_experiment(CPU_HOSTS, CPU_WINDOWS), _params())
    t0 = time.perf_counter()
    cm = cpu.run(n_windows=CPU_WINDOWS)
    wall = time.perf_counter() - t0
    return {
        "n_hosts": CPU_HOSTS,
        "windows": CPU_WINDOWS,
        "events": cm["events"],
        "wall_s": wall,
        "events_per_sec": cm["events"] / wall,
    }


def run_cpp_baseline(n_hosts: int, tpu_windows: int) -> dict | None:
    """The honest thread-per-core baseline (SURVEY §7.3.5): the C++
    multi-core DES on the SAME achieved experiment config (counters
    bit-match the oracle and the TPU engine — tests/test_native_
    comparator.py). PHOLD is stationary, so 1/5 of the windows gives a
    stable events/sec. Reported as the best of (one thread per available
    core, 16 shards) — on a single-core box extra shards still help via
    smaller, cache-resident heaps, and the baseline should be the CPU's
    best foot. Each variant fails independently (a timeout in one must not
    discard the other)."""
    import os

    try:
        from shadow1_tpu import native

        native.ensure_built()
    except Exception as e:  # noqa: BLE001 — no toolchain -> no baseline
        return {"kind": "cpp_thread_per_core", "error": repr(e)[:300]}
    windows = max(tpu_windows // 5, 10)
    variants = []
    for nt in dict.fromkeys((os.cpu_count() or 1, 16)):
        try:
            r = native.run_phold(
                n_hosts=n_hosts, seed=1234, n_windows=windows,
                window_ns=WINDOW_MS * 10**6, mean_delay_ns=MEAN_DELAY_MS * 1e6,
                init_events=INIT_EVENTS, ev_cap=_params().ev_cap,
                outbox_cap=_params().outbox_cap, n_threads=nt,
            )
            variants.append(
                {"n_threads": nt, "events": r["events"], "wall_s": r["wall_s"],
                 "events_per_sec": r["events_per_sec"]}
            )
        except Exception as e:  # noqa: BLE001 — per-variant best effort
            variants.append({"n_threads": nt, "error": repr(e)[:300]})
    ok = [v for v in variants if "events_per_sec" in v]
    out = {
        "kind": "cpp_thread_per_core",
        "n_hosts": n_hosts,
        "windows": windows,
        "cpu_cores": os.cpu_count(),
        "variants": variants,
    }
    if ok:
        out["best"] = max(ok, key=lambda v: v["events_per_sec"])
    return out


def _fleet_experiments(n_hosts: int, windows: int) -> list:
    from shadow1_tpu.config.compiled import single_vertex_experiment
    from shadow1_tpu.consts import MS

    return [
        single_vertex_experiment(
            n_hosts=n_hosts, seed=1234 + i,
            end_time=windows * WINDOW_MS * MS, latency_ns=WINDOW_MS * MS,
            model="phold",
            model_cfg={"mean_delay_ns": float(MEAN_DELAY_MS * MS),
                       "init_events": INIT_EVENTS},
        )
        for i in range(FLEET_E)
    ]


def run_fleet_bench(n_hosts: int = FLEET_HOSTS,
                    windows: int = FLEET_WINDOWS) -> dict:
    """E=16 phold seed variants: one vmapped fleet run vs 16 sequential
    solo runs, both chunked (<=CHUNK windows per program) and both paying
    their real compile bills — a solo engine re-traces per seed (the key
    is a closed-over constant), which IS the sequential cost the fleet
    amortizes away along with the per-kernel launches."""
    import jax

    from shadow1_tpu import ckpt
    from shadow1_tpu.core.engine import Engine
    from shadow1_tpu.fleet.engine import FleetEngine

    exps = _fleet_experiments(n_hosts, windows)
    params = _params()

    # -- fleet: one program for all E experiments --
    t0 = time.perf_counter()
    fleet = FleetEngine(exps, params)
    st0 = fleet.init_state()
    jax.block_until_ready(fleet.run(st0, n_windows=0))
    fleet_compile = time.perf_counter() - t0
    t0 = time.perf_counter()
    stf = ckpt.run_chunked(fleet, st0, n_windows=windows, chunk=CHUNK)
    jax.block_until_ready(stf)
    fleet_run_wall = time.perf_counter() - t0
    fleet_events = sum(m["events"] for m in fleet.metrics_per_exp(stf))
    fleet_total = fleet_compile + fleet_run_wall

    # -- sequential: E solo engines, each with its own compile + run --
    seq_compile = 0.0
    seq_run_wall = 0.0
    seq_events = 0
    for exp in exps:
        t0 = time.perf_counter()
        eng = Engine(exp, params)
        s0 = eng.init_state()
        jax.block_until_ready(eng.run(s0, n_windows=0))
        seq_compile += time.perf_counter() - t0
        t0 = time.perf_counter()
        s = ckpt.run_chunked(eng, s0, n_windows=windows, chunk=CHUNK)
        jax.block_until_ready(s)
        seq_run_wall += time.perf_counter() - t0
        seq_events += Engine.metrics_dict(s)["events"]
    seq_total = seq_compile + seq_run_wall

    return {
        "experiments": FLEET_E,
        "n_hosts": n_hosts,
        "windows": windows,
        "fleet": {
            "compile_wall_s": fleet_compile,
            "run_wall_s": fleet_run_wall,
            "total_wall_s": fleet_total,
            "events": fleet_events,
            "events_per_sec": fleet_events / fleet_run_wall,
        },
        "sequential": {
            "compile_wall_s": seq_compile,
            "run_wall_s": seq_run_wall,
            "total_wall_s": seq_total,
            "events": seq_events,
            "events_per_sec": seq_events / seq_run_wall,
        },
        "events_match": fleet_events == seq_events,
        "speedup_total": seq_total / fleet_total,
        "speedup_run_only": seq_run_wall / fleet_run_wall,
        "fleet_vs_sequential_wall_ratio": fleet_total / seq_total,
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
    }


def fleet_main() -> None:
    """bench.py --fleet → one fleet_e16 JSON row (BENCH_r06)."""
    result = None
    try:
        import shadow1_tpu  # noqa: F401
        from shadow1_tpu.platform import ensure_live_platform

        ensure_live_platform(min_devices=1)
        detail = run_fleet_bench()
        result = {
            "metric": "fleet_e16_events_per_sec",
            "value": round(detail["fleet"]["events_per_sec"], 1),
            "unit": "events/s (aggregate across 16 experiments)",
            # The sweep-throughput claim: the whole fleet's wall as a
            # fraction of 16 sequential solo runs (< 0.5 = acceptance).
            "fleet_vs_sequential_wall_ratio": round(
                detail["fleet_vs_sequential_wall_ratio"], 3),
            "detail": {
                k: ({kk: (round(vv, 4) if isinstance(vv, float) else vv)
                     for kk, vv in v.items()} if isinstance(v, dict)
                    else (round(v, 4) if isinstance(v, float) else v))
                for k, v in detail.items()
            },
        }
    except Exception as e:  # noqa: BLE001 — the JSON line must always print
        import traceback

        result = {
            "metric": "fleet_e16_events_per_sec",
            "value": None,
            "unit": "events/s",
            "error": repr(e),
            "detail": {"traceback": traceback.format_exc()[-2000:]},
        }
    print(json.dumps(result))


def _run_cpu_subprocess(n_hosts: int, windows: int) -> dict:
    """Last-resort rung: re-exec this script with the CPU platform forced
    BEFORE backend init (an in-process ``jax.config.update`` after a TPU
    attempt is a no-op — the backend is cached)."""
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, __file__, "--cpu-child", str(n_hosts), str(windows)],
        capture_output=True, text=True, timeout=1200,
    )
    if out.returncode != 0:
        raise RuntimeError(f"cpu-child rc={out.returncode}: {out.stderr[-500:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def _cpu_child(n_hosts: int, windows: int) -> None:
    import shadow1_tpu  # noqa: F401
    from shadow1_tpu.platform import force_cpu

    force_cpu()
    print(json.dumps(run_tpu(n_hosts, windows)))


def main() -> None:
    result = None
    try:
        import shadow1_tpu  # noqa: F401  (x64 on, before jax arrays exist)
        from shadow1_tpu.platform import ensure_live_platform, probe_default_backend

        backend = ensure_live_platform(min_devices=1)
        probe = probe_default_backend()

        if backend == "cpu":
            # Probe already forced CPU: the row will be invalid whatever its
            # size, so run the smoke-scale config and keep the minutes.
            ladder = ((SMOKE_HOSTS, SMOKE_WINDOWS, False),)
        else:
            ladder = (
                (N_HOSTS, SIM_WINDOWS, False),
                (N_HOSTS // 2, SIM_WINDOWS // 2, False),
                (SMOKE_HOSTS, SMOKE_WINDOWS, True),
            )
        attempts = []
        tpu = None
        for n_hosts, windows, cpu_sub in ladder:
            try:
                if cpu_sub:
                    tpu = _run_cpu_subprocess(n_hosts, windows)
                else:
                    tpu = run_tpu(n_hosts, windows)
                break
            except Exception as e:  # noqa: BLE001 — fall down the ladder
                attempts.append(
                    {"n_hosts": n_hosts, "windows": windows,
                     "cpu_subprocess": cpu_sub, "error": repr(e)[:300]}
                )
        if tpu is None:
            raise RuntimeError(f"all bench attempts failed: {attempts}")

        cpu = run_cpu_oracle()
        cpp = run_cpp_baseline(tpu["n_hosts"], tpu["windows"])
        # vs_baseline is against the HONEST thread-per-core C++ DES when it
        # built and ran; the interpreted Python oracle otherwise (labeled).
        if cpp and "best" in cpp:
            base_eps = cpp["best"]["events_per_sec"]
            base_kind = "cpp_thread_per_core"
        else:
            base_eps = cpu["events_per_sec"]
            base_kind = "python_oracle"
        # A TPU benchmark that landed on the CPU fallback is NOT a perf
        # datum: the headline fields must not publish a number whose
        # denominator is a different machine class. The measured fallback
        # row stays in detail for debugging.
        on_accel = tpu.get("backend") not in ("", None, "cpu")
        result = {
            "metric": "phold_events_per_sec",
            "value": round(tpu["events_per_sec"], 1) if on_accel else None,
            "unit": "events/s",
            "vs_baseline": (
                round(tpu["events_per_sec"] / base_eps, 3) if on_accel else None
            ),
            **({} if on_accel else {"invalid": "no accelerator: run fell back "
                                               "to the cpu backend"}),
            "detail": {
                **{k: (round(v, 4) if isinstance(v, float) else v) for k, v in tpu.items()},
                "baseline_kind": base_kind,
                "cpp_thread_per_core": cpp,
                "python_oracle": {
                    k: (round(v, 4) if isinstance(v, float) else v) for k, v in cpu.items()
                },
                "failed_attempts": attempts,
            },
        }
        if probe.get("error"):
            result["detail"]["backend_probe_error"] = probe["error"]
    except Exception as e:  # noqa: BLE001 — the JSON line must always print
        import traceback

        result = {
            "metric": "phold_events_per_sec",
            "value": None,
            "unit": "events/s",
            "vs_baseline": None,
            "error": repr(e),
            "detail": {"traceback": traceback.format_exc()[-2000:]},
        }
    print(json.dumps(result))


if __name__ == "__main__":
    import sys

    if len(sys.argv) == 4 and sys.argv[1] == "--cpu-child":
        _cpu_child(int(sys.argv[2]), int(sys.argv[3]))
    elif len(sys.argv) == 2 and sys.argv[1] == "--fleet":
        fleet_main()
    else:
        main()
