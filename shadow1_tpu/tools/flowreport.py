"""Flow report — render and diagnose the flow-probe stream.

The reference's per-socket Tracker output is what operators actually read
when a transfer misbehaves: cwnd over time, RTO growth, zero-window stalls
(src/main/host/tracker.c, SURVEY §5). This consumes the ``flow`` JSONL
records the probe plane emits (--watch / probes:, telemetry/probes.py;
docs/OBSERVABILITY.md "Flow probe records") and produces:

* per-flow time series — CSV (--csv) and terminal sparklines;
* a STALL DIAGNOSIS per flow: RTO storms, zero-window peer stalls, cwnd
  collapses, NIC backlog saturation and quiescent-but-open flows, each
  with the window range where it happened.

jax-free by design (log analysis must run anywhere), like
heartbeat_report. ``--selftest`` feeds the detectors a synthesized RTO
storm and fails loudly if it is not flagged — ci.sh runs it as the
observability smoke gate.

    python -m shadow1_tpu.tools.flowreport run.log [--csv flows.csv]
        [--json] [--spark-width 60]
    python -m shadow1_tpu.tools.flowreport --selftest
"""

from __future__ import annotations

import argparse
import csv
import json
import sys

from shadow1_tpu.telemetry.registry import PROBE_FIELDS, REC_FLOW

# TCP states mirrored from consts.py (kept literal so this tool never
# imports the jax-adjacent engine modules by accident).
TCP_ESTABLISHED = 4
DEFAULT_MSS = 1460

# Sparkline glyph ramp (8 levels) — degrades to ASCII with --ascii.
_SPARKS = "▁▂▃▄▅▆▇█"
_ASCII_SPARKS = "_.-=+*#%"


def load_records(path: str) -> list[dict]:
    recs = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                recs.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return recs


def group_flows(recs: list[dict]) -> dict[tuple, list[dict]]:
    """REC_FLOW records → {(exp, host, sock): rows sorted by window}.

    Fleet logs tag rows with ``exp``; solo logs leave it absent (None
    key). Duplicate windows (a resumed run replaying its drained chunk)
    collapse to the LAST occurrence — probe rows are deterministic, so
    replays carry identical values anyway."""
    by_key: dict[tuple, dict[int, dict]] = {}
    for r in recs:
        if r.get("type") != REC_FLOW:
            continue
        key = (r.get("exp"), r.get("host"), r.get("sock"))
        by_key.setdefault(key, {})[r.get("window", 0)] = r
    return {
        k: [w[i] for i in sorted(w)]
        for k, w in sorted(
            by_key.items(),
            key=lambda kv: (kv[0][0] is not None, kv[0][0] or 0,
                            kv[0][1] or 0, kv[0][2] or 0))
    }


def _window_ns(rows: list[dict]) -> int | None:
    """The window length, recovered from one row's (window, sim_time_s)
    pair: sim_time_s = (window + 1) * window_ns / 1e9 exactly."""
    for r in rows:
        w = r.get("window")
        t = r.get("sim_time_s")
        if w is not None and t is not None:
            return round(t * 1e9 / (w + 1))
    return None


# -- stall detectors --------------------------------------------------------
#
# Each detector takes the flow's window-sorted rows and returns a finding
# dict {"kind", "first_window", "last_window", ...detail} or None. They
# key off the PROBE_FIELDS columns only — anything derivable from the
# stream, nothing needing the config.

def detect_rto_storm(rows: list[dict]) -> dict | None:
    """Two or more CONSECUTIVE rto increases while data is in flight —
    the exponential-backoff signature of a flow losing every probe
    (retransmit timer doubling with nothing ACKed)."""
    best = None
    run_start = None
    run = 0
    for prev, cur in zip(rows, rows[1:]):
        grew = (cur.get("rto", 0) > prev.get("rto", 0)
                and cur.get("inflight", 0) > 0)
        if grew:
            if run == 0:
                run_start = prev.get("window")
            run += 1
            if run >= 2 and (best is None or run > best["backoffs"]):
                best = {"kind": "rto_storm",
                        "first_window": run_start,
                        "last_window": cur.get("window"),
                        "backoffs": run,
                        "rto_ns": cur.get("rto")}
        else:
            run = 0
    return best


def detect_zero_window(rows: list[dict]) -> dict | None:
    """peer_wnd == 0 while ESTABLISHED: the receiver closed its window and
    the sender is stalled waiting for a window update."""
    hit = [r for r in rows
           if r.get("tcp_state") == TCP_ESTABLISHED
           and r.get("peer_wnd") == 0]
    if not hit:
        return None
    return {"kind": "zero_window",
            "first_window": hit[0].get("window"),
            "last_window": hit[-1].get("window"),
            "windows": len(hit)}


def detect_cwnd_collapse(rows: list[dict],
                         mss: int = DEFAULT_MSS) -> dict | None:
    """cwnd fell to ≤ peak/4 after a peak of ≥ 4·mss — a loss event (or
    storm) cut the congestion window hard. Slow-start ramps are exempt:
    only the post-peak minimum counts."""
    peak = 0
    peak_w = None
    best = None
    for r in rows:
        c = r.get("cwnd", 0)
        if c > peak:
            peak, peak_w = c, r.get("window")
        elif peak >= 4 * mss and c <= peak // 4:
            if best is None or c < best["cwnd_min"]:
                best = {"kind": "cwnd_collapse",
                        "first_window": peak_w,
                        "last_window": r.get("window"),
                        "cwnd_peak": peak, "cwnd_min": c}
    return best


def detect_nic_backlog(rows: list[dict],
                       window_ns: int | None) -> dict | None:
    """NIC tx backlog beyond ~4 windows of serialization time: the host is
    generating traffic faster than its line rate drains it (the probe
    column is free-time beyond the window end, so > 0 already means the
    NIC is scheduled past the horizon)."""
    if window_ns is None:
        return None
    thresh = 4 * window_ns
    hit = [r for r in rows if r.get("nic_tx_backlog_ns", 0) > thresh]
    if not hit:
        return None
    worst = max(hit, key=lambda r: r.get("nic_tx_backlog_ns", 0))
    return {"kind": "nic_backlog_saturation",
            "first_window": hit[0].get("window"),
            "last_window": hit[-1].get("window"),
            "backlog_ns_max": worst.get("nic_tx_backlog_ns"),
            "threshold_ns": thresh}


def detect_quiescent(rows: list[dict], trailing: int = 4) -> dict | None:
    """ESTABLISHED with nothing in flight and no new data sent (snd_max
    frozen) over the trailing ``trailing`` windows — an open flow that
    has gone silent (app idle, or the peer's zero window outlived the
    capture)."""
    if len(rows) < trailing:
        return None
    tail = rows[-trailing:]
    if all(r.get("tcp_state") == TCP_ESTABLISHED
           and r.get("inflight", 1) == 0
           and r.get("snd_max") == tail[0].get("snd_max")
           for r in tail):
        return {"kind": "quiescent",
                "first_window": tail[0].get("window"),
                "last_window": tail[-1].get("window"),
                "windows": trailing}
    return None


def diagnose_flow(rows: list[dict], window_ns: int | None = None,
                  mss: int = DEFAULT_MSS) -> list[dict]:
    """All stall findings for one flow's window-sorted rows."""
    if window_ns is None:
        window_ns = _window_ns(rows)
    findings = [
        detect_rto_storm(rows),
        detect_zero_window(rows),
        detect_cwnd_collapse(rows, mss=mss),
        detect_nic_backlog(rows, window_ns),
        detect_quiescent(rows),
    ]
    return [f for f in findings if f is not None]


# -- rendering --------------------------------------------------------------

def sparkline(values: list, width: int = 60, ascii_only: bool = False) -> str:
    """Downsample ``values`` to ``width`` buckets (bucket max — spikes must
    survive) and render one glyph per bucket."""
    if not values:
        return ""
    ramp = _ASCII_SPARKS if ascii_only else _SPARKS
    if len(values) > width:
        n = len(values)
        values = [max(values[i * n // width:
                             max(i * n // width + 1, (i + 1) * n // width)])
                  for i in range(width)]
    lo, hi = min(values), max(values)
    span = hi - lo
    if span == 0:
        return ramp[0] * len(values)
    return "".join(ramp[int((v - lo) * (len(ramp) - 1) / span)]
                   for v in values)


def _flow_label(key: tuple) -> str:
    exp, host, sock = key
    tag = f"exp {exp} " if exp is not None else ""
    ent = f"host {host}" if (sock is None or sock < 0) else \
        f"host {host} sock {sock}"
    return tag + ent


def flow_stats(rows: list[dict]) -> dict:
    """Compact per-flow stats for the report header / heartbeat_report's
    flows section."""
    last = rows[-1]
    inflight = [r.get("inflight", 0) for r in rows]
    return {
        "windows": len(rows),
        "first_window": rows[0].get("window"),
        "last_window": last.get("window"),
        "tcp_state_last": last.get("tcp_state"),
        "cwnd_last": last.get("cwnd"),
        "srtt_last_ns": last.get("srtt"),
        "rto_last_ns": last.get("rto"),
        "inflight_max": max(inflight) if inflight else 0,
        "peer_wnd_min": min((r.get("peer_wnd", 0) for r in rows),
                            default=0),
        "nic_tx_backlog_ns_max": max(
            (r.get("nic_tx_backlog_ns", 0) for r in rows), default=0),
        "pending_events_last": last.get("pending_events"),
    }


def report(flows: dict[tuple, list[dict]], out=None, width: int = 60,
           ascii_only: bool = False, mss: int = DEFAULT_MSS) -> dict:
    out = out if out is not None else sys.stdout
    result: dict = {"flows": {}}
    for key, rows in flows.items():
        label = _flow_label(key)
        stats = flow_stats(rows)
        findings = diagnose_flow(rows, mss=mss)
        result["flows"][label] = {**stats, "stalls": findings}
        print(f"== flow: {label} ==", file=out)
        print(f"  windows {stats['windows']} "
              f"[{stats['first_window']}..{stats['last_window']}]  "
              f"state {stats['tcp_state_last']}  "
              f"cwnd {stats['cwnd_last']}  "
              f"srtt {stats['srtt_last_ns']} ns  "
              f"rto {stats['rto_last_ns']} ns", file=out)
        for field in ("cwnd", "inflight", "srtt",
                      "nic_tx_backlog_ns", "pending_events"):
            series = [r.get(field, 0) for r in rows]
            if not any(series):
                continue
            print(f"  {field:>18} "
                  f"{sparkline(series, width, ascii_only)}  "
                  f"max {max(series)}", file=out)
        if findings:
            for f in findings:
                detail = {k: v for k, v in f.items()
                          if k not in ("kind", "first_window",
                                       "last_window")}
                print(f"  STALL {f['kind']}: windows "
                      f"{f['first_window']}..{f['last_window']}  "
                      f"{detail}", file=out)
        else:
            print("  no stalls detected", file=out)
    return result


def write_csv(flows: dict[tuple, list[dict]], path: str) -> None:
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["exp", "host", "sock", "window", "sim_time_s",
                    *PROBE_FIELDS])
        for (exp, host, sock), rows in flows.items():
            for r in rows:
                w.writerow([exp, host, sock, r.get("window"),
                            r.get("sim_time_s"),
                            *[r.get(field) for field in PROBE_FIELDS]])


# -- self-test --------------------------------------------------------------

def _synth_rto_storm() -> list[dict]:
    """A synthesized flow: clean slow-start, then every window doubles the
    RTO with a full segment stuck in flight — the detector MUST flag it."""
    window_ns = 1_000_000
    rows = []
    rto = 250_000_000
    for w in range(20):
        cwnd = min(14600 * (w + 1), 64 * 1460)
        inflight = 1460
        if w >= 10:  # storm: nothing ACKs, the timer doubles per window
            rto *= 2
        rows.append({
            "type": REC_FLOW, "window": w,
            "sim_time_s": round((w + 1) * window_ns / 1e9, 9),
            "host": 0, "sock": 0,
            "tcp_state": TCP_ESTABLISHED, "cwnd": cwnd,
            "ssthresh": 1 << 28, "srtt": 2_000_000, "rttvar": 500_000,
            "rto": rto, "inflight": inflight, "snd_max": 1460 * (w + 1),
            "peer_wnd": 65535, "nic_tx_backlog_ns": 0,
            "nic_rx_backlog_ns": 0, "nic_tx_bytes": 1460 * (w + 1),
            "nic_rx_bytes": 0, "pending_events": 2,
        })
    return rows


def selftest(out=None) -> int:
    """Detector smoke: the injected RTO storm must be flagged, and a clean
    ramp must NOT be (ci.sh observability gate)."""
    out = out if out is not None else sys.stdout
    rows = _synth_rto_storm()
    findings = diagnose_flow(rows)
    kinds = {f["kind"] for f in findings}
    clean = diagnose_flow(rows[:10])  # pre-storm prefix: healthy ramp
    ok = "rto_storm" in kinds and not any(
        f["kind"] == "rto_storm" for f in clean)
    print(json.dumps({"selftest": "ok" if ok else "FAIL",
                      "storm_flagged": sorted(kinds),
                      "clean_prefix_flagged": sorted(
                          f["kind"] for f in clean)}), file=out)
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="shadow1_tpu.tools.flowreport")
    ap.add_argument("log", nargs="?",
                    help="JSONL log carrying 'flow' records "
                         "(CLI --watch stderr, or a heartbeat log)")
    ap.add_argument("--csv", default=None,
                    help="write the per-flow time series as CSV")
    ap.add_argument("--json", action="store_true",
                    help="print the stats+stalls result as JSON instead "
                         "of the terminal report")
    ap.add_argument("--spark-width", type=int, default=60, metavar="N",
                    help="sparkline width in glyphs (default 60)")
    ap.add_argument("--ascii", action="store_true",
                    help="ASCII sparklines (no Unicode blocks)")
    ap.add_argument("--mss", type=int, default=DEFAULT_MSS,
                    help="segment size for the cwnd-collapse threshold "
                         f"(default {DEFAULT_MSS})")
    ap.add_argument("--selftest", action="store_true",
                    help="run the stall-detector self-test and exit")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    if not args.log:
        ap.error("a log path is required (or --selftest)")
    recs = load_records(args.log)
    flows = group_flows(recs)
    if not flows:
        print("no 'flow' records found — run with --watch or a config "
              "'probes:' section", file=sys.stderr)
        return 1
    if args.json:
        result = {"flows": {}}
        for key, rows in flows.items():
            result["flows"][_flow_label(key)] = {
                **flow_stats(rows),
                "stalls": diagnose_flow(rows, mss=args.mss)}
        print(json.dumps(result, indent=2))
    else:
        report(flows, width=args.spark_width, ascii_only=args.ascii,
               mss=args.mss)
    if args.csv:
        write_csv(flows, args.csv)
        print(f"wrote {args.csv}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
