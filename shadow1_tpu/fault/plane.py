"""Traced fault-plane helpers — the device side of the fault schedule.

Every function here is pure jnp over the dense tables built by
``fault/schedule.py`` and rides inside the jitted window loop: the down
predicates cost K (intervals/host) compares, the link/ramp gates L/R
broadcast compares over the window's flat packet axis — all at window or
round granularity, never a host sync. The CPU oracle mirrors the identical
integer predicates from the same numpy tables (cpu_engine/engine.py), so
the decisions are bit-equal by construction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def hosts_down_at(down, up, t) -> jnp.ndarray:
    """bool down-mask for times ``t`` of shape [..., H] (per-host last
    axis), against the [K, H] interval tensors."""
    k, h = down.shape
    shape = (k,) + (1,) * (t.ndim - 1) + (h,)
    d, u = down.reshape(shape), up.reshape(shape)
    tt = t[None]
    return ((tt >= d) & (tt < u)).any(axis=0)


def hosts_down_at_idx(down, up, idx, t) -> jnp.ndarray:
    """Per-packet down-mask: ``idx`` [N] host indices, ``t`` [N] times."""
    d, u = down[:, idx], up[:, idx]
    tt = t[None, :]
    return ((tt >= d) & (tt < u)).any(axis=0)


def link_down_mask(link_fault, vs, vd, dep) -> jnp.ndarray:
    """bool [N]: packet's (src vertex, dst vertex, departure) hits an
    outage window. ``link_fault`` is the (src, dst, t0, t1) table."""
    src, dst, t0, t1 = link_fault
    m = (
        (vs[None, :] == src[:, None])
        & (vd[None, :] == dst[:, None])
        & (dep[None, :] >= t0[:, None])
        & (dep[None, :] < t1[:, None])
    )
    return m.any(axis=0)


def ramp_loss_thr(loss_ramp, vs, vd, dep, thr) -> jnp.ndarray:
    """Apply the timed loss ramps: where a packet's path+departure matches
    an entry, its u64 Bernoulli threshold is replaced (entries in order —
    later entries win, same rule as the oracle). Static unroll: R is a
    handful of config lines."""
    src, dst, t0, t1, rthr = loss_ramp
    for i in range(src.shape[0]):
        m = (vs == src[i]) & (vd == dst[i]) & (dep >= t0[i]) & (dep < t1[i])
        thr = jnp.where(m, rthr[i], thr)
    return thr


def restart_mask(up, win_start) -> jnp.ndarray:
    """bool [H]: hosts whose (window-quantized) up time IS this window's
    start — their restart reset applies before this window's rounds."""
    return (up == win_start).any(axis=0)


def reset_host_columns(tree, init_tree, mask, n_hosts: int):
    """Restore the masked hosts' columns of every per-host leaf to its
    initial value (the post-init model capture). The host axis is the LAST
    axis by the state layout contract (shard/engine._spec_for,
    compact._gather_tree use the same rule); leaves of other shapes —
    scalars, config tables — pass through untouched."""
    def r(cur, ini):
        if hasattr(cur, "ndim") and cur.ndim >= 1 and cur.shape[-1] == n_hosts:
            m = mask.reshape((1,) * (cur.ndim - 1) + (n_hosts,))
            return jnp.where(m, jnp.asarray(ini, cur.dtype), cur)
        return cur
    return jax.tree.map(r, tree, init_tree)
