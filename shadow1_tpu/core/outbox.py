"""Per-host per-window packet outboxes.

In the reference, a packet send walks NIC → topology path lookup → a locked
push onto the destination host's queue (SURVEY §3.3, src/main/routing/
topology.c + core/scheduler). Conservative windows guarantee every
cross-host event lands at least one window in the future, so the batched
engine buffers all sends of a window here and performs routing (latency
gather, loss draws) plus the destination scatter once per window — and, when
sharded, exactly one all_to_all per window over ICI (SURVEY §2.5).

Layout: slot-major, host-minor ([P, H]; payload [NP, P, H]) — see
core/dense.py for the tiling rationale.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from shadow1_tpu.consts import NP
from shadow1_tpu.core.dense import set_col


class Outbox(NamedTuple):
    dst: jnp.ndarray      # i32 [P, H]
    kind: jnp.ndarray     # i32 [P, H] event kind to deliver at dst
    depart: jnp.ndarray   # i64 [P, H] time the packet leaves the src NIC
    ctr: jnp.ndarray      # i64 [P, H] per-src lifetime packet counter
    p: jnp.ndarray        # i32 [NP, P, H]
    cnt: jnp.ndarray      # i32 [H] entries used this window
    pkt_ctr: jnp.ndarray  # i64 [H] lifetime per-src packet counter


def outbox_init(n_hosts: int, cap: int) -> Outbox:
    return Outbox(
        dst=jnp.zeros((cap, n_hosts), jnp.int32),
        kind=jnp.zeros((cap, n_hosts), jnp.int32),
        depart=jnp.zeros((cap, n_hosts), jnp.int64),
        ctr=jnp.zeros((cap, n_hosts), jnp.int64),
        p=jnp.zeros((NP, cap, n_hosts), jnp.int32),
        cnt=jnp.zeros(n_hosts, jnp.int32),
        pkt_ctr=jnp.zeros(n_hosts, jnp.int64),
    )


def outbox_space(ob: Outbox) -> jnp.ndarray:
    return ob.dst.shape[0] - ob.cnt


def outbox_append(ob: Outbox, mask, dst, kind, depart, p) -> tuple[Outbox, jnp.ndarray]:
    """Append one packet per host where ``mask``. Returns (ob, ok_mask).

    Callers that cannot tolerate drops (TCP) must check ``outbox_space``
    first and defer to the next window instead (K_TX_RESUME). Dense one-hot
    write — no scatter (core/dense.py). ``p`` is [NP, H].
    """
    cap = ob.dst.shape[0]
    ok = mask & (ob.cnt < cap)
    ob = ob._replace(
        dst=set_col(ob.dst, ob.cnt, dst, ok),
        kind=set_col(ob.kind, ob.cnt, kind, ok),
        depart=set_col(ob.depart, ob.cnt, depart, ok),
        ctr=set_col(ob.ctr, ob.cnt, ob.pkt_ctr, ok),
        p=set_col(ob.p, ob.cnt, p, ok),
        cnt=ob.cnt + ok.astype(jnp.int32),
        pkt_ctr=ob.pkt_ctr + ok.astype(jnp.int64),
    )
    return ob, ok


def outbox_clear(ob: Outbox) -> Outbox:
    return ob._replace(cnt=jnp.zeros_like(ob.cnt))
