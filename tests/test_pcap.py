"""pcap writer golden-bytes tests + watchlist capture filter.

The writer synthesizes real IPv4 + TCP/UDP headers from packet records
(tools/pcap.py); these tests pin the exact bytes so a header-layout
regression (endianness, field order, flag mapping, snaplen math) shows up
as a byte diff, not as "wireshark renders it oddly".
"""

import struct

from shadow1_tpu.consts import F_ACK, F_DGRAM, F_FIN, F_RST, F_SYN
from shadow1_tpu.tools.pcap import FilteredPcap, PcapWriter

M32 = 0xFFFFFFFF


def _pkt(ss=0, ds=0, flags=0, seq=0, ack=0, length=0, wnd=0):
    # The oracle's packet tuple: p[1] packs (ss, ds, flags), then
    # seq / ack / payload-length / advertised-window.
    return (0, ss | (ds << 8) | (flags << 16), seq, ack, length, wnd)


def _capture(tmp_path, calls, snaplen=128):
    path = str(tmp_path / "t.pcap")
    with PcapWriter(path, snaplen=snaplen) as w:
        for c in calls:
            w(*c)
    with open(path, "rb") as f:
        return f.read()


def _frames(data):
    """Split a pcap byte string into (per-packet header, frame) pairs."""
    assert struct.unpack_from("<IHHiIII", data, 0)[0] == 0xA1B2C3D4
    out, off = [], 24
    while off < len(data):
        hdr = struct.unpack_from("<IIII", data, off)
        off += 16
        out.append((hdr, data[off:off + hdr[2]]))
        off += hdr[2]
    return out


def test_syn_packet_golden_bytes(tmp_path):
    data = _capture(tmp_path, [
        (1_500_000_000, 1, 0, _pkt(ss=2, ds=3, flags=F_SYN, seq=7,
                                   wnd=65535), False),
    ])
    assert data[:24] == struct.pack(
        "<IHHiIII", 0xA1B2C3D4, 2, 4, 0, 0, 128, 101)
    (hdr, frame), = _frames(data)
    ts_sec, ts_usec, incl, orig = hdr
    assert (ts_sec, ts_usec) == (1, 500000)  # 1.5 s, ns → µs
    assert incl == orig == 40                # 20 IP + 20 TCP, no payload
    ip = struct.pack(">BBHHHBBH", 0x45, 0, 40, 0, 0, 64, 6, 0) \
        + bytes([10, 0, 0, 1]) + bytes([10, 0, 0, 0])
    tcp = struct.pack(">HHIIBBHHH", 10002, 10003, 7, 0, 5 << 4,
                      0x02, 65535, 0, 0)
    assert frame == ip + tcp


def test_fin_ack_and_rst_flag_mapping(tmp_path):
    data = _capture(tmp_path, [
        (0, 0, 1, _pkt(flags=F_FIN | F_ACK, seq=9, ack=10), False),
        (0, 1, 0, _pkt(flags=F_RST), False),
    ])
    (_, fin_frame), (_, rst_frame) = _frames(data)
    # TCP flags byte is offset 13 in the 20-byte header after 20 bytes IP.
    assert fin_frame[20 + 13] == 0x01 | 0x10
    assert rst_frame[20 + 13] == 0x04
    assert struct.unpack_from(">I", fin_frame, 20 + 4)[0] == 9
    assert struct.unpack_from(">I", fin_frame, 20 + 8)[0] == 10


def test_seq_ack_wrap_to_u32(tmp_path):
    data = _capture(tmp_path, [
        (0, 0, 1, _pkt(seq=(1 << 32) + 5, ack=-1 & M32, flags=F_ACK), False),
    ])
    (_, frame), = _frames(data)
    assert struct.unpack_from(">I", frame, 20 + 4)[0] == 5
    assert struct.unpack_from(">I", frame, 20 + 8)[0] == M32


def test_dgram_is_udp(tmp_path):
    data = _capture(tmp_path, [
        (2_000, 4, 5, _pkt(ss=1, ds=2, flags=F_DGRAM, length=100), False),
    ])
    (hdr, frame), = _frames(data)
    assert frame[9] == 17  # IP protocol = UDP
    sport, dport, ulen, csum = struct.unpack_from(">HHHH", frame, 20)
    assert (sport, dport, ulen, csum) == (10001, 10002, 108, 0)
    assert hdr[3] == 20 + 8 + 100  # orig_len carries the payload


def test_snaplen_truncation(tmp_path):
    data = _capture(tmp_path, [
        (0, 0, 1, _pkt(length=1000), False),
    ], snaplen=64)
    (hdr, frame), = _frames(data)
    _, _, incl, orig = hdr
    assert orig == 20 + 20 + 1000
    assert incl == 64 and len(frame) == 64
    # Payload is zero padding beyond the real headers.
    assert frame[40:] == b"\x00" * 24


def test_dropped_packets_are_skipped(tmp_path):
    data = _capture(tmp_path, [
        (0, 0, 1, _pkt(), True),
        (0, 0, 1, _pkt(), False),
    ])
    assert len(_frames(data)) == 1


def test_filtered_pcap_watchlist(tmp_path):
    path = str(tmp_path / "f.pcap")
    # Watch host 3's socket 0 and all of host 7.
    with FilteredPcap(PcapWriter(path), ((3, 0), (7, -1))) as w:
        w(0, 3, 1, _pkt(ss=0, ds=2), False)   # src match (3, sock 0)
        w(0, 3, 1, _pkt(ss=1, ds=2), False)   # src host 3 but sock 1: no
        w(0, 1, 3, _pkt(ss=5, ds=0), False)   # dst match (3, sock 0)
        w(0, 7, 1, _pkt(ss=9, ds=0), False)   # host 7 matches any sock
        w(0, 1, 2, _pkt(), False)             # unrelated
        assert w.n_packets == 3
    with open(path, "rb") as f:
        assert len(_frames(f.read())) == 3


def test_filtered_pcap_empty_watchlist_passes_all(tmp_path):
    path = str(tmp_path / "all.pcap")
    with FilteredPcap(PcapWriter(path), ()) as w:
        w(0, 0, 1, _pkt(), False)
        w(0, 5, 6, _pkt(), False)
        assert w.n_packets == 2
