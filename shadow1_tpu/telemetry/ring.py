"""On-device per-window telemetry ring.

The reference's Tracker emits one counter row per heartbeat interval
(src/main/host/tracker.c, SURVEY §5); our chunked heartbeat only sees the
chunk-AVERAGED deltas, so a one-window occupancy spike — the exact datum
the rung-cap sizing debate needed (docs/R6_NOTES.md) — vanishes into the
mean. The ring fixes that without reintroducing mid-window host syncs:

* a device-resident ``[W, F]`` i64 buffer rides in ``SimState.telem``;
* at the end of every conservative window the engine writes one row —
  per-window DELTAS of the core counters plus the occupancy gauges
  (``registry.RING_FIELDS`` order) — at slot ``window % W``, entirely
  inside the jitted window loop (one dynamic_update_slice, no sync);
* at chunk boundaries the host drains the rows that accumulated since the
  last drain (``drain_ring``) and emits them as JSONL ``type: "ring"``
  records.

The ring therefore holds the last W windows; if a chunk spans more than W
windows the overwritten rows are gone — ``drain_ring`` reports the gap
explicitly (a ``ring_gap`` record) rather than pretending continuity.

Under sharding each shard computes its local row and the per-window
reduction (``telem_reduce`` in shard/engine.py) psums the counter columns
and max-reduces the fill gauge, so every shard carries the identical,
globally-correct ring — replicated like ``win_start``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from shadow1_tpu.consts import SEC
from shadow1_tpu.telemetry.registry import (
    REC_RING,
    REC_RING_GAP,
    RING_COUNTERS,
    RING_FIELDS,
)


class TelemetryRing(NamedTuple):
    """The device-resident ring: one i64 row per window, RING_FIELDS order."""

    buf: jnp.ndarray  # i64 [W, len(RING_FIELDS)]


def ring_init(n_windows: int) -> TelemetryRing | None:
    """A W-row ring, or None when the ring is disabled (W == 0).

    None keeps the SimState pytree leaf count identical to a ring-less
    build, so checkpoints and sharding specs are unaffected unless the
    ring is actually on."""
    if n_windows <= 0:
        return None
    return TelemetryRing(
        buf=jnp.zeros((int(n_windows), len(RING_FIELDS)), jnp.int64)
    )


def ring_record(ring: TelemetryRing, m0, m1, ev_fill,
                telem_reduce=None, digests=None) -> TelemetryRing:
    """Write one per-window row (traced; called at the end of window_step).

    ``m0``/``m1`` are the Metrics before/after the window; counter columns
    store ``m1 - m0``. ``ev_fill`` is the window-end event-slot fill the
    engine already computed for the ``ev_max_fill`` gauge. ``digests`` is
    the per-window state-digest vector (i64 [len(RING_DIGESTS)],
    core/digest.state_digests) or None — the digest columns then hold 0.
    ``telem_reduce(counters, gauges) -> (counters, gauges)`` globalizes the
    row under sharding (psum the counter deltas, elementwise-max the gauge
    vector); identity on a single device. The digest words are per-shard
    PARTIAL SUMS, so they ride the psum'd counter vector and come out as
    the exact single-device digests. ``x2x_max_fill`` is already replicated
    by the exchange's psum trick, so it bypasses the reduce."""
    from shadow1_tpu.telemetry.registry import RING_DIGESTS, RING_WORK

    w = ring.buf.shape[0]
    # The wasted-work columns (RING_WORK) are deltas of running-sum
    # counters like the rest — additive across shards, so they ride the
    # same psum'd vector and come out globally exact.
    counters = jnp.stack(
        [getattr(m1, f) - getattr(m0, f) for f in RING_COUNTERS + RING_WORK]
    )
    if digests is None:
        digests = jnp.zeros(len(RING_DIGESTS), jnp.int64)
    n_ctr = counters.shape[0]
    counters = jnp.concatenate([counters, digests])
    # RING_GAUGES order minus the trailing replicated x2x_max_fill.
    gauges = jnp.stack(
        [ev_fill, m1.ev_max_fill, m1.ob_max_fill, m1.compact_max_fill]
    )
    if telem_reduce is not None:
        counters, gauges = telem_reduce(counters, gauges)
    counters, digests = counters[:n_ctr], counters[n_ctr:]
    row = jnp.concatenate(
        [counters, gauges, m1.x2x_max_fill[None], digests]
    ).astype(jnp.int64)
    # Slot = this window's global ordinal (the pre-increment counter).
    slot = (m0.windows % w).astype(jnp.int32)
    return ring._replace(
        buf=jax.lax.dynamic_update_slice(
            ring.buf, row[None, :], (slot, jnp.zeros((), jnp.int32))
        )
    )


def drain_ring(st, window_ns: int, start: int = 0) -> list[dict]:
    """Host-side drain: the ring rows for windows [start, windows_done).

    One device→host fetch per call (chunk boundary, never mid-window).
    Returns JSONL-ready dicts in window order; when more than W windows
    elapsed since ``start`` the overwritten head is reported as one
    ``ring_gap`` record instead of being silently skipped."""
    ring = getattr(st, "telem", None)
    if ring is None:
        return []
    buf = np.asarray(ring.buf)
    w = buf.shape[0]
    done = int(st.metrics.windows)
    lo = max(start, done - w)
    recs: list[dict] = []
    if lo > start:
        recs.append({
            "type": REC_RING_GAP,
            "windows_lost": lo - start,
            "first_window": start,
            "ring_slots": w,
        })
    for win in range(lo, done):
        row = buf[win % w]
        rec = {
            "type": REC_RING,
            "window": win,
            "sim_time_s": round((win + 1) * window_ns / SEC, 9),
        }
        rec.update({f: int(v) for f, v in zip(RING_FIELDS, row)})
        recs.append(rec)
    return recs
