"""The engine daemon — a persistent multi-tenant simulation service.

``python -m shadow1_tpu serve --spool DIR [--metrics-port P]`` starts a
long-lived process that owns one spool directory and serves job
submissions (standard YAML experiment configs) through four planes:

1. **hot engine cache** (serve/cache.py): compiled FleetEngine programs
   keyed by (shape class, caps, engine knobs, lane count, backend); a
   repeat-shape batch rebinds its per-job variants and skips trace +
   compile entirely (hit/miss/evict counters on the ledger);
2. **two-tier admission with backpressure**: every submission is priced
   by the ``mem.abstract_state`` pre-flight BEFORE any compile; only a
   job that could never fit an IDLE device is rejected
   (``error=memory_budget`` advice record). One that fits idle but not
   the live headroom is admitted as ``waiting_headroom`` and scheduled
   when the resident batch drains; the wait is bounded (--queue-depth /
   --queue-bytes) with a structured ``error=queue_full`` rejection
   carrying ``retry_after_s`` advice — never a silent drop, never an
   OOM for the tenants already running. --queue-ttl-s expires a job
   still waiting; --deadline-s bounds a running job at chunk
   boundaries, keeping its committed prefix (serve/client.py flags);
3. **lane-packing scheduler**: queued shape-compatible jobs bin into one
   fleet batch (vmapped lanes, fleet/run.py is the execution backend),
   always under ``on_lane_fail=quarantine`` (one tenant's capacity halt
   never kills cohabitants) and ``lane_finalize`` (short jobs exit lanes
   early and free capacity); a higher-priority submission arriving
   mid-batch EVICTS the batch through the preemption plane — the drain
   latch commits the in-flight chunk, checkpoints the batch, and the
   preempted jobs requeue behind their checkpoint cursor, resuming
   bit-identically;
4. **per-job observability**: every ring/digest row is routed into the
   job's ``result.jsonl`` tagged with its job id, state transitions land
   as ``serve_job`` records (spool status files + serve.log), and the
   job ledger exports as Prometheus gauges (``--metrics-port``,
   SERVE_SPECS namespace).

The contract that makes this safe to ship (docs/SEMANTICS.md §"Serving
contract"): a job run through the daemon produces a digest stream and
parity counters bit-identical to the same config run through the solo
CLI. Lanes are vmap-independent and the eviction path is the preemption
plane's commit-before-snapshot drain, so neither cohabitation nor
eviction can move a single bit of any tenant's stream.

Transient-failure retry (cli._supervise's classification, batch-scoped):
a batch that dies from anything but a deterministic taxonomy error
(allocator abort, capacity halt, memory budget, config, selfcheck) is
retried with exponential backoff (SHADOW1_SERVE_RETRY_BACKOFF_S) from
its last committed lineage generation; the second crash of the same
batch bisects the suspects into solo batches, and --retry-max solo
crashes make the job terminal ``failed`` with its crash ledger attached.

Graceful shutdown: the first SIGTERM/SIGINT (or a socket ``shutdown``
op) reuses ``preempt.DrainHandler`` — the in-flight batch drains at its
next chunk boundary and checkpoints, queued jobs persist to
``queue.json`` (atomic), and the daemon exits ``EXIT_SERVE_SHUTDOWN``;
restarting on the same spool resumes exactly where it left off. A
SIGKILLed daemon loses only in-flight batch progress: on restart,
non-terminal jobs are re-validated and requeued from scratch —
determinism makes the re-run bit-identical (chaosprobe --serve). Spool
ownership is an fcntl flock plus a heartbeat/pid stale-lock protocol
(serve/protocol.py) — a SIGKILLed daemon's spool is reclaimed, even on
NFS, while a live holder is always refused.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import socket
import sys
import threading
import time

from shadow1_tpu.consts import (
    EXIT_SERVE_SHUTDOWN,
    EXIT_SERVE_SPOOL,
)
from shadow1_tpu.serve.protocol import (
    HEARTBEAT_S,
    J_DONE,
    J_EVICTED,
    J_FAILED,
    J_QUEUED,
    J_REJECTED,
    J_RUNNING,
    J_WAITING,
    TERMINAL_STATES,
    Spool,
    send_line,
)


class SpoolError(RuntimeError):
    """The spool directory cannot be owned (unusable, or a live daemon
    already holds it) — the daemon refuses to start (EXIT_SERVE_SPOOL)."""


@dataclasses.dataclass
class ServeJob:
    """One admitted job: its compiled experiment plus scheduling state."""

    id: str
    exp: object                  # CompiledExperiment
    params: object               # EngineParams (daemon lane policies applied)
    priority: int
    seq: int                     # admission order (FIFO within priority)
    windows: int | None          # explicit horizon override, else config's
    est_peak: int                # pre-flight peak bytes (n_exp=1)
    queue_ttl_s: float | None = None   # expire if still waiting past this
    deadline_s: float | None = None    # bound on running wall time
    enqueued_at: float = 0.0     # wall time of admission (TTL + wait stats)
    first_run_at: float | None = None  # wall time of first batch_start
    waiting: bool = False        # admitted over live headroom (fits idle)
    solo: bool = False           # bisected after repeat crashes: own batch
    crashes: list = dataclasses.field(default_factory=list)  # crash ledger

    def pack_key(self):
        """Jobs with equal keys ride one fleet batch: same shape class,
        same engine params, same horizon (lanes run in lockstep)."""
        from shadow1_tpu.serve.cache import shape_class_key

        return (shape_class_key(self.exp, self.params, 1)[0], self.params,
                self.windows)


class _EvictionLatch:
    """Duck-typed preempt.DrainHandler for the batch runner: ``requested``
    flips when the daemon must take the device back — a real signal, a
    socket shutdown op, or a higher-priority tenant waiting. Polled at
    chunk boundaries only (run_fleet's drain contract), and the poll IS
    the daemon's mid-batch admission step: new submissions are accepted /
    rejected while the batch runs, which is exactly how a higher-priority
    arrival becomes visible."""

    def __init__(self, daemon: "ServeDaemon", batch_priority: int):
        self.daemon = daemon
        self.batch_priority = batch_priority
        self.evicting = False
        self.deadline_jobs: list[str] = []
        self._polls = 0

    @property
    def requested(self) -> bool:
        d = self.daemon
        if (d._drain is not None and d._drain.requested) \
                or d._shutdown.is_set():
            return True
        # Chunk-boundary admission (main thread — no races). Exception-
        # isolated: one tenant's broken submission must never tear down
        # the batch the OTHER tenants are riding. The poll also refreshes
        # the spool heartbeat, sweeps queue TTLs and fires the chaos
        # crash-injection hook (a raise here surfaces as a transient
        # batch failure — the retry plane's test handle).
        self._polls += 1
        d._touch_heartbeat()
        d._safe_intake()
        d._expire_ttl()
        d._maybe_inject_crash(self._polls)
        if any(j.priority > self.batch_priority for j in d.queue):
            self.evicting = True
            return True
        over = d._running_over_deadline()
        if over:
            # --deadline-s enforcement is boundary-quantized by design:
            # the drain commits the in-flight chunk first, so the expired
            # job keeps its committed prefix (bit-identical to the same
            # prefix of a straight run) and its cohabitants resume from
            # the same snapshot, minus its lane.
            self.deadline_jobs = over
            return True
        return False

    @property
    def signame(self) -> str:
        if self.evicting:
            return "EVICT"
        if self.deadline_jobs:
            return "DEADLINE"
        if self.daemon._drain is not None and self.daemon._drain.requested:
            return self.daemon._drain.signame
        return "SHUTDOWN"


class _RecordRouter:
    """The batch's record streams, demultiplexed per job.

    Implements the file protocol run_fleet prints heartbeats/ring rows to
    (``write``/``flush``) plus the ``emit_record`` hook for immediately-
    final records. Ring/digest/work rows carry the lane id (``exp``) —
    each lands in its job's result.jsonl tagged with the job id, so a
    tenant's stream reads exactly like a solo run's stderr. Heartbeats
    and retry audits are fleet-level → the daemon log."""

    def __init__(self, daemon: "ServeDaemon", lane_jobs: dict[int, str],
                 batch_id: str):
        self.daemon = daemon
        self.lane_jobs = lane_jobs
        self.batch_id = batch_id
        self._buf = ""
        # One append handle per job for the batch's duration (a batch
        # streams O(windows x lanes) rows; per-row open/close would be
        # thousands of syscalls on the scheduler thread). Flushed per
        # line so a status transition written AFTER an append is never
        # visible before the record it follows.
        self._files: dict[str, object] = {}

    def _append(self, job: str, rec: dict) -> None:
        f = self._files.get(job)
        if f is None:
            os.makedirs(self.daemon.spool.job_dir(job), exist_ok=True)
            f = open(self.daemon.spool.result_path(job), "a")
            self._files[job] = f
        f.write(json.dumps(rec) + "\n")
        f.flush()

    def close(self) -> None:
        for f in self._files.values():
            try:
                f.close()
            except OSError:
                pass
        self._files.clear()

    # -- file protocol (run_fleet's stream=) -------------------------------

    def write(self, s: str) -> None:
        self._buf += s
        while "\n" in self._buf:
            line, self._buf = self._buf.split("\n", 1)
            self._line(line.strip())

    def flush(self) -> None:
        pass

    def _line(self, line: str) -> None:
        if not line.startswith("{"):
            return
        try:
            rec = json.loads(line)
        except ValueError:
            return
        t = rec.get("type")
        if t in ("ring", "ring_gap", "work", "digest",
                 "link", "link_gap", "flow", "flow_gap"):
            if t == "link":
                self.daemon._note_link(rec)
            job = self.lane_jobs.get(rec.get("exp"))
            if job is not None:
                self._append(job, {**rec, "job": job})
            return
        if t in ("fleet_quarantine", "fleet_exp"):
            return  # handled once, via the emit_record hook below
        self.daemon._log({**rec, "batch": self.batch_id}, echo=False)

    # -- emit_record hook (quarantine / early-finalize) --------------------

    def record(self, rec: dict) -> None:
        job = rec.get("job") or self.lane_jobs.get(rec.get("exp"))
        if job is None:
            return
        self._append(job, {**rec, "job": job})
        if rec.get("type") == "fleet_quarantine":
            self.daemon._job_failed(job, "capacity", rec)
        elif rec.get("type") == "fleet_exp":
            self.daemon._job_done(job, rec)


class ServeDaemon:
    """One spool's scheduler: intake → admission → lane-packed batches."""

    def __init__(self, spool_dir: str, metrics_port: int | None = None,
                 max_lanes: int = 8, cache_capacity: int = 4,
                 poll_s: float = 0.2, ckpt_every_s: float = 60.0,
                 log_level: str = "message", queue_depth: int = 64,
                 queue_bytes: int | None = None, retry_max: int = 3):
        from shadow1_tpu.log import SimLogger
        from shadow1_tpu.serve.cache import EngineCache

        self.spool = Spool(spool_dir)
        self.metrics_port = metrics_port
        self.max_lanes = max(int(max_lanes), 1)
        self.poll_s = poll_s
        self.ckpt_every_s = ckpt_every_s
        self.queue_depth = max(int(queue_depth), 1)
        self.queue_bytes = (int(queue_bytes)
                            if queue_bytes is not None else None)
        self.retry_max = max(int(retry_max), 1)
        self.cache = EngineCache(cache_capacity)
        self.log = SimLogger(level=log_level)
        self.queue: list[ServeJob] = []       # admitted, waiting
        self.resume: list[dict] = []          # evicted/retry-batch cursors
        self.jobs: dict[str, ServeJob] = {}   # every live ServeJob by id
        self.ledger = {k: 0 for k in
                       ("jobs_submitted", "jobs_rejected", "jobs_done",
                        "jobs_failed", "jobs_evicted", "batches_run",
                        "jobs_queue_full", "jobs_expired",
                        "batch_retries", "jobs_bisected",
                        "top_edge_bytes", "top_edge_drops")}
        self.running: list[str] = []          # job ids of in-flight batch
        self._resident_bytes = 0              # in-flight batch estimate
        self._drain = None                    # preempt.DrainHandler
        self._shutdown = threading.Event()    # socket shutdown op
        self._wake = threading.Event()        # socket submit nudge
        self._seq = 0
        self._batch_seq = 0
        self._sock_srv = None
        self._metrics_srv = None
        self._log_f = None
        self._lock_fd = None                  # held flock (spool ownership)
        self._hb_last = 0.0

    # -- events / ledger ---------------------------------------------------

    def _log(self, rec: dict, echo: bool = True) -> None:
        """One JSONL event into serve.log (the report tool's feed) and —
        for daemon-level events — onto stderr for live operators. One
        persistent append handle, flushed per line (heartbeats + job
        transitions would otherwise pay an open/close per record)."""
        line = json.dumps(rec)
        try:
            if self._log_f is None:
                self._log_f = open(self.spool.log_path, "a")
            self._log_f.write(line + "\n")
            self._log_f.flush()
        except OSError:
            pass
        if echo:
            print(line, file=sys.stderr, flush=True)

    def _event(self, event: str, **fields) -> None:
        self._log({"type": "serve", "event": event, "t": time.time(),
                   **fields})

    def _note_link(self, rec: dict) -> None:
        """Track the hottest / lossiest edge seen across every batch (link
        records are cumulative snapshots, so per-edge maxima are just the
        latest values) — exported as the top_edge_* Prometheus gauges."""
        b = int(rec.get("bytes", 0))
        d = (int(rec.get("loss_drops", 0))
             + int(rec.get("link_down_drops", 0))
             + int(rec.get("nic_backlog_drops", 0)))
        if b > self.ledger.get("top_edge_bytes", 0):
            self.ledger["top_edge_bytes"] = b
        if d > self.ledger.get("top_edge_drops", 0):
            self.ledger["top_edge_drops"] = d

    def ledger_dict(self) -> dict[str, int]:
        oldest = min((j.enqueued_at for j in self.queue
                      if j.enqueued_at), default=None)
        return {**self.ledger,
                "jobs_queued": len([j for j in self.queue
                                    if not j.waiting]),
                "jobs_waiting": len([j for j in self.queue if j.waiting]),
                "jobs_running": len(self.running),
                "queue_depth": len(self.queue),
                "queue_bytes": sum(j.est_peak for j in self.queue),
                "oldest_wait_s": (round(time.time() - oldest, 3)
                                  if oldest else 0),
                **self.cache.counters()}

    def _set_state(self, job_id: str, state: str, **fields) -> None:
        self.spool.write_status(job_id, {"state": state, **fields})
        self._log({"type": "serve_job", "job": job_id, "state": state,
                   "t": time.time(), **fields}, echo=False)

    # -- startup / teardown ------------------------------------------------

    def start(self) -> "ServeDaemon":
        try:
            self.spool.ensure()
            probe = os.path.join(self.spool.root, ".probe")
            with open(probe, "w") as f:
                f.write("rw")
            os.remove(probe)
        except OSError as e:
            raise SpoolError(
                f"spool {self.spool.root} is unusable: {e}") from e
        # Spool ownership, NFS-safe: the fcntl flock (kernel-released on
        # ANY death, including SIGKILL) is the same-host gate; the
        # heartbeat/pid protocol in holder_liveness covers holders the
        # flock can't see (another host on a network filesystem). A
        # stale holder — dead pid, or a cross-host heartbeat past the
        # stale threshold — is reclaimed; a live one is refused. Two
        # daemons can never interleave writes: on one host the flock
        # arbitrates, across hosts a live holder keeps its heartbeat
        # fresher than the reclaim threshold.
        self._lock_fd = self.spool.acquire_lock()
        if self._lock_fd is None:
            info = self.spool.daemon_info() or {}
            raise SpoolError(
                f"spool {self.spool.root} is owned by a live daemon "
                f"(flock held; pid {info.get('pid')}) — one daemon per "
                f"spool")
        liveness, info = self.spool.holder_liveness()
        try:
            holder_pid = int((info or {}).get("pid"))
        except (TypeError, ValueError):
            holder_pid = -1
        if liveness == "live" and holder_pid != os.getpid():
            try:
                os.close(self._lock_fd)
            finally:
                self._lock_fd = None
            raise SpoolError(
                f"spool {self.spool.root} is owned by a live daemon "
                f"(pid {info.get('pid')} on {info.get('host')}, "
                f"heartbeat fresh) — one daemon per spool")
        reclaimed = None
        if liveness == "stale":
            reclaimed = {"pid": (info or {}).get("pid"),
                         "host": (info or {}).get("host")}
            for p in (self.spool.daemon_path, self.spool.sock_path):
                try:
                    os.unlink(p)
                except OSError:
                    pass
        self._start_socket()
        from shadow1_tpu.lineage import write_json_atomic

        from shadow1_tpu.serve.protocol import SPOOL_VERSION

        now = time.time()
        write_json_atomic(self.spool.daemon_path,
                          {"pid": os.getpid(),
                           "host": socket.gethostname(),
                           "started_at": now, "heartbeat_at": now,
                           "sock": self.spool.sock_path,
                           "spool_version": SPOOL_VERSION})
        self._hb_last = time.monotonic()
        if reclaimed:
            self._event("lock_reclaimed", stale_holder=reclaimed)
        if self.metrics_port is not None:
            from shadow1_tpu.telemetry.registry import (
                SERVE_SPECS,
                ExpositionServer,
            )

            self._metrics_srv = ExpositionServer(
                self.ledger_dict, port=self.metrics_port,
                prefix="shadow1_serve", specs=SERVE_SPECS).start()
        self._recover()
        self._event("start", pid=os.getpid(), spool=self.spool.root,
                    metrics_port=(self._metrics_srv.port
                                  if self._metrics_srv else None))
        return self

    def _start_socket(self) -> None:
        try:
            os.unlink(self.spool.sock_path)
        except OSError:
            pass
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(self.spool.sock_path)
        srv.listen(16)
        self._sock_srv = srv

        def accept_loop():
            while True:
                try:
                    conn, _ = srv.accept()
                except OSError:
                    return  # server closed — daemon exiting
                threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True).start()

        threading.Thread(target=accept_loop, daemon=True).start()

    def _serve_conn(self, conn) -> None:
        try:
            f = conn.makefile("rw", encoding="utf-8")
            line = f.readline()
            if not line:
                return
            try:
                req = json.loads(line)
            except ValueError:
                send_line(f, {"ok": False, "error": "bad json"})
                return
            op = req.get("op")
            if op == "ping":
                # A ping doubles as the scheduler nudge (client.submit
                # pings after writing its inbox file).
                self._wake.set()
                send_line(f, {"ok": True, "pid": os.getpid(),
                              "ledger": self.ledger_dict()})
            elif op == "submit":
                job = req.get("job") or {}
                if "config_yaml" not in job:
                    send_line(f, {"ok": False, "error": "no config_yaml"})
                    return
                job_id = self.spool.submit(job)
                self._wake.set()
                send_line(f, {"ok": True, "id": job_id})
            elif op == "status":
                st = self.spool.read_status(req.get("id", ""))
                send_line(f, st or {"ok": False, "error": "unknown job"})
            elif op == "watch":
                # Bounded: a status that never appears (bad id, job still
                # in the inbox) errors out after a short grace, and the
                # whole watch has a deadline — a disconnected client must
                # not pin a thread + fd for the daemon's lifetime.
                last = None
                grace = time.monotonic() + 30.0
                deadline = time.monotonic() + 3600.0
                while time.monotonic() < deadline:
                    st = self.spool.read_status(req.get("id", ""))
                    if st is None:
                        if time.monotonic() > grace:
                            send_line(f, {"ok": False,
                                          "error": "unknown job"})
                            return
                    elif st != last:
                        send_line(f, st)
                        last = st
                    if st is not None and st.get("state") in TERMINAL_STATES:
                        return
                    time.sleep(self.poll_s)
                send_line(f, {"ok": False, "error": "watch deadline"})
            elif op == "shutdown":
                self._shutdown.set()
                self._wake.set()
                send_line(f, {"ok": True})
            else:
                send_line(f, {"ok": False, "error": f"unknown op {op!r}"})
        except (OSError, BrokenPipeError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        if self._sock_srv is not None:
            try:
                self._sock_srv.close()
            except OSError:
                pass
        if self._metrics_srv is not None:
            self._metrics_srv.stop()
        if self._log_f is not None:
            try:
                self._log_f.close()
            except OSError:
                pass
            self._log_f = None
        for p in (self.spool.sock_path, self.spool.daemon_path):
            try:
                os.unlink(p)
            except OSError:
                pass
        if self._lock_fd is not None:
            # Closing the fd releases the flock; the lock FILE stays
            # (unlinking a lock file races a concurrent opener).
            try:
                os.close(self._lock_fd)
            except OSError:
                pass
            self._lock_fd = None

    def _touch_heartbeat(self) -> None:
        """Refresh daemon.json's mtime (the stale-lock protocol's
        cross-host liveness signal), throttled to HEARTBEAT_S. Called
        from the run loop and at every chunk boundary, so a daemon deep
        in a long batch still reads as live."""
        now = time.monotonic()
        if now - self._hb_last < HEARTBEAT_S:
            return
        self._hb_last = now
        self.spool.touch_heartbeat()

    # -- recovery (restart on a used spool) --------------------------------

    def _recover(self) -> None:
        """Reload a persisted queue (graceful shutdown) and requeue any
        non-terminal jobs a SIGKILLed daemon left behind (from scratch —
        determinism makes the re-run bit-identical)."""
        cursors = []
        try:
            with open(self.spool.queue_path) as f:
                saved = json.load(f)
            os.remove(self.spool.queue_path)
        except (OSError, ValueError):
            saved = {}
        seen: set[str] = set()
        for cur in saved.get("resume", []):
            # A retry cursor's backoff stamp is monotonic time — it does
            # not survive the process; resume immediately instead.
            cur.pop("not_before", None)
            ok = True
            pending = []
            for j in cur.get("jobs", []):
                seen.add(j)
                st = self.spool.read_status(j)
                if st is not None and st.get("state") in TERMINAL_STATES:
                    continue  # finished before the eviction — stays done
                pending.append(j)
                # No short-circuit: every pending job must be readmitted
                # even after one fails, or the survivors would be
                # stranded in 'queued' with no ServeJob behind them.
                got = self._readmit(j)
                ok = ok and got
            if ok and pending and os.path.exists(cur.get("ckpt", "")):
                cursors.append(cur)
            else:
                # Checkpoint gone / a job no longer parses: the surviving
                # jobs rerun from scratch (bit-identical) instead.
                for j in pending:
                    if j in self.jobs and self._readmit(j, fresh=True):
                        self.queue.append(self.jobs[j])
        for job_id in saved.get("queued", []):
            if job_id not in seen and self._readmit(job_id):
                self.queue.append(self.jobs[job_id])
                seen.add(job_id)
        # Crash sweep: job dirs whose status never reached a terminal
        # state and which no cursor covers.
        try:
            leftover = sorted(os.listdir(self.spool.jobs))
        except OSError:
            leftover = []
        for job_id in leftover:
            if job_id in seen or not os.path.exists(
                    self.spool.job_path(job_id)):
                continue
            st = self.spool.read_status(job_id)
            if st is not None and st.get("state") in TERMINAL_STATES:
                continue
            if self._readmit(job_id, fresh=True):
                self.queue.append(self.jobs[job_id])
                self._event("requeue_after_crash", job=job_id)
        self.resume = cursors
        # Sweep stale batch checkpoints (a SIGKILLed incarnation's
        # lineage) and start the batch counter past every surviving name:
        # a fresh batch must never reuse a previous incarnation's
        # checkpoint path — a torn head there would make lineage resolve
        # fall back onto a DIFFERENT batch's snapshot.
        keep = {c.get("ckpt") for c in cursors}
        try:
            names = sorted(os.listdir(self.spool.batches))
        except OSError:
            names = []
        for name in names:
            path = os.path.join(self.spool.batches, name)
            base = name.split(".npz")[0] + ".npz"
            # Quarantined lanes' solo-resumable checkpoints
            # (<batch>.npz.q<exp>.npz) are tenant deliverables — their
            # failed statuses point at them; never swept.
            stale = (os.path.join(self.spool.batches, base) not in keep
                     and ".npz.q" not in name)
            if stale:
                try:
                    os.remove(path)
                except OSError:
                    pass
            if name.startswith("b") and name[1:7].isdigit():
                self._batch_seq = max(self._batch_seq,
                                      int(name[1:7]) + 1)
        if self.queue or self.resume:
            self._event("recovered", queued=len(self.queue),
                        resume_batches=len(self.resume))

    def _readmit(self, job_id: str, fresh: bool = False) -> bool:
        try:
            with open(self.spool.job_path(job_id)) as f:
                job = json.load(f)
        except (OSError, ValueError):
            return False
        sj = self._validate(job)
        if sj is None:
            return False
        sj.enqueued_at = time.time()
        self.jobs[job_id] = sj
        if fresh:
            # A from-scratch rerun must not append to a half-written
            # record stream from the killed attempt.
            try:
                os.remove(self.spool.result_path(job_id))
            except OSError:
                pass
        self._set_state(job_id, J_QUEUED, priority=sj.priority,
                        resumed=not fresh)
        return True

    # -- intake / admission ------------------------------------------------

    def _intake(self) -> int:
        """Accept/reject everything in the inbox. Runs on the main thread
        only — between batches and at chunk boundaries (the eviction
        latch), so admission never races the scheduler."""
        n = 0
        for path, job in self.spool.scan_inbox():
            if job is None:
                bad = path + ".bad"
                os.replace(path, bad)
                self._event("reject", reason="unparseable submission",
                            file=os.path.basename(bad))
                self.ledger["jobs_rejected"] += 1
                continue
            self.spool.accept(path, job)   # one atomic move — kill-safe
            self._admit(job)
            n += 1
        return n

    def _safe_intake(self) -> int:
        """Intake with per-call exception isolation — the form the
        eviction latch and the main loop use, so one broken submission
        (or a transient spool IO error) is logged, not fatal to the
        daemon or to an in-flight batch's tenants."""
        try:
            return self._intake()
        except Exception as e:  # noqa: BLE001 — isolation is the point
            self.log.warning("intake failed; will retry next boundary",
                             error=repr(e))
            return 0

    def _admit(self, job: dict) -> None:
        job_id = job["id"]
        self.ledger["jobs_submitted"] += 1
        sj = self._validate(job, reject_status=True)
        if sj is None:
            return
        # ---- two-tier admission (docs/SEMANTICS.md admission ordering):
        # (1) reject only what can NEVER fit — est_peak vs the IDLE
        # device budget, not the live headroom; (2) bounded queue with
        # backpressure — depth/bytes caps produce a structured
        # queue_full rejection with retry-after advice, never a silent
        # drop; (3) what fits idle but not the live headroom is admitted
        # as waiting_headroom and scheduled when resident bytes drain.
        from shadow1_tpu import mem

        budget, budget_src = mem.device_budget()
        if budget is not None and sj.est_peak > int(budget):
            est = mem.estimate(sj.exp, sj.params, n_exp=1)
            rec = est.record(budget, budget_src)
            err = {
                "error": "memory_budget",
                "estimated": est.peak_bytes,
                "budget": int(budget),
                "budget_source": budget_src,
                "resident": self._resident_bytes,
                "headroom": int(budget),
                "planes": rec["planes"],
                "peaks": rec["peaks"],
                "advice": est.advice(int(budget)),
            }
            self._reject(job_id, err)
            return
        q_depth = len(self.queue)
        q_bytes = sum(j.est_peak for j in self.queue)
        full_depth = q_depth >= self.queue_depth
        full_bytes = (self.queue_bytes is not None
                      and q_bytes + sj.est_peak > self.queue_bytes)
        if full_depth or full_bytes:
            # Advisory only, but never zero: at least one poll interval,
            # stretched by how long the current head has already waited
            # (a deep queue drains no faster than its oldest tenant).
            oldest = min((j.enqueued_at for j in self.queue
                          if j.enqueued_at), default=time.time())
            retry_after = round(max(2 * self.poll_s,
                                    0.5 * (time.time() - oldest)), 3)
            err = {
                "error": "queue_full",
                "cap": "depth" if full_depth else "bytes",
                "queue_depth": q_depth,
                "queue_depth_cap": self.queue_depth,
                "queue_bytes": q_bytes,
                "queue_bytes_cap": self.queue_bytes,
                "est_peak": sj.est_peak,
                "retry_after_s": retry_after,
            }
            self.ledger["jobs_queue_full"] += 1
            self._reject(job_id, err)
            self._queue_record("reject_full", job=job_id,
                               cap=err["cap"],
                               retry_after_s=retry_after)
            return
        sj.enqueued_at = time.time()
        sj.waiting = bool(budget is not None and self._resident_bytes > 0
                          and sj.est_peak > int(budget)
                          - self._resident_bytes)
        self.jobs[job_id] = sj
        self.queue.append(sj)
        self._set_state(job_id, J_WAITING if sj.waiting else J_QUEUED,
                        priority=sj.priority, est_peak=sj.est_peak)
        self._event("accept", job=job_id, priority=sj.priority,
                    hosts=sj.exp.n_hosts, est_peak=sj.est_peak,
                    waiting=sj.waiting)
        self._queue_record("waiting_headroom" if sj.waiting else "enqueue",
                           job=job_id)

    def _queue_record(self, event: str, **fields) -> None:
        """One serve_queue record (the backpressure plane's feed) with
        the queue's shape at this instant."""
        oldest = min((j.enqueued_at for j in self.queue
                      if j.enqueued_at), default=None)
        self._log({"type": "serve_queue", "event": event,
                   "depth": len(self.queue),
                   "bytes": sum(j.est_peak for j in self.queue),
                   "oldest_wait_s": (round(time.time() - oldest, 3)
                                     if oldest else 0.0),
                   "t": time.time(), **fields}, echo=False)

    def _reject(self, job_id: str, err: dict) -> None:
        self.ledger["jobs_rejected"] += 1
        self._set_state(job_id, J_REJECTED, error=err)
        self._event("reject", job=job_id,
                    reason=err.get("error", "config"))

    def _validate(self, job: dict, reject_status: bool = False
                  ) -> ServeJob | None:
        """Submission → ServeJob, or None after writing the rejection.
        Pure config work — no compile, no device allocation (the memory
        side is an abstract eval_shape trace)."""
        import yaml

        from shadow1_tpu import mem
        from shadow1_tpu.config.experiment import build_experiment

        job_id = job["id"]

        def bad(msg: str) -> None:
            if reject_status:
                self._reject(job_id, {"error": "config", "message": msg})

        try:
            doc = yaml.safe_load(job["config_yaml"])
        except yaml.YAMLError as e:
            bad(f"config does not parse as YAML: {e}")
            return None
        if not isinstance(doc, dict):
            bad("config must be a YAML mapping")
            return None
        if "sweep" in doc:
            bad("serve jobs are single experiments — submit each sweep "
                "variant as its own job (the scheduler packs compatible "
                "jobs into fleet lanes itself)")
            return None
        try:
            exp, params, scheduler = build_experiment(
                doc, base_dir=job.get("base_dir", "."))
        except Exception as e:  # noqa: BLE001 — any schema violation
            bad(f"config rejected: {e}")
            return None
        if scheduler != "tpu":
            bad(f"serve runs the batched tpu engine (lane-packed fleet); "
                f"engine.scheduler={scheduler!r} is not servable — drop "
                f"the override or run it through the solo CLI")
            return None
        # The daemon's uniform lane policies: quarantine (one tenant's
        # halt never kills cohabitants) + finalize (short jobs free their
        # lane early). Ring transport for the digest stream mirrors the
        # solo CLI's auto-provision.
        repl = {"on_lane_fail": "quarantine", "lane_finalize": 1}
        if params.state_digest and params.metrics_ring <= 0:
            repl["metrics_ring"] = 64
        params = dataclasses.replace(params, **repl)
        windows = job.get("windows")
        windows = int(windows) if windows is not None else None
        try:
            est_peak = mem.estimate(exp, params, n_exp=1).peak_bytes
        except Exception as e:  # noqa: BLE001 — estimator fails soft
            self.log.warning("memory estimate unavailable", job=job_id,
                             error=repr(e))
            est_peak = 0
        def _opt_s(key: str) -> float | None:
            v = job.get(key)
            try:
                return float(v) if v is not None else None
            except (TypeError, ValueError):
                return None

        self._seq += 1
        return ServeJob(id=job_id, exp=exp, params=params,
                        priority=int(job.get("priority", 0)),
                        seq=self._seq, windows=windows, est_peak=est_peak,
                        queue_ttl_s=_opt_s("queue_ttl_s"),
                        deadline_s=_opt_s("deadline_s"))

    # -- deadlines / retry plane -------------------------------------------

    def _expire_ttl(self) -> None:
        """Sweep --queue-ttl-s: a job still waiting for its FIRST batch
        past its TTL goes terminal with a structured deadline_expired
        record (jobs already run once — evicted or retried — are past
        the queue TTL's scope). Exception-isolated like intake: called
        from the main loop and from the eviction latch mid-batch."""
        try:
            now = time.time()
            for sj in list(self.queue):
                if sj.queue_ttl_s is None or sj.first_run_at is not None:
                    continue
                waited = now - sj.enqueued_at
                if waited <= sj.queue_ttl_s:
                    continue
                self.queue.remove(sj)
                self.ledger["jobs_expired"] += 1
                err = {"error": "deadline_expired", "kind": "queue_ttl",
                       "queue_ttl_s": sj.queue_ttl_s,
                       "waited_s": round(waited, 3)}
                self._log({"type": "serve_deadline", "job": sj.id,
                           "kind": "queue_ttl",
                           "waited_s": round(waited, 3),
                           "t": now}, echo=False)
                self.spool.append_result(sj.id, {
                    "type": "serve_deadline", "job": sj.id,
                    "kind": "queue_ttl", "waited_s": round(waited, 3)})
                self._job_failed(sj.id, "deadline_expired", err)
        except Exception as e:  # noqa: BLE001 — never tear down a batch
            self.log.warning("ttl sweep failed; will retry next boundary",
                             error=repr(e))

    def _running_over_deadline(self) -> list[str]:
        """Running jobs past their --deadline-s (measured from their
        first batch_start — requeues after eviction/retry don't reset
        the clock)."""
        now = time.time()
        out = []
        for job_id in self.running:
            sj = self.jobs.get(job_id)
            if (sj is not None and sj.deadline_s is not None
                    and sj.first_run_at is not None
                    and now - sj.first_run_at > sj.deadline_s):
                out.append(job_id)
        return out

    def _maybe_inject_crash(self, polls: int) -> None:
        """Chaos hook (serveprobe / chaosprobe): SHADOW1_SERVE_CRASH_BATCH
        names a countdown file; while its count is positive, the batch
        dies with a transient RuntimeError at its second chunk boundary
        (the first boundary has already committed a lineage generation
        when --ckpt-every-s permits, so the retry path resumes mid-run).
        Decrement-then-raise: each count buys exactly one crash."""
        path = os.environ.get("SHADOW1_SERVE_CRASH_BATCH")
        if not path or polls < 2:
            return
        try:
            with open(path) as f:
                n = int(f.read().strip() or 0)
        except (OSError, ValueError):
            return
        if n <= 0:
            return
        with open(path, "w") as f:
            f.write(str(n - 1))
        raise RuntimeError(
            "injected transient batch crash (SHADOW1_SERVE_CRASH_BATCH)")

    def _classify_failure(self, e: BaseException) -> str:
        """'deterministic' | 'transient' — cli._supervise's rule, for
        batches: raw allocator aborts (RESOURCE_EXHAUSTED) and the
        structured taxonomy errors (capacity, memory budget, config,
        selfcheck) reproduce on retry by determinism, so retrying only
        re-kills the cohabitants; everything else (device resets,
        transport faults, injected chaos) is presumed transient."""
        from shadow1_tpu import mem
        from shadow1_tpu.fleet.expand import FleetConfigError
        from shadow1_tpu.txn import CapacityExceededError, SelfCheckError

        if mem.is_oom(e):
            return "deterministic"
        if isinstance(e, (CapacityExceededError, SelfCheckError,
                          FleetConfigError, mem.MemoryBudgetError)):
            return "deterministic"
        return "transient"

    def _retry_batch(self, e: BaseException, batch_id: str,
                     job_ids: list[str], ckpt: str,
                     prior_crashes: int) -> bool:
        """Absorb a transient batch failure: exponential-backoff retry
        from the last committed lineage generation; on the second crash
        of the same batch, bisect the suspects into solo batches (one
        poisonous tenant stops re-killing its cohabitants); a job that
        crashes its solo batch retry_max times goes terminal failed with
        its crash ledger attached. Returns True when the failure was
        absorbed here (retried, bisected, or terminalized)."""
        crashes = prior_crashes + 1
        err_s = f"{type(e).__name__}: {str(e)[:300]}"
        now = time.time()
        remaining = [j for j in job_ids
                     if j in self.jobs
                     and (self.spool.read_status(j) or {}).get("state")
                     not in TERMINAL_STATES]
        if not remaining:
            return False
        for j in remaining:
            self.jobs[j].crashes.append(
                {"t": now, "batch": batch_id, "attempt": crashes,
                 "error": err_s})
        base = float(os.environ.get("SHADOW1_SERVE_RETRY_BACKOFF_S",
                                    "0.5"))
        if len(remaining) == 1:
            sj = self.jobs[remaining[0]]
            if len(sj.crashes) >= self.retry_max:
                self._log({"type": "serve_retry", "event": "exhausted",
                           "batch": batch_id, "jobs": remaining,
                           "crashes": len(sj.crashes), "t": now},
                          echo=False)
                self._job_failed(sj.id, "retry_exhausted", {
                    "error": "retry_exhausted",
                    "crashes": sj.crashes, "message": err_s})
                self._finish_batch(batch_id, ckpt)
                return True
        elif crashes >= 2:
            # Bisect: every suspect reruns solo, from scratch (the fleet
            # snapshot can't be sliced without an engine — determinism
            # makes the from-scratch rerun bit-identical anyway).
            self.ledger["jobs_bisected"] += len(remaining)
            self._log({"type": "serve_retry", "event": "bisect",
                       "batch": batch_id, "jobs": remaining,
                       "attempt": crashes, "t": now}, echo=False)
            self._event("retry_bisect", batch=batch_id, jobs=remaining,
                        attempt=crashes)
            for j in remaining:
                sj = self.jobs[j]
                sj.solo = True
                try:
                    os.remove(self.spool.result_path(j))
                except OSError:
                    pass
                self.queue.append(sj)
                self._set_state(j, J_QUEUED, priority=sj.priority,
                                retrying=True, solo=True,
                                crashes=len(sj.crashes))
            self._finish_batch(batch_id, ckpt)
            return True
        backoff = round(base * (2 ** (crashes - 1)), 3)
        prio = max(self.jobs[j].priority for j in remaining)
        self.ledger["batch_retries"] += 1
        self.resume.append({"jobs": job_ids, "ckpt": ckpt,
                            "priority": prio, "crashes": crashes,
                            "retry": True,
                            "not_before": time.monotonic() + backoff})
        self._log({"type": "serve_retry", "event": "retry",
                   "batch": batch_id, "jobs": remaining,
                   "attempt": crashes, "backoff_s": backoff,
                   "error": err_s, "t": now}, echo=False)
        self._event("retry_backoff", batch=batch_id, jobs=remaining,
                    attempt=crashes, backoff_s=backoff, error=err_s)
        for j in remaining:
            self._set_state(j, J_QUEUED, priority=self.jobs[j].priority,
                            retrying=True, attempt=crashes,
                            backoff_s=backoff)
        self.running = []
        self._resident_bytes = 0
        self.ledger["batches_run"] += 1
        return True

    # -- scheduling --------------------------------------------------------

    def _pick_batch(self):
        """(jobs, cursor) — the next batch: an evicted batch resumes as
        soon as nothing strictly more important waits (jobs is then None;
        the cursor's lane↔job mapping is positional and resolved against
        the checkpoint manifest); otherwise the highest-priority queued
        job leads and every shape-compatible queued job packs in behind
        it (budget- and --max-lanes-capped)."""
        qprio = max((j.priority for j in self.queue), default=None)
        # Retry cursors in exponential backoff are invisible until their
        # not_before stamp passes — the queue keeps draining meanwhile.
        now = time.monotonic()
        ready = [c for c in self.resume
                 if c.get("not_before", 0) <= now]
        if ready:
            cur = max(ready, key=lambda c: (c["priority"],))
            if qprio is None or cur["priority"] >= qprio:
                self.resume.remove(cur)
                return None, cur
        if not self.queue:
            return None, None
        leader = sorted(self.queue, key=lambda j: (-j.priority, j.seq))[0]
        if leader.solo:
            # A bisected suspect rides alone — its crash must not take
            # cohabitants with it again.
            self.queue.remove(leader)
            return [leader], None
        key = leader.pack_key()
        cap = self.max_lanes
        from shadow1_tpu import mem

        budget, _ = mem.device_budget()
        if budget is not None:
            est = mem.estimate(leader.exp, leader.params, n_exp=1)
            cap = min(cap, max(est.max_lanes(int(budget)), 1))
        lanes = [j for j in sorted(self.queue, key=lambda j: j.seq)
                 if j.pack_key() == key and not j.solo][:cap]
        if leader not in lanes:  # the cap sliced the leader out — keep it
            lanes = [leader] + lanes[:cap - 1]
        for j in lanes:
            self.queue.remove(j)
        return lanes, None

    def _run_next_batch(self) -> bool:
        import numpy as np

        from shadow1_tpu import mem
        from shadow1_tpu.fleet.run import final_records, run_fleet
        from shadow1_tpu.lineage import Lineage
        from shadow1_tpu.preempt import PreemptedExit
        from shadow1_tpu.txn import CapacityExceededError

        lanes, cursor = self._pick_batch()
        if lanes is None and cursor is None:
            return False
        batch_id = f"b{self._batch_seq:06d}"
        self._batch_seq += 1
        # Crash count survives OUTSIDE the cursor: a batch whose retry
        # checkpoint is unusable falls back to a from-scratch rerun with
        # cursor=None, and forgetting its prior crashes there would retry
        # a poisonous batch forever instead of escalating to bisection.
        retry_crashes = int(cursor.get("crashes", 0)) if cursor else 0

        # ---- resume resolution (evicted-batch cursor) -------------------
        # The cursor's job list is POSITIONAL: index i is the lane id the
        # job had in the original batch, which is what the checkpoint
        # manifest's ``lanes`` meta (and every record's ``exp``) names.
        st = None
        resume_meta = None
        res_path = None
        caps_meta = None
        if cursor:
            job_ids = list(cursor["jobs"])
            ckpt = cursor["ckpt"]
            res = Lineage(ckpt).resolve(discard_invalid=True)
            live = None
            if res is not None and res.path is not None:
                meta = res.meta or {}
                live = [int(g) for g in
                        meta.get("lanes", range(len(job_ids)))]
                if all(job_ids[g] in self.jobs for g in live):
                    res_path = res.path
                    caps_meta = meta.get("caps")
                    resume_meta = {
                        "quarantined": meta.get("quarantined", []),
                        "finished": meta.get("finished", []),
                    }
            if res_path is None:
                # Checkpoint unusable (or a lane's job vanished): the
                # non-terminal jobs rerun from scratch — bit-identical by
                # determinism, so only wall time is lost.
                self._event("cursor_discarded", batch=batch_id, ckpt=ckpt)
                lanes = [self.jobs[j] for j in job_ids if j in self.jobs]
                if not lanes:
                    return True
                for j in lanes:
                    try:
                        os.remove(self.spool.result_path(j.id))
                    except OSError:
                        pass
                cursor = None
            else:
                lane_of = {g: self.jobs[job_ids[g]] for g in live}
        if not cursor:
            job_ids = [j.id for j in lanes]
            ckpt = os.path.join(self.spool.batches, batch_id + ".npz")
            live = list(range(len(lanes)))
            lane_of = dict(enumerate(lanes))
        params = lane_of[live[0]].params
        total = lane_of[live[0]].windows
        exps = [lane_of[i].exp for i in live]
        labels = [{"exp": i, "seed": int(lane_of[i].exp.seed),
                   "job": lane_of[i].id} for i in live]
        lane_jobs = {i: lane_of[i].id for i in live}

        import jax

        backend = jax.default_backend()
        if cursor and caps_meta:
            # Retry-grown caps from the evicted attempt, read off the
            # lineage manifest BEFORE the first cache lookup (the
            # solo-resume recipe): the hit/miss ledger then reflects the
            # engine that actually runs — never a hit followed by a
            # silent recompile at the real caps.
            caps = (int(caps_meta.get("ev_cap", params.ev_cap)),
                    int(caps_meta.get("outbox_cap", params.outbox_cap)))
            if caps != (params.ev_cap, params.outbox_cap):
                params = dataclasses.replace(params, ev_cap=caps[0],
                                             outbox_cap=caps[1])
        # Engine build + resume load under the same batch isolation as
        # the run itself: one tenant's compile-time OOM or a damaged
        # snapshot must fail THIS batch's jobs, never the whole daemon
        # (the other tenants' queue and the socket plane keep serving).
        try:
            engine, outcome = self.cache.get(exps, params,
                                             backend=backend)
            if cursor:
                from shadow1_tpu.ckpt import load_state, snapshot_caps

                template = engine.init_state()
                snap = snapshot_caps(template, res_path)
                if snap and snap != (params.ev_cap, params.outbox_cap):
                    # Manifest-less fallback (legacy snapshot): rebuild
                    # at the snapshot's own caps.
                    params = dataclasses.replace(params, ev_cap=snap[0],
                                                 outbox_cap=snap[1])
                    engine, outcome = self.cache.get(exps, params,
                                                     backend=backend)
                    template = engine.init_state()
                st = load_state(template, res_path)
        except Exception as e:  # noqa: BLE001 — batch isolation
            reason = "memory_exhausted" if mem.is_oom(e) else "runtime"
            for i in live:
                self._job_failed(lane_of[i].id, reason,
                                 {"error": reason,
                                  "message": str(e)[:500]})
            self._event("batch_failed", batch=batch_id,
                        reason=f"build:{reason}", error=str(e)[:400])
            self._finish_batch(batch_id, ckpt)
            self.log.warning("batch engine build failed", batch=batch_id,
                             error=repr(e))
            return True
        n_windows = total if total is not None else engine.n_windows
        remaining = n_windows
        if st is not None:
            done0 = int(np.asarray(st.win_start).max()) // engine.window
            remaining = max(n_windows - done0, 0)

        self.running = [lane_of[i].id for i in live]
        self._resident_bytes = 0
        try:
            self._resident_bytes = mem.estimate(
                exps[0], params, n_exp=len(exps)).peak_bytes
        except Exception:  # noqa: BLE001 — estimator fails soft
            pass
        batch_priority = max(lane_of[i].priority for i in live)
        for i in live:
            if lane_of[i].first_run_at is None:
                lane_of[i].first_run_at = time.time()
            self._set_state(lane_of[i].id, J_RUNNING, batch=batch_id,
                            lane=i, lanes=len(live), cache=outcome,
                            resumed=bool(cursor))
        self._event("batch_start", batch=batch_id, jobs=self.running,
                    lanes=len(live), cache=outcome,
                    resumed=bool(cursor), windows=remaining,
                    priority=batch_priority)
        router = _RecordRouter(self, lane_jobs, batch_id)
        latch = _EvictionLatch(self, batch_priority=batch_priority)
        t0 = time.perf_counter()
        try:
            st, hb = run_fleet(
                engine, st, n_windows=remaining,
                every_windows=params.metrics_ring or None,
                stream=router,
                ckpt_path=ckpt, ckpt_every_s=self.ckpt_every_s,
                emit_heartbeat=True, emit_ring=True,
                selfcheck=bool(params.selfcheck),
                labels=labels, ckpt_keep=2, drain=latch,
                quarantine_base=ckpt,
                emit_record=router.record,
                resume_meta={"jobs": job_ids},
                recovery_seed=resume_meta,
            )
            jax.block_until_ready(st)
        except PreemptedExit as e:
            self._preempted_batch(batch_id, latch, job_ids, ckpt,
                                  st=e.st)
            router.close()
            return True
        except CapacityExceededError as e:
            # Every lane quarantined: each already got its record + its
            # failed status through the router; nothing left to mark.
            self._event("batch_failed", batch=batch_id,
                        reason="capacity", error=str(e)[:400])
            self._finish_batch(batch_id, ckpt)
            router.close()
            return True
        except Exception as e:  # noqa: BLE001 — one batch must not kill the daemon
            router.close()
            if self._classify_failure(e) == "transient":
                # cli._supervise's classification, batch-scoped: presumed
                # device/transport flake — retry with backoff from the
                # last committed generation instead of failing tenants.
                if self._retry_batch(e, batch_id, job_ids, ckpt,
                                     retry_crashes):
                    self.log.warning("transient batch failure; retrying",
                                     batch=batch_id, error=repr(e))
                    return True
            reason = "memory_exhausted" if mem.is_oom(e) else "runtime"
            for job_id in list(self.running):
                self._job_failed(job_id, reason,
                                 {"error": reason,
                                  "message": str(e)[:500]})
            self._event("batch_failed", batch=batch_id, reason=reason,
                        error=str(e)[:400])
            self._finish_batch(batch_id, ckpt)
            if reason == "runtime":
                self.log.warning("batch runtime failure", batch=batch_id,
                                 error=repr(e))
            return True
        wall = time.perf_counter() - t0
        recs, summary = final_records(hb.engine, st, hb.labels, n_windows,
                                      wall, resumed=bool(cursor),
                                      recovery=hb.recovery)
        for rec in recs:
            router.record(rec)
        self._log({**summary, "batch": batch_id}, echo=False)
        self._event("batch_done", batch=batch_id, wall_s=round(wall, 3),
                    lanes=len(hb.labels),
                    quarantined=len(hb.recovery["quarantined"]),
                    finished_early=len(hb.recovery["finished"]))
        self._finish_batch(batch_id, ckpt)
        router.close()
        return True

    def _preempted_batch(self, batch_id: str, latch, job_ids: list[str],
                         ckpt: str, st=None) -> None:
        """The drain latch fired mid-batch: the chunk committed and the
        batch checkpointed (run_fleet's drain contract). Jobs still in
        the fleet requeue behind the checkpoint cursor — an eviction's
        tenants resume bit-identically once the device frees up; a
        shutdown's tenants resume on the next daemon start. A DEADLINE
        drain first terminalizes the expired jobs (their result streams
        keep the committed prefix) and slices their lanes out of the
        committed snapshot, so cohabitants resume undisturbed."""
        expired = [j for j in getattr(latch, "deadline_jobs", [])
                   if j in job_ids
                   and (self.spool.read_status(j) or {}).get("state")
                   not in TERMINAL_STATES]
        for job_id in expired:
            sj = self.jobs.get(job_id)
            ran = (round(time.time() - sj.first_run_at, 3)
                   if sj is not None and sj.first_run_at else None)
            err = {"error": "deadline_expired", "kind": "running",
                   "deadline_s": getattr(sj, "deadline_s", None),
                   "ran_s": ran}
            self.ledger["jobs_expired"] += 1
            self._log({"type": "serve_deadline", "job": job_id,
                       "kind": "running", "batch": batch_id,
                       "ran_s": ran, "t": time.time()}, echo=False)
            self.spool.append_result(job_id, {
                "type": "serve_deadline", "job": job_id,
                "kind": "running", "batch": batch_id, "ran_s": ran})
            self._job_failed(job_id, "deadline_expired", err)
        if expired and st is not None:
            self._drop_expired_lanes(ckpt, job_ids, set(expired), st)
        remaining = [j for j in job_ids
                     if (self.spool.read_status(j) or {}).get("state")
                     not in TERMINAL_STATES]
        evicting = latch.evicting
        if remaining:
            prio = max((self.jobs[j].priority for j in remaining
                        if j in self.jobs), default=0)
            cursor = {"jobs": job_ids, "ckpt": ckpt, "priority": prio}
            self.resume.append(cursor)
            for job_id in remaining:
                if evicting:
                    self.ledger["jobs_evicted"] += 1
                    self._set_state(job_id, J_EVICTED, batch=batch_id,
                                    ckpt=ckpt)
                    self.spool.append_result(job_id, {
                        "type": "serve", "event": "evict", "job": job_id,
                        "batch": batch_id, "ckpt": ckpt})
                self._set_state(job_id, J_QUEUED, resumed=True,
                                priority=(self.jobs[job_id].priority
                                          if job_id in self.jobs else 0))
        self._event("evict" if evicting else "batch_drained",
                    batch=batch_id, jobs=remaining, ckpt=ckpt,
                    signal=latch.signame, expired=expired)
        self.running = []
        self._resident_bytes = 0
        self.ledger["batches_run"] += 1
        if not remaining:
            # Every tenant is terminal (e.g. the whole batch expired):
            # nothing resumes, so the checkpoint lineage is garbage now.
            from shadow1_tpu.lineage import Lineage

            Lineage(ckpt).remove_all()
            for suffix in (".progress", ".meta"):
                try:
                    os.remove(ckpt + suffix)
                except OSError:
                    pass

    def _drop_expired_lanes(self, ckpt: str, job_ids: list[str],
                            expired: set, st) -> None:
        """Re-save the drain's committed snapshot minus the expired
        jobs' lanes (run_fleet's _repack recipe: lanes are
        vmap-independent, so select_lanes preserves every surviving
        lane's continuation bit-exactly). The sliced generation is what
        the resume cursor resolves, so the survivors' batch rebuilds at
        the smaller lane count without ever touching the expired lanes.
        Fails soft: without the slice, resume falls back to the
        cursor_discarded from-scratch rerun — slower, still correct."""
        try:
            from shadow1_tpu.fleet.engine import select_lanes
            from shadow1_tpu.lineage import Lineage

            lin = Lineage(ckpt)
            res = lin.resolve(discard_invalid=True)
            if res is None or res.path is None:
                return
            meta = dict(res.meta or {})
            lanes = [int(g) for g in
                     meta.get("lanes", range(len(job_ids)))]
            keep = [i for i, g in enumerate(lanes)
                    if job_ids[g] not in expired]
            if not keep or len(keep) == len(lanes):
                return
            meta["lanes"] = [lanes[i] for i in keep]
            lin.save(select_lanes(st, keep), meta)
        except Exception as e:  # noqa: BLE001 — fall back to from-scratch
            self.log.warning("expired-lane slice failed; survivors will "
                             "rerun from scratch", error=repr(e))

    def _finish_batch(self, batch_id: str, ckpt: str) -> None:
        from shadow1_tpu.lineage import Lineage

        Lineage(ckpt).remove_all()
        for suffix in (".progress", ".meta"):
            try:
                os.remove(ckpt + suffix)
            except OSError:
                pass
        self.running = []
        self._resident_bytes = 0
        self.ledger["batches_run"] += 1

    def _job_done(self, job_id: str, rec: dict) -> None:
        self.ledger["jobs_done"] += 1
        self.jobs.pop(job_id, None)
        prev = self.spool.read_status(job_id) or {}
        self._set_state(job_id, J_DONE,
                        windows=rec.get("windows"),
                        events=(rec.get("metrics") or {}).get("events"),
                        finished_early=bool(rec.get("finished_early")),
                        cache=prev.get("cache"), lane=prev.get("lane"),
                        lanes=prev.get("lanes"), batch=prev.get("batch"))
        if job_id in self.running:
            self.running.remove(job_id)

    def _job_failed(self, job_id: str, reason: str, rec: dict) -> None:
        self.ledger["jobs_failed"] += 1
        self.jobs.pop(job_id, None)
        self._set_state(job_id, J_FAILED, reason=reason, error=rec)
        if job_id in self.running:
            self.running.remove(job_id)

    # -- main loop ---------------------------------------------------------

    def step(self) -> bool:
        """One scheduler iteration; returns True when work was done
        (tests drive the daemon through this without threads)."""
        self._safe_intake()
        self._expire_ttl()
        self._touch_heartbeat()
        if self._draining():
            return False
        if self.resume or self.queue:
            # May still be idle: every cursor can sit in retry backoff.
            return self._run_next_batch()
        return False

    def _draining(self) -> bool:
        return (self._drain is not None and self._drain.requested) \
            or self._shutdown.is_set()

    def run(self) -> int:
        from shadow1_tpu.preempt import DrainHandler

        self._drain = DrainHandler().install()
        try:
            while True:
                worked = self.step()
                if self._draining():
                    break
                if not worked:
                    self._wake.wait(self.poll_s)
                    self._wake.clear()
        finally:
            self._persist_queue()
            self._event("shutdown", queued=len(self.queue),
                        resume_batches=len(self.resume),
                        ledger=self.ledger_dict())
            self.close()
        return EXIT_SERVE_SHUTDOWN

    def _persist_queue(self) -> None:
        from shadow1_tpu.lineage import write_json_atomic

        write_json_atomic(self.spool.queue_path, {
            "queued": [j.id for j in
                       sorted(self.queue, key=lambda j: j.seq)],
            "resume": self.resume,
            "persisted_at": time.time(),
        })


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="shadow1_tpu serve",
        description="persistent multi-tenant engine daemon "
                    "(shadow1_tpu/serve/)")
    ap.add_argument("--spool", required=True, metavar="DIR",
                    help="spool directory (job inbox, per-job results, "
                         "queue state, Unix socket)")
    ap.add_argument("--metrics-port", type=int, default=None, metavar="P",
                    help="serve the job-ledger gauges as Prometheus text "
                         "on 127.0.0.1:P (0 = ephemeral port, printed in "
                         "the start event)")
    ap.add_argument("--max-lanes", type=int, default=8,
                    help="max shape-compatible jobs packed into one "
                         "fleet batch (the budget may cap it lower)")
    ap.add_argument("--queue-depth", type=int, default=64,
                    help="admission backpressure: max jobs waiting "
                         "(queued + waiting_headroom); beyond it, "
                         "submissions get a structured queue_full "
                         "rejection with retry_after_s advice")
    ap.add_argument("--queue-bytes", type=int, default=None,
                    metavar="BYTES",
                    help="admission backpressure: cap on the summed "
                         "est_peak bytes of waiting jobs (default "
                         "unbounded)")
    ap.add_argument("--retry-max", type=int, default=3,
                    help="terminal-failure threshold: a job whose "
                         "batches crash transiently this many times "
                         "(solo included) fails with its crash ledger")
    ap.add_argument("--cache-cap", type=int, default=4,
                    help="hot-engine cache capacity (LRU entries)")
    ap.add_argument("--poll-s", type=float, default=0.2,
                    help="idle inbox poll interval")
    ap.add_argument("--ckpt-every-s", type=float, default=60.0,
                    help="batch checkpoint throttle (drains force one "
                         "regardless)")
    ap.add_argument("--log-level", default="message",
                    choices=["error", "warning", "message", "info",
                             "debug"])
    args = ap.parse_args(argv)

    import shadow1_tpu  # noqa: F401  (x64 before jax arrays)
    from shadow1_tpu.platform import ensure_live_platform

    ensure_live_platform(min_devices=1)
    try:
        daemon = ServeDaemon(
            args.spool, metrics_port=args.metrics_port,
            max_lanes=args.max_lanes, cache_capacity=args.cache_cap,
            poll_s=args.poll_s, ckpt_every_s=args.ckpt_every_s,
            log_level=args.log_level, queue_depth=args.queue_depth,
            queue_bytes=args.queue_bytes,
            retry_max=args.retry_max).start()
    except SpoolError as e:
        print(f"SpoolError: {e}", file=sys.stderr, flush=True)
        print(json.dumps({"error": "serve_spool", "message": str(e)}))
        return EXIT_SERVE_SPOOL
    return daemon.run()


if __name__ == "__main__":
    sys.exit(main())
